"""Multi-host transport for the replica scheduler.

The pieces that let the PR 9 replica runtime leave the single machine:

  * framing       — length-prefixed JSON frames (the wire format IS
                    journal lines) with partial-read reassembly;
  * socket_channel— the reliable seq/ack/resume channel implementing
                    the existing ReplicaChannel seam over TCP, plus the
                    coordinator-side ChannelListener;
  * faults        — seeded injectable delay/drop/reorder for drills;
  * replication   — coordinator-owned async replication of per-host
                    journal segments (fail-over without a shared fs);
  * watchdog      — BarrierStallError: the stalling pid/host/round
                    surfaced instead of a silent hang;
  * elastic       — backlog-driven replica scaling + Aryl-style
                    capacity loaning over the group-reassignment seam.

Kill switch: KUEUE_TPU_NO_SOCKET=1 forces the pipe transport
everywhere (the runtime falls back to PR 9's multiprocessing pipes).
"""

from kueue_tpu.transport.elastic import ElasticController
from kueue_tpu.transport.faults import (
    FaultInjector,
    FaultPlan,
    parse_fault_env,
)
from kueue_tpu.transport.framing import (
    FrameDecoder,
    FrameError,
    decode_message,
    encode_frame,
    encode_message,
)
from kueue_tpu.transport.replication import JournalReplicator, host_state_dir
from kueue_tpu.transport.socket_channel import (
    ChannelClosed,
    ChannelListener,
    SocketChannel,
    WorkerDiedError,
)
from kueue_tpu.transport.watchdog import BarrierStallError, barrier_deadline

__all__ = [
    "BarrierStallError",
    "ChannelClosed",
    "ChannelListener",
    "ElasticController",
    "FaultInjector",
    "FaultPlan",
    "FrameDecoder",
    "FrameError",
    "JournalReplicator",
    "SocketChannel",
    "WorkerDiedError",
    "barrier_deadline",
    "decode_message",
    "encode_frame",
    "encode_message",
    "host_state_dir",
    "parse_fault_env",
]
