"""Elastic replica count + Aryl-style capacity loaning.

Aryl (PAPERS.md, arxiv 2202.07896) scales a job's replica set with load
and LOANS idle capacity to loaded peers instead of letting it sit. The
replica runtime's shard groups are the unit of work here, and its group
reassignment (built for fail-over) is the mechanism: this controller
watches per-shard-group backlog depth (the `kueue_replica_backlog_depth`
gauge's feed) and drives three moves, all at barrier boundaries so the
quiescent-tick discipline is never violated mid-tick:

  * scale UP   — every worker is loaded past the high watermark: start
    a new replica process and migrate the deepest-backlog group onto it.
  * LOAN       — one worker idles while another drowns: migrate the
    loaded worker's deepest group onto the idle one, remembering its
    home; the loan RETURNS when the group's backlog drains. This is
    Aryl's capacity-loaning loop — the idle replica's process capacity
    serves the loaded group's solves, and the commit protocol (phase B)
    keeps any split-root quota math exact across the move.
  * scale DOWN — a surplus worker's groups are all idle: migrate them
    back to survivors and stop the process.

One move per step: each migration is a release/replay/adopt cycle, and
spacing them keeps every intermediate state settled (the post-resettle
steady window must dispatch ZERO solves — pinned by the elastic drill).
"""

from __future__ import annotations

from typing import Dict, List, Optional


class ElasticController:
    """Backlog-driven scaling policy over a ReplicaRuntime."""

    def __init__(self, runtime, *, scale_up_backlog: int = 64,
                 idle_backlog: int = 0, loan_min_backlog: int = 8,
                 min_replicas: int = 1, max_replicas: int = 8,
                 cooldown_ticks: int = 2):
        self.rt = runtime
        self.scale_up_backlog = scale_up_backlog
        self.idle_backlog = idle_backlog
        self.loan_min_backlog = loan_min_backlog
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.cooldown_ticks = cooldown_ticks
        self._cooldown = 0
        # gid -> home wid, for loans outstanding.
        self.loans: Dict[int, int] = {}
        self.actions: List[str] = []

    # -- introspection -------------------------------------------------------

    def _live_workers(self) -> List[int]:
        return [w.wid for w in self.rt.workers if w.alive]

    def _backlog_by_worker(self, backlog: Dict[int, int]) -> Dict[int, int]:
        by_worker = {wid: 0 for wid in self._live_workers()}
        for gid, depth in backlog.items():
            wid = self.rt.group_owner.get(gid)
            if wid in by_worker:
                by_worker[wid] += depth
        return by_worker

    # -- the policy step -----------------------------------------------------

    def step(self, backlog: Dict[int, int]) -> Optional[str]:
        """One policy decision against the tick's backlog depths
        (gid -> pending workloads). Returns the action taken (logged in
        `self.actions`) or None."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        action = self._decide(backlog)
        if action is not None:
            self.actions.append(action)
            self._cooldown = self.cooldown_ticks
        return action

    def _decide(self, backlog: Dict[int, int]) -> Optional[str]:
        by_worker = self._backlog_by_worker(backlog)
        if not by_worker:
            return None

        # 1. Return drained loans home first: the loan was temporary
        # capacity, and home placement keeps the cohort-hash locality.
        for gid, home in sorted(self.loans.items()):
            if home not in by_worker:
                # The home worker died: the loan can never return, and
                # keeping the entry would exclude this group from every
                # future move forever. Its current owner IS home now.
                del self.loans[gid]
            elif backlog.get(gid, 0) <= self.idle_backlog \
                    and self.rt.group_owner.get(gid) != home:
                if self.rt.migrate_group(gid, home):
                    del self.loans[gid]
                    return f"return g{gid}->w{home}"
            elif self.rt.group_owner.get(gid) == home:
                del self.loans[gid]

        # 2. Scale up: everyone loaded, room for one more replica.
        n_live = len(by_worker)
        if n_live < self.max_replicas \
                and by_worker \
                and min(by_worker.values()) > self.scale_up_backlog:
            gid = self._deepest_group(backlog,
                                      min_depth=self.loan_min_backlog)
            if gid is not None:
                # Capture the home BEFORE the migration rewrites
                # ownership — it is where the group returns on drain.
                home = self.rt.group_owner.get(gid, 0)
                new_wid = self.rt.add_worker()
                if self.rt.migrate_group(gid, new_wid):
                    self.loans.setdefault(gid, home)
                    return f"scale-up w{new_wid} g{gid}"
                # Migration failed: reap the group-less newcomer rather
                # than leaving a dead-weight process the policy would
                # only collect on a later scale-down pass.
                self.rt.remove_worker(new_wid)

        # 3. Loan: an idle worker next to a drowning one.
        idle = [w for w, b in by_worker.items() if b <= self.idle_backlog]
        loaded = [w for w, b in by_worker.items()
                  if b >= self.loan_min_backlog
                  and self._group_count(w) >= 2]
        if idle and loaded:
            donor = max(loaded, key=lambda w: (by_worker[w], w))
            taker = min(idle, key=lambda w: (by_worker[w], w))
            gid = self._deepest_group(backlog, owner=donor,
                                      min_depth=self.loan_min_backlog)
            if gid is not None and self.rt.migrate_group(gid, taker):
                self.loans.setdefault(gid, donor)
                return f"loan g{gid} w{donor}->w{taker}"

        # 4. Scale down: a surplus worker with nothing to do.
        if n_live > self.min_replicas:
            for wid in sorted(by_worker, reverse=True):
                if by_worker[wid] <= self.idle_backlog \
                        and all(backlog.get(g, 0) <= self.idle_backlog
                                for g in self._groups_of(wid)):
                    if self.rt.remove_worker(wid):
                        return f"scale-down w{wid}"
        return None

    # -- helpers -------------------------------------------------------------

    def _groups_of(self, wid: int) -> List[int]:
        return [g for g, w in self.rt.group_owner.items() if w == wid]

    def _group_count(self, wid: int) -> int:
        return len(self._groups_of(wid))

    def _deepest_group(self, backlog: Dict[int, int],
                       owner: Optional[int] = None,
                       min_depth: int = 0) -> Optional[int]:
        """The deepest-backlog group (optionally among one worker's),
        never the owner's last group (a worker must keep one — moving
        its only group is a scale-down, not a loan), never a group
        ALREADY on loan (a loaned group only moves again by returning
        home, or the policy ping-pongs it between a draining donor and
        its taker every step), and never one below `min_depth` (moving
        an empty group is churn with nothing to gain)."""
        best, best_depth = None, min_depth - 1
        for gid, depth in sorted(backlog.items()):
            if gid in self.loans:
                continue
            wid = self.rt.group_owner.get(gid)
            if wid is None:
                continue
            if owner is not None and wid != owner:
                continue
            if self._group_count(wid) < 2:
                continue
            if depth > best_depth:
                best, best_depth = gid, depth
        return best
