"""Injectable transport faults for multi-host drills.

A `FaultPlan` describes the fault mix (delay / drop / reorder) and a
seed; each channel derives a `FaultInjector` whose per-frame schedule is
a pure function of (seed, channel id, frame ordinal) — two runs with the
same plan draw the SAME schedule, so a fault drill is reproducible and
the two-host identity goldens can run WITH faults on.

Semantics against the reliable channel (socket_channel.py):

  * delay — hold the frame for `delay_ms` before writing. The barrier
    protocol is latency-tolerant by construction, so delay shows up as
    reconcile RTT, never as a decision change.
  * drop — sever the connection instead of silently discarding: the
    channel has no retransmit timer (messages are acked, not timed), so
    a silent drop would stall the barrier forever; a severed connection
    models the same packet loss at the only layer that can recover it —
    the reconnect handshake retransmits everything unacked.
  * reorder — swap the frame with the next one written. The receiver
    resequences by frame number, so reordering is absorbed; the drill
    proves that property stays true.
"""

from __future__ import annotations

import random
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass(frozen=True)
class FaultPlan:
    seed: int = 0
    delay_ms: float = 0.0
    delay_prob: float = 0.0
    drop_prob: float = 0.0
    reorder_prob: float = 0.0

    @property
    def active(self) -> bool:
        return (self.delay_prob > 0 and self.delay_ms > 0) \
            or self.drop_prob > 0 or self.reorder_prob > 0

    def injector(self, channel_id) -> Optional["FaultInjector"]:
        return FaultInjector(self, channel_id) if self.active else None

    def to_dict(self) -> Dict[str, float]:
        """Wire/opts form (spawned workers rebuild their side from it)."""
        return {"seed": self.seed, "delay_ms": self.delay_ms,
                "delay_prob": self.delay_prob, "drop_prob": self.drop_prob,
                "reorder_prob": self.reorder_prob}

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> Optional["FaultPlan"]:
        if not d:
            return None
        return cls(seed=int(d.get("seed", 0)),
                   delay_ms=float(d.get("delay_ms", 0.0)),
                   delay_prob=float(d.get("delay_prob", 0.0)),
                   drop_prob=float(d.get("drop_prob", 0.0)),
                   reorder_prob=float(d.get("reorder_prob", 0.0)))


def parse_fault_env(spec: Optional[str]) -> Optional[FaultPlan]:
    """Parse `KUEUE_TPU_FAULTS` ("delay_ms=5,delay_p=0.5,drop_p=0.01,
    reorder_p=0.1,seed=7"); None/empty disables."""
    if not spec:
        return None
    keys = {"delay_ms": "delay_ms", "delay_p": "delay_prob",
            "drop_p": "drop_prob", "reorder_p": "reorder_prob",
            "seed": "seed"}
    kw: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, val = part.partition("=")
        field_name = keys.get(name.strip())
        if field_name is None:
            raise ValueError(
                f"KUEUE_TPU_FAULTS: unknown knob {name.strip()!r} "
                f"(known: {', '.join(sorted(keys))})")
        kw[field_name] = float(val)
    if "seed" in kw:
        kw["seed"] = int(kw["seed"])
    plan = FaultPlan(**kw)
    return plan if plan.active else None


# Frame dispositions (FaultInjector.next_action return values).
PASS = "pass"
DELAY = "delay"
DROP = "drop"
REORDER = "reorder"


@dataclass
class FaultStats:
    delays: int = 0
    drops: int = 0
    reorders: int = 0
    schedule: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"delays": self.delays, "drops": self.drops,
                "reorders": self.reorders}


class FaultInjector:
    """Per-channel deterministic fault schedule.

    The RNG seeds from crc32 of the channel id mixed with the plan seed
    (never `hash()` — string hashing is salted per process, and the
    schedule must agree across runs and across spawned workers)."""

    def __init__(self, plan: FaultPlan, channel_id):
        self.plan = plan
        self.channel_id = channel_id
        self._rnd = random.Random(
            plan.seed * 1_000_003
            + zlib.crc32(str(channel_id).encode("utf-8")))
        self.stats = FaultStats()

    def next_action(self) -> str:
        """Disposition for the next data frame. Draw order is fixed
        (drop, reorder, delay) so the schedule is reproducible."""
        rnd = self._rnd
        plan = self.plan
        action = PASS
        if rnd.random() < plan.drop_prob:
            action = DROP
        elif rnd.random() < plan.reorder_prob:
            action = REORDER
        elif plan.delay_ms > 0 and rnd.random() < plan.delay_prob:
            action = DELAY
        stats = self.stats
        if action == DROP:
            stats.drops += 1
        elif action == REORDER:
            stats.reorders += 1
        elif action == DELAY:
            stats.delays += 1
        stats.schedule.append(action)
        return action
