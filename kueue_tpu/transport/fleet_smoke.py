"""`make fleet-smoke`: the zero-emulation fleet drill.

Two REAL operating-system worker processes (no loopback threads, no
per-host directory emulation) join an in-process coordinator over
`python -m kueue_tpu --join 127.0.0.1:PORT` with TLS and a shared auth
token. The drill then does to the control plane exactly what a fleet
does:

  1. admit a first wave over the wire (identity with single-process);
  2. kill the coordinator mid-window (listener torn down, object
     dropped) with a second wave pending;
  3. hold it dead while both workers' watchdogs fire, their
     re-election probes fail, and they drop to journaled DEGRADED
     admission — the second wave (flat cohorts) must keep admitting;
  4. start a NEW coordinator incarnation on the same port: the
     workers' channels detect the fresh session id, re-join carrying
     the shard groups they own, and serve their degraded reports;
  5. the rejoin reconcile replays the degraded window against merged
     state — and the final admitted set must equal an uninterrupted
     single-process run (zero revocations here: nothing shrank).

Exits 0 with a JSON summary line on success, 1 with a reason on any
violated gate.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time

N_CQS = 6
CPU = 6


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _build(t):
    from kueue_tpu.api.types import (
        ClusterQueue, FlavorQuotas, LocalQueue, ResourceFlavor,
        ResourceGroup)

    t.create_resource_flavor(ResourceFlavor.make("default"))
    for i in range(N_CQS):
        t.create_cluster_queue(ClusterQueue(
            name=f"fs-cq-{i}", resource_groups=(ResourceGroup(
                covered_resources=("cpu",),
                flavors=(FlavorQuotas.make("default", cpu=CPU),)),)))
        t.create_local_queue(LocalQueue(
            name=f"fs-lq-{i}", namespace="default",
            cluster_queue=f"fs-cq-{i}"))


def _submit_wave(t, tag, base_time):
    from kueue_tpu.api.types import PodSet, Workload

    for i in range(N_CQS):
        t.submit(Workload(
            name=f"fs-{tag}-{i}", namespace="default",
            queue_name=f"fs-lq-{i}", creation_time=base_time + i,
            pod_sets=[PodSet.make("ps0", count=1, cpu=3)]))


def _single_process_reference():
    from kueue_tpu.config import Configuration, TPUSolverConfig
    from kueue_tpu.controllers.runtime import Framework

    fw = Framework(batch_solver=None, config=Configuration(
        tpu_solver=TPUSolverConfig(enable=False)))
    fw.create_namespace("default", labels={})
    _build(fw)
    _submit_wave(fw, "a", 0.0)
    _submit_wave(fw, "b", 100.0)
    fw.run_until_settled(max_ticks=10)
    return {name: sorted(cq.workloads)
            for name, cq in fw.cache.cluster_queues.items()
            if cq.workloads}


def _fail(msg: str, procs=()) -> int:
    for p in procs:
        p.kill()
    print(json.dumps({"metric": "fleet_smoke", "ok": False,
                      "reason": msg}), flush=True)
    return 1


def main() -> int:
    from kueue_tpu.controllers.replica_runtime import ReplicaRuntime
    from kueue_tpu.transport.security import (generate_self_signed,
                                              openssl_available)

    if not openssl_available():
        return _fail("openssl CLI unavailable; fleet-smoke requires TLS")
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    token = "fleet-smoke-token"
    td = tempfile.mkdtemp(prefix="kueue-fleet-smoke-")
    cert, key = generate_self_signed(os.path.join(td, "pki"))
    port = _free_port()

    procs = []
    for i in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "kueue_tpu",
             "--join", f"127.0.0.1:{port}",
             "--state-dir", os.path.join(td, f"worker-{i}"),
             "--tls-cert", cert, "--auth-token", token,
             "--node-name", f"smoke-{i}",
             "--degraded-after", "0.5",
             "--join-timeout", "300"],
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": repo_root},
            cwd=repo_root))
    print(f"# fleet-smoke: 2 worker processes "
          f"(pids {[p.pid for p in procs]}) joining "
          f"127.0.0.1:{port} over TLS", file=sys.stderr, flush=True)

    def coordinator(state_tag):
        return ReplicaRuntime(
            2, remote=True, transport="socket",
            listen=("127.0.0.1", port), engine="host", solver=False,
            state_dir=os.path.join(td, state_tag),
            tls_cert=cert, tls_key=key, auth_token=token,
            join_timeout=240.0, degraded_after=0.5)

    expect = _single_process_reference()
    try:
        rt = coordinator("coord-1")
    except RuntimeError as exc:
        return _fail(f"join phase failed: {exc}", procs)
    hosts = sorted(w.host_id for w in rt.workers)
    if hosts != ["smoke-0", "smoke-1"]:
        return _fail(f"wrong fleet joined: {hosts}", procs)
    _build(rt)
    _submit_wave(rt, "a", 0.0)
    for _ in range(4):
        rt.tick()
    wave1 = sum(len(v) for v in rt.dump()["admitted"].values())
    if wave1 != N_CQS:
        return _fail(f"wave 1 admitted {wave1} != {N_CQS}", procs)
    rejected = rt.listener.rejected_hellos

    # -- the kill: second wave pending, coordinator dies ---------------------
    _submit_wave(rt, "b", 100.0)
    time.sleep(0.3)  # let the routed objs drain to the workers
    rt.listener.close()
    print("# fleet-smoke: coordinator KILLED; holding it dead while "
          "the workers degrade", file=sys.stderr, flush=True)
    t_dead = time.monotonic()
    time.sleep(4.0)  # watchdogs fire, probes fail, safe mode admits

    # -- the new incarnation -------------------------------------------------
    try:
        rt2 = coordinator("coord-2")
    except RuntimeError as exc:
        return _fail(f"re-join phase failed: {exc}", procs)
    _build(rt2)  # a restarted coordinator re-applies its manifests
    ev = rt2.rejoin()
    recover_s = time.monotonic() - t_dead
    if ev["degraded_workers"] < 1:
        return _fail(f"no worker entered degraded mode: {ev}", procs)
    if ev["degraded_admissions"] <= 0:
        return _fail(
            "flat-cohort admission did not continue during the "
            f"degraded window: {ev}", procs)
    for _ in range(4):
        rt2.tick()
    dump = rt2.dump()
    got = {name: sorted(keys)
           for name, keys in dump["admitted"].items() if keys}
    if got != expect:
        return _fail(
            f"post-rejoin admitted set diverged from the uninterrupted "
            f"single-process run: {got} != {expect}", procs)
    for name, usage in dump["usage"].items():
        used = sum(usage.get("default", {}).values())
        if used > CPU * 1000:
            return _fail(f"quota oversubscribed on {name}: {used} "
                         f"milli-units > {CPU * 1000}", procs)
    rejected += rt2.listener.rejected_hellos
    rt2.close()  # stops the workers cooperatively
    for p in procs:
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            return _fail("a worker did not stop on close", procs)
    summary = {
        "metric": "fleet_smoke", "ok": True,
        "workers": hosts,
        "tls": True, "auth": True,
        "rejected_hellos": rejected,
        "admitted": sum(len(v) for v in got.values()),
        "degraded_window_ticks": ev["degraded_window_ticks"],
        "degraded_admissions": ev["degraded_admissions"],
        "rejoin_revocations": ev["rejoin_revocations"],
        "time_to_recover_s": round(recover_s, 2),
    }
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
