"""Length-prefixed framing for the multi-host replica transport.

The cross-replica protocol's wire format already IS journal lines —
JSON documents, one logical message each (parallel/replica.py's round /
verdict payloads, the routed store entries of the partitioned watch
stream). This module frames those lines for a byte stream: each frame is
a 4-byte big-endian payload length followed by the UTF-8 JSON payload.

The decoder is a push parser: feed it whatever the socket returned and
it yields every COMPLETE frame, buffering partial ones across reads — a
frame split over ten 1-byte reads decodes identically to one big read.
A torn trailing frame (a writer killed mid-append, the socket analog of
the journal's torn final line) simply stays pending and is dropped with
the connection; the reconnect handshake retransmits it from the sender's
unacked buffer, so a torn write is never half-applied.
"""

from __future__ import annotations

import json
import struct
from typing import List, Optional

HEADER = struct.Struct("!I")
HEADER_SIZE = HEADER.size

# Frames beyond this declare a corrupt stream (a desynced reader parsing
# payload bytes as a header), not a real message: the biggest legitimate
# frames are routed object batches, orders of magnitude below this.
MAX_FRAME_BYTES = 256 << 20


class FrameError(ValueError):
    """Corrupt framing: the stream cannot be resynchronized."""


def encode_frame(payload: bytes) -> bytes:
    if len(payload) > MAX_FRAME_BYTES:
        raise FrameError(
            f"frame of {len(payload)} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte limit")
    return HEADER.pack(len(payload)) + payload


def encode_message(msg) -> bytes:
    """One protocol message as a framed JSON line (compact separators —
    the journal's own encoding)."""
    return encode_frame(
        json.dumps(msg, separators=(",", ":")).encode("utf-8"))


def decode_message(payload: bytes):
    """Inverse of encode_message. Top-level arrays come back as tuples
    so socket-delivered messages index and unpack exactly like the
    pipe/queue transports' native tuples."""
    obj = json.loads(payload.decode("utf-8"))
    if isinstance(obj, list):
        return tuple(obj)
    return obj


class FrameDecoder:
    """Stateful frame reassembly over arbitrary read boundaries."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        """Absorb `data`; return every frame completed by it."""
        self._buf.extend(data)
        frames: List[bytes] = []
        buf = self._buf
        pos = 0
        while True:
            if len(buf) - pos < HEADER_SIZE:
                break
            (length,) = HEADER.unpack_from(buf, pos)
            if length > MAX_FRAME_BYTES:
                raise FrameError(
                    f"declared frame length {length} exceeds the "
                    f"{MAX_FRAME_BYTES}-byte limit (desynced stream)")
            if len(buf) - pos < HEADER_SIZE + length:
                break
            start = pos + HEADER_SIZE
            frames.append(bytes(buf[start:start + length]))
            pos = start + length
        if pos:
            del buf[:pos]
        return frames

    @property
    def pending_bytes(self) -> int:
        """Bytes of an incomplete frame currently buffered (a torn write
        in flight; nonzero at EOF means the peer died mid-frame)."""
        return len(self._buf)

    def take_buffer(self) -> bytes:
        """Hand off the buffered partial-frame bytes (a new decoder can
        resume the stream exactly where this one stopped)."""
        out = bytes(self._buf)
        self._buf.clear()
        return out
