"""Lease arbitration over the channel protocol: no shared filesystem.

`FileLeaseStore` (controllers/leaderelection.py) gives kube-style lease
CAS across processes that share a mount — which a real fleet does not
have. This module moves the same compare-and-swap onto the framed
channel protocol:

  * `LeaseService` — the arbitration authority. It owns one in-process
    `LeaseStore` (or any store with the same interface, e.g. a
    `FileLeaseStore` for durability across coordinator restarts) and
    answers lease RPCs from any channel whose cid starts with
    ``lease/``. Attach it to the coordinator's existing
    `ChannelListener`: lease traffic rides the same port, TLS and
    auth-token guards included.

  * `ChannelLeaseStore` — the client. Same interface as
    `LeaseStore`/`FileLeaseStore` (`try_acquire_or_renew`, `release`,
    `holder`, `transitions`), implemented as blocking request/response
    over a `SocketChannel`. An unreachable service NEVER reports
    acquisition: `try_acquire_or_renew` returns False on timeout (a
    candidate that cannot confirm the CAS must not lead), `release` is
    best-effort, and `holder`/`transitions` fall back to the last
    confirmed value (with `available` False so callers can tell).

Clock semantics match the reference's coordination.k8s.io Lease: the
candidate supplies `now` and the renew/acquire timestamps, so the
store is a pure CAS and the deterministic fake-clock semantics suite
runs identically against all three stores. Production fleets therefore
need loosely synchronized clocks — the same requirement kube's
client-supplied renewTime imposes.
"""

from __future__ import annotations

import threading
import uuid
from typing import Optional

from kueue_tpu.transport.socket_channel import (
    PEER_RESTART,
    ChannelListener,
    SocketChannel,
    WorkerDiedError,
)

LEASE_CID_PREFIX = "lease/"


class LeaseUnavailable(RuntimeError):
    """The lease service did not answer within the deadline."""


class LeaseService:
    """Channel-side lease authority: serves the CAS to every dialer."""

    def __init__(self, store):
        self.store = store
        self.requests = 0
        self.clients = 0
        self._threads = []

    def attach(self, listener: ChannelListener) -> "LeaseService":
        """Serve lease cids on `listener`, chaining (not replacing) any
        existing on_hello hook — join traffic and lease traffic share
        the control-plane port."""
        prev = listener.on_hello

        def hook(cid, chan):
            if isinstance(cid, str) and cid.startswith(LEASE_CID_PREFIX):
                self.serve(cid, chan)
            elif prev is not None:
                prev(cid, chan)

        listener.on_hello = hook
        return self

    def serve(self, cid, chan) -> None:
        self.clients += 1
        t = threading.Thread(target=self._serve_loop, args=(chan,),
                             name=f"lease-{cid}", daemon=True)
        t.start()
        self._threads.append(t)

    def _serve_loop(self, chan) -> None:
        while True:
            try:
                msg = chan.recv()
            except WorkerDiedError:
                return  # client gone
            if not isinstance(msg, (tuple, list)) or len(msg) != 4 \
                    or msg[0] != "lease":
                continue  # restart markers / stray / malformed frames
            _, rid, op, kw = msg
            self.requests += 1
            try:
                result = self._dispatch(op, kw)
                reply = ("lease_reply", rid, result)
            except Exception as exc:  # surface, never kill the loop
                reply = ("lease_err", rid, repr(exc))
            try:
                chan.send(reply)
            except Exception:
                return

    def _dispatch(self, op: str, kw: dict):
        store = self.store
        if op == "acquire":
            return store.try_acquire_or_renew(
                kw["name"], kw["identity"], float(kw["duration"]),
                float(kw["now"]))
        if op == "release":
            store.release(kw["name"], kw["identity"])
            return None
        if op == "holder":
            return store.holder(kw["name"])
        if op == "transitions":
            return store.transitions(kw["name"])
        raise ValueError(f"unknown lease op {op!r}")


class ChannelLeaseStore:
    """Lease CAS client over the channel protocol (LeaseStore API)."""

    def __init__(self, addr, identity: Optional[str] = None,
                 tls_context=None, auth_token: Optional[str] = None,
                 timeout: float = 5.0,
                 chan: Optional[SocketChannel] = None):
        self.identity = identity or uuid.uuid4().hex[:8]
        self.timeout = timeout
        self.available = True
        self.last_error: Optional[str] = None
        self._transitions_cache = 0
        self._holder_cache = ""
        self._lock = threading.Lock()
        self._rid = 0
        self._chan = chan if chan is not None else SocketChannel.connect(
            (addr[0], int(addr[1])),
            cid=f"{LEASE_CID_PREFIX}{self.identity}",
            tls_context=tls_context, auth_token=auth_token,
            restart_markers=True,
            name=f"lease-{self.identity}")

    def _rpc(self, op: str, **kw):
        with self._lock:
            self._rid += 1
            rid = self._rid
            self._chan.send(("lease", rid, op, kw))
            while True:
                try:
                    msg = self._chan.recv(timeout=self.timeout)
                except WorkerDiedError as exc:
                    self.available = False
                    self.last_error = str(exc)
                    raise LeaseUnavailable(
                        f"lease service unreachable: {exc}")
                if msg == PEER_RESTART:
                    # The service restarted mid-request: the request is
                    # gone with the old conversation. Resend it on the
                    # fresh stream.
                    self._chan.send(("lease", rid, op, kw))
                    continue
                if not isinstance(msg, (tuple, list)) or len(msg) < 3 \
                        or msg[1] != rid:
                    continue  # stale reply from a timed-out earlier rpc
                if msg[0] == "lease_err":
                    self.available = False
                    self.last_error = msg[2]
                    raise LeaseUnavailable(f"lease service error: {msg[2]}")
                self.available = True
                return msg[2]

    # -- LeaseStore interface ------------------------------------------------

    def try_acquire_or_renew(self, name: str, identity: str,
                             lease_duration: float, now: float) -> bool:
        try:
            ok = bool(self._rpc("acquire", name=name, identity=identity,
                                duration=lease_duration, now=now))
        except LeaseUnavailable:
            # Unconfirmed CAS == not acquired: a candidate that cannot
            # reach the authority must not lead.
            return False
        if ok:
            with self._lock:
                self._holder_cache = identity
        return ok

    def release(self, name: str, identity: str) -> None:
        try:
            self._rpc("release", name=name, identity=identity)
        except LeaseUnavailable:
            pass  # best-effort: expiry reclaims it anyway

    def holder(self, name: str) -> str:
        try:
            got = self._rpc("holder", name=name)
        except LeaseUnavailable:
            return self._holder_cache
        with self._lock:
            self._holder_cache = got
        return got

    def transitions(self, name: str) -> int:
        try:
            got = int(self._rpc("transitions", name=name))
        except LeaseUnavailable:
            return self._transitions_cache
        with self._lock:
            self._transitions_cache = got
        return got

    def close(self) -> None:
        self._chan.close()
