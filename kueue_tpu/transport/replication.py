"""Coordinator-owned asynchronous replication of shard-group journals.

With per-host state directories, each replica journals its shard groups
LOCALLY (controllers/durable.py), and a fail-over can no longer assume
the adopter reads the dead owner's filesystem. The replication loop
closes that gap: every journal append (and every compaction snapshot)
is tapped as a segment op, shipped to the coordinator with the tick's
barrier reply, and applied here to a per-group replica file on the
coordinator's own disk — asynchronously, off the barrier path, by a
single writer thread. At adoption the coordinator flushes the queue and
ships the replica file's lines to the new owner, which seeds its own
local journal from them and replays.

Replication lag is bounded by the barrier: segments ride the `done`
reply, so the replica copy is complete through the last finished tick.
A worker killed MID-tick loses at most that tick's lines — and those
admissions never reached the parent either (the worker flushes before
`done`), so replay + re-scheduling converge on the identical set; the
multi-host drills pin exactly that.

Segment ops (JSON-safe, they travel the socket transport):
    ["append", <journal line>]          one recorded event
    ["reset", [<line>, ...]]            compaction snapshot (rewrite)
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Dict, List, Optional


class JournalReplicator:
    """Single-writer async applier of journal segment ops."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._q: "queue.Queue" = queue.Queue()
        self._files: Dict[int, object] = {}
        self._lock = threading.Lock()
        self._closed = False
        self.applied_ops = 0
        self.applied_lines = 0
        self.errors = 0
        self.last_error: Optional[str] = None
        self._thread = threading.Thread(
            target=self._run, name="journal-replicator", daemon=True)
        self._thread.start()

    def path(self, gid: int) -> str:
        return os.path.join(self.directory, f"journal-g{gid}.jsonl")

    # -- producer side -------------------------------------------------------

    def submit(self, gid: int, ops: List[list]) -> None:
        """Enqueue one shard group's segment ops (in order)."""
        if ops:
            self._q.put((int(gid), ops))

    def flush(self) -> None:
        """Block until everything submitted so far is on disk (adoption
        reads the replica file next)."""
        self._q.join()

    # -- writer thread -------------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                gid, ops = item
                try:
                    self._apply(gid, ops)
                except Exception as exc:
                    # The writer thread must OUTLIVE a bad segment
                    # (ENOSPC, EACCES, a corrupt op): dying here would
                    # leave every future flush()/read_lines() blocked
                    # on Queue.join() forever — inside the runtime
                    # lock, wedging fail-over with no error anywhere.
                    # Count + surface and keep consuming instead.
                    self.errors += 1
                    self.last_error = repr(exc)
                    import sys

                    print(f"kueue-tpu: journal replication of group "
                          f"{gid} failed: {exc!r}", file=sys.stderr,
                          flush=True)
            finally:
                self._q.task_done()

    def _apply(self, gid: int, ops: List[list]) -> None:
        with self._lock:
            for op in ops:
                kind = op[0]
                if kind == "append":
                    f = self._file(gid)
                    f.write(op[1] if op[1].endswith("\n") else op[1] + "\n")
                    self.applied_lines += 1
                elif kind == "reset":
                    # Compaction snapshot: atomic rewrite, like the
                    # journal's own compaction.
                    path = self.path(gid)
                    tmp = f"{path}.{os.getpid()}.tmp"
                    # The lock only serializes this dedicated writer
                    # thread against read_lines()/close(); file I/O IS
                    # the thread's job, and adoption must not read a
                    # half-rewritten replica.
                    with open(tmp, "w",  # kueuelint: disable=LOCK01
                              encoding="utf-8") as f:
                        for line in op[1]:
                            f.write(line if line.endswith("\n")
                                    else line + "\n")
                        f.flush()
                        # The snapshot fsync IS this thread's purpose
                        # (durability point of the compaction rewrite);
                        # a stalled disk is a host fault the disk-fault
                        # drills cover, and the loop survives errors
                        # (counted + surfaced, never wedged).
                        os.fsync(f.fileno())  # kueuelint: disable=THR02
                    old = self._files.pop(gid, None)
                    if old is not None:
                        old.close()
                    os.replace(tmp, path)
                    self.applied_lines += len(op[1])
                self.applied_ops += 1

    def _file(self, gid: int):
        f = self._files.get(gid)
        if f is None:
            f = self._files[gid] = open(self.path(gid), "a",
                                        encoding="utf-8")
        return f

    # -- adoption side -------------------------------------------------------

    def read_lines(self, gid: int) -> List[str]:
        """The replicated journal content for one shard group (flush
        first so in-flight segments land)."""
        self.flush()
        with self._lock:
            f = self._files.get(gid)
            if f is not None:
                f.flush()
        path = self.path(gid)
        if not os.path.exists(path):
            return []
        with open(path, "r", encoding="utf-8") as f:
            return [line.rstrip("\n") for line in f if line.strip()]

    def bootstrap_lines(self, gid: int, floor: int = 256):
        """Snapshot-shipped bootstrap for an adopting worker: compact the
        replicated history down to live state so the rejoin replays
        O(live-state) lines, not O(history).

        Returns (lines, meta) where meta records history_lines/lines/
        snapshot for the rejoin-cost evidence. Histories at or under
        `floor` ship raw (a snapshot would not pay for itself); so does
        anything the scratch replay cannot parse — raw lines are the
        lossless fallback. When a snapshot IS built, it is also pushed
        through the ("reset", ...) seam so this coordinator replica file
        compacts to match what was shipped."""
        lines = self.read_lines(gid)
        history = len(lines)
        meta = {"history_lines": history, "lines": history,
                "snapshot": False}
        if history <= max(int(floor), 0):
            return lines, meta
        import json

        from kueue_tpu.api.serialization import encode as serialization_encode
        from kueue_tpu.controllers import store as store_mod
        from kueue_tpu.controllers.durable import KIND_ORDER, Journal

        scratch = store_mod.Store()
        try:
            for line in lines:
                Journal._apply(scratch, json.loads(line))
        except Exception:
            # A line the scratch replay cannot digest: ship the raw
            # history — the adopter's own replay has the torn/corrupt
            # recovery machinery, this fast path does not.
            return lines, meta
        snapshot = []
        for kind in KIND_ORDER:
            for obj in scratch.list(kind):
                entry = {"type": store_mod.ADDED, "kind": kind,
                         "key": store_mod._obj_key(kind, obj),
                         "object": serialization_encode(kind, obj)}
                snapshot.append(json.dumps(entry, separators=(",", ":")))
        if len(snapshot) >= history:
            return lines, meta  # no shrink: raw is strictly simpler
        self.submit(gid, [("reset", snapshot)])
        self.flush()
        meta = {"history_lines": history, "lines": len(snapshot),
                "snapshot": True}
        return snapshot, meta

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._q.put(None)
        self._thread.join(timeout=10)
        with self._lock:
            for f in self._files.values():
                f.close()
            self._files.clear()


def host_state_dir(state_dir: str, host_id: str) -> str:
    """One emulated host's private state directory (its journals live
    here; nothing else reads it — fail-over goes through replication)."""
    path = os.path.join(state_dir, host_id)
    os.makedirs(path, exist_ok=True)
    return path
