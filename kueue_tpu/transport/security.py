"""Channel security: TLS contexts + shared-token auth for the listener.

A real fleet's control-plane port is reachable by more than the
control plane, so the `ChannelListener` grows the two guards every
kube-ish join path has: transport encryption (TLS on the accept loop,
`--tls-cert/--tls-key`) and a shared bearer token carried in the hello
frame (`--auth-token`). Both are optional and independent; rejected
hellos are counted (`ChannelListener.rejected_hellos`) and logged, and
surface in `kueue_channel_rejected_hellos_total` — a nonzero rate on a
production listener means something other than your workers is dialing
the control plane.

The worker side trusts exactly the coordinator's certificate: the same
`--tls-cert` file doubles as the dial-side CA anchor (self-signed
single-cert deployments — the fleet-smoke shape — need no real PKI).
Hostname checking is off because fleet workers dial by address, not by
name; the cert pin is the identity.

`generate_self_signed` shells out to the `openssl` CLI (no python
crypto dependency) so tests and `make fleet-smoke` can mint a
throwaway cert; callers must skip TLS coverage when the binary is
absent (`openssl_available`).
"""

from __future__ import annotations

import os
import shutil
import ssl
import subprocess
from typing import Tuple


def server_tls_context(certfile: str, keyfile: str) -> ssl.SSLContext:
    """The listener's accept-side context: present `certfile`, require
    nothing from the client (identity is the auth token's job)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile=certfile, keyfile=keyfile)
    return ctx


def client_tls_context(cafile: str) -> ssl.SSLContext:
    """The worker's dial-side context: trust exactly the coordinator's
    certificate (the pin), no hostname check (workers dial addresses)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_REQUIRED
    ctx.load_verify_locations(cafile=cafile)
    return ctx


def openssl_available() -> bool:
    return shutil.which("openssl") is not None


def generate_self_signed(directory: str, cn: str = "kueue-tpu-coordinator",
                         days: int = 3650) -> Tuple[str, str]:
    """Mint a self-signed cert + key under `directory` via the openssl
    CLI; returns (certfile, keyfile). Raises RuntimeError when openssl
    is unavailable or fails — callers gate on `openssl_available`."""
    if not openssl_available():
        raise RuntimeError("openssl CLI not found; cannot mint a "
                           "self-signed certificate")
    os.makedirs(directory, exist_ok=True)
    cert = os.path.join(directory, "coordinator.crt")
    key = os.path.join(directory, "coordinator.key")
    proc = subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", str(days),
         "-subj", f"/CN={cn}",
         "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost"],
        capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"openssl failed: {proc.stderr.strip()}")
    return cert, key
