"""Reliable framed socket channel: the replica protocol over real TCP.

Implements the existing `parallel.replica.ReplicaChannel` seam — the
same `send`/`recv` the pipe and loopback-queue transports implement —
over length-prefixed JSON frames (framing.py) with exactly-once in-order
delivery across reconnects:

  * every data frame carries a sequence number; the receiver delivers
    in sequence order (out-of-order frames are held, duplicates
    dropped) and acks cumulatively;
  * the sender keeps every unacked frame; a reconnect handshake
    exchanges each side's next-expected sequence and retransmits the
    gap — a connection severed mid-stream (process kill, injected
    drop fault, torn write) resumes with nothing lost or doubled;
  * partial reads reassemble through `FrameDecoder`; a torn trailing
    frame dies with its connection and is retransmitted whole.

Topology: the coordinator host runs ONE `ChannelListener`; each replica
host dials it and identifies itself with a hello frame, so N replicas
need N outbound connections and one listening port — the kube-ish
"workers dial the control plane" shape. Either side may lose the socket;
only the replica redials (the listener re-binds the endpoint on the
new connection's hello).

Faults (faults.py) inject at the data-frame write: delay sleeps, drop
severs (the reconnect machinery is the retransmission layer), reorder
swaps adjacent frames (absorbed by receiver resequencing).
"""

from __future__ import annotations

import socket
import ssl
import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import queue as queue_mod

from kueue_tpu.transport.faults import (
    DELAY,
    DROP,
    REORDER,
    FaultInjector,
    FaultPlan,
)
from kueue_tpu.transport.framing import (
    FrameDecoder,
    FrameError,
    decode_message,
    encode_message,
)

_CLOSED = object()

# In-band marker delivered through `recv()` when the PEER came back as a
# new incarnation (its hello carries a fresh session id): every sequence
# number of the old conversation is void, the channel has already reset
# itself, and the application layer must re-handshake (the worker's
# re-join path). Only channels that opted in (`restart_markers=True`)
# deliver it — everyone else just gets the silent reset.
PEER_RESTART = ("__peer_restart__",)

# Reconnect backoff (connector side): first retry fast, cap low — the
# drills sever connections constantly and the barrier is waiting.
_RECONNECT_BASE_S = 0.02
_RECONNECT_MAX_S = 1.0
_HELLO_TIMEOUT_S = 10.0
# Blocked-write ceiling: acks are written from the READER thread under
# the write lock, so two peers simultaneously pushing large frames into
# full TCP buffers could deadlock symmetrically (neither reader drains
# because both are stuck in sendall). A bounded send converts that into
# a severed connection — which the seq/ack/resume layer recovers.
_SEND_TIMEOUT_S = 30.0


class ChannelClosed(RuntimeError):
    pass


class SocketChannel:
    """One end of a reliable message channel (ReplicaChannel interface).

    Built either by `SocketChannel.connect` (replica side: dials and
    redials the listener) or by `ChannelListener.endpoint` (coordinator
    side: passive, rebound by each hello)."""

    def __init__(self, cid, faults: Optional[FaultInjector] = None,
                 name: str = "", auth_token: Optional[str] = None,
                 tls_context: Optional[ssl.SSLContext] = None,
                 restart_markers: bool = False):
        self.cid = cid
        self.name = name or f"chan-{cid}"
        self._faults = faults
        # This channel's incarnation id: a fresh one per construction,
        # carried in every hello. The peer detects a restart (all old
        # sequence numbers void) by the session id changing.
        self.session = uuid.uuid4().hex[:12]
        self._peer_session: Optional[str] = None
        self._auth_token = auth_token
        self._tls = tls_context
        self.restart_markers = restart_markers
        self.peer_restarts = 0
        self._in_q: "queue_mod.Queue" = queue_mod.Queue()
        self._wlock = threading.RLock()
        self._out_seq = 0
        self._out_buf: "OrderedDict[int, object]" = OrderedDict()
        self._in_next = 0
        self._in_hold: Dict[int, object] = {}
        self._sock: Optional[socket.socket] = None
        self._sock_gen = 0
        self._closed = False
        self._held_frame = None  # reorder fault: frame awaiting a swap
        # Frames that arrived ahead of sequence and were held for
        # resequencing (drill evidence that reordering really happened).
        self.resequenced = 0
        # Connector-side only:
        self._addr: Optional[Tuple[str, int]] = None
        self._dialer: Optional[threading.Thread] = None
        self._disconnected = threading.Event()
        self._disconnected.set()

    # -- construction --------------------------------------------------------

    @classmethod
    def connect(cls, addr, cid, faults: Optional[FaultInjector] = None,
                plan: Optional[FaultPlan] = None,
                name: str = "", auth_token: Optional[str] = None,
                tls_context: Optional[ssl.SSLContext] = None,
                restart_markers: bool = False) -> "SocketChannel":
        """Replica-side channel: dial `addr`, identify as `cid`, redial
        forever on loss until closed."""
        if faults is None and plan is not None:
            faults = plan.injector(cid)
        chan = cls(cid, faults=faults, name=name, auth_token=auth_token,
                   tls_context=tls_context,
                   restart_markers=restart_markers)
        chan._addr = (addr[0], int(addr[1]))
        chan._dialer = threading.Thread(
            target=chan._dial_loop, name=f"dial-{chan.name}", daemon=True)
        chan._dialer.start()
        return chan

    # -- ReplicaChannel ------------------------------------------------------

    def send(self, msg) -> None:
        """Enqueue + best-effort write. Never raises on connection loss:
        the frame stays in the unacked buffer and the reconnect
        handshake retransmits it."""
        with self._wlock:
            if self._closed:
                raise ChannelClosed(f"{self.name} is closed")
            seq = self._out_seq
            self._out_seq = seq + 1
            self._out_buf[seq] = msg
            self._write_data(seq, msg)

    def recv(self, timeout: Optional[float] = None):
        try:
            item = self._in_q.get(timeout=timeout)
        except queue_mod.Empty:
            raise WorkerDiedError(
                f"{self.name}: no message within {timeout}s")
        if item is _CLOSED:
            raise WorkerDiedError(f"{self.name}: channel closed")
        return item

    def close(self) -> None:
        with self._wlock:
            if self._closed:
                return
            self._closed = True
            self._drop_socket()
        self._in_q.put(_CLOSED)

    # -- wire ----------------------------------------------------------------

    def _write_frame(self, obj) -> bool:
        """Write one frame on the current socket; False (and socket
        dropped) on failure. Caller holds _wlock."""
        sock = self._sock
        if sock is None:
            return False
        try:
            sock.sendall(encode_message(obj))
            return True
        except OSError:
            self._drop_socket()
            return False

    def _write_data(self, seq: int, msg) -> None:
        """Data-frame write with fault injection. Caller holds _wlock."""
        frame = {"t": "d", "s": seq, "m": msg}
        faults = self._faults
        if faults is None or self._sock is None:
            self._flush_held()
            self._write_frame(frame)
            return
        action = faults.next_action()
        if action == DROP:
            # Model packet loss at the recoverable layer: sever. The
            # unacked buffer (this frame included) retransmits on the
            # reconnect handshake.
            self._drop_socket()
            return
        if action == REORDER:
            if self._held_frame is None:
                # Hold this frame so the NEXT one passes it on the wire
                # (the actual swap happens in _flush_held below, which
                # writes the newer frame FIRST). If nothing follows, a
                # short timer flushes it so a quiet channel cannot
                # stall behind its own fault.
                self._held_frame = frame
                gen = self._sock_gen
                t = threading.Timer(0.01, self._flush_held_timer,
                                    args=(gen,))
                t.daemon = True
                t.start()
            else:
                # Already holding one: emit this pair swapped.
                held, self._held_frame = self._held_frame, None
                self._write_frame(frame)
                self._write_frame(held)
            return
        if action == DELAY:
            time.sleep(self._faults.plan.delay_ms / 1000.0)
        # Current frame FIRST, held frame after: a held frame reaches
        # the wire one slot late — genuinely out of order, which the
        # receiver's resequencing absorbs (and the drills prove).
        self._write_frame(frame)
        self._flush_held()

    def _flush_held(self) -> None:
        """Emit the reorder-held frame, if any. Caller holds _wlock."""
        held, self._held_frame = self._held_frame, None
        if held is not None:
            self._write_frame(held)

    def _flush_held_timer(self, gen: int) -> None:
        with self._wlock:
            if not self._closed and self._sock_gen == gen:
                self._flush_held()

    def _drop_socket(self) -> None:
        """Caller holds _wlock."""
        sock, self._sock = self._sock, None
        self._sock_gen += 1
        self._held_frame = None
        if sock is not None:
            try:
                # shutdown BEFORE close: on Linux, close() does not
                # wake a thread blocked in recv() — the kernel socket
                # (and its port) would linger until the recv timeout.
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._disconnected.set()

    # -- attachment (both sides) --------------------------------------------

    def attach(self, sock: socket.socket, peer_rx: Optional[int] = None,
               send_hello: bool = False, preload: bytes = b"") -> None:
        """Adopt a connected socket: start its reader, optionally greet,
        and retransmit everything the peer has not seen (`peer_rx` is
        the peer's next-expected sequence from its hello; None = unknown
        yet, retransmission waits for the peer's hello frame).
        `preload` is residual stream bytes a handshake read past the
        hello — the reader resumes mid-frame from them."""
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        # Bounded blocking for BOTH directions (one socket timeout
        # governs send and recv): a send stuck past the ceiling severs
        # the connection instead of deadlocking the reader thread; the
        # reader treats the same timeout as "idle, keep reading".
        sock.settimeout(_SEND_TIMEOUT_S)
        with self._wlock:
            if self._closed:
                try:
                    sock.close()
                except OSError:
                    pass
                return
            self._drop_socket()
            self._sock = sock
            self._sock_gen += 1
            gen = self._sock_gen
            self._disconnected.clear()
            if send_hello:
                self._write_frame(self.hello_frame())
            if peer_rx is not None:
                self._retransmit(peer_rx)
        reader = threading.Thread(
            target=self._read_loop, args=(sock, gen, preload),
            name=f"read-{self.name}", daemon=True)
        reader.start()

    def hello_frame(self) -> dict:
        """This side's greeting: identity, next-expected sequence, our
        session (incarnation) id, and the auth token when configured.
        Caller holds _wlock (reads _in_next)."""
        frame = {"t": "h", "id": self.cid, "rx": self._in_next,
                 "sess": self.session}
        if self._auth_token:
            frame["tok"] = self._auth_token
        return frame

    def _note_peer_session(self, sess: Optional[str]) -> bool:
        """Track the peer's incarnation id from its hello. A CHANGED id
        means the peer restarted: every sequence number of the old
        conversation is void on its side, so restart ours to match —
        unacked frames are lost by definition (the process that would
        have consumed them is gone); the application re-handshakes over
        the fresh stream. Returns True on a detected restart. Caller
        holds _wlock."""
        if sess is None:
            return False
        restarted = (self._peer_session is not None
                     and self._peer_session != sess)
        if restarted:
            self._out_seq = 0
            self._out_buf.clear()
            self._in_next = 0
            self._in_hold.clear()
            self.peer_restarts += 1
        self._peer_session = sess
        return restarted

    def _retransmit(self, peer_rx: int) -> None:
        """Resend every buffered frame the peer has not delivered, and
        drop the ones it has (an ack can be lost with the connection).
        Caller holds _wlock."""
        for seq in [s for s in self._out_buf if s < peer_rx]:
            del self._out_buf[seq]
        for seq, msg in list(self._out_buf.items()):
            if not self._write_frame({"t": "d", "s": seq, "m": msg}):
                return

    # -- reader --------------------------------------------------------------

    def _read_loop(self, sock: socket.socket, gen: int,
                   preload: bytes = b"") -> None:
        decoder = FrameDecoder()
        try:
            if preload:
                for payload in decoder.feed(preload):
                    self._on_frame(decode_message(payload))
            while True:
                try:
                    data = sock.recv(1 << 16)
                except socket.timeout:
                    continue  # idle channel, not a dead one
                except OSError:
                    break
                if not data:
                    break
                try:
                    frames = decoder.feed(data)
                except FrameError:
                    break
                for payload in frames:
                    self._on_frame(decode_message(payload))
        finally:
            with self._wlock:
                if self._sock is sock:
                    self._drop_socket()

    def _on_frame(self, frame) -> None:
        t = frame.get("t")
        if t == "d":
            msg = frame["m"]
            if isinstance(msg, list):
                # The envelope decoded as a dict, so the message itself
                # is still a JSON array: deliver it as the tuple the
                # pipe/queue transports would have delivered.
                msg = tuple(msg)
            self._on_data(frame["s"], msg)
        elif t == "a":
            with self._wlock:
                acked = frame["s"]
                for seq in [s for s in self._out_buf if s <= acked]:
                    del self._out_buf[seq]
        elif t == "h":
            # Peer's (re)connect greeting: its next-expected sequence.
            restarted = False
            with self._wlock:
                restarted = self._note_peer_session(frame.get("sess"))
                self._retransmit(int(frame["rx"]))
            if restarted and self.restart_markers:
                self._in_q.put(PEER_RESTART)

    def _on_data(self, seq: int, msg) -> None:
        with self._wlock:
            if seq == self._in_next:
                self._in_next += 1
                self._in_q.put(msg)
                hold = self._in_hold
                while self._in_next in hold:
                    self._in_q.put(hold.pop(self._in_next))
                    self._in_next += 1
            elif seq > self._in_next:
                self._in_hold[seq] = msg
                self.resequenced += 1
            # seq < in_next: duplicate of a delivered frame; ack only.
            self._write_frame({"t": "a", "s": self._in_next - 1})

    # -- connector loop ------------------------------------------------------

    def _is_closed(self) -> bool:
        with self._wlock:
            return self._closed

    def _dial_loop(self) -> None:
        attempt = 0
        while True:
            self._disconnected.wait()
            if self._is_closed():
                return
            try:
                sock = socket.create_connection(self._addr, timeout=5.0)
                if sock.getsockname() == sock.getpeername():
                    # Loopback self-connect (TCP simultaneous open): a
                    # dial aimed at a dead port can land on ITSELF when
                    # the kernel picks the target as the ephemeral
                    # source port. The phantom "connection" would echo
                    # our own frames back and squat on the port the
                    # real listener needs — reject and back off.
                    sock.close()
                    raise OSError("self-connect rejected")
                if self._tls is not None:
                    # The TLS handshake rides the dial loop: a reject
                    # (bad cert, plaintext listener) retries with the
                    # same backoff as a refused connection.
                    sock = self._tls.wrap_socket(
                        sock, server_hostname=self._addr[0])
            except OSError:  # ssl.SSLError is an OSError subclass
                attempt += 1
                time.sleep(min(_RECONNECT_BASE_S * (2 ** min(attempt, 8)),
                               _RECONNECT_MAX_S))
                continue
            attempt = 0
            # Greet with our identity + next-expected seq; the listener
            # answers with its own hello, which triggers retransmit.
            self.attach(sock, peer_rx=None, send_hello=True)
            # Wait until this socket dies before dialing again.
            while not self._disconnected.wait(timeout=0.05):
                if self._is_closed():
                    return
            if self._is_closed():
                return

    # -- drills --------------------------------------------------------------

    def sever(self) -> None:
        """Drop the live connection (drill hook): everything unacked
        retransmits on the next handshake."""
        with self._wlock:
            self._drop_socket()

    @property
    def connected(self) -> bool:
        with self._wlock:
            return self._sock is not None

    @property
    def unacked(self) -> int:
        with self._wlock:
            return len(self._out_buf)


class WorkerDiedError(RuntimeError):
    """recv timeout / closed channel — the transport-level analog of
    replica_runtime.WorkerDied (kept separate so transport/ has no
    import cycle with controllers/; the runtime maps one to the
    other)."""


class ChannelListener:
    """The coordinator host's accept loop: one listening socket, one
    passive `SocketChannel` endpoint per replica id, re-bound on every
    hello (reconnects included)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 plan: Optional[FaultPlan] = None,
                 tls_context: Optional[ssl.SSLContext] = None,
                 auth_token: Optional[str] = None,
                 on_hello=None):
        self._plan = plan
        self._tls = tls_context
        self._auth_token = auth_token
        # on_hello(cid, chan) fires after a NEW endpoint's first hello
        # binds (not on reconnects of a known cid) — the remote-join and
        # lease-service attach points.
        self.on_hello = on_hello
        # Rejected hellos: bad/missing auth token, TLS handshake
        # failures, malformed greetings. Counted + logged — on a real
        # fleet's port a nonzero rate is a probe, not noise.
        self.rejected_hellos = 0
        self._endpoints: Dict[object, SocketChannel] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # Bounded bind retry: a coordinator RESTART re-binds a port the
        # dead incarnation's workers are actively redialing, and a
        # loopback redial can transiently self-connect (simultaneous
        # open) and squat on the port until the dialer rejects it —
        # seconds, not forever, so retry instead of failing the
        # restart.
        deadline = time.monotonic() + 10.0
        while True:
            try:
                self._sock.bind((host, port))
                break
            except OSError:
                if port == 0 or time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        self._sock.listen(64)
        self.address: Tuple[str, int] = self._sock.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="chan-listener", daemon=True)
        self._accept_thread.start()

    def endpoint(self, cid, name: str = "") -> SocketChannel:
        """The coordinator-side channel for replica `cid` (created on
        first use; the replica may not have dialed yet — sends buffer
        until its hello arrives)."""
        with self._lock:
            chan = self._endpoints.get(cid)
            if chan is None:
                faults = self._plan.injector(
                    f"listener/{cid}") if self._plan else None
                chan = SocketChannel(cid, faults=faults,
                                     name=name or f"endpoint-{cid}")
                self._endpoints[cid] = chan
            return chan

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._handshake, args=(sock,),
                             name="chan-hello", daemon=True).start()

    def _reject(self, sock: socket.socket, reason: str,
                detail: str = "") -> None:
        import sys

        from kueue_tpu.metrics import REGISTRY

        self.rejected_hellos += 1
        REGISTRY.channel_rejected_hellos_total.inc(reason)
        print(f"kueue-tpu: listener rejected hello ({reason})"
              + (f": {detail}" if detail else ""),
              file=sys.stderr, flush=True)
        try:
            sock.close()
        except OSError:
            pass

    def _handshake(self, sock: socket.socket) -> None:
        """TLS-wrap (when configured), read the dialer's hello, check
        its auth token, bind its endpoint, answer with ours (which
        carries our next-expected seq and triggers the peer's
        retransmission)."""
        if self._tls is not None:
            try:
                sock.settimeout(_HELLO_TIMEOUT_S)
                sock = self._tls.wrap_socket(sock, server_side=True)
            except (OSError, ssl.SSLError) as exc:
                self._reject(sock, "tls", repr(exc))
                return
        decoder = FrameDecoder()
        sock.settimeout(_HELLO_TIMEOUT_S)
        hello = None
        extra: list = []
        try:
            while hello is None:
                data = sock.recv(1 << 16)
                if not data:
                    sock.close()
                    return
                frames = decoder.feed(data)
                if frames:
                    hello = decode_message(frames[0])
                    extra = frames[1:]
        except (OSError, FrameError):
            try:
                sock.close()
            except OSError:
                pass
            return
        sock.settimeout(None)
        if not isinstance(hello, dict) or hello.get("t") != "h":
            self._reject(sock, "malformed", repr(hello)[:80])
            return
        if self._auth_token and hello.get("tok") != self._auth_token:
            self._reject(sock, "auth",
                         f"peer {hello.get('id')!r} presented a "
                         + ("wrong" if hello.get("tok") else "missing")
                         + " token")
            return
        cid = hello.get("id")
        with self._lock:
            fresh = cid not in self._endpoints
        chan = self.endpoint(cid)
        # Session FIRST: frames glued to a restarted peer's hello are
        # numbered in the NEW conversation — dispatching them before
        # the reset would misread them under the old sequence space
        # (dropped as duplicates now, re-delivered after the peer's
        # retransmit: duplicate delivery on an exactly-once channel).
        with chan._wlock:
            restarted = chan._note_peer_session(hello.get("sess"))
        if restarted and chan.restart_markers:
            chan._in_q.put(PEER_RESTART)
        # Frames that arrived glued to the hello dispatch BEFORE the
        # reader starts (resequencing absorbs any interleaving); the
        # decoder's residual partial-frame bytes ride into the reader.
        for payload in extra:
            chan._on_frame(decode_message(payload))
        chan.attach(sock, peer_rx=int(hello.get("rx", 0)),
                    preload=decoder.take_buffer())
        with chan._wlock:
            chan._write_frame(chan.hello_frame())
        if fresh and self.on_hello is not None:
            self.on_hello(cid, chan)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            endpoints = list(self._endpoints.values())
        try:
            # shutdown wakes the thread parked in accept() — without it
            # the LISTEN socket survives close() on Linux and keeps
            # accepting dials into a backlog nobody reads, wedging
            # every reconnecting worker until their hello timeouts.
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        for chan in endpoints:
            chan.close()
