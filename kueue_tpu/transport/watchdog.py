"""Barrier stall watchdog: name the process that is holding up the tick.

The replica tick is a barrier — every live replica must answer the
round, and the coordinator must answer every replica. Before this
module, a stalled participant (SIGSTOPped worker, wedged coordinator)
surfaced as a generic timeout at best and a silent forever-retry at
worst (a stopped process keeps its journal flocks, so group adoption
span forever with no error anyone could see). `BarrierStallError`
carries the offending pid / host / replica id and the barrier round
number, so the error that finally surfaces says exactly WHO missed WHAT.
"""

from __future__ import annotations

from typing import Optional

from kueue_tpu import knobs


def barrier_deadline(default: float) -> float:
    """Seconds a barrier participant may lag before the watchdog calls
    it stalled (`KUEUE_TPU_BARRIER_DEADLINE` overrides)."""
    override = knobs.raw("KUEUE_TPU_BARRIER_DEADLINE")
    if override:
        try:
            return float(override)
        except ValueError:
            pass
    return default


class BarrierStallError(RuntimeError):
    """A barrier participant missed its deadline.

    `who` is "replica" or "coordinator"; pid/host identify the process
    (host is the emulated host id in multi-host mode), `round_no` the
    barrier round that stalled, `phase` which barrier wait noticed
    ("pretick" / "round" / "verdicts" / "done")."""

    def __init__(self, who: str, *, wid: Optional[int] = None,
                 pid: Optional[int] = None, host: Optional[str] = None,
                 round_no: Optional[int] = None, phase: str = "",
                 timeout_s: Optional[float] = None):
        self.who = who
        self.wid = wid
        self.pid = pid
        self.host = host
        self.round_no = round_no
        self.phase = phase
        self.timeout_s = timeout_s
        ident = who
        if wid is not None:
            ident += f" {wid}"
        if pid is not None:
            ident += f" (pid {pid}"
            ident += f", {host})" if host else ")"
        elif host:
            ident += f" ({host})"
        msg = f"barrier stall: {ident} missed round {round_no}"
        if phase:
            msg += f" at the {phase} wait"
        if timeout_s is not None:
            msg += f" beyond the {timeout_s:g}s deadline"
        super().__init__(msg)

    def to_dict(self) -> dict:
        return {"who": self.who, "wid": self.wid, "pid": self.pid,
                "host": self.host, "round": self.round_no,
                "phase": self.phase, "timeout_s": self.timeout_s,
                "error": str(self)}
