"""kueue_tpu.twin: the digital twin — discrete-event trace replay on
the real decision kernels.

A capacity simulator that is not a model: the twin builds the same
Framework the fuzz lattice builds (flavor-fit, preemption,
fair-sharing, cohort quota — the real kernels) and drives it at
virtual time from a trace, so a multi-day 10^6-workload arrival
process replays in minutes in one process while making exactly the
decisions production would make. Cross-check mode proves it: on
lattice-sized scenarios the twin's decision trail is byte-identical
to lattice.drive().

    trace.py       Trace model + JSON formats (also loads fuzz
                   scenarios/reproducers), twin_cluster()
    generators.py  seeded lazy arrival shapes (diurnal, heavy-tailed,
                   adversarial-burst, Mesos-style mix)
    engine.py      TwinEngine: paced + event-driven virtual-time replay
    whatif.py      capacity sweeps + comparison report
    crosscheck.py  twin-vs-drive() byte-identity oracle
    __main__.py    python -m kueue_tpu.twin
"""

from kueue_tpu.twin.engine import DurationModel, TwinEngine, replay
from kueue_tpu.twin.trace import Trace, twin_cluster
from kueue_tpu.twin.whatif import (CapacityConfig, apply_config,
                                   default_sweep, parse_config, sweep)

__all__ = [
    "CapacityConfig", "DurationModel", "Trace", "TwinEngine",
    "apply_config", "default_sweep", "parse_config", "replay",
    "sweep", "twin_cluster",
]
