"""CLI: `python -m kueue_tpu.twin` — replay, what-if sweep, cross-check.

Replay a trace file (kueuetwin-trace/v1, a kueuefuzz/v1 scenario, or a
kueuefuzz-repro/v1 reproducer) or synthesize one from a generator
shape, on the real decision kernels at virtual time:

  # one replay, metrics to stdout
  python -m kueue_tpu.twin --shape diurnal_heavy --workloads 100000 \\
      --days 3 --out /tmp/twin.json

  # the capacity question: sweep 3 configs over one 10^6 trace
  python -m kueue_tpu.twin --shape diurnal_heavy --workloads 1000000 \\
      --days 3 --whatif baseline --whatif quota-75:quota=0.75 \\
      --whatif quota-150:quota=1.5 --out /tmp/twin-report.json

  # hold the twin to byte identity with lattice.drive()
  python -m kueue_tpu.twin --crosscheck 6
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _pin_cpu_backend() -> None:
    # Same pin as kueue_tpu.fuzz.__main__: CPU + 2 virtual host
    # devices before jax initializes, so sharded configs run anywhere.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    xf = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xf:
        os.environ["XLA_FLAGS"] = (
            xf + " --xla_force_host_platform_device_count=2").strip()


def build_trace(args) -> "Trace":
    from kueue_tpu.twin import generators, trace as trace_mod

    if args.trace:
        return trace_mod.Trace.load(args.trace)
    gen = {"shape": args.shape, "workloads": args.workloads,
           "days": args.days, "seed": args.seed, "cqs": args.cqs,
           "mean_duration_s": args.mean_duration_s}
    if args.cpu_quota is not None:
        quota = {"cpu": args.cpu_quota,
                 "memory_gi": 4 * args.cpu_quota}
    else:
        # Size the uniform cluster to carry the spec's offered load —
        # the baseline should be feasible so the sweep measures the
        # perturbations, not an arbitrary under-provisioning.
        quota = generators.size_cluster_quota(gen, args.cqs)
    cluster = trace_mod.twin_cluster(
        num_cqs=args.cqs, num_cohorts=args.cohorts,
        num_flavors=args.flavors, cpu_quota=quota["cpu"],
        memory_gi_quota=quota["memory_gi"], hetero=args.hetero)
    return trace_mod.Trace(
        name=f"{args.shape}-{args.workloads}x{args.days}d",
        seed=args.seed, cluster=cluster, generator=gen,
        tick_interval_s=args.tick_interval_s,
        meta={"sized_quota": quota})


def main(argv=None) -> int:
    _pin_cpu_backend()
    ap = argparse.ArgumentParser(
        prog="python -m kueue_tpu.twin",
        description="digital twin: discrete-event capacity simulator "
                    "on the real decision kernels")
    src = ap.add_argument_group("trace source")
    src.add_argument("--trace", metavar="FILE",
                     help="replay this trace file (kueuetwin-trace/v1, "
                          "kueuefuzz/v1, or kueuefuzz-repro/v1)")
    src.add_argument("--shape", default="diurnal_heavy",
                     help="generator shape (diurnal, heavy_tailed, "
                          "diurnal_heavy, adversarial_burst, mix)")
    src.add_argument("--workloads", type=int, default=100_000)
    src.add_argument("--days", type=float, default=1.0)
    src.add_argument("--seed", type=int, default=0)
    src.add_argument("--cqs", type=int, default=64)
    src.add_argument("--cohorts", type=int, default=16)
    src.add_argument("--flavors", type=int, default=2)
    src.add_argument("--hetero", action="store_true")
    src.add_argument("--cpu-quota", type=int, default=None,
                     help="per-CQ per-flavor cpu quota (default: "
                          "sized from the generator's offered load)")
    src.add_argument("--mean-duration-s", type=float, default=1800.0)
    src.add_argument("--tick-interval-s", type=float, default=600.0)
    run = ap.add_argument_group("modes")
    run.add_argument("--whatif", action="append", metavar="SPEC",
                     help="sweep configuration 'name[:k=v,...]' (keys: "
                          "quota, flavor.<name>, speed.<name>, shards, "
                          "engine); repeat for more configs; first is "
                          "the baseline; bare '--whatif default' runs "
                          "baseline/quota-75/quota-150")
    run.add_argument("--crosscheck", type=int, metavar="N",
                     help="byte-compare twin replay vs lattice.drive() "
                          "on N generator seeds instead of replaying")
    run.add_argument("--start-seed", type=int, default=0)
    run.add_argument("--engine", default="jax",
                     help="solver engine: jax | host | referee (the "
                          "sequential reference — fastest for huge "
                          "replays, decision-identical per the fuzz "
                          "lattice); also the default for what-if "
                          "configs that don't set engine=")
    run.add_argument("--default-duration-s", type=float, default=900.0,
                     help="DurationModel fallback for workloads with "
                          "no declared duration_s")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the full JSON report here")
    ap.add_argument("--save-trace", default=None, metavar="FILE",
                    help="also save the (synthesized) trace file")
    args = ap.parse_args(argv)

    if args.crosscheck is not None:
        from kueue_tpu.twin import crosscheck

        report = crosscheck.crosscheck_seeds(
            args.crosscheck, start_seed=args.start_seed)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(report, f, indent=1)
        print(json.dumps({
            "metric": "twin_crosscheck",
            "scenarios": report["scenarios"],
            "engines": report["engines"],
            "mismatched": report["mismatched"],
            "ok": report["ok"]}), flush=True)
        for res in report["results"]:
            if not res["ok"]:
                print(f"# seed {res['seed']}: BYTE MISMATCH "
                      f"{json.dumps(res['points'])}", file=sys.stderr)
        return 0 if report["ok"] else 1

    trace = build_trace(args)
    if args.save_trace:
        trace.save(args.save_trace)
        print(f"# trace saved: {args.save_trace}", file=sys.stderr)

    if args.whatif:
        from kueue_tpu.twin import whatif

        if args.whatif == ["default"]:
            configs = whatif.default_sweep()
        else:
            configs = [whatif.parse_config(s) for s in args.whatif]
        report = whatif.sweep(
            trace, configs, default_engine=args.engine,
            default_duration_s=args.default_duration_s)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(report, f, indent=1)
        print(whatif.format_report(report), file=sys.stderr)
        print(json.dumps({
            "metric": "twin_whatif", "trace": report["trace"]["name"],
            "baseline": report["baseline"],
            "configs": [r["name"] for r in report["configs"]],
            "goodput": {r["name"]:
                        r["metrics"]["goodput_wl_per_vday"]
                        for r in report["configs"]},
            "wall_seconds": round(sum(
                r["metrics"]["wall_seconds"]
                for r in report["configs"]), 2),
            "ok": report["ok"]}), flush=True)
        return 0 if report["ok"] else 1

    from kueue_tpu.twin.engine import TwinEngine

    res = TwinEngine(trace, engine=args.engine,
                     default_duration_s=args.default_duration_s).run()
    if args.out:
        from kueue_tpu.utils.envinfo import environment_block

        doc = dict(res)
        doc["environment"] = environment_block()
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
    print(json.dumps({
        "metric": "twin_replay", "trace": res["trace"]["name"],
        "engine": args.engine, "metrics": res["metrics"],
        "ok": res["violation_count"] == 0}), flush=True)
    return 0 if res["violation_count"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
