"""Cross-check mode: the twin must BE the scheduler, not a model of it.

For lattice-sized scenarios, convert the fuzz scenario into a paced
trace, replay it on the TwinEngine, and hold the result byte-identical
to lattice.drive() at the same framework point: the JSON encodings of
(decision trail, final admitted set, oracle violations) must match to
the byte. Any drift means the twin's replay loop departed from the
reference drive loop — a planning result from it would be fiction —
so cross-check failures are release-gating, not advisory.
"""

from __future__ import annotations

import json
from typing import List, Optional

from kueue_tpu.fuzz import generator, lattice
from kueue_tpu.fuzz.lattice import LatticePoint
from kueue_tpu.twin.engine import TwinEngine
from kueue_tpu.twin.trace import Trace


def _doc_bytes(trail, final_admitted, violations) -> str:
    return json.dumps(
        {"trail": trail, "final_admitted": final_admitted,
         "violations": violations},
        sort_keys=True, default=list)


def _first_divergence(ref_trail, twin_trail) -> Optional[dict]:
    for t in range(max(len(ref_trail), len(twin_trail))):
        r = ref_trail[t] if t < len(ref_trail) else None
        w = twin_trail[t] if t < len(twin_trail) else None
        if json.dumps(r, default=list) != json.dumps(w, default=list):
            return {"tick": t, "reference": r, "twin": w}
    return None


def crosscheck_scenario(sc, engines=("host", "jax",
                                     "referee")) -> dict:
    """Replay one fuzz scenario both ways at each engine; returns
    {"seed", "points": [...], "ok"} with per-point byte verdicts."""
    trace = Trace.from_scenario(sc)
    points = []
    ok = True
    for eng in engines:
        if eng == "referee":
            point = LatticePoint(name="crosscheck-referee",
                                 kind="referee")
        else:
            point = LatticePoint(name=f"crosscheck-{eng}",
                                 kind="framework", engine=eng)
        ref = lattice.drive(sc, point)
        twin = TwinEngine(trace, engine=eng, record_trail=True).run()
        ref_b = _doc_bytes(ref["trail"], ref["final_admitted"],
                           ref["violations"])
        twin_b = _doc_bytes(twin["trail"], twin["final_admitted"],
                            twin["violations"])
        match = ref_b == twin_b
        entry = {"engine": eng, "byte_identical": match,
                 "ticks": len(ref["trail"])}
        if not match:
            ok = False
            entry["divergence"] = _first_divergence(
                ref["trail"], twin.get("trail") or [])
            if (json.dumps(ref["final_admitted"], sort_keys=True)
                    != json.dumps(twin["final_admitted"],
                                  sort_keys=True)):
                entry["final_admitted"] = {
                    "reference": ref["final_admitted"],
                    "twin": twin["final_admitted"]}
        points.append(entry)
    return {"seed": sc.seed, "shape": sc.policy.get("shape"),
            "points": points, "ok": ok}


def crosscheck_seeds(seeds: int, start_seed: int = 0,
                     engines=("host", "jax", "referee")) -> dict:
    """The campaign form: N generator-drawn scenarios, each replayed
    twin-vs-drive at every engine. The what-if CI gate runs this on a
    small budget; red means no capacity report can be trusted."""
    results: List[dict] = []
    bad = 0
    for seed in range(start_seed, start_seed + seeds):
        sc = generator.draw_scenario(seed)
        res = crosscheck_scenario(sc, engines=engines)
        if not res["ok"]:
            bad += 1
        results.append(res)
    return {"scenarios": seeds, "start_seed": start_seed,
            "engines": list(engines), "mismatched": bad,
            "ok": bad == 0, "results": results}
