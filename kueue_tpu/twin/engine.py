"""TwinEngine: discrete-event virtual-time replay on the real kernels.

The engine builds the SAME Framework the fuzz lattice builds
(lattice._build_framework — real flavor-fit / preemption / fair-sharing
kernels, deterministic TickClock) and replaces wall-clock pacing with
an event-merged virtual clock. Two pacing modes:

  paced         the trace carries explicit tick events (a converted
                fuzz scenario): events apply at their recorded vtimes
                and every tick runs — the replay reproduces
                lattice._drive_framework's exact clock sequence, so
                the decision trail byte-matches drive() at the same
                lattice point (crosscheck.py holds it to that).

  event-driven  no tick events: arrivals stream from the lazy
                generator, completions come from declared durations on
                a heap, and the engine ticks only at grid boundaries
                (t0 + m * tick_interval_s) where something can change
                — arrivals land in vectorized waves (the batched
                solver admits a whole wave per tick), idle gaps cost
                nothing, and a multi-day 10^6-workload trace replays
                in minutes in one process.

Durations: declared per workload ("duration_s" in the spec) with a
learned fallback — an EWMA of observed completions per ClusterQueue
(DurationModel), so journal-shaped traces where some workloads carry
no declared runtime still advance. A preempted workload's scheduled
completion is invalidated by an epoch bump; readmission restarts the
full duration (restart semantics, the conservative planning choice).

Recording: the full admitted-set timeline at tick granularity
(per-tick admissions/preemptions/completions/backlog/live), the
virtual submit->admitted wait reservoir, the per-root quota high-water
marks, and the same quota oracle the fuzzer trusts, checked after
every tick.
"""

from __future__ import annotations

import gc
import heapq
import math
import time
from array import array
from typing import Dict, List, Optional

from kueue_tpu.fuzz import lattice
from kueue_tpu.fuzz import scenario as sc_mod
from kueue_tpu.fuzz.lattice import (FrameworkTrafficDriver,
                                    LatticePoint, TickClock)
from kueue_tpu.twin import generators
from kueue_tpu.twin.trace import Trace

_INF = float("inf")

# Cap on recorded oracle violations: the counter keeps counting, the
# list stops growing (a red 10^6-replay must not OOM on its own
# findings).
_MAX_RECORDED_VIOLATIONS = 200


def _pctl(sorted_vals, q: float):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1,
            max(0, int(math.ceil(q * len(sorted_vals))) - 1))
    return sorted_vals[i]


class DurationModel:
    """Learned durations: per-CQ EWMA of observed completions, falling
    back to a global EWMA, then to `default_s`. Workloads with a
    declared "duration_s" bypass the model entirely (and feed it)."""

    def __init__(self, default_s: float = 900.0, alpha: float = 0.2):
        self.default_s = float(default_s)
        self.alpha = float(alpha)
        self.by_cq: Dict[str, float] = {}
        self.global_est: Optional[float] = None

    def estimate(self, cq: str) -> float:
        est = self.by_cq.get(cq)
        if est is not None:
            return est
        if self.global_est is not None:
            return self.global_est
        return self.default_s

    def observe(self, cq: str, duration_s: float) -> None:
        a = self.alpha
        prev = self.by_cq.get(cq)
        self.by_cq[cq] = (duration_s if prev is None
                          else prev + a * (duration_s - prev))
        self.global_est = (duration_s if self.global_est is None
                           else self.global_est
                           + a * (duration_s - self.global_est))


class TwinEngine:
    """One replay of one trace at one capacity/solver configuration."""

    def __init__(self, trace: Trace, *, engine: str = "jax",
                 shards: int = 1, kill_switches: bool = False,
                 record_trail: Optional[bool] = None,
                 settle_ticks: int = 3, gc_every_ticks: int = 256,
                 default_duration_s: float = 900.0,
                 cycles_per_tick: int = 512):
        self.trace = trace
        self.engine = engine
        self.shards = shards
        self.kill_switches = kill_switches
        self.record_trail = (trace.paced if record_trail is None
                             else record_trail)
        self.settle_ticks = settle_ticks
        self.gc_every_ticks = gc_every_ticks
        self.durations = DurationModel(default_s=default_duration_s)
        self.cycles_per_tick = cycles_per_tick

    # -- lifecycle ----------------------------------------------------------

    def run(self) -> dict:
        from kueue_tpu import features

        sc = self.trace.cluster_scenario()
        lattice._set_gates(sc)
        try:
            return self._run(sc)
        finally:
            features.reset()

    def _run(self, sc) -> dict:
        t_wall = time.perf_counter()
        clock = TickClock()
        clock.now = self.trace.t0
        if self.engine == "referee":
            # The sequential reference drive — no batch solver, no jit
            # dispatch per cycle; decision-identical to the batched
            # engines by the fuzz lattice's standing identity oracle,
            # and the fastest path for huge capacity-planning replays.
            point = LatticePoint(name="twin-referee", kind="referee")
        else:
            point = LatticePoint(
                name=f"twin-{self.engine}", kind="framework",
                engine=self.engine,
                shards=self.shards if self.shards > 1 else 1,
                kill_switches=self.kill_switches)
        fw = lattice._build_framework(sc, point, clock)
        drv = FrameworkTrafficDriver(fw, sc)

        self._tick_admitted: List[str] = []
        self._tick_preempted: List[str] = []
        orig_admit = fw.scheduler.apply_admission
        orig_preempt = fw.scheduler.apply_preemption

        def apply_admission(wl):
            ok = orig_admit(wl)
            if ok:
                self._tick_admitted.append(wl.key)
            return ok

        def apply_preemption(wl, msg):
            self._tick_preempted.append(wl.key)
            return orig_preempt(wl, msg)

        fw.scheduler.apply_admission = apply_admission
        fw.scheduler.apply_preemption = apply_preemption

        self._roots = {cq["name"]: sc_mod.cq_root(sc, cq["name"])
                       for cq in sc.cluster_queues}
        self._high_water: dict = {}
        self._violations: List[dict] = []
        self._violation_count = 0
        self._timeline: List[list] = []
        self._trail: List[tuple] = []
        self._waits = array("d")
        self._counts = {"submitted": 0, "admissions": 0,
                        "preemptions": 0, "completed": 0,
                        "spikes": 0, "ticks": 0, "cycles": 0}

        # Long-replay hygiene (the PR 9 gen-2 GC lesson): freeze the
        # built cluster into the permanent generation and DISABLE the
        # allocation-pressure collector for the replay — at 10^6 live
        # workload objects its automatic gen-2 passes dominate wall
        # clock (measured 2.1x on a 10^5 replay). The engine collects
        # explicitly every `gc_every_ticks` boundaries instead, which
        # bounds cycle garbage by virtual time rather than allocation
        # count.
        gc.collect()
        gc.freeze()
        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            if self.trace.paced:
                self._run_paced(sc, fw, drv, clock)
            else:
                self._run_event_driven(sc, fw, drv, clock)
        finally:
            try:
                if gc_was_enabled:
                    gc.enable()
                gc.unfreeze()
            except Exception:
                pass

        final = {name: sorted(cq.workloads)
                 for name, cq in fw.cache.cluster_queues.items()}
        wall = time.perf_counter() - t_wall
        out = {
            "trace": {"name": self.trace.name, "seed": self.trace.seed,
                      "paced": self.trace.paced,
                      "shape": (self.trace.generator or {}).get(
                          "shape"),
                      "tick_interval_s": self.trace.tick_interval_s},
            "point": {"engine": self.engine, "shards": self.shards,
                      "kill_switches": self.kill_switches},
            "metrics": self._metrics(wall),
            "timeline": self._timeline,
            "violations": self._violations,
            "violation_count": self._violation_count,
            "high_water": self._high_water_report(),
            "final_admitted": final,
        }
        if self.record_trail:
            out["trail"] = self._trail
        return out

    # -- paced (fuzz-scenario) replay ---------------------------------------

    def _run_paced(self, sc, fw, drv, clock) -> None:
        t_index = 0
        seeded = False      # past the initial-submit prefix
        for e in (self.trace.events or ()):
            kind, v = e[0], float(e[1])
            if not seeded and kind != "submit":
                # drive() discards anything its hooks saw during the
                # initial submits (buffers clear at the top of tick 0)
                # — match that capture window exactly; from here on the
                # buffers clear at the END of each tick instead, so
                # op-time admissions land in the right tick's trail.
                self._tick_admitted.clear()
                self._tick_preempted.clear()
                seeded = True
            if kind == "submit":
                clock.now = v
                self._submit(drv, dict(e[2]), v)
            elif kind == "op":
                clock.now = v
                drv.apply(list(e[2]))
            elif kind == "spike":
                clock.now = v
                self._expand_spike(drv, e[2], v)
            elif kind == "tick":
                clock.now = v
                fw.tick()
                self._counts["ticks"] += 1
                self._counts["admissions"] += len(self._tick_admitted)
                self._counts["preemptions"] += len(
                    self._tick_preempted)
                drv.note_tick(t_index, self._tick_admitted,
                              self._tick_preempted)
                if self.record_trail:
                    self._trail.append(
                        (tuple(sorted(self._tick_admitted)),
                         tuple(sorted(self._tick_preempted))))
                usage = {name: {f: dict(r)
                                for f, r in cq.usage.items()}
                         for name, cq in
                         fw.cache.cluster_queues.items()}
                self._record_violations(lattice._check_oversub(
                    sc, usage, drv.caps_hw, t_index))
                self._quota_high_water(fw, drv)
                self._timeline.append(
                    [v, len(self._tick_admitted),
                     len(self._tick_preempted), 0,
                     len(drv.st.pending), len(drv.st.admitted)])
                self._tick_admitted.clear()
                self._tick_preempted.clear()
                t_index += 1
            else:
                raise ValueError(f"unknown trace event kind {kind!r}")

    # -- event-driven (capacity-planning) replay ----------------------------

    def _run_event_driven(self, sc, fw, drv, clock) -> None:
        t0 = self.trace.t0
        interval = float(self.trace.tick_interval_s)
        arrivals = generators.iter_trace_events(self.trace)
        completions: list = []      # heap: (vtime, seq, key, epoch)
        self._live_epoch: Dict[str, int] = {}
        self._wl_duration: Dict[str, float] = {}
        self._submit_v: Dict[str, float] = {}
        self._comp_seq = 0
        self._arrival_seq = 0
        pending = 0
        live = 0

        # Ops need the _TrafficState selectors maintained via
        # note_tick; pure arrival traces skip that bookkeeping (and
        # purge per-workload dicts on completion) so memory stays
        # bounded by the live population, not the trace length.
        ops_present = bool(self.trace.events) and any(
            e[0] == "op" for e in self.trace.events)

        pending_ev = next(arrivals, None)
        m = 0                       # last ticked grid index
        draining = False
        quiet = 0
        while True:
            self._tick_admitted.clear()
            self._tick_preempted.clear()
            a_v = pending_ev[0] if pending_ev is not None else _INF
            c_v = completions[0][0] if completions else _INF
            te = min(a_v, c_v)
            if te == _INF:
                if pending == 0 and not completions:
                    break
                if not draining and quiet >= self.settle_ticks:
                    break           # stuck backlog: stranded demand
                target_m = m + 1
            else:
                target_m = max(
                    int(math.ceil((te - t0) / interval - 1e-9)),
                    m + 1)
                if draining and pending > 0:
                    # A draining backlog keeps the tick cadence even
                    # when the next event is far out — waves stay one
                    # interval wide instead of ballooning.
                    target_m = m + 1
            tv = t0 + target_m * interval

            applied = 0
            completed_window = 0
            while True:
                a_v = (pending_ev[0] if pending_ev is not None
                       else _INF)
                c_v = completions[0][0] if completions else _INF
                if a_v > tv and c_v > tv:
                    break
                if a_v <= c_v:
                    v, kind, payload = pending_ev
                    clock.now = v
                    if kind == "submit":
                        self._submit(drv, payload, v,
                                     assign_name=True)
                        pending += 1
                    elif kind == "spike":
                        pending += self._expand_spike(drv, payload, v)
                    elif kind == "op":
                        drv.apply(list(payload))
                    else:
                        raise ValueError(
                            f"unknown trace event kind {kind!r}")
                    applied += 1
                    pending_ev = next(arrivals, None)
                else:
                    v, _seq, key, epoch = heapq.heappop(completions)
                    if self._live_epoch.get(key) != epoch:
                        continue    # preempted/readmitted: stale
                    clock.now = v
                    if drv.finish_key(key):
                        completed_window += 1
                        live -= 1
                        dur = self._wl_duration.get(key)
                        if dur is not None:
                            self.durations.observe(
                                drv.st.submitted.get(
                                    key, {}).get("queue", "")[3:],
                                dur)
                        self._cleanup_key(drv, key, ops_present)

            clock.now = tv
            m = target_m
            self._counts["ticks"] += 1
            # One boundary = one drained scheduling WAVE, not one
            # cycle: the real scheduler pops one head per CQ per cycle
            # and production runs cycles continuously, so the twin
            # cycles until quiescence — clock frozen at the boundary,
            # the same way drive() freezes it within a tick — under a
            # safety cap against preemption flapping.
            n_adm = n_pre = 0
            cycles = 0
            while True:
                self._tick_admitted.clear()
                self._tick_preempted.clear()
                inadm0 = getattr(fw.scheduler.metrics,
                                 "inadmissible", 0)
                fw.tick()
                cycles += 1
                parked = getattr(fw.scheduler.metrics,
                                 "inadmissible", 0) - inadm0
                adm = self._tick_admitted
                pre = self._tick_preempted
                if ops_present:
                    drv.note_tick(m, adm, pre)
                if self.record_trail:
                    self._trail.append((tuple(sorted(adm)),
                                        tuple(sorted(pre))))
                for key in pre:
                    # Invalidate the scheduled completion; the
                    # workload is back in the queue and restarts on
                    # readmission.
                    if key in self._live_epoch:
                        self._live_epoch[key] += 1
                        pending += 1
                        live -= 1
                for key in adm:
                    pending -= 1
                    live += 1
                    sv = self._submit_v.pop(key, None)
                    if sv is not None:
                        self._waits.append(tv - sv)
                    dur = self._wl_duration.get(key)
                    if dur is None:
                        cq = drv.st.submitted.get(key, {}).get(
                            "queue", "lq-")[3:]
                        dur = self.durations.estimate(cq)
                        self._wl_duration[key] = dur
                    ep = self._live_epoch.get(key, 0) + 1
                    self._live_epoch[key] = ep
                    self._comp_seq += 1
                    heapq.heappush(
                        completions,
                        (tv + dur, self._comp_seq, key, ep))
                n_adm += len(adm)
                n_pre += len(pre)
                # A cycle that admitted nothing but PARKED a NoFit
                # head still made progress: the next cycle pops the
                # workload behind it. Only a cycle that touched
                # nothing ends the wave.
                if (not adm and not pre and parked <= 0) \
                        or cycles >= self.cycles_per_tick:
                    break
            self._counts["cycles"] += cycles
            self._counts["admissions"] += n_adm
            self._counts["preemptions"] += n_pre
            self._counts["completed"] += completed_window
            self._quota_scan(fw, drv, tv)
            self._timeline.append([tv, n_adm, n_pre, completed_window,
                                   pending, live])
            draining = n_adm > 0
            quiet = (0 if (applied or n_adm or n_pre
                           or completed_window) else quiet + 1)
            if self.gc_every_ticks \
                    and self._counts["ticks"] % self.gc_every_ticks \
                    == 0:
                gc.collect()

        self._stranded = pending

    @staticmethod
    def _fast_workload(spec: dict):
        """Trusted bulk-ingest constructor: the SAME Workload object
        scenario.workload_object builds (asserted equal in tests), but
        built directly — no quantity-string formatting/parsing and no
        webhook validation downstream. Only for generator-shaped specs
        (no topology request, no per-flavor throughputs); anything
        richer falls back to the full path."""
        from kueue_tpu.api.types import PodSet, Workload

        if spec.get("tputs"):
            return None
        pod_sets = []
        for ps in spec["pod_sets"]:
            if ps.get("topo"):
                return None
            pod_sets.append(PodSet(
                name=ps.get("name", "ps0"), count=int(ps["count"]),
                requests={"cpu": int(ps["cpu"]) * 1000,
                          "memory": int(ps["memory_gi"]) << 30}))
        return Workload(
            name=spec["name"], namespace="default",
            queue_name=spec["queue"],
            priority=int(spec.get("priority", 0)),
            creation_time=float(spec["creation_time"]),
            pod_sets=pod_sets)

    def _submit(self, drv, spec: dict, vtime: float,
                assign_name: bool = False) -> str:
        if assign_name and "name" not in spec:
            self._arrival_seq += 1
            spec = dict(spec)
            spec["name"] = f"tw-{self._arrival_seq}"
        if "creation_time" not in spec:
            spec["creation_time"] = vtime
        wl = self._fast_workload(spec)
        wl = drv.submit(spec, wl=wl, validate=wl is None)
        self._counts["submitted"] += 1
        key = wl.key
        if hasattr(self, "_submit_v"):
            self._submit_v[key] = vtime
            if spec.get("duration_s") is not None:
                self._wl_duration[key] = float(spec["duration_s"])
        return key

    def _expand_spike(self, drv, payload: dict, vtime: float) -> int:
        """One spike event becomes n identical high-priority arrivals
        into one ClusterQueue — the adversarial-burst shape's hammer."""
        n = int(payload["n"])
        prefix = payload.get("name_prefix", "spike")
        base = {"queue": payload["queue"],
                "priority": int(payload.get("priority", 4)),
                "creation_time": vtime,
                "pod_sets": [{"name": "ps0",
                              "count": int(payload.get("count", 1)),
                              "cpu": int(payload.get("cpu", 1)),
                              "memory_gi": int(
                                  payload.get("memory_gi", 1)),
                              "topo": None}],
                "tputs": None,
                "duration_s": payload.get("duration_s")}
        for j in range(n):
            spec = dict(base)
            spec["name"] = f"{prefix}-{j}"
            self._submit(drv, spec, vtime)
        self._counts["spikes"] += 1
        return n

    def _cleanup_key(self, drv, key: str, ops_present: bool) -> None:
        self._live_epoch.pop(key, None)
        self._wl_duration.pop(key, None)
        self._submit_v.pop(key, None)
        if not ops_present:
            drv.objects.pop(key, None)
            drv.st.submitted.pop(key, None)

    # -- oracles + recording ------------------------------------------------

    def _record_violations(self, found: List[dict]) -> None:
        self._violation_count += len(found)
        room = _MAX_RECORDED_VIOLATIONS - len(self._violations)
        if room > 0:
            self._violations.extend(found[:room])

    def _quota_scan(self, fw, drv, tv: float) -> None:
        """The fuzzer's quota oracle at tick cadence, plus per-root
        high-water tracking: usage summed per cohort root must never
        exceed the (high-water) nominal capacity."""
        used: dict = {}
        roots = self._roots
        for name, cq in fw.cache.cluster_queues.items():
            root = roots[name]
            dst = used.setdefault(root, {})
            for fname, res in cq.usage.items():
                d = dst.setdefault(fname, {})
                for rname, val in res.items():
                    d[rname] = d.get(rname, 0) + val
        caps = drv.caps_hw
        found = []
        for root, by_flavor in used.items():
            for fname, res in by_flavor.items():
                for rname, val in res.items():
                    cap = caps.get(root, {}).get(fname, {}).get(
                        rname, 0)
                    hw = self._high_water.setdefault(
                        root, {}).setdefault(fname, {})
                    prev = hw.get(rname)
                    if prev is None or val > prev[0]:
                        hw[rname] = (val, cap)
                    if val > cap:
                        found.append({
                            "oracle": "quota", "vtime": tv,
                            "detail": f"root {root} {fname}/{rname}: "
                                      f"usage {val} > capacity "
                                      f"{cap}"})
        if found:
            self._record_violations(found)

    def _quota_high_water(self, fw, drv) -> None:
        # Paced mode reuses the oracle in lattice._check_oversub for
        # violations; this keeps only the high-water marks.
        for name, cq in fw.cache.cluster_queues.items():
            root = self._roots[name]
            for fname, res in cq.usage.items():
                hw = self._high_water.setdefault(
                    root, {}).setdefault(fname, {})
                for rname, val in res.items():
                    cap = drv.caps_hw.get(root, {}).get(
                        fname, {}).get(rname, 0)
                    prev = hw.get(rname)
                    # Per-CQ usage here (no cross-CQ sum): good enough
                    # for the paced small scenarios' report field.
                    if prev is None or val > prev[0]:
                        hw[rname] = (val, cap)

    def _high_water_report(self) -> dict:
        out: dict = {}
        for root, by_flavor in self._high_water.items():
            best = 0.0
            for res in by_flavor.values():
                for val, cap in res.values():
                    if cap > 0:
                        best = max(best, val / cap)
                    elif val > 0:
                        best = max(best, _INF)
            out[root] = round(best, 4) if best is not _INF else None
        return out

    def _metrics(self, wall_s: float) -> dict:
        waits = sorted(self._waits)
        vt = (self._timeline[-1][0] - self.trace.t0
              if self._timeline else 0.0)
        vdays = vt / 86400.0
        completed = self._counts["completed"]
        hw = [r for r in self._high_water_report().values()
              if r is not None]
        return {
            "workloads_submitted": self._counts["submitted"],
            "admissions": self._counts["admissions"],
            "preemptions": self._counts["preemptions"],
            "completed": completed,
            "stranded_pending": getattr(self, "_stranded", 0),
            "spikes": self._counts["spikes"],
            "ticks": self._counts["ticks"],
            "cycles": self._counts["cycles"],
            "virtual_seconds": round(vt, 1),
            "virtual_days": round(vdays, 4),
            "goodput_wl_per_vday": (round(completed / vdays, 2)
                                    if vdays > 0 else None),
            "wait_p50_s": _pctl(waits, 0.50),
            "wait_p99_s": _pctl(waits, 0.99),
            "wait_mean_s": (round(sum(waits) / len(waits), 2)
                            if waits else None),
            "quota_violations": self._violation_count,
            "quota_high_water_max": (round(max(hw), 4)
                                     if hw else None),
            "wall_seconds": round(wall_s, 2),
            "workloads_per_wall_s": (
                round(self._counts["submitted"] / wall_s, 1)
                if wall_s > 0 else None),
        }


def replay(trace: Trace, **kwargs) -> dict:
    """One-call replay: build the engine, run, return the result."""
    return TwinEngine(trace, **kwargs).run()
