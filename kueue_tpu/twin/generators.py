"""Seeded lazy trace generators: the capacity-planning arrival shapes.

Each generator streams (vtime, kind, payload) events in virtual-time
order WITHOUT materializing the trace — a 10^6-workload multi-day
trace costs bucket-local memory (one hour of arrivals at a time). All
draws run through per-(stream, bucket) child RNGs keyed on the trace
seed, so the same generator spec always streams the identical event
sequence (the twin determinism oracle) and a trace file can carry just
the spec.

Shapes (the Mesos multi-framework study's mixes, ROADMAP item 5b):

  diurnal           sinusoidal day/night arrival rate, modest sizes
  heavy_tailed      bounded-Pareto sizes AND per-hour burst weights
  diurnal_heavy     diurnal rate x heavy-tailed sizes/durations — the
                    production-shaped default for capacity planning
  adversarial_burst low uniform baseline + spike events: each spike
                    expands into a same-CQ high-priority burst at
                    replay time (one trace entry, thousands of
                    arrivals)
  mix               three frameworks a la Mesos: batch (heavy, long,
                    low priority), service (small, very long, high
                    priority), interactive (tiny, short, diurnal)

The generator spec is a plain dict (lives inside the trace JSON):
  {"shape", "workloads", "days", "seed", "cqs",
   "mean_duration_s", ...}
"""

from __future__ import annotations

import random
from typing import Iterator, List, Optional

from kueue_tpu.utils.synthetic import diurnal_rate, heavy_tailed_int

BUCKET_S = 3600.0          # one virtual hour per arrival bucket


def _bucket_counts(total: int, weights: List[float]) -> List[int]:
    """Split `total` arrivals over buckets proportionally to `weights`
    with cumulative rounding: deterministic, sums to exactly total."""
    s = sum(weights) or 1.0
    counts, acc, cum = [], 0, 0.0
    for w in weights:
        cum += w / s
        c = int(round(total * cum))
        counts.append(c - acc)
        acc = c
    return counts


def _child(seed: int, salt: int, bucket: int) -> random.Random:
    return random.Random(((seed + 1) * 1_000_003) ^ (salt * 7_919)
                         ^ (bucket * 104_729))


def _dur_exp(rb: random.Random, mean: float) -> float:
    return min(max(rb.expovariate(1.0 / mean), 60.0), 12.0 * mean)


def _dur_heavy(rb: random.Random, mean: float) -> float:
    return float(heavy_tailed_int(
        rb, max(int(mean / 6), 60), int(mean * 24)))


def _base_spec(rb: random.Random, num_cqs: int, *, cpu: int,
               count: int, memory_gi: int, priority: int,
               duration_s: float, queue: Optional[str] = None) -> dict:
    return {"queue": queue or f"lq-cq-{rb.randrange(num_cqs)}",
            "priority": priority,
            "pod_sets": [{"name": "ps0", "count": count, "cpu": cpu,
                          "memory_gi": memory_gi, "topo": None}],
            "tputs": None,
            "duration_s": duration_s}


def _spec_diurnal(rb: random.Random, gen: dict) -> dict:
    cpu = rb.choice((1, 1, 2, 4))
    return _base_spec(rb, gen["cqs"], cpu=cpu,
                      count=rb.choice((1, 1, 2)), memory_gi=cpu,
                      priority=rb.randrange(3),
                      duration_s=_dur_exp(
                          rb, gen.get("mean_duration_s", 1800.0)))


def _spec_heavy(rb: random.Random, gen: dict) -> dict:
    cpu = heavy_tailed_int(rb, 1, 16)
    return _base_spec(rb, gen["cqs"], cpu=cpu,
                      count=heavy_tailed_int(rb, 1, 4), memory_gi=cpu,
                      priority=rb.randrange(3),
                      duration_s=_dur_heavy(
                          rb, gen.get("mean_duration_s", 1800.0)))


def _spec_batch(rb: random.Random, gen: dict) -> dict:
    cpu = heavy_tailed_int(rb, 2, 16)
    return _base_spec(rb, gen["cqs"], cpu=cpu,
                      count=heavy_tailed_int(rb, 1, 8), memory_gi=cpu,
                      priority=0,
                      duration_s=_dur_heavy(
                          rb, 2.0 * gen.get("mean_duration_s",
                                            1800.0)))


def _spec_service(rb: random.Random, gen: dict) -> dict:
    return _base_spec(rb, gen["cqs"], cpu=rb.choice((1, 2)),
                      count=rb.choice((1, 2)), memory_gi=2,
                      priority=2,
                      duration_s=_dur_exp(
                          rb, 6.0 * gen.get("mean_duration_s",
                                            1800.0)))


def _spec_interactive(rb: random.Random, gen: dict) -> dict:
    return _base_spec(rb, gen["cqs"], cpu=1, count=1, memory_gi=1,
                      priority=1,
                      duration_s=_dur_exp(
                          rb, 0.2 * gen.get("mean_duration_s",
                                            1800.0)))


def _diurnal_weights(n_buckets: int) -> List[float]:
    # Hour-of-day sinusoid, never fully dark (lo) so the trough still
    # trickles arrivals.
    return [diurnal_rate(b, period=24, lo=0.2, hi=1.0)
            for b in range(n_buckets)]


def _heavy_weights(n_buckets: int, seed: int, salt: int) -> List[float]:
    return [float(heavy_tailed_int(_child(seed, salt, b), 1, 40))
            for b in range(n_buckets)]


def _flat_weights(n_buckets: int) -> List[float]:
    return [1.0] * n_buckets


# shape -> list of (salt, weight_fn(n_buckets, seed), spec_fn, share)
_STREAMS = {
    "diurnal": [(1, lambda n, s: _diurnal_weights(n),
                 _spec_diurnal, 1.0)],
    "heavy_tailed": [(2, lambda n, s: _heavy_weights(n, s, 2),
                      _spec_heavy, 1.0)],
    "diurnal_heavy": [(3, lambda n, s: _diurnal_weights(n),
                       _spec_heavy, 1.0)],
    "mix": [(4, lambda n, s: _heavy_weights(n, s, 4), _spec_batch, 0.5),
            (5, lambda n, s: _flat_weights(n), _spec_service, 0.2),
            (6, lambda n, s: _diurnal_weights(n),
             _spec_interactive, 0.3)],
}

SHAPES = tuple(_STREAMS) + ("adversarial_burst",)

# adversarial_burst: this fraction of the workload count arrives as
# spike events (same-CQ, high-priority bursts); the rest is a flat
# baseline.
_SPIKE_FRACTION = 0.4


def _spike_events(gen: dict, t0: float, horizon: float,
                  total: int) -> List[tuple]:
    seed = int(gen.get("seed", 0))
    n_spikes = max(1, int(gen.get("spikes",
                                  4 * float(gen.get("days", 1.0)))))
    rs = _child(seed, 9, 0)
    per = _bucket_counts(total, [1.0 + rs.random()
                                 for _ in range(n_spikes)])
    out = []
    for s, n in enumerate(per):
        if n <= 0:
            continue
        v = t0 + rs.random() * horizon
        cpu = rs.choice((1, 2, 4))
        out.append((v, "spike", {
            "n": n, "name_prefix": f"spike-{s}",
            "queue": f"lq-cq-{rs.randrange(gen['cqs'])}",
            "priority": 4, "cpu": cpu, "count": 1, "memory_gi": cpu,
            "duration_s": _dur_exp(rs, gen.get("mean_duration_s",
                                               1800.0))}))
    out.sort(key=lambda e: e[0])
    return out


def iter_generator(gen: dict, t0: float) -> Iterator[tuple]:
    """Stream the generator spec's events, sorted by vtime. Yields
    (vtime, "submit", spec) and (vtime, "spike", payload) tuples;
    submit specs carry no name/creation_time — the engine assigns the
    global arrival index and stamps creation_time = vtime."""
    shape = gen["shape"]
    if shape not in SHAPES:
        raise ValueError(f"unknown trace shape {shape!r} "
                         f"(have {sorted(SHAPES)})")
    seed = int(gen.get("seed", 0))
    total = int(gen["workloads"])
    days = float(gen.get("days", 1.0))
    horizon = days * 86400.0
    n_buckets = max(1, int(round(horizon / BUCKET_S)))
    width = horizon / n_buckets

    spikes: List[tuple] = []
    if shape == "adversarial_burst":
        spike_total = int(total * _SPIKE_FRACTION)
        spikes = _spike_events(gen, t0, horizon, spike_total)
        streams = [(8, lambda n, s: _flat_weights(n), _spec_diurnal,
                    1.0)]
        total -= spike_total
    else:
        streams = _STREAMS[shape]

    shares = [max(sh, 0.0) for _salt, _w, _f, sh in streams]
    totals = _bucket_counts(total, shares)
    counts = [_bucket_counts(totals[k], w_fn(n_buckets, seed))
              for k, (_salt, w_fn, _f, _sh) in enumerate(streams)]

    spike_i = 0
    for b in range(n_buckets):
        start = t0 + b * width
        bucket: List[tuple] = []
        for k, (salt, _w_fn, spec_fn, _sh) in enumerate(streams):
            c = counts[k][b]
            if not c:
                continue
            rb = _child(seed, salt, b)
            for j in range(c):
                v = start + (j + rb.random()) * width / c
                bucket.append((v, "submit", spec_fn(rb, gen)))
        while spike_i < len(spikes) \
                and spikes[spike_i][0] < start + width:
            bucket.append(spikes[spike_i])
            spike_i += 1
        bucket.sort(key=lambda e: e[0])
        for ev in bucket:
            yield ev
    # Spikes drawn exactly at the horizon edge.
    while spike_i < len(spikes):
        yield spikes[spike_i]
        spike_i += 1


def iter_trace_events(trace) -> Iterator[tuple]:
    """The engine's event source: explicit events verbatim (assumed
    recorded in vtime order), else the lazy generator stream."""
    if trace.events is not None:
        for e in trace.events:
            kind, vtime = e[0], float(e[1])
            yield (vtime, kind, e[2] if len(e) > 2 else None)
    elif trace.generator:
        for ev in iter_generator(trace.generator, trace.t0):
            yield ev


def estimate_demand(gen: dict, samples: int = 512) -> dict:
    """Mean per-arrival resource-time demand, estimated by sampling the
    spec's own draw functions — what the CLI sizes cluster quotas from
    (offered load = rate x mean cpu-seconds per arrival)."""
    probe = dict(gen)
    probe["workloads"] = samples
    probe.setdefault("days", 1.0)
    cpu_s = mem_s = 0.0
    n = 0
    for _v, kind, payload in iter_generator(probe, 0.0):
        if kind == "submit":
            ps = payload["pod_sets"][0]
            cpu_s += ps["cpu"] * ps["count"] * payload["duration_s"]
            mem_s += (ps["memory_gi"] * ps["count"]
                      * payload["duration_s"])
            n += 1
        elif kind == "spike":
            cpu_s += (payload["cpu"] * payload["count"]
                      * payload["duration_s"] * payload["n"])
            mem_s += (payload["memory_gi"] * payload["count"]
                      * payload["duration_s"] * payload["n"])
            n += payload["n"]
    n = max(n, 1)
    return {"cpu_core_s": cpu_s / n, "memory_gi_s": mem_s / n,
            "sampled": n}


def size_cluster_quota(gen: dict, num_cqs: int,
                       utilization: float = 0.6,
                       peak_factor: float = 2.0) -> dict:
    """Per-CQ quota that carries the spec's offered load: mean demand
    rate scaled by the diurnal peak and a utilization headroom. Returns
    {"cpu", "memory_gi"} per ClusterQueue."""
    d = estimate_demand(gen)
    rate = float(gen["workloads"]) / (float(gen.get("days", 1.0))
                                      * 86400.0)
    need_cpu = rate * d["cpu_core_s"] * peak_factor / utilization
    need_mem = rate * d["memory_gi_s"] * peak_factor / utilization
    return {"cpu": max(2, int(round(need_cpu / num_cqs)) + 1),
            "memory_gi": max(2, int(round(need_mem / num_cqs)) + 1)}
