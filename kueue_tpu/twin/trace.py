"""Trace model: the digital twin's unit of replay.

A Trace is a cluster description (the fuzz scenario language's cluster
fields: flavors, cohort tree, ClusterQueues, policy gates) plus a
virtual-time event stream. Events come in two interchangeable forms:

  explicit     trace.events = [[kind, vtime, payload...], ...] — small
               traces, recorded fuzz scenarios, future production
               journals; kinds: "submit" (a workload spec, with an
               optional "duration_s"), "op" (any fuzz traffic op —
               finish/delete/update_cq/ready selectors), "tick" (a
               barrier tick at vtime; its presence makes the trace
               PACED — see engine.py), "spike" (a burst expanded into
               n submits at pop time, so a 50k-workload burst costs
               one trace entry).
  generator    trace.generator = {"shape", "workloads", "days", ...} —
               a lazy, seeded arrival process (see generators.py) that
               streams ~10^6 events without materializing them; the
               multi-day capacity-planning traces.

The JSON format (kueuetwin-trace/v1) also LOADS the fuzz subsystem's
files directly: a kueuefuzz/v1 scenario or a kueuefuzz-repro/v1
reproducer converts through from_scenario() into a paced trace whose
replay byte-matches the lattice drive (the cross-check oracle).
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional

from kueue_tpu.fuzz.scenario import Scenario

FORMAT = "kueuetwin-trace/v1"

# The virtual epoch: the lattice's TickClock starts here; paced traces
# must replay on the same clock values or condition timestamps (which
# feed candidate ordering) would fake a divergence.
T0 = 1_000_000.0

CLUSTER_FIELDS = ("flavors", "topology", "cohorts", "cluster_queues",
                  "policy")


@dataclasses.dataclass
class Trace:
    name: str
    seed: int
    cluster: dict                      # the scenario-language cluster
    events: Optional[List[list]] = None
    generator: Optional[dict] = None   # lazy spec (generators.py)
    paced: bool = False                # explicit tick events present
    tick_interval_s: float = 600.0     # event-driven tick cadence
    t0: float = T0
    meta: dict = dataclasses.field(default_factory=dict)

    # -- (de)serialization --------------------------------------------------

    def to_dict(self) -> dict:
        return {"format": FORMAT, "name": self.name, "seed": self.seed,
                "cluster": self.cluster, "events": self.events,
                "generator": self.generator, "paced": self.paced,
                "tick_interval_s": self.tick_interval_s,
                "t0": self.t0, "meta": self.meta}

    @staticmethod
    def from_dict(d: dict) -> "Trace":
        fmt = str(d.get("format", FORMAT))
        if fmt.startswith("kueuefuzz-repro/"):
            return Trace.from_scenario(
                Scenario.from_dict(d["scenario"]),
                name=str(d.get("name") or "fuzz-repro"))
        if fmt.startswith("kueuefuzz/"):
            return Trace.from_scenario(Scenario.from_dict(d))
        if not fmt.startswith("kueuetwin-trace/"):
            raise ValueError(f"not a twin trace (format={fmt!r})")
        return Trace(
            name=str(d.get("name") or "trace"),
            seed=int(d.get("seed", 0)),
            cluster=dict(d["cluster"]),
            events=[list(e) for e in d["events"]]
            if d.get("events") is not None else None,
            generator=(dict(d["generator"])
                       if d.get("generator") else None),
            paced=bool(d.get("paced")),
            tick_interval_s=float(d.get("tick_interval_s", 600.0)),
            t0=float(d.get("t0", T0)),
            meta=dict(d.get("meta") or {}))

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=1, sort_keys=True)

    @staticmethod
    def load(path: str) -> "Trace":
        """Load any of the three accepted formats: kueuetwin-trace/v1,
        a kueuefuzz/v1 scenario, or a kueuefuzz-repro/v1 reproducer."""
        with open(path, "r", encoding="utf-8") as f:
            return Trace.from_dict(json.load(f))

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.to_json())
            f.write("\n")

    # -- scenario bridge ----------------------------------------------------

    @staticmethod
    def from_scenario(sc: Scenario, name: Optional[str] = None) -> "Trace":
        """Convert a fuzz scenario into a PACED trace: initial
        workloads at t0, then per tick t the tick's ops at t0+t
        followed by an explicit tick event at t0+t — exactly the clock
        sequence of lattice._drive_framework (ops apply at the frozen
        clock, the tick runs, the clock advances by 1s). Replaying the
        result must byte-match drive() at the same lattice point; the
        cross-check mode (crosscheck.py) holds the twin to that."""
        events: List[list] = []
        for spec in sc.workloads:
            events.append(["submit", T0, dict(spec)])
        for t in range(sc.ticks + sc.settle_ticks):
            v = T0 + t
            if t < sc.ticks:
                for op in (sc.traffic[t]
                           if t < len(sc.traffic) else ()):
                    events.append(["op", v, list(op)])
            events.append(["tick", v])
        return Trace(
            name=name or f"fuzz-seed-{sc.seed}",
            seed=sc.seed,
            cluster=cluster_from_scenario(sc),
            events=events, paced=True, tick_interval_s=1.0,
            meta={"source": "kueuefuzz", "ticks": sc.ticks,
                  "settle_ticks": sc.settle_ticks})

    def cluster_scenario(self) -> Scenario:
        """The trace's cluster as an (empty-traffic) Scenario — what
        the engine hands to the fuzz subsystem's builders (flavor /
        cohort / CQ objects, nominal-capacity oracle)."""
        c = self.cluster
        return Scenario(
            seed=self.seed, ticks=0, settle_ticks=0,
            flavors=list(c["flavors"]), topology=c.get("topology"),
            cohorts=list(c.get("cohorts") or ()),
            cluster_queues=list(c["cluster_queues"]),
            policy=dict(c.get("policy") or {}),
            workloads=[], traffic=[])


def cluster_from_scenario(sc: Scenario) -> dict:
    return {"flavors": list(sc.flavors), "topology": sc.topology,
            "cohorts": list(sc.cohorts),
            "cluster_queues": list(sc.cluster_queues),
            "policy": dict(sc.policy)}


def twin_cluster(num_cqs: int = 64, num_cohorts: int = 16,
                 num_flavors: int = 2, cpu_quota: int = 64,
                 memory_gi_quota: int = 256, hetero: bool = False,
                 strategy: str = "BestEffortFIFO",
                 preemption: Optional[dict] = None) -> dict:
    """A uniform capacity-planning cluster in the scenario language:
    num_cqs ClusterQueues round-robined over flat cohorts, each with
    the same per-flavor quota. The what-if harness then perturbs THIS
    dict (quota resize, flavor-ladder change) per configuration."""
    flavors = [{"name": f"flavor-{f}",
                "speed_class": (1.0 + 0.5 * f) if hetero else 1.0}
               for f in range(num_flavors)]
    cqs = []
    for i in range(num_cqs):
        quotas = {fl["name"]: {"cpu": [cpu_quota, None, None],
                               "memory_gi": [memory_gi_quota,
                                             None, None]}
                  for fl in flavors}
        cqs.append({
            "name": f"cq-{i}",
            "cohort": (f"cohort-{i % num_cohorts}"
                       if num_cohorts else ""),
            "strategy": strategy,
            "preemption": dict(preemption) if preemption
            else {"within": "Never", "reclaim": "Never"},
            "fair_weight": None,
            "quotas": quotas})
    return {"flavors": flavors, "topology": None, "cohorts": [],
            "cluster_queues": cqs,
            "policy": {"fair": False, "lending": False,
                       "hetero": hetero, "pods_ready": False,
                       "shape": "twin"}}
