"""What-if harness: one trace, a sweep of capacity configurations.

A CapacityConfig perturbs the trace's cluster in the scenario
language — global quota resize, per-flavor quota resize (the
flavor-ladder question: what if we shift capacity from flavor-0 to
flavor-1?), speed-class changes on the hetero ladder, solver shards —
then the SAME virtual-time replay runs once per configuration and the
report compares the outcomes: goodput (completions per virtual day),
p50/p99 virtual submit->admitted wait, preemption count, quota
high-water ratio, and the fuzzer's quota-oracle verdict. Deltas are
against the first (baseline) configuration.

Config spec strings (the CLI surface):

    baseline
    quota-150:quota=1.5
    ladder:flavor.flavor-0=0.5,flavor.flavor-1=2.0
    fast-1:speed.flavor-1=2.0,shards=2,engine=jax

i.e. `name[:k=v,...]` with keys quota, flavor.<name>, speed.<name>,
shards, engine.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Dict, List, Optional

from kueue_tpu.twin.engine import TwinEngine
from kueue_tpu.twin.trace import Trace
from kueue_tpu.utils.envinfo import environment_block

REPORT_FORMAT = "kueuetwin-report/v1"


@dataclasses.dataclass
class CapacityConfig:
    name: str
    quota_factor: float = 1.0
    flavor_factors: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    speed_factors: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    shards: int = 1
    engine: Optional[str] = None   # None = the sweep's default_engine

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def parse_config(spec: str) -> CapacityConfig:
    name, _, rest = spec.partition(":")
    cfg = CapacityConfig(name=name or "config")
    if not rest:
        return cfg
    for item in rest.split(","):
        k, _, v = item.partition("=")
        k = k.strip()
        if not _ or not k:
            raise ValueError(f"what-if config wants k=v items "
                             f"(got {item!r} in {spec!r})")
        if k == "quota":
            cfg.quota_factor = float(v)
        elif k.startswith("flavor."):
            cfg.flavor_factors[k[len("flavor."):]] = float(v)
        elif k.startswith("speed."):
            cfg.speed_factors[k[len("speed."):]] = float(v)
        elif k == "shards":
            cfg.shards = int(v)
        elif k == "engine":
            cfg.engine = v.strip()
        else:
            raise ValueError(f"unknown what-if key {k!r} in {spec!r} "
                             f"(have quota, flavor.<name>, "
                             f"speed.<name>, shards, engine)")
    return cfg


def default_sweep() -> List[CapacityConfig]:
    """The stock capacity question: would 75% of today's quota still
    carry the trace, and what does 150% buy?"""
    return [CapacityConfig(name="baseline"),
            CapacityConfig(name="quota-75", quota_factor=0.75),
            CapacityConfig(name="quota-150", quota_factor=1.5)]


def _scale(val, f: float):
    # Quota tuples are [nominal, borrowing_limit, lending_limit] with
    # None = unlimited; unlimited stays unlimited under any resize.
    if val is None:
        return None
    return max(1, int(round(val * f)))


def apply_config(cluster: dict, cfg: CapacityConfig) -> dict:
    """The perturbed cluster: per-CQ per-flavor quota triples scaled by
    quota_factor x flavor_factors[flavor], flavor speed_classes scaled
    by speed_factors. Pure function — the input dict is not touched."""
    out = copy.deepcopy(cluster)
    for fl in out["flavors"]:
        sf = cfg.speed_factors.get(fl["name"])
        if sf is not None and fl.get("speed_class") is not None:
            fl["speed_class"] = round(fl["speed_class"] * sf, 4)
    for cq in out["cluster_queues"]:
        for fname, quotas in cq["quotas"].items():
            f = cfg.quota_factor * cfg.flavor_factors.get(fname, 1.0)
            if f == 1.0:
                continue
            for rname, triple in quotas.items():
                quotas[rname] = [_scale(v, f) for v in triple]
    return out


_DELTA_KEYS = ("goodput_wl_per_vday", "wait_p50_s", "wait_p99_s",
               "preemptions", "completed", "quota_high_water_max")


def _delta(base: dict, m: dict) -> dict:
    out = {}
    for k in _DELTA_KEYS:
        b, v = base.get(k), m.get(k)
        if b is None or v is None:
            out[k] = None
        else:
            out[k] = round(v - b, 4)
            if b:
                out[k + "_pct"] = round(100.0 * (v - b) / b, 2)
    return out


def sweep(trace: Trace, configs: Optional[List[CapacityConfig]] = None,
          default_engine: str = "jax", **engine_kwargs) -> dict:
    """Replay `trace` once per configuration; returns the comparison
    report (kueuetwin-report/v1). The first config is the baseline."""
    configs = configs or default_sweep()
    rows = []
    for cfg in configs:
        t = Trace(name=trace.name, seed=trace.seed,
                  cluster=apply_config(trace.cluster, cfg),
                  events=trace.events, generator=trace.generator,
                  paced=trace.paced,
                  tick_interval_s=trace.tick_interval_s,
                  t0=trace.t0, meta=trace.meta)
        engine = cfg.engine or default_engine
        res = TwinEngine(t, engine=engine, shards=cfg.shards,
                         record_trail=False, **engine_kwargs).run()
        cfg_doc = cfg.to_dict()
        cfg_doc["engine"] = engine
        rows.append({"name": cfg.name, "config": cfg_doc,
                     "metrics": res["metrics"],
                     "high_water": res["high_water"],
                     "quota_violations": res["violation_count"],
                     "violations_sample": res["violations"][:8]})
    base = rows[0]["metrics"]
    for row in rows[1:]:
        row["delta_vs_baseline"] = _delta(base, row["metrics"])
    return {
        "format": REPORT_FORMAT,
        "trace": {"name": trace.name, "seed": trace.seed,
                  "generator": trace.generator,
                  "paced": trace.paced,
                  "tick_interval_s": trace.tick_interval_s,
                  "events": (len(trace.events)
                             if trace.events is not None else None)},
        "baseline": rows[0]["name"],
        "configs": rows,
        # Same machine block as every BENCH json (cpu count, load,
        # python/jax versions) — cross-run comparisons stay
        # machine-checkable.
        "environment": environment_block(),
        "ok": all(r["quota_violations"] == 0 for r in rows),
    }


def format_report(report: dict) -> str:
    """The human view: one aligned row per configuration."""
    cols = ("config", "goodput/vday", "p50 wait", "p99 wait",
            "preempt", "hiwater", "quota-red")
    lines = [" | ".join(f"{c:>13}" for c in cols)]
    lines.append("-+-".join("-" * 13 for _ in cols))
    for row in report["configs"]:
        m = row["metrics"]

        def fmt(v):
            return "-" if v is None else (f"{v:.1f}"
                                          if isinstance(v, float)
                                          else str(v))

        lines.append(" | ".join(f"{fmt(v):>13}" for v in (
            row["name"], m.get("goodput_wl_per_vday"),
            m.get("wait_p50_s"), m.get("wait_p99_s"),
            m.get("preemptions"), m.get("quota_high_water_max"),
            row["quota_violations"])))
    return "\n".join(lines)
