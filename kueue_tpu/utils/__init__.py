"""Generic helpers."""

from kueue_tpu.utils.heap import KeyedHeap
