"""Machine environment snapshot for benchmark / fuzz artifacts.

Every BENCH json record (and every fuzz/soak report) carries this block so
the long-standing "bench boxes drift run to run — compare within-run only"
caveat is machine-checkable: a reader comparing two artifacts can tell
whether they came from the same container shape (cpu count, python/jax
versions, container hint) and how loaded the box was while measuring
(load average next to cpu count), instead of trusting a prose note.

Import-light by design: jax is only version-probed through importlib
metadata (no backend initialization), so bench's subprocess drivers and
the stdlib-only analysis tools can all call it.
"""

from __future__ import annotations

import os
import platform
import socket


def _container_hint() -> str:
    """Best-effort container runtime detection: docker/podman drop
    marker files, k8s mounts a service-account dir, and cgroup paths
    name the runtime. "none" means no marker found, not proof of bare
    metal."""
    if os.path.exists("/var/run/secrets/kubernetes.io"):
        return "kubernetes"
    if os.path.exists("/.dockerenv"):
        return "docker"
    if os.path.exists("/run/.containerenv"):
        return "podman"
    try:
        with open("/proc/1/cgroup", "r", encoding="utf-8") as f:
            body = f.read()
        for marker in ("kubepods", "docker", "containerd", "lxc"):
            if marker in body:
                return marker
    except OSError:
        pass
    return "none"


def _dist_version(name: str):
    try:
        from importlib import metadata

        return metadata.version(name)
    except Exception:
        return None


def environment_block() -> dict:
    """The per-run environment evidence block (JSON-ready)."""
    try:
        load1, load5, load15 = os.getloadavg()
        load = [round(load1, 2), round(load5, 2), round(load15, 2)]
    except OSError:
        load = None
    return {
        "hostname": socket.gethostname(),
        "platform": platform.platform(),
        "cpu_count": os.cpu_count(),
        "load_avg": load,
        "python": platform.python_version(),
        "jax": _dist_version("jax"),
        "numpy": _dist_version("numpy"),
        "container": _container_hint(),
        "backend_env": os.environ.get("JAX_PLATFORMS") or None,
    }
