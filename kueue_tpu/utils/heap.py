"""A keyed binary heap with in-place update/delete.

Counterpart of reference pkg/util/heap (heap.go): items are addressed by a
string key; ordering comes from a user `less` function. Used for the pending
queues.
"""

from __future__ import annotations

from typing import Callable, Dict, Generic, List, Optional, TypeVar

T = TypeVar("T")


class KeyedHeap(Generic[T]):
    def __init__(self, key_fn: Callable[[T], str], less: Callable[[T, T], bool]):
        self._key_fn = key_fn
        self._less = less
        self._items: List[T] = []
        self._index: Dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def get_by_key(self, key: str) -> Optional[T]:
        i = self._index.get(key)
        return None if i is None else self._items[i]

    def items(self) -> List[T]:
        return list(self._items)

    def push_if_not_present(self, item: T) -> bool:
        key = self._key_fn(item)
        if key in self._index:
            return False
        self._push(key, item)
        return True

    def push_or_update(self, item: T) -> None:
        key = self._key_fn(item)
        i = self._index.get(key)
        if i is None:
            self._push(key, item)
        else:
            self._items[i] = item
            self._fix(i)

    def delete(self, key: str) -> Optional[T]:
        i = self._index.get(key)
        if i is None:
            return None
        return self._remove_at(i)

    def peek(self) -> Optional[T]:
        return self._items[0] if self._items else None

    def pop(self) -> Optional[T]:
        if not self._items:
            return None
        return self._remove_at(0)

    # -- internals ----------------------------------------------------------

    def _push(self, key: str, item: T) -> None:
        self._items.append(item)
        i = len(self._items) - 1
        self._index[key] = i
        self._up(i)

    def _remove_at(self, i: int) -> T:
        item = self._items[i]
        del self._index[self._key_fn(item)]
        last = self._items.pop()
        if i < len(self._items):
            self._items[i] = last
            self._index[self._key_fn(last)] = i
            self._fix(i)
        return item

    def _fix(self, i: int) -> None:
        if not self._down(i):
            self._up(i)

    def _up(self, i: int) -> None:
        items = self._items
        while i > 0:
            parent = (i - 1) // 2
            if not self._less(items[i], items[parent]):
                break
            self._swap(i, parent)
            i = parent

    def _down(self, i: int) -> bool:
        items = self._items
        n = len(items)
        start = i
        while True:
            left = 2 * i + 1
            if left >= n:
                break
            smallest = left
            right = left + 1
            if right < n and self._less(items[right], items[left]):
                smallest = right
            if not self._less(items[smallest], items[i]):
                break
            self._swap(i, smallest)
            i = smallest
        return i > start

    def _swap(self, i: int, j: int) -> None:
        items = self._items
        items[i], items[j] = items[j], items[i]
        self._index[self._key_fn(items[i])] = i
        self._index[self._key_fn(items[j])] = j
