"""LimitRange summarization, validation and workload resource adjustment.

Counterpart of reference pkg/util/limitrange/limitrange.go and
pkg/workload/resources.go: namespaces can carry LimitRange constraints that
(a) default container requests/limits and (b) bound what a pod may request.
Workload podset requests are derived from their pod templates only after
RuntimeClass overhead, LimitRange defaults and limits->requests defaulting
have been folded in (AdjustResources, resources.go:102-115), and the
scheduler rejects workloads whose templates violate the active LimitRange
summary (scheduler.go validateResources/validateLimitRange analog).

All quantities are canonical integers keyed by resource name.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence

from kueue_tpu.api.types import Container, PodTemplate, Workload

LIMIT_TYPE_POD = "Pod"
LIMIT_TYPE_CONTAINER = "Container"


@dataclass
class LimitRangeItem:
    """One constraint row (k8s core/v1 LimitRangeItem subset)."""

    type: str  # Pod | Container
    max: Dict[str, int] = field(default_factory=dict)
    min: Dict[str, int] = field(default_factory=dict)
    default: Dict[str, int] = field(default_factory=dict)  # default limits
    default_request: Dict[str, int] = field(default_factory=dict)


@dataclass
class LimitRange:
    name: str = ""
    namespace: str = "default"
    items: List[LimitRangeItem] = field(default_factory=list)


def _merge_keep_min(a: Dict[str, int], b: Mapping[str, int]) -> Dict[str, int]:
    out = dict(a)
    for k, v in b.items():
        if k not in out or v < out[k]:
            out[k] = v
    return out


def _merge_keep_max(a: Dict[str, int], b: Mapping[str, int]) -> Dict[str, int]:
    out = dict(a)
    for k, v in b.items():
        if k not in out or v > out[k]:
            out[k] = v
    return out


def _merge_keep_first(a: Dict[str, int], b: Mapping[str, int]) -> Dict[str, int]:
    out = dict(a)
    for k, v in b.items():
        out.setdefault(k, v)
    return out


class Summary(dict):
    """limit type -> folded LimitRangeItem (limitrange.go:31-57).

    Max keeps the lowest value across ranges, Min the highest, defaults the
    first encountered.
    """

    def validate_pod_template(self, pt: PodTemplate,
                              path: str = "podSpec") -> List[str]:
        """ValidatePodSpec (limitrange.go:103-118): container-level bounds on
        every (init)container, pod-level bounds on the pod total."""
        reasons: List[str] = []
        reasons += self._validate_containers(
            pt.init_containers, f"{path}.initContainers")
        reasons += self._validate_containers(
            pt.containers, f"{path}.containers")
        pod_range = self.get(LIMIT_TYPE_POD)
        if pod_range is not None:
            total = pt.total_requests()
            over = _greater_keys(total, pod_range.max)
            if over:
                reasons.append(_violate_max(path, over))
            under = _greater_keys(pod_range.min, total)
            if under:
                reasons.append(_violate_min(path, under))
        return reasons

    def _validate_containers(self, containers: Sequence[Container],
                             path: str) -> List[str]:
        crange = self.get(LIMIT_TYPE_CONTAINER)
        if crange is None:
            return []
        reasons = []
        for i, c in enumerate(containers):
            cmin = _merge_keep_min(dict(c.requests), c.limits)
            cmax = _merge_keep_max(dict(c.requests), c.limits)
            over = _greater_keys(cmax, crange.max)
            if over:
                reasons.append(_violate_max(f"{path}[{i}]", over))
            under = _greater_keys(crange.min, cmin)
            if under:
                reasons.append(_violate_min(f"{path}[{i}]", under))
        return reasons


def _greater_keys(a: Mapping[str, int], b: Mapping[str, int]) -> List[str]:
    """Keys present in both where a[k] > b[k] (resource.GetGreaterKeys)."""
    return sorted(k for k, v in a.items() if k in b and v > b[k])


def _violate_max(path: str, keys: List[str]) -> str:
    return f"the requests of {path}[{', '.join(keys)}] exceeds the limits"


def _violate_min(path: str, keys: List[str]) -> str:
    return f"the requests of {path}[{', '.join(keys)}] are less than the limits"


def summarize(ranges: Sequence[LimitRange]) -> Summary:
    """Fold many LimitRanges into one Summary (limitrange.go:37-45)."""
    out = Summary()
    for lr in ranges:
        for item in lr.items:
            cur = out.get(item.type)
            if cur is None:
                cur = LimitRangeItem(type=item.type)
                out[item.type] = cur
            cur.max = _merge_keep_min(cur.max, item.max)
            cur.min = _merge_keep_max(cur.min, item.min)
            cur.default = _merge_keep_first(cur.default, item.default)
            cur.default_request = _merge_keep_first(
                cur.default_request, item.default_request)
    return out


def adjust_resources(
        wl: Workload,
        limit_ranges: Sequence[LimitRange] = (),
        runtime_class_overheads: Optional[Mapping[str, Mapping[str, int]]] = None,
) -> None:
    """workload.AdjustResources (resources.go:102-115): for every podset
    that carries a template, fold in

    1. RuntimeClass pod overhead when the template names a runtime class and
       has no explicit overhead (handlePodOverhead, resources.go:36-53),
    2. LimitRange container defaults: default -> limits, defaultRequest ->
       requests, first-value-wins (handlePodLimitRange, resources.go:57-86),
    3. limits -> requests defaulting (handleLimitsToRequests, :88-100),

    then recompute the podset's per-pod `requests` from the template.
    """
    overheads = runtime_class_overheads or {}
    summary = summarize(limit_ranges)
    crange = summary.get(LIMIT_TYPE_CONTAINER)
    for ps in wl.pod_sets:
        pt = ps.template
        if pt is None:
            continue
        if pt.runtime_class_name and not pt.overhead:
            oh = overheads.get(pt.runtime_class_name)
            if oh is not None:
                pt.overhead = dict(oh)
        for c in list(pt.init_containers) + list(pt.containers):
            if crange is not None:
                c.limits = _merge_keep_first(c.limits, crange.default)
                c.requests = _merge_keep_first(
                    c.requests, crange.default_request)
            c.requests = _merge_keep_first(c.requests, c.limits)
        ps.requests = pt.total_requests()


def validate_workload_against(
        wl: Workload, limit_ranges: Sequence[LimitRange]) -> List[str]:
    """The scheduler-side admission gate (scheduler.go nominate ->
    validateLimitRange): reasons why the workload's templates violate the
    namespace's LimitRange summary; empty means admissible."""
    if not limit_ranges:
        return []
    summary = summarize(limit_ranges)
    reasons: List[str] = []
    for i, ps in enumerate(wl.pod_sets):
        if ps.template is None:
            continue
        reasons += summary.validate_pod_template(
            ps.template, path=f"podSets[{i}].template")
    return reasons


def validate_limits_fit_requests(wl: Workload) -> List[str]:
    """scheduler.go validateResources: requests must not exceed limits."""
    reasons: List[str] = []
    for i, ps in enumerate(wl.pod_sets):
        if ps.template is None:
            continue
        for kind, containers in (("initContainers", ps.template.init_containers),
                                 ("containers", ps.template.containers)):
            for j, c in enumerate(containers):
                bad = _greater_keys(c.requests, c.limits)
                if bad:
                    reasons.append(
                        f"requests exceed limits in podSets[{i}].template."
                        f"{kind}[{j}]: {', '.join(bad)}")
    return reasons
