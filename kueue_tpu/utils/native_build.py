"""Shared build-and-cache helper for the native (C++) components.

Compiles a source under kueue_tpu/native/ with the toolchain's g++ on first
use, caching the .so next to it; returns None when the toolchain or the
build is unavailable so callers fall back to their pure-Python twins.
Used by utils/native_heap.py (ctypes library) and utils/native_decode.py
(CPython extension).
"""

from __future__ import annotations

import os
import subprocess
import sysconfig
import threading
from typing import List, Optional

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")

_lock = threading.Lock()


def build(src_name: str, lib_name: str,
          python_ext: bool = False) -> Optional[str]:
    """Compile native/<src_name> into native/<lib_name> if stale.

    Returns the library path, or None when the build is unavailable. Safe
    under concurrent callers: the compile goes to a pid-suffixed temp file
    and lands with an atomic rename.
    """
    src = os.path.join(NATIVE_DIR, src_name)
    lib = os.path.join(NATIVE_DIR, lib_name)
    with _lock:
        try:
            if (os.path.exists(lib)
                    and os.path.getmtime(lib) >= os.path.getmtime(src)):
                return lib
            cmd: List[str] = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17"]
            if python_ext:
                cmd.append(f"-I{sysconfig.get_paths()['include']}")
            tmp = f"{lib}.{os.getpid()}.tmp"
            cmd += ["-o", tmp, src]
            result = subprocess.run(cmd, capture_output=True, timeout=180)
            if result.returncode != 0:
                return None
            os.replace(tmp, lib)
            return lib
        except (OSError, subprocess.SubprocessError, KeyError):
            return None
