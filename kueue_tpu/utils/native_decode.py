"""Loader for the native decision decoder (kueue_tpu/native/decode.cpp).

Builds the CPython extension with the toolchain's g++ on first use and
caches the .so next to the source (same discipline as native_heap.py).
`decode_available()` gates use; callers fall back to the pure-Python
decode loop in `kueue_tpu.models.flavor_fit` when the toolchain or the
build is unavailable.
"""

from __future__ import annotations

import importlib.machinery
import importlib.util
import threading
from typing import Optional

from kueue_tpu.utils import native_build

_lock = threading.Lock()
_mod = None
_tried = False


def load() -> Optional[object]:
    """The `_kueue_decode` extension module, or None."""
    global _mod, _tried
    with _lock:
        if _tried:
            return _mod
        _tried = True
        lib = native_build.build("decode.cpp", "_kueue_decode.so",
                                python_ext=True)
        if lib is None:
            return None
        try:
            loader = importlib.machinery.ExtensionFileLoader(
                "_kueue_decode", lib)
            spec = importlib.util.spec_from_loader("_kueue_decode", loader)
            mod = importlib.util.module_from_spec(spec)
            loader.exec_module(mod)
        except (ImportError, OSError):
            return None
        _mod = mod
        return _mod


def decode_available() -> bool:
    return load() is not None
