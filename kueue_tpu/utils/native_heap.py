"""ctypes binding for the native keyed heap (kueue_tpu/native/heap.cpp).

The shared library is built on first import with the toolchain's g++ and
cached next to the source; when the toolchain or the build is unavailable
the caller falls back to the pure-Python `utils.heap.KeyedHeap` (same
interface, same ordering contract).

`NativeKeyedHeap` orders items by a caller-supplied integer sort-key vector
(lexicographic ascending), the native mirror of the `less` callable of the
Python heap.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Callable, Dict, Generic, List, Optional, Sequence, TypeVar

from kueue_tpu.utils import native_build

T = TypeVar("T")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        path = native_build.build("heap.cpp", "_libkueue_heap.so")
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.kh_new.restype = ctypes.c_void_p
        lib.kh_new.argtypes = [ctypes.c_int]
        lib.kh_free.argtypes = [ctypes.c_void_p]
        lib.kh_len.restype = ctypes.c_int64
        lib.kh_len.argtypes = [ctypes.c_void_p]
        lib.kh_contains.restype = ctypes.c_int
        lib.kh_contains.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.kh_push_if_not_present.restype = ctypes.c_int
        lib.kh_push_if_not_present.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_int64)]
        lib.kh_push_or_update.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_int64)]
        lib.kh_delete.restype = ctypes.c_int
        lib.kh_delete.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.kh_pop.restype = ctypes.c_uint64
        lib.kh_pop.argtypes = [ctypes.c_void_p]
        lib.kh_peek.restype = ctypes.c_uint64
        lib.kh_peek.argtypes = [ctypes.c_void_p]
        lib.kh_items.restype = ctypes.c_int64
        lib.kh_items.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_uint64),
                                 ctypes.c_int64]
        # Older cached builds may predate kh_pop_many; callers probe via
        # pop_many_available().
        if hasattr(lib, "kh_pop_many"):
            lib.kh_pop_many.restype = None
            lib.kh_pop_many.argtypes = [ctypes.POINTER(ctypes.c_void_p),
                                        ctypes.c_int64,
                                        ctypes.POINTER(ctypes.c_uint64)]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def pop_many_available() -> bool:
    lib = _load()
    return lib is not None and hasattr(lib, "kh_pop_many")


class PopGroup:
    """Reusable batched-pop plan over a fixed set of NativeKeyedHeaps.

    One `kh_pop_many` call pops the head of every heap in the group —
    one Python/C crossing per TICK instead of one per ClusterQueue
    (manager.heads at 1k queues). The ctypes handle/result buffers are
    built once and reused; rebuild the group whenever the heap set
    changes (the queue manager keys it on its ClusterQueue-set
    version)."""

    __slots__ = ("heaps", "_handles", "_out", "_n", "_lib")

    def __init__(self, heaps: Sequence["NativeKeyedHeap"]):
        lib = _load()
        if lib is None or not hasattr(lib, "kh_pop_many"):
            raise RuntimeError("native pop_many unavailable")
        self._lib = lib
        self.heaps = list(heaps)
        n = len(self.heaps)
        self._n = n
        self._handles = (ctypes.c_void_p * n)(
            *[h._h for h in self.heaps])
        self._out = (ctypes.c_uint64 * n)()

    def pop_each(self) -> List[Optional[T]]:
        """Pop the top item of every heap (None where empty)."""
        out = self._out
        self._lib.kh_pop_many(self._handles, self._n, out)
        results: List[Optional[T]] = []
        append = results.append
        for i, heap in enumerate(self.heaps):
            iid = out[i]
            append(None if iid == _EMPTY else heap._claim(iid))
        return results


_EMPTY = 2**64 - 1


class NativeKeyedHeap(Generic[T]):
    """Drop-in for utils.heap.KeyedHeap, ordered by an integer key vector.

    `sort_key_fn(item)` returns a fixed-length tuple of ints; smaller sorts
    first (encode "priority desc" as -priority). Keys are refreshed on
    push_or_update, exactly like the Python heap's `_fix`.
    """

    def __init__(self, key_fn: Callable[[T], str],
                 sort_key_fn: Callable[[T], Sequence[int]],
                 key_len: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native heap unavailable")
        self._libref = lib
        self._key_fn = key_fn
        self._sort_key_fn = sort_key_fn
        self._key_len = key_len
        # +1: the item id is appended as a deterministic final tiebreak
        # (first-inserted key wins among equal sort keys).
        self._h = lib.kh_new(key_len + 1)
        self._next_id = 0
        self._id_by_key: Dict[str, int] = {}
        self._obj_by_id: Dict[int, T] = {}
        # Reverse map so pop/delete skip the key_fn property chain (the
        # heads sweep pops one item per ClusterQueue per tick).
        self._key_by_id: Dict[int, str] = {}
        # Reusable key buffer: the C side copies the key on push, so one
        # buffer per heap serves every call — constructing a fresh ctypes
        # array per push dominated the requeue sweep at scale.
        self._keybuf = (ctypes.c_int64 * (key_len + 1))()

    def __del__(self):
        try:
            self._libref.kh_free(self._h)
        except Exception:
            pass

    def __len__(self) -> int:
        return int(self._libref.kh_len(self._h))

    def __contains__(self, key: str) -> bool:
        return key in self._id_by_key

    def _ckey(self, item: T, item_id: int):
        vec = self._sort_key_fn(item)
        buf = self._keybuf
        i = 0
        for v in vec:
            buf[i] = v
            i += 1
        if i != self._key_len:
            raise ValueError(f"sort key length {i} != {self._key_len}")
        buf[i] = item_id
        return buf

    def _id_for(self, key: str) -> int:
        i = self._id_by_key.get(key)
        if i is None:
            i = self._next_id
            self._next_id += 1
            self._id_by_key[key] = i
            self._key_by_id[i] = key
        return i

    def get_by_key(self, key: str) -> Optional[T]:
        i = self._id_by_key.get(key)
        return self._obj_by_id.get(i) if i is not None else None

    def items(self) -> List[T]:
        n = len(self)
        buf = (ctypes.c_uint64 * n)()
        got = self._libref.kh_items(self._h, buf, n)
        return [self._obj_by_id[buf[i]] for i in range(got)]

    def push_if_not_present(self, item: T) -> bool:
        key = self._key_fn(item)
        i = self._id_for(key)
        inserted = self._libref.kh_push_if_not_present(
            self._h, i, self._ckey(item, i))
        if inserted:
            self._obj_by_id[i] = item
            return True
        return False

    def push_or_update(self, item: T) -> None:
        key = self._key_fn(item)
        i = self._id_for(key)
        self._obj_by_id[i] = item
        self._libref.kh_push_or_update(self._h, i, self._ckey(item, i))

    def delete(self, key: str) -> Optional[T]:
        i = self._id_by_key.get(key)
        if i is None or not self._libref.kh_delete(self._h, i):
            return None
        obj = self._obj_by_id.pop(i)
        del self._id_by_key[key]
        self._key_by_id.pop(i, None)
        return obj

    def peek(self) -> Optional[T]:
        i = self._libref.kh_peek(self._h)
        return None if i == _EMPTY else self._obj_by_id[i]

    def _claim(self, iid: int) -> T:
        """Unwind the Python-side bookkeeping of an id the C heap just
        popped — shared by pop() and PopGroup.pop_each so the batched
        sweep can never diverge from the single-pop path."""
        obj = self._obj_by_id.pop(iid)
        del self._id_by_key[self._key_by_id.pop(iid)]
        return obj

    def pop(self) -> Optional[T]:
        i = self._libref.kh_pop(self._h)
        if i == _EMPTY:
            return None
        return self._claim(i)
