"""Loader for the native usage-ledger walks (kueue_tpu/native/ledger.cpp).

Same build-and-cache discipline as native_decode.py; callers fall back to
the pure-Python walks in kueue_tpu.core.cache when the toolchain or the
build is unavailable.
"""

from __future__ import annotations

import importlib.machinery
import importlib.util
import threading
from typing import Optional

from kueue_tpu.utils import native_build

_lock = threading.Lock()
_mod = None
_tried = False


def load() -> Optional[object]:
    """The `_kueue_ledger` extension module, or None."""
    global _mod, _tried
    with _lock:
        if _tried:
            return _mod
        _tried = True
        lib = native_build.build("ledger.cpp", "_kueue_ledger.so",
                                python_ext=True)
        if lib is None:
            return None
        try:
            loader = importlib.machinery.ExtensionFileLoader(
                "_kueue_ledger", lib)
            spec = importlib.util.spec_from_loader("_kueue_ledger", loader)
            mod = importlib.util.module_from_spec(spec)
            loader.exec_module(mod)
        except (ImportError, OSError):
            return None
        _mod = mod
        return _mod


def ledger_available() -> bool:
    return load() is not None
