"""Bounded-fanout parallel apply.

Counterpart of reference pkg/util/parallelize (parallelize.go:25,60): run
one function over N indices on up to `workers` threads, collecting the
first error. The reference uses this for the 8-way parallel preemption SSA
patches (preemption.go:44,135) and workload status writes — host-side I/O
fan-out, which in this runtime matters when apply callbacks cross a network
boundary (store-backed or gRPC deployments).
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, Sequence, TypeVar

T = TypeVar("T")

DEFAULT_WORKERS = 8

# One long-lived pool for the default fan-out: the scheduler issues one
# for_each per preempting entry per cycle (~100/tick at preemption-heavy
# scale), and constructing/tearing down a ThreadPoolExecutor per call
# costs more than the apply work it parallelizes. Lazily created;
# never shut down (daemonic usage pattern, same lifetime as the process).
_SHARED_POOL: Optional[ThreadPoolExecutor] = None


def _shared_pool() -> ThreadPoolExecutor:
    global _SHARED_POOL
    if _SHARED_POOL is None:
        _SHARED_POOL = ThreadPoolExecutor(
            max_workers=DEFAULT_WORKERS, thread_name_prefix="kueue-par")
    return _SHARED_POOL


def until(n: int, fn: Callable[[int], None],
          workers: int = DEFAULT_WORKERS) -> Optional[BaseException]:
    """Run fn(0..n-1), at most `workers` at a time; returns the first
    exception raised (parallelize.Until returns the first error)."""
    if n <= 0:
        return None
    if n == 1 or workers <= 1:
        # No thread overhead for the common tiny case.
        try:
            for i in range(n):
                fn(i)
        except BaseException as exc:  # noqa: BLE001 — error-as-value API
            return exc
        return None
    first: list = [None]
    if workers == DEFAULT_WORKERS:
        if threading.current_thread().name.startswith("kueue-par"):
            # Nested fan-out from inside the shared pool would deadlock
            # (outer tasks waiting on futures that can only run on the
            # same saturated pool) — run inline instead.
            try:
                for i in range(n):
                    fn(i)
            except BaseException as exc:  # noqa: BLE001
                return exc
            return None
        pool = _shared_pool()
        futures = [pool.submit(fn, i) for i in range(n)]
        for f in futures:
            exc = f.exception()
            if exc is not None and first[0] is None:
                first[0] = exc
        return first[0]
    with ThreadPoolExecutor(max_workers=min(workers, n)) as pool:
        futures = [pool.submit(fn, i) for i in range(n)]
        for f in futures:
            exc = f.exception()
            if exc is not None and first[0] is None:
                first[0] = exc
    return first[0]


def for_each(items: Sequence[T], fn: Callable[[T], None],
             workers: int = DEFAULT_WORKERS) -> Optional[BaseException]:
    return until(len(items), lambda i: fn(items[i]), workers=workers)
