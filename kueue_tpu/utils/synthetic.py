"""Synthetic problem generator for benchmarks and compile checks.

Shapes follow the north-star scale target (BASELINE.md): up to 50k pending
Workloads x 1k ClusterQueues x 100 cohorts x 8 ResourceFlavors.
"""

from __future__ import annotations

import random
from typing import List, Optional, Tuple

from kueue_tpu.api.types import (
    Admission,
    ClusterQueue,
    ClusterQueuePreemption,
    FairSharing,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    PodSetAssignment,
    ResourceFlavor,
    ResourceGroup,
    Workload,
)
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.snapshot import Snapshot
from kueue_tpu.core.workload import WorkloadInfo


def hetero_profile_draw(rnd, num_flavors: int):
    """One workload's synthetic per-flavor speedup profile — shared by
    the generator's pending loop and bench.py's churn arrivals so the
    hetero bench measures ONE population (a drift between the two would
    silently mix distributions under the gain gate)."""
    f_a, f_b = rnd.sample(range(num_flavors), 2)
    return {f"flavor-{f_a}": float(rnd.choice([2, 4, 8])),
            f"flavor-{f_b}": float(rnd.choice([1, 2]))}


def churn_arrival_draw(rnd, num_cqs: int, num_flavors: int = 0, *,
                       preemption_heavy: bool = False, topology: bool = False,
                       hetero: bool = False, seq: int = 0) -> dict:
    """One churn/replacement arrival's randomized fields — the ONE home of
    the arrival distribution shared by bench.py's completion flux (both
    the in-process loop and the replica bulk-wire variant) and the fuzz
    generator's traffic shapes. Before this helper the three call sites
    carried drifting copies of the same draws; now a distribution change
    lands everywhere at once.

    Returns a plain spec dict (`queue_index`, `priority`, `count`, `cpu`,
    `memory_gi`, plus `topo_kw` / `tputs` extras) the caller turns into a
    Workload (or ships over the replica bulk wire)."""
    c = rnd.randrange(num_cqs)
    if preemption_heavy:
        priority = rnd.randint(1, 5) if seq % 2 else rnd.randint(-2, 0)
    else:
        priority = rnd.randint(-2, 2)
    topo_kw = {}
    if topology:
        topo_kw = ({"topology_required": "rack"} if seq % 4 == 0
                   else {"topology_preferred": "rack"})
    tputs = hetero_profile_draw(rnd, num_flavors) if hetero else None
    return {
        "queue_index": c,
        "priority": priority,
        "count": rnd.randint(1, 8),
        "cpu": rnd.randint(1, 8),
        "memory_gi": rnd.randint(1, 16),
        "topo_kw": topo_kw,
        "tputs": tputs,
    }


def diurnal_rate(tick: int, period: int = 24, lo: float = 0.0,
                 hi: float = 3.0) -> float:
    """Mean arrivals for tick `tick` of a diurnal (sinusoidal) traffic
    shape: peaks mid-period, troughs at the boundaries. Shared by the
    fuzz generator's `diurnal` traffic shape so replays are a pure
    function of the tick index."""
    import math

    period = max(period, 1)
    phase = (tick % period) / period
    return lo + (hi - lo) * 0.5 * (1.0 - math.cos(2.0 * math.pi * phase))


def heavy_tailed_int(rnd, lo: int = 1, hi: int = 64,
                     alpha: float = 1.3) -> int:
    """A bounded Pareto-ish integer draw (most values near `lo`, rare
    large spikes up to `hi`) — the heavy-tailed job-size distribution of
    the Mesos multi-framework study's workload mixes."""
    u = max(rnd.random(), 1e-9)
    v = int(lo / (u ** (1.0 / alpha)))
    return max(lo, min(hi, v))


def synthetic_objects(
    num_cqs: int = 1000,
    num_cohorts: int = 100,
    num_flavors: int = 8,
    num_pending: int = 1000,
    usage_fill: float = 0.5,
    seed: int = 0,
    pending_priority: Tuple[int, int] = (-2, 2),
    preemption_heavy: bool = False,
    fair_hierarchy: bool = False,
    lending: bool = False,
    topology: bool = False,
    strict_fifo: bool = False,
    no_preemption: bool = False,
    hetero: bool = False,
    cq_filter=None,
):
    """Generate the raw API objects of a north-star-scale cluster:
    (flavors, cluster_queues, local_queues, admitted workloads with their
    Admission pre-set, pending workloads, cohort_specs).

    `preemption_heavy` builds BASELINE config #3: reclaimWithinCohort +
    borrowWithinCohort(LowerPriority) + withinClusterQueue(LowerPriority)
    on every CQ, low-priority admitted background load and high-priority
    pending — most nominations resolve by preempting victims
    (preemption.go:81-231 is the exercised path).

    `fair_hierarchy` builds BASELINE config #4 (KEP-1714 over KEP-79): the
    flat cohorts become leaves of a 3-level tree (leaf cohorts → 10 mid
    cohorts → one root) and every ClusterQueue carries a fair-sharing
    weight; enable the FairSharing gate to exercise the DRF ordering.

    `topology` builds the topology-aware bench config: every flavor
    declares a block→rack→host TopologySpec (2x2x4 hosts of 8 pod slots)
    and every pending workload's podsets request slice packing — each
    fourth workload `required: rack`, the rest `preferred: rack` — so the
    whole topology stage (batched fit, cycle charging, ledger) runs on
    every tick.

    `hetero` builds the heterogeneity-aware bench config: the flavor set
    becomes a speed ladder (flavor-f at speed_class 1.0 + 0.5*f), every
    ClusterQueue lists its flavors SLOWEST FIRST (the regime where
    ordered first-fit parks fast workloads on slow accelerators — what
    Gavel measures as the 2-3x aggregate-throughput loss), and every
    pending workload declares per-flavor throughput overrides on two of
    its flavors.

    `cq_filter(c) -> bool` keeps only the objects of the selected
    ClusterQueue indices — the replica runtime's per-worker slice. The
    RANDOM DRAWS still run for every index (filtered or not), so any
    union of slices equals the unfiltered world object for object; only
    the construction (and memory) of filtered objects is skipped."""
    rnd = random.Random(seed)
    if preemption_heavy:
        pending_priority = (1, 5)

    cohort_specs: List = []
    if fair_hierarchy:
        from kueue_tpu.api.types import CohortSpec
        cohort_specs.append(CohortSpec(name="root"))
        n_mids = min(10, max(1, num_cohorts // 10))
        for m in range(n_mids):
            cohort_specs.append(CohortSpec(name=f"mid-{m}", parent="root"))
        for k in range(num_cohorts):
            cohort_specs.append(CohortSpec(
                name=f"cohort-{k}", parent=f"mid-{k % n_mids}"))

    topo_spec = None
    if topology:
        from kueue_tpu.api.types import TopologySpec
        topo_spec = TopologySpec.uniform(
            ("block", "rack", "host"), (2, 2, 4), leaf_capacity=8)
    flavors = [ResourceFlavor.make(
        f"flavor-{f}", topology=topo_spec,
        speed_class=(1.0 + 0.5 * f) if hetero else 1.0)
        for f in range(num_flavors)]

    cqs: List[ClusterQueue] = []
    lqs: List[LocalQueue] = []
    kept: List[int] = []
    cq_by_index = {}
    for c in range(num_cqs):
        keep = cq_filter is None or cq_filter(c)
        n_flavors = rnd.randint(2, min(4, num_flavors))
        chosen = rnd.sample(range(num_flavors), n_flavors)
        if hetero:
            # Slowest flavor first: the ordered first-fit baseline lands
            # here, which is exactly what the hetero mode must beat.
            chosen.sort()
        # Draw the quota numbers (and the fair weight) unconditionally
        # (the cq_filter draw contract), construct objects only for
        # kept indices.
        draws = [(rnd.randint(16, 128), rnd.randint(64, 512))
                 for _fi in chosen]
        fair_weight = float(rnd.randint(1, 4)) if fair_hierarchy else None
        if not keep:
            continue
        kept.append(c)
        if lending:
            # BASELINE config #2 quotas: borrowing allowed, lending
            # clamped below nominal (clusterqueue.go:583-629 semantics).
            def _q(nom, unit=1):
                return (nom * unit, (nom // 2) * unit,
                        max(1, (3 * nom) // 4) * unit)
            fqs = tuple(
                FlavorQuotas.make(
                    f"flavor-{fi}",
                    cpu=_q(cpu_nom),
                    memory=_q(mem_nom, unit=1024 ** 3),
                )
                for fi, (cpu_nom, mem_nom) in zip(chosen, draws)
            )
        else:
            fqs = tuple(
                FlavorQuotas.make(
                    f"flavor-{fi}",
                    cpu=cpu_nom,
                    memory=f"{mem_nom}Gi",
                )
                for fi, (cpu_nom, mem_nom) in zip(chosen, draws)
            )
        preemption = ClusterQueuePreemption(
            within_cluster_queue="LowerPriority",
            reclaim_within_cohort="Any")
        if no_preemption:
            # Steady-state shape: once the quotas saturate nothing can
            # move (no victim searches, no eviction churn), so every
            # subsequent tick is genuinely quiescent.
            preemption = ClusterQueuePreemption()
        if preemption_heavy:
            from kueue_tpu.api.types import BorrowWithinCohort
            preemption = ClusterQueuePreemption(
                within_cluster_queue="LowerPriority",
                reclaim_within_cohort="Any",
                borrow_within_cohort=BorrowWithinCohort(
                    policy="LowerPriority", max_priority_threshold=0))
        fair = None
        if fair_hierarchy:
            fair = FairSharing(weight=fair_weight)
        cq = ClusterQueue(
            name=f"cq-{c}",
            resource_groups=(ResourceGroup(("cpu", "memory"), fqs),),
            cohort=f"cohort-{c % num_cohorts}" if num_cohorts > 0
            else None,
            preemption=preemption,
            fair_sharing=fair,
            # StrictFIFO requeues NoFit losers straight back to the heap
            # (no parking lot), so every tick re-pops the same heads —
            # the steady-state/quiescent bench shape.
            **({"queueing_strategy": "StrictFIFO"} if strict_fifo else {}),
        )
        cqs.append(cq)
        cq_by_index[c] = cq
        lqs.append(LocalQueue(
            name=f"lq-{c}", namespace="default", cluster_queue=f"cq-{c}"))

    # Admitted background usage. Default shape fills `usage_fill` of each
    # CQ's first flavor with one workload; preemption_heavy fills EVERY
    # flavor with several small priority-0 workloads, so high-priority
    # arrivals can only start by preempting and minimalPreemptions has
    # granular victims to choose among (preemption.go:172-231).
    admitted: List[Workload] = []
    for c in kept:
        cq_flavors = cq_by_index[c].resource_groups[0].flavors
        fill_flavors = cq_flavors if preemption_heavy else cq_flavors[:1]
        chunks = 4 if preemption_heavy else 1
        for fq_obj in fill_flavors:
            cpu_quota = fq_obj.resources_dict["cpu"].nominal
            mem_quota = fq_obj.resources_dict["memory"].nominal
            cpu_target = int(cpu_quota * usage_fill) // chunks
            mem_target = int(mem_quota * usage_fill) // chunks
            if cpu_target <= 0:
                continue
            for k in range(chunks):
                wl = Workload(
                    name=f"adm-{c}-{fq_obj.name}-{k}", namespace="default",
                    queue_name=f"lq-{c}", creation_time=float(c),
                    pod_sets=[PodSet.make("main", count=1)])
                wl.admission = Admission(
                    cluster_queue=f"cq-{c}",
                    pod_set_assignments=[PodSetAssignment(
                        name="main",
                        flavors={"cpu": fq_obj.name, "memory": fq_obj.name},
                        resource_usage={"cpu": cpu_target,
                                        "memory": mem_target
                                        if preemption_heavy
                                        else cpu_target * (1024 ** 2)},
                        count=1)])
                wl.set_condition("QuotaReserved", True, now=float(c))
                wl.set_condition("Admitted", True, now=float(c))
                admitted.append(wl)

    kept_set = set(kept)
    pending: List[Workload] = []
    for i in range(num_pending):
        c = i % num_cqs
        n_podsets = rnd.randint(1, 2)
        topo_kw = {}
        if topology:
            topo_kw = ({"topology_required": "rack"} if i % 4 == 0
                       else {"topology_preferred": "rack"})
        # Draw-then-construct (the cq_filter draw contract): the random
        # stream advances identically whether or not this index is kept.
        specs = [(rnd.randint(1, 8), rnd.randint(1, 8),
                  rnd.randint(1, 16)) for _p in range(n_podsets)]
        priority = rnd.randint(*pending_priority)
        tputs = None
        if hetero:
            # Per-workload speedups on two random flavors (draw-then-
            # construct: the stream advances for filtered indices too).
            tputs = hetero_profile_draw(rnd, num_flavors)
        if c not in kept_set:
            continue
        pod_sets = [
            PodSet.make(
                f"ps{p}", count=count, cpu=cpu,
                memory=f"{mem}Gi", flavor_throughputs=tputs, **topo_kw)
            for p, (count, cpu, mem) in enumerate(specs)
        ]
        pending.append(Workload(
            name=f"pend-{i}", namespace="default", queue_name=f"lq-{c}",
            priority=priority, creation_time=float(i),
            pod_sets=pod_sets))
    return flavors, cqs, lqs, admitted, pending, cohort_specs


def synthetic_problem(
    num_cqs: int = 1000,
    num_cohorts: int = 100,
    num_flavors: int = 8,
    num_pending: int = 1000,
    usage_fill: float = 0.5,
    seed: int = 0,
    **object_kwargs,
) -> Tuple[Cache, List[WorkloadInfo]]:
    """Build a cache (with admitted usage) plus pending workloads.

    `num_pending` is the batch handed to the solver in one tick: the
    reference admits one head per ClusterQueue per cycle
    (manager.go:489-508), so a 1k-CQ cluster solves <=1k heads/tick
    regardless of the 50k-deep backlog.
    """
    flavors, cqs, lqs, admitted, pending, cohort_specs = synthetic_objects(
        num_cqs=num_cqs, num_cohorts=num_cohorts, num_flavors=num_flavors,
        num_pending=num_pending, usage_fill=usage_fill, seed=seed,
        **object_kwargs)
    cache = Cache()
    for rf in flavors:
        cache.add_or_update_resource_flavor(rf)
    for spec in cohort_specs:
        cache.add_or_update_cohort_spec(spec)
    for cq in cqs:
        cache.add_cluster_queue(cq)
    for lq in lqs:
        cache.add_local_queue(lq)
    for wl in admitted:
        cache.add_or_update_workload(wl)
    infos = [WorkloadInfo(wl, cluster_queue=wl.queue_name.replace("lq-", "cq-"))
             for wl in pending]
    return cache, infos


def synthetic_framework(
    num_cqs: int = 1000,
    num_cohorts: int = 100,
    num_flavors: int = 8,
    num_pending: int = 1000,
    usage_fill: float = 0.5,
    seed: int = 0,
    batch_solver=None,
    pending_priority: Tuple[int, int] = (-2, 2),
    preemption_heavy: bool = False,
    fair_hierarchy: bool = False,
    lending: bool = False,
    topology: bool = False,
    strict_fifo: bool = False,
    no_preemption: bool = False,
    hetero: bool = False,
    **framework_kwargs,
):
    """Build a full Framework loaded with the synthetic cluster — the
    end-to-end bench target: real queue manager, cache, scheduler, and
    reconcile passes, not just the solver kernel."""
    from kueue_tpu.controllers.runtime import Framework

    flavors, cqs, lqs, admitted, pending, cohort_specs = synthetic_objects(
        num_cqs=num_cqs, num_cohorts=num_cohorts, num_flavors=num_flavors,
        num_pending=num_pending, usage_fill=usage_fill, seed=seed,
        pending_priority=pending_priority, preemption_heavy=preemption_heavy,
        fair_hierarchy=fair_hierarchy, lending=lending, topology=topology,
        strict_fifo=strict_fifo, no_preemption=no_preemption,
        hetero=hetero)
    fw = Framework(batch_solver=batch_solver, **framework_kwargs)
    for rf in flavors:
        fw.create_resource_flavor(rf)
    for spec in cohort_specs:
        fw.create_cohort(spec)
    for cq in cqs:
        fw.create_cluster_queue(cq)
    for lq in lqs:
        fw.create_local_queue(lq)
    for wl in admitted:
        # Pre-admitted background load: straight into the cache, like the
        # reference rebuilding admitted state from the apiserver on startup
        # (cache.go:295-328).
        fw.workloads[wl.key] = wl
        fw.cache.add_or_update_workload(wl)
    for wl in pending:
        fw.submit(wl)
    return fw
