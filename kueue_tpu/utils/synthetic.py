"""Synthetic problem generator for benchmarks and compile checks.

Shapes follow the north-star scale target (BASELINE.md): up to 50k pending
Workloads x 1k ClusterQueues x 100 cohorts x 8 ResourceFlavors.
"""

from __future__ import annotations

import random
from typing import List, Tuple

from kueue_tpu.api.types import (
    Admission,
    ClusterQueue,
    ClusterQueuePreemption,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    PodSetAssignment,
    ResourceFlavor,
    ResourceGroup,
    Workload,
)
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.snapshot import Snapshot
from kueue_tpu.core.workload import WorkloadInfo


def synthetic_problem(
    num_cqs: int = 1000,
    num_cohorts: int = 100,
    num_flavors: int = 8,
    num_pending: int = 1000,
    usage_fill: float = 0.5,
    seed: int = 0,
) -> Tuple[Cache, List[WorkloadInfo]]:
    """Build a cache (with admitted usage) plus pending workloads.

    `num_pending` is the batch handed to the solver in one tick: the
    reference admits one head per ClusterQueue per cycle
    (manager.go:489-508), so a 1k-CQ cluster solves <=1k heads/tick
    regardless of the 50k-deep backlog.
    """
    rnd = random.Random(seed)
    cache = Cache()

    for f in range(num_flavors):
        cache.add_or_update_resource_flavor(ResourceFlavor.make(f"flavor-{f}"))

    for c in range(num_cqs):
        n_flavors = rnd.randint(2, min(4, num_flavors))
        chosen = rnd.sample(range(num_flavors), n_flavors)
        fqs = tuple(
            FlavorQuotas.make(
                f"flavor-{fi}",
                cpu=rnd.randint(16, 128),
                memory=f"{rnd.randint(64, 512)}Gi",
            )
            for fi in chosen
        )
        cache.add_cluster_queue(ClusterQueue(
            name=f"cq-{c}",
            resource_groups=(ResourceGroup(("cpu", "memory"), fqs),),
            cohort=f"cohort-{c % num_cohorts}",
            preemption=ClusterQueuePreemption(
                within_cluster_queue="LowerPriority",
                reclaim_within_cohort="Any"),
        ))
        cache.add_local_queue(LocalQueue(
            name=f"lq-{c}", namespace="default", cluster_queue=f"cq-{c}"))

    # Admitted usage: fill roughly `usage_fill` of each CQ's first flavor.
    for c in range(num_cqs):
        cq = cache.cluster_queues[f"cq-{c}"]
        fq0 = cq.resource_groups[0].flavors[0]
        quota = fq0.resources_dict["cpu"].nominal
        target = int(quota * usage_fill)
        if target <= 0:
            continue
        wl = Workload(
            name=f"adm-{c}", namespace="default", queue_name=f"lq-{c}",
            creation_time=float(c),
            pod_sets=[PodSet.make("main", count=1)])
        wl.admission = Admission(
            cluster_queue=f"cq-{c}",
            pod_set_assignments=[PodSetAssignment(
                name="main",
                flavors={"cpu": fq0.name, "memory": fq0.name},
                resource_usage={"cpu": target,
                                "memory": target * (1024 ** 2)},
                count=1)])
        wl.set_condition("QuotaReserved", True, now=float(c))
        wl.set_condition("Admitted", True, now=float(c))
        cache.add_or_update_workload(wl)

    pending: List[WorkloadInfo] = []
    for i in range(num_pending):
        c = i % num_cqs
        n_podsets = rnd.randint(1, 2)
        pod_sets = [
            PodSet.make(
                f"ps{p}", count=rnd.randint(1, 8),
                cpu=rnd.randint(1, 8),
                memory=f"{rnd.randint(1, 16)}Gi")
            for p in range(n_podsets)
        ]
        wl = Workload(
            name=f"pend-{i}", namespace="default", queue_name=f"lq-{c}",
            priority=rnd.randint(-2, 2), creation_time=float(i),
            pod_sets=pod_sets)
        pending.append(WorkloadInfo(wl, cluster_queue=f"cq-{c}"))
    return cache, pending
