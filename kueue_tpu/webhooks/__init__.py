"""Admission webhooks: defaulting + validation for every API object kind.

Counterpart of reference pkg/webhooks/ — in the reference these run as
apiserver admission webhooks; here they are pure functions invoked by the
runtime (and any API front end) before an object write is accepted.

`validate_*` functions return a list of human-readable error strings
(field-path prefixed, like field.ErrorList); empty list == valid.
`default_*` functions mutate the object in place and return it.
"""

from kueue_tpu.webhooks.defaulting import (
    default_cluster_queue,
    default_workload,
)
from kueue_tpu.webhooks.validation import (
    ValidationError,
    validate_admission_check,
    validate_admission_check_update,
    validate_cluster_queue,
    validate_cluster_queue_update,
    validate_cohort,
    validate_local_queue,
    validate_local_queue_update,
    validate_resource_flavor,
    validate_workload,
    validate_workload_update,
)

__all__ = [
    "ValidationError",
    "default_cluster_queue",
    "default_workload",
    "validate_admission_check",
    "validate_admission_check_update",
    "validate_cluster_queue",
    "validate_cluster_queue_update",
    "validate_cohort",
    "validate_local_queue",
    "validate_local_queue_update",
    "validate_resource_flavor",
    "validate_workload",
    "validate_workload_update",
]
