"""Defaulting webhooks (reference: pkg/webhooks/*_webhook.go Default()).

Our dataclasses already carry most defaults in their field initializers;
these functions cover the data-dependent cases.
"""

from __future__ import annotations

from kueue_tpu import features
from kueue_tpu.api.types import ClusterQueue, Workload

DEFAULT_POD_SET_NAME = "main"


def default_workload(wl: Workload) -> Workload:
    """workload_webhook.go:58-81: name a lone unnamed podset "main"; drop
    minCount when PartialAdmission is gated off."""
    if len(wl.pod_sets) == 1 and not wl.pod_sets[0].name:
        wl.pod_sets[0].name = DEFAULT_POD_SET_NAME
    if not features.enabled(features.PARTIAL_ADMISSION):
        for ps in wl.pod_sets:
            ps.min_count = None
    return wl


def default_cluster_queue(cq: ClusterQueue) -> ClusterQueue:
    """clusterqueue_webhook.go:60-85. Preemption / borrowWithinCohort /
    flavorFungibility defaults are carried by the dataclass field defaults
    (api/types.py); nothing data-dependent remains, but the hook exists so
    an API front end has a single defaulting entry point per kind."""
    return cq
