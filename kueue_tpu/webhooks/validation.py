"""Validation rules for API objects (reference: pkg/webhooks/*_webhook.go).

Each rule mirrors the reference's semantics; returns are lists of
"field.path: message" strings so callers can surface all violations at once.
"""

from __future__ import annotations

import re
from typing import List, Optional

from kueue_tpu.api.types import (
    AdmissionCheck,
    BorrowWithinCohortPolicy,
    ClusterQueue,
    LocalQueue,
    PreemptionPolicy,
    QueueingStrategy,
    ResourceFlavor,
    Workload,
)


PODS_RESOURCE = "pods"


class ValidationError(ValueError):
    """Raised by the runtime when a webhook rejects an object."""

    def __init__(self, errs: List[str]):
        super().__init__("; ".join(errs))
        self.errors = errs


_DNS1123_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_QUALIFIED_NAME = re.compile(
    r"^([a-z0-9A-Z]([-a-z0-9A-Z_.]*[a-z0-9A-Z])?/)?"
    r"[a-z0-9A-Z]([-a-z0-9A-Z_.]*[a-z0-9A-Z])?$")

_LABEL_VALUE = re.compile(r"^[a-z0-9A-Z]([-a-z0-9A-Z_.]*[a-z0-9A-Z])?$")

_TAINT_EFFECTS = ("NoSchedule", "PreferNoSchedule", "NoExecute")
_PREEMPTION_POLICIES = (
    PreemptionPolicy.NEVER, PreemptionPolicy.LOWER_PRIORITY,
    PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY, PreemptionPolicy.ANY)


def is_dns1123_label(value: str) -> bool:
    return len(value) <= 63 and bool(_DNS1123_LABEL.match(value))


def is_dns1123_subdomain(value: str) -> bool:
    return (len(value) <= 253
            and all(is_dns1123_label(part) for part in value.split(".")))


def _name_reference(name: str, path: str) -> List[str]:
    if not is_dns1123_subdomain(name):
        return [f"{path}: {name!r} must be a DNS-1123 subdomain"]
    return []


# ---------------------------------------------------------------------------
# ClusterQueue (clusterqueue_webhook.go:116-236)
# ---------------------------------------------------------------------------


def validate_cluster_queue(cq: ClusterQueue) -> List[str]:
    errs: List[str] = []
    if cq.cohort:
        errs += _name_reference(cq.cohort, "spec.cohort")
    if cq.queueing_strategy not in (
            QueueingStrategy.STRICT_FIFO, QueueingStrategy.BEST_EFFORT_FIFO):
        errs.append(f"spec.queueingStrategy: unknown {cq.queueing_strategy!r}")
    errs += _validate_namespace_selector(cq)
    errs += _validate_resource_groups(cq)
    errs += _validate_preemption(cq)
    return errs


def _validate_namespace_selector(cq: ClusterQueue) -> List[str]:
    """metav1.LabelSelector validation (clusterqueue_webhook.go validates
    spec.namespaceSelector through apimachinery's selector rules): label
    keys must be qualified names, values label-values, and In/NotIn
    expressions need at least one value."""
    errs: List[str] = []
    sel = cq.namespace_selector
    for k, v in sel.match_labels:
        if not _QUALIFIED_NAME.match(k):
            errs.append(
                f"spec.namespaceSelector.matchLabels: invalid key {k!r}")
        if v and not _LABEL_VALUE.match(v):
            errs.append(
                f"spec.namespaceSelector.matchLabels: invalid value {v!r}")
    for i, e in enumerate(sel.match_expressions):
        path = f"spec.namespaceSelector.matchExpressions[{i}]"
        if e.key != "__none__" and not _QUALIFIED_NAME.match(e.key):
            errs.append(f"{path}.key: invalid key {e.key!r}")
        if e.operator in ("In", "NotIn") and not e.values:
            errs.append(f"{path}.values: must be specified when operator is "
                        f"{e.operator}")
    return errs


def _validate_preemption(cq: ClusterQueue) -> List[str]:
    errs: List[str] = []
    p = cq.preemption
    if (p.reclaim_within_cohort == PreemptionPolicy.NEVER
            and p.borrow_within_cohort is not None
            and p.borrow_within_cohort.policy != BorrowWithinCohortPolicy.NEVER):
        errs.append("spec.preemption: reclaimWithinCohort=Never and "
                    "borrowWithinCohort.Policy!=Never")
    for fld, val in (("withinClusterQueue", p.within_cluster_queue),
                     ("reclaimWithinCohort", p.reclaim_within_cohort)):
        if val not in _PREEMPTION_POLICIES:
            errs.append(f"spec.preemption.{fld}: unknown policy {val!r}")
    return errs


# kubebuilder MaxItems on spec.resourceGroups (clusterqueue_types.go).
_MAX_RESOURCE_GROUPS = 16


def _validate_resource_groups(cq: ClusterQueue) -> List[str]:
    return _resource_group_structure(
        cq.resource_groups, in_cohort=bool(cq.cohort),
        no_parent_msg="when cohort is empty")


def _resource_group_structure(resource_groups, in_cohort: bool,
                              no_parent_msg: str,
                              lending_within_nominal: bool = True
                              ) -> List[str]:
    """Shared structural rules for ClusterQueue and Cohort resource groups
    (clusterqueue_webhook.go:116-236; cohorts reuse the same rule set):
    group cap, unique resources/flavors, quotas matching coveredResources
    in order, and borrowing/lending limits only where there is somewhere
    to borrow from / lend to."""
    errs: List[str] = []
    if len(resource_groups) > _MAX_RESOURCE_GROUPS:
        errs.append(f"spec.resourceGroups: must have at most "
                    f"{_MAX_RESOURCE_GROUPS} groups, got "
                    f"{len(resource_groups)}")
    seen_resources: set = set()
    seen_flavors: set = set()
    for gi, rg in enumerate(resource_groups):
        path = f"spec.resourceGroups[{gi}]"
        for res in rg.covered_resources:
            if not _QUALIFIED_NAME.match(res):
                errs.append(f"{path}.coveredResources: invalid name {res!r}")
            if res in seen_resources:
                errs.append(f"{path}.coveredResources: duplicate {res!r}")
            seen_resources.add(res)
        for fi, fq in enumerate(rg.flavors):
            fpath = f"{path}.flavors[{fi}]"
            if fq.name in seen_flavors:
                errs.append(f"{fpath}.name: duplicate flavor {fq.name!r}")
            seen_flavors.add(fq.name)
            errs += _name_reference(fq.name, f"{fpath}.name")
            # Quotas must cover exactly the covered resources, in order
            # (clusterqueue_webhook.go:182-195).
            quota_names = tuple(r for r, _ in fq.resources)
            if quota_names != tuple(rg.covered_resources):
                errs.append(f"{fpath}.resources: must match coveredResources "
                            f"{list(rg.covered_resources)}")
            for rname, quota in fq.resources:
                qpath = f"{fpath}.resources[{rname}]"
                if quota.nominal < 0:
                    errs.append(f"{qpath}.nominalQuota: must be >= 0")
                if quota.borrowing_limit is not None:
                    if quota.borrowing_limit < 0:
                        errs.append(f"{qpath}.borrowingLimit: must be >= 0")
                    if not in_cohort:
                        errs.append(f"{qpath}.borrowingLimit: must be empty "
                                    f"{no_parent_msg}")
                if quota.lending_limit is not None:
                    if quota.lending_limit < 0:
                        errs.append(f"{qpath}.lendingLimit: must be >= 0")
                    if not in_cohort:
                        errs.append(f"{qpath}.lendingLimit: must be empty "
                                    f"{no_parent_msg}")
                    elif lending_within_nominal \
                            and quota.lending_limit > quota.nominal:
                        errs.append(f"{qpath}.lendingLimit: must be <= "
                                    "nominalQuota")
    return errs


def validate_cluster_queue_update(new: ClusterQueue,
                                  old: ClusterQueue) -> List[str]:
    errs = validate_cluster_queue(new)
    if new.queueing_strategy != old.queueing_strategy:
        errs.append("spec.queueingStrategy: field is immutable")
    return errs


# ---------------------------------------------------------------------------
# Workload (workload_webhook.go:108-390)
# ---------------------------------------------------------------------------


def validate_cohort(spec) -> List[str]:
    """Hierarchical-cohort spec (KEP-79): DNS names, parent != self, the
    same structural resource-group rules as ClusterQueues (group cap,
    unique flavors/resources, quotas matching coveredResources), and no
    borrowing/lending limits on a root cohort — a cohort without a parent
    has nobody to borrow from or lend to."""
    errs = _name_reference(spec.name, "metadata.name")
    if spec.parent:
        errs += _name_reference(spec.parent, "spec.parent")
        if spec.parent == spec.name:
            errs.append("spec.parent: a Cohort cannot be its own parent")
    # A cohort's lendingLimit caps the whole subtree's outflow (which can
    # exceed the cohort's own nominal quota), so <= nominal is a
    # ClusterQueue-only rule.
    errs += _resource_group_structure(
        spec.resource_groups, in_cohort=bool(spec.parent),
        no_parent_msg="on a root Cohort (no parent)",
        lending_within_nominal=False)
    return errs


def validate_workload(wl: Workload) -> List[str]:
    errs: List[str] = []
    variable_count = 0
    names = set()
    # 1..8 podSets (workload_types.go PodSets kubebuilder MinItems/MaxItems).
    if not 1 <= len(wl.pod_sets) <= 8:
        errs.append("spec.podSets: must contain between 1 and 8 podSets, "
                    f"got {len(wl.pod_sets)}")
    for i, ps in enumerate(wl.pod_sets):
        path = f"spec.podSets[{i}]"
        if not is_dns1123_label(ps.name):
            errs.append(f"{path}.name: {ps.name!r} must be a DNS-1123 label")
        if ps.name in names:
            errs.append(f"{path}.name: duplicate podset {ps.name!r}")
        names.add(ps.name)
        if ps.count < 1:
            errs.append(f"{path}.count: must be >= 1")
        if ps.min_count is not None:
            variable_count += 1
            if not 0 < ps.min_count <= ps.count:
                errs.append(f"{path}.minCount: must be in [1, count]")
        if PODS_RESOURCE in ps.requests:
            # The pods resource is implicit (one per pod); requesting it
            # explicitly is rejected (workload_webhook.go container
            # requests rule).
            errs.append(f"{path}.requests: must not contain the "
                        f"{PODS_RESOURCE!r} resource")
        if ps.topology_required is not None \
                and ps.topology_preferred is not None:
            errs.append(f"{path}.topologyRequest: required and preferred "
                        "are mutually exclusive")
        for fld, val in (("required", ps.topology_required),
                         ("preferred", ps.topology_preferred)):
            if val is not None and (not val or not _QUALIFIED_NAME.match(val)):
                errs.append(f"{path}.topologyRequest.{fld}: invalid level "
                            f"name {val!r}")
        errs += _validate_flavor_throughputs(ps, path)
    if variable_count > 1:
        errs.append("spec.podSets: at most one podSet can use minCount")
    if wl.priority_class:
        errs += _name_reference(wl.priority_class, "spec.priorityClassName")
    if wl.queue_name:
        errs += _name_reference(wl.queue_name, "spec.queueName")
    errs += _validate_reclaimable(wl)
    errs += _validate_pod_set_updates(wl)
    if wl.has_quota_reservation and wl.admission is None:
        errs.append("status.admission: must be set when QuotaReserved")
    if wl.admission is not None:
        errs += _name_reference(wl.admission.cluster_queue,
                                "status.admission.clusterQueue")
        psa_names = [a.name for a in wl.admission.pod_set_assignments]
        if sorted(psa_names) != sorted(ps.name for ps in wl.pod_sets):
            errs.append("status.admission.podSetAssignments: must have "
                        "assignments for all podsets")
        for ai, psa in enumerate(wl.admission.pod_set_assignments):
            for rname, v in psa.resource_usage.items():
                # Per-pod value must be integral (workload_webhook.go
                # resourceUsage divisibility by the assigned count).
                if psa.count and v % psa.count:
                    errs.append(
                        f"status.admission.podSetAssignments[{ai}]"
                        f".resourceUsage[{rname}]: {v} is not divisible by "
                        f"the assigned count {psa.count}")
    return errs


def _validate_pod_set_updates(wl: Workload) -> List[str]:
    """AdmissionCheckState.podSetUpdates rules (workload_webhook.go
    validateAdmissionChecks): empty is fine; otherwise one update per
    podSet, names drawn from the podSets, and label/annotation/
    nodeSelector maps carrying valid keys and values."""
    errs: List[str] = []
    ps_names = {ps.name for ps in wl.pod_sets}
    for check_name, state in sorted(wl.admission_check_states.items()):
        updates = state.pod_set_updates
        if not updates:
            continue
        base = f"status.admissionChecks[{check_name}].podSetUpdates"
        if len(updates) != len(wl.pod_sets):
            errs.append(f"{base}: must have the same number of podSetUpdates "
                        "as the podSets")
        for ui, upd in enumerate(updates):
            upath = f"{base}[{ui}]"
            name = upd.get("name", "")
            if name not in ps_names:
                errs.append(f"{upath}.name: no podSet named {name!r}")
            for fld in ("labels", "nodeSelector"):
                for k, v in (upd.get(fld) or {}).items():
                    if not _QUALIFIED_NAME.match(k):
                        errs.append(f"{upath}.{fld}: invalid key {k!r}")
                    elif fld == "labels" and v and not _LABEL_VALUE.match(v):
                        errs.append(f"{upath}.{fld}: invalid value {v!r}")
            for k in (upd.get("annotations") or {}):
                if not _QUALIFIED_NAME.match(k):
                    errs.append(f"{upath}.annotations: invalid key {k!r}")
    return errs


def _validate_reclaimable(wl: Workload) -> List[str]:
    errs = []
    by_name = {ps.name: ps for ps in wl.pod_sets}
    for name, count in wl.reclaimable_pods.items():
        ps = by_name.get(name)
        if ps is None:
            errs.append(f"status.reclaimablePods[{name}]: no such podset")
        elif not 0 <= count <= ps.count:
            errs.append(f"status.reclaimablePods[{name}].count: must be in "
                        f"[0, {ps.count}]")
    return errs


def validate_workload_update(new: Workload, old: Workload) -> List[str]:
    errs = validate_workload(new)
    if old.has_quota_reservation:
        if [_podset_sig(ps) for ps in new.pod_sets] != \
                [_podset_sig(ps) for ps in old.pod_sets]:
            errs.append("spec.podSets: field is immutable after quota "
                        "reservation")
        if new.priority_class != old.priority_class:
            errs.append("spec.priorityClassName: field is immutable after "
                        "quota reservation")
        if new.priority_class_source != old.priority_class_source:
            errs.append("spec.priorityClassSource: field is immutable after "
                        "quota reservation")
    # podSetUpdates freeze once their check reports Ready
    # (workload_webhook.go validateAdmissionChecksUpdate).
    for check_name, old_state in old.admission_check_states.items():
        if old_state.state != "Ready":
            continue
        new_state = new.admission_check_states.get(check_name)
        if new_state is not None \
                and new_state.pod_set_updates != old_state.pod_set_updates:
            errs.append(f"status.admissionChecks[{check_name}]"
                        ".podSetUpdates: field is immutable once the check "
                        "is Ready")
    if new.has_quota_reservation and old.has_quota_reservation:
        if new.queue_name != old.queue_name:
            errs.append("spec.queueName: field is immutable while quota is "
                        "reserved")
        # Reclaimable counts can only grow while admitted
        # (workload_webhook.go:375-390).
        for name, old_count in old.reclaimable_pods.items():
            if new.reclaimable_pods.get(name, 0) < old_count:
                errs.append(f"status.reclaimablePods[{name}].count: cannot "
                            f"be less than {old_count}")
    if (new.admission is not None and old.admission is not None
            and new.admission != old.admission):
        errs.append("status.admission: field is immutable once set")
    return errs


def _podset_sig(ps) -> tuple:
    return (ps.name, ps.count, tuple(sorted(ps.requests.items())),
            ps.min_count)


# ---------------------------------------------------------------------------
# LocalQueue / ResourceFlavor / AdmissionCheck
# ---------------------------------------------------------------------------


def validate_local_queue(lq: LocalQueue) -> List[str]:
    return _name_reference(lq.cluster_queue, "spec.clusterQueue")


def validate_local_queue_update(new: LocalQueue, old: LocalQueue) -> List[str]:
    errs = validate_local_queue(new)
    if new.cluster_queue != old.cluster_queue:
        errs.append("spec.clusterQueue: field is immutable")
    return errs


def _validate_flavor_throughputs(ps, path: str) -> List[str]:
    """Heterogeneity-aware scheduling hardening: throughput values must
    be finite and non-negative (a NaN/inf would poison every dense-score
    comparison in the hetero solve; a negative value is meaningless),
    and flavor references must be syntactically valid ResourceFlavor
    names. This is a SYNTAX check — the webhook has no flavor list; a
    well-formed name that matches no live flavor falls back to that
    flavor's speed-class default at scoring time (documented in
    hetero/profile.workload_throughputs)."""
    import math
    errs: List[str] = []
    for fname, val in ps.flavor_throughputs:
        fpath = f"{path}.flavorThroughputs[{fname}]"
        if not is_dns1123_subdomain(fname):
            errs.append(f"{fpath}: invalid flavor reference — {fname!r} "
                        "is not a valid ResourceFlavor name")
        if not isinstance(val, (int, float)) or math.isnan(val) \
                or math.isinf(val) or val < 0:
            errs.append(f"{fpath}: throughput must be a finite "
                        f"non-negative number, got {val!r}")
    return errs


def validate_resource_flavor(rf: ResourceFlavor) -> List[str]:
    errs: List[str] = []
    import math
    sc = rf.speed_class
    if not isinstance(sc, (int, float)) or math.isnan(sc) \
            or math.isinf(sc) or sc <= 0:
        errs.append("spec.speedClass: must be a finite positive number, "
                    f"got {sc!r}")
    for k, v in rf.node_labels:
        if not _QUALIFIED_NAME.match(k):
            errs.append(f"spec.nodeLabels: invalid key {k!r}")
    for i, taint in enumerate(rf.node_taints):
        path = f"spec.nodeTaints[{i}]"
        if not taint.key or not _QUALIFIED_NAME.match(taint.key):
            errs.append(f"{path}.key: invalid or empty")
        if taint.effect not in _TAINT_EFFECTS:
            errs.append(f"{path}.effect: must be one of "
                        f"{list(_TAINT_EFFECTS)}")
    errs += _validate_topology_spec(rf)
    return errs


# kubebuilder-style caps on the topology tree (keeps the dense encoding's
# padded tensors bounded: levels x leaves per flavor).
_MAX_TOPOLOGY_LEVELS = 8
_MAX_TOPOLOGY_LEAVES = 4096


def _validate_topology_spec(rf: ResourceFlavor) -> List[str]:
    """TopologySpec structural rules: named unique levels, every leaf path
    exactly one value per level, positive capacities, unique leaf paths."""
    spec = rf.topology
    if spec is None:
        return []
    errs: List[str] = []
    if not spec.levels:
        errs.append("spec.topologySpec.levels: must name at least one level")
    if len(spec.levels) > _MAX_TOPOLOGY_LEVELS:
        errs.append(f"spec.topologySpec.levels: at most "
                    f"{_MAX_TOPOLOGY_LEVELS} levels")
    seen_levels = set()
    for level in spec.levels:
        if not level or not _QUALIFIED_NAME.match(level):
            errs.append(f"spec.topologySpec.levels: invalid level {level!r}")
        if level in seen_levels:
            errs.append(f"spec.topologySpec.levels: duplicate {level!r}")
        seen_levels.add(level)
    if not spec.leaves:
        errs.append("spec.topologySpec.leaves: must enumerate at least one "
                    "leaf domain")
    if len(spec.leaves) > _MAX_TOPOLOGY_LEAVES:
        errs.append(f"spec.topologySpec.leaves: at most "
                    f"{_MAX_TOPOLOGY_LEAVES} leaves")
    seen_paths = set()
    for i, leaf in enumerate(spec.leaves):
        path = f"spec.topologySpec.leaves[{i}]"
        if len(leaf.path) != len(spec.levels):
            errs.append(f"{path}.path: must have one value per level "
                        f"({len(spec.levels)}), got {len(leaf.path)}")
        if leaf.capacity < 1:
            errs.append(f"{path}.capacity: must be >= 1")
        if leaf.path in seen_paths:
            errs.append(f"{path}.path: duplicate leaf {'/'.join(leaf.path)!r}")
        seen_paths.add(leaf.path)
    return errs


def validate_admission_check(ac: AdmissionCheck) -> List[str]:
    errs: List[str] = []
    if not ac.controller_name:
        errs.append("spec.controllerName: must not be empty")
    if ac.parameters is not None:
        api_group, kind, name = ac.parameters
        if not kind:
            errs.append("spec.parameters.kind: must not be empty")
        if not name or not is_dns1123_subdomain(name):
            errs.append("spec.parameters.name: invalid")
    return errs


def validate_admission_check_update(new: AdmissionCheck,
                                    old: AdmissionCheck) -> List[str]:
    errs = validate_admission_check(new)
    if new.controller_name != old.controller_name:
        errs.append("spec.controllerName: field is immutable")
    return errs
