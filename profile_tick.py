"""Ad-hoc profiling of the e2e tick at north-star shape (not shipped)."""
import cProfile
import io
import os
import pstats
import random
import sys
import time
from collections import deque

import numpy as np

sys.argv = [sys.argv[0]]

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # The remote-attachment plugin ignores the env var alone; pin the
    # backend through jax.config before any array op (see bench.py).
    import jax

    jax.config.update("jax_platforms", "cpu")

from kueue_tpu.models.flavor_fit import BatchSolver
from kueue_tpu.api.types import PodSet, Workload
from kueue_tpu.utils.synthetic import synthetic_framework
from kueue_tpu.metrics import REGISTRY

TICKS = int(os.environ.get("TICKS", "20"))
PREEMPT = os.environ.get("PREEMPT") == "1"
FAIR = os.environ.get("FAIR") == "1"
if FAIR:
    from kueue_tpu import features
    features.set_enabled(features.FAIR_SHARING, True)

t0 = time.perf_counter()
fw = synthetic_framework(
    num_cqs=1000, num_cohorts=100, num_flavors=8,
    num_pending=50_000, usage_fill=0.9 if PREEMPT else 0.7, seed=42,
    preemption_heavy=PREEMPT, fair_hierarchy=FAIR,
    batch_solver=BatchSolver(),
    pipeline_depth=int(os.environ.get("DEPTH", "8")))
print(f"setup {time.perf_counter()-t0:.1f}s", file=sys.stderr)

admitted_log = deque()
tick_no = [0]
orig_apply = fw.scheduler.apply_admission


def apply_admission(wl):
    ok = orig_apply(wl)
    if ok:
        admitted_log.append((tick_no[0], wl))
    return ok


fw.scheduler.apply_admission = apply_admission
rnd = random.Random(43)
submit_seq = [0]


def submit_replacement():
    submit_seq[0] += 1
    i = submit_seq[0]
    c = rnd.randrange(1000)
    if PREEMPT:
        priority = rnd.randint(1, 5) if i % 2 else rnd.randint(-2, 0)
    else:
        priority = rnd.randint(-2, 2)
    fw.submit(Workload(
        name=f"churn-{i}", namespace="default",
        queue_name=f"lq-{c}", priority=priority,
        creation_time=float(100_000 + i),
        pod_sets=[PodSet.make(
            "ps0", count=rnd.randint(1, 8), cpu=rnd.randint(1, 8),
            memory=f"{rnd.randint(1, 16)}Gi")]))


def churn():
    while admitted_log and admitted_log[0][0] <= tick_no[0] - 5:
        _, wl = admitted_log.popleft()
        if wl.is_admitted and not wl.is_finished:
            fw.finish(wl)
            fw.delete_workload(wl)
            submit_replacement()


for _ in range(14):
    tick_no[0] += 1
    fw.tick()
    churn()

import gc
gc.collect()
gc.freeze()
if os.environ.get("GCOFF") == "1":
    gc.disable()
else:
    g0 = int(os.environ.get("GC0", "200000"))
    g1 = int(os.environ.get("GC1", "100"))
    g2 = int(os.environ.get("GC2", "100"))
    gc.set_threshold(g0, g1, g2)

# Reset phase histograms after warmup.
phases = REGISTRY.tick_phase_seconds
phases.counts.clear()
phases.sums.clear()
phases.totals.clear()

PROFILE = os.environ.get("PROFILE") == "1"
TICK_ONLY = os.environ.get("TICK_ONLY") == "1"
pr = cProfile.Profile()
times = []
if PROFILE and not TICK_ONLY:
    pr.enable()
phase_rows = []
cpu_times = []
for _ in range(TICKS):
    tick_no[0] += 1
    before = dict(phases.sums)
    if PROFILE and TICK_ONLY:
        pr.enable()
    t = time.perf_counter()
    tc = time.process_time()
    fw.tick()
    cpu_times.append(time.process_time() - tc)
    times.append(time.perf_counter() - t)
    if PROFILE and TICK_ONLY:
        pr.disable()
    phase_rows.append({k[0]: phases.sums[k] - before.get(k, 0.0)
                       for k in phases.sums})
    churn()
if PROFILE and not TICK_ONLY:
    pr.disable()

times_ms = np.array(times) * 1000
cpu_ms = np.array(cpu_times) * 1000
print(f"p50 {np.percentile(times_ms,50):.1f}ms p99 {np.percentile(times_ms,99):.1f}ms mean {times_ms.mean():.1f}ms "
      f"| cpu p50 {np.percentile(cpu_ms,50):.1f}ms mean {cpu_ms.mean():.1f}ms", file=sys.stderr)

print("phase sums over run (s) / count / mean ms:", file=sys.stderr)
for key in sorted(phases.sums):
    s_, n_ = phases.sums[key], phases.totals[key]
    print(f"  {key}: {s_:.3f}s  n={n_}  mean={1000*s_/max(n_,1):.1f}ms",
          file=sys.stderr)

print("per-tick ms:", " ".join(f"{t*1000:.0f}" for t in times),
      file=sys.stderr)
if os.environ.get("GCOFF") == "1":
    import resource
    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    collected = gc.collect()
    print(f"end-of-run gc.collect(): {collected} cyclic objects; "
          f"peak RSS {rss/1e6:.0f}MB", file=sys.stderr)
names = sorted(phase_rows[0])
print("tick  " + "  ".join(f"{n[:8]:>8}" for n in names), file=sys.stderr)
for i, row in enumerate(phase_rows):
    if i < 6 or i >= len(phase_rows) - 6:
        print(f"{i:4d}  " + "  ".join(f"{1000*row.get(n,0):8.1f}" for n in names),
              file=sys.stderr)
m = fw.scheduler.metrics
print(f"admitted={m.admitted} skipped={m.skipped} "
      f"inadmissible={m.inadmissible} preempted={m.preempted}",
      file=sys.stderr)
qm = fw.queues
try:
    heaps = sum(len(cq.heap) for cq in qm.cluster_queues.values())
    parked = sum(len(cq.inadmissible) for cq in qm.cluster_queues.values())
    print(f"heap total={heaps} parked={parked}", file=sys.stderr)
except Exception as e:
    print("introspect fail:", e,
          {k: type(v).__name__ for k, v in vars(qm).items()}, file=sys.stderr)
if PROFILE:
    pr.dump_stats("/tmp/tick.prof")
    s = io.StringIO()
    ps = pstats.Stats(pr, stream=s).sort_stats("cumulative")
    ps.print_stats(45)
    print(s.getvalue()[:7000])
