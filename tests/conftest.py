import os

# Solver tests run on a virtual 8-device CPU mesh; must be set before the
# backend initializes. Env vars alone are not enough here: the image's
# sitecustomize force-registers a TPU platform, so pin the platform through
# jax.config as well.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import pytest

from kueue_tpu import features


@pytest.fixture(autouse=True)
def reset_features():
    features.reset()
    yield
    features.reset()
