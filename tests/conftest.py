import os

# Solver tests run on a virtual 8-device CPU mesh; must be set before jax import.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import pytest

from kueue_tpu import features


@pytest.fixture(autouse=True)
def reset_features():
    features.reset()
    yield
    features.reset()
