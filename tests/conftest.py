import os

# Solver tests run on a virtual 8-device CPU mesh; must be set before the
# backend initializes. Env vars alone are not enough here: the image's
# sitecustomize force-registers a TPU platform, so pin the platform through
# jax.config as well.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import pytest

from kueue_tpu import features
from kueue_tpu.solver.schema import UsageEncoder

# Every refresh in the test suite cross-checks the incremental usage
# tensor against a from-scratch encode (cheap at test scale; would defeat
# the encoder's purpose in production).
UsageEncoder.debug_verify = True


@pytest.fixture(autouse=True)
def reset_features():
    features.reset()
    yield
    features.reset()
