# Bad fixture: API-hygiene violations (API01/API02).
from dataclasses import dataclass
from typing import Optional


def enqueue(item, batch=[]):  # API01: mutable default argument
    batch.append(item)
    return batch


def configure(name, opts={}):  # API01: mutable default argument
    opts.setdefault("retries", 3)
    return name, opts


@dataclass
class FlavorRef:  # API02: all fields immutable-typed, should be frozen
    name: str
    resource: str
    weight: float = 1.0
    parent: Optional[str] = None
