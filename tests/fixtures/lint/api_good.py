# Good fixture: API-hygiene counterparts — zero findings.
from dataclasses import dataclass, field
from typing import Dict, List, Optional


def enqueue(item, batch: Optional[List] = None):
    batch = [] if batch is None else batch
    batch.append(item)
    return batch


@dataclass(frozen=True)
class FlavorRef:
    name: str
    resource: str
    weight: float = 1.0
    parent: Optional[str] = None


@dataclass
class MutableStatus:  # fine: carries mutable state, not freezable
    counts: Dict[str, int] = field(default_factory=dict)
