# Fixture: determinism-engine violations (DET01 unordered iteration,
# DET02 wall-clock/randomness into decision state) — each marked line
# is pinned by tests/test_det_taint.py. The disciplined twin is
# det_good.py.
import os
import random
import time
from typing import Dict, List, Optional, Set


class Workload:
    def __init__(self, name: str, priority: int):
        self.name = name
        self.priority = priority


class Condition:
    def __init__(self, kind: str, stamp: float):
        self.kind = kind
        self.stamp = stamp


class Cohort:
    def __init__(self):
        self.members: Set[Workload] = set()
        self.by_workload: Dict[Workload, int] = {}
        self.children: List["Cohort"] = []

    def victim_walk(self) -> List[Workload]:
        # DET01: the PR 8 revert shape — an identity-hashed set
        # materialized into an arbitrarily-ordered list that escapes.
        return list(self.members)                        # line 32: DET01

    def first_member(self) -> Workload:
        # DET01: next(iter(set)) picks whichever element hashes first.
        return next(iter(self.members))                  # line 36: DET01

    def collect(self) -> List[str]:
        out: List[str] = []
        # DET01: order-sensitive loop body (append) over the raw set.
        for wl in self.members:                          # line 41: DET01
            out.append(wl.name)
        return out

    def usage_rows(self) -> List[int]:
        # DET01: list comprehension over an object-keyed dict's values.
        return [v for v in self.by_workload.values()]    # line 47: DET01

    def stamp_admission(self, wl: Workload) -> Condition:
        # DET02: the PR 9 shape — wall clock into a decision record.
        return Condition("Admitted", time.time())        # line 51: DET02

    def tiebreak(self, wls: List[Workload]) -> List[Workload]:
        # DET02: randomness inside a sort key.
        return sorted(wls, key=lambda w: random.random())  # line 55: DET02


def spill_listing(root: str) -> List[str]:
    # DET01: readdir order is filesystem-arbitrary; returning it raw
    # makes the caller's walk nondeterministic across hosts.
    return os.listdir(root)                              # line 61: DET01


def stamp_via_local(wl: Workload) -> Condition:
    # DET02: taint through a local assignment still reaches the
    # constructor — the finding carries the full source->sink path.
    now = time.monotonic()
    elapsed = now + 5.0
    return Condition("Requeued", elapsed)                # line 69: DET02
