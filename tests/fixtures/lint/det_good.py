# Fixture: the disciplined twin of det_bad.py — every unordered source
# is sorted, reduced, or membership-tested before its order could reach
# decision state, and every timestamp comes from the injected clock or
# a seeded PRNG. Must produce ZERO det-engine findings.
import os
import random
import time
from typing import Callable, Dict, List, Set


class Workload:
    def __init__(self, name: str, priority: int):
        self.name = name
        self.priority = priority


class Condition:
    def __init__(self, kind: str, stamp: float):
        self.kind = kind
        self.stamp = stamp


class Cohort:
    def __init__(self, clock: Callable[[], float] = time.time):
        # An attribute REFERENCE as the injectable default is the
        # sanctioned TickClock seam — not a call, so never a source.
        self._clock = clock
        self.members: Set[Workload] = set()
        self.by_workload: Dict[Workload, int] = {}
        self.names: Set[str] = set()

    def victim_walk(self) -> List[Workload]:
        # Sanitized: name-keyed sort before the order can matter.
        return sorted(self.members, key=lambda w: w.name)

    def total_priority(self) -> int:
        # Reductions are order-insensitive.
        return sum(w.priority for w in self.members)

    def has(self, wl: Workload) -> bool:
        # Membership tests never observe iteration order.
        return wl in self.members

    def usage_total(self) -> int:
        return sum(self.by_workload.values())

    def rebuild(self) -> Set[str]:
        # Set-to-set rebuilds stay unordered (no order observed).
        return {w.name for w in self.members}

    def stamp_admission(self, wl: Workload) -> Condition:
        # Stamps come from the INJECTED clock, not the wall.
        return Condition("Admitted", self._clock())

    def tiebreak(self, wls: List[Workload]) -> List[Workload]:
        # Stable field keys; no wall-clock, no randomness.
        return sorted(wls, key=lambda w: (w.priority, w.name))


def spill_listing(root: str) -> List[str]:
    # Directory listings are sorted at the boundary.
    return sorted(os.listdir(root))


def jittered_backoff(seed: int) -> float:
    # Seeded PRNG instances are the sanctioned randomness path.
    rng = random.Random(seed)
    return rng.uniform(0.5, 1.5)
