# Bad fixture: every jit-purity violation family (JIT01/JIT02/JIT03).
# Analyzed statically by kueuelint — never imported or executed.
import functools

import jax
import jax.numpy as jnp
import numpy as np

_HOST_STATE = []


@jax.jit
def host_sync_item(x):
    total = jnp.sum(x)
    return total.item()  # JIT01: .item() host sync


@jax.jit
def host_cast(x):
    return float(x) + 1.0  # JIT01: float() on a traced value


@jax.jit
def host_numpy(x):
    return np.log(x)  # JIT01: host numpy on a traced value


@jax.jit
def trace_print(x):
    print(x)  # JIT01: print inside traced code
    return x


@functools.partial(jax.jit, static_argnames=("n",))
def traced_branch(x, n):
    if x > 0:  # JIT02: Python `if` on a traced value
        return x * n
    return x


@jax.jit
def traced_loop(x):
    while x < 10:  # JIT02: Python `while` on a traced value
        x = x + 1
    return x


@jax.jit
def leaks_tracer(x):
    y = x * 2
    _HOST_STATE.append(y)  # JIT03: traced value into closed-over state
    return y


@jax.jit
def global_mutation(x):
    global _COUNTER  # JIT03: global inside traced code
    _COUNTER = 1
    return x
