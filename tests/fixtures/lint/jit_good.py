# Good fixture: the same computations written trace-safely — zero findings.
import functools

import jax
import jax.numpy as jnp


@jax.jit
def device_sum(x):
    return jnp.sum(x)  # stays on device; caller syncs when it chooses


@functools.partial(jax.jit, static_argnames=("n",))
def static_branch(x, n):
    if n > 4:  # fine: `n` is a static argument, resolved at trace time
        return x * n
    W, = x.shape
    if W == 0:  # fine: shapes are static under jit
        return x
    return jnp.where(x > 0, x * n, x)  # traced select stays on device


@jax.jit
def bounded_loop(x):
    return jax.lax.while_loop(lambda v: jnp.all(v < 10), lambda v: v + 1, x)


@jax.jit
def structure_check(x, bias=None):
    if bias is None:  # fine: pytree-structure check, static at trace time
        return x
    return x + bias


def host_driver(batch):
    # Host-side code may sync freely — it is not jit-reachable.
    out = device_sum(jnp.asarray(batch))
    return float(out)
