# Fixture: three ways to break the knob contract (KNOB01) — a raw
# os.environ read of a registered knob, a raw read of a knob the
# registry never declared, and an accessor call with a typo'd name.
# The disciplined twin is knob_good.py.
import os
from os import environ

from kueue_tpu import knobs


def arena_disabled():
    # Registered knob, but read bare: bypasses the registry default and
    # the README-table contract.
    return os.environ.get("KUEUE_TPU_NO_ARENA", "") == "1"


def secret_mode():
    # A knob nobody declared: invisible to the docs and the lattice.
    return environ["KUEUE_TPU_SECRET_MODE"]


def eager():
    # Accessor with a name the registry does not know — a typo that
    # would otherwise surface as a KeyError inside a kill-switch drill.
    return knobs.flag("KUEUE_TPU_NO_EAGER_ENCODING")
