# Fixture: the disciplined twin of knob_bad.py — every env knob goes
# through the kueue_tpu.knobs registry accessors with registered names.
from kueue_tpu import knobs


def arena_disabled():
    return knobs.flag("KUEUE_TPU_NO_ARENA")


def round_timeout():
    return float(knobs.raw("KUEUE_TPU_ROUND_TIMEOUT"))


def native_heap():
    # Opt-out knobs compare raw() against their off value explicitly.
    return knobs.raw("KUEUE_TPU_NATIVE_HEAP") != "0"
