# Fixture: ledger charge/release violations (LED01). The assume path
# charges the topology ledger but no forget/delete path ever releases it,
# and a validating path charges before it can still raise — both leak
# occupancy that HA replay then rebuilds wrong.


class LeakyCache:
    def __init__(self):
        self.ledger = object()
        self.workloads = {}

    def assume_workload(self, wl):
        self.workloads[wl.key] = wl
        # charged on assume, but NO method in this class ever calls
        # self.ledger.charge(..., -1)
        self.ledger.charge(wl.admission, 1)
        return wl

    def forget_workload(self, wl):
        # release path forgot the ledger entirely
        self.workloads.pop(wl.key, None)


class ErrorPathCache:
    def __init__(self):
        self.books = object()

    def assume(self, wl):
        self.books.charge(wl.admission, 1)
        if wl.key in ("dup",):
            # error exit AFTER the charge: the ledger stays charged for a
            # workload that was never accounted
            raise ValueError("already assumed")
        return wl

    def forget(self, wl):
        self.books.charge(wl.admission, -1)
