# Fixture: balanced ledger discipline — every assume/add charge has the
# forget/delete release twin, and charges commit only after the last
# failure point (the real Cache shape). Zero LED01 findings.


class BalancedCache:
    def __init__(self):
        self.ledger = object()
        self.workloads = {}

    def assume_workload(self, wl):
        if wl.key in self.workloads:
            raise ValueError("already assumed")
        # the charge is the LAST mutation: nothing after it can fail
        self.workloads[wl.key] = wl
        self.ledger.charge(wl.admission, 1)
        return wl

    def forget_workload(self, wl):
        if self.workloads.pop(wl.key, None) is not None:
            self.ledger.charge(wl.admission, -1)
