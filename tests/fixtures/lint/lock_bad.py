# Bad fixture: lock-discipline hazards (LOCK01/LOCK02).
import subprocess
import threading
import time

from kueue_tpu.utils.parallelize import for_each


class Controller:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._state = {}
        self._applied = 0

    def apply_all(self, items, fn):
        with self._lock:
            # LOCK01: thread fan-out while holding the lock — workers that
            # call back into this controller deadlock on self._lock.
            for_each(items, fn)
            self._applied += len(items)

    def reconcile(self, key):
        with self._lock:
            time.sleep(0.1)  # LOCK01: sleeping while holding the lock
            self._state[key] = "ready"

    def run_hook(self, cmd):
        with self._lock:
            subprocess.run(cmd)  # LOCK01: subprocess under the lock

    def wait_forever(self):
        with self._cond:
            self._cond.wait()  # LOCK01: untimed wait — missed notify hangs

    def fast_path_write(self, n):
        # LOCK02: `_applied` is lock-guarded in apply_all but bare here.
        self._applied = n
