# Good fixture: lock-disciplined counterparts — zero findings.
import subprocess
import threading
import time

from kueue_tpu.utils.parallelize import for_each


class Controller:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._state = {}
        self._applied = 0

    def apply_all(self, items, fn):
        # Collect under the lock, fan out after release.
        with self._lock:
            batch = list(items)
        for_each(batch, fn)
        with self._lock:
            self._applied += len(batch)

    def reconcile(self, key):
        time.sleep(0.1)  # backoff happens outside the critical section
        with self._lock:
            self._state[key] = "ready"

    def run_hook(self, cmd):
        subprocess.run(cmd)
        with self._lock:
            self._state["hook"] = "done"

    def wait_ready(self, timeout=5.0):
        # Deadline arithmetic for a timed wait, not a latency measurement
        # (the OBS01 suppression discipline for non-tracer timing).
        deadline = time.monotonic() + timeout  # kueuelint: disable=OBS01
        with self._cond:
            while not self._state.get("ready"):
                remaining = deadline - time.monotonic()  # kueuelint: disable=OBS01
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)  # timed wait, predicate re-checked
        return True

    def _bump_locked(self, n):
        # `*_locked` suffix documents that the caller holds self._lock.
        self._applied = n
