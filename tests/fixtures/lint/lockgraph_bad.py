# Fixture: a lock-order cycle across two runtime classes. The cache takes
# its lock and calls into the queue (which takes the queue lock); the
# queue's flush path takes its lock and calls back into the cache (which
# takes the cache lock). Two threads entering from opposite ends deadlock.
import threading


class CacheSide:
    def __init__(self):
        self._lock = threading.RLock()
        self.queue = QueueSide(self)
        self.items = {}

    def admit(self, key):
        with self._lock:
            self.items[key] = True
            # cache lock held -> queue lock acquired inside
            self.queue.notify(key)

    def usage_locked(self, key):
        return self.items.get(key)

    def read_usage(self, key):
        with self._lock:
            return self.items.get(key)


class QueueSide:
    def __init__(self, owner):
        self._cond = threading.Condition()
        self.owner = CacheSide() if owner is None else owner
        self.pending = []

    def notify(self, key):
        with self._cond:
            self.pending.append(key)
            self._cond.notify_all()

    def flush(self):
        with self._cond:
            # queue lock held -> cache lock acquired inside (opposite
            # order to CacheSide.admit)
            for key in self.pending:
                self.owner.read_usage(key)
            self.pending.clear()
