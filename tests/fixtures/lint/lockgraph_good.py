# Fixture: same two classes with a single global acquisition order — the
# queue never calls back into the cache while holding its own lock (it
# collects under the lock, applies after release). No cycle.
import threading


class CacheSide:
    def __init__(self):
        self._lock = threading.RLock()
        self.queue = QueueSide(self)
        self.items = {}

    def admit(self, key):
        with self._lock:
            self.items[key] = True
            self.queue.notify(key)

    def read_usage(self, key):
        with self._lock:
            return self.items.get(key)


class QueueSide:
    def __init__(self, owner):
        self._cond = threading.Condition()
        self.owner = CacheSide() if owner is None else owner
        self.pending = []

    def notify(self, key):
        with self._cond:
            self.pending.append(key)
            self._cond.notify_all()

    def flush(self):
        with self._cond:
            batch = list(self.pending)
            self.pending.clear()
        # cache lock taken only AFTER the queue lock is released
        for key in batch:
            self.owner.read_usage(key)
