# Fixture: a lock-order cycle only visible through Protocol/annotation
# attribute typing. The runtime's channel attribute is typed by a
# Protocol annotation (the concrete class is wired through a factory, so
# constructor inference sees nothing), and the channel's owner back-ref
# is a bare class annotation. LOCK03 must resolve submit() ->
# Channel.push -> LockedChannel.push (structural conformer) ->
# Runtime.note and report the Runtime._lock <-> LockedChannel._lock
# cycle.
import threading
from typing import Protocol


class Channel(Protocol):
    def push(self, item): ...


def make_channel(owner):
    return LockedChannel(owner)


class LockedChannel:
    owner: "Runtime"

    def __init__(self, owner):
        self._lock = threading.Lock()
        self.owner = owner
        self.items = []

    def push(self, item):
        with self._lock:
            self.items.append(item)
            # channel lock held -> runtime lock acquired inside
            # (opposite order to Runtime.submit)
            self.owner.note(item)


class Runtime:
    chan: Channel

    def __init__(self):
        self._lock = threading.RLock()
        self.chan = make_channel(self)
        self.seen = []

    def submit(self, item):
        with self._lock:
            # runtime lock held -> channel lock acquired inside
            self.chan.push(item)

    def note(self, item):
        with self._lock:
            self.seen.append(item)
