# Fixture: the same Protocol-typed channel wiring as
# lockgraph_proto_bad.py, but with one global acquisition order — the
# channel's callback runs OUTSIDE its lock, so no cycle exists and
# LOCK03 must stay silent (the Protocol resolution must not invent
# edges that are not there).
import threading
from typing import Protocol


class Channel(Protocol):
    def push(self, item): ...


def make_channel(owner):
    return LockedChannel(owner)


class LockedChannel:
    owner: "Runtime"

    def __init__(self, owner):
        self._lock = threading.Lock()
        self.owner = owner
        self.items = []

    def push(self, item):
        with self._lock:
            self.items.append(item)
        # callback outside the channel lock: runtime lock is only ever
        # taken lock-free or strictly first
        self.owner.note(item)


class Runtime:
    chan: Channel

    def __init__(self):
        self._lock = threading.RLock()
        self.chan = make_channel(self)
        self.seen = []

    def submit(self, item):
        with self._lock:
            self.chan.push(item)

    def note(self, item):
        with self._lock:
            self.seen.append(item)
