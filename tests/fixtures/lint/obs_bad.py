"""OBS01 fixtures: raw timing in tick-pipeline code bypassing the tracer."""

import time
import time as _time
from time import perf_counter  # OBS01: direct function import

from kueue_tpu.metrics import REGISTRY


def schedule_phase(entries):
    t0 = time.perf_counter()  # OBS01: raw perf_counter measurement
    for e in entries:
        e.solve()
    REGISTRY.tick_phase_seconds.observe(
        "nominate", value=time.perf_counter() - t0)  # OBS01


def aliased_module_timer():
    start = _time.monotonic()  # OBS01: aliased module, monotonic
    return start


def from_import_timer():
    return perf_counter()


def wall_clock_ok():
    # time.time() is a wall-clock read, not a timing measurement.
    return time.time()
