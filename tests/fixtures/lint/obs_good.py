"""OBS01-clean twin: the same phases timed through the tracer."""

import time

from kueue_tpu.tracing import TRACER, trace_now


def schedule_phase(entries):
    # One measurement feeds the phase histogram, bench means and the
    # trace export together.
    with TRACER.phase("nominate") as sp:
        for e in entries:
            e.solve()
        sp.set("entries", len(entries))


def lock_wait(cond):
    with TRACER.lock(cond, "queue.lock_wait"):
        pass


def dispatch_anchor():
    # Raw timestamps on the tracer's timebase come from trace_now().
    return trace_now()


def wall_clock_ok():
    return time.time()
