# Fixture: a packed byte-buffer kernel whose SENTINEL FIELD overflows
# after the unpack chain. The wire layout packs an int64 quota plane
# (carrying the 2^62 NO_LIMIT sentinel) next to an int64 usage plane;
# the kernel bitcasts them apart and adds them — exactly the hazard the
# bitcast-aware Packed domain exists to catch (a flat interval seed
# would see only "uint8 in [0, 255]" and prove nothing). The good twin
# of this shape is the real roster: batch-jax / flavor-fit-packed are
# verified clean with the same packed seeding.
import jax
import jax.numpy as jnp  # noqa: F401
import numpy as np

import kueue_tpu.ops  # noqa: F401  (x64 before tracing)

from kueue_tpu.analysis.jaxpr_tools import packed_layout

SENTINEL = (0, 2**62)
CANON = (-(2**50), 2**50)


def packed_sentinel_add(buf, *, n):
    # Unpack chain: slice the byte planes apart, bitcast to int64.
    nominal = jax.lax.bitcast_convert_type(
        buf[:n * 8].reshape(-1, 8), jnp.int64)
    usage = jax.lax.bitcast_convert_type(
        buf[n * 8:].reshape(-1, 8), jnp.int64)
    # Headroom computed ADDITIVELY on the sentinel plane: 2^62 + 2^62
    # escapes int64 (the pre-fix `own <= nominal + blim` shape, now
    # reached through the packed wire format).
    return usage <= nominal + nominal


def _layout(n):
    return packed_layout([(n, 8, SENTINEL), (n, 8, CANON)])


def _build(n):
    import functools
    fn = functools.partial(packed_sentinel_add, n=n)
    return fn, (np.zeros(2 * n * 8, np.uint8),)


KUEUEVERIFY_KERNELS = [
    dict(name="bad-packed-sentinel", buckets=(4, 8), rules=("TRC02",),
         seeds=lambda n: {0: _layout(n)}, build=_build),
]
