# Fixture: the PR 2 Pallas int32-rescale bug shape, reproduced for the
# trace engine. The real bug: quota columns gcd-rescaled to int32 carry an
# I32_SENTINEL = 2^30 for "no limit"; the kernel then (a) added two
# sentinel-bearing columns — 2^30 + 2^30 wraps int32 and flips the
# fits verdict (TRC02), and (b) wrote weak int64 values (bare Python ints
# under x64) into int32 state, which the interpret-mode discharge rejects
# or silently truncates (TRC01). The preemption goldens only caught this
# at runtime, at the shapes they exercise; the jaxpr rules decide it
# statically at every bucket shape.
import jax.numpy as jnp
import numpy as np

import kueue_tpu.ops  # noqa: F401  (x64 before tracing)

I32_SENTINEL = np.int32(2**30)


def rescaled_fits(usage, wl_req, nominal, blim, blim_def):
    # (a) sentinel + sentinel: nominal and blim both carry 2^30 where
    # undefined; the int32 sum wraps negative and the masked comparison
    # silently mis-decides (TRC02).
    own = usage + wl_req
    cap = jnp.where(blim_def, own <= nominal + blim, True)
    return cap.all()


def rescaled_state_write(state, taken):
    # (b) weak-int64 write into the int32 scan state: a bare Python int
    # traces as (weak) int64 under x64 and the store casts back (TRC01).
    flags = state.at[0].set(taken[0] + jnp.int64(1))
    return flags


KUEUEVERIFY_KERNELS = [
    dict(name="pallas-rescale-fits", buckets=(4, 8), rules=("TRC02",),
         # real rescaled values stay below 2^30; nominal/blim carry the
         # sentinel 2^30 itself where undefined
         seeds={0: (0, 2**30 - 1), 1: (0, 2**30 - 1), 2: (0, 2**30),
                3: (0, 2**30)},
         build=lambda n: (rescaled_fits, (
             np.zeros(n, np.int32), np.zeros(n, np.int32),
             np.zeros(n, np.int32), np.zeros(n, np.int32),
             np.zeros(n, bool)))),
    dict(name="pallas-rescale-write", buckets=(4, 8), rules=("TRC01",),
         build=lambda n: (rescaled_state_write, (
             np.zeros(n, np.int32), np.zeros(n, np.int64)))),
]
