"""PERF01 fixtures: per-workload Python loops over solver output tensors."""

import numpy as np


def decode_slow(workloads, out):
    # Direct element-wise read of an output tensor inside the loop.
    modes = []
    for w in range(len(workloads)):
        modes.append(out["wl_mode"][w])  # finding: direct subscript
    return modes


def decode_alias_slow(workloads, out):
    n = len(workloads)
    ps_ok = out["ps_ok"][:n]
    flavors = out["res_flavor"]
    picked = []
    for w, wi in enumerate(workloads):
        if ps_ok[w].all():  # finding: aliased tensor, loop-var index
            picked.append(flavors[w])  # finding
    return picked


def flush_slow(entries, out):
    total = 0
    i = 0
    while i < len(entries):
        total += int(out["ps_mode"][i])  # finding: while-loop counter
        i += 1
    return total


def flush_assume_slow(entries, out):
    # The admission-commit shape PERF01 now polices in core/cache.py and
    # core/snapshot.py too: walking the solve's usage coordinates one
    # entry at a time instead of one aggregated np pass.
    total = {}
    for j, entry in enumerate(entries):
        total[entry] = int(out["res_mode"][j].sum())  # finding
    return total
