"""PERF01 fair-loop fixtures: per-iteration share dict walks in loops."""

from kueue_tpu.solver.fair_share import dominant_resource_share


def fair_victims_slow(snapshot, per_cq, strategies, cq, wl_req):
    # The KEP-1714 loop shape PERF01 polices: dominant_resource_share
    # re-derived per candidate per while-iteration.
    targets = []
    while per_cq:
        share_x, _ = dominant_resource_share(cq, wl_req)  # finding
        for name, cands in per_cq.items():
            y = snapshot.cluster_queues[name]
            for z in cands:
                share_y, _ = dominant_resource_share(y)  # finding
                if share_y > share_x:
                    targets.append(z)
        break
    return targets


def order_slow(snapshot, names):
    out = []
    for name in names:
        out.append(dominant_resource_share(  # finding
            snapshot.cluster_queues[name])[0])
    return out
