"""PERF01 fair-loop good twins: shares computed once, arrays compared."""

import numpy as np

from kueue_tpu.solver.fair_share import dominant_resource_share


def fair_victims_vectorized(state, swo, valid, sx):
    # Shares computed ONCE on the vectorized tensors; the loop compares
    # precomputed arrays (masked argmax), never re-walking the dicts.
    ok = valid & (swo >= sx)
    targets = []
    while ok.any():
        z = int(np.argmax(ok))
        targets.append(z)
        ok[z] = False
    return targets


def share_once_outside_loop(snapshot, cq, names):
    # A single share walk OUTSIDE any loop is fine (the referee's
    # one-shot reads, the metrics fallback).
    base = dominant_resource_share(cq)[0]
    return [base for _ in names]
