"""PERF01 negative fixtures: the sanctioned vectorized/tolist patterns."""

import numpy as np


def decode_vectorized(workloads, out):
    n = len(workloads)
    ps_ok = out["ps_ok"][:n]
    # Whole-tensor numpy work outside any loop: fine.
    ws, pp = np.nonzero(ps_ok)
    flavors = out["res_flavor"][:n][ws, pp]
    return ws, flavors


def decode_tolist(workloads, out):
    n = len(workloads)
    # One materialization, then plain-list iteration: fine.
    modes_l = out["wl_mode"][:n].tolist()
    picked = []
    for w, mode in enumerate(modes_l):
        if mode > 0:
            picked.append((w, modes_l[w]))
    return picked


def unrelated_loop(rows, table):
    # Subscripting non-tensor containers in a loop: fine.
    out = []
    for r in rows:
        out.append(table[r])
    return out


def flush_assume_aggregated(entries, out):
    # The sanctioned commit shape: ONE aggregation over the whole
    # cycle's coordinates (np.unique + np.add.at), then plain-dict
    # stores over the deduped triples.
    n = len(entries)
    modes = out["res_mode"][:n]
    key = modes.reshape(n, -1).argmax(axis=1)
    ukey, inv = np.unique(key, return_inverse=True)
    sums = np.zeros(len(ukey), dtype=np.int64)
    np.add.at(sums, inv, 1)
    return dict(zip(ukey.tolist(), sums.tolist()))
