"""PERF01 ingest-loop fixture: per-object ingest calls inside a loop
over a batch payload — the decode→webhook→sink fan-out shape the batch
lane collapses."""


def ingest_docs(store, fw, serialization, docs):
    created = []
    for doc in docs:
        kind, obj = serialization.decode(doc)  # PERF01: per-object decode
        created.append(store.create(kind, obj))  # PERF01: per-object create
    return created


def submit_all(fw, workloads):
    for wl in workloads:
        fw.submit(wl)  # PERF01: per-object submit


def decode_items(items):
    out = []
    for doc in items:
        out.append(decode_workload(doc))  # PERF01: per-object decode
    return out


def decode_workload(doc):
    return doc
