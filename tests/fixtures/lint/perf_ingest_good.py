"""PERF01 ingest-loop fixture (clean): the batch lane — one decode
sweep, one create_batch, one submit_batch — plus a sanctioned
kill-switch twin carrying an explanatory suppression."""

def ingest_docs(store, fw, serialization, docs):
    wls = serialization.decode_workload_batch(docs)
    return store.create_batch("Workload", wls)


def submit_all(fw, workloads):
    fw.submit_batch(list(workloads), validate=False)


def kill_switch_twin(store, kind, objs, no_batch_ingest=False):
    if no_batch_ingest:
        out = []
        for obj in objs:  # the per-object twin, on purpose
            one = store.create(kind, obj)  # kueuelint: disable=PERF01
            out.append(one)
        return out
    return store.create_batch(kind, objs)
