# Bad fixture: retrace-hygiene hazards (RET01/RET02).
import functools
from typing import List

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("shape", "missing"))
def typo_static(x, shape):  # RET01: `missing` is not a parameter
    return jnp.zeros(shape) + x


@functools.partial(jax.jit, static_argnums=(5,))
def out_of_range(x, y):  # RET01: static_argnums index 5 out of range
    return x + y


@functools.partial(jax.jit, static_argnames=("sizes",))
def unhashable_static(x, sizes: List[int]):  # RET01: list static arg
    return x[: sizes[0]]


def _direct_impl(x, flags):
    return x


# RET01: statics declared on a direct jax.jit(...) call are checked too.
direct_call_typo = jax.jit(_direct_impl, static_argnames=("flag",))


def build_step(scale, offset):
    @jax.jit
    def step(x):
        # RET02: `scale`/`offset` captured from the enclosing scope; a new
        # build_step call with different values silently retraces.
        return x * scale + offset

    return step
