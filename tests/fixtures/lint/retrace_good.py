# Good fixture: retrace-safe patterns — zero findings.
import functools
from typing import Tuple

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("shape",))
def named_static(x, shape: Tuple[int, ...]):  # hashable tuple static
    return jnp.zeros(shape) + x


@functools.partial(jax.jit, static_argnums=(1,))
def indexed_static(x, n: int):
    return x * n


@jax.jit
def scale_as_arg(x, scale, offset):
    # Per-call values ride as traced arguments: one trace serves them all.
    return x * scale + offset


def _branch_impl(x, n):
    if n > 2:  # fine: `n` is static via the direct jax.jit(...) call below
        return x * n
    return x


direct_call_static = jax.jit(_branch_impl, static_argnums=(1,))
