# Bad fixture (API03): decode/encode forget JobSpec.retries.
from .types import JobSpec


def decode_job_spec(doc):
    return JobSpec(
        name=doc["name"],
        queue=doc.get("queue", ""),
        priority=int(doc.get("priority", 0)))


def encode_job_spec(spec):
    return {"name": spec.name, "queue": spec.queue,
            "priority": spec.priority}
