# Bad fixture (API03): `retries` never appears in the sibling
# serialization.py, so an encode/decode roundtrip silently drops it.
from dataclasses import dataclass


@dataclass(frozen=True)
class JobSpec:
    name: str
    queue: str
    priority: int = 0
    retries: int = 0
