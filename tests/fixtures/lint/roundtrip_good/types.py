# Good fixture (API03): every field round-trips.
from dataclasses import dataclass


@dataclass(frozen=True)
class JobSpec:
    name: str
    queue: str
    priority: int = 0
    retries: int = 0
