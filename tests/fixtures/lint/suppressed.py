# Fixture: violations silenced by per-line suppression comments.
# kueuelint must report ZERO findings here.
import threading
import time

import jax


@jax.jit
def checked_sync(x):
    # Deliberate: this kernel is only called from the debug CLI.
    return x.item()  # kueuelint: disable=JIT01


class Controller:
    def __init__(self):
        self._lock = threading.Lock()

    def reconcile(self):
        with self._lock:
            time.sleep(0.01)  # kueuelint: disable=LOCK01


def legacy(batch=[]):  # kueuelint: disable
    return batch
