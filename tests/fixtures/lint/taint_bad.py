# Fixture: knob decision-contract violations (TNT01) — a gate knob read
# off its registered gate sites, and neutral-knob values reaching
# decision state (attribute store, decision-record constructor, sort
# key). Knob names are REAL registry entries: the rule resolves their
# contracts from the package registry when the analyzed set carries no
# knobs.py of its own. The disciplined twin is taint_good.py.
from typing import List, Optional

from kueue_tpu import knobs


class AdmissionRecord:
    def __init__(self, name: str, debug_tag: Optional[str]):
        self.name = name
        self.debug_tag = debug_tag


class TickState:
    def __init__(self):
        # TNT01: KUEUE_TPU_NO_ARENA gates at models/flavor_fit.py only;
        # reading it here is an unregistered gate point.
        self.arena_off = knobs.flag("KUEUE_TPU_NO_ARENA")  # line 22: TNT01 (gate)
        # TNT01: a neutral knob's VALUE persisted into decision-core
        # state (branching on it would be fine; storing it is not).
        self.debug_fair = knobs.raw("KUEUE_TPU_DEBUG_FAIR")  # line 25: TNT01 (neutral store)

    def record(self, name: str) -> AdmissionRecord:
        # TNT01: neutral knob value embedded in a decision record.
        tag = knobs.raw("KUEUE_TPU_TRACE")
        return AdmissionRecord(name, tag)                # line 30: TNT01 (neutral ctor)

    def order(self, names: List[str]) -> List[str]:
        # TNT01: neutral knob value inside a sort key.
        return sorted(
            names,
            key=lambda n: (knobs.raw("KUEUE_TPU_DEBUG_HETERO"), n))  # line 34: TNT01 (neutral key)
