# Fixture: the disciplined twin of taint_bad.py — neutral knobs only
# ever BRANCH (enabling a cross-check or a tracer), their values never
# persist into decision state, and no gate knob is read outside its
# registered sites. Must produce ZERO det-engine findings.
from typing import List, Optional

from kueue_tpu import knobs


class AdmissionRecord:
    def __init__(self, name: str, debug_tag: Optional[str]):
        self.name = name
        self.debug_tag = debug_tag


class TickState:
    def __init__(self):
        self.cross_check_ran = False

    def maybe_cross_check(self, result: int, referee: int) -> None:
        # Branching on a neutral knob is exactly what neutral knobs are
        # for — the VALUE dies at the test.
        if knobs.flag("KUEUE_TPU_DEBUG_FAIR"):
            assert result == referee
            self.cross_check_ran = True

    def record(self, name: str) -> AdmissionRecord:
        # Decision records carry decision inputs only.
        return AdmissionRecord(name, None)

    def order(self, names: List[str]) -> List[str]:
        # Stable, knob-free sort key.
        return sorted(names, key=lambda n: n)

    def trace_enabled(self) -> bool:
        # Returning the flag for a BRANCH decision elsewhere is fine —
        # nothing here stores it into decision-core state.
        return knobs.flag("KUEUE_TPU_TRACE")
