# Fixture: cross-thread state shared without a consistent lock (THR01)
# and unbounded blocking calls issued on service threads (THR02) — the
# symmetric-sendall deadlock and zombie-socket wedge shapes. The
# disciplined twin is thr_good.py.
import os
import threading


class BadPump:
    """Reader thread publishes into shared state bare and writes acks
    with an unbounded sendall on a socket nobody ever bounded."""

    def __init__(self, sock):
        self._sock = sock
        self._lock = threading.Lock()
        self._last = None
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True)
        self._reader.start()

    def _read_loop(self):
        while True:
            data = self._sock.recv(1 << 16)
            if not data:
                return
            self._last = data
            self._sock.sendall(b"ack")
            os.fsync(self._sock.fileno())

    def last(self):
        with self._lock:
            return self._last

    def close(self):
        with self._lock:
            self._closed = True


class BadFlusher:
    """Service thread makes another queue's liveness its own with an
    untimed join."""

    def __init__(self, inbox, outbox):
        self._q = inbox
        self._other = outbox
        threading.Thread(target=self._drain_loop, daemon=True).start()

    def _drain_loop(self):
        while True:
            item = self._q.get()
            self._other.put(item)
            self._other.join()
            self._q.task_done()
