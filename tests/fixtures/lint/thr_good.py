# Fixture: the disciplined twin of thr_bad.py. Same thread topology —
# reader thread, shared state, acks written from the reader — but the
# socket is bounded with settimeout (a stuck send severs instead of
# wedging), every cross-thread access is guarded, and the helper
# documents the lock contract via the *_locked naming convention.
import socket
import threading


class GoodPump:
    def __init__(self, sock):
        sock.settimeout(30.0)
        self._sock = sock
        self._lock = threading.Lock()
        self._last = None
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True)
        self._reader.start()

    def _read_loop(self):
        while True:
            try:
                data = self._sock.recv(1 << 16)
            except socket.timeout:
                continue
            except OSError:
                return
            if not data:
                return
            with self._lock:
                self._note_locked(data)
            self._sock.sendall(b"ack")

    def _note_locked(self, data):
        self._last = data

    def last(self):
        with self._lock:
            return self._last

    def stop(self):
        with self._lock:
            self._closed = True
        self._reader.join(timeout=5.0)
