# Bad fixture: jit-purity violations in a topology-style fit kernel.
# Analyzed statically by kueuelint — never imported or executed.
import jax
import jax.numpy as jnp
import numpy as np

_DOMAIN_LOG = []


@jax.jit
def leaky_domain_fit(leaf_cap, leaf_used, count):
    free = jnp.maximum(leaf_cap - leaf_used, 0)
    total = jnp.sum(free)
    if total < count:  # JIT02: Python `if` on a traced value
        return -1
    best = jnp.argmax(free)
    _DOMAIN_LOG.append(best)  # JIT03: traced value into closed-over state
    return int(best)  # JIT01: int() host cast on a traced value


@jax.jit
def host_numpy_fit(leaf_free):
    return np.argmin(leaf_free)  # JIT01: host numpy on a traced value
