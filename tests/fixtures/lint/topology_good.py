# Good fixture: a topology-style best-fit-level search written
# trace-safely (the kueue_tpu/topology/fit.py idiom) — zero findings.
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("shapes",))
def domain_fit(leaf_cap, leaf_used, leaf_domain, count, *, shapes):
    T, E, D = shapes  # fine: static shapes resolved at trace time
    free = jnp.maximum(leaf_cap - leaf_used, 0)
    dom = jnp.where(leaf_domain >= 0, leaf_domain, D)
    seg = (jnp.arange(T)[:, None] * (D + 1) + dom).reshape(-1)
    dom_free = jax.ops.segment_sum(
        free.reshape(-1), seg, num_segments=T * (D + 1))
    dom_free = dom_free.reshape(T, D + 1)[:, :D]
    fits = dom_free >= count[:, None]
    best = jnp.argmin(jnp.where(fits, dom_free, 1 << 30), axis=1)
    return jnp.where(fits.any(axis=1), best, -1)


def host_driver(enc, used, counts):
    # Host code syncs freely — it is not jit-reachable.
    out = domain_fit(jnp.asarray(enc), jnp.asarray(used),
                     jnp.asarray(enc), jnp.asarray(counts),
                     shapes=(2, 4, 4))
    return [int(v) for v in out]
