# Fixture: kernels whose JAXPRS carry the hazards the kueueverify trace
# engine (TRC01-04) exists to catch. Each manifest entry restricts itself
# to the rule it demonstrates so the test can assert per-rule hits.
import jax
import jax.numpy as jnp
import numpy as np

import kueue_tpu.ops  # noqa: F401  (x64 before tracing)


def mixed_dtype_write(buf, vals):
    # int64 value stored into an int32 buffer: jax widens the buffer,
    # scatters, and silently casts back (TRC01).
    return buf.at[0].set(vals[0])


def literal_widening(x):
    # 64-bit literal widens the int32 tensor in an add (TRC01).
    return x + jnp.int64(7)


def sentinel_add(nominal, blim, own):
    # Both operands carry a 2^62 "no limit" sentinel; the sum passes
    # int64 max and wraps (TRC02) — the shape of the pre-fix
    # `own <= nominal + blim` in the victim scan.
    return own <= nominal + blim


def shape_unrolled(x):
    # Python-level unroll over the padded axis: every bucket lowers to a
    # DIFFERENT jaxpr, so each rotation recompiles a new program (TRC03).
    total = jnp.zeros((), dtype=x.dtype)
    for i in range(x.shape[0]):
        total = total + x[i]
    return total


def debug_printing(x):
    # Host callback inside the kernel (TRC04).
    jax.debug.print("solve state {}", x)
    return x * 2


def _args_i32_i64(n):
    return mixed_dtype_write, (np.zeros(n, np.int32), np.zeros(n, np.int64))


KUEUEVERIFY_KERNELS = [
    dict(name="bad-write", buckets=(4, 8), rules=("TRC01",),
         build=_args_i32_i64),
    dict(name="bad-literal", buckets=(4, 8), rules=("TRC01",),
         build=lambda n: (literal_widening, (np.zeros(n, np.int32),))),
    dict(name="bad-sentinel", buckets=(4, 8), rules=("TRC02",),
         seeds={0: (0, 2**62), 1: (0, 2**62)},
         build=lambda n: (sentinel_add, (np.zeros(n, np.int64),
                                         np.zeros(n, np.int64),
                                         np.zeros(n, np.int64)))),
    dict(name="bad-unroll", buckets=(4, 8), rules=("TRC03",),
         build=lambda n: (shape_unrolled, (np.zeros(n, np.int64),))),
    dict(name="bad-effect", buckets=(4, 8), rules=("TRC04",),
         build=lambda n: (debug_printing, (np.zeros(n, np.int64),))),
]
