# Fixture: a clean kernel — dtype-pinned writes, subtraction-form sentinel
# comparisons, shape-stable trace, no host callbacks. The trace engine
# must report ZERO findings here.
import jax.numpy as jnp
import numpy as np

import kueue_tpu.ops  # noqa: F401  (x64 before tracing)


def clean_kernel(nominal, blim, blim_def, own, buf, vals):
    # Sentinel-safe: compare via subtraction, never add two sentinels.
    cap_ok = jnp.where(blim_def, own - blim <= nominal, True)
    # Dtype-pinned write: the stored value matches the buffer dtype.
    buf = buf.at[0].set(vals[0].astype(buf.dtype))
    # Shape-stable reduction (one jaxpr per bucket).
    return cap_ok.all(), buf.sum(dtype=buf.dtype)


KUEUEVERIFY_KERNELS = [
    dict(name="good-kernel", buckets=(4, 8),
         # nominal/blim carry the 2^62 sentinel; the write buffer and its
         # source are small bookkeeping counters
         seeds={0: (0, 2**62), 1: (0, 2**62), 4: (0, 1 << 20),
                5: (0, 1 << 20)},
         build=lambda n: (clean_kernel, (
             np.zeros(n, np.int64), np.zeros(n, np.int64),
             np.zeros(n, bool), np.zeros(n, np.int64),
             np.zeros(n, np.int32), np.zeros(n, np.int64)))),
]
