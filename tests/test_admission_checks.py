"""Two-phase admission: provisioning and MultiKueue check controllers
(scenarios modeled on the reference's admissionchecks integration suites;
the two-cluster setup mirrors test/integration/multikueue)."""

from kueue_tpu.controllers.multikueue import (
    InProcessRemote,
    MultiKueueController,
)
from kueue_tpu.controllers.provisioning import (
    ProvisioningController,
    ProvisioningRequestConfig,
)
from kueue_tpu.controllers.runtime import Framework

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg


def checked_framework(checks=("prov",), quota_cpu=8):
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=quota_cpu)),
        admission_checks=checks))
    fw.create_local_queue(make_lq("main", cq="cq"))
    return fw


def test_provisioning_success_admits():
    fw = checked_framework()
    ctrl = ProvisioningController(fw)
    ctrl.register_check("prov", ProvisioningRequestConfig(name="default-prov"))
    wl = make_wl("w", cpu=2)
    fw.submit(wl)
    fw.run_until_settled()
    assert wl.has_quota_reservation and not wl.is_admitted
    ctrl.reconcile()   # creates the request; instant provider provisions it
    fw.reconcile()     # flips Admitted
    assert wl.is_admitted
    assert wl.admission_check_states["prov"].state == "Ready"
    assert len(ctrl.requests) == 1


def test_provisioning_retry_then_reject():
    """A Failed request is retried with exponential backoff (fresh request,
    attempt suffix incremented) up to MaxRetries(3); then the check is
    Rejected with the failure message and the workload is deactivated
    (controller.go:240-258,496-513)."""
    from kueue_tpu.controllers import provisioning as prov_mod

    fw = checked_framework()
    now = [1000.0]

    def failing_provider(req):
        if req.state == "Pending":
            req.state = "Failed"
            req.failure_message = "nodes unavailable"

    ctrl = ProvisioningController(fw, provider=failing_provider,
                                  clock=lambda: now[0])
    ctrl.register_check("prov", ProvisioningRequestConfig(name="p"))
    wl = make_wl("w", cpu=2)
    fw.submit(wl)
    fw.run_until_settled()
    assert wl.has_quota_reservation

    ctrl.reconcile()  # attempt 1 fails
    st = wl.admission_check_states["prov"]
    assert st.state == "Pending"
    assert "Retrying after failure: nodes unavailable" in st.message
    assert ctrl._latest_request(wl, "prov").attempt == 1

    # Before the backoff elapses no new attempt is made.
    now[0] += 10
    ctrl.reconcile()
    assert ctrl._latest_request(wl, "prov").attempt == 1

    # Each elapsed backoff yields a fresh request with the next attempt
    # suffix: 60s, 120s, 240s (MinBackoffSeconds * 2^(attempt-1)).
    for attempt, backoff in ((2, 60), (3, 120), (4, 240)):
        now[0] += backoff
        ctrl.reconcile()
        req = ctrl._latest_request(wl, "prov")
        assert req.attempt == attempt
        assert req.name == f"w-prov-{attempt}"

    # attempt 4 > MaxRetries(3): Rejected with the raw failure message.
    now[0] += 1000
    ctrl.reconcile()
    assert wl.admission_check_states["prov"].state == "Rejected"
    assert wl.admission_check_states["prov"].message == "nodes unavailable"
    fw.reconcile()
    fw.reconcile()
    assert not wl.active
    assert prov_mod.backoff_seconds(10) == prov_mod.MAX_BACKOFF_SECONDS


def test_provisioning_managed_resources_and_annotations():
    """Pod sets not requesting a managed resource are excluded; with no
    relevant pod sets the check is Ready with NoRequestNeeded. Workload
    provreq.kueue.x-k8s.io/* annotations become request parameters."""
    from kueue_tpu.api.types import PodSet
    from kueue_tpu.controllers.provisioning import (
        CONSUMES_ANNOTATION_KEY,
        NO_REQUEST_NEEDED,
    )

    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq(
        "cq", rg(("cpu", "tpu"), fq("default", cpu=8, tpu=8)),
        admission_checks=("prov",)))
    fw.create_local_queue(make_lq("main", cq="cq"))
    ctrl = ProvisioningController(fw)
    ctrl.register_check("prov", ProvisioningRequestConfig(
        name="p", parameters={"zone": "us-central2"},
        managed_resources=("tpu",)))

    # No pod set requests "tpu": Ready without a request.
    wl = make_wl("plain", cpu=2)
    fw.submit(wl)
    fw.run_until_settled()
    ctrl.reconcile()
    assert wl.admission_check_states["prov"].state == "Ready"
    assert wl.admission_check_states["prov"].message == NO_REQUEST_NEEDED
    assert not ctrl.requests

    # Mixed workload: only the tpu pod set lands in the request; annotation
    # parameters override/extend the config's.
    wl2 = make_wl("mixed", pod_sets=[
        PodSet(name="driver", count=1, requests={"cpu": 1000}),
        PodSet(name="workers", count=2, requests={"cpu": 1000, "tpu": 4}),
    ])
    wl2.annotations["provreq.kueue.x-k8s.io/priority"] = "high"
    fw.submit(wl2)
    fw.run_until_settled()
    ctrl.reconcile()
    (req,) = ctrl.requests.values()
    assert [ps["name"] for ps in req.pod_sets] == ["workers"]
    assert req.parameters == {"zone": "us-central2", "priority": "high"}
    st = wl2.admission_check_states["prov"]
    assert st.state == "Ready"
    assert st.pod_set_updates == [
        {"name": "workers",
         "annotations": {CONSUMES_ANNOTATION_KEY: "mixed-prov-1"}}]


def test_provisioning_inactive_check_and_gc():
    """A check with no config reports 'the check is not active'; requests of
    workloads that lost their quota are garbage-collected."""
    from kueue_tpu.controllers.provisioning import CHECK_INACTIVE_MESSAGE

    fw = checked_framework()
    ctrl = ProvisioningController(fw)
    ctrl.register_check("prov")  # no config -> inactive
    wl = make_wl("w", cpu=2)
    fw.submit(wl)
    fw.run_until_settled()
    ctrl.reconcile()
    assert wl.admission_check_states["prov"].state == "Pending"
    assert wl.admission_check_states["prov"].message == CHECK_INACTIVE_MESSAGE

    ctrl.register_check("prov", ProvisioningRequestConfig(name="p"))
    ctrl.reconcile()
    assert wl.admission_check_states["prov"].state == "Ready"
    assert len(ctrl.requests) == 1
    fw.finish(wl)
    ctrl.reconcile()
    assert not ctrl.requests


def make_worker(name="worker"):
    worker = Framework()
    worker.create_resource_flavor(make_flavor("default"))
    worker.create_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=8))))
    worker.create_local_queue(make_lq("main", cq="cq"))
    return worker


def test_multikueue_first_reservation_wins():
    manager = checked_framework(checks=("multikueue",))
    worker1, worker2 = make_worker(), make_worker()
    mk = MultiKueueController(manager, check_name="multikueue")
    mk.add_cluster("w1", InProcessRemote(worker1))
    mk.add_cluster("w2", InProcessRemote(worker2))

    wl = make_wl("train", cpu=2)
    manager.submit(wl)
    manager.run_until_settled()
    mk.reconcile()  # dispatch to both workers
    assert wl.key in worker1.workloads and wl.key in worker2.workloads

    # worker1 admits first.
    worker1.run_until_settled()
    mk.reconcile()
    assert wl.admission_check_states["multikueue"].state == "Ready"
    assert "w1" in wl.admission_check_states["multikueue"].message
    # The mirror on the losing worker was deleted.
    assert wl.key not in worker2.workloads
    manager.reconcile()
    assert wl.is_admitted

    # Remote finishes -> local finishes, remote mirror GCed.
    worker1.finish(worker1.workloads[wl.key])
    mk.reconcile()
    assert wl.is_finished
    assert wl.key not in worker1.workloads


def test_multikueue_worker_lost_retries():
    manager = checked_framework(checks=("multikueue",))

    class FakeClock:
        now = 1000.0

        def __call__(self):
            return FakeClock.now

    manager.clock = FakeClock()
    worker1 = make_worker()
    remote1 = InProcessRemote(worker1)
    mk = MultiKueueController(manager, check_name="multikueue",
                              worker_lost_timeout=60.0)
    mk.add_cluster("w1", remote1)

    wl = make_wl("train", cpu=2)
    manager.submit(wl)
    manager.run_until_settled()
    mk.reconcile()
    worker1.run_until_settled()
    mk.reconcile()
    assert wl.admission_check_states["multikueue"].state == "Ready"

    # The worker disconnects; after workerLostTimeout the check retries.
    remote1.set_connected(False)
    mk.reconcile()
    assert wl.admission_check_states["multikueue"].state == "Ready"
    FakeClock.now += 61.0
    mk.reconcile()
    assert wl.admission_check_states["multikueue"].state == "Retry"
    # The Retry check evicts the local workload for a fresh dispatch.
    manager.reconcile()
    manager.reconcile()
    assert not wl.has_quota_reservation


def test_workload_manifest_annotations_reach_provisioning():
    """provreq.kueue.x-k8s.io/* annotations survive manifest decoding and
    job->workload construction (reconciler.go:808)."""
    from kueue_tpu.api.serialization import decode_workload
    from kueue_tpu.jobs.batch_job import BatchJob

    wl = decode_workload({
        "apiVersion": "kueue.x-k8s.io/v1beta1", "kind": "Workload",
        "metadata": {"name": "w", "namespace": "ns", "annotations": {
            "provreq.kueue.x-k8s.io/priority": "high"}},
        "spec": {"queueName": "main", "podSets": [
            {"name": "main", "count": 1}]},
    })
    assert wl.annotations == {"provreq.kueue.x-k8s.io/priority": "high"}

    fw = checked_framework()
    job = BatchJob(name="j", queue_name="main", parallelism=1,
                   requests={"cpu": 1000},
                   annotations={"provreq.kueue.x-k8s.io/zone": "z",
                                "other": "ignored"})
    jwl = fw.submit_job(job)
    assert jwl.annotations == {"provreq.kueue.x-k8s.io/zone": "z"}
