"""Two-phase admission: provisioning and MultiKueue check controllers
(scenarios modeled on the reference's admissionchecks integration suites;
the two-cluster setup mirrors test/integration/multikueue)."""

from kueue_tpu.controllers.multikueue import (
    InProcessRemote,
    MultiKueueController,
)
from kueue_tpu.controllers.provisioning import (
    ProvisioningController,
    ProvisioningRequestConfig,
)
from kueue_tpu.controllers.runtime import Framework

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg


def checked_framework(checks=("prov",), quota_cpu=8):
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=quota_cpu)),
        admission_checks=checks))
    fw.create_local_queue(make_lq("main", cq="cq"))
    return fw


def test_provisioning_success_admits():
    fw = checked_framework()
    ctrl = ProvisioningController(fw)
    ctrl.register_check("prov", ProvisioningRequestConfig(name="default-prov"))
    wl = make_wl("w", cpu=2)
    fw.submit(wl)
    fw.run_until_settled()
    assert wl.has_quota_reservation and not wl.is_admitted
    ctrl.reconcile()   # creates the request; instant provider provisions it
    fw.reconcile()     # flips Admitted
    assert wl.is_admitted
    assert wl.admission_check_states["prov"].state == "Ready"
    assert len(ctrl.requests) == 1


def test_provisioning_retry_then_reject():
    fw = checked_framework()
    outcomes = iter(["Failed", "Failed"])

    def flaky_provider(req):
        if req.state == "Pending":
            req.state = next(outcomes, "Failed")

    ctrl = ProvisioningController(fw, provider=flaky_provider)
    ctrl.register_check("prov", ProvisioningRequestConfig(
        name="p", max_retries=2))
    wl = make_wl("w", cpu=2)
    fw.submit(wl)
    fw.run_until_settled()
    ctrl.reconcile()
    assert wl.admission_check_states["prov"].state == "Retry"
    # Retry evicts and releases quota; the check resets to Pending.
    fw.reconcile()
    fw.reconcile()
    assert not wl.has_quota_reservation
    assert wl.admission_check_states["prov"].state == "Pending"
    # Re-reserve; second attempt fails and exhausts retries -> Rejected.
    fw.run_until_settled()
    assert wl.has_quota_reservation
    ctrl.reconcile()
    assert wl.admission_check_states["prov"].state == "Rejected"
    fw.reconcile()
    fw.reconcile()
    assert not wl.active


def make_worker(name="worker"):
    worker = Framework()
    worker.create_resource_flavor(make_flavor("default"))
    worker.create_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=8))))
    worker.create_local_queue(make_lq("main", cq="cq"))
    return worker


def test_multikueue_first_reservation_wins():
    manager = checked_framework(checks=("multikueue",))
    worker1, worker2 = make_worker(), make_worker()
    mk = MultiKueueController(manager, check_name="multikueue")
    mk.add_cluster("w1", InProcessRemote(worker1))
    mk.add_cluster("w2", InProcessRemote(worker2))

    wl = make_wl("train", cpu=2)
    manager.submit(wl)
    manager.run_until_settled()
    mk.reconcile()  # dispatch to both workers
    assert wl.key in worker1.workloads and wl.key in worker2.workloads

    # worker1 admits first.
    worker1.run_until_settled()
    mk.reconcile()
    assert wl.admission_check_states["multikueue"].state == "Ready"
    assert "w1" in wl.admission_check_states["multikueue"].message
    # The mirror on the losing worker was deleted.
    assert wl.key not in worker2.workloads
    manager.reconcile()
    assert wl.is_admitted

    # Remote finishes -> local finishes, remote mirror GCed.
    worker1.finish(worker1.workloads[wl.key])
    mk.reconcile()
    assert wl.is_finished
    assert wl.key not in worker1.workloads


def test_multikueue_worker_lost_retries():
    manager = checked_framework(checks=("multikueue",))

    class FakeClock:
        now = 1000.0

        def __call__(self):
            return FakeClock.now

    manager.clock = FakeClock()
    worker1 = make_worker()
    remote1 = InProcessRemote(worker1)
    mk = MultiKueueController(manager, check_name="multikueue",
                              worker_lost_timeout=60.0)
    mk.add_cluster("w1", remote1)

    wl = make_wl("train", cpu=2)
    manager.submit(wl)
    manager.run_until_settled()
    mk.reconcile()
    worker1.run_until_settled()
    mk.reconcile()
    assert wl.admission_check_states["multikueue"].state == "Ready"

    # The worker disconnects; after workerLostTimeout the check retries.
    remote1.set_connected(False)
    mk.reconcile()
    assert wl.admission_check_states["multikueue"].state == "Ready"
    FakeClock.now += 61.0
    mk.reconcile()
    assert wl.admission_check_states["multikueue"].state == "Retry"
    # The Retry check evicts the local workload for a fresh dispatch.
    manager.reconcile()
    manager.reconcile()
    assert not wl.has_quota_reservation
