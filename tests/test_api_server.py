"""HTTP API server tests: the out-of-process surface.

Covers the object API (CRUD + webhook rejection + labelSelector), the
visibility endpoints, Prometheus /metrics, the chunked watch stream, and
batch/v1 job creation incl. prebuilt-workload binding — the reference's
apiserver-facing behaviors (pkg/visibility/server.go, webhooks, metrics
endpoint) on one listener.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    Workload,
)
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.controllers.store import (
    KIND_CLUSTER_QUEUE,
    KIND_LOCAL_QUEUE,
    KIND_RESOURCE_FLAVOR,
    Store,
    StoreAdapter,
)
from kueue_tpu.controllers.visibility import VisibilityServer
from kueue_tpu.server import APIServer


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read())


def _post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())


def _delete(url):
    req = urllib.request.Request(url, method="DELETE")
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())


@pytest.fixture()
def served():
    fw = Framework()
    store = Store()
    adapter = StoreAdapter(store, fw)
    server = APIServer(store, fw,
                       visibility=VisibilityServer(
                           fw.queues, explain=fw.scheduler.explain),
                       sync_status=adapter.sync_status).start()
    store.create(KIND_RESOURCE_FLAVOR, ResourceFlavor.make("default"))
    store.create(KIND_CLUSTER_QUEUE, ClusterQueue(
        name="cq",
        resource_groups=(ResourceGroup(
            covered_resources=("cpu",),
            flavors=(FlavorQuotas.make("default", cpu=4),)),)))
    store.create(KIND_LOCAL_QUEUE, LocalQueue(
        name="main", namespace="default", cluster_queue="cq"))
    try:
        yield server, fw, store, adapter
    finally:
        server.stop()


WL_DOC = {
    "apiVersion": "kueue.x-k8s.io/v1beta1", "kind": "Workload",
    "metadata": {"name": "wl1", "namespace": "default"},
    "spec": {"queueName": "main", "podSets": [
        {"name": "main", "count": 2, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "1"}}}]}}}]},
}


class TestObjectAPI:
    def test_health_and_metrics(self, served):
        server, *_ = served
        with urllib.request.urlopen(server.url + "/healthz") as resp:
            assert resp.read() == b"ok"
        with urllib.request.urlopen(server.url + "/metrics") as resp:
            text = resp.read().decode()
        assert "kueue_pending_workloads" in text

    def test_crud_and_schedule(self, served):
        server, fw, store, adapter = served
        base = server.url + "/apis/kueue.x-k8s.io/v1beta1"
        created = _post(base + "/namespaces/default/workloads", WL_DOC)
        assert created["metadata"]["name"] == "wl1"

        adapter.tick()
        doc = _get(base + "/namespaces/default/workloads/wl1")
        conds = {c["type"]: c["status"] for c in doc["status"]["conditions"]}
        assert conds["Admitted"] == "True"
        adm = doc["status"]["admission"]
        assert adm["clusterQueue"] == "cq"
        assert adm["podSetAssignments"][0]["flavors"] == {"cpu": "default"}

        listing = _get(base + "/workloads")
        assert [i["metadata"]["name"] for i in listing["items"]] == ["wl1"]

        _delete(base + "/namespaces/default/workloads/wl1")
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(base + "/namespaces/default/workloads/wl1")
        assert err.value.code == 404
        assert "default/wl1" not in fw.workloads

    def test_webhook_rejection_is_422(self, served):
        server, *_ = served
        bad = json.loads(json.dumps(WL_DOC))
        bad["metadata"]["name"] = "bad"
        bad["spec"]["podSets"] = []  # workload must have 1..8 podsets
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(server.url + "/apis/kueue.x-k8s.io/v1beta1"
                  "/namespaces/default/workloads", bad)
        assert err.value.code == 422

    def test_duplicate_create_is_409(self, served):
        server, *_ = served
        base = server.url + "/apis/kueue.x-k8s.io/v1beta1"
        _post(base + "/namespaces/default/workloads", WL_DOC)
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(base + "/namespaces/default/workloads", WL_DOC)
        assert err.value.code == 409

    def test_label_selector_filtering(self, served):
        server, *_ = served
        base = server.url + "/apis/kueue.x-k8s.io/v1beta1"
        labeled = json.loads(json.dumps(WL_DOC))
        labeled["metadata"]["name"] = "labeled"
        labeled["metadata"]["labels"] = {"origin": "mk"}
        _post(base + "/namespaces/default/workloads", WL_DOC)
        _post(base + "/namespaces/default/workloads", labeled)
        listing = _get(base + "/workloads?labelSelector=origin%3Dmk")
        assert [i["metadata"]["name"] for i in listing["items"]] == ["labeled"]

    def test_visibility_pending_workloads(self, served):
        server, fw, store, adapter = served
        base = server.url + "/apis/kueue.x-k8s.io/v1beta1"
        # 4 cpu quota; each workload wants 2 -> third stays pending.
        for i in range(3):
            doc = json.loads(json.dumps(WL_DOC))
            doc["metadata"]["name"] = f"wl{i}"
            _post(base + "/namespaces/default/workloads", doc)
        adapter.tick()
        adapter.tick()
        summary = _get(server.url
                       + "/apis/visibility.kueue.x-k8s.io/v1alpha1"
                       "/clusterqueues/cq/pendingworkloads")
        assert [i["name"] for i in summary["items"]] == ["wl2"]
        assert summary["items"][0]["positionInClusterQueue"] == 0
        by_lq = _get(server.url
                     + "/apis/visibility.kueue.x-k8s.io/v1alpha1"
                     "/namespaces/default/localqueues/main/pendingworkloads")
        assert [i["name"] for i in by_lq["items"]] == ["wl2"]

    def test_visibility_explain_decisions(self, served):
        """?explain=true attaches the per-workload admission story: every
        flavor tried with its verdict and the final reason (admission
        explainability, the visibility half)."""
        server, fw, store, adapter = served
        base = server.url + "/apis/kueue.x-k8s.io/v1beta1"
        for i in range(3):
            doc = json.loads(json.dumps(WL_DOC))
            doc["metadata"]["name"] = f"wl{i}"
            _post(base + "/namespaces/default/workloads", doc)
        # One head per CQ per tick: the third tick nominates wl2 against
        # a full CQ and parks it with its decision record.
        for _ in range(3):
            adapter.tick()
        vis = (server.url + "/apis/visibility.kueue.x-k8s.io/v1alpha1"
               "/clusterqueues/cq/pendingworkloads")
        plain = _get(vis)
        assert "decisions" not in plain["items"][0]
        summary = _get(vis + "?explain=true")
        [item] = summary["items"]
        assert item["name"] == "wl2"
        decisions = item["decisions"]
        assert decisions, "explain=true must return the decision history"
        last = decisions[-1]
        assert last["outcome"] == "Inadmissible"
        assert last["clusterQueue"] == "cq"
        assert "insufficient unused quota" in last["reason"]
        # The first attempt nominated the default flavor before losing
        # the cycle: the story names the flavor WITH a verdict.
        assert any(f["flavor"] == "default" and f["verdict"]
                   for d in decisions for f in d["flavors"]) \
            or all(d["outcome"] == "Inadmissible" for d in decisions)

    def test_debug_traces_endpoint(self, served):
        """GET /debug/traces returns Chrome trace-event JSON of the
        retained ticks, schema-valid for Perfetto."""
        from kueue_tpu.tracing import TRACER, validate_chrome_trace

        server, fw, store, adapter = served
        TRACER.configure(enabled=True)
        TRACER.reset()
        try:
            _post(server.url + "/apis/kueue.x-k8s.io/v1beta1"
                  "/namespaces/default/workloads", WL_DOC)
            adapter.tick()
            doc = _get(server.url + "/debug/traces")
            assert validate_chrome_trace(doc) == []
            names = {ev["name"] for ev in doc["traceEvents"]}
            assert {"tick", "snapshot", "admit", "requeue"} <= names
            assert doc["otherData"]["ticks_retained"] >= 1
            slow = _get(server.url + "/debug/traces?slowest=true")
            assert validate_chrome_trace(slow) == []
            assert {ev.get("args", {}).get("tick")
                    for ev in slow["traceEvents"]
                    if ev["ph"] == "X"} == {TRACER.slowest_tick().seq}
        finally:
            TRACER.configure(enabled=False)
            TRACER.reset()

    def test_finish_endpoint(self, served):
        server, fw, store, adapter = served
        base = server.url + "/apis/kueue.x-k8s.io/v1beta1"
        _post(base + "/namespaces/default/workloads", WL_DOC)
        adapter.tick()
        _post(base + "/namespaces/default/workloads/wl1/finish", {})
        doc = _get(base + "/namespaces/default/workloads/wl1")
        conds = {c["type"]: c["status"] for c in doc["status"]["conditions"]}
        assert conds["Finished"] == "True"


class TestJobsAPI:
    JOB_DOC = {
        "apiVersion": "batch/v1", "kind": "Job",
        "metadata": {"name": "j1", "namespace": "default",
                     "labels": {"kueue.x-k8s.io/queue-name": "main"}},
        "spec": {"parallelism": 2, "completions": 2,
                 "template": {"spec": {"containers": [
                     {"name": "c",
                      "resources": {"requests": {"cpu": "1"}}}]}}},
    }

    def test_job_create_schedule_complete(self, served):
        server, fw, store, adapter = served
        _post(server.url + "/apis/batch/v1/namespaces/default/jobs",
              self.JOB_DOC)
        adapter.tick()
        doc = _get(server.url + "/apis/batch/v1/namespaces/default/jobs/j1")
        assert doc["spec"]["suspend"] is False
        _post(server.url
              + "/apis/batch/v1/namespaces/default/jobs/j1/complete", {})
        doc = _get(server.url + "/apis/batch/v1/namespaces/default/jobs/j1")
        assert doc["status"]["succeeded"] == 2
        wl = fw.workloads[doc["workloadKey"]]
        assert wl.is_finished

    def test_prebuilt_workload_binding(self, served):
        """A job posted with the prebuilt-workload-name label binds to the
        existing workload instead of creating a second one (the MultiKueue
        worker-side contract)."""
        server, fw, store, adapter = served
        base = server.url + "/apis/kueue.x-k8s.io/v1beta1"
        _post(base + "/namespaces/default/workloads", WL_DOC)
        job = json.loads(json.dumps(self.JOB_DOC))
        job["metadata"]["labels"]["kueue.x-k8s.io/prebuilt-workload-name"] = \
            "wl1"
        _post(server.url + "/apis/batch/v1/namespaces/default/jobs", job)
        assert len(fw.workloads) == 1
        assert fw.job_reconciler.jobs["default/j1"][1] == "default/wl1"

    def test_prebuilt_missing_is_404(self, served):
        server, *_ = served
        job = json.loads(json.dumps(self.JOB_DOC))
        job["metadata"]["labels"]["kueue.x-k8s.io/prebuilt-workload-name"] = \
            "ghost"
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(server.url + "/apis/batch/v1/namespaces/default/jobs", job)
        assert err.value.code == 404


class TestWatch:
    def test_watch_streams_initial_and_live_events(self, served):
        server, fw, store, adapter = served
        base = server.url + "/apis/kueue.x-k8s.io/v1beta1"
        _post(base + "/namespaces/default/workloads", WL_DOC)

        events = []
        ready = threading.Event()

        def consume():
            req = urllib.request.Request(base + "/watch/workloads")
            with urllib.request.urlopen(req, timeout=10) as resp:
                for raw in resp:
                    line = raw.strip()
                    if not line:
                        continue
                    events.append(json.loads(line))
                    ready.set()
                    if len(events) >= 3:
                        return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        assert ready.wait(5), "no initial replay event"
        assert events[0]["type"] == "ADDED"
        assert events[0]["object"]["metadata"]["name"] == "wl1"

        adapter.tick()  # admission -> status sync -> MODIFIED event
        t.join(timeout=5)
        assert len(events) >= 3
        # End-of-replay bookmark separates the ADDED replay from live
        # events (clients stage the replay until they see it).
        assert events[1]["type"] == "BOOKMARK"
        assert events[2]["type"] == "MODIFIED"
        conds = {c["type"]: c["status"]
                 for c in events[2]["object"]["status"]["conditions"]}
        assert conds["Admitted"] == "True"


class TestLocalQueueStatus:
    def test_lq_get_reports_usage_and_counts(self, served):
        """LocalQueue GET carries the reconciler-maintained status
        (cache.go:607-658: reserving/admitted counts, flavor usage,
        pending count)."""
        server, fw, store, adapter = served
        _post(server.url + "/apis/kueue.x-k8s.io/v1beta1"
              "/namespaces/default/workloads", WL_DOC)
        adapter.tick()
        doc = _get(server.url + "/apis/kueue.x-k8s.io/v1beta1"
                   "/namespaces/default/localqueues/main")
        status = doc["status"]
        assert status["reservingWorkloads"] == 1
        assert status["admittedWorkloads"] == 1
        assert status["pendingWorkloads"] == 0
        assert status["flavorUsage"]["default"]["cpu"] > 0
