"""Differential goldens for the incremental host pipeline (WorkloadArena).

Drives 200 randomized ticks of add/admit/preempt/delete churn through the
REAL Framework twice — once with the persistent workload tensor arena
(the incremental encode), once with the from-scratch `encode_workloads`
path — and asserts the two produce IDENTICAL admission decisions tick by
tick. The arena run additionally executes with `debug_verify` on, so
every gather is tensor-compared against a from-scratch encode in-line:
one scenario pins both halves of the contract ("identical tensors" and
"identical decisions").

The decision comparison is parametrized over every registered
victim-search engine (solver/modes.ENGINES), mapped onto the scheduler's
`preemption_engine` knob — host referee, lax.scan, Pallas-interpret, and
the batched native/XLA engines all replay the same stream.
"""

import random

import pytest

from kueue_tpu.api.types import ClusterQueuePreemption, PodSet, Workload
from kueue_tpu.config import Configuration, TPUSolverConfig
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.models.flavor_fit import BatchSolver
from kueue_tpu.solver import modes as _modes
from kueue_tpu.solver import schema as sch

from tests.util import fq, make_cq, make_flavor, make_lq, rg

TICKS = 200

# Registered engine -> the scheduler's preemption_engine knob. The
# coverage meta-test pins the registry; this map must name every entry
# (test_registry_covered below fails when a new engine lands unmapped).
_ENGINE_KNOB = {
    "host": None,
    "scan-jax": "jax",
    "scan-pallas": "pallas",
    "batch-native": "native",
    "batch-jax": "jax",
}

_KNOBS = []
for _spec in _modes.ENGINES:
    if _spec.optional_import and not _modes.engine_importable(_spec):
        continue
    knob = _ENGINE_KNOB[_spec.name]
    if knob not in _KNOBS:
        _KNOBS.append(knob)


def test_registry_covered():
    assert set(_ENGINE_KNOB) == {e.name for e in _modes.ENGINES}, \
        "new victim-search engine registered; map it onto a " \
        "preemption_engine knob here so the arena differential runs it"


def build(incremental: bool, engine):
    """`incremental` toggles ALL the cross-tick fast paths at once: the
    pending workload arena, the admitted-set arena (mirror flush + victim
    rows), and the fingerprinted nominate cache — exactly what the two
    kill switches (KUEUE_TPU_NO_ADMIT_ARENA / KUEUE_TPU_NO_NOMINATE_CACHE
    plus KUEUE_TPU_NO_ARENA) restore in production."""
    cfg = Configuration(tpu_solver=TPUSolverConfig(
        preemption_engine="host" if engine is None else engine))
    fw = Framework(batch_solver=BatchSolver(
        use_arena=incremental, use_admit_arena=incremental,
        use_nominate_cache=incremental), config=cfg)
    fw.create_namespace("default", labels={})
    fw.create_resource_flavor(make_flavor("on-demand", zone="a"))
    fw.create_resource_flavor(make_flavor("spot", zone="b"))
    for i in range(4):
        fw.create_cluster_queue(make_cq(
            f"cq-{i}",
            rg("cpu", fq("on-demand", cpu=(16, 16)), fq("spot", cpu=(8, 8))),
            cohort=f"cohort-{i % 2}",
            preemption=ClusterQueuePreemption(
                within_cluster_queue="LowerPriority",
                reclaim_within_cohort="Any")))
        fw.create_local_queue(make_lq(f"lq-{i}", "default", cq=f"cq-{i}"))
    return fw


def drive(incremental: bool, engine, ticks: int = TICKS):
    """Run the seeded churn stream; returns the per-tick decision trail."""
    fw = build(incremental, engine)
    rnd = random.Random(1234)
    seq = [0]
    pending: dict = {}
    admitted: dict = {}
    trail = []

    orig_admit = fw.scheduler.apply_admission
    orig_preempt = fw.scheduler.apply_preemption
    tick_admitted: list = []
    tick_preempted: list = []

    def apply_admission(wl):
        ok = orig_admit(wl)
        if ok:
            tick_admitted.append(wl.key)
            admitted[wl.key] = wl
            pending.pop(wl.key, None)
        return ok

    def apply_preemption(wl, msg):
        tick_preempted.append(wl.key)
        return orig_preempt(wl, msg)

    fw.scheduler.apply_admission = apply_admission
    fw.scheduler.apply_preemption = apply_preemption

    def submit_one():
        seq[0] += 1
        i = seq[0]
        sel = {"zone": rnd.choice(["a", "b"])} if i % 5 == 0 else None
        n_ps = 2 if i % 7 == 0 else 1
        wl = Workload(
            name=f"wl-{i}", namespace="default",
            queue_name=f"lq-{rnd.randrange(4)}",
            priority=rnd.randint(-2, 3),
            creation_time=float(1000 + i),
            pod_sets=[PodSet.make(f"ps{p}", count=rnd.randint(1, 3),
                                  cpu=rnd.randint(1, 4),
                                  node_selector=sel)
                      for p in range(n_ps)])
        pending[wl.key] = wl
        fw.submit(wl)

    for _ in range(40):
        submit_one()

    for tick in range(ticks):
        tick_admitted.clear()
        tick_preempted.clear()
        fw.tick()
        trail.append((tuple(sorted(tick_admitted)),
                      tuple(sorted(tick_preempted))))
        # Churn: arrivals, pending deletes, admitted finishes — seeded,
        # so identical decisions keep the two streams identical.
        for _ in range(rnd.randint(0, 3)):
            submit_one()
        if pending and rnd.random() < 0.3:
            key = rnd.choice(sorted(pending))
            wl = pending.pop(key)
            if not wl.is_admitted:
                fw.delete_workload(wl)
            else:
                pending.pop(key, None)
        done = [k for k, w in sorted(admitted.items())
                if w.is_admitted and not w.is_finished]
        for key in done[:rnd.randint(0, 4)]:
            wl = admitted.pop(key)
            fw.finish(wl)
            fw.delete_workload(wl)
        # Preempted (evicted) workloads requeue through the reconcile
        # pass; drop them from the admitted set so churn never finishes
        # an evicted workload.
        for key in list(admitted):
            if not admitted[key].is_admitted:
                wl = admitted.pop(key)
                if not wl.is_finished:
                    pending[key] = wl
        fw.prewarm_idle()

    trail.append(("pending", sum(fw.queues.pending(f"cq-{i}")
                                 for i in range(4))))
    return trail


@pytest.mark.parametrize("engine", _KNOBS,
                         ids=[str(k) for k in _KNOBS])
def test_incremental_vs_fullrebuild_decisions_identical(engine,
                                                        monkeypatch):
    # The incremental run verifies EVERY workload-arena gather against a
    # from-scratch encode (tensor identity) AND the admitted arena
    # against the cache dicts on every mirror flush, and the decision
    # trails — workload arena + admitted arena + nominate cache all ON
    # vs ALL off (the kill-switch path) — must match byte for byte
    # across 200 randomized churn ticks.
    monkeypatch.setattr(sch.WorkloadArena, "debug_verify", True)
    monkeypatch.setattr(sch.AdmittedArena, "debug_verify", True)
    # Force the CSR commit + arena mirror-flush (auto mode prefers the
    # native ledger walks when the toolchain built them) so the
    # differential always covers the aggregated paths.
    monkeypatch.setenv("KUEUE_TPU_CSR_ASSUME", "1")
    monkeypatch.setenv("KUEUE_TPU_ARENA_FLUSH", "1")
    with_arena = drive(True, engine)
    monkeypatch.setattr(sch.WorkloadArena, "debug_verify", False)
    monkeypatch.setattr(sch.AdmittedArena, "debug_verify", False)
    monkeypatch.setenv("KUEUE_TPU_CSR_ASSUME", "0")
    monkeypatch.delenv("KUEUE_TPU_ARENA_FLUSH")
    without = drive(False, engine)
    assert with_arena == without


def test_arena_reuses_rows_across_ticks():
    """Steady-state gathers are row reuse, not re-encodes (the >0.9
    reuse contract the bench gates on, pinned at test scale)."""
    fw = build(True, None)
    rnd = random.Random(7)
    for i in range(60):
        fw.submit(Workload(
            name=f"w-{i}", namespace="default",
            queue_name=f"lq-{rnd.randrange(4)}",
            priority=rnd.randint(-2, 3), creation_time=float(i),
            pod_sets=[PodSet.make("ps0", count=1, cpu=1)]))
    for _ in range(12):
        fw.tick()
    solver = fw.scheduler.batch_solver
    reused0, missed0 = solver.arena_rows_reused, solver.arena_rows_missed
    for _ in range(10):
        fw.tick()
    reused = solver.arena_rows_reused - reused0
    missed = solver.arena_rows_missed - missed0
    assert reused > 0
    assert reused / max(reused + missed, 1) > 0.9
    assert solver.arena_full_rebuilds == 1  # the initial build only


def test_quiescent_tick_zero_encode_and_solve_work():
    """When no dirty events arrive between ticks, every head replays its
    fingerprint-cached verdict: no gather, no device dispatch, no decode
    — the 'nothing-changed ticks cost nothing' contract. StrictFIFO
    keeps the NoFit heads re-popping every tick (BestEffortFIFO would
    park them, which trivially empties the tick)."""

    fw = Framework(batch_solver=BatchSolver())
    fw.create_namespace("default", labels={})
    fw.create_resource_flavor(make_flavor("on-demand"))
    for i in range(3):
        fw.create_cluster_queue(make_cq(
            f"cq-{i}", rg("cpu", fq("on-demand", cpu=4)),
            strategy="StrictFIFO"))
        fw.create_local_queue(make_lq(f"lq-{i}", "default", cq=f"cq-{i}"))
    # One admissible head per CQ fills the quota; the rest stay NoFit
    # forever (nothing releases quota).
    for i in range(3):
        for j in range(3):
            fw.submit(Workload(
                name=f"w-{i}-{j}", namespace="default",
                queue_name=f"lq-{i}", priority=0,
                creation_time=float(10 * i + j),
                pod_sets=[PodSet.make("ps0", count=1, cpu=4)]))
    solver = fw.scheduler.batch_solver
    for _ in range(12):
        fw.tick()
    # Steady state reached: the same NoFit heads re-pop with unchanged
    # fingerprints — further ticks must do ZERO encode/solve work.
    d0 = solver.dispatches
    reused0 = solver.arena_rows_reused
    missed0 = solver.arena_rows_missed
    hits0 = solver.nominate_cache_hits
    for _ in range(5):
        fw.tick()
    assert solver.dispatches == d0, "quiescent tick dispatched a solve"
    assert solver.arena_rows_reused == reused0
    assert solver.arena_rows_missed == missed0, \
        "quiescent tick re-encoded arena rows"
    assert solver.nominate_cache_hits - hits0 == 5 * 3
    # The scheduler-side fast path engaged too: sort/admit/requeue
    # bookkeeping replayed instead of recomputing.
    assert fw.scheduler.metrics.quiescent_ticks > 0
    # The backlog is still live: releasing quota un-quiesces the system
    # and the next head admits (the cache replays only while its
    # fingerprint holds).
    victim = fw.workloads["default/w-0-0"]
    fw.finish(victim)
    fw.delete_workload(victim)
    fw.run_until_settled()
    assert "default/w-0-1" in fw.admitted_workloads("cq-0")


def test_quiescent_fast_path_decisions_identical(monkeypatch):
    """The quiescent-tick replay (sort-order reuse, admit-cycle outcome
    replay, requeue condition-write skip) must be decision-invisible:
    the same churn stream with KUEUE_TPU_NO_QUIET_TICK=1 produces the
    identical trail."""
    monkeypatch.setenv("KUEUE_TPU_NO_QUIET_TICK", "1")
    without = drive(True, None, ticks=120)
    monkeypatch.delenv("KUEUE_TPU_NO_QUIET_TICK")
    with_quiet = drive(True, None, ticks=120)
    assert with_quiet == without


def test_arena_full_rebuild_on_structure_change():
    """A structural mutation (new CQ) rotates the encoding and rebuilds
    the arena; decisions keep flowing and rows re-seed."""
    fw = build(True, None)
    for i in range(10):
        fw.submit(Workload(
            name=f"w-{i}", namespace="default", queue_name="lq-0",
            priority=0, creation_time=float(i),
            pod_sets=[PodSet.make("ps0", count=1, cpu=1)]))
    fw.tick()
    solver = fw.scheduler.batch_solver
    assert solver.arena_full_rebuilds == 1
    fw.create_cluster_queue(make_cq(
        "cq-new", rg("cpu", fq("on-demand", cpu=4))))
    fw.create_local_queue(make_lq("lq-new", "default", cq="cq-new"))
    fw.submit(Workload(name="nw", namespace="default", queue_name="lq-new",
                       priority=0, creation_time=99.0,
                       pod_sets=[PodSet.make("ps0", count=1, cpu=1)]))
    fw.tick()
    assert solver.arena_full_rebuilds == 2
    assert solver.arena_rows_encoded > 0
