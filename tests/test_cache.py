from kueue_tpu import features
from kueue_tpu.api.types import (Admission, FlavorQuotas,
                                 PodSetAssignment, ResourceQuota)
from kueue_tpu.core.cache import Cache

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg


def admit(wl, cq_name, flavor, admitted=True):
    wl.admission = Admission(
        cluster_queue=cq_name,
        pod_set_assignments=[
            PodSetAssignment(
                name=ps.name,
                flavors={r: flavor for r in ps.requests},
                resource_usage={r: v * ps.count for r, v in ps.requests.items()},
                count=ps.count,
            ) for ps in wl.pod_sets
        ])
    wl.set_condition("QuotaReserved", True)
    if admitted:
        wl.set_condition("Admitted", True)
    return wl


def build_cache():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq-a", rg(("cpu", "memory"), fq("default", cpu=10, memory="10Gi")),
        cohort="co"))
    cache.add_cluster_queue(make_cq(
        "cq-b", rg(("cpu", "memory"), fq("default", cpu=5, memory="5Gi")),
        cohort="co"))
    cache.add_local_queue(make_lq("main", cq="cq-a"))
    return cache


def test_usage_accounting():
    cache = build_cache()
    wl = admit(make_wl("w1", cpu=2, memory="1Gi"), "cq-a", "default")
    assert cache.add_or_update_workload(wl)
    assert cache.usage("cq-a")["default"]["cpu"] == 2000
    assert cache.usage("cq-a")["default"]["memory"] == 1024**3
    cache.delete_workload(wl)
    assert cache.usage("cq-a")["default"]["cpu"] == 0


def test_assume_and_forget():
    cache = build_cache()
    wl = admit(make_wl("w1", cpu=2), "cq-a", "default")
    cache.assume_workload(wl)
    assert cache.is_assumed_or_admitted(wl)
    assert cache.usage("cq-a")["default"]["cpu"] == 2000
    cache.forget_workload(wl)
    assert not cache.is_assumed_or_admitted(wl)
    assert cache.usage("cq-a")["default"]["cpu"] == 0


def test_snapshot_cohort_aggregation():
    cache = build_cache()
    wl = admit(make_wl("w1", cpu=2), "cq-a", "default")
    cache.add_or_update_workload(wl)
    snap = cache.snapshot()
    cqa = snap.cluster_queues["cq-a"]
    assert cqa.cohort is not None
    # Cohort requestable = 10 + 5 CPUs.
    assert cqa.cohort.requestable_resources["default"]["cpu"] == 15000
    assert cqa.cohort.usage["default"]["cpu"] == 2000
    assert cqa.requestable_cohort_quota("default", "cpu") == 15000
    assert cqa.used_cohort_quota("default", "cpu") == 2000


def test_snapshot_isolated_from_cache():
    cache = build_cache()
    snap = cache.snapshot()
    wl = admit(make_wl("w1", cpu=2), "cq-a", "default")
    cache.add_or_update_workload(wl)
    assert snap.cluster_queues["cq-a"].usage["default"]["cpu"] == 0


def test_snapshot_remove_add_workload_roundtrip():
    cache = build_cache()
    wl = admit(make_wl("w1", cpu=2), "cq-a", "default")
    cache.add_or_update_workload(wl)
    snap = cache.snapshot()
    cqa = snap.cluster_queues["cq-a"]
    wi = cqa.workloads[wl.key]
    snap.remove_workload(wi)
    assert cqa.usage["default"]["cpu"] == 0
    assert cqa.cohort.usage["default"]["cpu"] == 0
    snap.add_workload(wi)
    assert cqa.usage["default"]["cpu"] == 2000
    assert cqa.cohort.usage["default"]["cpu"] == 2000


def test_lending_limit_guaranteed_quota():
    features.set_enabled(features.LENDING_LIMIT, True)
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    # cq-a lends at most 4 of its 10 CPUs; 6 are guaranteed.
    cache.add_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("default", cpu=(10, None, 4))), cohort="co"))
    cache.add_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("default", cpu=5)), cohort="co"))
    snap = cache.snapshot()
    cqa = snap.cluster_queues["cq-a"]
    cqb = snap.cluster_queues["cq-b"]
    # Cohort requestable counts cq-a's lending limit (4), not nominal (10).
    assert cqa.cohort.requestable_resources["default"]["cpu"] == 4000 + 5000
    # From cq-a's view: lendable pool + own guaranteed 6.
    assert cqa.requestable_cohort_quota("default", "cpu") == 9000 + 6000
    # From cq-b's view: no guaranteed quota of its own.
    assert cqb.requestable_cohort_quota("default", "cpu") == 9000


def test_lending_limit_cohort_usage():
    features.set_enabled(features.LENDING_LIMIT, True)
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("default", cpu=(10, None, 4))), cohort="co"))
    cache.add_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("default", cpu=5)), cohort="co"))
    cache.add_local_queue(make_lq("main", cq="cq-a"))
    # Usage of 8 CPUs: 6 guaranteed + 2 above.
    wl = admit(make_wl("w1", cpu=8), "cq-a", "default")
    cache.add_or_update_workload(wl)
    snap = cache.snapshot()
    cqa = snap.cluster_queues["cq-a"]
    # Cohort usage only tracks what exceeds guaranteed: 8 - 6 = 2.
    assert cqa.cohort.usage["default"]["cpu"] == 2000
    # cq-a's own used-cohort view adds min(usage, guaranteed) = 6.
    assert cqa.used_cohort_quota("default", "cpu") == 8000
    cqb = snap.cluster_queues["cq-b"]
    assert cqb.used_cohort_quota("default", "cpu") == 2000


def test_local_queue_status_incremental():
    """Per-LQ stats stay exact across assume -> admitted-flip -> release
    (the keyed admitted split of Cache._lq_apply)."""
    from tests.util import fq, make_cq, make_flavor, make_lq

    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq("cq", rg("cpu", fq("default", cpu=8))))
    cache.add_local_queue(make_lq("main", cq="cq"))

    wl = make_wl("w", "main", cpu=2)
    wl.admission = Admission(
        cluster_queue="cq",
        pod_set_assignments=[PodSetAssignment(
            name="main", flavors={"cpu": "default"},
            resource_usage={"cpu": 2000}, count=1)])
    wl.set_condition("QuotaReserved", True)
    cache.assume_workload(wl)          # reserved, NOT admitted yet
    st = cache.local_queue_status("default/main")
    assert st["reservingWorkloads"] == 1 and st["admittedWorkloads"] == 0
    assert st["flavorsReservation"] == {"default": {"cpu": 2000}}
    assert st["flavorUsage"] == {}

    # Admitted flips AFTER accounting; the release must still subtract
    # exactly what was added (no negative admitted counts).
    wl.set_condition("Admitted", True)
    assert cache.delete_workload(wl) is not None
    st = cache.local_queue_status("default/main")
    assert st["reservingWorkloads"] == 0 and st["admittedWorkloads"] == 0
    assert st["flavorsReservation"] == {"default": {"cpu": 0}}

    # Late-created LQ adopts existing accounted workloads.
    wl2 = make_wl("w2", "late", cpu=1)
    wl2.admission = Admission(
        cluster_queue="cq",
        pod_set_assignments=[PodSetAssignment(
            name="main", flavors={"cpu": "default"},
            resource_usage={"cpu": 1000}, count=1)])
    wl2.set_condition("QuotaReserved", True)
    wl2.set_condition("Admitted", True)
    cache.add_or_update_workload(wl2)
    cache.add_local_queue(make_lq("late", cq="cq"))
    st = cache.local_queue_status("default/late")
    assert st["reservingWorkloads"] == 1 and st["admittedWorkloads"] == 1


def test_lq_stats_released_on_cluster_queue_delete():
    """Deleting a ClusterQueue releases its accounted workloads from the
    per-LQ stats — a later delete_workload can no longer find the CQ to
    subtract them (cache.go:607-658 recomputes from the live cache)."""
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq("cq", rg("cpu", fq("default", cpu=8))))
    cache.add_local_queue(make_lq("main", cq="cq"))

    wl = admit(make_wl("w", "main", cpu=2), "cq", "default")
    cache.add_or_update_workload(wl)
    st = cache.local_queue_status("default/main")
    assert st["reservingWorkloads"] == 1 and st["admittedWorkloads"] == 1

    cache.delete_cluster_queue("cq")
    st = cache.local_queue_status("default/main")
    assert st["reservingWorkloads"] == 0 and st["admittedWorkloads"] == 0
    assert st["flavorsReservation"] == {"default": {"cpu": 0}}

    # The (now CQ-less) workload delete must not double-subtract.
    cache.delete_workload(wl)
    st = cache.local_queue_status("default/main")
    assert st["reservingWorkloads"] == 0 and st["admittedWorkloads"] == 0


def test_lq_stats_survive_delete_recreate_to_new_cq():
    """A LocalQueue deleted and recreated against a DIFFERENT ClusterQueue
    must not count (or release) workloads accounted in the old CQ — adds
    and subtracts apply the same owning-CQ filter, so stats never go
    negative."""
    from tests.util import make_lq

    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq("cq-old", rg("cpu", fq("default", cpu=8))))
    cache.add_cluster_queue(make_cq("cq-new", rg("cpu", fq("default", cpu=8))))
    cache.add_local_queue(make_lq("main", cq="cq-old"))

    wl = admit(make_wl("w", "main", cpu=2), "cq-old", "default")
    cache.add_or_update_workload(wl)
    assert cache.local_queue_status("default/main")["reservingWorkloads"] == 1

    lq_old = cache.local_queues["default/main"]
    cache.delete_local_queue(lq_old)
    cache.add_local_queue(make_lq("main", cq="cq-new"))
    st = cache.local_queue_status("default/main")
    assert st["reservingWorkloads"] == 0

    # The old-CQ workload releasing must not drive the new stats negative.
    cache.delete_workload(wl)
    st = cache.local_queue_status("default/main")
    assert st["reservingWorkloads"] == 0 and st["admittedWorkloads"] == 0


def test_fit_in_cohort_fused_matches_split_path():
    """The admission cycle's fused cohort gate must agree with the
    three-step reference path (_has_common_flavor_resources +
    _common_usage_sum + fit_in_cohort) on randomized cycle/assignment
    usage — with and without LendingLimit quota splits. Pins the
    hand-inlined quota arithmetic of fit_in_cohort_fused to the shared
    helpers it duplicates."""
    import random

    from kueue_tpu.scheduler.scheduler import (
        _common_usage_sum,
        _has_common_flavor_resources,
    )

    rnd = random.Random(7)
    flavors = ["f0", "f1", "f2"]
    resources = ["cpu", "memory"]

    for lending in (False, True):
        features.set_enabled("LendingLimit", lending)
        for trial in range(200):
            cache = Cache()
            for f in flavors:
                cache.add_or_update_resource_flavor(make_flavor(f))
            for c in range(3):
                quotas = []
                for f in flavors:
                    kw = {r: rnd.randint(1, 8) for r in resources}
                    q = fq(f, **kw)
                    if lending and rnd.random() < 0.5:
                        q = FlavorQuotas(name=f, resources=tuple(
                            (rn, ResourceQuota(
                                nominal=rq.nominal,
                                lending_limit=rnd.randint(
                                    0, rq.nominal // resource_scale(rn))
                                * resource_scale(rn)))
                            for rn, rq in q.resources))
                    quotas.append(q)
                cache.add_cluster_queue(make_cq(
                    f"cq-{c}", rg(tuple(resources), *quotas), cohort="pool"))
            snap = cache.snapshot()
            cq = snap.cluster_queues["cq-0"]
            # Random admitted usage on cq-0 so the lending min() path sees
            # nonzero own usage.
            for f in flavors:
                for r in resources:
                    if rnd.random() < 0.5:
                        cq.usage.setdefault(f, {})[r] = \
                            rnd.randint(0, 6) * resource_scale(r)

            def rand_frq(p=0.5):
                out = {}
                for f in flavors:
                    for r in resources:
                        if rnd.random() < p:
                            out.setdefault(f, {})[r] = \
                                rnd.randint(0, 5) * resource_scale(r)
                return out

            cycle = rand_frq()
            assignment = rand_frq(0.7)
            if not assignment:
                continue

            common_ref = _has_common_flavor_resources(cycle, assignment)
            fits_ref = True
            if common_ref:
                fits_ref = cq.fit_in_cohort(
                    _common_usage_sum(cycle, assignment))
            common, fits = cq.fit_in_cohort_fused(cycle, assignment, lending)
            assert common == common_ref, (trial, lending, cycle, assignment)
            if common:
                assert fits == fits_ref, (trial, lending, cycle, assignment)


def resource_scale(r):
    return 1000 if r == "cpu" else 1


def test_flush_mirror_native_matches_python(monkeypatch):
    """The native SnapshotMirror flush (ledger.cpp flush_mirror) must leave
    the mirrored snapshot byte-identical to the Python loop over the same
    randomized admission/removal stream."""
    import random

    from kueue_tpu.api.types import PodSet, Workload
    from kueue_tpu.core import snapshot as snapshot_mod
    from kueue_tpu.core.snapshot import SnapshotMirror
    from kueue_tpu.core.workload import WorkloadInfo

    if snapshot_mod._ledger is None:
        import pytest as _pytest
        _pytest.skip("native ledger unavailable")

    def build_cache():
        cache = Cache()
        cache.add_or_update_resource_flavor(make_flavor("default"))
        for c in range(4):
            cache.add_cluster_queue(make_cq(
                f"cq-{c}", rg(("cpu", "memory"),
                              fq("default", cpu=64, memory="64Gi")),
                cohort="pool" if c % 2 else ""))
            cache.add_local_queue(make_lq(f"lq-{c}", cq=f"cq-{c}"))
        return cache

    def run(native: bool):
        if not native:
            monkeypatch.setattr(snapshot_mod, "_ledger", None)
        cache = build_cache()
        mirror = SnapshotMirror(cache)
        mirror.refresh()
        rnd = random.Random(11)
        live = []
        for step in range(300):
            if live and rnd.random() < 0.4:
                wl, wi = live.pop(rnd.randrange(len(live)))
                cache.delete_workload(wl)
                mirror.note_removal(wl)
            else:
                i = len(live) + step
                c = rnd.randrange(4)
                wl = Workload(
                    name=f"w{step}-{i}", queue_name=f"lq-{c}",
                    creation_time=float(step),
                    pod_sets=[PodSet.make("m", rnd.randint(1, 3),
                                          cpu=rnd.randint(1, 4),
                                          memory="1Gi")])
                from kueue_tpu.api.types import (Admission,
                                                 PodSetAssignment)
                ps = wl.pod_sets[0]
                wl.admission = Admission(
                    cluster_queue=f"cq-{c}",
                    pod_set_assignments=[PodSetAssignment(
                        name="m", flavors={"cpu": "default",
                                           "memory": "default"},
                        resource_usage={"cpu": 1000 * ps.count,
                                        "memory": 1024**3 * ps.count},
                        count=ps.count)])
                wl.set_condition("QuotaReserved", True, now=1.0)
                wi = cache.assume_workload(wl)
                mirror.note_admission(wl, wi)
                live.append((wl, wi))
            if step % 37 == 0:
                mirror.refresh()
        snap = mirror.refresh()
        return {
            name: (dict(cq.usage),
                   sorted(cq.workloads),
                   cq.usage_version,
                   dict(cq.cohort.usage) if cq.cohort else None)
            for name, cq in snap.cluster_queues.items()}

    native_state = run(True)
    python_state = run(False)
    assert native_state == python_state


def test_mirror_removal_not_masked_by_same_batch_admission():
    """Eviction reconciling clears wl.admission right after noting the
    removal. The mirror must still apply that removal at the next flush —
    and a later same-CQ admission in the same pending batch (recording a
    newer base version) must not mask the drop. Regression for the
    flush-time admission re-derivation bug: the mirrored clone would keep
    counting the evicted workload's usage forever."""
    from kueue_tpu.api.types import Admission, PodSet, PodSetAssignment, Workload
    from kueue_tpu.core.snapshot import SnapshotMirror

    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=8))))
    cache.add_local_queue(make_lq("lq", cq="cq"))
    mirror = SnapshotMirror(cache)
    mirror.refresh()

    def admit(name):
        wl = Workload(name=name, queue_name="lq", creation_time=1.0,
                      pod_sets=[PodSet.make("m", 1, cpu=2)])
        wl.admission = Admission(cluster_queue="cq", pod_set_assignments=[
            PodSetAssignment(name="m", flavors={"cpu": "default"},
                             resource_usage={"cpu": 2000}, count=1)])
        wl.set_condition("QuotaReserved", True, now=1.0)
        wi = cache.assume_workload(wl)
        mirror.note_admission(wl, wi)
        return wl

    victim = admit("victim")
    mirror.refresh()

    # Eviction flow (runtime.reconcile order): release from the cache,
    # note the removal, THEN clear the admission.
    cache.delete_workload(victim)
    mirror.note_removal(victim)
    victim.admission = None
    # Same-batch later admission on the same ClusterQueue.
    admit("winner")

    snap = mirror.refresh()
    cq = snap.cluster_queues["cq"]
    assert cq.usage.get("default", {}).get("cpu", 0) == 2000, \
        "mirror must reflect the eviction (only the winner's 2 cpu)"
    assert "default/victim" not in cq.workloads
    assert "default/winner" in cq.workloads


def test_assume_workloads_fast_matches_python():
    """The native bulk-commit loop (ledger.cpp assume_batch, fast=True)
    must leave the cache bit-identical to the Python twin: usage,
    admitted split, LocalQueue stats, assumed map, dirty marks, and the
    duplicate/missing-CQ error strings."""
    import copy

    from kueue_tpu.core.workload import WorkloadInfo

    def build_items(cache):
        items = []
        for i in range(12):
            cq = "cq-a" if i % 3 else "cq-b"
            admitted = i % 4 != 0
            wl = admit(make_wl(f"bulk{i}", cpu=1 + i % 3, memory="1Gi"),
                       cq, "default", admitted=admitted)
            wi = WorkloadInfo(wl, cluster_queue=cq)
            triples = [(flv, res, v)
                       for flv, res_map in _wl_usage(wl).items()
                       for res, v in res_map.items()]
            items.append((wl, triples, wi, admitted))
        # A duplicate (same key assumed twice) and a missing CQ exercise
        # the error strings.
        dup_wl, dup_t, dup_wi, dup_adm = items[0]
        items.append((dup_wl, dup_t, WorkloadInfo(
            dup_wl, cluster_queue="cq-a"), dup_adm))
        ghost = admit(make_wl("ghost", cpu=1), "cq-gone", "default")
        items.append((ghost, [("default", "cpu", 1000)],
                      WorkloadInfo(ghost, cluster_queue="cq-gone"), True))
        return items

    def _wl_usage(wl):
        out = {}
        for psa in wl.admission.pod_set_assignments:
            for res, v in psa.resource_usage.items():
                flv = psa.flavors[res]
                out.setdefault(flv, {})[res] = \
                    out.setdefault(flv, {}).get(res, 0) + v
        return out

    def state(cache):
        return (
            {n: copy.deepcopy(cq.usage)
             for n, cq in cache.cluster_queues.items()},
            {n: copy.deepcopy(cq.admitted_usage)
             for n, cq in cache.cluster_queues.items()},
            {n: sorted(cq.workloads) for n, cq in
             cache.cluster_queues.items()},
            dict(cache.assumed_workloads),
            copy.deepcopy(cache._lq_stats),
        )

    fast_cache = build_cache()
    slow_cache = build_cache()
    fast_out = fast_cache.assume_workloads(build_items(fast_cache),
                                           fast=True)
    slow_out = slow_cache.assume_workloads(build_items(slow_cache))
    assert [o if isinstance(o, str) else o.key for o in fast_out] \
        == [o if isinstance(o, str) else o.key for o in slow_out]
    assert state(fast_cache) == state(slow_cache)
