"""Configuration loading/validation (pkg/config analog), YAML manifest
decoding (examples/ format), leader election, and the __main__ CLI."""

import json
import subprocess
import sys
import textwrap

import pytest

from kueue_tpu import config as config_mod
from kueue_tpu.api import serialization
from kueue_tpu.controllers.leaderelection import (
    LeaderAwareReconciler,
    LeaderElector,
    LeaseStore,
    RequeueAfter,
)

SETUP_YAML = textwrap.dedent("""\
    apiVersion: kueue.x-k8s.io/v1beta1
    kind: ResourceFlavor
    metadata:
      name: "default-flavor"
    ---
    apiVersion: kueue.x-k8s.io/v1beta1
    kind: ClusterQueue
    metadata:
      name: "cluster-queue"
    spec:
      namespaceSelector: {}
      resourceGroups:
      - coveredResources: ["cpu", "memory"]
        flavors:
        - name: "default-flavor"
          resources:
          - name: "cpu"
            nominalQuota: 9
          - name: "memory"
            nominalQuota: 36Gi
    ---
    apiVersion: kueue.x-k8s.io/v1beta1
    kind: LocalQueue
    metadata:
      namespace: "default"
      name: "user-queue"
    spec:
      clusterQueue: "cluster-queue"
""")

JOB_YAML = textwrap.dedent("""\
    apiVersion: batch/v1
    kind: Job
    metadata:
      name: sample-job
      namespace: default
      labels:
        kueue.x-k8s.io/queue-name: user-queue
    spec:
      parallelism: 3
      completions: 3
      suspend: true
      template:
        spec:
          containers:
          - name: dummy-job
            resources:
              requests:
                cpu: 1
                memory: "200Mi"
""")


# -- config ------------------------------------------------------------------

class TestConfiguration:
    def test_defaults(self):
        cfg = config_mod.from_dict({})
        assert cfg.namespace == "kueue-system"
        assert cfg.integrations.frameworks == ("batch",)
        assert cfg.queue_visibility.max_count == 10
        assert cfg.multikueue.worker_lost_timeout_seconds == 900.0
        assert not cfg.leader_election.enable

    def test_wait_for_pods_ready_defaulting(self):
        cfg = config_mod.from_dict({
            "waitForPodsReady": {"enable": True, "timeout": "10m"}})
        w = cfg.wait_for_pods_ready
        assert w.enable and w.block_admission
        assert w.timeout_seconds == 600.0
        assert w.requeuing_strategy.timestamp == "Eviction"

    def test_duration_forms(self):
        assert config_mod._duration_seconds("1m30s", 0) == 90.0
        assert config_mod._duration_seconds("500ms", 0) == 0.5
        assert config_mod._duration_seconds(42, 0) == 42.0
        assert config_mod._duration_seconds(None, 7.0) == 7.0

    def test_invalid_requeuing_timestamp(self):
        with pytest.raises(config_mod.ConfigurationError) as ei:
            config_mod.from_dict({"waitForPodsReady": {
                "enable": True,
                "requeuingStrategy": {"timestamp": "Bogus"}}})
        assert "timestamp" in str(ei.value)

    def test_negative_backoff_limit(self):
        with pytest.raises(config_mod.ConfigurationError):
            config_mod.from_dict({"waitForPodsReady": {
                "enable": True,
                "requeuingStrategy": {"backoffLimitCount": -1}}})

    def test_queue_visibility_bounds(self):
        with pytest.raises(config_mod.ConfigurationError):
            config_mod.from_dict({"queueVisibility": {
                "clusterQueues": {"maxCount": 4001}}})
        with pytest.raises(config_mod.ConfigurationError):
            config_mod.from_dict({"queueVisibility": {
                "updateIntervalSeconds": 0}})

    def test_unknown_framework(self):
        with pytest.raises(config_mod.ConfigurationError) as ei:
            config_mod.from_dict({"integrations": {"frameworks": ["nope"]}})
        assert "unknown framework" in str(ei.value)

    def test_pod_integration_requires_namespace_selector(self):
        with pytest.raises(config_mod.ConfigurationError) as ei:
            config_mod.from_dict({"integrations": {"frameworks": ["podgroup"]}})
        assert "podOptions" in str(ei.value)
        # kube-system must never be reconciled (validation.go prohibited).
        with pytest.raises(config_mod.ConfigurationError):
            config_mod.from_dict({"integrations": {
                "frameworks": ["podgroup"],
                "podOptions": {"namespaceSelector": {"matchLabels": {
                    "kubernetes.io/metadata.name": "kube-system"}}}}})

    def test_load_file(self, tmp_path):
        p = tmp_path / "cfg.yaml"
        p.write_text("namespace: my-ns\n"
                     "multiKueue:\n  gcInterval: 2m\n  origin: org\n")
        cfg = config_mod.load(str(p))
        assert cfg.namespace == "my-ns"
        assert cfg.multikueue.gc_interval_seconds == 120.0
        assert cfg.multikueue.origin == "org"

    def test_leader_election_validation(self):
        with pytest.raises(config_mod.ConfigurationError):
            config_mod.from_dict({"leaderElection": {
                "leaderElect": True,
                "leaseDuration": "5s", "renewDeadline": "10s"}})

    def test_transport_defaults_and_loading(self):
        cfg = config_mod.from_dict({})
        assert cfg.transport.mode == "pipe"
        assert cfg.transport.listen_addr() == ("127.0.0.1", 0)
        cfg = config_mod.from_dict({"transport": {
            "mode": "socket", "listen": "0.0.0.0:7070",
            "peers": ["10.0.0.2:7071"],
            "faults": "delay_ms=5,delay_p=0.5,seed=3"}})
        assert cfg.transport.mode == "socket"
        assert cfg.transport.listen_addr() == ("0.0.0.0", 7070)
        assert cfg.transport.peers == ("10.0.0.2:7071",)

    def test_transport_validation(self):
        with pytest.raises(config_mod.ConfigurationError):
            config_mod.from_dict({"transport": {"mode": "carrier-pigeon"}})
        with pytest.raises(config_mod.ConfigurationError):
            config_mod.from_dict({"transport": {"listen": "no-port"}})
        with pytest.raises(config_mod.ConfigurationError):
            config_mod.from_dict({"transport": {
                "mode": "socket", "faults": "bogus_knob=1"}})


# -- manifest decoding -------------------------------------------------------

class TestSerialization:
    def test_reference_setup_manifest(self, tmp_path):
        p = tmp_path / "setup.yaml"
        p.write_text(SETUP_YAML)
        objs = serialization.load_manifests(str(p))
        kinds = [k for k, _ in objs]
        assert kinds == ["ResourceFlavor", "ClusterQueue", "LocalQueue"]
        cq = objs[1][1]
        fq = cq.resource_groups[0].flavors[0]
        quotas = dict(fq.resources)
        assert quotas["cpu"].nominal == 9000  # milliCPU
        assert quotas["memory"].nominal == 36 * 1024 ** 3

    def test_batch_job_decode_round_trips_requests(self, tmp_path):
        p = tmp_path / "job.yaml"
        p.write_text(JOB_YAML)
        [(kind, job)] = serialization.load_manifests(str(p))
        assert kind == "Job"
        [ps] = job.pod_sets()
        assert ps.count == 3
        assert ps.requests["cpu"] == 1000  # not double-scaled
        assert ps.requests["memory"] == 200 * 1024 ** 2

    def test_workload_decode(self):
        kind, wl = serialization.decode({
            "kind": "Workload",
            "metadata": {"name": "w", "namespace": "ns"},
            "spec": {
                "queueName": "q",
                "priorityClassName": "high",
                "podSets": [{
                    "name": "main", "count": 2, "minCount": 1,
                    "template": {"spec": {
                        "nodeSelector": {"zone": "a"},
                        "tolerations": [{"key": "k", "operator": "Exists"}],
                        "containers": [{"resources": {
                            "requests": {"cpu": "500m"}}}],
                    }},
                }],
            }})
        assert kind == "Workload"
        [ps] = wl.pod_sets
        assert ps.requests["cpu"] == 500 * 2 // 2  # 500m per pod
        assert ps.min_count == 1
        assert dict(ps.node_selector) == {"zone": "a"}
        assert wl.priority_class == "high"

    def test_unsupported_kind(self):
        with pytest.raises(serialization.DecodeError):
            serialization.decode({"kind": "Gizmo", "metadata": {"name": "x"}})


# -- config wiring into the runtime ------------------------------------------

class TestConfigWiring:
    def _fw(self, cfg):
        from kueue_tpu.api import (ClusterQueue, FlavorQuotas, LocalQueue,
                                   ResourceFlavor, ResourceGroup)
        from kueue_tpu.controllers.runtime import Framework
        fw = Framework(config=cfg)
        fw.create_resource_flavor(ResourceFlavor.make("default"))
        fw.create_cluster_queue(ClusterQueue(
            name="cq", resource_groups=(ResourceGroup(
                ("cpu",), (FlavorQuotas.make("default", cpu=8),)),)))
        fw.create_local_queue(LocalQueue(
            name="main", namespace="default", cluster_queue="cq"))
        return fw

    def test_disabled_integration_rejected(self):
        from kueue_tpu.jobs import BatchJob
        from kueue_tpu.jobs.jobset import JobSet, ReplicatedJob
        cfg = config_mod.from_dict({"integrations": {"frameworks": ["batch"]}})
        fw = self._fw(cfg)
        fw.submit_job(BatchJob(name="ok", queue_name="main", parallelism=1,
                               requests={"cpu": 1}))
        with pytest.raises(ValueError, match="not enabled"):
            fw.submit_job(JobSet(name="no", queue_name="main",
                                 replicated_jobs=[ReplicatedJob(
                                     "r", 1, 1, {"cpu": 1})]))

    def test_default_library_config_enables_all(self):
        from kueue_tpu.jobs.jobset import JobSet, ReplicatedJob
        fw = self._fw(config_mod.Configuration())
        wl = fw.submit_job(JobSet(name="js", queue_name="main",
                                  replicated_jobs=[ReplicatedJob(
                                      "r", 1, 1, {"cpu": 1})]))
        assert wl is not None

    def test_unqueued_job_unmanaged_by_default(self):
        from kueue_tpu.jobs import BatchJob
        fw = self._fw(config_mod.Configuration())
        job = BatchJob(name="free", queue_name="", parallelism=1,
                       requests={"cpu": 1})
        assert fw.submit_job(job) is None
        assert job.is_suspended()  # constructed suspended, left untouched

    def test_unqueued_job_held_when_managed(self):
        from kueue_tpu.jobs import BatchJob
        cfg = config_mod.from_dict({"manageJobsWithoutQueueName": True})
        fw = self._fw(cfg)
        job = BatchJob(name="held", queue_name="", parallelism=1,
                       requests={"cpu": 1})
        assert fw.submit_job(job) is None
        assert job.is_suspended()

    def test_multikueue_timeout_from_config(self):
        from kueue_tpu.controllers.multikueue import MultiKueueController
        cfg = config_mod.from_dict({"multiKueue": {"workerLostTimeout": "1m"}})
        fw = self._fw(cfg)
        ctrl = MultiKueueController(fw)
        assert ctrl.worker_lost_timeout == 60.0

    def test_fair_sharing_strategy_validated(self):
        with pytest.raises(config_mod.ConfigurationError, match="unsupported"):
            config_mod.from_dict({"fairSharing": {
                "enable": True,
                "preemptionStrategies": ["LessThanFinalShare"]}})


# -- leader election ---------------------------------------------------------

class TestLeaderElection:
    def test_single_candidate_acquires_and_renews(self):
        now = [0.0]
        store = LeaseStore()
        a = LeaderElector(store, "a", clock=lambda: now[0])
        assert a.step() and a.is_leader()
        now[0] += 5.0
        assert a.step() and a.is_leader()

    def test_second_candidate_waits_for_expiry(self):
        now = [0.0]
        store = LeaseStore()
        a = LeaderElector(store, "a", clock=lambda: now[0])
        b = LeaderElector(store, "b", clock=lambda: now[0])
        assert a.step()
        assert not b.step()
        # a stops renewing; lease expires after leaseDuration (15s).
        now[0] += 16.0
        assert b.step() and b.is_leader()
        assert not a.is_leader()  # renew deadline passed

    def test_transitions_counted(self):
        now = [0.0]
        store = LeaseStore()
        a = LeaderElector(store, "a", clock=lambda: now[0])
        b = LeaderElector(store, "b", clock=lambda: now[0])
        a.step()
        a.release()
        b.step()
        assert store._leases[b.config.resource_name].transitions == 2

    def test_leader_aware_reconciler_defers(self):
        now = [0.0]
        store = LeaseStore()
        a = LeaderElector(store, "a", clock=lambda: now[0])
        b = LeaderElector(store, "b", clock=lambda: now[0])
        a.step()
        b.step()
        seen = []
        rec_b = LeaderAwareReconciler(b, seen.append, exists=lambda k: True)
        out = rec_b.reconcile("obj")
        assert isinstance(out, RequeueAfter) and not seen
        rec_a = LeaderAwareReconciler(a, seen.append, exists=lambda k: True)
        rec_a.reconcile("obj")
        assert seen == ["obj"]
        # deleted objects are discarded, not requeued (IgnoreNotFound).
        rec_gone = LeaderAwareReconciler(b, seen.append, exists=lambda k: False)
        assert rec_gone.reconcile("gone") is None


# -- CLI ---------------------------------------------------------------------

class TestMain:
    def _write(self, tmp_path):
        setup = tmp_path / "setup.yaml"
        setup.write_text(SETUP_YAML)
        job = tmp_path / "job.yaml"
        job.write_text(JOB_YAML)
        return setup, job

    def test_cli_admits_example_job(self, tmp_path):
        setup, job = self._write(tmp_path)
        from kueue_tpu.__main__ import main
        import io, contextlib
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = main(["--objects", str(setup), "--objects", str(job)])
        assert rc == 0
        out = json.loads(buf.getvalue())
        assert out["admitted"] == 1
        assert out["clusterQueues"]["cluster-queue"]["pending"] == 0

    def test_cli_feature_gates_flag(self, tmp_path):
        setup, job = self._write(tmp_path)
        from kueue_tpu.__main__ import main
        from kueue_tpu import features
        import io, contextlib
        with features.override(features.PARTIAL_ADMISSION, False):
            with contextlib.redirect_stdout(io.StringIO()):
                rc = main(["--objects", str(setup),
                           "--feature-gates", "PartialAdmission=true",
                           "--ticks", "1"])
            assert rc == 0
            assert features.enabled(features.PARTIAL_ADMISSION)

    def test_cli_subprocess_smoke(self, tmp_path):
        setup, job = self._write(tmp_path)
        proc = subprocess.run(
            [sys.executable, "-m", "kueue_tpu",
             "--objects", str(setup), "--objects", str(job)],
            capture_output=True, text=True, timeout=120,
            env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": "/root/repo",
                 "HOME": "/root"})
        assert proc.returncode == 0, proc.stderr
        assert json.loads(proc.stdout.strip())["admitted"] == 1
