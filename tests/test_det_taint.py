"""det engine (DET01/DET02/TNT01) — the static twin of the fuzzer.

Four layers:

  * fixture pins — det_bad/taint_bad produce EXACTLY the marked
    (rule, line) sets, the good twins produce nothing;
  * the static half of the oracle-mutation drill — the same
    `unsorted-members` mutation the fuzz campaign catches dynamically
    (tests/test_fuzz_corpus) is caught by DET01 on the mutated SOURCE,
    no campaign required;
  * the package gate — `--engine det` over kueue_tpu/ reports zero
    errors (the det analog of test_kueuelint's headline test);
  * roster sync — every top-level package entry is classified in
    exactly one of DECISION_CORE / CLOCK_SENSITIVE / NON_DECISION, so
    adding a module forces an explicit determinism-scope decision.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

from kueue_tpu import knobs
from kueue_tpu.analysis import Severity, run_analysis
from kueue_tpu.analysis.det_rules import (
    CLOCK_SENSITIVE, DECISION_CORE, NON_DECISION)

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "kueue_tpu"
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "lint"


def _det(path, **kw):
    return run_analysis([str(path)], engine="det", **kw)


def _pins(findings):
    return {(f.rule, f.line) for f in findings}


# ---------------------------------------------------------------------------
# Fixture pairs: exact finding sets
# ---------------------------------------------------------------------------


def test_det_bad_fixture_exact_findings():
    findings = _det(FIXTURES / "det_bad.py")
    assert _pins(findings) == {
        ("DET01", 32),   # list(self.members) escapes via return
        ("DET01", 36),   # next(iter(set)) arbitrary pick
        ("DET01", 41),   # order-sensitive loop body (append)
        ("DET01", 47),   # comprehension over object-keyed dict values
        ("DET02", 51),   # wall clock into a Condition stamp
        ("DET02", 55),   # randomness inside a sort key
        ("DET01", 61),   # raw os.listdir escapes via return
        ("DET02", 69),   # clock taint through two local assignments
    }


def test_det02_finding_carries_source_to_sink_path():
    findings = _det(FIXTURES / "det_bad.py")
    (through_locals,) = [f for f in findings if f.line == 69]
    # The report narrates the full hop chain, not just the sink.
    assert "`time.monotonic()` (line 67)" in through_locals.message
    assert "assigned to `now` at line 67" in through_locals.message
    assert "assigned to `elapsed` at line 68" in through_locals.message
    assert "constructor argument at line 69" in through_locals.message


def test_det_good_fixture_clean():
    assert _det(FIXTURES / "det_good.py") == []


def test_taint_bad_fixture_exact_findings():
    findings = _det(FIXTURES / "taint_bad.py")
    assert _pins(findings) == {
        ("TNT01", 22),   # gate knob read off its registered sites
        ("TNT01", 25),   # neutral knob value stored on decision state
        ("TNT01", 30),   # neutral knob value into a decision record
        ("TNT01", 34),   # neutral knob value inside a sort key
    }
    msgs = "\n".join(f.message for f in findings)
    # Contracts resolve from the real package registry: the gate report
    # names the registered site, the neutral reports name the knob.
    assert "models/flavor_fit.py" in msgs
    assert "KUEUE_TPU_TRACE" in msgs
    assert "KUEUE_TPU_DEBUG_FAIR" in msgs


def test_taint_good_fixture_clean():
    assert _det(FIXTURES / "taint_good.py") == []


# ---------------------------------------------------------------------------
# The static half of the oracle-mutation drill
# ---------------------------------------------------------------------------

_SORTED_WALK = (
    "sm = self._sorted_members = sorted(\n"
    "                    self.members, key=lambda c: c.name)")
_MUTATED_WALK = "sm = self._sorted_members = list(self.members)"


def test_unsorted_members_mutation_caught_statically(tmp_path):
    """KUEUE_TPU_FUZZ_MUTATION=unsorted-members takes a bounded fuzz
    campaign to catch dynamically (test_fuzz_corpus); applying the same
    mutation to the SOURCE is caught by DET01 in one analyzer pass."""
    src = (PACKAGE / "core" / "cache.py").read_text(encoding="utf-8")
    mutated = src.replace(_SORTED_WALK, _MUTATED_WALK)
    assert mutated != src, \
        "the sorted_members() walk moved in core/cache.py — update the " \
        "static half of the unsorted-members drill"

    core = tmp_path / "core"
    core.mkdir()
    target = core / "cache.py"

    # Control: the shipped source is det-clean (the armed drill branch
    # carries its justification suppression).
    target.write_text(src, encoding="utf-8")
    assert _det(tmp_path) == []

    # Mutated: the PR 8 revert fires DET01, pointing at the raw walk.
    target.write_text(mutated, encoding="utf-8")
    findings = _det(tmp_path)
    assert [f.rule for f in findings] == ["DET01"]
    (f,) = findings
    assert "self.members" in f.message
    assert "PR 8" in f.message
    assert f.line == src[:src.index(_SORTED_WALK)].count("\n") + 1


# ---------------------------------------------------------------------------
# Package gate and CLI plumbing
# ---------------------------------------------------------------------------


def test_package_det_engine_zero_errors():
    findings = _det(PACKAGE)
    errors = [f for f in findings if f.severity == Severity.ERROR]
    report = "\n".join(f.render() for f in errors)
    assert not errors, f"det-engine errors in kueue_tpu/:\n{report}"


def test_det_wide_extends_roster_beyond_decision_core(tmp_path):
    # Outside the decision-core roster the engine stays quiet by
    # default; --det-wide (nightly) analyzes everything.
    mod = tmp_path / "helpers.py"
    mod.write_text((FIXTURES / "det_bad.py").read_text(encoding="utf-8"),
                   encoding="utf-8")
    assert _det(tmp_path) == []
    assert _det(tmp_path, options={"det_wide": True}) != []
    proc = subprocess.run(
        [sys.executable, "-m", "kueue_tpu.analysis", "--engine", "det",
         "--det-wide", str(tmp_path)],
        cwd=str(REPO), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "DET01" in proc.stdout and "DET02" in proc.stdout


# ---------------------------------------------------------------------------
# Rosters and the knob decision contract stay in sync with reality
# ---------------------------------------------------------------------------


def test_decision_rosters_cover_package_exactly():
    entries = set()
    for p in sorted(PACKAGE.iterdir()):
        if p.name == "__pycache__":
            continue
        if p.is_dir():
            entries.add(p.name)
        elif p.suffix == ".py":
            entries.add(p.stem)

    core, clock, non = set(DECISION_CORE), set(CLOCK_SENSITIVE), \
        set(NON_DECISION)
    assert not core & clock and not core & non and not clock & non, \
        "determinism rosters overlap"
    classified = core | clock | non
    missing = entries - classified
    stale = classified - entries
    assert not missing, \
        f"new top-level package entries need a determinism-scope call " \
        f"(DECISION_CORE / CLOCK_SENSITIVE / NON_DECISION in " \
        f"analysis/det_rules.py): {sorted(missing)}"
    assert not stale, \
        f"det_rules.py rosters name entries that left the package: " \
        f"{sorted(stale)}"


def test_every_registered_gate_site_exists():
    # TNT01's gate discipline keys off path fragments in knobs.py; a
    # renamed module would silently legalize reads everywhere.
    paths = [p.as_posix() for p in PACKAGE.rglob("*.py")]
    for k in knobs.REGISTRY:
        assert k.decision in (knobs.NEUTRAL, knobs.GATE)
        if k.kind == knobs.KILL_SWITCH:
            assert k.decision == knobs.GATE, \
                f"{k.name}: kill-switches are decision gates by definition"
        for frag in k.gates:
            assert any(frag in p for p in paths), \
                f"{k.name} registers gate site {frag!r} which matches " \
                f"no file under kueue_tpu/"
