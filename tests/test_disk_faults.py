"""Disk-fault hardening on the durable journal: seeded ENOSPC / fsync
/ torn-write injection (controllers/diskfaults.py), write errors
surfaced in kueue_journal_write_errors_total instead of swallowed, and
attach-time replay that TRUNCATES a torn trailing line — the
crash-mid-append regression fixtures the fleet-grade control plane
requires."""

import json
import os

import pytest

from kueue_tpu.api.types import ResourceFlavor
from kueue_tpu.controllers.diskfaults import (
    ENOSPC,
    PASS,
    TORN,
    DiskFaultInjector,
    DiskFaultPlan,
    parse_disk_fault_env,
)
from kueue_tpu.controllers.durable import Journal
from kueue_tpu.controllers.store import KIND_RESOURCE_FLAVOR, Store
from kueue_tpu.metrics import REGISTRY


def _flavor(name):
    return ResourceFlavor.make(name)


def _journal_with_store(path, **kw):
    store = Store()
    journal = Journal(str(path), **kw)
    journal.attach(store)
    return journal, store


# -- seeded schedule reproducibility -----------------------------------------


def test_injector_schedule_is_deterministic():
    plan = DiskFaultPlan(seed=7, enospc_prob=0.3, torn_prob=0.2,
                         fsync_prob=0.1)
    a = plan.injector("/state/journal.jsonl")
    b = plan.injector("/state/journal.jsonl")
    other = plan.injector("/state/journal-g1.jsonl")
    sched_a = [a.next_action() for _ in range(64)]
    sched_b = [b.next_action() for _ in range(64)]
    assert sched_a == sched_b
    assert sched_a != [other.next_action() for _ in range(64)]
    assert set(sched_a) - {PASS}, "seed 7 drew no faults in 64 appends"


def test_parse_disk_fault_env():
    plan = parse_disk_fault_env("enospc_p=0.01,torn_p=0.005,seed=9")
    assert plan == DiskFaultPlan(seed=9, enospc_prob=0.01,
                                 torn_prob=0.005)
    assert parse_disk_fault_env("") is None
    assert parse_disk_fault_env("enospc_p=0") is None
    with pytest.raises(ValueError):
        parse_disk_fault_env("bogus_knob=1")


# -- write errors surfaced, never swallowed ----------------------------------


class _Scripted(DiskFaultInjector):
    """An injector with an explicit per-append script (deterministic
    fixtures want exact placement, not probabilities)."""

    def __init__(self, script, torn_len=5):
        super().__init__(DiskFaultPlan(seed=0, torn_prob=1e-9), "x")
        self._script = list(script)
        self._torn_len = torn_len

    def next_action(self):
        return self._script.pop(0) if self._script else PASS

    def torn_prefix_len(self, line_len):
        return min(self._torn_len, max(1, line_len - 1))


def test_enospc_is_counted_and_journal_survives(tmp_path):
    before = REGISTRY.journal_write_errors_total.get("enospc")
    journal, store = _journal_with_store(tmp_path / "j.jsonl")
    journal.faults = _Scripted([PASS, ENOSPC, PASS])
    store.create(KIND_RESOURCE_FLAVOR, _flavor("f-ok"))
    store.create(KIND_RESOURCE_FLAVOR, _flavor("f-lost"))  # ENOSPC
    store.create(KIND_RESOURCE_FLAVOR, _flavor("f-after"))
    journal.close()
    assert journal.write_errors == 1
    assert REGISTRY.journal_write_errors_total.get("enospc") \
        == before + 1
    # The lost record is lost (unacknowledged-write semantics); the
    # journal stays consistent and later appends replay cleanly.
    store2 = Store()
    j2 = Journal(str(tmp_path / "j.jsonl"))
    j2.attach(store2)
    names = sorted(rf.name for rf in store2.list(KIND_RESOURCE_FLAVOR))
    assert names == ["f-after", "f-ok"]
    j2.close()


def test_fsync_failure_keeps_the_record_and_counts(tmp_path):
    before = REGISTRY.journal_write_errors_total.get("fsync")
    journal, store = _journal_with_store(tmp_path / "j.jsonl",
                                         fsync=True)
    journal.faults = _Scripted(["fsync"])
    store.create(KIND_RESOURCE_FLAVOR, _flavor("f-maybe"))
    journal.close()
    assert REGISTRY.journal_write_errors_total.get("fsync") == before + 1
    # The data write landed: the record survives this (non-crash) run.
    store2 = Store()
    j2 = Journal(str(tmp_path / "j.jsonl"))
    j2.attach(store2)
    assert [rf.name for rf in store2.list(KIND_RESOURCE_FLAVOR)] \
        == ["f-maybe"]
    j2.close()


def test_torn_write_repairs_tail_before_next_append(tmp_path):
    """A torn append inside a LIVE journal: the next append first
    truncates back to the last complete record, so the torn prefix can
    never glue onto a later line."""
    before = REGISTRY.journal_write_errors_total.get("torn")
    journal, store = _journal_with_store(tmp_path / "j.jsonl")
    journal.faults = _Scripted([PASS, TORN, PASS])
    store.create(KIND_RESOURCE_FLAVOR, _flavor("f-0"))
    store.create(KIND_RESOURCE_FLAVOR, _flavor("f-torn"))
    store.create(KIND_RESOURCE_FLAVOR, _flavor("f-1"))
    journal.close()
    assert REGISTRY.journal_write_errors_total.get("torn") == before + 1
    with open(tmp_path / "j.jsonl") as f:
        entries = [json.loads(line) for line in f if line.strip()]
    assert [e["object"]["metadata"]["name"] for e in entries] \
        == ["f-0", "f-1"]


# -- torn-tail regression fixtures: crash mid-append, attach recovers --------


def _crash_mid_append(path, n_complete=5):
    """Build a journal of `n_complete` records whose writer 'crashes'
    mid-append on the LAST one (fault hook tears it), leaving the torn
    tail on disk exactly as a power cut would."""
    store = Store()
    journal = Journal(str(path))
    journal.attach(store)
    journal.faults = _Scripted([PASS] * n_complete + [TORN])
    for i in range(n_complete):
        store.create(KIND_RESOURCE_FLAVOR, _flavor(f"f-{i}"))
    # The fatal append: tear, then abandon the journal object without
    # repair (the process died). Re-tear the file AFTER close because
    # close() flushes nothing new but the next test stage needs the
    # torn bytes present.
    store.create(KIND_RESOURCE_FLAVOR, _flavor("f-crash"))
    journal._file.close()  # simulate process death: no repair runs
    journal._owner_lock_file.close()
    raw = open(path, "rb").read()
    assert not raw.endswith(b"\n"), "fixture did not produce a torn tail"
    return raw


def test_attach_replay_truncates_torn_tail_and_recovers_all(tmp_path):
    path = tmp_path / "crash.jsonl"
    raw_before = _crash_mid_append(path, n_complete=5)
    store = Store()
    journal = Journal(str(path))
    restored = journal.attach(store)
    # Every COMPLETE record recovered; the torn record dropped (its
    # write was never acknowledged); the torn bytes gone from disk.
    assert restored == 5
    assert sorted(rf.name for rf in store.list(KIND_RESOURCE_FLAVOR)) \
        == [f"f-{i}" for i in range(5)]
    assert journal.torn_tail_recovered == 1
    raw_after = open(path, "rb").read()
    assert len(raw_after) < len(raw_before)
    # ...and the journal is APPENDABLE: a new record lands on a clean
    # line, and a third replay sees exactly 6 records.
    store.create(KIND_RESOURCE_FLAVOR, _flavor("f-new"))
    journal.close()
    store3 = Store()
    j3 = Journal(str(path))
    assert j3.attach(store3) == 6
    j3.close()


def test_mid_file_corruption_is_skipped_counted_not_truncated(tmp_path):
    """Corruption that is NOT a trailing torn line cannot be a clean
    crash artifact: skip + count + keep every later complete record."""
    path = tmp_path / "corrupt.jsonl"
    journal, store = _journal_with_store(path)
    for i in range(3):
        store.create(KIND_RESOURCE_FLAVOR, _flavor(f"f-{i}"))
    journal.close()
    lines = open(path).read().splitlines()
    lines[1] = lines[1][: len(lines[1]) // 2]  # wound the middle
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    before = REGISTRY.journal_write_errors_total.get("corrupt-replay")
    store2 = Store()
    j2 = Journal(str(path))
    restored = j2.attach(store2)
    assert restored == 2
    assert j2.replay_skipped == 1
    assert j2.torn_tail_recovered == 0
    assert REGISTRY.journal_write_errors_total.get("corrupt-replay") \
        == before + 1
    j2.close()


def test_soak_random_faults_lose_no_acknowledged_record(tmp_path):
    """The seeded fault soak at journal level: every record whose
    append RETURNED cleanly (acknowledged) must survive replay; records
    the injector killed must be exactly the ones missing."""
    plan = DiskFaultPlan(seed=11, enospc_prob=0.08, torn_prob=0.08,
                         fsync_prob=0.05)
    path = tmp_path / "soak.jsonl"
    store = Store()
    journal = Journal(str(path), faults=plan)
    journal.attach(store)
    acked = []
    for i in range(200):
        errors_before = journal.write_errors
        store.create(KIND_RESOURCE_FLAVOR, _flavor(f"s-{i}"))
        if journal.write_errors == errors_before:
            acked.append(f"s-{i}")
    # fsync faults ack the record (the data write landed), so the only
    # permissible difference is fsync-flagged survivors.
    journal.close()
    store2 = Store()
    j2 = Journal(str(path))
    j2.attach(store2)
    names = {rf.name for rf in store2.list(KIND_RESOURCE_FLAVOR)}
    missing_acked = [n for n in acked if n not in names]
    assert not missing_acked, \
        f"acknowledged records lost on replay: {missing_acked[:5]}"
    assert journal.write_errors > 0, "seed 11 drew no faults in 200"
    j2.close()
