"""Durable state + restart recovery (controllers/durable.py).

The reference externalizes every decision to etcd and rebuilds caches on
startup (cache.go:295-328, queue/manager.go:121-134). These tests cover
the journal analog: an in-process rebuild, journal compaction, and the
VERDICT-mandated process-kill scenario — a `--serve` process is killed
mid-load (SIGKILL, no shutdown path), restarted on the same state dir,
and admitted workloads keep their quota while pending ones re-queue.
"""

import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

import pytest

from kueue_tpu.api.types import PodSet, Workload
from kueue_tpu.controllers.durable import Journal
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.controllers.store import (
    KIND_CLUSTER_QUEUE,
    KIND_LOCAL_QUEUE,
    KIND_RESOURCE_FLAVOR,
    KIND_WORKLOAD,
    Store,
    StoreAdapter,
)

from tests.util import fq, make_cq, make_flavor, make_lq, rg


def build_world(state_path):
    """A store+framework with a journal attached, 4-cpu single queue."""
    store = Store()
    journal = Journal(state_path)
    restored = journal.attach(store)
    fw = Framework()
    adapter = StoreAdapter(store, fw)
    return store, journal, fw, adapter, restored


def test_in_process_restart_recovers_admissions(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    store, journal, fw, adapter, restored = build_world(path)
    assert restored == 0
    store.create(KIND_RESOURCE_FLAVOR, make_flavor("default"))
    store.create(KIND_CLUSTER_QUEUE,
                 make_cq("cq", rg("cpu", fq("default", cpu=4))))
    store.create(KIND_LOCAL_QUEUE, make_lq("main", cq="cq"))
    store.create(KIND_WORKLOAD, Workload(
        name="fits", queue_name="main",
        pod_sets=[PodSet.make("m", 1, cpu=3)]))
    store.create(KIND_WORKLOAD, Workload(
        name="waits", queue_name="main",
        pod_sets=[PodSet.make("m", 1, cpu=3)]))
    for _ in range(4):
        adapter.tick()
    assert fw.workloads["default/fits"].is_admitted
    assert not fw.workloads["default/waits"].has_quota_reservation
    journal.close()

    # "Restart": a brand-new store/framework on the same journal.
    store2, journal2, fw2, adapter2, restored2 = build_world(path)
    assert restored2 == 5
    wl = fw2.workloads["default/fits"]
    assert wl.is_admitted
    # The quota is re-accounted, NOT re-admitted through the scheduler.
    assert fw2.cache.usage("cq")["default"]["cpu"] == 3000
    assert fw2.pending_workloads("cq") == 1
    # The pending one stays pending (no quota) across further ticks...
    adapter2.tick()
    assert not fw2.workloads["default/waits"].has_quota_reservation
    # ...until the recovered admission releases its quota.
    fw2.finish(fw2.workloads["default/fits"])
    for _ in range(4):
        adapter2.tick()
    assert fw2.workloads["default/waits"].is_admitted


def test_journal_compacts_dead_events(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    store, journal, fw, adapter, _ = build_world(path)
    store.create(KIND_RESOURCE_FLAVOR, make_flavor("default"))
    store.create(KIND_CLUSTER_QUEUE,
                 make_cq("cq", rg("cpu", fq("default", cpu=4))))
    store.create(KIND_LOCAL_QUEUE, make_lq("main", cq="cq"))
    for i in range(20):
        store.create(KIND_WORKLOAD, Workload(
            name=f"w{i}", queue_name="main",
            pod_sets=[PodSet.make("m", 1, cpu=1)]))
        adapter.tick()
        wl = fw.workloads[f"default/w{i}"]
        if wl.is_admitted:
            fw.finish(wl)
            fw.delete_workload(wl)
            store.delete(KIND_WORKLOAD, f"default/w{i}")
    journal.close()
    lines_before = sum(1 for _ in open(path))
    # Re-attach: replay + compaction rewrites to live state only.
    store2, journal2, fw2, _, restored = build_world(path)
    journal2.close()
    lines_after = sum(1 for _ in open(path))
    assert lines_after == restored <= 4 + 20
    assert lines_after < lines_before


SETUP_YAML = """\
apiVersion: kueue.x-k8s.io/v1beta1
kind: ResourceFlavor
metadata:
  name: default
---
apiVersion: kueue.x-k8s.io/v1beta1
kind: ClusterQueue
metadata:
  name: cq
spec:
  namespaceSelector: {}
  resourceGroups:
  - coveredResources: ["cpu"]
    flavors:
    - name: default
      resources:
      - name: cpu
        nominalQuota: 4
---
apiVersion: kueue.x-k8s.io/v1beta1
kind: LocalQueue
metadata:
  name: main
  namespace: default
spec:
  clusterQueue: cq
"""

WL_FITS = {
    "apiVersion": "kueue.x-k8s.io/v1beta1", "kind": "Workload",
    "metadata": {"name": "fits", "namespace": "default"},
    "spec": {"queueName": "main", "podSets": [{
        "name": "m", "count": 1, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "3"}}}]}}}]},
}
WL_WAITS = {
    "apiVersion": "kueue.x-k8s.io/v1beta1", "kind": "Workload",
    "metadata": {"name": "waits", "namespace": "default"},
    "spec": {"queueName": "main", "podSets": [{
        "name": "m", "count": 1, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "3"}}}]}}}]},
}


def _spawn(state_dir, setup_path):
    proc = subprocess.Popen(
        [sys.executable, "-m", "kueue_tpu", "--serve", "--port", "0",
         "--tick-interval", "0.05", "--state-dir", state_dir,
         "--objects", setup_path],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stderr=subprocess.PIPE, stdout=subprocess.DEVNULL, text=True)
    url = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stderr.readline()
        m = re.search(r"serving HTTP API on (http://\S+)", line or "")
        if m:
            url = m.group(1)
            break
        if proc.poll() is not None:
            raise RuntimeError("serve subprocess died during startup")
    assert url, "server never reported its URL"
    # Keep draining stderr: a full pipe would block the server.
    import threading
    threading.Thread(target=lambda: proc.stderr.read(), daemon=True).start()
    return proc, url


def _get_status(url, name):
    base = f"{url}/apis/kueue.x-k8s.io/v1beta1/namespaces/default/workloads"
    with urllib.request.urlopen(f"{base}/{name}", timeout=5) as resp:
        doc = json.load(resp)
    conds = {c["type"]: c.get("status") == "True"
             for c in (doc.get("status") or {}).get("conditions") or ()}
    return conds


def _post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=5):
        pass


def test_serve_process_kill_and_recover(tmp_path):
    """Kill -9 a --serve process mid-load; the restarted process keeps
    admitted quota and re-queues pending workloads."""
    state_dir = str(tmp_path / "state")
    setup = tmp_path / "setup.yaml"
    setup.write_text(SETUP_YAML)

    proc, url = _spawn(state_dir, str(setup))
    try:
        wl_base = (f"{url}/apis/kueue.x-k8s.io/v1beta1/"
                   "namespaces/default/workloads")
        _post(wl_base, WL_FITS)
        _post(wl_base, WL_WAITS)
        deadline = time.time() + 30
        while time.time() < deadline:
            if _get_status(url, "fits").get("Admitted"):
                break
            time.sleep(0.1)
        assert _get_status(url, "fits").get("Admitted")
        assert not _get_status(url, "waits").get("QuotaReserved")
    finally:
        # Hard kill: no graceful shutdown path runs.
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)

    # Restart on the same state dir; the setup manifests re-apply
    # idempotently (create errors are surfaced, not fatal).
    proc2, url2 = _spawn(state_dir, str(setup))
    try:
        status = _get_status(url2, "fits")
        assert status.get("Admitted"), status
        # The pending workload survived as pending and must NOT have been
        # admitted (quota is still held by the recovered admission).
        for _ in range(10):
            time.sleep(0.05)
            assert not _get_status(url2, "waits").get("QuotaReserved")
    finally:
        proc2.send_signal(signal.SIGKILL)
        proc2.wait(timeout=10)
