"""Durable state + restart recovery (controllers/durable.py).

The reference externalizes every decision to etcd and rebuilds caches on
startup (cache.go:295-328, queue/manager.go:121-134). These tests cover
the journal analog: an in-process rebuild, journal compaction, and the
VERDICT-mandated process-kill scenario — a `--serve` process is killed
mid-load (SIGKILL, no shutdown path), restarted on the same state dir,
and admitted workloads keep their quota while pending ones re-queue.
"""

import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

import pytest

from kueue_tpu.api.types import PodSet, Workload
from kueue_tpu.controllers.durable import Journal
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.controllers.store import (
    KIND_CLUSTER_QUEUE,
    KIND_LOCAL_QUEUE,
    KIND_RESOURCE_FLAVOR,
    KIND_WORKLOAD,
    Store,
    StoreAdapter,
)

from tests.util import fq, make_cq, make_flavor, make_lq, rg


def build_world(state_path):
    """A store+framework with a journal attached, 4-cpu single queue."""
    store = Store()
    journal = Journal(state_path)
    restored = journal.attach(store)
    fw = Framework()
    adapter = StoreAdapter(store, fw)
    return store, journal, fw, adapter, restored


def test_in_process_restart_recovers_admissions(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    store, journal, fw, adapter, restored = build_world(path)
    assert restored == 0
    store.create(KIND_RESOURCE_FLAVOR, make_flavor("default"))
    store.create(KIND_CLUSTER_QUEUE,
                 make_cq("cq", rg("cpu", fq("default", cpu=4))))
    store.create(KIND_LOCAL_QUEUE, make_lq("main", cq="cq"))
    store.create(KIND_WORKLOAD, Workload(
        name="fits", queue_name="main",
        pod_sets=[PodSet.make("m", 1, cpu=3)]))
    store.create(KIND_WORKLOAD, Workload(
        name="waits", queue_name="main",
        pod_sets=[PodSet.make("m", 1, cpu=3)]))
    for _ in range(4):
        adapter.tick()
    assert fw.workloads["default/fits"].is_admitted
    assert not fw.workloads["default/waits"].has_quota_reservation
    journal.close()

    # "Restart": a brand-new store/framework on the same journal.
    store2, journal2, fw2, adapter2, restored2 = build_world(path)
    assert restored2 == 5
    wl = fw2.workloads["default/fits"]
    assert wl.is_admitted
    # The quota is re-accounted, NOT re-admitted through the scheduler.
    assert fw2.cache.usage("cq")["default"]["cpu"] == 3000
    assert fw2.pending_workloads("cq") == 1
    # The pending one stays pending (no quota) across further ticks...
    adapter2.tick()
    assert not fw2.workloads["default/waits"].has_quota_reservation
    # ...until the recovered admission releases its quota.
    fw2.finish(fw2.workloads["default/fits"])
    for _ in range(4):
        adapter2.tick()
    assert fw2.workloads["default/waits"].is_admitted


def test_journal_compacts_dead_events(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    store, journal, fw, adapter, _ = build_world(path)
    store.create(KIND_RESOURCE_FLAVOR, make_flavor("default"))
    store.create(KIND_CLUSTER_QUEUE,
                 make_cq("cq", rg("cpu", fq("default", cpu=4))))
    store.create(KIND_LOCAL_QUEUE, make_lq("main", cq="cq"))
    for i in range(20):
        store.create(KIND_WORKLOAD, Workload(
            name=f"w{i}", queue_name="main",
            pod_sets=[PodSet.make("m", 1, cpu=1)]))
        adapter.tick()
        wl = fw.workloads[f"default/w{i}"]
        if wl.is_admitted:
            fw.finish(wl)
            fw.delete_workload(wl)
            store.delete(KIND_WORKLOAD, f"default/w{i}")
    journal.close()
    lines_before = sum(1 for _ in open(path))
    # Re-attach: replay + compaction rewrites to live state only.
    store2, journal2, fw2, _, restored = build_world(path)
    journal2.close()
    lines_after = sum(1 for _ in open(path))
    assert lines_after == restored <= 4 + 20
    assert lines_after < lines_before


def test_takeover_replay_processes_finish_transitions(tmp_path):
    """A standby whose store already holds a workload (applied via
    --objects) must process the journal's FULL history at takeover —
    including the finish after the admission. Dropping the finished
    record would leave the workload charging quota forever on the new
    leader (the MODIFIED-replay transition gap)."""
    path = str(tmp_path / "journal.jsonl")
    store, journal, fw, adapter, _ = build_world(path)
    store.create(KIND_RESOURCE_FLAVOR, make_flavor("default"))
    store.create(KIND_CLUSTER_QUEUE,
                 make_cq("cq", rg("cpu", fq("default", cpu=4))))
    store.create(KIND_LOCAL_QUEUE, make_lq("main", cq="cq"))
    wl = Workload(name="job", queue_name="main",
                  pod_sets=[PodSet.make("m", 1, cpu=3)])
    store.create(KIND_WORKLOAD, wl)
    for _ in range(3):
        adapter.tick()
    assert fw.workloads["default/job"].is_admitted
    fw.finish(fw.workloads["default/job"])
    adapter.tick()  # publishes the Finished status into the journal
    journal.close()

    # Standby: the SAME spec objects pre-exist in its store (the
    # --objects manifests), so the replay folds status via MODIFIED
    # events — admitted first, then finished.
    store2 = Store()
    fw2 = Framework()
    adapter2 = StoreAdapter(store2, fw2)
    store2.create(KIND_RESOURCE_FLAVOR, make_flavor("default"))
    store2.create(KIND_CLUSTER_QUEUE,
                  make_cq("cq", rg("cpu", fq("default", cpu=4))))
    store2.create(KIND_LOCAL_QUEUE, make_lq("main", cq="cq"))
    store2.create(KIND_WORKLOAD, Workload(
        name="job", queue_name="main",
        pod_sets=[PodSet.make("m", 1, cpu=3)]))
    journal2 = Journal(path)
    journal2.attach(store2)
    wl2 = fw2.workloads["default/job"]
    assert wl2.is_finished
    # The finished workload must NOT hold quota: a fresh 3-cpu workload
    # fits immediately.
    assert fw2.cache.usage("cq")["default"]["cpu"] == 0
    store2.create(KIND_WORKLOAD, Workload(
        name="next", queue_name="main",
        pod_sets=[PodSet.make("m", 1, cpu=3)]))
    for _ in range(3):
        adapter2.tick()
    assert fw2.workloads["default/next"].is_admitted
    journal2.close()


SETUP_YAML = """\
apiVersion: kueue.x-k8s.io/v1beta1
kind: ResourceFlavor
metadata:
  name: default
---
apiVersion: kueue.x-k8s.io/v1beta1
kind: ClusterQueue
metadata:
  name: cq
spec:
  namespaceSelector: {}
  resourceGroups:
  - coveredResources: ["cpu"]
    flavors:
    - name: default
      resources:
      - name: cpu
        nominalQuota: 4
---
apiVersion: kueue.x-k8s.io/v1beta1
kind: LocalQueue
metadata:
  name: main
  namespace: default
spec:
  clusterQueue: cq
"""

WL_FITS = {
    "apiVersion": "kueue.x-k8s.io/v1beta1", "kind": "Workload",
    "metadata": {"name": "fits", "namespace": "default"},
    "spec": {"queueName": "main", "podSets": [{
        "name": "m", "count": 1, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "3"}}}]}}}]},
}
WL_WAITS = {
    "apiVersion": "kueue.x-k8s.io/v1beta1", "kind": "Workload",
    "metadata": {"name": "waits", "namespace": "default"},
    "spec": {"queueName": "main", "podSets": [{
        "name": "m", "count": 1, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "3"}}}]}}}]},
}


def _spawn(state_dir, setup_path, extra_args=()):
    proc = subprocess.Popen(
        [sys.executable, "-m", "kueue_tpu", "--serve", "--port", "0",
         "--tick-interval", "0.05", "--state-dir", state_dir,
         "--objects", setup_path, *extra_args],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stderr=subprocess.PIPE, stdout=subprocess.DEVNULL, text=True)
    url = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stderr.readline()
        m = re.search(r"serving HTTP API on (http://\S+)", line or "")
        if m:
            url = m.group(1)
            break
        if proc.poll() is not None:
            raise RuntimeError("serve subprocess died during startup")
    assert url, "server never reported its URL"
    # Keep draining stderr (a full pipe would block the server), capturing
    # the lines so tests can assert on the takeover-replay log.
    import threading
    captured = []

    def _drain():
        for line in proc.stderr:
            captured.append(line)

    threading.Thread(target=_drain, daemon=True).start()
    return proc, url, captured


def _get_status(url, name):
    base = f"{url}/apis/kueue.x-k8s.io/v1beta1/namespaces/default/workloads"
    with urllib.request.urlopen(f"{base}/{name}", timeout=5) as resp:
        doc = json.load(resp)
    conds = {c["type"]: c.get("status") == "True"
             for c in (doc.get("status") or {}).get("conditions") or ()}
    return conds


def _post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=5):
        pass


def test_serve_process_kill_and_recover(tmp_path):
    """Kill -9 a --serve process mid-load; the restarted process keeps
    admitted quota and re-queues pending workloads."""
    state_dir = str(tmp_path / "state")
    setup = tmp_path / "setup.yaml"
    setup.write_text(SETUP_YAML)

    proc, url, _ = _spawn(state_dir, str(setup))
    try:
        wl_base = (f"{url}/apis/kueue.x-k8s.io/v1beta1/"
                   "namespaces/default/workloads")
        _post(wl_base, WL_FITS)
        _post(wl_base, WL_WAITS)
        deadline = time.time() + 30
        while time.time() < deadline:
            if _get_status(url, "fits").get("Admitted"):
                break
            time.sleep(0.1)
        assert _get_status(url, "fits").get("Admitted")
        assert not _get_status(url, "waits").get("QuotaReserved")
    finally:
        # Hard kill: no graceful shutdown path runs.
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)

    # Restart on the same state dir; the setup manifests re-apply
    # idempotently (create errors are surfaced, not fatal).
    proc2, url2, _ = _spawn(state_dir, str(setup))
    try:
        status = _get_status(url2, "fits")
        assert status.get("Admitted"), status
        # The pending workload survived as pending and must NOT have been
        # admitted (quota is still held by the recovered admission).
        for _ in range(10):
            time.sleep(0.05)
            assert not _get_status(url2, "waits").get("QuotaReserved")
    finally:
        proc2.send_signal(signal.SIGKILL)
        proc2.wait(timeout=10)


LEADER_CFG = """\
apiVersion: config.kueue.x-k8s.io/v1beta1
kind: Configuration
leaderElection:
  leaderElect: true
  leaseDuration: 2s
  renewDeadline: 1s
  retryPeriod: 200ms
"""


def test_replica_failover_replays_partition_journal(tmp_path):
    """Multi-process replica HA (the PR 2 takeover, per PARTITION): kill
    a replica mid-window; the lease-holding runtime reassigns its shard
    group to a survivor, which attaches the dead replica's per-group
    journal and replays it — the admitted set then matches the
    uninterrupted single-process run exactly (quota restored by replay,
    never re-admission), and pending workloads keep waiting."""
    from kueue_tpu.config import Configuration, TPUSolverConfig
    from kueue_tpu.controllers.replica_runtime import ReplicaRuntime
    from tests.util import fq, make_cq, make_lq, make_wl, rg

    def build(target):
        target.create_resource_flavor(make_flavor("default"))
        for i in range(4):
            target.create_cluster_queue(make_cq(
                f"cq-{i}", rg("cpu", fq("default", cpu=4))))
            target.create_local_queue(make_lq(
                f"lq-{i}", "default", cq=f"cq-{i}"))

    def load(target):
        for i in range(4):
            target.submit(make_wl(f"fits-{i}", f"lq-{i}", cpu=3,
                                  creation_time=float(i)))
            target.submit(make_wl(f"waits-{i}", f"lq-{i}", cpu=3,
                                  creation_time=float(10 + i)))

    # Uninterrupted single-process reference.
    fw = Framework(config=Configuration(
        tpu_solver=TPUSolverConfig(enable=False)))
    fw.create_namespace("default", labels={})
    build(fw)
    load(fw)
    fw.run_until_settled(max_ticks=8)
    expect = {f"cq-{i}": sorted(fw.cache.cluster_queues[f"cq-{i}"].workloads)
              for i in range(4)}

    rt = ReplicaRuntime(3, spawn=False, engine="host",
                        state_dir=str(tmp_path / "state"))
    try:
        build(rt)
        load(rt)
        for _ in range(4):
            rt.tick()
        assert rt.dump()["admitted"] == expect
        victim_gid = rt.gmap.cq_group["cq-1"]
        victim = rt.group_owner[victim_gid]
        rt.kill_replica(victim)
        for _ in range(5):
            rt.tick()
        after = rt.dump()
        assert rt.group_owner[victim_gid] != victim
        assert after["admitted"] == expect
        # Exactly-once: recovered admissions hold the quota, so every
        # pending workload must still be waiting.
        assert all(n == 1 for n in after["pending"].values()), \
            after["pending"]
        # The reassigned group's journal kept recording: one owner file
        # per shard-group journal exists in the shared state dir.
        journals = sorted(p for p in os.listdir(tmp_path / "state")
                          if p.startswith("journal-g")
                          and p.endswith(".jsonl"))
        assert len(journals) == 3
    finally:
        rt.close()


def test_ha_takeover_replays_shared_journal(tmp_path):
    """HA takeover with ONE shared journal across both replicas (the
    deferred-attach replay path): replicas share the state dir AND the
    lease; the journal attach is deferred until a replica actually leads,
    so the standby replays the dead leader's journal at takeover — the
    admitted workload stays admitted exactly once (its quota is restored
    by REPLAY, not re-admission, and the pending one must keep waiting)."""
    state_dir = str(tmp_path / "state")
    os.makedirs(state_dir)
    setup = tmp_path / "setup.yaml"
    setup.write_text(SETUP_YAML)
    cfg = tmp_path / "config.yaml"
    cfg.write_text(LEADER_CFG)
    lease = os.path.join(state_dir, "leases.json")
    ha_args = ("--config", str(cfg), "--lease-file", lease)

    proc_a, url_a, _ = _spawn(state_dir, str(setup), ha_args)
    proc_b = None
    try:
        wl_base = (f"{url_a}/apis/kueue.x-k8s.io/v1beta1/"
                   "namespaces/default/workloads")
        _post(wl_base, WL_FITS)
        _post(wl_base, WL_WAITS)
        deadline = time.time() + 30
        while time.time() < deadline:
            if _get_status(url_a, "fits").get("Admitted"):
                break
            time.sleep(0.1)
        assert _get_status(url_a, "fits").get("Admitted")
        assert not _get_status(url_a, "waits").get("QuotaReserved")

        # Standby on the SAME state dir: defers (no journal attach, no
        # reconcile) while A leads — its store knows only the setup
        # objects, not the POSTed workloads.
        proc_b, url_b, captured_b = _spawn(state_dir, str(setup), ha_args)
        time.sleep(1.0)
        assert proc_b.poll() is None, "standby died (journal flock clash?)"

        # Kill the leader; B takes the lease and replays A's journal.
        proc_a.send_signal(signal.SIGKILL)
        proc_a.wait(timeout=10)

        def _try_status(url, name):
            # 404 until the replay materializes the workload in B's store.
            try:
                return _get_status(url, name)
            except Exception:
                return {}

        deadline = time.time() + 30
        while time.time() < deadline:
            if _try_status(url_b, "fits").get("Admitted"):
                break
            time.sleep(0.1)
        status = _try_status(url_b, "fits")
        assert status.get("Admitted"), status
        # The replay path (not a fresh scheduler admission) restored it.
        # The drain thread delivers stderr asynchronously — poll briefly.
        deadline = time.time() + 5
        while time.time() < deadline and not any(
                "replayed" in line and "shared journal" in line
                for line in captured_b):
            time.sleep(0.05)
        assert any("replayed" in line and "shared journal" in line
                   for line in captured_b), captured_b
        # Exactly-once: the recovered admission still holds the quota, so
        # the pending workload must NOT gain a reservation.
        for _ in range(10):
            time.sleep(0.05)
            assert not _get_status(url_b, "waits").get("QuotaReserved")
    finally:
        for p in (proc_a, proc_b):
            if p is not None and p.poll() is None:
                p.send_signal(signal.SIGKILL)
                p.wait(timeout=10)
