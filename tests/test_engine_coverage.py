"""Meta-test: the preemption-engine registry (solver/modes.ENGINES) is the
single source of truth, and every consumer that must cover ALL engines
provably does — so a future engine cannot land unverified:

  * the preemption goldens parametrize over every registered engine
    (modulo optional engines whose toolchain is absent);
  * the kueueverify trace roster lowers every traceable engine's kernel;
  * every registry entry points at an importable module/attribute.
"""

from __future__ import annotations

import importlib

from kueue_tpu.analysis import trace_rules
from kueue_tpu.solver import modes


_importable = modes.engine_importable


def test_registry_is_well_formed():
    names = [e.name for e in modes.ENGINES]
    assert len(names) == len(set(names))
    kinds = {e.kind for e in modes.ENGINES}
    assert kinds == {"host", "native", "jax"}
    # The reference semantics live in exactly one host referee.
    assert sum(e.kind == "host" for e in modes.ENGINES) == 1


def test_every_engine_entry_point_exists():
    for spec in modes.ENGINES:
        if spec.optional_import and not _importable(spec):
            continue
        mod = importlib.import_module(spec.module)
        assert hasattr(mod, spec.entry), \
            f"{spec.name}: {spec.module}.{spec.entry} does not exist"


def test_goldens_parametrize_every_registered_engine():
    """A registered engine missing from the preemption-golden
    parametrization would ship decision semantics nobody pinned against
    the reference — the exact gap that let the PR 2 Pallas bugs live."""
    from tests import test_preemption_goldens as goldens

    required = {e.name for e in modes.ENGINES
                if not e.optional_import or _importable(e)}
    assert required <= set(goldens.ENGINES), \
        f"goldens miss engines: {required - set(goldens.ENGINES)}"


def test_trace_roster_covers_every_traceable_engine():
    roster = {spec.name for spec in trace_rules.package_roster()}
    traceable = {e.name for e in modes.ENGINES if e.traceable}
    assert traceable <= roster, \
        f"kueueverify roster misses engines: {traceable - roster}"


def test_trace_roster_covers_every_solve_entry():
    """The flavor-fit solve entry points (single-device, packed,
    cohort-sharded, topology) carry the same cannot-land-unverified
    contract as the victim-search engines."""
    roster = {spec.name for spec in trace_rules.package_roster()}
    solves = {s.name for s in modes.SOLVE_ENTRYPOINTS}
    assert solves <= roster, \
        f"kueueverify roster misses solve entry points: {solves - roster}"


def test_every_registered_kernel_is_trc02_verified():
    """No roster entry — in particular no PACKED entry point — may opt
    out of sentinel-overflow verification: the "verified unpacked
    instead" exemption is retired (the bitcast-aware Packed domain seeds
    byte buffers with their wire layout), so every traceable engine and
    every SOLVE_ENTRYPOINTS kernel runs the full TRC rule set."""
    by_name = {spec.name: spec for spec in trace_rules.package_roster()}
    must_verify = {e.name for e in modes.ENGINES if e.traceable}
    must_verify |= {s.name for s in modes.SOLVE_ENTRYPOINTS}
    for name in sorted(must_verify):
        spec = by_name[name]
        assert "TRC02" in spec.rules, \
            f"{name}: TRC02 exempted — packed kernels must be verified " \
            "directly, not via an unpacked stand-in"


def test_every_solve_entry_point_exists():
    for spec in modes.SOLVE_ENTRYPOINTS:
        mod = importlib.import_module(spec.module)
        assert hasattr(mod, spec.entry), \
            f"{spec.name}: {spec.module}.{spec.entry} does not exist"


def test_every_solve_mode_is_registered():
    """An UNREGISTERED solve mode fails CI: every mode in SOLVE_MODES
    must name only registered SOLVE_ENTRYPOINTS kernels, every one of
    those kernels must be in the kueueverify trace roster, and the
    config layer must accept exactly the registered mode names — so a
    new `tpuSolver.mode` cannot land with unverified kernels."""
    entry_names = {s.name for s in modes.SOLVE_ENTRYPOINTS}
    roster = {spec.name for spec in trace_rules.package_roster()}
    names = [m.name for m in modes.SOLVE_MODES]
    assert len(names) == len(set(names))
    assert "default" in names
    for mode in modes.SOLVE_MODES:
        assert mode.entrypoints, f"mode {mode.name}: no entrypoints"
        missing = set(mode.entrypoints) - entry_names
        assert not missing, \
            f"mode {mode.name}: entrypoints missing from " \
            f"SOLVE_ENTRYPOINTS: {missing}"
        untraced = set(mode.entrypoints) - roster
        assert not untraced, \
            f"mode {mode.name}: kernels missing from the kueueverify " \
            f"trace roster: {untraced}"


def test_config_accepts_only_registered_solve_modes():
    from kueue_tpu.config import (
        Configuration, TPUSolverConfig, validate_configuration)

    for name in modes.solve_mode_names():
        cfg = Configuration(tpu_solver=TPUSolverConfig(mode=name))
        assert not [e for e in validate_configuration(cfg)
                    if "tpuSolver.mode" in e]
    bad = Configuration(tpu_solver=TPUSolverConfig(mode="not-a-mode"))
    assert any("tpuSolver.mode" in e
               for e in validate_configuration(bad))


def test_optional_engines_are_skipped_only_when_unimportable():
    from tests import test_preemption_goldens as goldens

    for spec in modes.ENGINES:
        if spec.optional_import and _importable(spec):
            assert spec.name in goldens.ENGINES
