"""Device-side fair sharing: differential goldens + unit coverage.

The PR-8 contract: the vectorized fair path (incremental share state,
packed int64 fair sort key, tensor victim search) is DECISION-IDENTICAL
to the dict-walk referee everywhere. The churn goldens drive 200
randomized ticks of add/admit/preempt/delete churn over a WEIGHTED
KEP-79 hierarchical tree + a flat cohort + cohortless ClusterQueues,
with FairSharing on, twice — device fair on (with KUEUE_TPU_DEBUG_FAIR=1,
so every search additionally runs the host oracle in-line and asserts
equal victim sequences, and every tick cross-checks the incremental
share state against the referee) and off (KUEUE_TPU_NO_DEVICE_FAIR=1) —
across every registered victim-search engine and both
FairSharingStrategy orders.
"""

import random

import numpy as np
import pytest

from kueue_tpu import features
from kueue_tpu.api.types import (
    ClusterQueuePreemption,
    CohortSpec,
    FairSharing,
    FairSharingStrategy,
    PodSet,
    Workload,
)
from kueue_tpu.config import Configuration, FairSharingConfig, TPUSolverConfig
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.models.flavor_fit import BatchSolver
from kueue_tpu.solver import modes as _modes

from tests.util import fq, make_cq, make_flavor, make_lq, rg

TICKS = 200

_ENGINE_KNOB = {
    "host": None,
    "scan-jax": "jax",
    "scan-pallas": "pallas",
    "batch-native": "native",
    "batch-jax": "jax",
}

_KNOBS = []
for _spec in _modes.ENGINES:
    if _spec.optional_import and not _modes.engine_importable(_spec):
        continue
    knob = _ENGINE_KNOB[_spec.name]
    if knob not in _KNOBS:
        _KNOBS.append(knob)

S2A_FIRST = (FairSharingStrategy.LESS_THAN_OR_EQUAL_TO_FINAL_SHARE,
             FairSharingStrategy.LESS_THAN_INITIAL_SHARE)
S2B_FIRST = (FairSharingStrategy.LESS_THAN_INITIAL_SHARE,
             FairSharingStrategy.LESS_THAN_OR_EQUAL_TO_FINAL_SHARE)


@pytest.fixture(autouse=True)
def fair_on():
    features.set_enabled(features.FAIR_SHARING, True)
    yield


class TickClock:
    """Deterministic scheduler clock: frozen within a tick, advanced by
    the churn driver between ticks. The A/B goldens compare two full
    drives, and real wall-clock condition timestamps (QuotaReserved /
    Evicted transition times feed the candidate ordering) differ between
    them — a microsecond tie in one drive but not the other flips a
    sort tiebreak and fakes a decision divergence."""

    def __init__(self):
        self.now = 1_000_000.0

    def advance(self, dt: float = 1.0) -> None:
        self.now += dt

    def __call__(self) -> float:
        return self.now


def build(engine, strategies):
    cfg = Configuration(
        tpu_solver=TPUSolverConfig(
            preemption_engine="host" if engine is None else engine),
        fair_sharing=FairSharingConfig(
            enable=True, preemption_strategies=tuple(strategies)))
    fw = Framework(batch_solver=BatchSolver(), config=cfg,
                   clock=TickClock())
    fw.create_namespace("default", labels={})
    fw.create_resource_flavor(make_flavor("default"))
    # A weighted KEP-79 tree: two mid cohorts under one root, plus a
    # flat cohort and two cohortless CQs (the classic engine path).
    fw.create_cohort(CohortSpec(name="root"))
    fw.create_cohort(CohortSpec(name="mid-a", parent="root"))
    fw.create_cohort(CohortSpec(name="mid-b", parent="root"))
    weights = [0.0, 1.0, 2.0, 4.0, 1.0, 3.0, 2.0, 1.0]
    for i in range(8):
        cohort = ("mid-a" if i < 3 else "mid-b" if i < 5
                  else "flatpool" if i < 7 else "")
        import dataclasses
        quota = fq("default", cpu=(4, 8)) if cohort \
            else fq("default", cpu=4)
        cq = make_cq(
            f"cq-{i}", rg("cpu", quota),
            cohort=cohort,
            preemption=ClusterQueuePreemption(
                within_cluster_queue="LowerPriority",
                reclaim_within_cohort="Any"))
        cq = dataclasses.replace(
            cq, fair_sharing=FairSharing(weight=weights[i]))
        fw.create_cluster_queue(cq)
        fw.create_local_queue(make_lq(f"lq-{i}", "default", cq=f"cq-{i}"))
    return fw


def drive(engine, strategies, ticks: int = TICKS):
    fw = build(engine, strategies)
    rnd = random.Random(99)
    seq = [0]
    pending: dict = {}
    admitted: dict = {}
    trail = []

    orig_admit = fw.scheduler.apply_admission
    orig_preempt = fw.scheduler.apply_preemption
    tick_admitted: list = []
    tick_preempted: list = []

    def apply_admission(wl):
        ok = orig_admit(wl)
        if ok:
            tick_admitted.append(wl.key)
            admitted[wl.key] = wl
            pending.pop(wl.key, None)
        return ok

    def apply_preemption(wl, msg):
        tick_preempted.append(wl.key)
        return orig_preempt(wl, msg)

    fw.scheduler.apply_admission = apply_admission
    fw.scheduler.apply_preemption = apply_preemption

    def submit_one():
        seq[0] += 1
        i = seq[0]
        wl = Workload(
            name=f"wl-{i}", namespace="default",
            queue_name=f"lq-{rnd.randrange(8)}",
            priority=rnd.randint(-2, 3),
            creation_time=float(1000 + i),
            pod_sets=[PodSet.make("ps0", count=rnd.randint(1, 2),
                                  cpu=rnd.randint(1, 4))])
        pending[wl.key] = wl
        fw.submit(wl)

    for _ in range(30):
        submit_one()

    for _ in range(ticks):
        tick_admitted.clear()
        tick_preempted.clear()
        fw.clock.advance()
        fw.tick()
        # Preserving tick ORDER of preemptions pins the victim SEQUENCE
        # (issue order), not just the set.
        trail.append((tuple(sorted(tick_admitted)), tuple(tick_preempted)))
        for _ in range(rnd.randint(0, 3)):
            submit_one()
        done = [k for k, w in sorted(admitted.items())
                if w.is_admitted and not w.is_finished]
        for key in done[:rnd.randint(0, 3)]:
            wl = admitted.pop(key)
            fw.finish(wl)
            fw.delete_workload(wl)
        for key in list(admitted):
            if not admitted[key].is_admitted:
                wl = admitted.pop(key)
                if not wl.is_finished:
                    pending[key] = wl
        fw.prewarm_idle()
    trail.append(("pending", sum(fw.queues.pending(f"cq-{i}")
                                 for i in range(8))))
    return trail


_PARAMS = [(k, S2A_FIRST) for k in _KNOBS] + [(None, S2B_FIRST)]


@pytest.mark.parametrize(
    "engine,strategies", _PARAMS,
    ids=[f"{k}-s2a" for k in _KNOBS] + ["None-s2b"])
def test_device_fair_vs_referee_decisions_identical(engine, strategies,
                                                    monkeypatch):
    monkeypatch.setenv("KUEUE_TPU_DEBUG_FAIR", "1")
    with_device = drive(engine, strategies)
    monkeypatch.delenv("KUEUE_TPU_DEBUG_FAIR")
    monkeypatch.setenv("KUEUE_TPU_NO_DEVICE_FAIR", "1")
    without = drive(engine, strategies)
    monkeypatch.delenv("KUEUE_TPU_NO_DEVICE_FAIR")
    assert with_device == without


def test_registry_covered():
    assert set(_ENGINE_KNOB) == {e.name for e in _modes.ENGINES}, \
        "new victim-search engine registered; map it here so the fair " \
        "differential goldens run it"


# -- scenario goldens: weighted KEP-79 tree, every engine, A/B -------------


@pytest.mark.parametrize("device_fair", [True, False],
                         ids=["device", "referee"])
@pytest.mark.parametrize("engine", _KNOBS, ids=[str(k) for k in _KNOBS])
@pytest.mark.parametrize("weight,expect_preempt",
                         [(1.0, True), (3.0, False)])
def test_weighted_tree_fair_preemption_golden(weight, expect_preempt,
                                              engine, device_fair,
                                              monkeypatch):
    """The TestPreemption-style fair golden over a weighted (weight != 1)
    hierarchical tree: `heavy` (in one subtree) borrows the whole shared
    pool; a borrowing request from `light` (in the sibling subtree)
    preempts heavy at weight 1 (equal standing) but not at weight 3 —
    identical victims for every registered engine with the device fair
    path on or off."""
    import dataclasses

    if device_fair:
        monkeypatch.setenv("KUEUE_TPU_DEBUG_FAIR", "1")
    else:
        monkeypatch.setenv("KUEUE_TPU_NO_DEVICE_FAIR", "1")
    cfg = Configuration(
        tpu_solver=TPUSolverConfig(
            preemption_engine="host" if engine is None else engine),
        fair_sharing=FairSharingConfig(enable=True))
    fw = Framework(batch_solver=BatchSolver(), config=cfg)
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cohort(CohortSpec(name="root"))
    fw.create_cohort(CohortSpec(name="wing-a", parent="root"))
    fw.create_cohort(CohortSpec(name="wing-b", parent="root"))
    for name, cohort, w in (("heavy", "wing-a", weight),
                            ("light", "wing-b", 1.0),
                            ("pool", "wing-b", 1.0)):
        cq = make_cq(name, rg("cpu", fq("default", cpu=2)), cohort=cohort,
                     preemption=ClusterQueuePreemption(
                         reclaim_within_cohort="Any",
                         within_cluster_queue="LowerPriority"))
        cq = dataclasses.replace(cq, fair_sharing=FairSharing(weight=w))
        fw.create_cluster_queue(cq)
    fw.create_local_queue(make_lq("h", cq="heavy"))
    fw.create_local_queue(make_lq("l", cq="light"))
    from tests.util import make_wl
    for i in range(3):
        fw.submit(make_wl(f"h{i}", "h", cpu=2, creation_time=float(i)))
    fw.run_until_settled()
    assert len(fw.admitted_workloads("heavy")) == 3  # borrowing 4 of 6
    fw.submit(make_wl("l0", "l", cpu="3500m", creation_time=10.0))
    fw.run_until_settled()
    if expect_preempt:
        assert len(fw.admitted_workloads("light")) == 1
        assert len(fw.admitted_workloads("heavy")) == 1
    else:
        assert len(fw.admitted_workloads("light")) == 0
        assert len(fw.admitted_workloads("heavy")) == 3


# -- incremental share state ------------------------------------------------


def test_share_state_matches_referee_after_churn():
    """The generation-memoized shares equal a from-scratch referee pass
    after randomized admit/finish churn (the replay path, not just the
    seed pass)."""
    from kueue_tpu.solver.fair_share import dominant_resource_share

    fw = build(None, S2A_FIRST)
    rnd = random.Random(5)
    for i in range(24):
        fw.submit(Workload(
            name=f"w-{i}", namespace="default",
            queue_name=f"lq-{rnd.randrange(8)}",
            priority=rnd.randint(-1, 2), creation_time=float(i),
            pod_sets=[PodSet.make("ps0", count=1, cpu=rnd.randint(1, 4))]))
    for _ in range(12):
        fw.tick()
    solver = fw.scheduler.batch_solver
    snapshot = fw.scheduler._mirror.refresh()
    st = solver.fair_share_state(snapshot)
    assert st is not None
    st.verify(snapshot)
    # Ranks order exactly as the float shares.
    order_rank = np.lexsort((np.arange(len(st.share)), st.rank))
    order_share = np.lexsort((np.arange(len(st.share)), st.share))
    assert list(order_rank) == list(order_share)
    # And the dict view matches the referee per CQ.
    shares = solver.fair_shares(snapshot)
    for name, cq in snapshot.cluster_queues.items():
        assert shares[name] == dominant_resource_share(cq)[0], name


def test_share_state_replays_untouched_cohorts():
    """A tick with no usage movement recomputes nothing: the state's
    version is stable and refresh() is a pure generation compare."""
    fw = build(None, S2A_FIRST)
    for i in range(6):
        fw.submit(Workload(
            name=f"w-{i}", namespace="default", queue_name=f"lq-{i}",
            priority=0, creation_time=float(i),
            pod_sets=[PodSet.make("ps0", count=1, cpu=6)]))
    for _ in range(6):
        fw.tick()
    solver = fw.scheduler.batch_solver
    snapshot = fw.scheduler._mirror.refresh()
    st = solver.fair_share_state(snapshot)
    v0 = st.version
    st2 = solver.fair_share_state(snapshot)
    assert st2 is st and st2.version == v0
    # Releasing quota moves a cohort's generation and its shares.
    victim = fw.workloads["default/w-0"]
    fw.finish(victim)
    fw.delete_workload(victim)
    fw.tick()
    snapshot = fw.scheduler._mirror.refresh()
    st3 = solver.fair_share_state(snapshot)
    st3.verify(snapshot)


def test_fair_bulk_covers_every_cq_in_normal_tick():
    """`fair.bulk_miss` stays 0 when the solver's encoding is current —
    every ClusterQueue's share comes from the bulk tensors, never the
    per-CQ dict walk."""
    fw = build(None, S2A_FIRST)
    for i in range(8):
        fw.submit(Workload(
            name=f"w-{i}", namespace="default",
            queue_name=f"lq-{i % 8}", priority=0, creation_time=float(i),
            pod_sets=[PodSet.make("ps0", count=1, cpu=6)]))
    for _ in range(4):
        fw.tick()
        assert fw.scheduler._fair_bulk_miss == 0
    assert fw.scheduler._tick_fair_state is not None


def test_sharded_fair_shares_bitwise_identical():
    """The per-shard share kernel (zero collectives over the cohort
    mesh) equals the numpy arithmetic bitwise."""
    from kueue_tpu.models.fair_share import weighted_shares_np
    from kueue_tpu.parallel.mesh import CohortMesh, sharded_fair_shares

    rnd = np.random.RandomState(7)
    C, F, R = 23, 3, 2
    nominal = rnd.randint(0, 50, size=(C, F, R)).astype(np.int64)
    usage = rnd.randint(0, 80, size=(C, F, R)).astype(np.int64)
    cap = rnd.randint(0, 120, size=(C, R)).astype(np.int64)
    cap[3] = 0
    weight = rnd.choice([0.0, 1.0, 2.0, 4.0], size=C)
    above = np.maximum(usage - nominal, 0).sum(axis=1)
    want = weighted_shares_np(above, cap, weight)
    cmesh = CohortMesh(4)
    got = sharded_fair_shares(cmesh, nominal, usage, cap, weight)
    assert got.shape == want.shape
    assert np.array_equal(got, want)


def test_quiescent_fair_steady_state_dispatches_nothing():
    """The fair twin of the PR-6 quiescent-tick contract: with fair
    sharing ON, a steady state (StrictFIFO, nothing changing) replays
    fingerprint-cached verdicts, dispatches ZERO solves, and takes the
    quiescent-tick replay path — fair sharing no longer defeats the
    steady-state machinery."""
    fw = Framework(batch_solver=BatchSolver())
    fw.create_namespace("default", labels={})
    fw.create_resource_flavor(make_flavor("default"))
    import dataclasses
    for i in range(3):
        cq = make_cq(f"cq-{i}", rg("cpu", fq("default", cpu=4)),
                     cohort="pool", strategy="StrictFIFO")
        cq = dataclasses.replace(cq,
                                 fair_sharing=FairSharing(weight=2.0))
        fw.create_cluster_queue(cq)
        fw.create_local_queue(make_lq(f"lq-{i}", "default", cq=f"cq-{i}"))
    for i in range(3):
        for j in range(3):
            fw.submit(Workload(
                name=f"w-{i}-{j}", namespace="default",
                queue_name=f"lq-{i}", priority=0,
                creation_time=float(10 * i + j),
                pod_sets=[PodSet.make("ps0", count=1, cpu=4)]))
    solver = fw.scheduler.batch_solver
    for _ in range(12):
        fw.tick()
    d0 = solver.dispatches
    q0 = fw.scheduler.metrics.quiescent_ticks
    for _ in range(5):
        fw.tick()
    assert solver.dispatches == d0, \
        "quiescent fair tick dispatched a solve"
    assert fw.scheduler.metrics.quiescent_ticks > q0, \
        "fair steady state never took the quiescent replay path"


def test_fair_share_gauge_served_from_bulk_and_pruned_on_delete():
    """The metrics scrape serves cluster_queue_fair_share from the share
    kernel's last tick output (no per-scrape snapshot + DRF walk) and a
    deleted ClusterQueue's series prunes away."""
    from kueue_tpu.metrics import REGISTRY
    from kueue_tpu.solver.fair_share import dominant_resource_share

    fw = build(None, S2A_FIRST)
    for i in range(4):
        fw.submit(Workload(
            name=f"w-{i}", namespace="default", queue_name=f"lq-{i}",
            priority=0, creation_time=float(i),
            pod_sets=[PodSet.make("ps0", count=1, cpu=6)]))
    for _ in range(4):
        fw.tick()
    assert fw.scheduler.batch_solver.fair_shares_last() is not None
    fw.update_metrics_gauges()
    snapshot = fw.scheduler._mirror.refresh()
    gauge = REGISTRY.cluster_queue_fair_share
    for name, cq in snapshot.cluster_queues.items():
        assert gauge.values.get((name,)) == pytest.approx(
            dominant_resource_share(cq)[0]), name
    # Delete a CQ: its series must prune on the next scrape, whether or
    # not a tick has rebuilt the share tensors since.
    fw.delete_cluster_queue("cq-7")
    fw.update_metrics_gauges()
    assert ("cq-7",) not in gauge.values


def test_fair_share_publication_fresh_after_drain():
    """The end-of-tick republish (`fair.publish`): a commit on the LAST
    tick before the system drains must reach the scrape — the
    nominate-time refresh alone runs before the cycle's commits, so a
    drained system would serve the pre-admission shares forever."""
    from kueue_tpu.solver.fair_share import dominant_resource_share

    fw = build(None, S2A_FIRST)
    # cq-5 (flatpool, nominal 4, borrowable to 8): cpu=6 borrows 2
    # above nominal, so its post-admission share is strictly positive.
    fw.submit(Workload(
        name="w-drain", namespace="default", queue_name="lq-5",
        priority=0, creation_time=1.0,
        pod_sets=[PodSet.make("ps0", count=1, cpu=6)]))
    fw.tick()
    assert fw.cache.cluster_queues["cq-5"].workloads, "setup: not admitted"
    # No further tick: the publication must already hold end-of-tick
    # shares, matching the referee on the CURRENT usage.
    shares = fw.scheduler.batch_solver.fair_shares_last()
    assert shares is not None
    snapshot = fw.scheduler._mirror.refresh()
    for name, cq in snapshot.cluster_queues.items():
        assert shares[name] == pytest.approx(
            dominant_resource_share(cq)[0]), name
    assert shares["cq-5"] > 0
