"""Fair sharing (KEP-1714): share values, fair admission ordering, fair
preemption strategies."""

import dataclasses

import pytest

from kueue_tpu import features
from kueue_tpu.api.types import (
    ClusterQueue,
    ClusterQueuePreemption,
    FairSharing,
    FlavorQuotas,
    ResourceGroup,
)
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.core.cache import Cache
from kueue_tpu.solver.fair_share import dominant_resource_share

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg
from tests.test_cache import admit


@pytest.fixture(autouse=True)
def fair_sharing_on():
    features.set_enabled(features.FAIR_SHARING, True)
    yield


def fair_cq(name, cohort="co", cpu=4, weight=None, preemption=None):
    spec = make_cq(name, rg("cpu", fq("default", cpu=cpu)), cohort=cohort,
                   preemption=preemption or ClusterQueuePreemption(
                       reclaim_within_cohort="Any",
                       within_cluster_queue="LowerPriority"))
    if weight is not None:
        spec = dataclasses.replace(spec,
                                   fair_sharing=FairSharing(weight=weight))
    return spec


def two_cq_cache(weight_a=None, weight_b=None, cpu=4):
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(fair_cq("cq-a", cpu=cpu, weight=weight_a))
    cache.add_cluster_queue(fair_cq("cq-b", cpu=cpu, weight=weight_b))
    cache.add_local_queue(make_lq("a", cq="cq-a"))
    cache.add_local_queue(make_lq("b", cq="cq-b"))
    return cache


def test_share_value_zero_without_borrowing():
    cache = two_cq_cache()
    cache.add_or_update_workload(admit(make_wl("w", "a", cpu=4), "cq-a", "default"))
    snap = cache.snapshot()
    assert dominant_resource_share(snap.cluster_queues["cq-a"]) == (0.0, "")


def test_share_value_proportional_to_overage():
    cache = two_cq_cache()
    # cq-a uses 6 of its 4 nominal: 2 above, cohort lendable 8.
    cache.add_or_update_workload(admit(make_wl("w", "a", cpu=6), "cq-a", "default"))
    snap = cache.snapshot()
    share, dom = dominant_resource_share(snap.cluster_queues["cq-a"])
    assert share == (2000 * 1024) // 8000
    assert dom == "cpu"


def test_share_value_weighted():
    cache = two_cq_cache(weight_a=2.0)
    cache.add_or_update_workload(admit(make_wl("w", "a", cpu=6), "cq-a", "default"))
    snap = cache.snapshot()
    share, _ = dominant_resource_share(snap.cluster_queues["cq-a"])
    assert share == ((2000 * 1024) // 8000) / 2.0


def test_share_value_zero_weight_is_infinite():
    cache = two_cq_cache(weight_a=0.0)
    cache.add_or_update_workload(admit(make_wl("w", "a", cpu=6), "cq-a", "default"))
    snap = cache.snapshot()
    share, _ = dominant_resource_share(snap.cluster_queues["cq-a"])
    assert share == float("inf")


def test_share_value_with_delta():
    cache = two_cq_cache()
    snap = cache.snapshot()
    share, _ = dominant_resource_share(
        snap.cluster_queues["cq-a"], {"default": {"cpu": 6000}})
    assert share == (2000 * 1024) // 8000


def test_fair_admission_ordering():
    # Both CQ heads borrow; the CQ with the lower current share admits first
    # even though the other head is older.
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(fair_cq("cq-a", cpu=2))
    fw.create_cluster_queue(fair_cq("cq-b", cpu=2))
    fw.create_cluster_queue(fair_cq("cq-c", cpu=8))
    fw.create_local_queue(make_lq("a", cq="cq-a"))
    fw.create_local_queue(make_lq("b", cq="cq-b"))
    # cq-a is already borrowing 2 (share > 0); cq-b borrows nothing yet.
    wa0 = admit(make_wl("a0", "a", cpu=4), "cq-a", "default")
    fw.cache.add_or_update_workload(wa0)
    # Two new heads, each needing 4 (borrowing): only one fits (12 total,
    # 4 used, 8 free -> both would fit... shrink: use 6-cpu requests).
    fw.submit(make_wl("a1", "a", cpu=6, creation_time=1.0))
    fw.submit(make_wl("b1", "b", cpu=6, creation_time=2.0))
    fw.scheduler.schedule(timeout=0.0)
    fw.reconcile()
    # cq-b has the lower share -> b1 admitted despite being newer.
    assert fw.admitted_workloads("cq-b") == ["default/b1"]


def test_fair_preemption_rebalances():
    # TeamE/TeamW story: E borrowed the whole shared pool; W arrives and
    # reclaims its fair share via preemption.
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(fair_cq("team-e", cpu=4))
    fw.create_cluster_queue(fair_cq("team-w", cpu=4))
    fw.create_local_queue(make_lq("e", cq="team-e"))
    fw.create_local_queue(make_lq("w", cq="team-w"))
    for i in range(4):
        fw.submit(make_wl(f"e{i}", "e", cpu=2, creation_time=float(i)))
    fw.run_until_settled()
    assert len(fw.admitted_workloads("team-e")) == 4  # 8 cpu: 4 borrowed
    # W submits two 2-cpu workloads: it should get capacity back.
    fw.submit(make_wl("w0", "w", cpu=2, creation_time=10.0))
    fw.submit(make_wl("w1", "w", cpu=2, creation_time=11.0))
    fw.run_until_settled()
    assert len(fw.admitted_workloads("team-w")) == 2
    assert len(fw.admitted_workloads("team-e")) == 2


@pytest.mark.parametrize("weight,expect_preempt", [(1.0, True), (3.0, False)])
def test_fair_preemption_respects_weight(weight, expect_preempt):
    # heavy borrows the whole pool. A borrowing request from light preempts
    # heavy at weight 1 (equal standing) but not at weight 3 (heavy's
    # weighted share stays below light's prospective share).
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(fair_cq("heavy", cpu=2, weight=weight))
    fw.create_cluster_queue(fair_cq("light", cpu=2))
    fw.create_cluster_queue(fair_cq("pool", cpu=2))
    fw.create_local_queue(make_lq("h", cq="heavy"))
    fw.create_local_queue(make_lq("l", cq="light"))
    for i in range(3):
        fw.submit(make_wl(f"h{i}", "h", cpu=2, creation_time=float(i)))
    fw.run_until_settled()
    assert len(fw.admitted_workloads("heavy")) == 3  # borrowing 4 of 6
    # light asks 3.5: its prospective share (1.5 above nominal) exceeds
    # heavy's weighted share only at weight 1.
    fw.submit(make_wl("l0", "l", cpu="3500m", creation_time=10.0))
    fw.run_until_settled()
    if expect_preempt:
        assert len(fw.admitted_workloads("light")) == 1
        assert len(fw.admitted_workloads("heavy")) == 1
    else:
        assert len(fw.admitted_workloads("light")) == 0
        assert len(fw.admitted_workloads("heavy")) == 3


def test_device_share_values_match_host():
    from kueue_tpu.models.fair_share import share_values
    cache = two_cq_cache(weight_a=2.0)
    cache.add_or_update_workload(admit(make_wl("w", "a", cpu=7), "cq-a", "default"))
    cache.add_or_update_workload(admit(make_wl("w2", "b", cpu=3), "cq-b", "default"))
    snap = cache.snapshot()
    device = share_values(snap)
    for name, cq in snap.cluster_queues.items():
        host = dominant_resource_share(cq)
        assert device[name][0] == host[0], name
        if host[0] > 0:
            assert device[name][1] == host[1], name


def test_fair_preemption_honors_reclaim_never():
    # The preemptor CQ forbids cross-queue reclaim: fair sharing must not
    # override the per-CQ contract.
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(fair_cq(
        "strict", cpu=4,
        preemption=ClusterQueuePreemption(reclaim_within_cohort="Never")))
    fw.create_cluster_queue(fair_cq("greedy", cpu=4))
    fw.create_local_queue(make_lq("s", cq="strict"))
    fw.create_local_queue(make_lq("g", cq="greedy"))
    for i in range(4):
        fw.submit(make_wl(f"g{i}", "g", cpu=2, creation_time=float(i)))
    fw.run_until_settled()
    assert len(fw.admitted_workloads("greedy")) == 4
    fw.submit(make_wl("s0", "s", cpu=2, creation_time=10.0))
    fw.run_until_settled()
    # No preemption allowed: strict stays pending.
    assert fw.admitted_workloads("strict") == []
    assert len(fw.admitted_workloads("greedy")) == 4


def test_fair_preemption_scans_past_strategy_failing_head():
    # Offender's head victim is huge (removing it would drop the offender
    # below the preemptor's share under S2-a), but a smaller later victim
    # satisfies the rule.
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(fair_cq("x", cpu=2))
    fw.create_cluster_queue(fair_cq("y", cpu=2))
    fw.create_cluster_queue(fair_cq("pool", cpu=8))
    fw.create_local_queue(make_lq("x", cq="x"))
    fw.create_local_queue(make_lq("y", cq="y"))
    # y borrows 8: one big 6-cpu (newest => head candidate) + two 2-cpu.
    fw.submit(make_wl("y-small1", "y", cpu=2, creation_time=1.0))
    fw.submit(make_wl("y-small2", "y", cpu=2, creation_time=2.0))
    fw.submit(make_wl("y-big", "y", cpu=6, creation_time=3.0))
    fw.run_until_settled()
    assert len(fw.admitted_workloads("y")) == 3
    # x asks 6 (borrowing 4): evicting y-big (the newest => head candidate)
    # would drop y's share below x's prospective share, failing S2-a; the
    # smaller victims later in the list pass it.
    fw.submit(make_wl("x0", "x", cpu=6, creation_time=10.0))
    fw.run_until_settled()
    assert fw.admitted_workloads("x") == ["default/x0"]
    evicted = sorted(w.name for w in fw.workloads.values() if w.is_evicted)
    assert evicted == ["y-small1", "y-small2"]


def test_batch_solver_fair_shares_match_referee():
    """BatchSolver.fair_shares (the scheduler's vectorized share source)
    must equal dominant_resource_share for every ClusterQueue — on flat
    cohorts, cohortless CQs, and hierarchical trees (where the capacity
    denominator is the whole structure under the root)."""
    import random

    from kueue_tpu.api.types import CohortSpec, FairSharing
    from kueue_tpu.controllers.runtime import Framework
    from kueue_tpu.models.flavor_fit import BatchSolver
    from tests.util import fq, make_cq, make_flavor, make_lq, rg

    rnd = random.Random(3)
    fw = Framework(batch_solver=BatchSolver())
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_resource_flavor(make_flavor("spot"))
    fw.create_cohort(CohortSpec(name="root"))
    fw.create_cohort(CohortSpec(name="mid", parent="root"))
    for i in range(9):
        cohort_name = ("" if i % 3 == 0
                       else "flatpool" if i % 3 == 1 else "mid")
        cq = make_cq(
            f"cq-{i}",
            rg(("cpu",), fq("default", cpu=8), fq("spot", cpu=4)),
            cohort=cohort_name)
        cq = dataclasses.replace(cq, fair_sharing=FairSharing(
            weight=float(rnd.choice([0, 1, 2, 4]))))
        fw.create_cluster_queue(cq)
        fw.create_local_queue(make_lq(f"lq-{i}", cq=f"cq-{i}"))
    for i in range(9):
        for j in range(rnd.randint(0, 3)):
            fw.submit(make_wl(f"w-{i}-{j}", f"lq-{i}", cpu=rnd.randint(2, 6),
                              creation_time=float(i * 10 + j)))
    fw.run_until_settled(max_ticks=40)

    snapshot = fw.scheduler._mirror.refresh()
    # Force the encoding to exist (a tick may not have run the solver).
    fw.scheduler.batch_solver._encoding_for(snapshot)
    fw.scheduler.batch_solver._usage_enc.refresh(snapshot)
    shares = fw.scheduler.batch_solver.fair_shares(snapshot)
    assert shares is not None
    for name, cq in snapshot.cluster_queues.items():
        want = dominant_resource_share(cq)[0]
        assert shares[name] == want, (name, shares[name], want)
