"""Golden flavor-assignment scenarios transliterated from the reference's
TestAssignFlavors table (pkg/scheduler/flavorassigner/flavorassigner_test.go
:40-1455): same flavors (one/two/b_one/b_two/tainted), same ClusterQueue
quota shapes, usage and cohort overlays, same expected per-resource
(flavor, mode) assignments, representative mode, usage, and borrowing flag.

Run against both the sequential referee and the batched device kernel
(through BatchSolver-equivalent plumbing) via the shared `solve` helper."""

import pytest

from kueue_tpu.api.resources import resource_value
from kueue_tpu.api.types import (
    FlavorQuotas,
    MatchExpression,
    PodSet,
    ResourceFlavor,
    ResourceQuota,
    Taint,
    Toleration,
    Workload,
)
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.workload import WorkloadInfo
from kueue_tpu.models.flavor_fit import (
    decode_assignments,
    solve_flavor_fit,
)
from kueue_tpu.solver import schema as sch
from kueue_tpu.solver.modes import FIT, NO_FIT, PREEMPT
from kueue_tpu.solver.referee import assign_flavors

from tests.util import fq, make_cq, make_flavor, rg

GPU = "example.com/gpu"
Mi = 1024 * 1024
Gi = 1024 * Mi


def cpu(v):
    return resource_value("cpu", v)


def gpu_quotas(name, nominal):
    """FlavorQuotas for the gpu resource (not a Python identifier)."""
    return FlavorQuotas(name=name,
                        resources=((GPU, ResourceQuota(nominal=nominal)),))


def flavors():
    return [
        make_flavor("default"),
        make_flavor("one", type="one"),
        make_flavor("two", type="two"),
        make_flavor("b_one", b_type="one"),
        make_flavor("b_two", b_type="two"),
        ResourceFlavor.make("tainted",
                            node_taints=[Taint(key="instance", value="spot")]),
    ]


def build(cq_spec, usage=None, extra=()):
    """Build a snapshot around ClusterQueue "cq".

    `usage` overlays admitted usage onto "cq"; `extra` is a list of
    (cq_spec, usage) cohort members that realize the reference scenarios'
    explicit Cohort RequestableResources/Usage numbers — the reference sets
    those internal fields directly, but here cohort aggregates are always
    derived from members (as in production), so the same totals are
    produced by real member ClusterQueues instead.
    """
    cache = Cache()
    for f in flavors():
        cache.add_or_update_resource_flavor(f)
    cache.add_cluster_queue(cq_spec)
    for spec, _ in extra:
        cache.add_cluster_queue(spec)
    # Scenarios referencing a nonexistent flavor exercise the assigner's
    # skip-missing-flavor path; in the full framework such a CQ is inactive
    # and never reaches the assigner (the reference test also constructs the
    # internal struct directly, bypassing the Active condition).
    cache.cluster_queues["cq"].has_missing_flavors = False
    for name, cq_usage in [("cq", usage)] + [
            (spec.name, u) for spec, u in extra]:
        for fname, res in (cq_usage or {}).items():
            for rname, val in res.items():
                cache.cluster_queues[name].usage.setdefault(
                    fname, {})[rname] = val
    snap = cache.snapshot()
    return snap, snap.cluster_queues["cq"]


@pytest.fixture(params=["referee", "device"])
def solve(request):
    """assignment = solve(snap, cq, workload): referee or device kernel."""
    if request.param == "referee":
        def _solve(snap, cq, workload):
            wi = WorkloadInfo(workload, cluster_queue="cq")
            return assign_flavors(wi, cq, snap.resource_flavors)
    else:
        def _solve(snap, cq, workload):
            wi = WorkloadInfo(workload, cluster_queue="cq")
            enc = sch.encode_cluster_queues(snap)
            usage = sch.encode_usage(snap, enc)
            wt = sch.encode_workloads([wi], snap, enc)
            out = solve_flavor_fit(enc, usage, wt)
            return decode_assignments([wi], snap, enc, out)[0]
    return _solve


def mk_wl(pod_sets, reclaimable=None):
    w = Workload(name="wl", namespace="ns", queue_name="q",
                 pod_sets=list(pod_sets), creation_time=1.0)
    if reclaimable:
        w.reclaimable_pods = dict(reclaimable)
    return w


def got_flavors(assignment):
    return [{r: (fa.name, fa.mode) for r, fa in ps.flavors.items()}
            for ps in assignment.pod_sets]


# "single flavor, fits"
def test_single_flavor_fits(solve):
    snap, cq = build(make_cq("cq", rg(("cpu", "memory"),
                                      fq("default", cpu=1, memory="1Mi"))))
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=1, memory="1Mi")]))
    assert a.representative_mode == FIT
    assert got_flavors(a) == [
        {"cpu": ("default", FIT), "memory": ("default", FIT)}]
    assert a.usage == {"default": {"cpu": 1000, "memory": Mi}}


# "single flavor, used resources, doesn't fit"
def test_single_flavor_used_resources_preempt(solve):
    snap, cq = build(make_cq("cq", rg("cpu", fq("default", cpu=4))),
                     usage={"default": {"cpu": 3000}})
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=2)]))
    assert a.representative_mode == PREEMPT
    assert got_flavors(a) == [{"cpu": ("default", PREEMPT)}]
    assert a.usage == {"default": {"cpu": 2000}}


# "multiple resource groups, fits"
def test_multiple_resource_groups_fits(solve):
    snap, cq = build(make_cq(
        "cq",
        rg("cpu", fq("one", cpu=2), fq("two", cpu=4)),
        rg("memory", fq("b_one", memory="1Gi"), fq("b_two", memory="5Gi"))))
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=3, memory="10Mi")]))
    assert a.representative_mode == FIT
    assert got_flavors(a) == [
        {"cpu": ("two", FIT), "memory": ("b_one", FIT)}]
    assert a.usage == {"two": {"cpu": 3000}, "b_one": {"memory": 10 * Mi}}


# "multiple resource groups, one could fit with preemption, other doesn't fit"
def test_multiple_groups_one_preempt_other_nofit(solve):
    snap, cq = build(make_cq(
        "cq",
        rg("cpu", fq("one", cpu=3)),
        rg("memory", fq("b_one", memory="1Mi"))),
        usage={"one": {"cpu": 1000}})
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=3, memory="10Mi")]))
    assert a.representative_mode == NO_FIT
    assert a.usage == {}


# "multiple resource groups with multiple resources, fits"
def test_multiple_groups_multiple_resources_fits(solve):
    snap, cq = build(make_cq(
        "cq",
        rg(("cpu", "memory"), fq("one", cpu=2, memory="1Gi"),
           fq("two", cpu=4, memory="15Mi")),
        rg((GPU,), gpu_quotas("b_one", 4), gpu_quotas("b_two", 2))))
    a = solve(snap, cq, mk_wl([PodSet(name="main", count=1, requests={
        "cpu": cpu(3), "memory": 10 * Mi, GPU: 3})]))
    assert a.representative_mode == FIT
    assert got_flavors(a) == [{"cpu": ("two", FIT), "memory": ("two", FIT),
                               GPU: ("b_one", FIT)}]
    assert a.usage == {"two": {"cpu": 3000, "memory": 10 * Mi},
                       "b_one": {GPU: 3}}


# "multiple resource groups with multiple resources, fits with different
# modes"
def test_multiple_groups_fits_with_different_modes(solve):
    snap, cq = build(make_cq(
        "cq",
        rg(("cpu", "memory"), fq("one", cpu=2, memory="1Gi"),
           fq("two", cpu=4, memory="15Mi")),
        rg((GPU,), gpu_quotas("b_one", 4)),
        cohort="co"),
        usage={"two": {"memory": 10 * Mi}},
        # A zero-quota member borrowing 2 gpus realizes the reference's
        # cohort Usage{b_one: gpu 2} without adding requestable quota.
        extra=[(make_cq("cq-other", rg((GPU,), gpu_quotas("b_one", 0)),
                        cohort="co"),
                {"b_one": {GPU: 2}})])
    a = solve(snap, cq, mk_wl([PodSet(name="main", count=1, requests={
        "cpu": cpu(3), "memory": 10 * Mi, GPU: 3})]))
    assert a.representative_mode == PREEMPT
    assert got_flavors(a) == [{"cpu": ("two", FIT),
                               "memory": ("two", PREEMPT),
                               GPU: ("b_one", PREEMPT)}]
    assert a.usage == {"two": {"cpu": 3000, "memory": 10 * Mi},
                       "b_one": {GPU: 3}}


# "multiple flavors, fits while skipping tainted flavor"
def test_skip_tainted_flavor(solve):
    snap, cq = build(make_cq(
        "cq", rg("cpu", fq("tainted", cpu=4), fq("two", cpu=4))))
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=3)]))
    assert a.representative_mode == FIT
    assert got_flavors(a) == [{"cpu": ("two", FIT)}]


# "multiple flavors, skip missing ResourceFlavor"
def test_skip_missing_resource_flavor(solve):
    snap, cq = build(make_cq(
        "cq", rg("cpu", fq("nonexistent-flavor", cpu=4), fq("two", cpu=4))))
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=3)]))
    assert a.representative_mode == FIT
    assert got_flavors(a) == [{"cpu": ("two", FIT)}]


# "multiple flavors, fits a node selector" (irrelevant selector keys and
# affinity expressions are ignored)
def test_fits_node_selector_ignoring_foreign_keys(solve):
    snap, cq = build(make_cq(
        "cq", rg("cpu", fq("nonexistent-flavor", cpu=4), fq("one", cpu=4),
                 fq("two", cpu=4))))
    w = mk_wl([PodSet.make(
        "main", 1, cpu=1,
        node_selector={"type": "two", "ignored1": "foo"},
        affinity_terms=[[MatchExpression("ignored2", "In", ("bar",))]])])
    a = solve(snap, cq, w)
    assert a.representative_mode == FIT
    assert got_flavors(a) == [{"cpu": ("two", FIT)}]


# "multiple flavors, fits with node affinity"
def test_fits_with_node_affinity(solve):
    snap, cq = build(make_cq(
        "cq", rg(("cpu", "memory"), fq("one", cpu=4, memory="1Gi"),
                 fq("two", cpu=4, memory="1Gi"))))
    w = mk_wl([PodSet.make(
        "main", 1, cpu=1, memory="1Mi",
        node_selector={"ignored1": "foo"},
        affinity_terms=[[MatchExpression("type", "In", ("two",))]])])
    a = solve(snap, cq, w)
    assert a.representative_mode == FIT
    assert got_flavors(a) == [
        {"cpu": ("two", FIT), "memory": ("two", FIT)}]


# "multiple flavors, node affinity fits any flavor" (ORed terms; a term
# with only foreign keys matches everything)
def test_node_affinity_fits_any_flavor(solve):
    snap, cq = build(make_cq(
        "cq", rg("cpu", fq("one", cpu=4), fq("two", cpu=4))))
    w = mk_wl([PodSet.make(
        "main", 1, cpu=1,
        affinity_terms=[[MatchExpression("ignored2", "In", ("bar",))],
                        [MatchExpression("cpuType", "In", ("two",))]])])
    a = solve(snap, cq, w)
    assert a.representative_mode == FIT
    assert got_flavors(a) == [{"cpu": ("one", FIT)}]


# "multiple flavors, doesn't fit node affinity"
def test_does_not_fit_node_affinity(solve):
    snap, cq = build(make_cq(
        "cq", rg("cpu", fq("one", cpu=4), fq("two", cpu=4))))
    w = mk_wl([PodSet.make(
        "main", 1, cpu=1,
        affinity_terms=[[MatchExpression("type", "In", ("three",))]])])
    a = solve(snap, cq, w)
    assert a.representative_mode == NO_FIT
    assert a.usage == {}


# "multiple specs, fit different flavors"
def test_multiple_specs_fit_different_flavors(solve):
    snap, cq = build(make_cq(
        "cq", rg("cpu", fq("one", cpu=4), fq("two", cpu=10))))
    a = solve(snap, cq, mk_wl([PodSet.make("driver", 1, cpu=5),
                               PodSet.make("worker", 1, cpu=3)]))
    assert a.representative_mode == FIT
    assert got_flavors(a) == [{"cpu": ("two", FIT)}, {"cpu": ("one", FIT)}]
    assert a.usage == {"one": {"cpu": 3000}, "two": {"cpu": 5000}}


# "multiple specs, fits borrowing"
def test_multiple_specs_fits_borrowing(solve):
    snap, cq = build(make_cq(
        "cq", rg(("cpu", "memory"),
                 fq("default", cpu=(2, 98), memory="2Gi")),
        cohort="co"),
        extra=[(make_cq("cq-other",
                        rg(("cpu", "memory"),
                           fq("default", cpu=198, memory="198Gi")),
                        cohort="co"), None)])
    a = solve(snap, cq, mk_wl([
        PodSet.make("driver", 1, cpu=4, memory="1Gi"),
        PodSet.make("worker", 1, cpu=6, memory="4Gi")]))
    assert a.representative_mode == FIT
    assert a.borrowing
    assert got_flavors(a) == [
        {"cpu": ("default", FIT), "memory": ("default", FIT)},
        {"cpu": ("default", FIT), "memory": ("default", FIT)}]
    assert a.usage == {"default": {"cpu": 10000, "memory": 5 * Gi}}


# "not enough space to borrow"
def test_not_enough_space_to_borrow(solve):
    snap, cq = build(make_cq(
        "cq", rg("cpu", fq("one", cpu=1)), cohort="co"),
        extra=[(make_cq("cq-other", rg("cpu", fq("one", cpu=9)),
                        cohort="co"), {"one": {"cpu": 9_000}})])
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=2)]))
    assert a.representative_mode == NO_FIT
    assert a.usage == {}


# "past max, but can preempt in ClusterQueue"
def test_past_max_can_preempt_in_cluster_queue(solve):
    snap, cq = build(make_cq(
        "cq", rg("cpu", fq("one", cpu=(2, 8))), cohort="co"),
        usage={"one": {"cpu": 9_000}},
        extra=[(make_cq("cq-other", rg("cpu", fq("one", cpu=98)),
                        cohort="co"), None)])
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=2)]))
    assert a.representative_mode == PREEMPT
    assert got_flavors(a) == [{"cpu": ("one", PREEMPT)}]
    assert a.usage == {"one": {"cpu": 2000}}


# "past min, but can preempt in ClusterQueue"
def test_past_min_can_preempt_in_cluster_queue(solve):
    snap, cq = build(make_cq("cq", rg("cpu", fq("one", cpu=2))),
                     usage={"one": {"cpu": 1_000}})
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=2)]))
    assert a.representative_mode == PREEMPT
    assert got_flavors(a) == [{"cpu": ("one", PREEMPT)}]


# "past min, but can preempt in cohort and ClusterQueue"
def test_past_min_can_preempt_in_cohort_and_cq(solve):
    snap, cq = build(make_cq(
        "cq", rg("cpu", fq("one", cpu=3)), cohort="co"),
        usage={"one": {"cpu": 2_000}},
        extra=[(make_cq("cq-other", rg("cpu", fq("one", cpu=7)),
                        cohort="co"), {"one": {"cpu": 8_000}})])
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=2)]))
    assert a.representative_mode == PREEMPT
    assert got_flavors(a) == [{"cpu": ("one", PREEMPT)}]


# "can only preempt flavors that match affinity"
def test_can_only_preempt_flavors_matching_affinity(solve):
    snap, cq = build(make_cq(
        "cq", rg("cpu", fq("one", cpu=4), fq("two", cpu=4))),
        usage={"one": {"cpu": 3000}, "two": {"cpu": 3000}})
    w = mk_wl([PodSet.make("main", 1, cpu=2,
                           node_selector={"type": "two"})])
    a = solve(snap, cq, w)
    assert a.representative_mode == PREEMPT
    assert got_flavors(a) == [{"cpu": ("two", PREEMPT)}]
    assert a.usage == {"two": {"cpu": 2000}}


# "each podset requires preemption on a different flavor"
def test_each_podset_preempts_different_flavor(solve):
    snap, cq = build(make_cq(
        "cq", rg("cpu", fq("one", cpu=4), fq("tainted", cpu=10))),
        usage={"one": {"cpu": 3000}, "tainted": {"cpu": 3000}})
    w = mk_wl([
        PodSet.make("launcher", 1, cpu=2),
        PodSet.make("workers", 10, cpu=1, tolerations=[
            Toleration(key="instance", operator="Equal", value="spot",
                       effect="NoSchedule")]),
    ])
    a = solve(snap, cq, w)
    assert a.representative_mode == PREEMPT
    assert got_flavors(a) == [{"cpu": ("one", PREEMPT)},
                              {"cpu": ("tainted", PREEMPT)}]
    assert a.usage == {"one": {"cpu": 2000}, "tainted": {"cpu": 10000}}


# "resource not listed in clusterQueue"
def test_resource_not_listed_in_cluster_queue(solve):
    snap, cq = build(make_cq("cq", rg("cpu", fq("one", cpu=4))))
    a = solve(snap, cq, mk_wl([PodSet(name="main", count=1,
                                      requests={GPU: 2})]))
    assert a.representative_mode == NO_FIT
    assert a.usage == {}


# "flavor not found"
def test_flavor_not_found(solve):
    snap, cq = build(make_cq(
        "cq", rg("cpu", fq("nonexistent-flavor", cpu=1))))
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=1)]))
    assert a.representative_mode == NO_FIT
    assert a.usage == {}


# "num pods fit"
def test_num_pods_fit(solve):
    snap, cq = build(make_cq(
        "cq", rg(("cpu", "pods"), fq("default", cpu=10, pods=3))))
    a = solve(snap, cq, mk_wl([PodSet.make("main", 3, cpu=1)]))
    assert a.representative_mode == FIT
    assert got_flavors(a) == [
        {"cpu": ("default", FIT), "pods": ("default", FIT)}]
    assert a.usage == {"default": {"cpu": 3000, "pods": 3}}


# "num pods don't fit"
def test_num_pods_dont_fit(solve):
    snap, cq = build(make_cq(
        "cq", rg(("cpu", "pods"), fq("default", cpu=10, pods=2))))
    a = solve(snap, cq, mk_wl([PodSet.make("main", 3, cpu=1)]))
    assert a.representative_mode == NO_FIT
    assert a.usage == {}


# "with reclaimable pods"
def test_with_reclaimable_pods(solve):
    snap, cq = build(make_cq(
        "cq", rg(("cpu", "pods"), fq("default", cpu=10, pods=3))))
    w = mk_wl([PodSet.make("main", 5, cpu=1)], reclaimable={"main": 2})
    a = solve(snap, cq, w)
    assert a.representative_mode == FIT
    assert got_flavors(a) == [
        {"cpu": ("default", FIT), "pods": ("default", FIT)}]
    assert a.usage == {"default": {"cpu": 3000, "pods": 3}}


# -- round-4 expansion: the remaining TestAssignFlavors cases ----------------

from kueue_tpu import features
from kueue_tpu.api.types import (
    BorrowWithinCohort,
    ClusterQueuePreemption,
    FlavorFungibility,
)


def mk_wl_tolerating(count, cpu_v):
    return mk_wl([PodSet.make(
        "main", count, cpu=cpu_v,
        tolerations=[Toleration(key="instance", operator="Equal",
                                value="spot", effect="NoSchedule")])])


# "single flavor, fits tainted flavor"
def test_single_flavor_fits_tainted_flavor(solve):
    snap, cq = build(make_cq("cq", rg("cpu", fq("tainted", cpu=4))))
    a = solve(snap, cq, mk_wl_tolerating(1, 1))
    assert a.representative_mode == FIT
    assert got_flavors(a) == [{"cpu": ("tainted", FIT)}]
    assert a.usage == {"tainted": {"cpu": 1000}}


# "multiple resources in a group, doesn't fit"
def test_multiple_resources_in_group_dont_fit(solve):
    snap, cq = build(make_cq(
        "cq", rg(("cpu", "memory"),
                 fq("one", cpu=2, memory="1Gi"),
                 fq("two", cpu=4, memory="5Mi"))))
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=3, memory="10Mi")]))
    assert a.representative_mode == NO_FIT
    assert a.usage == {}


def _two_flavor_pods_cq(fungibility, one_quota=None, two_quota=None):
    return make_cq(
        "cq",
        rg(("cpu", "pods"),
           fq("one", cpu=one_quota if one_quota is not None else 10, pods=10),
           fq("two", cpu=two_quota if two_quota is not None else 10, pods=10)),
        fungibility=fungibility)


# "preempt before try next flavor": WhenCanPreempt=Preempt stops at the
# first flavor's Preempt instead of scanning to a Fit on flavor two.
def test_preempt_before_try_next_flavor(solve):
    snap, cq = build(
        _two_flavor_pods_cq(FlavorFungibility(
            when_can_borrow="Borrow", when_can_preempt="Preempt")),
        usage={"one": {"cpu": 2000}})
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=9)]))
    assert a.representative_mode == PREEMPT
    assert got_flavors(a) == [
        {"cpu": ("one", PREEMPT), "pods": ("one", FIT)}]
    assert a.usage == {"one": {"cpu": 9000, "pods": 1}}


# "preempt try next flavor": the default rule scans to flavor two's Fit.
def test_preempt_try_next_flavor(solve):
    snap, cq = build(_two_flavor_pods_cq(None),
                     usage={"one": {"cpu": 2000}})
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=9)]))
    assert a.representative_mode == FIT
    assert got_flavors(a) == [{"cpu": ("two", FIT), "pods": ("two", FIT)}]
    assert a.usage == {"two": {"cpu": 9000, "pods": 1}}


# "borrow try next flavor, found the first flavor": trying past the
# borrowing Fit on flavor one finds nothing better (flavor two can never
# hold the request), so flavor one's borrowing Fit is chosen.
def test_borrow_try_next_flavor_found_first(solve):
    snap, cq = build(
        make_cq("cq",
                rg(("cpu", "pods"),
                   fq("one", cpu=(10, 1), pods=10),
                   fq("two", cpu=1, pods=10)),
                cohort="co",
                fungibility=FlavorFungibility(
                    when_can_borrow="TryNextFlavor",
                    when_can_preempt="TryNextFlavor")),
        usage={"one": {"cpu": 2000}},
        extra=[(make_cq("cq-other", rg("cpu", fq("one", cpu=1)),
                        cohort="co"), None)])
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=9)]))
    assert a.representative_mode == FIT
    assert a.borrowing
    assert got_flavors(a) == [{"cpu": ("one", FIT), "pods": ("one", FIT)}]
    assert a.usage == {"one": {"cpu": 9000, "pods": 1}}


# "borrow try next flavor, found the second flavor": flavor two fits
# without borrowing, so trying past flavor one's borrowing Fit wins.
def test_borrow_try_next_flavor_found_second(solve):
    snap, cq = build(
        make_cq("cq",
                rg(("cpu", "pods"),
                   fq("one", cpu=(10, 1), pods=10),
                   fq("two", cpu=10, pods=10)),
                cohort="co",
                fungibility=FlavorFungibility(
                    when_can_borrow="TryNextFlavor",
                    when_can_preempt="TryNextFlavor")),
        usage={"one": {"cpu": 2000}},
        extra=[(make_cq("cq-other", rg("cpu", fq("one", cpu=1)),
                        cohort="co"), None)])
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=9)]))
    assert a.representative_mode == FIT
    assert not a.borrowing
    assert got_flavors(a) == [{"cpu": ("two", FIT), "pods": ("two", FIT)}]
    assert a.usage == {"two": {"cpu": 9000, "pods": 1}}


# "borrow before try next flavor": the default WhenCanBorrow=Borrow stops
# at flavor one's borrowing Fit.
def test_borrow_before_try_next_flavor(solve):
    snap, cq = build(
        make_cq("cq",
                rg(("cpu", "pods"),
                   fq("one", cpu=(10, 1), pods=10),
                   fq("two", cpu=10, pods=10)),
                cohort="co"),
        usage={"one": {"cpu": 2000}},
        extra=[(make_cq("cq-other", rg("cpu", fq("one", cpu=1)),
                        cohort="co"), None)])
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=9)]))
    assert a.representative_mode == FIT
    assert a.borrowing
    assert got_flavors(a) == [{"cpu": ("one", FIT), "pods": ("one", FIT)}]
    assert a.usage == {"one": {"cpu": 9000, "pods": 1}}


def _bwc_cq(fungibility, one_cpu, cohort="co"):
    return make_cq(
        "cq", rg("cpu", fq("one", cpu=one_cpu), fq("two", cpu=12)),
        cohort=cohort,
        preemption=ClusterQueuePreemption(
            reclaim_within_cohort="LowerPriority",
            borrow_within_cohort=BorrowWithinCohort(policy="LowerPriority")),
        fungibility=fungibility)


# "when borrowing while preemption is needed for flavor one;
# WhenCanBorrow=Borrow": borrowWithinCohort turns the over-cohort-usage
# case into Preempt-with-borrowing, and WhenCanPreempt=Preempt stops there.
def test_borrow_with_preemption_needed_borrow(solve):
    snap, cq = build(
        _bwc_cq(FlavorFungibility(when_can_borrow="Borrow",
                                  when_can_preempt="Preempt"),
                one_cpu=(0, 12)),
        extra=[(make_cq("cq-other", rg("cpu", fq("one", cpu=12)),
                        cohort="co"), {"one": {"cpu": 10000}})])
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=12)]))
    assert a.representative_mode == PREEMPT
    assert a.borrowing
    assert got_flavors(a) == [{"cpu": ("one", PREEMPT)}]
    assert a.usage == {"one": {"cpu": 12000}}


# Same without a borrowingLimit on flavor one.
def test_borrow_with_preemption_needed_no_limit(solve):
    snap, cq = build(
        _bwc_cq(FlavorFungibility(when_can_borrow="Borrow",
                                  when_can_preempt="Preempt"),
                one_cpu=0),
        extra=[(make_cq("cq-other", rg("cpu", fq("one", cpu=12)),
                        cohort="co"), {"one": {"cpu": 10000}})])
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=12)]))
    assert a.representative_mode == PREEMPT
    assert a.borrowing
    assert got_flavors(a) == [{"cpu": ("one", PREEMPT)}]
    assert a.usage == {"one": {"cpu": 12000}}


# Same but WhenCanBorrow=TryNextFlavor: skip to flavor two's clean Fit.
def test_borrow_with_preemption_needed_try_next(solve):
    snap, cq = build(
        _bwc_cq(FlavorFungibility(when_can_borrow="TryNextFlavor",
                                  when_can_preempt="Preempt"),
                one_cpu=(0, 12)),
        extra=[(make_cq("cq-other", rg("cpu", fq("one", cpu=12)),
                        cohort="co"), {"one": {"cpu": 10000}})])
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=12)]))
    assert a.representative_mode == FIT
    assert not a.borrowing
    assert got_flavors(a) == [{"cpu": ("two", FIT)}]
    assert a.usage == {"two": {"cpu": 12000}}


# "when borrowing while preemption is needed, but borrowingLimit exceeds
# the quota available in the cohort": nothing can make the request fit.
def test_borrowing_limit_exceeds_cohort_quota(solve):
    snap, cq = build(
        make_cq("cq", rg("cpu", fq("one", cpu=(0, 12))), cohort="co",
                preemption=ClusterQueuePreemption(
                    reclaim_within_cohort="LowerPriority",
                    borrow_within_cohort=BorrowWithinCohort(
                        policy="LowerPriority"))),
        extra=[(make_cq("cq-other", rg("cpu", fq("one", cpu=11)),
                        cohort="co"), {"one": {"cpu": 10000}})])
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=12)]))
    assert a.representative_mode == NO_FIT
    assert a.usage == {}


# "lend try next flavor, found the second flavor"
def test_lend_try_next_flavor_found_second(solve):
    features.set_enabled(features.LENDING_LIMIT, True)
    snap, cq = build(
        make_cq("cq",
                rg(("cpu", "pods"),
                   fq("one", cpu=(10, None, 1), pods=10),
                   fq("two", cpu=(10, None, 0), pods=10)),
                cohort="co",
                fungibility=FlavorFungibility(
                    when_can_borrow="TryNextFlavor",
                    when_can_preempt="TryNextFlavor")),
        usage={"one": {"cpu": 2000}},
        extra=[(make_cq("cq-other", rg("cpu", fq("one", cpu=10),
                                       fq("two", cpu=10)),
                        cohort="co"), {"one": {"cpu": 2000}})])
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=9)]))
    assert a.representative_mode == FIT
    assert not a.borrowing
    assert got_flavors(a) == [{"cpu": ("two", FIT), "pods": ("two", FIT)}]
    assert a.usage == {"two": {"cpu": 9000, "pods": 1}}


# "lend try next flavor, found the first flavor"
def test_lend_try_next_flavor_found_first(solve):
    features.set_enabled(features.LENDING_LIMIT, True)
    snap, cq = build(
        make_cq("cq",
                rg(("cpu", "pods"),
                   fq("one", cpu=(10, None, 1), pods=10),
                   fq("two", cpu=(1, None, 0), pods=10)),
                cohort="co",
                fungibility=FlavorFungibility(
                    when_can_borrow="TryNextFlavor",
                    when_can_preempt="TryNextFlavor")),
        usage={"one": {"cpu": 2000}},
        extra=[(make_cq("cq-other", rg("cpu", fq("one", cpu=10),
                                       fq("two", cpu=1)),
                        cohort="co"), {"one": {"cpu": 2000}})])
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=9)]))
    assert a.representative_mode == FIT
    assert a.borrowing
    assert got_flavors(a) == [{"cpu": ("one", FIT), "pods": ("one", FIT)}]
    assert a.usage == {"one": {"cpu": 9000, "pods": 1}}


# "lendingLimit exceeded, but can preempt in cohort and ClusterQueue".
# The reference case writes internal cohort fields that its own production
# accumulation would not produce (GuaranteedQuota omitted while
# lendingLimit=0); here the same intent — the lendable pool is exhausted
# by above-guarantee usage, so the request needs cohort preemption — is
# realized with derived aggregates: the member's above-guarantee usage
# (10 used vs 9 guaranteed) eats its own 1-cpu lending pool.
def test_lending_limit_exceeded_can_preempt(solve):
    features.set_enabled(features.LENDING_LIMIT, True)
    snap, cq = build(
        make_cq("cq",
                rg(("cpu", "pods"),
                   fq("one", cpu=(10, None, 0), pods=10)),
                cohort="co"),
        usage={"one": {"cpu": 2000}},
        extra=[(make_cq("cq-other",
                        rg("cpu", fq("one", cpu=(10, None, 1))),
                        cohort="co"), {"one": {"cpu": 10000}})])
    a = solve(snap, cq, mk_wl([PodSet.make("main", 1, cpu=9)]))
    assert a.representative_mode == PREEMPT
    assert not a.borrowing
    assert got_flavors(a) == [{"cpu": ("one", PREEMPT), "pods": ("one", FIT)}]
    assert a.usage == {"one": {"cpu": 9000, "pods": 1}}
