"""Fleet-grade control plane drills: remote worker join over TLS +
auth (`--join`), degraded-mode admission when the coordinator dies
(flat cohorts keep admitting shard-locally, split roots park), the
rejoin catch-up reconcile with counted revocations, and the coordinator
restart/re-join cycle over the channel's session ids."""

import os
import socket
import threading
import time

import pytest

from kueue_tpu import features
from kueue_tpu.controllers.replica_runtime import (
    ReplicaRuntime,
    ReplicaWorker,
    _QueueChan,
    worker_join_main,
)
from kueue_tpu.metrics import REGISTRY
from kueue_tpu.transport import openssl_available
from kueue_tpu.transport.security import generate_self_signed

from tests.test_replica import _lending_world, _split_pair
from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg


def _flat_world(rt, n_cqs=4, cpu=4):
    rt.create_resource_flavor(make_flavor("default"))
    for i in range(n_cqs):
        rt.create_cluster_queue(make_cq(
            f"cq-{i}", rg("cpu", fq("default", cpu=cpu))))
        rt.create_local_queue(make_lq(f"lq-{i}", "default", cq=f"cq-{i}"))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _start_join_workers(port, tmp_path, n=2, cert=None, token=None,
                        degraded_after=0.3):
    threads = []
    for i in range(n):
        t = threading.Thread(
            target=worker_join_main, args=(("127.0.0.1", port),),
            kwargs=dict(state_dir=str(tmp_path / f"w{i}"),
                        tls_cafile=cert, auth_token=token,
                        node=f"node-{i}", join_timeout=60.0,
                        degraded_after=degraded_after),
            daemon=True)
        t.start()
        threads.append(t)
    return threads


# -- remote worker join -------------------------------------------------------


@pytest.mark.skipif(not openssl_available(), reason="needs openssl CLI")
def test_remote_join_admits_over_tls_with_auth(tmp_path):
    """The zero-emulation fleet shape: workers dial a REMOTE
    coordinator (TLS + token), receive shard groups + the admin seed
    over the channel, and the whole admission pipeline runs across the
    wire."""
    cert, key = generate_self_signed(str(tmp_path / "pki"))
    port = _free_port()
    _start_join_workers(port, tmp_path, cert=cert, token="sekrit")
    rt = ReplicaRuntime(2, remote=True, transport="socket",
                        listen=("127.0.0.1", port), engine="host",
                        solver=False,
                        state_dir=str(tmp_path / "coord"),
                        tls_cert=cert, tls_key=key,
                        auth_token="sekrit", join_timeout=60.0,
                        degraded_after=0.5)
    try:
        # Join ORDER is a race (whichever worker dials first gets wid
        # 0); membership is not.
        assert sorted(w.host_id for w in rt.workers) \
            == ["node-0", "node-1"]
        assert all(w.remote for w in rt.workers)
        assert sorted(rt.group_owner) == [0, 1]
        _flat_world(rt)
        for i in range(4):
            rt.submit(make_wl(f"w-{i}", f"lq-{i}", cpu=3,
                              creation_time=float(i)))
        for _ in range(3):
            rt.tick()
        dump = rt.dump()
        assert sum(len(v) for v in dump["admitted"].values()) == 4
        # Workers journal on their OWN disks (per-host by construction).
        for i in range(2):
            journals = [f for f in os.listdir(tmp_path / f"w{i}")
                        if f.startswith("journal-g")]
            assert journals, f"worker {i} journaled nothing locally"
        assert rt.listener.rejected_hellos == 0
        info = rt.reconcile_info()
        assert info["remoteWorkers"] is True
        assert {h["host"] for h in info["hosts"].values()} \
            == {"node-0", "node-1"}
    finally:
        rt.close()


@pytest.mark.skipif(not openssl_available(), reason="needs openssl CLI")
def test_wrong_token_hello_rejected_counted_and_logged(tmp_path,
                                                      capfd):
    from kueue_tpu.transport import ChannelListener, SocketChannel

    cert, key = generate_self_signed(str(tmp_path / "pki"))
    from kueue_tpu.transport.security import (client_tls_context,
                                              server_tls_context)

    before = REGISTRY.channel_rejected_hellos_total.get("auth")
    listener = ChannelListener(
        "127.0.0.1", 0, tls_context=server_tls_context(cert, key),
        auth_token="right")
    chan = SocketChannel.connect(
        listener.address, cid="join/evil", auth_token="wrong",
        tls_context=client_tls_context(cert))
    try:
        deadline = time.monotonic() + 10
        while listener.rejected_hellos == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert listener.rejected_hellos >= 1
        assert REGISTRY.channel_rejected_hellos_total.get("auth") \
            > before
        assert "rejected hello (auth)" in capfd.readouterr().err
    finally:
        chan.close()
        listener.close()


def test_plaintext_hello_against_tls_listener_rejected(tmp_path):
    if not openssl_available():
        pytest.skip("needs openssl CLI")
    from kueue_tpu.transport import ChannelListener, SocketChannel
    from kueue_tpu.transport.security import server_tls_context

    cert, key = generate_self_signed(str(tmp_path / "pki"))
    before = REGISTRY.channel_rejected_hellos_total.get("tls")
    listener = ChannelListener(
        "127.0.0.1", 0, tls_context=server_tls_context(cert, key))
    chan = SocketChannel.connect(listener.address, cid="join/plain")
    try:
        deadline = time.monotonic() + 10
        while listener.rejected_hellos == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert listener.rejected_hellos >= 1
        assert REGISTRY.channel_rejected_hellos_total.get("tls") > before
    finally:
        chan.close()
        listener.close()


# -- degraded-mode admission --------------------------------------------------


def test_degraded_window_flat_cohorts_keep_admitting(tmp_path):
    """The acceptance drill (loopback transport): coordinator silent
    for >= K ticks -> flat-cohort admission throughput stays > 0,
    every degraded verdict is journaled with a degraded-epoch stamp,
    the gauge raises and zeroes, and post-rejoin state equals an
    uninterrupted run (no revocations needed here: nothing
    oversubscribed)."""
    import json

    state = tmp_path / "state"
    rt = ReplicaRuntime(2, spawn=False, engine="host",
                        state_dir=str(state), degraded_after=0.25)
    try:
        _flat_world(rt)
        for i in range(4):
            rt.submit(make_wl(f"w-{i}", f"lq-{i}", cpu=3,
                              creation_time=float(i)))
        rt.tick()
        # New arrivals land, then the coordinator goes silent.
        for i in range(4):
            rt.submit(make_wl(f"d-{i}", f"lq-{i}", cpu=1,
                              creation_time=float(10 + i)))
        rt.degraded_window(1.2)
        hosts_degraded = [h for h in ("host-0", "host-1")
                          if REGISTRY.coordinator_degraded.get(h) == 1.0]
        assert hosts_degraded, "no replica raised the degraded gauge"
        ev = rt.rejoin()
        assert ev["degraded_workers"] >= 1
        assert ev["degraded_window_ticks"] >= 3
        assert ev["degraded_admissions"] == 4  # throughput stayed > 0
        assert ev["rejoin_revocations"] == 0
        assert REGISTRY.coordinator_degraded.get("host-0") == 0.0
        assert REGISTRY.coordinator_degraded.get("host-1") == 0.0
        assert sum(REGISTRY.degraded_admissions_total.get(h)
                   for h in ("host-0", "host-1")) >= 4
        # Post-rejoin state == the uninterrupted outcome: everything
        # that fits is admitted.
        for _ in range(2):
            rt.tick()
        dump = rt.dump()
        assert sum(len(v) for v in dump["admitted"].values()) == 8
        # The degraded journal stamps every window event with its epoch.
        djs = [os.path.join(root, f)
               for root, _dirs, files in os.walk(state)
               for f in files if f.startswith("degraded-")]
        assert djs, "no degraded journal written"
        events = [json.loads(line)
                  for p in djs for line in open(p) if line.strip()]
        kinds = {e["event"] for e in events}
        assert {"enter", "tick", "rejoin"} <= kinds
        assert all(e.get("degraded_epoch", e.get("epoch")) is not None
                   for e in events)
        tick_events = [e for e in events if e["event"] == "tick"]
        assert sum(len(e["admitted"]) for e in tick_events) == 4
        # The SIGUSR2 view carries the window's evidence.
        info = rt.reconcile_info()
        assert info["degradedWindow"]["degraded_admissions"] == 4
    finally:
        rt.close()


def test_degraded_split_roots_park_not_admit(tmp_path):
    """Split-root entries must PARK during a degraded window (the
    merged lending-clamp arithmetic is unavailable), then admit after
    rejoin exactly as the uninterrupted run would."""
    features.set_enabled(features.LENDING_LIMIT, True)
    try:
        ca, cb = _split_pair(2)
        rt = ReplicaRuntime(2, spawn=False, engine="host",
                            degraded_after=0.25)
        try:
            _lending_world(rt, ca, cb)
            assert "hroot" in rt.gmap.split_roots
            rt.tick()
            # Borrowers whose roots are split across the two replicas.
            rt.submit(make_wl("wa", "lq-a", cpu=8, creation_time=1.0))
            rt.submit(make_wl("wb", "lq-b", cpu=8, creation_time=2.0))
            rt.degraded_window(1.0)
            ev = rt.rejoin()
            assert ev["degraded_window_ticks"] >= 1
            # Parked: degraded ticks saw the split-root heads and
            # refused them locally.
            assert ev["parked"] >= 1
            assert ev["degraded_admissions"] == 0
            mid = rt.dump()
            assert not mid["admitted"].get("cq-a") \
                and not mid["admitted"].get("cq-b")
            # After rejoin the coordinator arbitration resumes and
            # exactly one borrower wins — the single-process outcome.
            for _ in range(4):
                rt.tick()
            dump = rt.dump()
            winners = sorted(dump["admitted"].get("cq-a", [])
                             + dump["admitted"].get("cq-b", []))
            assert len(winners) == 1
        finally:
            rt.close()
    finally:
        features.reset()


def test_degraded_parking_explain_reason(monkeypatch):
    """Unit: a split-root head parked by a degraded replica carries the
    degraded explain reason, not the priority-race one."""
    monkeypatch.setenv("KUEUE_TPU_BARRIER_DEADLINE", "5")
    features.set_enabled(features.LENDING_LIMIT, True)
    try:
        import queue

        to_worker: "queue.Queue" = queue.Queue()
        to_parent: "queue.Queue" = queue.Queue()
        worker = ReplicaWorker(
            0, {"solver": False, "n_groups": 1, "engine": "host",
                "degraded_after": 0.1},
            _QueueChan(to_parent, to_worker))
        ca, cb = _split_pair(2)
        fw = worker.fw
        fw.create_namespace("default", labels={})
        _lending_world(fw, ca, cb)
        worker.rctx.split_roots = frozenset({"hroot"})
        worker._enter_degraded("test")
        fw.submit(make_wl("wa", "lq-a", cpu=8, creation_time=1.0))
        worker._degraded_tick()
        assert worker.rctx.parked >= 1
        assert not fw.admitted_workloads("cq-a")
        records = fw.scheduler.explain.snapshot(limit=10)
        reasons = [str(rec.get("reason", "")) + str(rec)
                   for rec in records.values()]
        assert any("degraded mode (coordinator unreachable)" in r
                   for r in reasons), records
        assert REGISTRY.coordinator_degraded.get(worker.host_id) == 1.0
        worker._exit_degraded("test-done")
        assert REGISTRY.coordinator_degraded.get(worker.host_id) == 0.0
    finally:
        features.reset()


def test_rejoin_revokes_when_merged_capacity_shrank(tmp_path):
    """The revocation half of the catch-up contract: the coordinator
    comes back knowing a SMALLER quota than the degraded window
    admitted against — the rejoin reconcile revokes (newest first,
    counted, journaled as evictions) until nothing is oversubscribed,
    at milli-unit resolution."""
    rt = ReplicaRuntime(2, spawn=False, engine="host",
                        state_dir=str(tmp_path / "state"),
                        degraded_after=0.25)
    try:
        _flat_world(rt, n_cqs=2, cpu=6)
        # The old pair admits NORMALLY (and pays the first-tick device
        # compiles outside the degraded window).
        for i in range(2):
            rt.submit(make_wl(f"old-{i}", f"lq-{i}", cpu=3,
                              creation_time=float(i)))
        for _ in range(2):
            rt.tick()
        # The new pair arrives, then the coordinator goes silent: the
        # degraded window admits them against the OLD quota (6 cpu).
        for i in range(2):
            rt.submit(make_wl(f"new-{i}", f"lq-{i}", cpu=3,
                              creation_time=float(10 + i)))
        rt.degraded_window(1.2)
        # The restarted coordinator's config shrank every CQ to cpu=3
        # (3000 milli-units): only ONE of each pair still fits.
        for i in range(2):
            spec = make_cq(f"cq-{i}", rg("cpu", fq("default", cpu=3)))
            rt._cq_specs[spec.name] = spec
            rt.coordinator.note_cluster_queue(spec)
        ev = rt.rejoin()
        assert ev["degraded_admissions"] == 2
        assert ev["rejoin_revocations"] == 2
        # Newest-first: the creation-order survivors are the old pair.
        assert ev["revoked_keys"] == ["default/new-0", "default/new-1"]
        # The restarted coordinator now applies its (shrunk) manifests
        # — the routed MODIFIED events shrink the workers' quota, so
        # the revoked pair stays pending instead of re-admitting.
        from kueue_tpu.controllers.store import (KIND_CLUSTER_QUEUE,
                                                 MODIFIED)

        for i in range(2):
            rt.apply_event(KIND_CLUSTER_QUEUE, MODIFIED,
                           obj=rt._cq_specs[f"cq-{i}"])
        for _ in range(2):
            rt.tick()
        dump = rt.dump()
        for i in range(2):
            assert dump["admitted"][f"cq-{i}"] == [f"default/old-{i}"]
            usage = dump["usage"][f"cq-{i}"]["default"]["cpu"]
            assert usage <= 3000, f"cq-{i} oversubscribed: {usage}"
    finally:
        rt.close()


def test_remote_mode_conflicts_loudly_with_no_socket(monkeypatch):
    """KUEUE_TPU_NO_SOCKET=1 + remote workers cannot coexist: fail at
    construction with a clear message, not later on a missing
    listener."""
    monkeypatch.setenv("KUEUE_TPU_NO_SOCKET", "1")
    with pytest.raises(RuntimeError, match="socket transport"):
        ReplicaRuntime(2, remote=True, transport="socket",
                       join_timeout=0.1)


def test_drop_group_releases_slice_without_reply(monkeypatch):
    """A rejoin assignment that took a group away drops its whole
    vertical slice (objects, quota, journal flock) WITHOUT a released
    reply — the single-owner invariant after first-join-wins conflict
    resolution."""
    import queue

    to_worker: "queue.Queue" = queue.Queue()
    to_parent: "queue.Queue" = queue.Queue()
    worker = ReplicaWorker(0, {"solver": False, "n_groups": 2},
                           _QueueChan(to_parent, to_worker))
    worker.add_group(0)
    worker.add_group(1)
    fw = worker.fw
    fw.create_namespace("default", labels={})
    from kueue_tpu.controllers.store import (KIND_CLUSTER_QUEUE,
                                             KIND_RESOURCE_FLAVOR)

    for gid, name in ((0, "cq-keep"), (1, "cq-drop")):
        store = worker.groups[gid][0]
        store.create(KIND_RESOURCE_FLAVOR, make_flavor(f"f-{gid}"))
        store.create(KIND_CLUSTER_QUEUE,
                     make_cq(name, rg("cpu", fq("default", cpu=4))))
    assert "cq-drop" in fw.cache.cluster_queues
    worker._drop_group(1, want_entries=False)
    assert 1 not in worker.groups
    assert "cq-drop" not in fw.cache.cluster_queues
    assert "cq-keep" in fw.cache.cluster_queues
    assert to_parent.empty()  # no released reply on the rejoin path


# -- coordinator restart + re-join -------------------------------------------


def test_coordinator_restart_workers_rejoin_and_report(tmp_path):
    """Kill the coordinator OUTRIGHT (listener closed, object gone) and
    start a new incarnation on the same port: the workers' channels
    detect the new session, re-join carrying the shard groups they
    already own, serve their degraded report, and the admitted set ends
    identical to an uninterrupted single-process run."""
    from kueue_tpu.config import Configuration, TPUSolverConfig
    from kueue_tpu.controllers.runtime import Framework

    def build(t):
        _flat_world(t)
        for i in range(4):
            t.submit(make_wl(f"w-{i}", f"lq-{i}", cpu=3,
                             creation_time=float(i)))

    # The uninterrupted single-process reference.
    fw = Framework(batch_solver=None, config=Configuration(
        tpu_solver=TPUSolverConfig(enable=False)))
    fw.create_namespace("default", labels={})
    build(fw)
    fw.run_until_settled(max_ticks=8)
    expect = {name: sorted(cq.workloads)
              for name, cq in fw.cache.cluster_queues.items()
              if cq.workloads}

    port = _free_port()
    _start_join_workers(port, tmp_path, degraded_after=0.3)
    rt = ReplicaRuntime(2, remote=True, transport="socket",
                        listen=("127.0.0.1", port), engine="host",
                        solver=False, join_timeout=60.0,
                        degraded_after=0.3)
    owner_before = {
        g: rt.workers[w].host_id for g, w in rt.group_owner.items()}
    build(rt)
    for _ in range(3):
        rt.tick()
    assert sum(len(v) for v in rt.dump()["admitted"].values()) == 4
    # Coordinator dies. (Do not rt.close(): that would stop the
    # workers — this is the crash path.)
    rt.listener.close()
    time.sleep(1.0)
    rt2 = ReplicaRuntime(2, remote=True, transport="socket",
                         listen=("127.0.0.1", port), engine="host",
                         solver=False, join_timeout=60.0,
                         degraded_after=0.3)
    try:
        # The new incarnation re-learns the world (a restarted
        # coordinator re-applies its manifests), then reconciles.
        _flat_world(rt2)
        ev = rt2.rejoin()
        assert ev["workers"] == 2
        # Shard groups survived the restart with their owners.
        owner_after = {
            g: rt2.workers[w].host_id
            for g, w in rt2.group_owner.items()}
        assert owner_after == owner_before
        for _ in range(3):
            rt2.tick()
        dump = rt2.dump()
        got = {name: sorted(keys)
               for name, keys in dump["admitted"].items() if keys}
        assert got == expect
    finally:
        rt2.close()
