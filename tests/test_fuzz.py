"""kueuefuzz unit + smoke tests: generator, lattice driver, oracles,
shrinker. The CI-budget campaign itself runs via `make fuzz-smoke`
(python -m kueue_tpu.fuzz); here we pin the machinery's contracts at
test scale."""

import json

import pytest

from kueue_tpu.fuzz import generator, lattice, shrink
from kueue_tpu.fuzz.generator import TRAFFIC_SHAPES
from kueue_tpu.fuzz.scenario import Scenario


def test_generator_is_deterministic():
    a = generator.draw_scenario(7)
    b = generator.draw_scenario(7)
    assert a.to_dict() == b.to_dict()
    assert a.to_dict() != generator.draw_scenario(8).to_dict()


def test_scenario_json_roundtrip():
    sc = generator.draw_scenario(3)
    again = Scenario.from_json(sc.to_json())
    assert again.to_dict() == sc.to_dict()
    with pytest.raises(ValueError):
        Scenario.from_dict({"format": "not-a-scenario"})


def test_generator_covers_the_draw_space():
    """25 seeds (the smoke budget) must cover every traffic shape and
    every policy dimension — the whole point of the fuzzer is breadth
    the hand-written suites don't have."""
    scs = [generator.draw_scenario(s) for s in range(25)]
    shapes = {sc.policy["shape"] for sc in scs}
    assert shapes == set(TRAFFIC_SHAPES)
    assert any(sc.policy["hetero"] for sc in scs)
    assert any(sc.policy["fair"] for sc in scs)
    assert any(sc.policy["lending"] for sc in scs)
    assert any(sc.policy["pods_ready"] for sc in scs)
    assert any(sc.topology for sc in scs)
    assert any(sc.cohorts for sc in scs)
    assert any(sc.replica_safe() for sc in scs)
    # The adversarial tie storm (the PR 8 bug-class population).
    assert any(w["name"].startswith("tie-borrow")
               for sc in scs for w in sc.workloads)


def test_lattice_covers_the_required_axes():
    """Acceptance shape: engine x shards {1,2} x replicas {1,2} x one
    kill-switch set, plus the fail-over / loan / degraded-window /
    snapshot-rejoin drill points and the micro-tick on/off pair on the
    rotating seed subsets."""
    axes = {"engines": set(), "shards": set(), "replicas": set(),
            "kill": set(), "drills": set(), "micro": set()}
    for s in range(25):
        for p in lattice.default_lattice(generator.draw_scenario(s)):
            axes["engines"].add(p.axes()["engine"])
            axes["shards"].add(p.shards)
            axes["replicas"].add(p.replicas)
            axes["kill"].add(p.kill_switches)
            axes["micro"].add(p.micro)
            if p.drill:
                axes["drills"].add(p.drill)
    assert {"referee", "jax"} <= axes["engines"]
    assert {1, 2} <= axes["shards"]
    assert {1, 2} <= axes["replicas"]
    assert axes["kill"] == {False, True}
    assert axes["drills"] == {"failover", "loan", "degraded", "snapshot"}
    assert axes["micro"] == {False, True}


def test_replica_points_only_inside_the_identity_envelope():
    for s in range(25):
        sc = generator.draw_scenario(s)
        has_replica = any(p.kind == "replica"
                          for p in lattice.default_lattice(sc))
        assert has_replica == sc.replica_safe()


def test_smoke_scenarios_replay_identically():
    """A slice of the campaign in tier-1: a replica-profile seed (drill
    coverage) and an ordinary seed replay with zero oracle violations
    across the full lattice."""
    for seed in (0, 3):
        report = lattice.check_scenario(generator.draw_scenario(seed))
        assert report["violations"] == [], report["violations"][:3]


def test_quota_oracle_flags_minted_quota():
    sc = generator.draw_scenario(0)
    caps = lattice.sc_mod.nominal_capacity(sc, {})
    cq = sc.cluster_queues[0]
    flavor = sorted(cq["quotas"])[0]
    over = {cq["name"]: {flavor: {"cpu": 10 ** 12}}}
    out = lattice._check_oversub(sc, over, caps, tick=5)
    assert out and out[0]["oracle"] == "quota"
    assert "10" in out[0]["detail"]
    # At-capacity usage is legal.
    root = lattice.sc_mod.cq_root(sc, cq["name"])
    exact = {cq["name"]: {flavor: dict(caps[root][flavor])}}
    assert lattice._check_oversub(sc, exact, caps, tick=5) == []


def test_high_water_capacity_tolerates_quota_shrink():
    """A quota SHRINK leaves committed usage above the new nominal —
    the oracle bounds by high-water capacity, not the live one."""
    sc = generator.draw_scenario(0)
    hw = lattice.sc_mod.nominal_capacity(sc, {})
    shrunk = lattice.sc_mod.nominal_capacity(
        sc, {sc.cluster_queues[0]["name"]: 0.5})
    lattice._merge_caps(hw, shrunk)
    root = lattice.sc_mod.cq_root(sc, sc.cluster_queues[0]["name"])
    flavor = sorted(sc.cluster_queues[0]["quotas"])[0]
    assert hw[root][flavor]["cpu"] \
        >= shrunk[root][flavor]["cpu"]


def test_first_divergence_reports_the_tick():
    ref = [(("a",), ()), (("b",), ()), (("c",), ())]
    same = [tuple(x) for x in ref]
    assert lattice._first_divergence(ref, same, False) is None
    div = [(("a",), ()), (("X",), ()), (("c",), ())]
    t, a, b = lattice._first_divergence(ref, div, False)
    assert t == 1 and a != b
    # admitted_only ignores preempted-set differences.
    pre = [(("a",), ("p",)), (("b",), ()), (("c",), ())]
    assert lattice._first_divergence(ref, pre, True) is None
    assert lattice._first_divergence(ref, pre, False)[0] == 0


def test_traffic_ops_apply_deterministically():
    """finish/delete/update_cq resolve through deterministic selectors;
    the update_cq op actually raises quota (the parked workload admits
    afterwards — the PR 9 corpus shape, checked here at unit scale)."""
    from kueue_tpu.fuzz.corpus import CORPUS_DIR, load_entry
    import os

    entry = load_entry(os.path.join(
        CORPUS_DIR, "pr9-quota-raise-requeue.json"))
    sc = entry["scenario_obj"]
    ref = lattice.drive(sc, lattice.default_lattice(sc)[0])
    admitted = {k for keys in ref["final_admitted"].values()
                for k in keys}
    assert "default/park-me" in admitted


def test_shrinker_minimizes_under_a_pure_predicate():
    """Structural passes only: a predicate that needs one 'poison'
    submission and >= 2 ClusterQueues must shrink everything else
    away (no scheduler drives involved — pure and fast)."""
    sc = generator.draw_scenario(2)
    poison = {
        "name": "poison", "queue": f"lq-{sc.cluster_queues[0]['name']}",
        "priority": 0, "creation_time": 1.0,
        "pod_sets": [{"name": "ps0", "count": 1, "cpu": 1,
                      "memory_gi": 1, "topo": None}], "tputs": None}
    sc = Scenario.from_dict({**sc.to_dict(),
                             "workloads": sc.workloads + [poison]})

    def fails(cand):
        has_poison = any(w["name"] == "poison" for w in cand.workloads)
        return has_poison and len(cand.cluster_queues) >= 2

    small, attempts = shrink.shrink(sc, fails, budget=300)
    assert fails(small)
    assert len(small.cluster_queues) == 2
    assert [w["name"] for w in small.workloads] == ["poison"]
    assert small.ticks <= sc.ticks
    assert attempts <= 300


def test_shrinker_converges_without_exhausting_the_budget():
    """An always-failing predicate (the crash-class shape) must reach
    the floor and STOP: stale policy patches used to resurrect already-
    simplified dimensions and ping-pong until the budget ran out."""
    sc = generator.draw_scenario(5)  # hetero+fair+lending draw
    assert sc.policy["hetero"] and sc.policy["fair"]
    small, attempts = shrink.shrink(sc, lambda cand: True, budget=250)
    assert attempts < 250, "shrinker burned the whole budget"
    assert not small.policy["fair"]
    assert not small.policy["hetero"]
    assert not small.policy["lending"]
    assert len(small.cluster_queues) == 1
    assert small.size()[1] == 0  # every submission dropped


def test_shrinker_merge_cq_retargets_workloads():
    sc = generator.draw_scenario(2)
    src = sc.cluster_queues[0]["name"]
    dst = sc.cluster_queues[1]["name"]
    merged = shrink._merge_cq(sc, src, dst)
    assert all(c["name"] != src for c in merged.cluster_queues)
    assert not any(w["queue"] == f"lq-{src}" for w in merged.workloads)


def test_reproducer_roundtrip(tmp_path):
    sc = generator.draw_scenario(5)
    path = str(tmp_path / "repro.json")
    shrink.write_reproducer(path, sc, name="t", description="d",
                            expect={"min_preempted": 0})
    doc = json.load(open(path))
    assert doc["format"] == shrink.REPRO_FORMAT
    assert Scenario.from_dict(doc["scenario"]).to_dict() == sc.to_dict()


def test_crash_is_a_finding_not_an_abort():
    """A lattice point that crashes mid-drive must surface as a crash
    violation while the other points still run."""
    sc = generator.draw_scenario(0)
    bad = Scenario.from_dict({**sc.to_dict(), "traffic": [
        [["no-such-op"]]] + [list(o) for o in sc.traffic[1:]]})
    report = lattice.check_scenario(
        bad, points=lattice.default_lattice(bad)[:2])
    assert report["violations"]
    assert all(v["oracle"] == "crash" for v in report["violations"])


def test_parse_shard_validates_and_partitions():
    """--shard I/N: strict parse, and the N slices of a seed range
    partition it exactly — no seed dropped, none doubled (the nightly
    split's correctness condition)."""
    from kueue_tpu.fuzz.__main__ import parse_shard, shard_range

    assert parse_shard("0/4") == (0, 4)
    assert parse_shard("3/4") == (3, 4)
    for bad in ("4/4", "-1/4", "1", "a/b", "1/0"):
        with pytest.raises(ValueError):
            parse_shard(bad)
    for start, seeds, n in ((0, 10, 4), (100, 7, 3), (5, 1000, 4),
                            (0, 3, 8)):
        covered = []
        for i in range(n):
            lo, hi = shard_range(start, seeds, (i, n))
            covered.extend(range(lo, hi))
        assert covered == list(range(start, start + seeds))
    assert shard_range(7, 10, None) == (7, 17)


def test_scenario_dimensions_are_stable_labels():
    """The coverage vocabulary: every drawn scenario labels itself
    with shape/structure/preemption dimensions, deterministically."""
    for seed in range(12):
        sc = generator.draw_scenario(seed)
        dims = generator.scenario_dimensions(sc)
        assert dims == generator.scenario_dimensions(sc)
        assert any(d.startswith("shape=") for d in dims)
        assert any(d.startswith("structure=") for d in dims)
        assert any(d.startswith("preemption=") for d in dims)
    all_dims = {d for s in range(12)
                for d in generator.scenario_dimensions(
                    generator.draw_scenario(s))}
    assert len(all_dims) > 4   # the space is not one label


def test_check_scenario_reports_event_rollup():
    """Per-oracle coverage raw material: every campaign report carries
    the reference drive's admitted/preempted counts plus micro/
    revocation evidence sums."""
    sc = generator.draw_scenario(1)
    report = lattice.check_scenario(
        sc, points=lattice.default_lattice(sc)[:3])
    ev = report["events"]
    assert set(ev) >= {"admitted", "preempted", "micro_admitted",
                       "revocations"}
    assert ev["admitted"] >= 0
    assert all(isinstance(v, int) for v in ev.values())


def test_campaign_emits_shard_and_oracle_coverage(tmp_path):
    """End-to-end campaign contract: a sharded run writes the shard
    block, per-family oracle coverage with a `never` list, and stays
    inside its seed slice."""
    from kueue_tpu.fuzz.__main__ import run_campaign

    out = str(tmp_path / "campaign.json")
    rc = run_campaign(2, 0, out, shrink_on_failure=False,
                      shard=(1, 2))
    assert rc == 0
    doc = json.loads(open(out).read())
    assert doc["scenarios"] == 1
    assert doc["start_seed"] == 1
    assert doc["shard"] == {"index": 1, "of": 2,
                            "seed_lo": 1, "seed_hi": 1}
    assert doc["requested"] == {"seeds": 2, "start_seed": 0}
    cov = doc["oracle_coverage"]
    assert set(cov) == {"preemption", "revocation",
                        "micro_admission"}
    for family in cov.values():
        assert set(family) == {"events_by_dimension", "never"}
        assert sorted(family["events_by_dimension"]) \
            == sorted(generator.scenario_dimensions(
                generator.draw_scenario(1)))
        for dim in family["never"]:
            assert family["events_by_dimension"][dim] == 0
