"""Corpus meta-test + oracle-mutation self-tests.

Every reproducer under tests/fixtures/fuzz/ must replay GREEN on the
fixed build — the corpus is the fuzzer's regression surface. The
mutation drills then prove the harness can actually DETECT the bug
classes it exists for: with an env-gated revert of a real past bug
compiled in, the corpus entry (and, for the PR 8 class, a bounded-seed
campaign plus the shrinker) must go red."""

import pytest

from kueue_tpu.fuzz import corpus, generator, lattice, shrink

ENTRIES = corpus.load_corpus()


def test_corpus_is_populated():
    names = {e["name"] for e in ENTRIES}
    assert {"pr8-identity-victim-flip", "pr9-quota-raise-requeue",
            "shrunk-unsorted-members"} <= names


@pytest.mark.parametrize("entry", ENTRIES,
                         ids=[e["name"] for e in ENTRIES])
def test_corpus_entry_replays_green(entry):
    violations = corpus.replay_entry(entry)
    assert violations == [], violations[:3]


def _entry(name):
    return next(e for e in ENTRIES if e["name"] == name)


def test_pr9_entry_catches_the_requeue_mutation(monkeypatch):
    """The checked-in PR 9 reproducer must go RED when the manager's
    requeue-on-every-spec-update fix is reverted (the env-gated
    mutation): park-me stays parked after the quota raise and the
    expect clause fires."""
    monkeypatch.setenv("KUEUE_TPU_FUZZ_MUTATION",
                       "no-requeue-on-cq-update")
    violations = corpus.replay_entry(_entry("pr9-quota-raise-requeue"))
    assert any(v["oracle"] == "expect"
               and "park-me" in v["detail"] for v in violations), \
        violations


def test_pr8_entry_catches_the_unsorted_members_mutation(monkeypatch):
    """The checked-in PR 8 reproducer must go RED under the
    identity-hashed member-walk revert: the fair victim choice between
    the two equal-share borrowers falls to set-iteration order, which
    differs between two drives in one process. The flip depends on
    allocator layout, so we allow a few replay attempts — the point is
    the corpus CAN catch it, bounded."""
    monkeypatch.setenv("KUEUE_TPU_FUZZ_MUTATION", "unsorted-members")
    for _ in range(4):
        violations = corpus.replay_entry(
            _entry("pr8-identity-victim-flip"))
        if violations:
            assert any(v["oracle"] in ("determinism", "identity")
                       for v in violations), violations
            return
    pytest.fail("the unsorted-members mutation was never caught in 4 "
                "replays of the PR 8 reproducer")


def test_mutation_self_test_campaign_catches_and_shrinks(monkeypatch):
    """THE oracle-mutation self-test (acceptance gate): with the
    name-sorted Cohort member walk reverted, a bounded seeded campaign
    must catch the divergence, and the shrinker must reduce it to a
    reproducer of <= 10 workloads / <= 3 ClusterQueues that replays
    GREEN once the mutation is lifted. The scan drives each seed's
    repeat-determinism pair (the oracle this bug class trips); the full
    lattice runs in `make fuzz-smoke`."""
    monkeypatch.setenv("KUEUE_TPU_FUZZ_MUTATION", "unsorted-members")
    caught_sc = None
    caught_report = None
    for seed in range(25):  # the bounded seed budget
        sc = generator.draw_scenario(seed)
        pair = [p for p in lattice.default_lattice(sc)
                if "referee" in p.name]
        # The flip is layout-dependent (that IS the bug class); a few
        # repeat drives per seed roll the allocator state.
        for _ in range(3):
            report = lattice.check_scenario(sc, points=pair)
            if report["violations"]:
                caught_sc, caught_report = sc, report
                break
        if caught_sc is not None:
            break
    assert caught_sc is not None, \
        "the fuzzer failed to catch the unsorted-members mutation " \
        "within 25 seeds — it cannot detect the bug class it exists for"
    assert any(v["oracle"] in ("determinism", "identity")
               for v in caught_report["violations"])

    pair = [p for p in lattice.default_lattice(caught_sc)
            if "referee" in p.name]

    def still_fails(cand):
        for _ in range(3):
            if lattice.check_scenario(cand, points=pair)["violations"]:
                return True
        return False

    small, _attempts = shrink.shrink(caught_sc, still_fails, budget=300)
    if len(small.cluster_queues) > 3 or small.size()[1] > 10:
        # The probabilistic predicate can miss a reduction; one more
        # pass settles it.
        small, _attempts = shrink.shrink(small, still_fails, budget=300)
    n_cqs, n_submits = len(small.cluster_queues), small.size()[1]
    assert n_cqs <= 3, f"shrunk reproducer still has {n_cqs} CQs"
    assert n_submits <= 10, \
        f"shrunk reproducer still has {n_submits} workloads"

    # Lifted mutation: the minimized scenario replays green on the
    # fixed build — exactly the shape checked in as
    # tests/fixtures/fuzz/shrunk-unsorted-members.json.
    monkeypatch.delenv("KUEUE_TPU_FUZZ_MUTATION")
    clean = lattice.check_scenario(small, points=pair)
    assert clean["violations"] == [], clean["violations"][:3]


def test_mutations_are_inert_without_the_env_gate(monkeypatch):
    """Belt and braces: with no KUEUE_TPU_FUZZ_MUTATION set, the member
    walk is name-sorted and the corpus replays green (covered above),
    and an UNKNOWN mutation value changes nothing either."""
    monkeypatch.setenv("KUEUE_TPU_FUZZ_MUTATION", "no-such-mutation")
    violations = corpus.replay_entry(_entry("pr9-quota-raise-requeue"))
    assert violations == []
