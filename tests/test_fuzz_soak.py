"""Soak harness tests: the drift detector's contracts (fast, pure) and
the long churn soak itself (slow-marked; hours-scale in `make
fuzz-soak` via KUEUE_FUZZ_SOAK_SECONDS, a short budget here)."""

import pytest

from kueue_tpu.fuzz import soak


def _samples(n, **overrides):
    base = {"tick": 0, "rss_mb": 500.0, "arena_occupancy": 0.5,
            "arena_reuse_ratio": 0.95, "nominate_hit_ratio": 0.6,
            "dispatches_per_tick": 1.0, "backlog": 300}
    out = []
    for i in range(n):
        s = dict(base, tick=25 * (i + 1))
        for key, fn in overrides.items():
            s[key] = fn(i, n)
        out.append(s)
    return out


def test_drift_verdict_passes_flat_curves():
    v = soak.drift_verdict(_samples(20))
    assert v and all(m["ok"] for m in v.values())


def test_drift_verdict_flags_rss_leak():
    v = soak.drift_verdict(_samples(
        20, rss_mb=lambda i, n: 500.0 + 40.0 * i))
    assert not v["rss_mb"]["ok"]
    assert all(m["ok"] for k, m in v.items() if k != "rss_mb")


def test_drift_verdict_flags_occupancy_creep():
    v = soak.drift_verdict(_samples(
        20, arena_occupancy=lambda i, n: min(0.2 + 0.05 * i, 1.0)))
    assert not v["arena_occupancy"]["ok"]


def test_drift_verdict_flags_cache_decay():
    v = soak.drift_verdict(_samples(
        20, nominate_hit_ratio=lambda i, n: max(0.8 - 0.05 * i, 0.0)))
    assert not v["nominate_hit_ratio"]["ok"]


def test_drift_verdict_flags_dispatch_rate_growth():
    v = soak.drift_verdict(_samples(
        20, dispatches_per_tick=lambda i, n: 0.5 + 0.3 * i))
    assert not v["dispatches_per_tick"]["ok"]


def test_drift_verdict_tolerates_noise_and_nones():
    v = soak.drift_verdict(_samples(
        20,
        rss_mb=lambda i, n: 500.0 + (7.0 if i % 2 else -7.0),
        arena_reuse_ratio=lambda i, n: None if i % 3 == 0 else 0.93))
    assert all(m["ok"] for m in v.values())
    assert soak.drift_verdict([]) == {}
    assert soak.drift_verdict(_samples(3)) == {}


def test_soak_smoke_brief(tmp_path):
    """A seconds-scale soak: the loop runs, samples accumulate, the
    report lands on disk with the environment block."""
    report = soak.run_soak(
        3.0, seed=1, num_cqs=8, backlog=96, sample_every=10,
        report_path=str(tmp_path / "soak.json"))
    assert report["ticks"] > 0
    assert report["samples"], "no samples collected"
    assert (tmp_path / "soak.json").exists()
    assert report["environment"]["cpu_count"]
    first = report["samples"][0]
    assert first["rss_mb"] > 0
    assert first["backlog"] >= 0


@pytest.mark.slow
def test_soak_long_run_has_no_monotonic_drift():
    """The registered long soak (the `slow` marker keeps it out of
    tier-1): default 120s here, hours-scale in `make fuzz-soak` where
    KUEUE_FUZZ_SOAK_SECONDS drives the budget."""
    seconds = soak.soak_seconds_from_env(default=120.0)
    report = soak.run_soak(seconds, seed=0)
    assert report["verdict"], "soak too short to produce a verdict"
    bad = {k: v for k, v in report["verdict"].items() if not v["ok"]}
    assert report["ok"], f"monotonic drift detected: {bad}"


def test_oracle_spot_check_files_shrunk_reproducer(tmp_path):
    """The soak divergence lane: a red spot-check shrinks and lands as
    a campaign-style reproducer file (injected check/shrinker — a real
    shrink loop is not tier-1 budget)."""
    import json

    violations = [{"oracle": "identity", "detail": "injected"}]

    def check(sc, points=None):
        return {"violations": violations}

    def shrinker(sc, still_fails):
        assert still_fails(sc)   # the predicate re-runs the check
        return sc, 5

    findings = soak._oracle_spot_check(
        123, str(tmp_path), check=check, shrinker=shrinker)
    assert len(findings) == 1
    f = findings[0]
    assert f["kind"] == "oracle" and f["seed"] == 123
    doc = json.load(open(f["reproducer"]))
    assert doc["format"].startswith("kueuefuzz-repro/")
    assert doc["found"]["lane"] == "soak-oracle"
    assert doc["found"]["shrink_attempts"] == 5
    assert doc["found"]["violations"] == violations


def test_oracle_spot_check_green_files_nothing(tmp_path):
    findings = soak._oracle_spot_check(
        7, str(tmp_path), check=lambda sc, points=None:
        {"violations": []})
    assert findings == []
    assert list(tmp_path.iterdir()) == []


def test_drift_failure_files_self_contained_repro(tmp_path):
    """A failed drift verdict writes the soak-repro doc: the exact
    run_soak params plus the red verdict — re-runnable evidence, not a
    log line."""
    import json

    verdict = {"rss_mb": {"ok": False, "first": 500.0, "last": 900.0},
               "backlog": {"ok": True}}
    params = {"duration_s": 60.0, "seed": 3, "num_cqs": 8}
    finding = soak._file_drift_repro(
        str(tmp_path), params, [{"tick": 25}], verdict)
    assert finding["kind"] == "drift"
    assert finding["failed"] == ["rss_mb"]
    doc = json.load(open(finding["reproducer"]))
    assert doc["format"] == soak.SOAK_REPRO_FORMAT
    assert doc["params"] == params
    assert doc["verdict"]["rss_mb"]["ok"] is False
