"""The multi-HOST socket lattice point (kueuefuzz): budget-gated
behind `--lattice socket` / nightly / soak — never in the 25-seed CI
smoke — and decision-identical to the reference when driven."""

import pytest

from kueue_tpu.fuzz import generator, lattice


def test_socket_points_are_budget_gated():
    """The smoke lattice NEVER contains socket points; the nightly
    lattice appends them (clean + seeded-fault) for replica-safe
    scenarios only."""
    for seed in range(6):
        sc = generator.draw_scenario(seed)
        smoke = lattice.default_lattice(sc)
        assert not any(p.transport == "socket" for p in smoke), \
            "socket points leaked into the smoke budget"
        nightly = lattice.default_lattice(sc, include_socket=True)
        socket_pts = [p for p in nightly if p.transport == "socket"]
        if sc.replica_safe():
            names = {p.name for p in socket_pts}
            assert names == {"socket", "socket-faults"}
            assert all(p.kind == "replica" for p in socket_pts)
            assert any(p.socket_faults for p in socket_pts)
        else:
            assert not socket_pts
        # The axes advertise the transport (coverage accounting).
        for p in nightly:
            ax = p.axes()
            if p.kind == "replica":
                assert ax["transport"] in ("loopback", "socket")


def test_fuzz_cli_accepts_lattice_flag(capsys):
    """--lattice socket parses; --lattice default is the default."""
    import argparse

    from kueue_tpu.fuzz.__main__ import main

    with pytest.raises(SystemExit):
        main(["--lattice", "bogus"])  # argparse rejects unknown values
    capsys.readouterr()


@pytest.mark.slow
def test_socket_point_decision_identity_one_seed():
    """Nightly-shape spot check: one replica-safe seed driven at the
    socket points (clean + faults) agrees with the sequential referee
    on every tick and the final admitted set."""
    sc = None
    for seed in range(32):
        cand = generator.draw_scenario(seed)
        if cand.replica_safe():
            sc = cand
            break
    assert sc is not None, "no replica-safe seed in the first 32"
    points = [p for p in lattice.default_lattice(sc, include_socket=True)
              if p.kind == "referee" or p.transport == "socket"]
    report = lattice.check_scenario(sc, points=points)
    assert report["violations"] == [], report["violations"][:3]
