import random

from kueue_tpu.utils.heap import KeyedHeap


def make_heap():
    return KeyedHeap(key_fn=lambda x: x[0], less=lambda a, b: a[1] < b[1])


def test_push_pop_order():
    h = make_heap()
    items = [(f"k{i}", v) for i, v in enumerate([5, 3, 8, 1, 9, 2])]
    for it in items:
        assert h.push_if_not_present(it)
    popped = [h.pop()[1] for _ in range(len(items))]
    assert popped == sorted(v for _, v in items)
    assert h.pop() is None


def test_push_if_not_present_dedup():
    h = make_heap()
    assert h.push_if_not_present(("a", 1))
    assert not h.push_if_not_present(("a", 2))
    assert h.get_by_key("a") == ("a", 1)


def test_update_reorders():
    h = make_heap()
    h.push_or_update(("a", 10))
    h.push_or_update(("b", 5))
    h.push_or_update(("a", 1))
    assert h.pop() == ("a", 1)


def test_delete():
    h = make_heap()
    for i in range(10):
        h.push_if_not_present((f"k{i}", i))
    h.delete("k0")
    h.delete("k5")
    assert len(h) == 8
    assert h.pop() == ("k1", 1)


def test_randomized_against_sort():
    rnd = random.Random(42)
    h = make_heap()
    live = {}
    for step in range(2000):
        op = rnd.random()
        key = f"k{rnd.randrange(50)}"
        if op < 0.5:
            val = rnd.randrange(1000)
            h.push_or_update((key, val))
            live[key] = val
        elif op < 0.75 and live:
            h.delete(key)
            live.pop(key, None)
        elif live:
            item = h.pop()
            assert item[1] == min(live.values())
            del live[item[0]]
    while live:
        item = h.pop()
        assert item[1] == min(live.values())
        del live[item[0]]
