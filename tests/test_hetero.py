"""Heterogeneity-aware flavor scoring (kueue_tpu/hetero, the `hetero`
solve mode).

Covers the whole ISSUE-10 contract:

  * API/serialization: `PodSet.flavor_throughputs` + `ResourceFlavor.
    speed_class` roundtrip; decoder + webhook hardening (NaN/inf/
    negative throughputs, invalid flavor references).
  * Score kernel: the jit projected dual iteration is BITWISE identical
    to the numpy referee twin (all-integer arithmetic).
  * Decision policy: the device solve picks the fastest FITTING flavor,
    respects quota (falls back when the fast flavor is full), and is
    decision-identical to the sequential host referee on weighted /
    borrowing / KEP-79 scenarios (KUEUE_TPU_DEBUG_HETERO re-runs the
    oracle inside every tick).
  * Identity: 200-tick churn goldens across every registered
    victim-search engine with the mode ON-but-unprofiled vs OFF, plus
    the kill-switch A/B with live profiles.
  * Caching: a hetero steady state dispatches ZERO solves (fingerprints
    ride the score-matrix version).
  * Sharding: cohort-mesh hetero (shards=2) decision-identical to
    single-device.
  * Observability: `?explain=true` answers "why flavor B".
"""

import math
import random

import numpy as np
import pytest

from kueue_tpu import features
from kueue_tpu.api import serialization as ser
from kueue_tpu.api.types import (
    ClusterQueuePreemption,
    CohortSpec,
    FairSharing,
    PodSet,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.config import Configuration, TPUSolverConfig
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.hetero.profile import (
    ThroughputProfileStore,
    aggregate_effective_throughput,
)
from kueue_tpu.hetero.solve import (
    SCORE_SCALE,
    hetero_scores,
    hetero_scores_np,
)
from kueue_tpu.models.flavor_fit import BatchSolver
from kueue_tpu.solver import modes as _modes
from kueue_tpu.webhooks import validation

from tests.util import fq, make_cq, make_lq, rg

# ---------------------------------------------------------------------------
# API + serialization + webhook hardening
# ---------------------------------------------------------------------------


def test_podset_flavor_throughputs_roundtrip():
    wl = Workload(
        name="w", namespace="default", queue_name="lq",
        pod_sets=[PodSet.make(
            "main", count=2, cpu=4,
            flavor_throughputs={"fast": 4.0, "slow": 1.0})])
    doc = ser.encode_workload(wl)
    back = ser.decode_workload(doc)
    assert back.pod_sets[0].flavor_throughputs == \
        (("fast", 4.0), ("slow", 1.0))


def test_resource_flavor_speed_class_roundtrip():
    rf = ResourceFlavor.make("v5p", speed_class=2.5)
    back = ser.decode_resource_flavor(ser.encode_resource_flavor(rf))
    assert back.speed_class == 2.5
    # The default stays implicit (and decodes back to 1.0).
    rf1 = ResourceFlavor.make("plain")
    doc = ser.encode_resource_flavor(rf1)
    assert "speedClass" not in doc["spec"]
    assert ser.decode_resource_flavor(doc).speed_class == 1.0


@pytest.mark.parametrize("bad", [float("nan"), float("inf"), -1.0, "x"])
def test_decoder_rejects_bad_throughputs(bad):
    doc = {
        "apiVersion": "kueue.x-k8s.io/v1beta1", "kind": "Workload",
        "metadata": {"name": "w"},
        "spec": {"podSets": [{"name": "main", "count": 1,
                              "flavorThroughputs": {"fast": bad}}]},
    }
    with pytest.raises(ser.DecodeError):
        ser.decode_workload(doc)


@pytest.mark.parametrize("bad", [float("nan"), float("inf"), -0.5])
def test_decoder_rejects_bad_speed_class(bad):
    doc = {"apiVersion": "kueue.x-k8s.io/v1beta1", "kind": "ResourceFlavor",
           "metadata": {"name": "f"}, "spec": {"speedClass": bad}}
    with pytest.raises(ser.DecodeError):
        ser.decode_resource_flavor(doc)


def test_webhook_rejects_bad_throughput_values():
    for bad in (float("nan"), float("inf"), -1.0):
        wl = Workload(name="w", pod_sets=[PodSet(
            name="main", count=1, requests={"cpu": 1},
            flavor_throughputs=(("fast", bad),))])
        errs = validation.validate_workload(wl)
        assert any("flavorThroughputs" in e for e in errs), (bad, errs)
    # Unknown flavor reference == not a valid ResourceFlavor name.
    wl = Workload(name="w", pod_sets=[PodSet(
        name="main", count=1, requests={"cpu": 1},
        flavor_throughputs=(("Not A Flavor!", 2.0),))])
    assert any("invalid flavor reference" in e
               for e in validation.validate_workload(wl))
    # A valid profile passes.
    wl = Workload(name="w", pod_sets=[PodSet(
        name="main", count=1, requests={"cpu": 1},
        flavor_throughputs=(("fast", 2.0),))])
    assert not validation.validate_workload(wl)


def test_webhook_rejects_bad_speed_class():
    for bad in (float("nan"), float("inf"), 0.0, -2.0):
        rf = ResourceFlavor.make("f", speed_class=bad)
        assert any("speedClass" in e
                   for e in validation.validate_resource_flavor(rf)), bad
    assert not validation.validate_resource_flavor(
        ResourceFlavor.make("f", speed_class=3.0))


# ---------------------------------------------------------------------------
# Score kernel: device == numpy referee, bitwise
# ---------------------------------------------------------------------------


def test_score_kernel_bitwise_identical_to_numpy_twin():
    rng = np.random.default_rng(7)
    for n, f in ((8, 4), (64, 8), (128, 16)):
        tput = rng.integers(0, 8 * SCORE_SCALE, size=(n, f)).astype(np.int64)
        tput[rng.random((n, f)) < 0.2] = 0  # "cannot run here" holes
        demand = rng.integers(1, 64, size=n).astype(np.int64)
        active = rng.random(n) > 0.3
        cap = rng.integers(0, 512, size=f).astype(np.int64)
        dev = hetero_scores(tput, demand, active, cap)
        ref = hetero_scores_np(tput, demand, active, cap)
        assert np.array_equal(dev, ref)


def test_sentinel_capacity_never_wraps_and_stays_bitwise():
    """flavor_capacity sums nominal quotas, and a nominal can be the
    schema's NO_LIMIT/BIG = 2^62 sentinel. Before the CAP_CEIL/PRICE_CEIL
    clamps, `over * PRICE_STEP` on a sentinel capacity wrapped int64
    (found statically by TRC02 once the hetero-scores roster entry got
    its sentinel seed). Pin: sentinel capacity behaves exactly like
    abundant capacity (price never rises), both twins stay bitwise
    identical, and nothing wraps."""
    rng = np.random.default_rng(11)
    n, f = 32, 4
    tput = rng.integers(1, 8 * SCORE_SCALE, size=(n, f)).astype(np.int64)
    demand = rng.integers(1, 64, size=n).astype(np.int64)
    active = np.ones(n, dtype=bool)
    sentinel_cap = np.full(f, np.int64(1) << 62, dtype=np.int64)
    dev = hetero_scores(tput, demand, active, sentinel_cap)
    ref = hetero_scores_np(tput, demand, active, sentinel_cap)
    assert np.array_equal(dev, ref)
    # Capacity is unconstrained -> no flavor is ever overloaded -> the
    # dual price never moves and every score is the raw throughput.
    assert np.array_equal(ref, tput)
    # Zero-capacity extreme with the price ascent saturated: still
    # bitwise, still inside int64 (the PRICE_CEIL clamp binds).
    zero_cap = np.zeros(f, dtype=np.int64)
    dev0 = hetero_scores(tput, demand, active, zero_cap)
    ref0 = hetero_scores_np(tput, demand, active, zero_cap)
    assert np.array_equal(dev0, ref0)


def test_score_iteration_prices_contended_flavor():
    """One fast flavor everyone wants, with tiny capacity: the dual
    price must push part of the crowd toward the runner-up."""
    n, f = 32, 2
    tput = np.tile(np.array([[4 * SCORE_SCALE, 2 * SCORE_SCALE]],
                            dtype=np.int64), (n, 1))
    demand = np.full(n, 10, dtype=np.int64)
    active = np.ones(n, dtype=bool)
    cap = np.array([20, 10_000], dtype=np.int64)
    scores = hetero_scores_np(tput, demand, active, cap)
    # The dual priced the contended flavor down to (at most) the free
    # one — the equilibrium is indifference, never a free lunch.
    assert scores[0, 0] <= scores[0, 1]
    assert scores[0, 0] < 4 * SCORE_SCALE  # price actually rose
    assert scores[0, 1] == 2 * SCORE_SCALE  # free flavor unpriced


# ---------------------------------------------------------------------------
# Profile store
# ---------------------------------------------------------------------------


class _FakeEnc:
    def __init__(self, flavor_names, resource_names=("cpu",)):
        self.flavor_names = list(flavor_names)
        self.flavor_index = {n: i for i, n in enumerate(flavor_names)}
        self.resource_names = list(resource_names)


def _wi(name, tputs=None, cpu=2, count=1):
    from kueue_tpu.core.workload import WorkloadInfo

    wl = Workload(name=name, queue_name="lq", pod_sets=[PodSet.make(
        "main", count=count, cpu=cpu, flavor_throughputs=tputs)])
    return WorkloadInfo(wl, cluster_queue="cq")


def test_profile_store_note_forget_generation():
    rfs = {"slow": ResourceFlavor.make("slow"),
           "fast": ResourceFlavor.make("fast", speed_class=2.0)}
    store = ThroughputProfileStore(_FakeEnc(["fast", "slow"]), rfs,
                                   capacity=2)
    g0 = store.generation
    a = _wi("a", {"fast": 4.0})
    ra = store.note(a)
    assert store.generation > g0
    assert store.tput[ra, store.flavor_index["fast"]] == 4 * SCORE_SCALE
    assert store.tput[ra, store.flavor_index["slow"]] == SCORE_SCALE
    assert store.profiled[ra] and store.valid[ra]
    # Unchanged re-note: no generation bump.
    g1 = store.generation
    assert store.note(a) == ra
    assert store.generation == g1
    # Unknown flavor references are ignored, not crashed on.
    b = _wi("b", {"no-such-flavor": 9.0})
    rb = store.note(b)
    assert np.array_equal(store.tput[rb], store.speed_q)
    # Growth past capacity.
    store.note(_wi("c"))
    assert store.capacity >= 4
    store.forget(a.obj.uid)
    assert not store.valid[ra]


def test_profile_store_min_over_podsets_rule():
    rfs = {"f": ResourceFlavor.make("f")}
    store = ThroughputProfileStore(_FakeEnc(["f"]), rfs, capacity=2)
    from kueue_tpu.core.workload import WorkloadInfo

    wl = Workload(name="w", queue_name="lq", pod_sets=[
        PodSet.make("a", count=1, cpu=1, flavor_throughputs={"f": 4.0}),
        PodSet.make("b", count=1, cpu=1, flavor_throughputs={"f": 2.0}),
        PodSet.make("c", count=1, cpu=1),  # no override: flavor default
    ])
    ri = store.note(WorkloadInfo(wl, cluster_queue="cq"))
    # min over the OVERRIDING pod sets only.
    assert store.tput[ri, 0] == 2 * SCORE_SCALE


def test_unprofiled_store_is_inert():
    rfs = {"a": ResourceFlavor.make("a"), "b": ResourceFlavor.make("b")}
    store = ThroughputProfileStore(_FakeEnc(["a", "b"]), rfs, capacity=2)
    store.note(_wi("w"))
    assert not store.any_profiled()


# ---------------------------------------------------------------------------
# End-to-end decision policy
# ---------------------------------------------------------------------------


def _hetero_fw(hetero=True, shards=None, fast_speed=4.0, cqs=1,
               cohort="", preemption=None, depth=1):
    cfg = Configuration(tpu_solver=TPUSolverConfig(preemption_engine="host"))
    fw = Framework(batch_solver=BatchSolver(hetero=hetero, shards=shards),
                   config=cfg, pipeline_depth=depth)
    fw.create_namespace("default", labels={})
    fw.create_resource_flavor(ResourceFlavor.make("slow"))
    fw.create_resource_flavor(
        ResourceFlavor.make("fast", speed_class=fast_speed))
    for i in range(cqs):
        quota = (16, 16) if cohort else 16
        fw.create_cluster_queue(make_cq(
            f"cq-{i}",
            rg("cpu", fq("slow", cpu=quota), fq("fast", cpu=quota)),
            cohort=cohort,
            preemption=preemption or ClusterQueuePreemption()))
        fw.create_local_queue(make_lq(f"lq-{i}", "default", cq=f"cq-{i}"))
    return fw


def _assigned_flavor(wl):
    return wl.admission.pod_set_assignments[0].flavors["cpu"]


def test_hetero_picks_fastest_fitting_flavor():
    fw = _hetero_fw(hetero=True)
    wl = Workload(name="w", namespace="default", queue_name="lq-0",
                  pod_sets=[PodSet.make("main", count=1, cpu=4)])
    fw.submit(wl)
    assert fw.tick() == 1
    # Slow is listed first (the first-fit choice); the speed ladder makes
    # every workload profiled, so hetero lands on fast.
    assert _assigned_flavor(wl) == "fast"
    # Explain answers "why flavor B".
    rec = fw.scheduler.explain.last_decision(wl.key)
    assert rec is not None and "hetero" in rec
    assert rec["hetero"]["flavor"] == "fast"
    assert rec["hetero"]["firstFitFlavor"] == "slow"
    assert rec["hetero"]["throughput"] == 4.0
    assert rec["hetero"]["scoreRank"] == 1


def test_hetero_off_keeps_first_fit():
    fw = _hetero_fw(hetero=False)
    wl = Workload(name="w", namespace="default", queue_name="lq-0",
                  pod_sets=[PodSet.make("main", count=1, cpu=4)])
    fw.submit(wl)
    assert fw.tick() == 1
    assert _assigned_flavor(wl) == "slow"


def test_kill_switch_restores_first_fit(monkeypatch):
    monkeypatch.setenv("KUEUE_TPU_NO_HETERO", "1")
    fw = _hetero_fw(hetero=True)
    wl = Workload(name="w", namespace="default", queue_name="lq-0",
                  pod_sets=[PodSet.make("main", count=1, cpu=4)])
    fw.submit(wl)
    assert fw.tick() == 1
    assert _assigned_flavor(wl) == "slow"


def test_hetero_respects_quota():
    """The fast flavor is saturated: hetero must take the best flavor
    among the ones that actually FIT — quota precedes throughput."""
    fw = _hetero_fw(hetero=True)
    filler = Workload(name="filler", namespace="default", queue_name="lq-0",
                      pod_sets=[PodSet.make(
                          "main", count=1, cpu=16,
                          flavor_throughputs={"fast": 8.0, "slow": 0.5})])
    fw.submit(filler)
    assert fw.tick() == 1
    assert _assigned_flavor(filler) == "fast"
    wl = Workload(name="w", namespace="default", queue_name="lq-0",
                  pod_sets=[PodSet.make("main", count=1, cpu=4)])
    fw.submit(wl)
    assert fw.tick() == 1
    assert _assigned_flavor(wl) == "slow"


def test_zero_throughput_on_every_fitting_flavor_keeps_default():
    """A profiled workload declaring 0 ("cannot run here") on BOTH
    flavors: every FIT slot scores the NEG_SCORE sentinel, the strict
    `best_score > neg` gate skips the override, and the default
    first-fit decision stands — device and referee agree (the argmax
    would otherwise land on slot 0 blind)."""
    import os

    os.environ["KUEUE_TPU_DEBUG_HETERO"] = "1"
    try:
        fw = _hetero_fw(hetero=True)
        wl = Workload(name="w", namespace="default", queue_name="lq-0",
                      pod_sets=[PodSet.make(
                          "main", count=1, cpu=4,
                          flavor_throughputs={"fast": 0.0, "slow": 0.0})])
        fw.submit(wl)
        assert fw.tick() == 1
        assert _assigned_flavor(wl) == "slow"  # the first-fit choice
    finally:
        os.environ.pop("KUEUE_TPU_DEBUG_HETERO", None)


def test_zero_throughput_flavor_is_never_chosen():
    """0 on the fast flavor only: hetero must keep the workload off it
    even though fast would FIT and carries the higher speed class."""
    fw = _hetero_fw(hetero=True)
    wl = Workload(name="w", namespace="default", queue_name="lq-0",
                  pod_sets=[PodSet.make(
                      "main", count=1, cpu=4,
                      flavor_throughputs={"fast": 0.0})])
    fw.submit(wl)
    assert fw.tick() == 1
    assert _assigned_flavor(wl) == "slow"


def test_decoder_rejects_zero_speed_class():
    doc = {"apiVersion": "kueue.x-k8s.io/v1beta1", "kind": "ResourceFlavor",
           "metadata": {"name": "f"}, "spec": {"speedClass": 0}}
    with pytest.raises(ser.DecodeError):
        ser.decode_resource_flavor(doc)


def test_requestless_group_never_reports_override(monkeypatch):
    """A second resource group the workload never requests must not
    surface in the explain payload: the kernel pins requestless groups
    to the default slot (`ghr` gate), so the group_ff diff only counts
    real decisions. Oracle-in-the-loop via KUEUE_TPU_DEBUG_HETERO."""
    monkeypatch.setenv("KUEUE_TPU_DEBUG_HETERO", "1")
    cfg = Configuration(tpu_solver=TPUSolverConfig(
        preemption_engine="host"))
    fw = Framework(batch_solver=BatchSolver(hetero=True), config=cfg)
    fw.create_namespace("default", labels={})
    for name, speed in (("slow", 1.0), ("fast", 4.0),
                        ("gpu-a", 1.0), ("gpu-b", 2.0)):
        fw.create_resource_flavor(
            ResourceFlavor.make(name, speed_class=speed))
    fw.create_cluster_queue(make_cq(
        "cq",
        rg("cpu", fq("slow", cpu=16), fq("fast", cpu=16)),
        rg("gpu", fq("gpu-a", gpu=8), fq("gpu-b", gpu=8))))
    fw.create_local_queue(make_lq("lq", "default", cq="cq"))
    wl = Workload(name="w", namespace="default", queue_name="lq",
                  pod_sets=[PodSet.make("main", count=1, cpu=4)])
    fw.submit(wl)
    assert fw.tick() == 1
    assert _assigned_flavor(wl) == "fast"
    rec = fw.scheduler.explain.last_decision(wl.key)
    assert rec["hetero"]["flavor"] == "fast"      # the cpu group's win,
    assert rec["hetero"]["firstFitFlavor"] == "slow"  # not a gpu ghost


def test_per_workload_override_beats_speed_class():
    """A workload whose override says fast is SLOW for it stays put."""
    fw = _hetero_fw(hetero=True)
    wl = Workload(name="w", namespace="default", queue_name="lq-0",
                  pod_sets=[PodSet.make(
                      "main", count=1, cpu=4,
                      flavor_throughputs={"fast": 0.25, "slow": 2.0})])
    fw.submit(wl)
    assert fw.tick() == 1
    assert _assigned_flavor(wl) == "slow"


# ---------------------------------------------------------------------------
# Default-mode identity: churn goldens across every registered engine
# ---------------------------------------------------------------------------

_ENGINE_KNOB = {
    "host": None,
    "scan-jax": "jax",
    "scan-pallas": "pallas",
    "batch-native": "native",
    "batch-jax": "jax",
}

_KNOBS = []
for _spec in _modes.ENGINES:
    if _spec.optional_import and not _modes.engine_importable(_spec):
        continue
    knob = _ENGINE_KNOB[_spec.name]
    if knob not in _KNOBS:
        _KNOBS.append(knob)


def test_registry_covered():
    assert set(_ENGINE_KNOB) == {e.name for e in _modes.ENGINES}, \
        "new victim-search engine registered; map it onto a " \
        "preemption_engine knob here so the hetero goldens run it"


def _drive(hetero_mode: bool, engine, ticks: int = 200,
           profiled: bool = False, weighted_tree: bool = False):
    """Seeded churn stream through the REAL Framework; returns the
    per-tick decision trail (the test_arena golden harness shape)."""
    cfg = Configuration(tpu_solver=TPUSolverConfig(
        preemption_engine="host" if engine is None else engine))
    fw = Framework(batch_solver=BatchSolver(hetero=hetero_mode),
                   config=cfg)
    fw.create_namespace("default", labels={})
    # speed_class 1.0 everywhere: profiles only come from per-workload
    # overrides, which `profiled` gates.
    fw.create_resource_flavor(ResourceFlavor.make("on-demand"))
    fw.create_resource_flavor(ResourceFlavor.make("spot"))
    if weighted_tree:
        fw.create_cohort(CohortSpec(name="root"))
        fw.create_cohort(CohortSpec(name="left", parent="root"))
        fw.create_cohort(CohortSpec(name="right", parent="root"))
    import dataclasses

    for i in range(4):
        cohort = (("left" if i % 2 else "right") if weighted_tree
                  else f"cohort-{i % 2}")
        cq = make_cq(
            f"cq-{i}",
            rg("cpu", fq("on-demand", cpu=(16, 16, 12)),
               fq("spot", cpu=(8, 8, 6))),
            cohort=cohort,
            preemption=ClusterQueuePreemption(
                within_cluster_queue="LowerPriority",
                reclaim_within_cohort="Any"))
        if weighted_tree:
            cq = dataclasses.replace(
                cq, fair_sharing=FairSharing(weight=float(1 + i % 3)))
        fw.create_cluster_queue(cq)
        fw.create_local_queue(make_lq(f"lq-{i}", "default", cq=f"cq-{i}"))

    rnd = random.Random(4321)
    seq = [0]
    pending: dict = {}
    admitted: dict = {}
    trail = []
    tick_admitted: list = []
    tick_preempted: list = []
    orig_admit = fw.scheduler.apply_admission
    orig_preempt = fw.scheduler.apply_preemption

    def apply_admission(wl):
        ok = orig_admit(wl)
        if ok:
            tick_admitted.append(
                (wl.key, tuple(sorted(
                    (psa.name, tuple(sorted(psa.flavors.items())))
                    for psa in wl.admission.pod_set_assignments))))
            admitted[wl.key] = wl
            pending.pop(wl.key, None)
        return ok

    def apply_preemption(wl, msg):
        tick_preempted.append(wl.key)
        return orig_preempt(wl, msg)

    fw.scheduler.apply_admission = apply_admission
    fw.scheduler.apply_preemption = apply_preemption

    def submit_one():
        seq[0] += 1
        i = seq[0]
        tputs = None
        if profiled and i % 3 == 0:
            tputs = {"spot": float(rnd.choice([2, 4])),
                     "on-demand": 1.0}
        wl = Workload(
            name=f"wl-{i}", namespace="default",
            queue_name=f"lq-{rnd.randrange(4)}",
            priority=rnd.randint(-2, 3),
            creation_time=float(1000 + i),
            pod_sets=[PodSet.make("ps0", count=rnd.randint(1, 3),
                                  cpu=rnd.randint(1, 4),
                                  flavor_throughputs=tputs)])
        pending[wl.key] = wl
        fw.submit(wl)

    for _ in range(30):
        submit_one()
    for _ in range(ticks):
        tick_admitted.clear()
        tick_preempted.clear()
        fw.tick()
        trail.append((tuple(sorted(tick_admitted)),
                      tuple(sorted(tick_preempted))))
        for _ in range(rnd.randint(0, 3)):
            submit_one()
        if pending and rnd.random() < 0.3:
            key = rnd.choice(sorted(pending))
            wl = pending.pop(key)
            if not wl.is_admitted:
                fw.delete_workload(wl)
        done = [k for k, w in sorted(admitted.items())
                if w.is_admitted and not w.is_finished]
        for key in done[:rnd.randint(0, 4)]:
            wl = admitted.pop(key)
            fw.finish(wl)
            fw.delete_workload(wl)
        for key in list(admitted):
            if not admitted[key].is_admitted:
                wl = admitted.pop(key)
                if not wl.is_finished:
                    pending[key] = wl
        fw.prewarm_idle()
    return trail


@pytest.mark.parametrize("engine", _KNOBS, ids=[str(k) for k in _KNOBS])
def test_unprofiled_hetero_is_byte_identical(engine):
    """Mode ON but nothing profiled (homogeneous speed classes, no
    overrides) vs mode OFF: 200 randomized churn ticks, identical
    admissions (with flavor detail) and preemptions — the default mode
    is provably untouched, per registered engine."""
    on = _drive(True, engine, profiled=False)
    off = _drive(False, engine, profiled=False)
    assert on == off


def test_kill_switch_ab_identity_with_profiles(monkeypatch):
    """Profiles PRESENT but the kill switch set: decisions must equal
    the mode-off run byte for byte."""
    monkeypatch.setenv("KUEUE_TPU_NO_HETERO", "1")
    killed = _drive(True, None, ticks=120, profiled=True)
    monkeypatch.delenv("KUEUE_TPU_NO_HETERO")
    off = _drive(False, None, ticks=120, profiled=True)
    assert killed == off


# ---------------------------------------------------------------------------
# Referee identity (weighted / borrowing / KEP-79)
# ---------------------------------------------------------------------------


def test_device_matches_referee_borrowing_churn(monkeypatch):
    """KUEUE_TPU_DEBUG_HETERO=1 re-derives every fresh device verdict
    with the sequential hetero referee inside the tick — a divergence
    raises. Borrowing-limit cohort scenario with live profiles."""
    monkeypatch.setenv("KUEUE_TPU_DEBUG_HETERO", "1")
    _drive(True, None, ticks=80, profiled=True)


def test_device_matches_referee_weighted_kep79(monkeypatch):
    """The same oracle-in-the-loop drive over a weighted KEP-79 tree
    with FairSharing on (fair ordering + hetero choice compose)."""
    monkeypatch.setenv("KUEUE_TPU_DEBUG_HETERO", "1")
    features.set_enabled(features.FAIR_SHARING, True)
    _drive(True, None, ticks=80, profiled=True, weighted_tree=True)


def test_referee_unit_identity():
    """Direct oracle comparison: one batched device solve vs the
    sequential referee, per workload, on a mixed-profile batch."""
    from kueue_tpu.hetero.referee import hetero_assign_flavors

    fw = _hetero_fw(hetero=True)
    wls = []
    for i in range(6):
        tputs = {"fast": float(1 + i), "slow": 2.0} if i % 2 else None
        wl = Workload(name=f"w-{i}", namespace="default",
                      queue_name="lq-0",
                      pod_sets=[PodSet.make("main", count=1, cpu=2,
                                            flavor_throughputs=tputs)])
        wls.append(wl)
        fw.submit(wl)
    solver = fw.scheduler.batch_solver
    snapshot = fw.scheduler._mirror.refresh()
    infos = fw.queues.pending_infos()
    infos.sort(key=lambda wi: wi.obj.name)
    assignments = solver.solve(infos, snapshot)
    # Replay against the exact scores/rows the solver used.
    store = solver._hetero_store
    rows = store.rows_for(infos)
    scores = solver._hetero_scores
    assert scores is not None
    for k, (wi, a) in enumerate(zip(infos, assignments)):
        cq = snapshot.cluster_queues[wi.cluster_queue]
        saved = wi.last_assignment
        ref = hetero_assign_flavors(
            wi, cq, snapshot.resource_flavors, scores[rows[k]],
            solver._enc.flavor_index, bool(store.profiled[rows[k]]))
        wi.last_assignment = saved
        got = [sorted((r, fa.name, fa.mode, fa.borrow)
                      for r, fa in ps.flavors.items())
               for ps in a.pod_sets]
        want = [sorted((r, fa.name, fa.mode, fa.borrow)
                       for r, fa in ps.flavors.items())
                for ps in ref.pod_sets]
        assert got == want, wi.obj.name


# ---------------------------------------------------------------------------
# Steady state: zero dispatches
# ---------------------------------------------------------------------------


def test_hetero_steady_state_dispatches_nothing():
    """Saturated StrictFIFO backlog under the hetero mode: once the
    fingerprints (which ride the score-matrix version) settle, ticks
    replay cached verdicts and dispatch NOTHING."""
    cfg = Configuration(tpu_solver=TPUSolverConfig(
        preemption_engine="host"))
    fw = Framework(batch_solver=BatchSolver(hetero=True), config=cfg)
    fw.create_namespace("default", labels={})
    fw.create_resource_flavor(ResourceFlavor.make("slow"))
    fw.create_resource_flavor(
        ResourceFlavor.make("fast", speed_class=4.0))
    fw.create_cluster_queue(make_cq(
        "cq", rg("cpu", fq("slow", cpu=4), fq("fast", cpu=4)),
        strategy="StrictFIFO"))
    fw.create_local_queue(make_lq("lq", "default", cq="cq"))
    for i in range(6):
        fw.submit(Workload(
            name=f"w-{i}", namespace="default", queue_name="lq",
            creation_time=float(i),
            pod_sets=[PodSet.make("main", count=1, cpu=3,
                                  flavor_throughputs={"fast": 4.0})]))
    solver = fw.scheduler.batch_solver
    quiet = 0
    for _ in range(60):
        before = solver.dispatches
        fw.tick()
        quiet = quiet + 1 if solver.dispatches == before else 0
        if quiet >= 5:
            break
    assert quiet >= 5, "hetero steady state kept dispatching solves"
    v = solver.hetero_version
    d = solver.dispatches
    for _ in range(5):
        fw.tick()
    assert solver.dispatches == d
    assert solver.hetero_version == v


# ---------------------------------------------------------------------------
# Cohort-mesh sharding
# ---------------------------------------------------------------------------


def test_hetero_shard_identity(monkeypatch):
    """shards=2 hetero decisions == single-device hetero decisions."""
    monkeypatch.delenv("KUEUE_TPU_SHARDS", raising=False)

    def run(shards):
        fw = _hetero_fw(hetero=True, shards=shards, cqs=4)
        rnd = random.Random(11)
        for i in range(24):
            tputs = {"fast": float(rnd.choice([2, 4]))} if i % 2 else None
            fw.submit(Workload(
                name=f"w-{i}", namespace="default",
                queue_name=f"lq-{i % 4}", creation_time=float(i),
                pod_sets=[PodSet.make("main", count=1,
                                      cpu=rnd.randint(1, 4),
                                      flavor_throughputs=tputs)]))
        got = []
        for _ in range(10):
            fw.tick()
        for key, wl in sorted(fw.workloads.items()):
            if wl.admission is not None:
                got.append((key, tuple(sorted(
                    (psa.name, tuple(sorted(psa.flavors.items())))
                    for psa in wl.admission.pod_set_assignments))))
        return got

    assert run(None) == run(2)


# ---------------------------------------------------------------------------
# Aggregate throughput: the in-process gain gate
# ---------------------------------------------------------------------------


def test_hetero_beats_first_fit_aggregate_throughput():
    from kueue_tpu.utils.synthetic import synthetic_framework

    def run(hetero_mode):
        fw = synthetic_framework(
            num_cqs=8, num_cohorts=2, num_flavors=8, num_pending=96,
            usage_fill=0.1, seed=5, hetero=True,
            batch_solver=BatchSolver(hetero=hetero_mode),
            config=Configuration(tpu_solver=TPUSolverConfig(
                preemption_engine="host")))
        for _ in range(10):
            fw.tick()
        return aggregate_effective_throughput(fw.cache)

    # Moderate contention — the regime the mode exists for (at full
    # saturation every flavor fills either way and the gain washes out).
    gain = run(True) / max(run(False), 1e-9)
    assert gain > 1.05, f"hetero gain {gain:.3f} <= first-fit"


def test_flavor_utilization_reader():
    fw = _hetero_fw(hetero=True)
    wl = Workload(name="w", namespace="default", queue_name="lq-0",
                  pod_sets=[PodSet.make("main", count=1, cpu=4)])
    fw.submit(wl)
    fw.tick()
    util = fw.scheduler.batch_solver.flavor_utilization()
    assert util["fast"]["used"] == 4_000  # canonical milli-cpu
    assert util["slow"]["used"] == 0
    assert util["fast"]["nominal"] == 16_000
