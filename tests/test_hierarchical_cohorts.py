"""Hierarchical cohorts (KEP-79), implemented natively from the KEP.

Covers the KEP's own test plan (keps/79-hierarchical-cohorts "Unit Tests"):
existing functionality at 2 levels, long-distance borrowing on multi-level
hierarchies, lending/borrowing limits placed on many levels, preemptions
across the hierarchy — plus both KEP user stories, cohort-level quota, and
the cycle failure mode (all admissions in the broken tree stop)."""

import pytest

from kueue_tpu.api.types import ClusterQueuePreemption, CohortSpec
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.core.hierarchy import hierarchical_lack, subtree_t
from kueue_tpu.models.flavor_fit import BatchSolver

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg


def cohort(name, parent="", *groups):
    return CohortSpec(name=name, parent=parent,
                      resource_groups=tuple(groups))


def framework(batch=False):
    fw = Framework(batch_solver=BatchSolver() if batch else None)
    fw.create_resource_flavor(make_flavor("default"))
    return fw


def add_cq(fw, name, cpu, cohort_name, lq=None, borrow=None, lend=None,
           preemption=None):
    fw.create_cluster_queue(make_cq(
        name, rg("cpu", fq("default", cpu=(cpu, borrow, lend))),
        cohort=cohort_name, preemption=preemption))
    fw.create_local_queue(make_lq(lq or f"lq-{name}", cq=name))


# -- 2-level compatibility ---------------------------------------------------


@pytest.mark.parametrize("batch", [False, True], ids=["referee", "batch"])
def test_flat_two_level_unchanged(batch):
    """A spec-less cohort stays on the flat code path and behaves exactly
    as before (borrowing within the cohort, capacity capped)."""
    fw = framework(batch)
    add_cq(fw, "a", 4, "co")
    add_cq(fw, "b", 4, "co")
    fw.submit(make_wl("w1", "lq-a", cpu=6, creation_time=1.0))  # borrows 2
    fw.run_until_settled()
    assert fw.admitted_workloads("a") == ["default/w1"]
    fw.submit(make_wl("w2", "lq-b", cpu=3, creation_time=2.0))
    fw.run_until_settled()
    assert fw.pending_workloads("b") == 1  # 6+3 > 8


def test_flat_decisions_identical_under_t_invariant():
    """On a flat tree the hierarchical T-invariant agrees with the flat
    capacity check for every reachable state (the 2-level special case of
    the KEP formula)."""
    fw = framework()
    add_cq(fw, "a", 4, "co")
    add_cq(fw, "b", 4, "co")
    fw.submit(make_wl("w1", "lq-a", cpu=6, creation_time=1.0))
    fw.run_until_settled()
    snap = fw.cache.snapshot()
    cq_b = snap.cluster_queues["b"]
    # Flat path objects report no hierarchy...
    assert not cq_b.cohort.is_hierarchical()
    # ...but the T math still gives the same verdicts: 2 more cpu fit,
    # 3 do not (6 used of 8).
    assert hierarchical_lack(cq_b, "default", "cpu", 2000) == 0
    assert hierarchical_lack(cq_b, "default", "cpu", 3000) == 1000


# -- long-distance borrowing -------------------------------------------------


@pytest.mark.parametrize("batch", [False, True], ids=["referee", "batch"])
def test_long_distance_borrowing(batch):
    """A ClusterQueue borrows capacity from a sibling subtree two levels
    away: root -> {left -> cq-a, right -> cq-b}."""
    fw = framework(batch)
    fw.create_cohort(cohort("root"))
    fw.create_cohort(cohort("left", "root"))
    fw.create_cohort(cohort("right", "root"))
    add_cq(fw, "a", 2, "left", borrow=100)
    add_cq(fw, "b", 6, "right")
    fw.submit(make_wl("big", "lq-a", cpu=8))  # needs 6 borrowed via root
    fw.run_until_settled()
    assert fw.admitted_workloads("a") == ["default/big"]

    # The lender's subtree balance went negative nowhere; the borrower's
    # subtree carries the debt.
    snap = fw.cache.snapshot()
    left = snap.cluster_queues["a"].cohort
    assert left.name == "left"
    assert subtree_t(left, "default", "cpu") == -6000
    assert subtree_t(left.root(), "default", "cpu") == 0


@pytest.mark.parametrize("batch", [False, True], ids=["referee", "batch"])
def test_cohort_level_quota_shared_with_subtree(batch):
    """Nominal quota at a Cohort level has no owning CQ and is shared with
    the whole subtree (KEP proposal bullet 3)."""
    fw = framework(batch)
    fw.create_cohort(cohort("org", "", rg("cpu", fq("default", cpu=10))))
    add_cq(fw, "a", 0, "org", borrow=100)
    fw.submit(make_wl("w", "lq-a", cpu=10))
    fw.run_until_settled()
    assert fw.admitted_workloads("a") == ["default/w"]
    fw.submit(make_wl("over", "lq-a", cpu=1))
    fw.run_until_settled()
    assert fw.pending_workloads("a") == 1


# -- limits at many levels ---------------------------------------------------


def test_story1_research_cannot_borrow_production_can():
    """KEP Story 1: production may borrow research quota, not vice versa —
    research org's top cohort sets borrowingLimit 0."""
    fw = framework()
    fw.create_cohort(cohort("company"))
    fw.create_cohort(cohort(
        "research", "company",
        rg("cpu", fq("default", cpu=(0, 0)))))  # borrowingLimit 0
    fw.create_cohort(cohort("production", "company"))
    add_cq(fw, "res-team", 4, "research", borrow=100)
    add_cq(fw, "prod-team", 4, "production", borrow=100)

    fw.submit(make_wl("prod-big", "lq-prod-team", cpu=8, creation_time=1.0))
    fw.run_until_settled()
    assert fw.admitted_workloads("prod-team") == ["default/prod-big"]

    fw.submit(make_wl("res-big", "lq-res-team", cpu=5, creation_time=2.0))
    fw.run_until_settled()
    # research subtree may not go negative: 5 > its own 4.
    assert fw.admitted_workloads("res-team") == []


def test_story2_special_queue_borrows_from_sealed_orgs():
    """KEP Story 2: organizations don't borrow from each other
    (borrowingLimit 0 at their cohorts), but a special low-priority queue
    under the top cohort can borrow everyone's unused capacity."""
    fw = framework()
    fw.create_cohort(cohort("top"))
    fw.create_cohort(cohort("org1", "top",
                            rg("cpu", fq("default", cpu=(0, 0)))))
    fw.create_cohort(cohort("org2", "top",
                            rg("cpu", fq("default", cpu=(0, 0)))))
    add_cq(fw, "team1", 4, "org1", borrow=100)
    add_cq(fw, "team2", 4, "org2", borrow=100)
    add_cq(fw, "special", 0, "top", borrow=100)

    fw.submit(make_wl("sp", "lq-special", cpu=8, creation_time=1.0))
    fw.run_until_settled()
    assert fw.admitted_workloads("special") == ["default/sp"]

    # team1 can no longer use even its own quota's worth of borrowing
    # room... but its own nominal is untouched:
    fw.submit(make_wl("t1", "lq-team1", cpu=4, creation_time=2.0))
    fw.run_until_settled()
    assert fw.admitted_workloads("team1") == []  # capacity all consumed
    # org borrowing seal: even with free capacity, crossing orgs is barred.
    fw2 = framework()
    fw2.create_cohort(cohort("top"))
    fw2.create_cohort(cohort("org1", "top",
                             rg("cpu", fq("default", cpu=(0, 0)))))
    fw2.create_cohort(cohort("org2", "top",
                             rg("cpu", fq("default", cpu=(0, 0)))))
    add_cq(fw2, "team1", 4, "org1", borrow=100)
    add_cq(fw2, "team2", 4, "org2", borrow=100)
    fw2.submit(make_wl("t1", "lq-team1", cpu=6))
    fw2.run_until_settled()
    assert fw2.admitted_workloads("team1") == []


def test_lending_limit_at_cohort_level():
    """lendingLimit on a cohort caps what the rest of the tree can take
    from its subtree."""
    fw = framework()
    fw.create_cohort(cohort("root"))
    fw.create_cohort(cohort(
        "givers", "root",
        rg("cpu", fq("default", cpu=(0, None, 2)))))  # lend at most 2
    fw.create_cohort(cohort("takers", "root"))
    add_cq(fw, "g", 8, "givers")
    add_cq(fw, "t", 0, "takers", borrow=100)
    fw.submit(make_wl("w3", "lq-t", cpu=3))
    fw.run_until_settled()
    assert fw.admitted_workloads("t") == []  # 3 > lending cap 2
    fw.submit(make_wl("w2", "lq-t", cpu=2))
    fw.run_until_settled()
    assert fw.admitted_workloads("t") == ["default/w2"]


# -- preemption across the hierarchy ----------------------------------------


def test_reclaim_across_subtrees():
    """A ClusterQueue reclaims its nominal quota from a borrower in a
    different subtree (preemption acts on the whole structure)."""
    fw = framework()
    fw.create_cohort(cohort("root"))
    fw.create_cohort(cohort("left", "root"))
    fw.create_cohort(cohort("right", "root"))
    add_cq(fw, "a", 4, "left", borrow=100,
           preemption=ClusterQueuePreemption(reclaim_within_cohort="Any"))
    add_cq(fw, "b", 4, "right", borrow=100)
    fw.submit(make_wl("borrower", "lq-b", cpu=8, creation_time=1.0))
    fw.run_until_settled()
    assert fw.admitted_workloads("b") == ["default/borrower"]

    fw.submit(make_wl("reclaimer", "lq-a", cpu=4, creation_time=2.0))
    fw.run_until_settled()
    assert fw.admitted_workloads("a") == ["default/reclaimer"]
    assert fw.admitted_workloads("b") == []


# -- cycles ------------------------------------------------------------------


def test_cycle_stops_admissions_in_tree():
    """A parent cycle deactivates every ClusterQueue in the structure;
    an unrelated tree keeps admitting (KEP Risks and Mitigations)."""
    fw = framework()
    fw.create_cohort(cohort("x", "y"))
    fw.create_cohort(cohort("y", "x"))
    add_cq(fw, "broken", 4, "x")
    add_cq(fw, "fine", 4, "healthy")
    fw.submit(make_wl("w1", "lq-broken", cpu=1))
    fw.submit(make_wl("w2", "lq-fine", cpu=1))
    fw.run_until_settled()
    assert fw.admitted_workloads("broken") == []
    assert fw.admitted_workloads("fine") == ["default/w2"]

    # Breaking the cycle reactivates the tree.
    fw.update_cohort(cohort("y", ""))
    fw.run_until_settled()
    assert fw.admitted_workloads("broken") == ["default/w1"]


def test_self_parent_rejected():
    import pytest as _pytest

    from kueue_tpu.webhooks.validation import validate_cohort
    errs = validate_cohort(cohort("a", "a"))
    assert any("own parent" in e for e in errs)
    fw = framework()
    with _pytest.raises(Exception):
        fw.create_cohort(cohort("a", "a"))


# -- randomized device-vs-referee equivalence --------------------------------


@pytest.mark.parametrize("seed", range(6))
def test_hierarchical_device_equivalence(seed):
    """The device kernel's ancestor-path walk must reproduce the referee's
    hierarchical decisions exactly on randomized trees (random depths,
    cohort quotas, limits, usage)."""
    import random

    from kueue_tpu.core.cache import Cache
    from kueue_tpu.core.workload import WorkloadInfo
    from kueue_tpu.solver.referee import assign_flavors
    from tests.test_cache import admit
    from tests.test_solver_equivalence import assert_assignment_equal

    rnd = random.Random(seed)
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_or_update_resource_flavor(make_flavor("spot"))

    # Random forest: root + two mid cohorts (random quota/limits), CQs
    # attached at random levels.
    def maybe_limits():
        return (rnd.choice([0, rnd.randint(0, 8)]),
                rnd.choice([None, rnd.randint(0, 8)]),
                rnd.choice([None, rnd.randint(0, 4)]))

    cache.add_or_update_cohort_spec(CohortSpec(name="root"))
    for mid in ("m1", "m2"):
        n, b, l = maybe_limits()
        groups = ()
        if rnd.random() < 0.7:
            groups = (rg("cpu", fq("default", cpu=(n, b, l))),)
        cache.add_or_update_cohort_spec(
            CohortSpec(name=mid, parent="root", resource_groups=groups))

    num_cqs = 4
    for i in range(num_cqs):
        lend = rnd.choice([None, rnd.randint(0, 4)])
        cache.add_cluster_queue(make_cq(
            f"cq{i}",
            rg("cpu", fq("default", cpu=(rnd.randint(0, 8),
                                         rnd.choice([None, 100]), lend)),
               fq("spot", cpu=rnd.randint(0, 6))),
            cohort=rnd.choice(["m1", "m2", "root"])))
        cache.add_local_queue(make_lq(f"lq{i}", cq=f"cq{i}"))

    for i in range(6):
        c = rnd.randrange(num_cqs)
        cache.add_or_update_workload(admit(
            make_wl(f"adm{i}", f"lq{c}", cpu=rnd.randint(1, 4)),
            f"cq{c}", rnd.choice(["default", "spot"])))

    snap = cache.snapshot()
    pending = []
    for i in range(16):
        c = rnd.randrange(num_cqs)
        pending.append(WorkloadInfo(
            make_wl(f"p{i}", f"lq{c}", cpu=rnd.randint(1, 8)),
            cluster_queue=f"cq{c}"))

    solver = BatchSolver()
    got = solver.solve(pending, snap)
    for i, wi in enumerate(pending):
        cq = snap.cluster_queues[wi.cluster_queue]
        want = assign_flavors(
            WorkloadInfo(wi.obj, cluster_queue=wi.cluster_queue), cq,
            snap.resource_flavors)
        assert_assignment_equal(want, got[i], f"seed {seed} wl {i}")


@pytest.mark.parametrize("batch", [False, True], ids=["referee", "batch"])
def test_spec_only_subtree_quota_counts(batch):
    """A spec-only cohort subtree with quota but no member ClusterQueues
    still lends its capacity to the rest of the tree — on both solver
    paths (regression: the device encoding must walk trees downward from
    the roots, not only up from member CQs)."""
    fw = framework(batch)
    fw.create_cohort(cohort("root"))
    fw.create_cohort(cohort("reserve", "root",
                            rg("cpu", fq("default", cpu=10))))
    add_cq(fw, "a", 0, "root", borrow=100)
    fw.submit(make_wl("w", "lq-a", cpu=10))
    fw.run_until_settled()
    assert fw.admitted_workloads("a") == ["default/w"]


def test_sibling_subtrees_admit_same_tick():
    """The admission-cycle guard charges same-tick reservations to the
    admitting CQ's own cohort node, not root-wide: an admission in one
    subtree must not defer an independent sibling subtree (only genuinely
    shared ancestor capacity defers). Regression for the r1/r2 advisor
    finding on the per-ancestor-path cycle guard."""
    fw = framework()
    fw.create_cohort(cohort("root"))
    # left cannot lend anything out of its subtree; right is independent.
    fw.create_cohort(cohort("left", "root",
                            rg("cpu", fq("default", cpu=(0, None, 0)))))
    fw.create_cohort(cohort("right", "root",
                            rg("cpu", fq("default", cpu=(0, None, 0)))))
    add_cq(fw, "l", 4, "left")
    add_cq(fw, "r", 4, "right")
    # Same tick: one head per CQ. Both fit within their own subtrees.
    fw.submit(make_wl("wl-left", "lq-l", cpu=4, creation_time=1.0))
    fw.submit(make_wl("wl-right", "lq-r", cpu=4, creation_time=2.0))
    n = fw.tick()
    assert n == 2, "sibling subtrees must both admit in one tick"
    assert fw.admitted_workloads("l") == ["default/wl-left"]
    assert fw.admitted_workloads("r") == ["default/wl-right"]


def test_shared_ancestor_capacity_still_guarded_same_tick():
    """Two same-tick candidates that both need the SAME ancestor's
    capacity: the first reserves it, the second must be deferred —
    the per-node charge still propagates up through lending clamps."""
    fw = framework()
    # All capacity lives at the root cohort; both leaves borrow from it.
    fw.create_cohort(cohort("root", "",
                            rg("cpu", fq("default", cpu=4))))
    fw.create_cohort(cohort("left", "root"))
    fw.create_cohort(cohort("right", "root"))
    add_cq(fw, "l", 0, "left")
    add_cq(fw, "r", 0, "right")
    fw.submit(make_wl("wl-left", "lq-l", cpu=4, creation_time=1.0))
    fw.submit(make_wl("wl-right", "lq-r", cpu=4, creation_time=2.0))
    n = fw.tick()
    assert n == 1, "root capacity admits only one of the two"
    fw.run_until_settled()
    total = len(fw.admitted_workloads("l")) + len(fw.admitted_workloads("r"))
    assert total == 1  # 4 cpu total can't hold both


def test_mirror_incremental_refresh_matches_rebuild_on_tree():
    """The incremental snapshot mirror now serves hierarchical trees too
    (usage churn re-clones member CQs; the tree wiring is structural and
    only rebuilds on structure_version bumps). After admission/finish
    churn on a 3-level tree, the mirrored snapshot must match a
    from-scratch Snapshot.build on every CQ's usage, the tree wiring, and
    the feasibility verdicts the hierarchy walk derives from it."""
    import random

    from kueue_tpu.core.hierarchy import tree_capacity
    from kueue_tpu.core.snapshot import Snapshot

    fw = framework(batch=True)
    fw.create_cohort(cohort("root"))
    for mid in ("west", "east"):
        fw.create_cohort(cohort(mid, "root"))
    for i in range(8):
        add_cq(fw, f"cq-{i}", 8, "west" if i % 2 else "east")

    rnd = random.Random(5)
    live = []
    seq = [0]

    def submit():
        seq[0] += 1
        wl = make_wl(f"w-{seq[0]}", f"lq-cq-{rnd.randrange(8)}",
                     cpu=rnd.randint(1, 4), creation_time=float(seq[0]))
        fw.submit(wl)
        return wl

    for step in range(12):
        for _ in range(4):
            live.append(submit())
        fw.run_until_settled(max_ticks=20)
        done = [wl for wl in live if wl.is_admitted][:2]
        for wl in done:
            fw.finish(wl)
            fw.delete_workload(wl)
            live.remove(wl)

        mirror_snap = fw.scheduler._mirror.refresh()
        rebuilt = Snapshot.build(fw.cache)
        assert set(mirror_snap.cluster_queues) == set(rebuilt.cluster_queues)
        for name, m_cq in mirror_snap.cluster_queues.items():
            r_cq = rebuilt.cluster_queues[name]
            assert m_cq.usage == r_cq.usage, (step, name)
            assert sorted(m_cq.workloads) == sorted(r_cq.workloads)
            assert (m_cq.cohort.name if m_cq.cohort else None) == \
                (r_cq.cohort.name if r_cq.cohort else None)
        # Tree wiring + feasibility view agree.
        m_root = next(iter(mirror_snap.cluster_queues.values())).cohort.root()
        r_root = next(iter(rebuilt.cluster_queues.values())).cohort.root()
        assert tree_capacity(m_root) == tree_capacity(r_root), step


def test_hier_cycle_state_matches_dict_walk():
    """ops/hier_cycle.HierCycleState (the dense per-cycle tree
    bookkeeping) must agree with fits_in_hierarchy(..., extra=...) — the
    dict referee — on randomized trees, reservations, and probes: same
    fits verdicts after every fold."""
    import random

    from kueue_tpu.core.hierarchy import fits_in_hierarchy
    from kueue_tpu.core.workload import WorkloadInfo
    from kueue_tpu.ops.hier_cycle import HierCycleState
    from kueue_tpu.solver import schema as sch

    for seed in range(6):
        rnd = random.Random(seed)
        fw = framework(batch=True)
        fw.create_cohort(cohort("root"))
        n_mids = rnd.randint(1, 3)
        for m in range(n_mids):
            # Mid cohorts sometimes carry their own quota and limits.
            groups = ()
            if rnd.random() < 0.5:
                nom = rnd.randint(0, 8)
                groups = (rg("cpu", fq("default", cpu=(
                    nom,
                    rnd.choice([None, rnd.randint(0, 8)]),
                    rnd.choice([None, rnd.randint(0, nom)])))),)
            fw.create_cohort(cohort(f"mid-{m}", "root", *groups))
        n_cqs = rnd.randint(4, 10)
        for i in range(n_cqs):
            nom = rnd.randint(2, 10)
            add_cq(fw, f"cq-{i}", nom,
                   f"mid-{rnd.randrange(n_mids)}",
                   borrow=rnd.choice([None, rnd.randint(0, 6)]),
                   lend=rnd.choice([None, rnd.randint(0, nom)]))
        # Random admitted usage.
        for i in range(n_cqs):
            if rnd.random() < 0.6:
                wl = make_wl(f"bg-{i}", f"lq-cq-{i}",
                             cpu=rnd.randint(1, 4), creation_time=float(i))
                fw.submit(wl)
        fw.run_until_settled(max_ticks=30)

        snapshot = fw.cache.snapshot()
        enc = sch.encode_cluster_queues(snapshot)
        usage = sch.encode_usage(snapshot, enc)
        if enc.hier is None:
            continue
        state = HierCycleState(enc, usage.usage)

        cycle_usage: dict = {}
        for step in range(30):
            name = f"cq-{rnd.randrange(n_cqs)}"
            cq = snapshot.cluster_queues.get(name)
            if cq is None:
                continue
            val = rnd.randint(1, 5) * 1000
            frq = {"default": {"cpu": val}}
            ci = enc.cq_index[name]
            want = fits_in_hierarchy(cq, frq, extra=cycle_usage)
            got = state.fits(ci, state.coords(frq))
            assert got == want, (seed, step, name, val, cycle_usage)
            if not state.folds:
                # The vectorized fold-free batch check must agree too.
                (fi, ri, v), = state.coords(frq)
                got_v = bool(state.fits_many([ci], [fi], [ri], [v])[0])
                assert got_v == want, (seed, step, name, "fits_many")
            if rnd.random() < 0.6:
                # Fold the reservation into both bookkeepers.
                state.fold(ci, state.coords(frq))
                node = cq.cohort.name
                cycle_usage.setdefault(node, {}).setdefault(
                    "default", {})
                cycle_usage[node]["default"]["cpu"] = \
                    cycle_usage[node]["default"].get("cpu", 0) + val


def test_mirror_keeps_cycle_deactivated_cqs_excluded_on_churn():
    """Regression: a cohort cycle deactivates its tree's ClusterQueues in
    the snapshot (cache-side active() cannot see this). Usage-only churn
    on such a CQ (an admitted workload finishing) must NOT make the
    incremental mirror re-insert it as a phantom cohortless entry — the
    mirrored snapshot must keep matching a from-scratch build."""
    from kueue_tpu.api.types import CohortSpec
    from kueue_tpu.core.snapshot import Snapshot

    fw = framework(batch=True)
    fw.create_cohort(cohort("a"))
    add_cq(fw, "cq-0", 8, "a")
    wl = make_wl("w1", "lq-cq-0", cpu=2, creation_time=1.0)
    fw.submit(wl)
    fw.run_until_settled(max_ticks=10)
    assert wl.is_admitted

    # Introduce a cycle a -> b -> a: the tree's CQs deactivate.
    fw.cache.add_or_update_cohort_spec(CohortSpec(name="b", parent="a"))
    fw.cache.add_or_update_cohort_spec(CohortSpec(name="a", parent="b"))
    snap = fw.scheduler._mirror.refresh()
    assert "cq-0" in snap.inactive_cluster_queues
    assert "cq-0" not in snap.cluster_queues

    # Usage-only churn on the deactivated CQ.
    fw.finish(wl)
    fw.delete_workload(wl)
    snap = fw.scheduler._mirror.refresh()
    rebuilt = Snapshot.build(fw.cache)
    assert "cq-0" not in snap.cluster_queues, \
        "cycle-deactivated CQ must not be re-inserted by usage churn"
    assert set(snap.cluster_queues) == set(rebuilt.cluster_queues)
    assert snap.inactive_cluster_queues == rebuilt.inactive_cluster_queues
