"""Importer tests (reference: cmd/importer/pod/{check,import}_test.go)."""

import json

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    ResourceGroup,
    Workload,
    PodSet,
    WorkloadPriorityClass,
)
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.importer import ImportPod, check, import_pods, main


def make_fw():
    fw = Framework()
    fw.create_resource_flavor(ResourceFlavor.make("default"))
    fw.create_cluster_queue(ClusterQueue(
        name="cq",
        resource_groups=(ResourceGroup(
            covered_resources=("cpu",),
            flavors=(FlavorQuotas.make("default", cpu=10),)),)))
    fw.create_local_queue(LocalQueue(
        name="lq", namespace="default", cluster_queue="cq"))
    fw.create_workload_priority_class(WorkloadPriorityClass("vip", 100))
    return fw


MAPPING = {"team-a": "lq"}
LABEL = "src.lbl"


class TestCheck:
    def test_ok(self):
        fw = make_fw()
        pods = [ImportPod("p1", labels={LABEL: "team-a"},
                          requests={"cpu": 1})]
        s = check(fw, pods, LABEL, MAPPING)
        assert s.ok() and s.skipped == 0

    def test_unmapped_pod_skipped(self):
        fw = make_fw()
        pods = [ImportPod("p1", labels={"other": "x"}, requests={"cpu": 1})]
        s = check(fw, pods, LABEL, MAPPING)
        assert s.ok() and s.skipped == 1

    def test_missing_local_queue_fails(self):
        fw = make_fw()
        pods = [ImportPod("p1", labels={LABEL: "team-a"})]
        s = check(fw, pods, LABEL, {"team-a": "nope"})
        assert not s.ok() and "LocalQueue" in s.errors[0]

    def test_unknown_priority_class_fails(self):
        fw = make_fw()
        pods = [ImportPod("p1", labels={LABEL: "team-a"},
                          priority_class="ghost")]
        s = check(fw, pods, LABEL, MAPPING)
        assert not s.ok() and "priority class" in s.errors[0]


class TestImport:
    def test_direct_admission_and_usage(self):
        fw = make_fw()
        pods = [ImportPod("p1", labels={LABEL: "team-a"},
                          requests={"cpu": 2}),
                ImportPod("p2", labels={LABEL: "team-a"},
                          requests={"cpu": 3}, priority_class="vip")]
        s = import_pods(fw, pods, LABEL, MAPPING,
                        add_labels={"managed": "yes"})
        assert s.imported == 2 and s.ok()
        # Workloads admitted without a scheduler tick; usage accounted.
        assert fw.cache.cluster_queues["cq"].usage["default"]["cpu"] == 5000
        wl = fw.workloads["default/pod-p2"]
        assert wl.is_admitted and wl.priority == 100
        assert pods[0].labels["managed"] == "yes"

    def test_imported_usage_visible_to_scheduler(self):
        fw = make_fw()
        import_pods(fw, [ImportPod("p1", labels={LABEL: "team-a"},
                                   requests={"cpu": 8})], LABEL, MAPPING)
        # Only 2 cpu left; a 4-cpu workload must stay pending.
        wl = Workload(name="late", queue_name="lq",
                      pod_sets=[PodSet.make("main", 1, cpu=4)])
        fw.submit(wl)
        fw.run_until_settled()
        assert not wl.has_quota_reservation


class TestCLI:
    def test_check_then_import(self, tmp_path):
        setup = {
            "resource_flavors": [{"name": "default"}],
            "cluster_queues": [{
                "name": "cq",
                "resource_groups": [{
                    "covered_resources": ["cpu"],
                    "flavors": [{"name": "default",
                                 "quotas": {"cpu": 10}}]}]}],
            "local_queues": [{"name": "lq", "cluster_queue": "cq"}],
        }
        pods = [{"name": "p1", "labels": {LABEL: "team-a"},
                 "requests": {"cpu": 1}}]
        sp = tmp_path / "setup.json"
        pp = tmp_path / "pods.json"
        sp.write_text(json.dumps(setup))
        pp.write_text(json.dumps(pods))
        rc = main(["check", "--setup", str(sp), "--pods", str(pp),
                   "--queuelabel", LABEL, "--queuemapping", "team-a=lq"])
        assert rc == 0
        rc = main(["import", "--setup", str(sp), "--pods", str(pp),
                   "--queuelabel", LABEL, "--queuemapping", "team-a=lq"])
        assert rc == 0
