"""Ingest-plane differential goldens.

The batch submit lane (decode_workload_batch -> Store.create_batch ->
Framework.submit_batch) must be decision-identical to the per-object
lane it replaces: same decoded objects, same published documents, same
admission trail, with KUEUE_TPU_NO_BATCH_INGEST=1 reverting to the
per-object twin byte for byte. Snapshot bootstrap (the O(live-state)
rejoin seam) must reproduce the line-replay rejoin and the
uninterrupted run exactly, including the torn-write fallback.
"""

import json

import pytest

from kueue_tpu.api import serialization
from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    Workload,
)
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.controllers.store import (
    KIND_CLUSTER_QUEUE,
    KIND_LOCAL_QUEUE,
    KIND_RESOURCE_FLAVOR,
    KIND_WORKLOAD,
    Store,
    StoreAdapter,
)


def _wl_doc(name, queue="lq-0", cpu="1", count=1, namespace="default"):
    return {
        "apiVersion": "kueue.x-k8s.io/v1beta1", "kind": "Workload",
        "metadata": {"name": name, "namespace": namespace,
                     "creationTimestamp": 100.0},
        "spec": {"queueName": queue, "podSets": [
            {"name": "main", "count": count,
             "template": {"spec": {"containers": [
                 {"name": "c",
                  "resources": {"requests": {"cpu": cpu}}}]}}}]},
    }


def _norm(wl):
    """Identity-free encoding: uid and creation_time are minted per
    decode (serialization._WORKLOAD_SPEC_FIELDS excludes them), so two
    decodes of one doc legitimately differ there and nowhere else."""
    doc = serialization.encode(KIND_WORKLOAD, wl)
    doc["metadata"].pop("uid", None)
    doc["metadata"].pop("creationTimestamp", None)
    return doc


class TestDecodeBatch:
    def test_batch_equals_per_doc(self):
        docs = (
            [_wl_doc(f"a-{i}") for i in range(6)]           # template run
            + [_wl_doc("big", cpu="3", count=2)]            # spec change
            + [_wl_doc(f"b-{i}", queue="lq-1") for i in range(4)]
        )
        batch = serialization.decode_workload_batch(docs)
        singles = [serialization.decode(d)[1] for d in docs]
        assert [_norm(w) for w in batch] == [_norm(w) for w in singles]
        assert [w.name for w in batch] == [w.name for w in singles]

    def test_status_docs_never_template(self):
        doc = _wl_doc("with-status")
        doc["status"] = {"conditions": [
            {"type": "QuotaReserved", "status": "True", "reason": "r",
             "message": "", "lastTransitionTime": 5.0}]}
        plain = _wl_doc("plain")
        batch = serialization.decode_workload_batch([doc, plain, doc | {
            "metadata": {"name": "with-status-2",
                         "namespace": "default"}}])
        assert batch[0].has_quota_reservation
        assert not batch[1].conditions
        assert batch[2].has_quota_reservation

    def test_generate_name_docs_mint_distinct_names(self):
        doc = _wl_doc("ignored")
        del doc["metadata"]["name"]
        doc["metadata"]["generateName"] = "gen-"
        batch = serialization.decode_workload_batch([doc, dict(doc)])
        assert len({w.name for w in batch}) == 2


def _stack():
    fw = Framework(clock=lambda: 1000.0)
    fw.create_namespace("default", labels={})
    store = Store()
    adapter = StoreAdapter(store, fw)
    store.create(KIND_RESOURCE_FLAVOR, ResourceFlavor.make("rf"))
    for i, cohort in enumerate(("pool-a", "pool-a", "pool-b")):
        store.create(KIND_CLUSTER_QUEUE, ClusterQueue(
            name=f"cq-{i}", cohort=cohort,
            resource_groups=(ResourceGroup(
                ("cpu",), (FlavorQuotas.make("rf", cpu=4),)),)))
        store.create(KIND_LOCAL_QUEUE, LocalQueue(
            name=f"lq-{i}", namespace="default",
            cluster_queue=f"cq-{i}"))
    return fw, store, adapter


def _drive(docs, ticks=4, batch=True):
    fw, store, adapter = _stack()
    trail = []
    orig = fw.scheduler.apply_admission

    def hook(wl):
        ok = orig(wl)
        if ok:
            trail.append(wl.key)
        return ok

    fw.scheduler.apply_admission = hook
    if batch:
        wls = serialization.decode_workload_batch(docs)
        store.create_batch(KIND_WORKLOAD, wls)
    else:
        for doc in docs:
            kind, obj = serialization.decode(doc)
            store.create(kind, obj)
    for _ in range(ticks):
        fw.tick()
    state = sorted(
        (key, json.dumps({**d, "metadata": {
            k: v for k, v in d["metadata"].items()
            if k not in ("uid", "creationTimestamp")}}, sort_keys=True))
        for key, d in ((w.key, store.encoded_get(KIND_WORKLOAD, w.key))
                       for w in store.list(KIND_WORKLOAD)))
    return trail, state


BURST = ([_wl_doc(f"w-{i}", queue=f"lq-{i % 3}") for i in range(18)]
         + [_wl_doc("fat", queue="lq-1", cpu="3")])


class TestBatchLaneGoldens:
    def test_batch_vs_per_object_decision_trail(self):
        batch_trail, batch_state = _drive(BURST, batch=True)
        po_trail, po_state = _drive(BURST, batch=False)
        assert batch_trail == po_trail
        assert batch_state == po_state
        assert batch_trail  # the golden admits something

    def test_kill_switch_twin_identical(self, monkeypatch):
        on_trail, on_state = _drive(BURST, batch=True)
        monkeypatch.setenv("KUEUE_TPU_NO_BATCH_INGEST", "1")
        off_trail, off_state = _drive(BURST, batch=True)
        assert on_trail == off_trail
        assert on_state == off_state

    def test_published_clone_doc_byte_identical(self):
        """create_batch publishes template-equal workloads through
        encode_workload_cloned; the published doc must be json-identical
        to a from-scratch encode of the same object."""
        fw, store, adapter = _stack()
        wls = serialization.decode_workload_batch(
            [_wl_doc(f"c-{i}") for i in range(8)])
        created = store.create_batch(KIND_WORKLOAD, wls)
        assert len(created) == 8
        for wl in created:
            assert json.dumps(store.encoded_get(KIND_WORKLOAD, wl.key),
                              sort_keys=True) == json.dumps(
                serialization.encode(KIND_WORKLOAD, wl), sort_keys=True)

    def test_batch_validation_still_rejects(self):
        fw, store, adapter = _stack()
        from kueue_tpu import webhooks

        bad = _wl_doc("bad", count=0)
        wls = serialization.decode_workload_batch(
            [_wl_doc("ok-0"), bad, _wl_doc("ok-1")])
        with pytest.raises(webhooks.ValidationError):
            store.create_batch(KIND_WORKLOAD, wls)
        # Per-object error semantics: the prefix stays created.
        assert [w.name for w in store.list(KIND_WORKLOAD)] == ["ok-0"]

    def test_batch_dirty_marks_once_per_cohort(self, monkeypatch):
        fw, store, adapter = _stack()
        reasons = []
        orig = fw.queues._mark_dirty

        def spy(cq, reason):
            reasons.append(reason)
            return orig(cq, reason)

        monkeypatch.setattr(fw.queues, "_mark_dirty", spy)
        wls = serialization.decode_workload_batch(
            [_wl_doc(f"d-{i}", queue=f"lq-{i % 3}") for i in range(30)])
        store.create_batch(KIND_WORKLOAD, wls)
        # 30 workloads across cohorts {pool-a, pool-b}: one mark each,
        # not one per workload.
        assert len(reasons) == 2
        assert all(r.startswith("submit-batch") for r in reasons)


class TestWorkloadListEndpoint:
    @pytest.fixture()
    def served(self):
        from kueue_tpu.server import APIServer

        fw, store, adapter = _stack()
        server = APIServer(store, fw,
                           sync_status=adapter.sync_status).start()
        try:
            yield server, fw, store
        finally:
            server.stop()

    def _post_list(self, server, docs):
        import urllib.request

        req = urllib.request.Request(
            server.url + "/apis/kueue.x-k8s.io/v1beta1/namespaces/"
                         "default/workloads",
            data=json.dumps({"apiVersion": "kueue.x-k8s.io/v1beta1",
                             "kind": "WorkloadList",
                             "items": docs}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, json.loads(resp.read())

    def test_batch_post_creates_all(self, served):
        server, fw, store = served
        status, body = self._post_list(
            server, [_wl_doc(f"e-{i}") for i in range(5)])
        assert status == 201
        assert [it["metadata"]["name"] for it in body["items"]] \
            == [f"e-{i}" for i in range(5)]
        assert len(store.list(KIND_WORKLOAD)) == 5

    def test_batch_post_kill_switch_equivalent(self, served,
                                               monkeypatch):
        server, fw, store = served
        monkeypatch.setenv("KUEUE_TPU_NO_BATCH_INGEST", "1")
        status, body = self._post_list(
            server, [_wl_doc(f"f-{i}") for i in range(4)])
        assert status == 201
        assert len(body["items"]) == 4
        assert len(store.list(KIND_WORKLOAD)) == 4


# -- snapshot bootstrap goldens ----------------------------------------------


def _drill(tmp_path, kill=True):
    """A per-host replica run with churned journal history: submitted +
    finished + deleted workloads leave lines behind while the live set
    stays small, then (optionally) a worker dies and the survivor
    adopts its groups. Returns (final_admitted, bootstrap_evidence)."""
    from kueue_tpu.controllers.replica_runtime import ReplicaRuntime

    rt = ReplicaRuntime(2, spawn=False, engine="host", transport="pipe",
                        per_host=True, state_dir=str(tmp_path))
    try:
        rt.create_resource_flavor(ResourceFlavor.make("rf"))
        for i in range(4):
            rt.create_cluster_queue(ClusterQueue(
                name=f"rj-cq-{i}", resource_groups=(ResourceGroup(
                    ("cpu",), (FlavorQuotas.make("rf", cpu=8),)),)))
            rt.create_local_queue(LocalQueue(
                name=f"rj-lq-{i}", namespace="default",
                cluster_queue=f"rj-cq-{i}"))
        for r in range(3):
            pairs = []
            for i in range(r * 24, (r + 1) * 24):
                rt.submit(Workload(
                    name=f"churn-{i}", namespace="default",
                    queue_name=f"rj-lq-{i % 4}", creation_time=float(i),
                    pod_sets=[PodSet.make("ps0", count=1, cpu=1)]))
                pairs.append((f"default/churn-{i}", f"rj-cq-{i % 4}"))
            rt.tick()
            rt.finish_many(pairs)
            rt.tick()
        for i in range(8):  # the live residue the snapshot must carry
            rt.submit(Workload(
                name=f"live-{i}", namespace="default",
                queue_name=f"rj-lq-{i % 4}",
                creation_time=float(1000 + i),
                pod_sets=[PodSet.make("ps0", count=1, cpu=1)]))
        rt.tick()
        if kill:
            victim = rt.group_owner[min(rt.group_owner)]
            rt.kill_replica(victim)
            rt.tick()  # reassignment adopts via the bootstrap seed
        rt.tick()
        dump = rt.dump()
        final = {cq: sorted(keys)
                 for cq, keys in (dump.get("admitted") or {}).items()}
        boot = (dict(rt.bootstrap_evidence)
                if rt.bootstrap_evidence is not None else None)
        return final, boot
    finally:
        rt.close()


class TestSnapshotBootstrap:
    def test_snapshot_equals_line_replay_and_uninterrupted(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("KUEUE_TPU_SNAPSHOT_BOOT_FLOOR", "1")
        snap_final, snap_boot = _drill(tmp_path / "snap")
        assert snap_boot is not None and snap_boot["snapshot"] is True
        assert 0 < snap_boot["lines"] < snap_boot["history_lines"]

        monkeypatch.setenv("KUEUE_TPU_NO_SNAPSHOT_BOOT", "1")
        replay_final, replay_boot = _drill(tmp_path / "replay")
        monkeypatch.delenv("KUEUE_TPU_NO_SNAPSHOT_BOOT")
        assert replay_boot is None  # kill switch: raw line replay

        clean_final, _ = _drill(tmp_path / "clean", kill=False)

        assert snap_final == replay_final == clean_final
        assert any(snap_final.values())  # the golden admits something

    def test_torn_snapshot_falls_back_to_line_replay(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("KUEUE_TPU_SNAPSHOT_BOOT_FLOOR", "1")
        monkeypatch.setenv("KUEUE_TPU_SNAPSHOT_BOOT_FAULTS",
                           "torn_p=1.0,seed=11")
        torn_final, torn_boot = _drill(tmp_path / "torn")
        assert torn_boot is not None
        assert torn_boot.get("torn_fallback") is True
        assert torn_boot["snapshot"] is False

        monkeypatch.delenv("KUEUE_TPU_SNAPSHOT_BOOT_FAULTS")
        clean_final, _ = _drill(tmp_path / "clean", kill=False)
        # Zero records lost: the fallback line replay lands the same
        # final admitted state as the uninterrupted run.
        assert torn_final == clean_final
