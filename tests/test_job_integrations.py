"""Named job-integration tests: kubeflow family, MPIJob, Ray, noop.

Mirrors the per-framework controller tests in reference
pkg/controller/jobs/{kubeflow,mpijob,rayjob,raycluster}/ at the
behavioral level: podset construction order, atomic admission,
priority-class resolution, suspend/resume.
"""

import pytest

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    ResourceGroup,
    WorkloadPriorityClass,
)
from kueue_tpu.controllers.jobframework import integrations
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.jobs import (
    MPIJob,
    MXJob,
    NoopJob,
    PyTorchJob,
    RayCluster,
    RayJob,
    ReplicaSpec,
    TFJob,
    WorkerGroup,
)


def make_fw(cpu=16):
    fw = Framework()
    fw.create_resource_flavor(ResourceFlavor.make("default"))
    fw.create_cluster_queue(ClusterQueue(
        name="cq",
        resource_groups=(ResourceGroup(
            covered_resources=("cpu",),
            flavors=(FlavorQuotas.make("default", cpu=cpu),)),)))
    fw.create_local_queue(LocalQueue(
        name="lq", namespace="default", cluster_queue="cq"))
    return fw


class TestRegistry:
    def test_all_reference_integrations_registered(self):
        kinds = set(integrations())
        # The reference's integration list (integrationmanager, jobs/*).
        for kind in ("batch", "jobset", "podgroup", "mpijob", "rayjob",
                     "raycluster", "noop", "kubeflow.pytorchjob",
                     "kubeflow.tfjob", "kubeflow.paddlejob",
                     "kubeflow.xgboostjob", "kubeflow.mxjob"):
            assert kind in kinds, kind


class TestKubeflow:
    def test_pytorch_podsets_in_master_worker_order(self):
        job = PyTorchJob(
            name="pt", queue_name="lq",
            replica_specs={"Worker": ReplicaSpec(4, {"cpu": 1}),
                           "Master": ReplicaSpec(1, {"cpu": 1})})
        assert [ps.name for ps in job.pod_sets()] == ["master", "worker"]

    def test_tfjob_replica_order(self):
        job = TFJob(
            name="tf", queue_name="lq",
            replica_specs={"Worker": ReplicaSpec(2, {"cpu": 1}),
                           "PS": ReplicaSpec(1, {"cpu": 1}),
                           "Chief": ReplicaSpec(1, {"cpu": 1})})
        assert [ps.name for ps in job.pod_sets()] == ["chief", "ps", "worker"]

    def test_unknown_replica_type_rejected(self):
        with pytest.raises(ValueError):
            PyTorchJob(name="bad", queue_name="lq",
                       replica_specs={"Chief": ReplicaSpec(1, {"cpu": 1})})

    def test_mxjob_mode_switches_order(self):
        train = MXJob(name="mx", queue_name="lq",
                      replica_specs={"Worker": ReplicaSpec(2, {"cpu": 1}),
                                     "Scheduler": ReplicaSpec(1, {"cpu": 1})})
        assert [ps.name for ps in train.pod_sets()] == ["scheduler", "worker"]
        tune = MXJob(name="mxt", queue_name="lq", job_mode="MXTune",
                     replica_specs={"Tuner": ReplicaSpec(1, {"cpu": 1})})
        assert [ps.name for ps in tune.pod_sets()] == ["tuner"]

    def test_priority_class_resolution(self):
        # schedulingPolicy wins over replica templates
        # (kubeflowjob_controller.go:146-165).
        job = PyTorchJob(
            name="pt", queue_name="lq",
            scheduling_priority_class="high",
            replica_specs={"Master": ReplicaSpec(1, {"cpu": 1},
                                                 priority_class="low")})
        assert job.priority_class() == "high"
        job2 = PyTorchJob(
            name="pt2", queue_name="lq",
            replica_specs={
                "Master": ReplicaSpec(1, {"cpu": 1}, priority_class="mid"),
                "Worker": ReplicaSpec(2, {"cpu": 1}, priority_class="low")})
        assert job2.priority_class() == "mid"

    def test_workload_priority_class_applied_end_to_end(self):
        fw = make_fw()
        fw.create_workload_priority_class(
            WorkloadPriorityClass(name="vip", value=1000))
        job = PyTorchJob(
            name="pt", queue_name="lq", scheduling_priority_class="vip",
            replica_specs={"Master": ReplicaSpec(1, {"cpu": 1})})
        wl = fw.submit_job(job)
        assert wl.priority == 1000

    def test_atomic_admission_and_run(self):
        fw = make_fw(cpu=8)
        started = []
        job = PyTorchJob(
            name="pt", queue_name="lq",
            replica_specs={"Master": ReplicaSpec(1, {"cpu": 2}),
                           "Worker": ReplicaSpec(3, {"cpu": 2})},
            on_run=lambda j: started.append(j.name))
        fw.submit_job(job)
        fw.run_until_settled()
        assert started == ["pt"]
        assert not job.is_suspended()
        # 1*2 + 3*2 = 8 cpu all accounted
        assert fw.cache.cluster_queues["cq"].usage["default"]["cpu"] == 8000

    def test_too_big_not_admitted(self):
        fw = make_fw(cpu=4)
        job = PyTorchJob(
            name="pt", queue_name="lq",
            replica_specs={"Master": ReplicaSpec(1, {"cpu": 2}),
                           "Worker": ReplicaSpec(3, {"cpu": 2})})
        fw.submit_job(job)
        fw.run_until_settled()
        assert job.is_suspended()


class TestMPIJob:
    def test_simple_shape(self):
        job = MPIJob.simple("mpi", "lq", workers=8,
                            worker_requests={"cpu": 2})
        names = [(ps.name, ps.count) for ps in job.pod_sets()]
        assert names == [("launcher", 1), ("worker", 8)]

    def test_runs_and_finishes(self):
        fw = make_fw(cpu=32)
        job = MPIJob.simple("mpi", "lq", workers=8, worker_requests={"cpu": 2})
        wl = fw.submit_job(job)
        fw.run_until_settled()
        assert wl.has_quota_reservation and not job.is_suspended()
        job.succeeded = True
        fw.tick()
        assert wl.is_finished
        assert fw.cache.cluster_queues["cq"].usage["default"]["cpu"] == 0


class TestRay:
    def test_raycluster_podsets(self):
        rc = RayCluster(
            name="rc", queue_name="lq", head_requests={"cpu": 1},
            worker_groups=[WorkerGroup("GPU-Group", 4, {"cpu": 2}),
                           WorkerGroup("small", 2, {"cpu": 1})])
        names = [(ps.name, ps.count) for ps in rc.pod_sets()]
        assert names == [("head", 1), ("gpu-group", 4), ("small", 2)]

    def test_rayjob_lifecycle(self):
        fw = make_fw(cpu=16)
        rj = RayJob(name="rj", queue_name="lq", head_requests={"cpu": 1},
                    worker_groups=[WorkerGroup("w", 4, {"cpu": 2})])
        wl = fw.submit_job(rj)
        fw.run_until_settled()
        assert not rj.is_suspended()
        rj.head_ready = True
        for wg in rj.worker_groups:
            wg.ready = wg.replicas
        assert rj.pods_ready()
        rj.succeeded = True
        fw.tick()
        assert wl.is_finished

    def test_raycluster_released_on_delete(self):
        fw = make_fw(cpu=16)
        rc = RayCluster(name="rc", queue_name="lq", head_requests={"cpu": 1},
                        worker_groups=[WorkerGroup("w", 2, {"cpu": 2})])
        fw.submit_job(rc)
        fw.run_until_settled()
        assert fw.cache.cluster_queues["cq"].usage["default"]["cpu"] == 5000
        fw.job_reconciler.delete(rc)
        assert fw.cache.cluster_queues["cq"].usage["default"]["cpu"] == 0


class TestNoop:
    def test_contributes_nothing(self):
        job = NoopJob(name="managed-pod")
        assert job.pod_sets() == []
        assert job.finished() == (False, False)


class TestTaintsTolerationsPod:
    """The experimental out-of-tree integration sample
    (cmd/experimental/podtaintstolerations)."""

    def test_suspension_encoded_in_tolerations(self):
        from kueue_tpu.jobs import TaintsTolerationsPod
        from kueue_tpu.jobs.taints_job import ADMISSION_TAINT_KEY
        pod = TaintsTolerationsPod(name="p", queue_name="lq",
                                   requests={"cpu": 1})
        assert pod.is_suspended()
        fw = make_fw()
        wl = fw.submit_job(pod)
        fw.run_until_settled()
        assert not pod.is_suspended()
        assert any(t.key == ADMISSION_TAINT_KEY and t.operator == "Exists"
                   for t in pod.tolerations)
        assert wl.is_admitted

    def test_flavor_labels_become_tolerations(self):
        from kueue_tpu.api.types import ResourceFlavor as RF
        from kueue_tpu.jobs import TaintsTolerationsPod
        fw = Framework()
        fw.create_resource_flavor(RF.make("spot", node_labels={"tier": "spot"}))
        fw.create_cluster_queue(ClusterQueue(
            name="cq", resource_groups=(ResourceGroup(
                ("cpu",), (FlavorQuotas.make("spot", cpu=4),)),)))
        fw.create_local_queue(LocalQueue(
            name="lq", namespace="default", cluster_queue="cq"))
        pod = TaintsTolerationsPod(name="p", queue_name="lq",
                                   requests={"cpu": 1})
        fw.submit_job(pod)
        fw.run_until_settled()
        assert any(t.key == "tier" and t.value == "spot" and
                   t.operator == "Equal" for t in pod.tolerations)

    def test_stop_strips_injected_tolerations(self):
        from kueue_tpu.jobs import TaintsTolerationsPod
        fw = make_fw(cpu=2)
        pod = TaintsTolerationsPod(name="low", queue_name="lq",
                                   requests={"cpu": 2})
        fw.submit_job(pod)
        fw.run_until_settled()
        assert not pod.is_suspended()
        wl = fw.workloads["default/job-low"]
        fw._apply_preemption(wl, "test eviction")
        fw.tick()
        assert pod.is_suspended()
        assert pod.deleted  # the reference deletes the pod on stop
