"""Jobframework + integrations: job <-> workload lifecycle (scenarios
modeled on the reference's jobframework reconciler and per-integration
tests)."""

from kueue_tpu.api.types import ClusterQueuePreemption, ResourceFlavor
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.jobs import (
    BatchJob,
    GroupedPod,
    JobSet,
    MultiRoleJob,
    PodGroup,
    ReplicatedJob,
    Role,
)

from tests.util import fq, make_cq, make_flavor, make_lq, rg


def job_framework(quota_cpu=8, **cq_kwargs):
    fw = Framework()
    fw.create_resource_flavor(ResourceFlavor.make(
        "default", node_labels={"pool": "tpu-v5e"}))
    fw.create_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=quota_cpu)), **cq_kwargs))
    fw.create_local_queue(make_lq("main", cq="cq"))
    return fw


def test_batch_job_lifecycle():
    fw = job_framework()
    launched = []
    job = BatchJob("train", "main", parallelism=4, requests={"cpu": 1},
                   on_run=lambda j: launched.append(j.name))
    wl = fw.submit_job(job)
    assert job.is_suspended()
    fw.run_until_settled()
    # Admitted: job started with flavor node selectors injected.
    assert not job.is_suspended()
    assert launched == ["train"]
    assert job.podset_info.node_selector == {"pool": "tpu-v5e"}
    assert wl.is_admitted
    # Finish the job: quota released.
    job.succeeded = 4
    fw.tick()
    assert wl.is_finished
    assert fw.cache.usage("cq")["default"]["cpu"] == 0


def test_batch_job_partial_admission():
    fw = job_framework(quota_cpu=4)
    job = BatchJob("wide", "main", parallelism=8, min_parallelism=2,
                   requests={"cpu": 1})
    fw.submit_job(job)
    fw.run_until_settled()
    assert not job.is_suspended()
    assert job.parallelism == 4  # shrunk to the available quota
    # Stopping restores the original parallelism.
    job.failed = True
    fw.tick()
    assert job.finished()[0]


def test_batch_job_preemption_stops_job():
    fw = job_framework(
        quota_cpu=4,
        preemption=ClusterQueuePreemption(within_cluster_queue="LowerPriority"))
    low = BatchJob("low", "main", parallelism=4, requests={"cpu": 1}, priority=-1)
    fw.submit_job(low)
    fw.run_until_settled()
    assert not low.is_suspended()
    high = BatchJob("high", "main", parallelism=4, requests={"cpu": 1}, priority=5)
    fw.submit_job(high)
    fw.run_until_settled()
    # Low got preempted and suspended; high is running.
    assert low.is_suspended()
    assert low.parallelism == low.original_parallelism
    assert not high.is_suspended()


def test_multi_role_job_atomic_admission():
    fw = job_framework(quota_cpu=8)
    job = MultiRoleJob("mpi", "main", roles=[
        Role("launcher", count=1, requests={"cpu": 1}),
        Role("worker", count=6, requests={"cpu": 1}),
    ])
    wl = fw.submit_job(job)
    fw.run_until_settled()
    assert not job.is_suspended()
    assert [ps.name for ps in wl.pod_sets] == ["launcher", "worker"]
    assert {i.name: i.count for i in job.podset_infos} == \
        {"launcher": 1, "worker": 6}

    # A second job needing 8 can't fit atomically (1 cpu free).
    job2 = MultiRoleJob("mpi2", "main", roles=[
        Role("launcher", count=1, requests={"cpu": 1}),
        Role("worker", count=7, requests={"cpu": 1}),
    ])
    fw.submit_job(job2)
    fw.run_until_settled()
    assert job2.is_suspended()


def test_jobset_integration():
    fw = job_framework(quota_cpu=8)
    js = JobSet("set", "main", replicated_jobs=[
        ReplicatedJob("driver", replicas=1, parallelism=1, requests={"cpu": 1}),
        ReplicatedJob("workers", replicas=2, parallelism=3, requests={"cpu": 1}),
    ])
    wl = fw.submit_job(js)
    fw.run_until_settled()
    assert not js.is_suspended()
    assert {ps.name: ps.count for ps in wl.pod_sets} == \
        {"driver": 1, "workers": 6}
    js.succeeded = True
    fw.tick()
    assert wl.is_finished


def test_pod_group_gating():
    fw = job_framework(quota_cpu=4)
    pods = [GroupedPod(f"p{i}", requests={"cpu": 1}, group="g") for i in range(3)]
    group = PodGroup("g", "main", pods=pods, total_count=3)
    wl = fw.submit_job(group)
    assert all(p.gated for p in pods)
    fw.run_until_settled()
    # Admitted atomically: all pods ungated with placement injected.
    assert all(not p.gated and p.running for p in pods)
    assert all(p.node_selector == {"pool": "tpu-v5e"} for p in pods)
    assert wl.is_admitted
    # All pods finish -> workload finished.
    for p in pods:
        p.finished = True
        p.running = False
    fw.tick()
    assert wl.is_finished
    assert fw.cache.usage("cq")["default"]["cpu"] == 0


def test_pod_group_heterogeneous_roles():
    fw = job_framework(quota_cpu=8)
    pods = ([GroupedPod(f"w{i}", requests={"cpu": 1}, group="g") for i in range(4)]
            + [GroupedPod("head", requests={"cpu": 2}, group="g")])
    group = PodGroup("g", "main", pods=pods, total_count=5)
    wl = fw.submit_job(group)
    fw.run_until_settled()
    # Two role PodSets: 4x1cpu + 1x2cpu.
    counts = sorted(ps.count for ps in wl.pod_sets)
    assert counts == [1, 4]
    assert all(not p.gated for p in pods)


def test_reclaimable_pods_release_quota():
    fw = job_framework(quota_cpu=4)
    job = BatchJob("j", "main", parallelism=4, completions=4, requests={"cpu": 1})
    fw.submit_job(job)
    fw.run_until_settled()
    assert fw.cache.usage("cq")["default"]["cpu"] == 4000
    # Two pods complete: their quota is reclaimed before the job finishes.
    job.succeeded = 2
    fw.tick()
    assert fw.cache.usage("cq")["default"]["cpu"] == 2000
    # The freed quota admits another job.
    job2 = BatchJob("j2", "main", parallelism=2, requests={"cpu": 1})
    fw.submit_job(job2)
    fw.run_until_settled()
    assert not job2.is_suspended()
