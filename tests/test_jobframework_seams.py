"""GenericJob optional capability seams, exercised through
JobReconciler.reconcile (reference: jobframework/interface.go:56-114 —
JobWithSkip, JobWithCustomStop, JobWithFinalize, ComposableJob, prebuilt
workloads — and reconciler.go:478-579 ensureOneWorkload dedup /
finish-stale / job<->workload equivalence)."""

from typing import Dict, List, Optional, Sequence, Tuple

from kueue_tpu.api.types import PodSet, ResourceFlavor, Workload
from kueue_tpu.controllers.jobframework import (
    ComposableJob,
    GenericJob,
    JobWithCustomStop,
    JobWithFinalize,
    JobWithSkip,
    StopReason,
    equivalent_to_workload,
)
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.jobs.pod_group import GroupedPod, PodGroup

from tests.util import fq, make_cq, make_lq, rg


def make_fw(cpu=8):
    fw = Framework()
    fw.create_resource_flavor(ResourceFlavor.make("default"))
    fw.create_cluster_queue(make_cq("cq", rg("cpu", fq("default", cpu=cpu))))
    fw.create_local_queue(make_lq("main", cq="cq"))
    return fw


class FakeJob(GenericJob):
    """Minimal concrete job with togglable state."""

    def __init__(self, name="j", queue="main", cpu=2, count=1):
        self._name = name
        self._queue = queue
        self._suspended = True
        self._pod_sets = [PodSet.make("main", count=count, cpu=cpu)]
        self.done = False
        self.success = True
        self.run_calls: List[Sequence] = []
        self.restore_calls: List[Sequence] = []

    @property
    def name(self):
        return self._name

    @property
    def queue_name(self):
        return self._queue

    def is_suspended(self):
        return self._suspended

    def suspend(self):
        self._suspended = True

    def run(self, infos):
        self._suspended = False
        self.run_calls.append(infos)

    def restore(self, infos):
        self.restore_calls.append(infos)

    def pod_sets(self):
        return list(self._pod_sets)

    def finished(self):
        return self.done, self.success


class SkippingJob(FakeJob, JobWithSkip):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.skipping = True

    def skip(self):
        return self.skipping


class CustomStopJob(FakeJob, JobWithCustomStop):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.stop_calls: List[Tuple[StopReason, str]] = []

    def stop(self, infos, stop_reason, event_msg):
        was = not self._suspended
        self._suspended = True
        self.stop_calls.append((stop_reason, event_msg))
        return was


class FinalizingJob(FakeJob, JobWithFinalize):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.finalized = 0

    def finalize(self):
        self.finalized += 1


class TestSkip:
    def test_skipped_job_gets_no_workload(self):
        fw = make_fw()
        job = SkippingJob()
        wl = fw.submit_job(job)
        assert wl is None
        assert fw.workloads == {}
        # Un-skip: the next reconcile pass creates the workload.
        job.skipping = False
        fw.job_reconciler.reconcile()
        assert "default/job-j" in fw.workloads


class TestCustomStop:
    def test_eviction_routes_through_custom_stop(self):
        fw = make_fw(cpu=4)
        job = CustomStopJob(cpu=4)
        wl = fw.submit_job(job)
        fw.run_until_settled()
        assert not job.is_suspended()
        # Evict (deactivation path) — the stop must use the seam.
        fw.evict_workload(wl, reason="Test", message="evicted for test")
        fw.tick()
        assert job.stop_calls
        reason, msg = job.stop_calls[0]
        assert reason == StopReason.WORKLOAD_EVICTED
        assert "evicted" in msg
        assert job.is_suspended()
        # Default restore() was NOT used.
        assert job.restore_calls == []

    def test_no_matching_workload_stop_reason(self):
        fw = make_fw()
        job = CustomStopJob()
        wl = fw.submit_job(job)
        fw.run_until_settled()
        assert not job.is_suspended()
        # The job changes shape while running: its workload no longer
        # matches -> stopped with NO_MATCHING_WORKLOAD and the stale
        # workload deleted.
        job._pod_sets = [PodSet.make("main", count=2, cpu=1)]
        fw.job_reconciler.reconcile()
        assert job.stop_calls[-1][0] == StopReason.NO_MATCHING_WORKLOAD
        # The stale workload object is gone (quota released) and a fresh
        # matching one was constructed.
        recreated = fw.workloads.get("default/job-j")
        assert recreated is not None and recreated is not wl
        assert recreated.admission is None
        assert equivalent_to_workload(job, recreated)


class TestFinalize:
    def test_finalize_called_once_after_finish(self):
        fw = make_fw()
        job = FinalizingJob()
        fw.submit_job(job)
        fw.run_until_settled()
        job.done = True
        fw.tick()
        assert job.finalized == 1
        fw.tick()
        fw.tick()
        assert job.finalized == 1


class TestEnsureOneWorkload:
    def test_duplicate_workloads_deduped(self):
        fw = make_fw()
        job = FakeJob()
        wl = fw.submit_job(job)
        # A duplicate enters (e.g. two replicas raced); adopt it.
        dup = Workload(name="job-j-dup", queue_name="main",
                       pod_sets=[PodSet.make("main", count=1, cpu=2)])
        fw.submit(dup)
        fw.job_reconciler.adopt_workload(job, dup)
        fw.job_reconciler.reconcile()
        # The matching one survives; the duplicate is deleted.
        assert wl.key in fw.workloads
        assert dup.key not in fw.workloads

    def test_stale_suspended_workload_updated_in_place(self):
        fw = make_fw(cpu=1)   # nothing fits: stays pending/suspended
        job = FakeJob(cpu=2)
        wl = fw.submit_job(job)
        fw.run_until_settled()
        assert not wl.has_quota_reservation
        # The suspended job's shape changes: the unreserved workload is
        # updated in place, not recreated (reconciler.go:517-521).
        job._pod_sets = [PodSet.make("main", count=3, cpu=1)]
        fw.job_reconciler.reconcile()
        assert wl.key in fw.workloads
        assert [ps.count for ps in wl.pod_sets] == [3]
        assert equivalent_to_workload(job, wl)

    def test_equivalence_tolerates_partial_admission_counts(self):
        fw = make_fw(cpu=2)
        job = FakeJob(cpu=1, count=4)
        job._pod_sets = [PodSet.make("main", count=4, min_count=1, cpu=1)]
        wl = fw.submit_job(job)
        fw.run_until_settled()
        assert wl.is_admitted
        admitted_count = wl.admission.pod_set_assignments[0].count
        assert admitted_count == 2
        # The job now reports the reduced count (partial admission rewrote
        # its parallelism); still equivalent to the workload.
        job._pod_sets = [PodSet.make("main", count=admitted_count, cpu=1)]
        assert equivalent_to_workload(job, wl)
        fw.job_reconciler.reconcile()
        assert wl.key in fw.workloads


class TestPrebuilt:
    def test_binds_to_prebuilt_workload(self):
        fw = make_fw()

        class PrebuiltJob(FakeJob):
            def prebuilt_workload(self):
                return "pre"

        pre = Workload(name="pre", queue_name="main",
                       pod_sets=[PodSet.make("main", count=1, cpu=2)])
        fw.submit(pre)
        job = PrebuiltJob()
        fw.submit_job(job)
        fw.run_until_settled()
        assert pre.is_admitted
        assert not job.is_suspended()
        # No second workload was constructed.
        assert list(fw.workloads) == [pre.key]

    def test_out_of_sync_prebuilt_is_finished(self):
        fw = make_fw()

        class PrebuiltJob(FakeJob):
            def prebuilt_workload(self):
                return "pre"

        pre = Workload(name="pre", queue_name="main",
                       pod_sets=[PodSet.make("main", count=9, cpu=1)])
        fw.submit(pre)
        job = PrebuiltJob()   # wants count=1 cpu=2: out of sync
        fw.submit_job(job)
        assert pre.is_finished
        cond = pre.find_condition("Finished")
        assert cond.reason == "OutOfSync"


class TestComposable:
    def test_incomplete_group_defers_workload(self):
        fw = make_fw()
        group = PodGroup("g", "main",
                         [GroupedPod("p0", {"cpu": 1}, group="g")],
                         total_count=2)
        wl = fw.submit_job(group)
        assert wl is None
        assert fw.workloads == {}
        # The missing member arrives: the next pass constructs the group
        # workload atomically.
        group.add_pod(GroupedPod("p1", {"cpu": 1}, group="g"))
        fw.job_reconciler.reconcile()
        fw.run_until_settled()
        [(key, wl)] = list(fw.workloads.items())
        assert wl.is_admitted
        assert sum(ps.count for ps in wl.pod_sets) == 2


class TestPerJobWebhooks:
    """Per-job webhook validation breadth (jobframework/validation.go +
    per-framework *_webhook.go): create-time name rules, update-time
    immutability, and per-framework invariants, enforced through the
    reconcile pass (the denied-apiserver-write analog)."""

    def test_create_rejects_invalid_queue_name(self):
        from kueue_tpu.webhooks import ValidationError
        fw = make_fw()
        job = FakeJob(queue="Not_A_Valid_Name!")
        try:
            fw.submit_job(job)
            assert False, "expected ValidationError"
        except ValidationError as e:
            assert "queue-name" in str(e)

    def test_queue_change_while_running_rejected(self):
        fw = make_fw()
        fw.create_local_queue(__import__(
            "tests.util", fromlist=["make_lq"]).make_lq("other", cq="cq"))
        job = FakeJob()
        wl = fw.submit_job(job)
        fw.run_until_settled()
        assert not job.is_suspended()
        # Mutate the queue while running: the webhook analog rejects it.
        job._queue = "other"
        fw.job_reconciler.reconcile()
        assert wl.queue_name == "main"
        rejected = fw.events.for_object("default/j", reason="UpdateRejected")
        assert rejected and "queue-name" in rejected[-1].message
        # Reverting the mutation clears the rejection.
        job._queue = "main"
        before = len(fw.events.for_object("default/j",
                                          reason="UpdateRejected"))
        fw.job_reconciler.reconcile()
        assert len(fw.events.for_object(
            "default/j", reason="UpdateRejected")) == before

    def test_priority_class_immutable(self):
        fw = make_fw()

        class PCJob(FakeJob):
            pc = ""

            def priority_class(self):
                return self.pc

        job = PCJob()
        fw.submit_job(job)
        fw.run_until_settled()
        job.pc = "high"
        fw.job_reconciler.reconcile()
        assert fw.events.for_object("default/j", reason="UpdateRejected")

    def test_batch_job_parallelism_frozen_under_partial_admission(self):
        from kueue_tpu.jobs.batch_job import BatchJob
        fw = make_fw(cpu=2)
        job = BatchJob("bj", "main", parallelism=4, min_parallelism=1,
                       requests={"cpu": 1})
        wl = fw.submit_job(job)
        fw.run_until_settled()
        assert not job.is_suspended()
        assert job.parallelism == 2  # partially admitted
        job.parallelism = 4          # forbidden while running
        fw.job_reconciler.reconcile()
        assert fw.events.for_object("default/bj", reason="UpdateRejected")

    def test_rejected_update_does_not_wedge_eviction(self):
        """A persistent invalid mutation must not block the quota-safety
        path: an evicted workload's job still stops."""
        fw = make_fw(cpu=4)
        job = FakeJob(cpu=4)

        class PC(type(job)):
            pass

        wl = fw.submit_job(job)
        fw.run_until_settled()
        assert not job.is_suspended()
        # Invalid mutation: queue change while running.
        fw.create_local_queue(__import__(
            "tests.util", fromlist=["make_lq"]).make_lq("other2", cq="cq"))
        job._queue = "other2"
        fw.job_reconciler.reconcile()
        assert fw.events.for_object("default/j", reason="UpdateRejected")
        # The workload is evicted while the rejection persists: the job
        # must still be stopped.
        fw.evict_workload(wl, reason="Test", message="evicted")
        fw.tick()
        assert job.is_suspended()
