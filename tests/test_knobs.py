"""The knob-contract registry: accessor semantics, registry hygiene,
and the generated README table (the CI drift gate).

KNOB01 (tests/test_kueuelint.py) proves every env read goes THROUGH the
registry; this file proves the registry itself is sound and that the
documented table is byte-identical to what the registry generates.
"""

from __future__ import annotations

import re
from pathlib import Path

import pytest

from kueue_tpu import knobs

README = Path(__file__).resolve().parent.parent / "README.md"


def test_registry_names_are_unique_and_prefixed():
    names = [k.name for k in knobs.REGISTRY]
    assert len(names) == len(set(names))
    assert all(n.startswith("KUEUE_TPU_") for n in names)


def test_registry_kinds_and_read_disciplines_are_closed():
    for k in knobs.REGISTRY:
        assert k.kind in (knobs.KILL_SWITCH, knobs.DEBUG, knobs.TUNING)
        assert k.read in (knobs.LIVE, knobs.STARTUP)
        assert k.doc  # every knob is documented; the README table needs it


def test_every_kill_switch_reads_as_a_flag_or_documented_opt_out():
    """NO_* kill switches are opt-in `=1` flags; the only non-NO_* kill
    switch is the documented NATIVE_HEAP opt-out (default "1", off at
    "0")."""
    for k in knobs.REGISTRY:
        if k.kind != knobs.KILL_SWITCH:
            continue
        if "KUEUE_TPU_NO_" in k.name:
            assert k.default == ""
        else:
            assert k.name == "KUEUE_TPU_NATIVE_HEAP"
            assert k.default == "1"


def test_flag_and_raw_semantics(monkeypatch):
    monkeypatch.delenv("KUEUE_TPU_NO_ARENA", raising=False)
    assert knobs.flag("KUEUE_TPU_NO_ARENA") is False
    assert knobs.raw("KUEUE_TPU_NO_ARENA") == ""
    monkeypatch.setenv("KUEUE_TPU_NO_ARENA", "1")
    assert knobs.flag("KUEUE_TPU_NO_ARENA") is True
    # Anything but "1" is off — same as the historical `== "1"` sites.
    monkeypatch.setenv("KUEUE_TPU_NO_ARENA", "true")
    assert knobs.flag("KUEUE_TPU_NO_ARENA") is False


def test_raw_returns_registered_default(monkeypatch):
    monkeypatch.delenv("KUEUE_TPU_ROUND_TIMEOUT", raising=False)
    assert knobs.raw("KUEUE_TPU_ROUND_TIMEOUT") == "60"
    monkeypatch.delenv("KUEUE_TPU_FAULTS", raising=False)
    assert knobs.raw("KUEUE_TPU_FAULTS") is None
    monkeypatch.setenv("KUEUE_TPU_ROUND_TIMEOUT", "5")
    assert knobs.raw("KUEUE_TPU_ROUND_TIMEOUT") == "5"


def test_unregistered_name_is_a_keyerror():
    """The runtime twin of KNOB01: an undeclared knob cannot be read."""
    with pytest.raises(KeyError):
        knobs.raw("KUEUE_TPU_NOT_A_KNOB")
    with pytest.raises(KeyError):
        knobs.flag("KUEUE_TPU_NOT_A_KNOB")


def test_get_returns_the_declaration():
    k = knobs.get("KUEUE_TPU_NO_MICROTICK")
    assert k.kind == knobs.KILL_SWITCH
    assert k.read == knobs.LIVE


def test_readme_knob_table_matches_registry():
    """The README table between the knob-table markers is EXACTLY
    markdown_table() — edit kueue_tpu/knobs.py and regenerate
    (`make knob-table`), never the README by hand."""
    text = README.read_text(encoding="utf-8")
    m = re.search(r"<!-- knob-table:begin -->\n(.*?)\n"
                  r"<!-- knob-table:end -->", text, re.DOTALL)
    assert m, "README.md lost its knob-table markers"
    assert m.group(1) == knobs.markdown_table(), (
        "README knob table drifted from kueue_tpu/knobs.py — regenerate "
        "with `make knob-table` (see README 'Environment knobs')")


def test_fuzz_lattice_toggles_are_registered_kill_switches():
    """The fuzz identity lattice flips env toggles per run; every
    toggle it uses must be a registered live kill switch, or the
    lattice is drilling a knob the contract does not cover."""
    import ast

    src = (Path(__file__).resolve().parent.parent / "kueue_tpu" / "fuzz"
           / "lattice.py").read_text(encoding="utf-8")
    used = {node.value for node in ast.walk(ast.parse(src))
            if isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value.startswith("KUEUE_TPU_")}
    assert used, "lattice.py no longer names any env toggles?"
    for name in sorted(used):
        k = knobs.get(name)  # KeyError -> unregistered toggle
        if name.startswith("KUEUE_TPU_NO_"):
            assert k.kind == knobs.KILL_SWITCH, name
            assert k.read == knobs.LIVE, name
