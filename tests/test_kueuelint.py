"""kueuelint (kueue_tpu.analysis) — tier-1 gate and analyzer unit tests.

The headline test runs the analyzer over the kueue_tpu package itself and
asserts zero error-severity findings: any PR that introduces a host sync in
a jitted kernel, a blocking call under a lock, a retrace hazard, or an API
hygiene violation fails tier-1 with a precise file:line report.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from kueue_tpu.analysis import Severity, all_rules, run_analysis
from kueue_tpu.analysis.reporters import render_json, render_text

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "kueue_tpu"
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "lint"


def _rules_of(findings):
    return {f.rule for f in findings}


def _errors(findings):
    return [f for f in findings if f.severity == Severity.ERROR]


# ---------------------------------------------------------------------------
# The gate: the package itself must be clean
# ---------------------------------------------------------------------------


def test_package_has_zero_error_findings():
    findings = run_analysis([str(PACKAGE)])
    errors = _errors(findings)
    report = "\n".join(f.render() for f in errors)
    assert not errors, f"kueuelint errors in kueue_tpu/:\n{report}"


def test_cli_exits_zero_on_package():
    proc = subprocess.run(
        [sys.executable, "-m", "kueue_tpu.analysis", str(PACKAGE)],
        cwd=str(REPO), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "kueuelint:" in proc.stdout


def test_cli_fails_on_introduced_violation(tmp_path):
    # Simulate a PR dropping a host sync into a jitted kernel under models/.
    bad_dir = tmp_path / "models"
    bad_dir.mkdir()
    shutil.copy(FIXTURES / "jit_bad.py", bad_dir / "new_kernel.py")
    proc = subprocess.run(
        [sys.executable, "-m", "kueue_tpu.analysis", str(tmp_path)],
        cwd=str(REPO), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    # Precise file:line:col report naming the rule.
    assert "new_kernel.py:" in proc.stdout
    assert "JIT01" in proc.stdout


# ---------------------------------------------------------------------------
# Rule families on good/bad fixture pairs
# ---------------------------------------------------------------------------


def test_jit_purity_bad_fixture():
    findings = run_analysis([str(FIXTURES / "jit_bad.py")])
    rules = _rules_of(findings)
    assert {"JIT01", "JIT02", "JIT03"} <= rules
    # Each family fires on the expected construct.
    msgs = {f.rule: [] for f in findings}
    for f in findings:
        msgs[f.rule].append(f.message)
    assert any(".item()" in m for m in msgs["JIT01"])
    assert any("float" in m for m in msgs["JIT01"])
    assert any("numpy" in m for m in msgs["JIT01"])
    assert any("print" in m for m in msgs["JIT01"])
    assert any("`if`" in m for m in msgs["JIT02"])
    assert any("`while`" in m for m in msgs["JIT02"])
    assert all(f.severity == Severity.ERROR for f in findings)


def test_jit_purity_good_fixture():
    assert run_analysis([str(FIXTURES / "jit_good.py")]) == []


def test_topology_bad_fixture():
    """The jit-purity scan covers kueue_tpu/topology/: a topology-style
    fit kernel carrying host syncs / traced branches / closure leaks
    fires the same JIT rule family there."""
    findings = run_analysis([str(FIXTURES / "topology_bad.py")])
    rules = _rules_of(findings)
    assert {"JIT01", "JIT02", "JIT03"} <= rules
    msgs = [f.message for f in findings if f.rule == "JIT01"]
    assert any("int" in m or "numpy" in m for m in msgs)


def test_topology_good_fixture():
    assert run_analysis([str(FIXTURES / "topology_good.py")]) == []


def test_topology_module_in_jit_roster(tmp_path):
    """Files under a topology/ directory are jit-purity scanned (the
    roster gate for the kueue_tpu/topology subsystem)."""
    bad_dir = tmp_path / "topology"
    bad_dir.mkdir()
    shutil.copy(FIXTURES / "topology_bad.py", bad_dir / "fit.py")
    findings = run_analysis([str(tmp_path)])
    assert "JIT01" in _rules_of(findings)


def test_retrace_bad_fixture():
    findings = run_analysis([str(FIXTURES / "retrace_bad.py")])
    rules = _rules_of(findings)
    assert {"RET01", "RET02"} <= rules
    ret01 = [f for f in findings if f.rule == "RET01"]
    assert any("missing" in f.message for f in ret01)
    assert any("out of range" in f.message for f in ret01)
    assert any("list" in f.message.lower() for f in ret01)
    # statics declared on a direct jax.jit(f, ...) call are seen too
    assert any("`flag`" in f.message for f in ret01)
    ret02 = [f for f in findings if f.rule == "RET02"]
    captured = {f.message.split("`")[1] for f in ret02}
    assert captured == {"scale", "offset"}
    assert all(f.severity == Severity.WARNING for f in ret02)


def test_retrace_good_fixture():
    assert run_analysis([str(FIXTURES / "retrace_good.py")]) == []


def test_lock_bad_fixture():
    findings = run_analysis([str(FIXTURES / "lock_bad.py")])
    rules = _rules_of(findings)
    assert {"LOCK01", "LOCK02"} <= rules
    lock01 = [f for f in findings if f.rule == "LOCK01"]
    joined = " ".join(f.message for f in lock01)
    assert "for_each" in joined          # parallelize fan-out under lock
    assert "time.sleep" in joined
    assert "subprocess" in joined
    assert "wait()" in joined            # untimed Condition.wait
    lock02 = [f for f in findings if f.rule == "LOCK02"]
    assert any("_applied" in f.message for f in lock02)


def test_lock_good_fixture():
    assert run_analysis([str(FIXTURES / "lock_good.py")]) == []


def test_thr_bad_fixture():
    findings = run_analysis([str(FIXTURES / "thr_bad.py")])
    assert _rules_of(findings) == {"THR01", "THR02"}
    assert all(f.severity == Severity.ERROR for f in findings)
    thr01 = [f for f in findings if f.rule == "THR01"]
    assert len(thr01) == 1  # one finding per attribute, not per access
    assert "`self._last`" in thr01[0].message
    assert "_read_loop" in thr01[0].message
    text = (FIXTURES / "thr_bad.py").read_text().splitlines()
    assert "self._last = data" in text[thr01[0].line - 1]
    joined = " ".join(f.message for f in findings if f.rule == "THR02")
    assert "sendall" in joined           # the symmetric-sendall deadlock
    assert "recv" in joined              # unbounded recv, no settimeout
    assert "fsync" in joined             # durability on the service loop
    assert "join()" in joined            # untimed Queue.join
    assert "_drain_loop" in joined       # root attribution in the message


def test_thr_good_fixture():
    """Identical thread topology, disciplined: settimeout bounds the
    socket, locks guard the shared state, `*_locked` documents the
    helper contract — zero findings."""
    assert run_analysis([str(FIXTURES / "thr_good.py")]) == []


def test_thread_roots_inferred_from_real_transport():
    """Regression-pin the root inference on the richest real surface:
    SocketChannel spawns a dialer (from a classmethod, via `chan.X`), a
    reader, and a Timer callback; ChannelListener spawns the accept
    loop and per-connection handshakes. Losing any of these roots would
    silently blind THR01/THR02 to the exact threads the PR 11/13
    incidents ran on."""
    import ast as ast_mod

    from kueue_tpu.analysis import thread_rules

    src = (Path(__file__).resolve().parent.parent / "kueue_tpu"
           / "transport" / "socket_channel.py").read_text()
    tree = ast_mod.parse(src)
    roots = {}
    for node in ast_mod.walk(tree):
        if isinstance(node, ast_mod.ClassDef):
            roots[node.name] = thread_rules._ClassModel(node).roots
    assert roots["SocketChannel"] == {"_dial_loop", "_read_loop",
                                      "_flush_held_timer"}
    assert roots["ChannelListener"] == {"_accept_loop", "_handshake"}


def test_knob_bad_fixture():
    findings = run_analysis([str(FIXTURES / "knob_bad.py")])
    assert _rules_of(findings) == {"KNOB01"}
    assert all(f.severity == Severity.ERROR for f in findings)
    assert len(findings) == 3
    joined = " ".join(f.message for f in findings)
    # Raw read of a registered knob: flagged for bypassing the registry,
    # but NOT called undeclared.
    no_arena = [f for f in findings if "KUEUE_TPU_NO_ARENA" in f.message]
    assert len(no_arena) == 1
    assert "does not declare" not in no_arena[0].message
    # Raw read of an undeclared knob: both complaints.
    secret = [f for f in findings if "KUEUE_TPU_SECRET_MODE" in f.message]
    assert len(secret) == 1
    assert "does not declare" in secret[0].message
    # Typo'd accessor name: caught at lint time, not as a drill KeyError.
    assert "KUEUE_TPU_NO_EAGER_ENCODING" in joined


def test_knob_good_fixture():
    """Same knobs, read through the registry accessors with registered
    names — zero findings."""
    assert run_analysis([str(FIXTURES / "knob_good.py")]) == []


def test_knob_dead_registry_entry(tmp_path):
    """A registry entry no analyzed file references is flagged AT the
    entry (whole-package runs include knobs.py, so the dead-entry half
    is live exactly when the registry itself is in scope)."""
    (tmp_path / "knobs.py").write_text(
        "class Knob:\n"
        "    def __init__(self, name, kind, default, read, doc):\n"
        "        pass\n"
        "\n"
        "REGISTRY = (\n"
        '    Knob("KUEUE_TPU_USED_KNOB", "debug", "", "live", "used"),\n'
        '    Knob("KUEUE_TPU_UNUSED_KNOB", "debug", "", "live", "dead"),\n'
        ")\n")
    (tmp_path / "app.py").write_text(
        "from kueue_tpu import knobs\n"
        "\n"
        "\n"
        "def on():\n"
        '    return knobs.flag("KUEUE_TPU_USED_KNOB")\n')
    findings = run_analysis([str(tmp_path)], select=["KNOB01"])
    assert len(findings) == 1
    assert "KUEUE_TPU_UNUSED_KNOB" in findings[0].message
    assert "no read site" in findings[0].message
    assert findings[0].path.endswith("knobs.py")


def test_knob_registry_covers_every_env_read():
    """The package-wide contract: zero raw KUEUE_TPU_* env reads outside
    knobs.py, every accessor name registered, every registry entry
    read somewhere."""
    findings = run_analysis([str(PACKAGE)], select=["KNOB01"])
    report = "\n".join(f.render() for f in findings)
    assert findings == [], f"knob contract violations:\n{report}"


def test_api_bad_fixture():
    findings = run_analysis([str(FIXTURES / "api_bad.py")])
    rules = _rules_of(findings)
    assert {"API01", "API02"} <= rules
    api01 = [f for f in findings if f.rule == "API01"]
    assert len(api01) == 2  # enqueue(batch=[]) and configure(opts={})
    api02 = [f for f in findings if f.rule == "API02"]
    assert any("FlavorRef" in f.message for f in api02)


def test_api_good_fixture():
    assert run_analysis([str(FIXTURES / "api_good.py")]) == []


def test_obs_bad_fixture():
    findings = run_analysis([str(FIXTURES / "obs_bad.py")])
    obs = [f for f in findings if f.rule == "OBS01"]
    assert len(obs) == 4  # from-import + 2x perf_counter + monotonic alias
    joined = " ".join(f.message for f in obs)
    assert "TRACER.phase" in joined
    assert "from time import perf_counter" in joined
    assert "_time.monotonic" in joined
    # time.time() wall-clock reads never fire.
    assert "time.time" not in joined


def test_obs_good_fixture():
    assert run_analysis([str(FIXTURES / "obs_good.py")]) == []


def test_perf_bad_fixture():
    findings = run_analysis([str(FIXTURES / "perf_bad.py")])
    perf = [f for f in findings if f.rule == "PERF01"]
    # direct subscript + 2 aliased reads + while-counter read +
    # per-entry flush walk
    assert len(perf) == 5
    assert all("solver output tensor" in f.message for f in perf)
    assert all(f.severity.label == "error" for f in perf)


def test_perf_good_fixture():
    assert run_analysis([str(FIXTURES / "perf_good.py")]) == []


def test_perf_fair_bad_fixture():
    findings = run_analysis([str(FIXTURES / "perf_fair_bad.py")])
    perf = [f for f in findings if f.rule == "PERF01"]
    # share_x + per-candidate share in the while loop, plus the
    # per-name for-loop walk.
    assert len(perf) == 3
    assert all("dominant_resource_share" in f.message for f in perf)
    assert all(f.severity.label == "error" for f in perf)


def test_perf_fair_good_fixture():
    assert run_analysis([str(FIXTURES / "perf_fair_good.py")]) == []


def test_perf_ingest_bad_fixture():
    findings = run_analysis([str(FIXTURES / "perf_ingest_bad.py")])
    perf = [f for f in findings if f.rule == "PERF01"]
    # per-object decode + create + submit + decode_workload
    assert len(perf) == 4
    assert all("batch ingest lane" in f.message for f in perf)
    assert all(f.severity.label == "error" for f in perf)


def test_perf_ingest_good_fixture():
    # The kill-switch twin's suppressed loop both stays quiet AND keeps
    # its suppression live (no W001).
    assert run_analysis([str(FIXTURES / "perf_ingest_good.py")]) == []


def test_perf_ingest_scoped_to_ingest_files(tmp_path):
    # The same per-object loop outside store/server (a test driver, the
    # bench harness) is not the ingest rule's business.
    other = tmp_path / "driver_tool.py"
    other.write_text(
        "def drive(fw, wls):\n"
        "    for wl in wls:\n"
        "        fw.submit(wl)\n")
    assert run_analysis([str(other)]) == []


def test_perf_rule_scoped_to_solver_packages(tmp_path):
    # The same loop shape OUTSIDE scheduler//solver//models/ (analysis
    # tooling, tests, benchmarks post-processing) is not PERF01's
    # business.
    other = tmp_path / "report_tool.py"
    other.write_text(
        "def summarize(out, n):\n"
        "    rows = []\n"
        "    for w in range(n):\n"
        "        rows.append(out['wl_mode'][w])\n"
        "    return rows\n")
    assert run_analysis([str(other)]) == []


def test_obs_rule_scoped_to_tick_pipeline(tmp_path):
    # The same raw timing OUTSIDE the pipeline paths is none of OBS01's
    # business (CLI glue, benchmarks, tests keep their perf_counters).
    other = tmp_path / "cli_tool.py"
    other.write_text("import time\nt0 = time.perf_counter()\n")
    assert run_analysis([str(other)]) == []


def test_roundtrip_fixture_pair():
    bad = run_analysis([str(FIXTURES / "roundtrip_bad")])
    assert _rules_of(bad) == {"API03"}
    assert any("retries" in f.message for f in bad)
    assert run_analysis([str(FIXTURES / "roundtrip_good")]) == []


# ---------------------------------------------------------------------------
# Suppressions, reporters, CLI plumbing
# ---------------------------------------------------------------------------


def test_suppression_comments_silence_findings():
    assert run_analysis([str(FIXTURES / "suppressed.py")]) == []


def test_suppression_is_rule_specific(tmp_path):
    # A disable comment for a DIFFERENT rule must not silence the finding.
    src = FIXTURES / "suppressed.py"
    patched = src.read_text().replace("disable=JIT01", "disable=LOCK01")
    target = tmp_path / "fixtures" / "lint" / "suppressed.py"
    target.parent.mkdir(parents=True)
    target.write_text(patched)
    findings = run_analysis([str(target)])
    assert "JIT01" in _rules_of(findings)


def test_select_and_disable_filters():
    bad = str(FIXTURES / "lock_bad.py")
    only_lock01 = run_analysis([bad], select=["LOCK01"])
    assert _rules_of(only_lock01) == {"LOCK01"}
    no_lock01 = run_analysis([bad], disable=["LOCK01"])
    assert "LOCK01" not in _rules_of(no_lock01)


def test_json_reporter_schema():
    findings = run_analysis([str(FIXTURES / "jit_bad.py")])
    doc = json.loads(render_json(findings))
    assert doc["tool"] == "kueuelint"
    assert doc["counts"]["error"] == len(findings)
    for item in doc["findings"]:
        assert set(item) == {"rule", "severity", "path", "line", "col",
                             "message"}
        assert item["severity"] in ("error", "warning")
        assert item["line"] >= 1


def test_json_cli_roundtrip():
    proc = subprocess.run(
        [sys.executable, "-m", "kueue_tpu.analysis", "--format", "json",
         str(FIXTURES / "api_bad.py")],
        cwd=str(REPO), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["counts"]["error"] >= 1


def test_text_reporter_format():
    findings = run_analysis([str(FIXTURES / "api_bad.py")])
    text = render_text(findings)
    first = text.splitlines()[0]
    # path:line:col: RULE [severity] message
    assert first.count(":") >= 3
    assert "[error]" in first
    assert text.splitlines()[-1].startswith("kueuelint:")


def test_fail_on_warning_escalates():
    # retrace_bad has RET02 warnings; --fail-on warning must gate on them.
    proc = subprocess.run(
        [sys.executable, "-m", "kueue_tpu.analysis", "--fail-on", "warning",
         "--select", "RET02", str(FIXTURES / "retrace_bad.py")],
        cwd=str(REPO), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1


def test_unknown_select_id_is_a_usage_error():
    # A typo'd --select must NOT produce a clean exit-0 run.
    proc = subprocess.run(
        [sys.executable, "-m", "kueue_tpu.analysis", "--select", "LOCK1",
         str(FIXTURES / "lock_bad.py")],
        cwd=str(REPO), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
    assert "unknown rule id" in proc.stderr


def test_rule_registry_covers_all_families():
    ids = {r.id for r in all_rules()}
    assert {"JIT01", "JIT02", "JIT03", "RET01", "RET02",
            "LOCK01", "LOCK02", "API01", "API02", "API03", "OBS01"} <= ids


def test_parse_error_is_reported(tmp_path):
    broken = tmp_path / "broken.py"
    broken.write_text("def oops(:\n")
    findings = run_analysis([str(broken)])
    assert _rules_of(findings) == {"PARSE"}
    assert findings[0].severity == Severity.ERROR


# ---------------------------------------------------------------------------
# W001 — stale suppressions
# ---------------------------------------------------------------------------


def test_stale_suppression_is_reported(tmp_path):
    # A disable comment on a clean line is dead weight: it masks a future
    # regression on that line without excusing anything today.
    src = tmp_path / "stale.py"
    src.write_text("def fine():\n"
                   "    return 1  # kueuelint: disable=JIT01\n")
    findings = run_analysis([str(src)])
    assert _rules_of(findings) == {"W001"}
    assert "JIT01" in findings[0].message
    assert findings[0].severity == Severity.WARNING


def test_live_suppression_is_not_stale():
    # suppressed.py's disables all excuse real findings: zero W001.
    assert run_analysis([str(FIXTURES / "suppressed.py")]) == []


def test_w001_ignores_rules_that_did_not_run(tmp_path):
    # A TRC suppression is not stale in an ast-only run (the trace engine
    # did not execute, so the rule had no chance to fire).
    src = tmp_path / "trace_suppr.py"
    src.write_text("def fine():\n"
                   "    return 1  # kueuelint: disable=TRC02\n")
    assert run_analysis([str(src)], engine="ast") == []


def test_w001_ignores_bare_disable(tmp_path):
    # Bare `disable` makes no per-rule claim; W001 only judges named ones.
    src = tmp_path / "bare.py"
    src.write_text("def fine():\n"
                   "    return 1  # kueuelint: disable\n")
    assert run_analysis([str(src)]) == []


def test_docstring_mention_is_not_a_suppression(tmp_path):
    # Directives are tokenized: prose inside a docstring that MENTIONS
    # `# kueuelint: disable=API01` neither suppresses nor goes stale.
    src = tmp_path / "doc.py"
    src.write_text('"""Use `# kueuelint: disable=API01` to suppress."""\n'
                   "def bad(batch=[]):\n"
                   "    return batch\n")
    findings = run_analysis([str(src)])
    assert _rules_of(findings) == {"API01"}


def test_package_has_no_stale_suppressions():
    findings = run_analysis([str(PACKAGE)])
    stale = [f for f in findings if f.rule == "W001"]
    assert not stale, "\n".join(f.render() for f in stale)


def test_w001_skips_unparseable_files(tmp_path):
    # A file mid-edit ran no rules, so its suppressions are not stale.
    src = tmp_path / "midedit.py"
    src.write_text("def broken(:\n"
                   "    x = 1  # kueuelint: disable=JIT01\n")
    findings = run_analysis([str(src)])
    assert _rules_of(findings) == {"PARSE"}


def test_select_w001_alone_is_a_usage_error():
    # Alone, W001 has no rules to judge — a silent exit-0 would read as
    # "no stale suppressions" when nothing was checked.
    proc = subprocess.run(
        [sys.executable, "-m", "kueue_tpu.analysis", "--select", "W001",
         str(FIXTURES / "suppressed.py")],
        cwd=str(REPO), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
    assert "W001" in proc.stderr
