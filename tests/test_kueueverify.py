"""kueueverify (trace engine) + flow engine — tier-1 gate and unit tests.

The headline gate runs EVERY analysis engine over the package: the ast
rules, the whole-program flow rules (lock-order graph, ledger pairing),
and the trace rules (every registered solver kernel lowered to a jaxpr
and verified — dtype hazards, sentinel overflow, bucket-stable structure,
forbidden effects). A PR that reintroduces the PR 2 Pallas bug class, or
adds arithmetic that can wrap on sentinel inputs, or makes a kernel's
trace shape-dependent, fails here with a file:line report.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

import pytest

from kueue_tpu.analysis import Severity, run_analysis
from kueue_tpu.analysis import trace_rules
from kueue_tpu.solver import modes

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "kueue_tpu"
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "lint"


def _rules_of(findings):
    return {f.rule for f in findings}


def _errors(findings):
    return [f for f in findings if f.severity == Severity.ERROR]


# ---------------------------------------------------------------------------
# The gate: all engines, zero errors on the package
# ---------------------------------------------------------------------------


def test_package_clean_under_all_engines():
    findings = run_analysis([str(PACKAGE)], engine="all")
    errors = _errors(findings)
    report = "\n".join(f.render() for f in errors)
    assert not errors, f"kueuelint --engine all errors in kueue_tpu/:\n{report}"


def test_cli_engine_all_exits_zero_on_package():
    proc = subprocess.run(
        [sys.executable, "-m", "kueue_tpu.analysis", "--engine", "all",
         "--fail-on", "error", str(PACKAGE)],
        cwd=str(REPO), capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_unknown_engine_rejected():
    with pytest.raises(ValueError):
        run_analysis([str(FIXTURES / "trace_good.py")], engine="jaxpr")


def test_trace_rules_do_not_run_under_ast_engine():
    findings = run_analysis([str(FIXTURES / "trace_bad.py")], engine="ast")
    assert not (_rules_of(findings)
                & {"TRC01", "TRC02", "TRC03", "TRC04"})


# ---------------------------------------------------------------------------
# Trace engine on fixture manifests
# ---------------------------------------------------------------------------


def test_trace_bad_fixture_triggers_every_trc_rule():
    findings = run_analysis([str(FIXTURES / "trace_bad.py")], engine="trace")
    assert {"TRC01", "TRC02", "TRC03", "TRC04"} <= _rules_of(findings)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f.message)
    assert any("mixed-dtype write" in m for m in by_rule["TRC01"])
    assert any("literal" in m for m in by_rule["TRC01"])
    assert any("exceeds int64" in m for m in by_rule["TRC02"])
    assert any("adjacent buckets" in m for m in by_rule["TRC03"])
    assert any("debug_callback" in m for m in by_rule["TRC04"])
    assert all(f.severity == Severity.ERROR for f in findings)


def test_trace_good_fixture_is_clean():
    assert run_analysis([str(FIXTURES / "trace_good.py")],
                        engine="trace") == []


def test_pr2_pallas_rescale_repro_caught_statically():
    """The PR 2 Pallas int32-rescale bug shape (sentinel-poisoned int32
    arithmetic + weak-int64 state writes) — found at runtime by the
    all-engine preemption goldens back then — must be decided statically
    by TRC01/TRC02 from the jaxpr alone."""
    findings = run_analysis([str(FIXTURES / "pallas_rescale_bad.py")],
                            engine="trace")
    rules = _rules_of(findings)
    assert {"TRC01", "TRC02"} <= rules
    trc02 = [f for f in findings if f.rule == "TRC02"]
    assert any("exceeds int32" in f.message for f in trc02)


def test_broken_manifest_reports_parse_finding(tmp_path):
    bad = tmp_path / "manifest_broken.py"
    bad.write_text("KUEUEVERIFY_KERNELS = undefined_name\n")
    findings = run_analysis([str(bad)], engine="trace")
    assert _rules_of(findings) == {"PARSE"}


def test_trace_findings_anchor_to_kernel_source_lines():
    findings = run_analysis([str(FIXTURES / "trace_bad.py")],
                            engine="trace")
    text = (FIXTURES / "trace_bad.py").read_text().splitlines()
    f = next(f for f in findings if f.rule == "TRC02")
    assert "nominal + blim" in text[f.line - 1]


def test_trace_suppressions_work_on_kernel_lines(tmp_path):
    src = (FIXTURES / "trace_bad.py").read_text()
    patched = src.replace("return own <= nominal + blim",
                          "return own <= nominal + blim  "
                          "# kueuelint: disable=TRC02")
    target = tmp_path / "trace_suppressed.py"
    target.write_text(patched)
    findings = run_analysis([str(target)], engine="trace")
    assert "TRC02" not in _rules_of(findings)


# ---------------------------------------------------------------------------
# TRC03: the one-compile-per-bucket contract, per engine
# ---------------------------------------------------------------------------


def test_trc03_every_batched_kernel_is_bucket_stable():
    """Regression-pin: every roster kernel lowers to a structurally
    IDENTICAL jaxpr at two adjacent head-count buckets — the contract
    prewarm_idle's neighbor-bucket compilation relies on (exactly one XLA
    compile per bucket, nothing shape-specialized)."""
    report = trace_rules.bucket_report()
    assert report, "empty kernel roster"
    bad = [r for r in report if not r["equal"]]
    assert not bad, f"bucket-unstable kernels: {bad}"
    covered = {r["kernel"] for r in report}
    # Every traceable registered engine, plus the flavor-fit and topology
    # entry points, prove the contract.
    want = {e.name for e in modes.ENGINES if e.traceable and e.batched}
    want |= {"flavor-fit", "flavor-fit-packed", "topology-fit", "scan-jax"}
    assert want <= covered, f"missing from roster: {want - covered}"


def test_roster_buckets_are_adjacent_powers():
    for spec in trace_rules.package_roster():
        b0, b1 = spec.buckets
        assert b1 == 2 * b0, (spec.name, spec.buckets)


# ---------------------------------------------------------------------------
# TRC02 through the packed byte-buffer kernels (bitcast-aware domain)
# ---------------------------------------------------------------------------


def test_no_roster_kernel_is_exempt_from_trc02():
    """The packed kernels used to run NO_TRC02 ("verified unpacked
    instead"); the bitcast-aware Packed domain retired that exemption —
    every roster entry must run the FULL rule set."""
    for spec in trace_rules.package_roster():
        assert spec.rules == trace_rules.ALL_TRC, \
            f"{spec.name} exempts {trace_rules.ALL_TRC - spec.rules}"


def test_packed_kernels_have_wire_layout_seeds():
    """The packed twins verify via their declared wire layout, not the
    meaningless uint8 dtype default: their seeds must be bucket-callables
    producing at least one Packed value."""
    from kueue_tpu.analysis import jaxpr_tools as jt

    by_name = {s.name: s for s in trace_rules.package_roster()}
    for name in ("batch-jax", "flavor-fit-packed"):
        spec = by_name[name]
        assert callable(spec.seeds), name
        seeded = spec.seeds(spec.buckets[0])
        assert any(isinstance(v, jt.Packed) for v in seeded.values()), name
    pallas = by_name["scan-pallas"]
    assert pallas.seeds and pallas.scratch_seeds


def test_packed_domain_survives_unpack_chain():
    """Unit-level: a Packed window pushed through the canonical
    slice -> reshape -> bitcast unpack chain degrades to exactly the
    seeded per-field interval, and a window that fuses two fields
    degrades to UNKNOWN (never a false bound)."""
    from kueue_tpu.analysis import jaxpr_tools as jt

    layout = jt.packed_layout([(4, 8, (0, 2**62)), (4, 8, (-5, 7))])
    assert not layout.to_interval().known  # mixed widths vs elem_bytes=1
    first = jt.Packed(0, 32, 8, layout.sections)
    assert (first.to_interval().lo, first.to_interval().hi) == (0, 2**62)
    second = jt.Packed(32, 32, 8, layout.sections)
    assert (second.to_interval().lo, second.to_interval().hi) == (-5, 7)
    both = jt.Packed(0, 64, 8, layout.sections)
    assert (both.to_interval().lo, both.to_interval().hi) == (-5, 2**62)
    misaligned = jt.Packed(4, 32, 8, layout.sections)
    assert not misaligned.to_interval().known
    wrong_width = jt.Packed(0, 32, 4, layout.sections)
    assert not wrong_width.to_interval().known


def test_packed_overflow_bad_fixture_caught():
    """A sentinel overflow reachable only THROUGH the packed wire format
    (slice + bitcast unpack) must be found — a flat interval seed on the
    uint8 buffer proves nothing about the int64 planes inside."""
    findings = run_analysis([str(FIXTURES / "packed_overflow_bad.py")],
                            engine="trace")
    assert _rules_of(findings) == {"TRC02"}
    assert any("exceeds int64" in f.message for f in findings)
    text = (FIXTURES / "packed_overflow_bad.py").read_text().splitlines()
    f = next(f for f in findings if f.rule == "TRC02")
    assert "nominal + nominal" in text[f.line - 1]


def test_packed_roster_kernels_verify_clean_under_trc02():
    """The real packed kernels, seeded with their wire layouts (and the
    Pallas scratch contract), carry NO sentinel-overflow hazards — the
    tentpole acceptance: TRC02 verifies every packed kernel at its
    canonical buckets."""
    from kueue_tpu.analysis.trace_rules import (
        _check_trc02, package_roster)

    class _Ctx:
        files = ()

    for spec in package_roster():
        if spec.name not in ("batch-jax", "flavor-fit-packed",
                             "scan-pallas", "hetero-scores"):
            continue
        jaxprs = trace_rules._lower(spec)
        for bucket in spec.buckets:
            found = _check_trc02(_Ctx(), spec, jaxprs[bucket], bucket)
            assert not found, (spec.name, bucket,
                               [f.message for f in found])


# ---------------------------------------------------------------------------
# Flow engine fixtures
# ---------------------------------------------------------------------------


def test_lockgraph_bad_fixture_reports_cycle():
    findings = run_analysis([str(FIXTURES / "lockgraph_bad.py")],
                            engine="flow")
    assert _rules_of(findings) == {"LOCK03"}
    msg = findings[0].message
    assert "CacheSide._lock" in msg and "QueueSide._cond" in msg
    assert "deadlock" in msg


def test_lockgraph_good_fixture_is_clean():
    assert run_analysis([str(FIXTURES / "lockgraph_good.py")],
                        engine="flow") == []


def test_lockgraph_protocol_bad_fixture_reports_cycle():
    """LOCK03 resolves calls through Protocol- and annotation-typed
    attributes: the channel attribute is typed only by a Protocol
    annotation (the concrete class hides behind a factory) and the
    back-ref only by a string annotation — the cycle must still be
    found, through the structural conformer."""
    findings = run_analysis([str(FIXTURES / "lockgraph_proto_bad.py")],
                            engine="flow")
    assert _rules_of(findings) == {"LOCK03"}
    msg = findings[0].message
    assert "Runtime._lock" in msg and "LockedChannel._lock" in msg


def test_lockgraph_protocol_good_fixture_is_clean():
    assert run_analysis([str(FIXTURES / "lockgraph_proto_good.py")],
                        engine="flow") == []


def test_ledger_bad_fixture_reports_imbalance_and_error_path():
    findings = run_analysis([str(FIXTURES / "ledger_bad.py")],
                            engine="flow")
    assert _rules_of(findings) == {"LED01"}
    msgs = [f.message for f in findings]
    assert any("never released" in m for m in msgs)
    assert any("error exit" in m for m in msgs)


def test_ledger_good_fixture_is_clean():
    assert run_analysis([str(FIXTURES / "ledger_good.py")],
                        engine="flow") == []


def test_flow_engine_clean_on_package():
    findings = run_analysis([str(PACKAGE)], engine="flow")
    assert _errors(findings) == [], \
        "\n".join(f.render() for f in _errors(findings))
