"""ChannelLeaseStore vs FileLeaseStore vs LeaseStore: the same kube
lease semantics (CAS acquire/renew, expiry takeover, holder abdication,
transitions/epoch audit) must hold across all three substrates — and
the channel store must hold them with NO shared filesystem between the
candidate processes (the fleet requirement PR 11 left open)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from kueue_tpu.controllers.leaderelection import (
    FileLeaseStore,
    LeaderElector,
    LeaseStore,
)
from kueue_tpu.metrics import REGISTRY
from kueue_tpu.transport import (
    ChannelLeaseStore,
    ChannelListener,
    LeaseService,
)

NAME = "test-lease"


def _channel_pair():
    """A LeaseService on a real listener + a connected client store."""
    authority = LeaseStore()
    listener = ChannelListener("127.0.0.1", 0)
    LeaseService(authority).attach(listener)
    store = ChannelLeaseStore(listener.address, identity="c1",
                              timeout=10.0)
    return store, (listener, authority)


@pytest.fixture(params=["memory", "file", "channel"])
def store(request, tmp_path):
    if request.param == "memory":
        yield LeaseStore()
    elif request.param == "file":
        yield FileLeaseStore(str(tmp_path / "leases.json"))
    else:
        s, (listener, _authority) = _channel_pair()
        try:
            yield s
        finally:
            s.close()
            listener.close()


def test_semantics_suite(store):
    """The FileLeaseStore semantics suite, run verbatim against every
    substrate: CAS, renewal, denial while fresh, expiry takeover,
    abdication, and the transitions epoch audit."""
    # Unheld: first candidate takes it; transitions == 1.
    assert store.try_acquire_or_renew(NAME, "a", 15.0, now=100.0)
    assert store.holder(NAME) == "a"
    assert store.transitions(NAME) == 1
    # Fresh lease: a rival is denied, holder renews.
    assert not store.try_acquire_or_renew(NAME, "b", 15.0, now=105.0)
    assert store.try_acquire_or_renew(NAME, "a", 15.0, now=110.0)
    assert store.transitions(NAME) == 1  # renewals are not transitions
    # Expiry: renewed at 110 with 15s duration -> b takes it at >= 125.
    assert not store.try_acquire_or_renew(NAME, "b", 15.0, now=124.9)
    assert store.try_acquire_or_renew(NAME, "b", 15.0, now=125.1)
    assert store.holder(NAME) == "b"
    assert store.transitions(NAME) == 2
    # Abdication: release frees it immediately for the next candidate.
    store.release(NAME, "b")
    assert store.holder(NAME) == ""
    assert store.try_acquire_or_renew(NAME, "a", 15.0, now=126.0)
    assert store.transitions(NAME) == 3
    # A non-holder's release is a no-op.
    store.release(NAME, "b")
    assert store.holder(NAME) == "a"


def test_transitions_metric_counts_holder_changes():
    before = REGISTRY.lease_transitions_total.get("metric-lease")
    s = LeaseStore()
    s.try_acquire_or_renew("metric-lease", "a", 15.0, now=0.0)
    s.try_acquire_or_renew("metric-lease", "a", 15.0, now=1.0)  # renew
    s.try_acquire_or_renew("metric-lease", "b", 15.0, now=20.0)
    assert REGISTRY.lease_transitions_total.get("metric-lease") \
        == before + 2


def test_elector_runs_on_channel_store():
    """LeaderElector is substrate-agnostic: the channel store slots
    into the same seam (the ReplicaRuntime lease_store parameter)."""
    store, (listener, _authority) = _channel_pair()
    try:
        clock = [1000.0]
        elector = LeaderElector(store, identity="coordinator-x",
                                clock=lambda: clock[0])
        assert elector.step()
        assert elector.is_leader()
        assert store.holder(elector.config.resource_name) \
            == "coordinator-x"
        elector.release()
        assert store.holder(elector.config.resource_name) == ""
    finally:
        store.close()
        listener.close()


def test_unreachable_service_never_reports_acquisition():
    """A candidate that cannot confirm the CAS must not lead: after the
    service dies, try_acquire returns False and holder/transitions fall
    back to the last confirmed values."""
    store, (listener, _authority) = _channel_pair()
    try:
        assert store.try_acquire_or_renew(NAME, "a", 15.0, now=0.0)
        t = store.transitions(NAME)
        listener.close()
        store.timeout = 0.3
        assert not store.try_acquire_or_renew(NAME, "a", 15.0, now=1.0)
        assert not store.available
        assert store.transitions(NAME) == t  # cached, flagged stale
    finally:
        store.close()


_CHILD = textwrap.dedent("""
    import json, sys
    from kueue_tpu.transport import ChannelLeaseStore

    host, port = sys.argv[1], int(sys.argv[2])
    store = ChannelLeaseStore((host, port), identity="child",
                              timeout=20.0)
    out = {
        "denied_while_fresh": not store.try_acquire_or_renew(
            "xproc", "child", 15.0, now=105.0),
        "took_after_expiry": store.try_acquire_or_renew(
            "xproc", "child", 15.0, now=130.0),
        "holder": store.holder("xproc"),
        "transitions": store.transitions("xproc"),
    }
    store.close()
    print(json.dumps(out))
""")


def test_two_processes_no_shared_filesystem(tmp_path):
    """The acceptance shape: two real OS processes race the same lease
    purely over TCP — the child runs in its own cwd with no file in
    common; only the (host, port) travels."""
    authority = LeaseStore()
    listener = ChannelListener("127.0.0.1", 0)
    LeaseService(authority).attach(listener)
    parent = ChannelLeaseStore(listener.address, identity="parent",
                               timeout=20.0)
    try:
        assert parent.try_acquire_or_renew("xproc", "parent", 15.0,
                                           now=100.0)
        child_dir = tmp_path / "elsewhere"
        child_dir.mkdir()
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD, listener.address[0],
             str(listener.address[1])],
            capture_output=True, text=True, timeout=60,
            cwd=str(child_dir),
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 # Import path only — the child's cwd shares no files
                 # with the parent; the lease rides pure TCP.
                 "PYTHONPATH": os.path.dirname(
                     os.path.dirname(os.path.abspath(__file__)))})
        assert proc.returncode == 0, proc.stderr
        got = json.loads(proc.stdout.strip().splitlines()[-1])
        # The child was denied while the parent's lease was fresh, took
        # it over after expiry, and both sides agree on the epoch audit.
        assert got["denied_while_fresh"] is True
        assert got["took_after_expiry"] is True
        assert got["holder"] == "child"
        assert got["transitions"] == 2
        assert parent.holder("xproc") == "child"
        assert parent.transitions("xproc") == 2
    finally:
        parent.close()
        listener.close()
