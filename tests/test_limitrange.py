"""LimitRange summarization + AdjustResources tests.

Mirrors reference pkg/util/limitrange/limitrange_test.go and the
AdjustResources pipeline in pkg/workload/resources.go.
"""

from kueue_tpu.api.resources import resource_value
from kueue_tpu.api.types import (
    ClusterQueue,
    Container,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    PodTemplate,
    ResourceFlavor,
    ResourceGroup,
    Workload,
)
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.utils.limitrange import (
    LimitRange,
    LimitRangeItem,
    adjust_resources,
    summarize,
    validate_limits_fit_requests,
    validate_workload_against,
)

CPU = "cpu"
MEM = "memory"


def cpuq(v):
    return resource_value(CPU, v)


class TestSummarize:
    def test_max_keeps_min_min_keeps_max_defaults_first(self):
        r1 = LimitRange(items=[LimitRangeItem(
            type="Container", max={CPU: 4000}, min={CPU: 100},
            default={CPU: 2000}, default_request={CPU: 500})])
        r2 = LimitRange(items=[LimitRangeItem(
            type="Container", max={CPU: 3000}, min={CPU: 200},
            default={CPU: 1000}, default_request={CPU: 250})])
        s = summarize([r1, r2])
        item = s["Container"]
        assert item.max[CPU] == 3000      # lowest max wins
        assert item.min[CPU] == 200       # highest min wins
        assert item.default[CPU] == 2000  # first default wins
        assert item.default_request[CPU] == 500


class TestTotalRequests:
    def test_max_of_init_and_sum_plus_overhead(self):
        pt = PodTemplate(
            containers=[Container.make(requests={CPU: 1}),
                        Container.make(requests={CPU: 1})],
            init_containers=[Container.make(requests={CPU: 5})],
            overhead={CPU: cpuq("100m")})
        total = pt.total_requests()
        # init container (5) > sum of main (2); overhead added on top.
        assert total[CPU] == cpuq(5) + cpuq("100m")


class TestAdjustResources:
    def test_limits_default_to_requests(self):
        pt = PodTemplate(containers=[Container.make(limits={CPU: 2})])
        wl = Workload(name="w", pod_sets=[
            PodSet(name="main", count=1, template=pt)])
        adjust_resources(wl)
        assert wl.pod_sets[0].requests[CPU] == cpuq(2)

    def test_limitrange_defaults_applied(self):
        pt = PodTemplate(containers=[Container.make()])
        wl = Workload(name="w", pod_sets=[
            PodSet(name="main", count=1, template=pt)])
        lr = LimitRange(items=[LimitRangeItem(
            type="Container", default_request={CPU: cpuq(1)})])
        adjust_resources(wl, [lr])
        assert wl.pod_sets[0].requests[CPU] == cpuq(1)

    def test_runtime_class_overhead(self):
        pt = PodTemplate(containers=[Container.make(requests={CPU: 1})],
                         runtime_class_name="gvisor")
        wl = Workload(name="w", pod_sets=[
            PodSet(name="main", count=1, template=pt)])
        adjust_resources(wl, [], {"gvisor": {CPU: cpuq("250m")}})
        assert wl.pod_sets[0].requests[CPU] == cpuq(1) + cpuq("250m")

    def test_explicit_requests_win_over_defaults(self):
        pt = PodTemplate(containers=[
            Container.make(requests={CPU: 3}, limits={CPU: 4})])
        wl = Workload(name="w", pod_sets=[
            PodSet(name="main", count=1, template=pt)])
        lr = LimitRange(items=[LimitRangeItem(
            type="Container", default_request={CPU: cpuq(1)})])
        adjust_resources(wl, [lr])
        assert wl.pod_sets[0].requests[CPU] == cpuq(3)


class TestValidation:
    def test_container_over_max(self):
        pt = PodTemplate(containers=[Container.make(requests={CPU: 8})])
        wl = Workload(name="w", pod_sets=[
            PodSet(name="main", count=1, requests={CPU: cpuq(8)},
                   template=pt)])
        lr = LimitRange(items=[LimitRangeItem(
            type="Container", max={CPU: cpuq(4)})])
        reasons = validate_workload_against(wl, [lr])
        assert reasons and "exceeds" in reasons[0]

    def test_pod_total_under_min(self):
        pt = PodTemplate(containers=[Container.make(requests={CPU: 1})])
        wl = Workload(name="w", pod_sets=[
            PodSet(name="main", count=1, requests={CPU: cpuq(1)},
                   template=pt)])
        lr = LimitRange(items=[LimitRangeItem(
            type="Pod", min={CPU: cpuq(2)})])
        reasons = validate_workload_against(wl, [lr])
        assert reasons and "less than" in reasons[0]

    def test_requests_over_limits(self):
        pt = PodTemplate(containers=[
            Container.make(requests={CPU: 4}, limits={CPU: 2})])
        wl = Workload(name="w", pod_sets=[
            PodSet(name="main", count=1, requests={CPU: cpuq(4)},
                   template=pt)])
        reasons = validate_limits_fit_requests(wl)
        assert reasons and "exceed limits" in reasons[0]


class TestEndToEnd:
    """The scheduler parks LimitRange-violating workloads as inadmissible
    (scheduler.go nominate -> validateLimitRange)."""

    def _fw(self):
        fw = Framework()
        fw.create_resource_flavor(ResourceFlavor.make("default"))
        fw.create_cluster_queue(ClusterQueue(
            name="cq",
            resource_groups=(ResourceGroup(
                covered_resources=(CPU,),
                flavors=(FlavorQuotas.make("default", cpu=10),)),)))
        fw.create_local_queue(LocalQueue(
            name="lq", namespace="default", cluster_queue="cq"))
        return fw

    def test_violating_workload_not_admitted(self):
        fw = self._fw()
        fw.create_limit_range(LimitRange(
            namespace="default",
            items=[LimitRangeItem(type="Container", max={CPU: cpuq(1)})]))
        pt = PodTemplate(containers=[Container.make(requests={CPU: 2})])
        wl = Workload(name="big", queue_name="lq",
                      pod_sets=[PodSet(name="main", count=1, template=pt)])
        fw.submit(wl)
        fw.run_until_settled()
        assert not wl.has_quota_reservation
        assert fw.pending_workloads("cq") == 1

    def test_late_limit_range_readjusts_pending_workloads(self):
        # LimitRange created AFTER submit must re-run AdjustResources on
        # pending workloads (the reference's LimitRange watch handler).
        fw = self._fw()
        pt = PodTemplate(containers=[Container.make()])
        wl = Workload(name="late", queue_name="lq",
                      pod_sets=[PodSet(name="main", count=1, template=pt)])
        fw.submit(wl)
        fw.create_limit_range(LimitRange(
            namespace="default",
            items=[LimitRangeItem(type="Container",
                                  default_request={CPU: cpuq(2)})]))
        fw.run_until_settled()
        assert wl.has_quota_reservation
        assert wl.admission.pod_set_assignments[0].resource_usage[CPU] \
            == cpuq(2)

    def test_reclaimable_update_rejected_out_of_range(self):
        import pytest

        from kueue_tpu import webhooks
        fw = self._fw()
        wl = Workload(name="w", queue_name="lq",
                      pod_sets=[PodSet.make("main", 2, cpu=1)])
        fw.submit(wl)
        fw.run_until_settled()
        assert wl.has_quota_reservation
        with pytest.raises(webhooks.ValidationError):
            fw.update_reclaimable_pods(wl, {"main": 5})
        fw.update_reclaimable_pods(wl, {"main": 1})
        with pytest.raises(webhooks.ValidationError):
            fw.update_reclaimable_pods(wl, {"main": 0})  # shrink while reserved

    def test_conforming_workload_admitted_with_defaults(self):
        fw = self._fw()
        fw.create_limit_range(LimitRange(
            namespace="default",
            items=[LimitRangeItem(type="Container",
                                  default_request={CPU: cpuq(1)})]))
        pt = PodTemplate(containers=[Container.make()])
        wl = Workload(name="defaulted", queue_name="lq",
                      pod_sets=[PodSet(name="main", count=1, template=pt)])
        fw.submit(wl)
        fw.run_until_settled()
        assert wl.has_quota_reservation
        assert wl.admission.pod_set_assignments[0].resource_usage[CPU] \
            == cpuq(1)

    def test_late_runtime_class_revalidates_parked_workload(self):
        """A RuntimeClass created after submit mutates pod templates in
        place (overhead); the nomination-time validation memo must not
        keep serving the pre-overhead verdict (scheduler.go
        validateLimitRange would now reject the pod total)."""
        fw = self._fw()
        fw.create_limit_range(LimitRange(
            namespace="default",
            items=[LimitRangeItem(type="Pod", max={CPU: cpuq(2)})]))
        pt = PodTemplate(containers=[Container.make(requests={CPU: 2})],
                         runtime_class_name="gvisor")
        wl = Workload(name="w", queue_name="lq",
                      pod_sets=[PodSet(name="main", count=1, template=pt)])
        fw.submit(wl)
        # First nomination: pod total 2 <= max 2 — validation passes (and
        # memoizes); keep it pending by oversubscribing the request later.
        assert fw._validate_workload_resources(wl) == []
        # Overhead pushes the pod total to 2.25 > max 2.
        fw.create_runtime_class("gvisor", {CPU: cpuq("250m")})
        reasons = fw._validate_workload_resources(wl)
        assert reasons, "overhead must re-trigger the LimitRange max gate"
        fw.run_until_settled()
        assert not wl.has_quota_reservation
