"""Event-driven admission: dirty-cohort micro-ticks + eager encode.

The tentpole contract (PR 15): between full ticks, a micro-tick solves
ONLY the cohorts dirtied since the last tick — flat cohorts are
solve-independent, hierarchical/split roots defer to the full tick —
pinned by linearizability-style invariants (no oversubscription, no
unjournaled take-backs, per-CQ FIFO) instead of byte identity, with
KUEUE_TPU_NO_MICROTICK=1 restoring the barrier-paced trail exactly. The
replica half: a worker blocked behind a slow sibling keeps admitting its
own flat cohorts via micro-ticks and predispatches its next tick's
encode (eager encode), abandoned whenever a state-changing message
lands first.
"""

import os
import time

import pytest

from kueue_tpu.api.types import (
    ClusterQueue,
    CohortSpec,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    Workload,
)
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.metrics import REGISTRY
from kueue_tpu.models.flavor_fit import BatchSolver


def build_fw(num_cqs=4, quota=8, cohort_of=None, depth=1, solver=True,
             cohort_specs=()):
    fw = Framework(batch_solver=BatchSolver() if solver else None,
                   pipeline_depth=depth)
    fw.create_resource_flavor(ResourceFlavor.make("default"))
    for spec in cohort_specs:
        fw.create_cohort(spec)
    for c in range(num_cqs):
        fw.create_cluster_queue(ClusterQueue(
            name=f"cq-{c}",
            cohort=cohort_of(c) if cohort_of else "",
            resource_groups=(ResourceGroup(
                ("cpu",), (FlavorQuotas.make("default", cpu=quota),)),)))
        fw.create_local_queue(LocalQueue(
            name=f"lq-{c}", namespace="default", cluster_queue=f"cq-{c}"))
    return fw


def submit(fw, name, lq, cpu=2, ts=1.0, priority=0):
    wl = Workload(name=name, queue_name=lq, creation_time=ts,
                  priority=priority,
                  pod_sets=[PodSet.make("main", count=1, cpu=cpu)])
    fw.submit(wl)
    return wl


def usage_cpu(fw, cq_name):
    return fw.cache.cluster_queues[cq_name].usage.get(
        "default", {}).get("cpu", 0)


class TestMicrotick:
    def test_submit_admits_without_a_tick(self):
        fw = build_fw(cohort_of=lambda c: f"pool-{c % 2}")
        submit(fw, "w0", "lq-0")
        assert fw.microtick() == 1
        assert usage_cpu(fw, "cq-0") == 2000
        assert fw.scheduler.metrics.microticks >= 1
        assert fw.scheduler.metrics.micro_admitted == 1

    def test_kill_switch_makes_it_a_noop(self, monkeypatch):
        monkeypatch.setenv("KUEUE_TPU_NO_MICROTICK", "1")
        fw = build_fw()
        submit(fw, "w0", "lq-0")
        assert fw.microtick() == 0
        assert usage_cpu(fw, "cq-0") == 0
        monkeypatch.delenv("KUEUE_TPU_NO_MICROTICK")
        # Marks survived the disabled call; the next tick admits.
        assert fw.tick() == 1

    def test_explain_reason_names_the_dirty_event(self):
        fw = build_fw()
        submit(fw, "w0", "lq-0")
        fw.microtick()
        rec = fw.scheduler.explain.last_decision("default/w0")
        assert rec["outcome"] == "Admitted"
        assert rec["reason"] == "admitted: micro-tick (submit w0)"

    def test_metrics_counters_move(self):
        before = REGISTRY.microticks_total.get()
        fw = build_fw()
        submit(fw, "w0", "lq-0")
        fw.microtick()
        assert REGISTRY.microticks_total.get() == before + 1
        assert REGISTRY.microtick_latency_seconds.totals.get((), 0) >= 1

    def test_hierarchical_roots_defer_to_the_full_tick(self):
        specs = (CohortSpec(name="leaf", parent="root"),
                 CohortSpec(name="root"))
        fw = build_fw(cohort_of=lambda c: "leaf", cohort_specs=specs)
        submit(fw, "w0", "lq-0")
        assert fw.microtick() == 0          # split/hier roots park
        assert usage_cpu(fw, "cq-0") == 0
        assert fw.tick() == 1               # the full tick admits

    def test_referee_mode_microticks_too(self):
        fw = build_fw(solver=False)
        submit(fw, "w0", "lq-0")
        assert fw.microtick() == 1

    def test_deep_burst_drains_in_one_call(self):
        fw = build_fw(num_cqs=2, quota=32)
        for i in range(6):
            submit(fw, f"w{i}", "lq-0", cpu=2, ts=float(i))
        # One head pops per CQ per round; the drain loop keeps going
        # while admissions flow.
        assert fw.microtick() == 6
        assert usage_cpu(fw, "cq-0") == 12000

    def test_fifo_within_cq_and_no_oversubscription(self):
        fw = build_fw(num_cqs=2, quota=8, cohort_of=lambda c: "pool")
        order = []
        orig = fw.scheduler.apply_admission

        def apply(wl):
            ok = orig(wl)
            if ok:
                order.append(wl.name)
            return ok

        fw.scheduler.apply_admission = apply
        for i in range(10):
            submit(fw, f"w{i:02d}", "lq-0", cpu=2, ts=float(i))
            fw.microtick()
        # Quota 8 cpu per CQ, 16 in the flat pool: never oversubscribed
        # at milli resolution...
        total = usage_cpu(fw, "cq-0") + usage_cpu(fw, "cq-1")
        assert total <= 16000
        # ...and the admitted prefix is exactly FIFO within the CQ.
        assert order == sorted(order)

    def test_pipelined_full_ticks_interleaved_with_microticks(self):
        """Micro admissions land between pipelined dispatch and finish:
        the staleness re-validation must catch them (never overadmit)."""
        fw = build_fw(num_cqs=4, quota=8, depth=4)
        for i in range(6):
            for c in range(4):
                submit(fw, f"wl-{c}-{i}", f"lq-{c}", cpu=2,
                       ts=float(i * 4 + c))
        fw.tick()                     # dispatch in flight at depth 4
        for c in range(4):
            submit(fw, f"burst-{c}", f"lq-{c}", cpu=2, ts=100.0 + c)
        fw.microtick()                # commits under the in-flight solve
        fw.run_until_settled(max_ticks=80)
        for c in range(4):
            assert usage_cpu(fw, f"cq-{c}") <= 8000

    def test_quiescent_goldens_unaffected_by_standing_marks(self):
        """A full tick's heads sweep consumes standing dirty marks, so
        micro-disabled deployments accumulate nothing."""
        fw = build_fw()
        submit(fw, "w0", "lq-0")
        assert fw.queues.has_dirty_cohorts()
        fw.tick()
        assert not fw.queues.has_dirty_cohorts()

    def test_stage_spans_and_device_lane_in_trace(self):
        from kueue_tpu.tracing import DEVICE_LANE, TRACER

        TRACER.reset()
        TRACER.configure(enabled=True)
        try:
            fw = build_fw()
            for c in range(4):
                submit(fw, f"w{c}", f"lq-{c}", ts=float(c))
            fw.tick()
            doc = TRACER.export_chrome()
        finally:
            TRACER.configure(enabled=False)
            TRACER.reset()
        names = {ev["name"] for ev in doc["traceEvents"]}
        assert "tick.stage.ingest" in names
        assert "tick.stage.encode" in names
        assert "tick.stage.flush" in names
        solve = [ev for ev in doc["traceEvents"]
                 if ev["name"] == "tick.stage.solve"]
        assert solve and all(ev["tid"] == DEVICE_LANE for ev in solve)


class TestDirtyCohortRouting:
    def test_quota_release_marks_the_cohort(self):
        fw = build_fw(num_cqs=2, quota=4, cohort_of=lambda c: "pool")
        a = submit(fw, "a", "lq-0", cpu=4, ts=1.0)
        submit(fw, "b", "lq-1", cpu=4, ts=2.0)
        fw.run_until_settled(max_ticks=20)
        assert not fw.queues.has_dirty_cohorts()
        # b parked NoFit?  quota 4 each + flat pool: both fit.  Fill it:
        submit(fw, "c", "lq-0", cpu=4, ts=3.0)
        fw.run_until_settled(max_ticks=20)
        assert usage_cpu(fw, "cq-0") == 4000
        # Finishing `a` flushes the cohort -> dirty -> micro admits c.
        fw.finish(a)
        assert fw.queues.has_dirty_cohorts()
        assert fw.microtick() == 1
        assert usage_cpu(fw, "cq-0") == 4000

    def test_drain_returns_latest_event_and_clears(self):
        fw = build_fw()
        submit(fw, "w0", "lq-0")
        marks = fw.queues.drain_dirty_cohorts()
        assert marks and not fw.queues.has_dirty_cohorts()
        assert any(ev.startswith("submit") for ev in marks.values())


class TestReplicaBarrierStall:
    """Satellite 4: one laggard must no longer pace everyone's
    throughput. Worker 1's flat cohorts admit via micro-ticks the moment
    arrivals land, while worker 0 sleeps inside every barrier tick; and
    fast workers predispatch their next tick's encode at the barrier."""

    def _cluster(self, rt, n_cqs=4):
        rt.create_resource_flavor(ResourceFlavor.make("default"))
        for c in range(n_cqs):
            rt.create_cluster_queue(ClusterQueue(
                name=f"cq-{c}", cohort=f"pool-{c}",
                resource_groups=(ResourceGroup(
                    ("cpu",),
                    (FlavorQuotas.make("default", cpu=64),)),)))
            rt.create_local_queue(LocalQueue(
                name=f"lq-{c}", namespace="default",
                cluster_queue=f"cq-{c}"))

    def _drive(self, micro: bool, barriers=3, per_round=4):
        from kueue_tpu.controllers.replica_runtime import ReplicaRuntime

        rt = ReplicaRuntime(2, spawn=False, engine="host",
                            microtick=micro, drill_slow={0: 0.05})
        try:
            self._cluster(rt)
            rt.tick()
            seq = [0]
            t0 = time.perf_counter()
            for _ in range(barriers):
                for _ in range(per_round):
                    seq[0] += 1
                    for c in range(4):
                        rt.submit(Workload(
                            name=f"w-{c}-{seq[0]}", queue_name=f"lq-{c}",
                            creation_time=float(seq[0]),
                            pod_sets=[PodSet.make("m", count=1, cpu=1)]))
                time.sleep(0.15)   # let workers drain + micro-tick
                rt.tick()
            wall = time.perf_counter() - t0
            dump = rt.dump()
            admitted = sum(len(v) for v in dump["admitted"].values())
            return admitted, wall, rt.stats_last
        finally:
            rt.close()

    def test_throughput_no_longer_barrier_paced(self):
        # Micro OFF: each barrier admits ONE head per CQ -> 3 barriers
        # admit ~3 per CQ of the 12 queued.
        admitted_off, _, _ = self._drive(micro=False)
        # Micro ON: every arrival admits between barriers -> all 48.
        admitted_on, _, stats = self._drive(micro=True)
        assert admitted_off <= 4 * 4   # barrier-paced (one/CQ/barrier +1)
        assert admitted_on == 4 * 3 * 4  # everything, laggard or not
        assert stats["micro_admitted"] > 0

    def test_eager_encode_uses_the_barrier_idle_window(self):
        from kueue_tpu.controllers.replica_runtime import ReplicaRuntime

        rt = ReplicaRuntime(2, spawn=False, engine="host")
        try:
            self._cluster(rt)
            # Deep per-CQ backlog: consecutive barriers with NO messages
            # in between keep every predispatch valid.
            for i in range(6):
                for c in range(4):
                    rt.submit(Workload(
                        name=f"w-{c}-{i}", queue_name=f"lq-{c}",
                        creation_time=float(i * 4 + c),
                        pod_sets=[PodSet.make("m", count=1, cpu=1)]))
            time.sleep(0.1)
            used = abandoned = 0
            for _ in range(7):
                s = rt.tick()
                used += s["predispatch"][0]
                abandoned += s["predispatch"][1]
            assert used > 0
            dump = rt.dump()
            assert sum(len(v) for v in dump["admitted"].values()) == 24
        finally:
            rt.close()

    def test_eager_encode_abandons_on_new_state(self):
        """A message between barriers invalidates the predispatch — the
        decisions stay byte-identical to the lazy path."""
        from kueue_tpu.controllers.replica_runtime import ReplicaRuntime

        def drive(eager):
            rt = ReplicaRuntime(2, spawn=False, engine="host",
                                eager_encode=eager)
            try:
                self._cluster(rt)
                trail = []
                used = 0
                for i in range(8):
                    for c in range(4):
                        rt.submit(Workload(
                            name=f"w-{c}-{i}", queue_name=f"lq-{c}",
                            creation_time=float(i * 4 + c),
                            pod_sets=[PodSet.make("m", count=1, cpu=1)]))
                    time.sleep(0.05)
                    s = rt.tick()
                    used += s["predispatch"][0]
                    trail.append(tuple(sorted(s["admitted"])))
                return trail, rt.dump()["admitted"], used
            finally:
                rt.close()

        trail_eager, final_eager, used = drive(True)
        trail_lazy, final_lazy, _ = drive(False)
        assert trail_eager == trail_lazy
        assert final_eager == final_lazy
        # Every predispatch was invalidated by the submit batches.
        assert used == 0


class TestMicrotickScopeBudget:
    def test_overflow_cohorts_hand_back_to_the_full_tick(self):
        from kueue_tpu.scheduler.scheduler import Scheduler

        n = Scheduler.MICROTICK_MAX_CQS + 8
        fw = build_fw(num_cqs=n, quota=8)
        for c in range(n):
            submit(fw, f"w{c}", f"lq-{c}", ts=float(c))
        admitted = fw.microtick()
        assert 0 < admitted <= Scheduler.MICROTICK_MAX_CQS
        # The overflow was re-marked; a second micro (or the tick)
        # finishes the job.
        admitted += fw.microtick()
        admitted += fw.tick()
        assert admitted == n
