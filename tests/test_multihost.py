"""Multi-host runtime drills: coordinator fail-over mid-window, the
barrier stall watchdog, journal replication across per-host state dirs,
elastic replica scaling + capacity loaning, and the host-lane trace
merge (kueue_tpu/transport/ + the replica runtime's multi-host wiring).
"""

import json
import os
import signal
import tempfile
import time

import pytest

from kueue_tpu import features
from kueue_tpu.controllers.replica_runtime import ReplicaRuntime
from kueue_tpu.metrics import REGISTRY
from kueue_tpu.transport import BarrierStallError, ElasticController

from tests.test_replica import _lending_world, _split_pair
from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg


def _flat_world(rt, n_cqs=4, cpu=4):
    rt.create_resource_flavor(make_flavor("default"))
    for i in range(n_cqs):
        rt.create_cluster_queue(make_cq(
            f"cq-{i}", rg("cpu", fq("default", cpu=cpu))))
        rt.create_local_queue(make_lq(f"lq-{i}", "default", cq=f"cq-{i}"))


def _settle(rt, ticks=4):
    for _ in range(ticks):
        rt.tick()


# -- coordinator fail-over ---------------------------------------------------


def test_coordinator_failover_replays_journaled_round(tmp_path):
    """Kill the coordinator MID-WINDOW at the worst moment: after it
    arbitrated and journaled a round with real split-root candidates,
    before any replica heard a verdict. The newly elected incarnation
    (epoch bump via lease transitions) must replay the journaled
    verdicts and resume the barrier — and the admitted set must match
    the single-process decision."""
    from kueue_tpu.config import Configuration, TPUSolverConfig
    from kueue_tpu.controllers.runtime import Framework
    from kueue_tpu.models.flavor_fit import BatchSolver

    features.set_enabled(features.LENDING_LIMIT, True)
    ca, cb = _split_pair(2)

    fw = Framework(batch_solver=BatchSolver(), config=Configuration(
        tpu_solver=TPUSolverConfig(preemption_engine="host")),
        pipeline_depth=1)
    fw.create_namespace("default", labels={})
    _lending_world(fw, ca, cb)
    fw.submit(make_wl("wa", "lq-a", cpu=8, creation_time=1.0))
    fw.submit(make_wl("wb", "lq-b", cpu=8, creation_time=2.0))
    fw.run_until_settled(max_ticks=6)
    single = tuple(sorted(
        fw.admitted_workloads("cq-a") + fw.admitted_workloads("cq-b")))
    assert len(single) == 1

    rt = ReplicaRuntime(2, spawn=False, engine="host",
                        state_dir=str(tmp_path / "state"))
    try:
        _lending_world(rt, ca, cb)
        assert "hroot" in rt.gmap.split_roots
        rt.submit(make_wl("wa", "lq-a", cpu=8, creation_time=1.0))
        rt.submit(make_wl("wb", "lq-b", cpu=8, creation_time=2.0))
        epoch_before = rt.coordinator.epoch
        rt.kill_coordinator()  # dies inside the NEXT round
        for _ in range(6):
            rt.tick()
        ev = rt.failover_evidence
        assert ev is not None
        assert ev["epoch_after"] > epoch_before == ev["epoch_before"]
        # The interrupted round carried the two borrowers' candidates,
        # and the new incarnation REPLAYED their journaled verdicts.
        assert ev["candidates"] >= 2
        assert ev["replayed_verdicts"] >= 2
        assert rt.coordinator.replayed_verdicts >= 2
        dump = rt.dump()
        winners = tuple(sorted(dump["admitted"].get("cq-a", [])
                               + dump["admitted"].get("cq-b", [])))
        assert winners == single
        # The coordinator journal shows the same round under two epochs
        # (the takeover's audit trail).
        with open(rt.coordinator.journal_path) as f:
            entries = [json.loads(line) for line in f if line.strip()]
        by_round = {}
        for e in entries:
            by_round.setdefault(e["round"], set()).add(e["epoch"])
        assert any(len(eps) > 1 for eps in by_round.values()), by_round
    finally:
        rt.close()


def test_coordinator_failover_without_journal_recomputes(tmp_path):
    """No state dir -> no verdict journal: the takeover recomputes the
    round from the shipped absolute usage (the coordinator is
    restart-safe by construction) and the contract check still holds."""
    rt = ReplicaRuntime(2, spawn=False, engine="host")
    try:
        _flat_world(rt)
        for i in range(4):
            rt.submit(make_wl(f"w-{i}", f"lq-{i}", cpu=3,
                              creation_time=float(i)))
        rt.kill_coordinator()
        _settle(rt)
        assert rt.failover_evidence is not None
        assert rt.failover_evidence["epoch_after"] \
            > rt.failover_evidence["epoch_before"]
        admitted = rt.dump()["admitted"]
        assert sum(len(v) for v in admitted.values()) == 4
    finally:
        rt.close()


# -- barrier stall watchdog --------------------------------------------------


def test_worker_side_coordinator_stall_raises(monkeypatch):
    """A replica blocked on verdicts past the deadline raises a
    BarrierStallError naming itself and the round — today's silent
    forever-block, surfaced."""
    import queue

    from kueue_tpu.controllers.replica_runtime import (
        ReplicaWorker,
        _QueueChan,
    )

    monkeypatch.setenv("KUEUE_TPU_BARRIER_DEADLINE", "0.1")
    to_worker: "queue.Queue" = queue.Queue()
    to_parent: "queue.Queue" = queue.Queue()
    worker = ReplicaWorker(0, {"solver": False, "n_groups": 1},
                           _QueueChan(to_parent, to_worker))
    with pytest.raises(BarrierStallError) as exc_info:
        worker._submit_round({"candidates": [], "usage": {}})
    err = exc_info.value
    assert err.who == "coordinator"
    assert err.pid == os.getpid()
    assert err.phase == "verdicts"
    assert "round" in str(err) and "deadline" in str(err)


@pytest.mark.slow
def test_sigstopped_worker_surfaces_stall_and_recovers(tmp_path):
    """REGRESSION for today's silent stall: a SIGSTOPped worker used to
    hold the barrier to the timeout and then its journal flocks forever
    (adoption retried silently every tick). Now the watchdog surfaces a
    BarrierStallError with the offending pid + round, kills the stalled
    process so the flocks clear, and the groups fail over."""
    stalled_errors = []
    rt = ReplicaRuntime(2, spawn=True, engine="host",
                        state_dir=str(tmp_path / "state"))
    rt.round_timeout = 5.0
    rt.on_stall = stalled_errors.append
    try:
        _flat_world(rt, n_cqs=3, cpu=4)
        for i in range(3):
            rt.submit(make_wl(f"a-{i}", f"lq-{i}", cpu=3,
                              creation_time=float(i)))
            rt.submit(make_wl(f"b-{i}", f"lq-{i}", cpu=3,
                              creation_time=float(10 + i)))
        _settle(rt, 3)
        before = rt.dump()["admitted"]
        assert sum(len(v) for v in before.values()) == 3
        victim = rt.workers[0]
        os.kill(victim.pid, signal.SIGSTOP)
        stalls_before = REGISTRY.replica_barrier_stalls_total.get(
            str(victim.wid))
        stats = rt.tick()
        assert stats["stalls"], "the stall never surfaced"
        stall = stats["stalls"][0]
        assert stall["pid"] == victim.pid
        assert stall["round"] == rt.tick_no
        assert stall["who"] == "replica"
        assert stalled_errors and isinstance(
            stalled_errors[0], BarrierStallError)
        assert REGISTRY.replica_barrier_stalls_total.get(
            str(victim.wid)) == stalls_before + 1
        assert rt.stall_count >= 1
        # Recovery: the stalled process was killed (flocks cleared) and
        # its groups adopted — the admitted set survives intact.
        _settle(rt, 4)
        after = rt.dump()["admitted"]
        assert after == before
        assert all(owner != victim.wid
                   for owner in rt.group_owner.values())
    finally:
        rt.close()


# -- per-host journals + replication -----------------------------------------


def test_per_host_journals_replicate_to_coordinator(tmp_path):
    """Per-host mode: each replica journals in its OWN host dir; the
    coordinator's replica copies mirror them line for line through the
    async segment stream — no shared filesystem between hosts."""
    state = str(tmp_path / "state")
    rt = ReplicaRuntime(2, spawn=False, engine="host", state_dir=state,
                        transport="socket")
    try:
        assert rt.per_host and rt.replicator is not None
        _flat_world(rt)
        for i in range(4):
            rt.submit(make_wl(f"w-{i}", f"lq-{i}", cpu=3,
                              creation_time=float(i)))
        _settle(rt)
        rt.replicator.flush()
        host_dirs = sorted(d for d in os.listdir(state)
                           if d.startswith("host-"))
        assert host_dirs == ["host-0", "host-1"]
        mirrored = 0
        for wid, host in enumerate(host_dirs):
            for fn in sorted(os.listdir(os.path.join(state, host))):
                if not fn.endswith(".jsonl"):
                    continue
                local = os.path.join(state, host, fn)
                gid = int(fn[len("journal-g"):-len(".jsonl")])
                with open(local) as f:
                    local_lines = [ln.rstrip("\n") for ln in f
                                   if ln.strip()]
                assert rt.replicator.read_lines(gid) == local_lines, \
                    f"replica copy of {fn} diverged"
                mirrored += 1
        assert mirrored == rt.n_groups
        assert rt.replicator.applied_lines > 0
    finally:
        rt.close()


def test_backlog_gauge_and_dumper_reconcile_info():
    """Satellite: the per-shard-group backlog gauge feeds from the
    barrier replies, and the SIGUSR2 Dumper carries the reconcile
    round + coordinator epoch + backlog depth."""
    from kueue_tpu.controllers.debugger import Dumper

    rt = ReplicaRuntime(2, spawn=False, engine="host")
    try:
        _flat_world(rt, n_cqs=4, cpu=4)
        for i in range(4):
            for j in range(3):  # 1 fits, 2 wait per CQ
                rt.submit(make_wl(f"w-{i}-{j}", f"lq-{i}", cpu=3,
                                  creation_time=float(i * 10 + j)))
        _settle(rt, 3)
        assert rt.backlog_last, "no backlog reported"
        assert sum(rt.backlog_last.values()) == 8  # 2 waiting per CQ
        for gid, depth in rt.backlog_last.items():
            assert REGISTRY.replica_backlog_depth.get(str(gid)) \
                == float(depth)
        dump = Dumper(reconcile=rt.reconcile_info).dump()
        rec = dump["reconcile"]
        assert rec["round"] == rt.coordinator.rounds
        assert rec["epoch"] == rt.coordinator.epoch >= 1
        assert rec["backlogDepth"] \
            == {str(g): n for g, n in rt.backlog_last.items()}
        assert REGISTRY.reconcile_round_epoch.get() \
            == float(rt.coordinator.epoch)
    finally:
        rt.close()


# -- elastic scaling + capacity loaning --------------------------------------


def test_elastic_scale_up_loan_and_scale_down(tmp_path):
    """The Aryl loop end to end on the socket transport: scale N->N+1
    under load (group migrated to the new replica), capacity LOANED
    from an idle replica to a loaded one, loans returned + scale down
    to N once drained — with the decision set complete and the
    post-resettle steady window dispatching ZERO solves (the
    quiescent-tick discipline survives every migration)."""
    rt = ReplicaRuntime(2, spawn=False, engine="host",
                        state_dir=str(tmp_path / "state"),
                        transport="socket", n_groups=4)
    ctl = ElasticController(rt, scale_up_backlog=3, idle_backlog=0,
                            loan_min_backlog=2, min_replicas=2,
                            max_replicas=3, cooldown_ticks=0)
    try:
        _flat_world(rt, n_cqs=8, cpu=2)
        # Load EVERY group deeply -> scale-up fires first.
        keys = []
        for i in range(8):
            for j in range(4):
                key = f"w-{i}-{j}"
                keys.append((f"default/{key}", f"cq-{i}"))
                rt.submit(make_wl(key, f"lq-{i}", cpu=2,
                                  creation_time=float(i * 100 + j)))
        stats = rt.tick()
        actions = []
        for _ in range(30):
            # Churn: finish everything admitted so the backlog drains
            # and the DOWN half of the loop gets its turn.
            done = [(k, cq) for k, cq in stats["admitted"]]
            if done:
                rt.finish_many(done)
            act = ctl.step(rt.backlog_last)
            if act:
                actions.append(act)
            stats = rt.tick()
        assert any(a.startswith("scale-up") for a in actions), actions
        assert any(a.startswith("scale-down") or a.startswith("return")
                   for a in actions), actions
        assert len(rt.workers) == 3  # the elastic worker was created
        # Post-resettle steady window: everything drained, every tick
        # must dispatch zero solves.
        _settle(rt, 2)
        for _ in range(3):
            stats = rt.tick()
            assert stats["dispatches"] == 0, \
                f"steady tick dispatched solves after elastic churn: {stats}"
        # Nothing lost across all the migrations: every workload was
        # admitted exactly once (finish_many consumed them).
        assert sum(rt.dump()["pending"].values()) == 0
    finally:
        rt.close()


def test_capacity_loan_moves_group_to_idle_replica(tmp_path):
    """The loan in isolation: one replica drowning, one idle -> the
    controller migrates the deepest group onto the idle replica and
    RETURNS it home once drained."""
    rt = ReplicaRuntime(2, spawn=False, engine="host", n_groups=4)
    ctl = ElasticController(rt, scale_up_backlog=10_000, idle_backlog=0,
                            loan_min_backlog=2, min_replicas=2,
                            max_replicas=2, cooldown_ticks=0)
    try:
        _flat_world(rt, n_cqs=8, cpu=2)
        # Load ONLY worker 0's groups.
        loaded = [i for i in range(8)
                  if rt.group_owner[rt.gmap.cq_group[f"cq-{i}"]] == 0]
        assert loaded, "hash landed every cq on worker 1; world too small"
        for i in loaded:
            for j in range(4):
                rt.submit(make_wl(f"w-{i}-{j}", f"lq-{i}", cpu=2,
                                  creation_time=float(i * 100 + j)))
        stats = rt.tick()
        act = ctl.step(rt.backlog_last)
        assert act is not None and act.startswith("loan"), act
        gid = int(act.split()[1][1:])
        assert rt.group_owner[gid] == 1  # moved to the idle replica
        assert ctl.loans == {gid: 0}
        # Drain the loaned group's backlog -> the loan returns home.
        for _ in range(24):
            done = [(k, cq) for k, cq in stats["admitted"]]
            if done:
                rt.finish_many(done)
            stats = rt.tick()
            act = ctl.step(rt.backlog_last)
            if act and act.startswith("return"):
                break
        assert act == f"return g{gid}->w0", act
        assert rt.group_owner[gid] == 0
        assert not ctl.loans
    finally:
        rt.close()


def test_migrate_group_preserves_admitted_set(tmp_path):
    """A live migration moves a group's ENTIRE vertical slice (admitted
    quota re-accounted via journal replay, pending re-queued) without
    changing a single decision."""
    rt = ReplicaRuntime(2, spawn=False, engine="host",
                        state_dir=str(tmp_path / "state"), n_groups=2)
    try:
        _flat_world(rt, n_cqs=4, cpu=4)
        for i in range(4):
            rt.submit(make_wl(f"a-{i}", f"lq-{i}", cpu=3,
                              creation_time=float(i)))
            rt.submit(make_wl(f"b-{i}", f"lq-{i}", cpu=3,
                              creation_time=float(10 + i)))
        _settle(rt, 3)
        before = rt.dump()
        gid = rt.gmap.cq_group["cq-0"]
        assert rt.migrate_group(gid, 1 - rt.group_owner[gid])
        _settle(rt, 2)
        after = rt.dump()
        assert after["admitted"] == before["admitted"]
        assert after["pending"] == before["pending"]
        # Finishing a migrated admitted workload still releases quota on
        # the adopter: its waiting twin admits.
        rt.finish("default/a-0", cq="cq-0")
        _settle(rt, 3)
        assert rt.dump()["admitted"]["cq-0"] == ["default/b-0"]
    finally:
        rt.close()


# -- host-lane trace merge ---------------------------------------------------


def test_merged_trace_host_lanes_and_skew_clamped_flows():
    """Satellite: merged Chrome traces label every process lane with its
    host id, and the reconcile flow arrows survive cross-host clock
    rebasing — an epoch skew that would point an arrow backwards in
    merged time is clamped, never dropped."""
    from kueue_tpu.tracing import merge_chrome_traces, validate_chrome_trace

    def doc(epoch, events):
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"tracer": "kueue-tpu", "enabled": True,
                              "ticks_retained": 1, "epoch_unix": epoch}}

    rtt = {"name": "admit.reconcile.rtt", "ph": "X", "ts": 1000.0,
           "dur": 500.0, "pid": 1, "tid": 2, "cat": "kueue",
           "args": {"round": 1}}
    rnd = {"name": "reconcile.round", "ph": "X", "ts": 1100.0,
           "dur": 100.0, "pid": 1, "tid": 3, "cat": "kueue",
           "args": {"round": 1}}
    # The replica host's clock runs 10ms AHEAD of the coordinator's:
    # naive rebasing would start the flow after its finish.
    merged = merge_chrome_traces([
        (100, "coordinator", doc(1000.0, [rnd]), "host-coordinator"),
        (200, "replica-0", doc(1000.010, [rtt]), "host-0"),
    ])
    assert validate_chrome_trace(merged) == []
    labels = {e["pid"]: e["args"]["labels"]
              for e in merged["traceEvents"]
              if e.get("name") == "process_labels"}
    assert labels == {100: "host-coordinator", 200: "host-0"}
    names = {e["args"]["name"] for e in merged["traceEvents"]
             if e.get("name") == "process_name"}
    assert names == {"coordinator @host-coordinator",
                     "replica-0 @host-0"}
    assert merged["otherData"]["hosts"] == ["host-coordinator", "host-0"]
    flows = {e["ph"]: e for e in merged["traceEvents"]
             if e.get("ph") in ("s", "f")}
    assert set(flows) == {"s", "f"}
    assert flows["s"]["ts"] <= flows["f"]["ts"], \
        "flow arrow points backwards after rebasing"
    # 3-tuple docs (no host) still merge — the PR 9 call sites.
    legacy = merge_chrome_traces([(1, "solo", doc(0.0, []))])
    assert validate_chrome_trace(legacy) == []
    assert legacy["otherData"]["hosts"] == []


def test_runtime_merged_trace_carries_host_lanes():
    """The loopback runtime's own export rides the same path: the
    coordinator lane is host-labeled and the doc validates."""
    from kueue_tpu.tracing import TRACER, validate_chrome_trace

    TRACER.reset()
    TRACER.configure(enabled=True)
    try:
        rt = ReplicaRuntime(2, spawn=False, engine="host")
        try:
            _flat_world(rt, n_cqs=2)
            rt.submit(make_wl("w", "lq-0", cpu=2, creation_time=1.0))
            _settle(rt, 2)
            doc = rt.export_chrome()
        finally:
            rt.close()
    finally:
        TRACER.configure(enabled=False)
        TRACER.reset()
    assert validate_chrome_trace(doc) == []
    assert doc["otherData"]["hosts"] == ["host-coordinator"]
