"""MultiKueue depth tests: cluster lifecycle with reconnect backoff,
MultiKueueConfig scoping, batch-job adapter sync, orphan GC.

Mirrors reference test/integration/multikueue/ (two in-process frameworks
simulate manager + worker clusters, like the two-envtest-apiserver setup).
"""

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    Workload,
)
from kueue_tpu.controllers.multikueue import (
    BatchJobAdapter,
    InProcessRemote,
    MultiKueueCluster,
    MultiKueueConfig,
    MultiKueueController,
)
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.jobs.batch_job import BatchJob


def make_cluster_fw(cpu=10):
    fw = Framework()
    fw.create_resource_flavor(ResourceFlavor.make("default"))
    fw.create_cluster_queue(ClusterQueue(
        name="cq",
        resource_groups=(ResourceGroup(
            covered_resources=("cpu",),
            flavors=(FlavorQuotas.make("default", cpu=cpu),)),)))
    fw.create_local_queue(LocalQueue(
        name="main", namespace="default", cluster_queue="cq"))
    return fw


def make_manager(check="mk"):
    mgr = Framework()
    mgr.create_resource_flavor(ResourceFlavor.make("default"))
    mgr.create_cluster_queue(ClusterQueue(
        name="cq",
        resource_groups=(ResourceGroup(
            covered_resources=("cpu",),
            flavors=(FlavorQuotas.make("default", cpu=100),)),),
        admission_checks=(check,)))
    mgr.create_local_queue(LocalQueue(
        name="main", namespace="default", cluster_queue="cq"))
    return mgr


class TestClusterLifecycle:
    def test_factory_connect_with_backoff(self):
        clock = [1000.0]
        mgr = Framework(clock=lambda: clock[0])
        worker = make_cluster_fw()
        attempts = []

        fail_until = [3]

        def factory(spec):
            attempts.append(spec.name)
            if len(attempts) < fail_until[0]:
                return None
            return InProcessRemote(worker)

        ctl = MultiKueueController(mgr, client_factory=factory)
        ctl.add_cluster_spec(MultiKueueCluster(name="w1"))

        ctl.reconcile_clusters()
        spec = ctl.cluster_specs["w1"]
        assert not spec.active and spec.failed_connection_attempts == 1
        first_deadline = spec.next_reconnect_at
        assert first_deadline == 1000.0 + 5.0

        # Before the backoff deadline: no new attempt.
        clock[0] = 1002.0
        ctl.reconcile_clusters()
        assert len(attempts) == 1

        # After: second attempt fails, backoff doubles.
        clock[0] = 1006.0
        ctl.reconcile_clusters()
        assert len(attempts) == 2
        assert spec.next_reconnect_at == 1006.0 + 10.0

        # Third attempt succeeds; Active condition flips.
        clock[0] = 1017.0
        ctl.reconcile_clusters()
        assert spec.active and spec.active_reason == "Active"
        assert spec.failed_connection_attempts == 0
        assert "w1" in ctl.clusters


class TestConfigScoping:
    def test_dispatch_only_to_configured_clusters(self):
        mgr = make_manager()
        w1, w2 = make_cluster_fw(), make_cluster_fw()
        ctl = MultiKueueController(mgr, check_name="mk")
        ctl.add_cluster("w1", InProcessRemote(w1))
        ctl.add_cluster("w2", InProcessRemote(w2))
        ctl.add_config(MultiKueueConfig(name="cfg", clusters=("w2",)))
        ctl.check_configs["mk"] = "cfg"

        wl = Workload(name="w", queue_name="main",
                      pod_sets=[PodSet.make("main", 1, cpu=2)])
        mgr.submit(wl)
        mgr.run_until_settled()
        ctl.reconcile()
        assert "default/w" not in w1.workloads
        assert "default/w" in w2.workloads


class TestBatchJobAdapter:
    def test_remote_job_runs_and_finishes_local(self):
        mgr = make_manager()
        worker = make_cluster_fw()
        remote_client = InProcessRemote(worker)
        ctl = MultiKueueController(mgr, check_name="mk")
        ctl.add_cluster("w1", remote_client)
        ctl.register_adapter("batch", BatchJobAdapter())

        job = BatchJob(name="train", queue_name="main", parallelism=2,
                       requests={"cpu": 1})
        wl = mgr.submit_job(job)
        mgr.run_until_settled()
        assert wl.has_quota_reservation and not wl.is_admitted
        ctl.reconcile()

        # Remote job mirrored onto the worker and bound to the mirror wl.
        assert "default/train" in remote_client.jobs
        worker.run_until_settled()
        ctl.reconcile()
        mgr.run_until_settled()
        assert wl.is_admitted  # check flipped Ready -> two-phase admitted

        # Remote progress flows back; remote finish finishes local.
        remote_job = remote_client.jobs["default/train"]
        remote_job.ready_pods = 2
        ctl.reconcile()
        assert job.ready_pods == 2
        remote_job.succeeded = 2
        worker.run_until_settled()
        ctl.reconcile()
        assert wl.is_finished


class TestOrphanGC:
    def test_remote_orphans_deleted(self):
        mgr = make_manager()
        worker = make_cluster_fw()
        client = InProcessRemote(worker)
        ctl = MultiKueueController(mgr, check_name="mk")
        ctl.add_cluster("w1", client)

        wl = Workload(name="w", queue_name="main",
                      pod_sets=[PodSet.make("main", 1, cpu=2)])
        mgr.submit(wl)
        mgr.run_until_settled()
        ctl.reconcile()
        assert "default/w" in worker.workloads

        # The local workload disappears (user deletion): next reconcile
        # garbage-collects the remote mirror.
        mgr.delete_workload(wl)
        ctl.reconcile()
        assert "default/w" not in worker.workloads


class TestPermanentRejection:
    def test_all_workers_reject_sets_check_rejected(self):
        """A permanent 4xx-style rejection (RemoteRejected) must not be
        retried every pass; once every worker rejects, the check goes
        Rejected with the worker's message (ADVICE r2, low #4)."""
        from kueue_tpu.controllers.multikueue import RemoteRejected

        class RejectingRemote(InProcessRemote):
            def __init__(self, fw):
                super().__init__(fw)
                self.create_calls = 0

            def create_workload(self, wl):
                self.create_calls += 1
                raise RemoteRejected("webhook denied: podSets invalid")

        mgr = make_manager()
        worker = make_cluster_fw()
        remote = RejectingRemote(worker)
        ctl = MultiKueueController(mgr, check_name="mk")
        ctl.add_cluster("w1", remote)

        wl = Workload(name="w", queue_name="main",
                      pod_sets=[PodSet.make("main", 1, cpu=2)])
        mgr.submit(wl)
        mgr.run_until_settled()
        ctl.reconcile()
        state = wl.admission_check_states["mk"]
        assert state.state == "Rejected"
        assert "webhook denied" in state.message
        assert remote.create_calls == 1

        # Further passes must not re-POST the permanently-invalid mirror.
        ctl.reconcile()
        ctl.reconcile()
        assert remote.create_calls == 1

    def test_one_worker_rejects_other_wins(self):
        """A rejection on one worker doesn't block dispatch to others."""
        from kueue_tpu.controllers.multikueue import RemoteRejected

        class RejectingRemote(InProcessRemote):
            def create_workload(self, wl):
                raise RemoteRejected("denied")

        mgr = make_manager()
        w1, w2 = make_cluster_fw(), make_cluster_fw()
        ctl = MultiKueueController(mgr, check_name="mk")
        ctl.add_cluster("w1", RejectingRemote(w1))
        ctl.add_cluster("w2", InProcessRemote(w2))

        wl = Workload(name="w", queue_name="main",
                      pod_sets=[PodSet.make("main", 1, cpu=2)])
        mgr.submit(wl)
        mgr.run_until_settled()
        ctl.reconcile()
        w2.run_until_settled()
        ctl.reconcile()
        assert wl.admission_check_states["mk"].state == "Ready"

    def test_rejection_with_disconnected_worker_not_permanent(self):
        """One rejecting worker + one transiently disconnected worker must
        NOT mark the check Rejected: the disconnected worker might accept
        after its reconnect (denominator = configured set, not live set)."""
        from kueue_tpu.controllers.multikueue import RemoteRejected

        class RejectingRemote(InProcessRemote):
            def create_workload(self, wl):
                raise RemoteRejected("denied")

        mgr = make_manager()
        w1, w2 = make_cluster_fw(), make_cluster_fw()
        down = InProcessRemote(w2)
        down.set_connected(False)
        ctl = MultiKueueController(mgr, check_name="mk")
        ctl.add_cluster("w1", RejectingRemote(w1))
        ctl.add_cluster("w2", down)

        wl = Workload(name="w", queue_name="main",
                      pod_sets=[PodSet.make("main", 1, cpu=2)])
        mgr.submit(wl)
        mgr.run_until_settled()
        ctl.reconcile()
        state = wl.admission_check_states.get("mk")
        assert state is None or state.state != "Rejected"

        # w2 comes back: dispatch proceeds and the check goes Ready.
        down.set_connected(True)
        ctl.reconcile()
        w2.run_until_settled()
        ctl.reconcile()
        assert wl.admission_check_states["mk"].state == "Ready"
