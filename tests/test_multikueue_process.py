"""Cross-process MultiKueue: manager dispatches to a worker SUBPROCESS.

The round-1 gap was that MultiKueue only worked against in-process
remotes. This test is the reference's two-cluster integration scenario
(test/integration/multikueue/) with a real process boundary: the worker
is `python -m kueue_tpu --serve --port 0` in its own interpreter, the
manager talks to it through `HTTPRemote` (watch-based mirroring over the
chunked watch stream), the batch job is synced through the wire with the
prebuilt-workload binding, remote completion flows back, and the remote
mirror is garbage-collected.
"""

import re
import subprocess
import sys
import time

import pytest

from kueue_tpu.api.types import (
    AdmissionCheck,
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    ResourceGroup,
)
from kueue_tpu.controllers.multikueue import (
    BatchJobAdapter,
    MultiKueueController,
)
from kueue_tpu.controllers.multikueue_remote import HTTPRemote
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.jobs.batch_job import BatchJob

WORKER_SETUP = """\
apiVersion: kueue.x-k8s.io/v1beta1
kind: ResourceFlavor
metadata:
  name: default
---
apiVersion: kueue.x-k8s.io/v1beta1
kind: ClusterQueue
metadata:
  name: worker-cq
spec:
  namespaceSelector: {}
  resourceGroups:
  - coveredResources: ["cpu"]
    flavors:
    - name: default
      resources:
      - name: cpu
        nominalQuota: 8
---
apiVersion: kueue.x-k8s.io/v1beta1
kind: LocalQueue
metadata:
  name: main
  namespace: default
spec:
  clusterQueue: worker-cq
"""


@pytest.fixture(scope="module")
def worker():
    """Spawn a worker cluster as a separate interpreter."""
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                     delete=False) as fh:
        fh.write(WORKER_SETUP)
        setup_path = fh.name
    import os
    proc = subprocess.Popen(
        [sys.executable, "-m", "kueue_tpu", "--serve", "--port", "0",
         "--tick-interval", "0.05", "--objects", setup_path],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stderr=subprocess.PIPE, stdout=subprocess.DEVNULL, text=True)
    url = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stderr.readline()
        m = re.search(r"serving HTTP API on (http://\S+)", line or "")
        if m:
            url = m.group(1)
            break
        if proc.poll() is not None:
            raise RuntimeError("worker subprocess died during startup")
    assert url, "worker never reported its URL"
    try:
        yield url
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def make_manager():
    mgr = Framework()
    mgr.create_resource_flavor(ResourceFlavor.make("default"))
    mgr.create_admission_check(AdmissionCheck(
        name="mk", controller_name="kueue.x-k8s.io/multikueue"))
    mgr.create_cluster_queue(ClusterQueue(
        name="cq",
        resource_groups=(ResourceGroup(
            covered_resources=("cpu",),
            flavors=(FlavorQuotas.make("default", cpu=100),)),),
        admission_checks=("mk",)))
    mgr.create_local_queue(LocalQueue(
        name="main", namespace="default", cluster_queue="cq"))
    return mgr


def spin(mgr, ctl, predicate, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        mgr.tick()
        ctl.reconcile()
        if predicate():
            return True
        time.sleep(0.05)
    return False


class TestCrossProcessDispatch:
    def test_dispatch_run_finish_gc(self, worker):
        mgr = make_manager()
        ctl = MultiKueueController(mgr, check_name="mk")
        client = HTTPRemote(worker, queue_name="main")
        ctl.add_cluster("w1", client)
        ctl.register_adapter("batch", BatchJobAdapter())

        job = BatchJob(name="xjob", queue_name="main", parallelism=2,
                       requests={"cpu": 1})
        wl = mgr.submit_job(job)
        assert wl is not None

        # Quota reserved locally, mirrored remotely, remote reserves ->
        # check flips Ready -> local workload admitted.
        assert spin(mgr, ctl, lambda: wl.is_admitted), \
            "workload never got admitted via the remote reservation"
        state = wl.admission_check_states["mk"]
        assert state.state == "Ready"
        assert 'reservation on "w1"' in state.message

        # The job was synced through the wire and bound to the mirror.
        assert client.get_job("default", "xjob") is not None

        # Remote completion flows back: complete the remote job over HTTP.
        client._request(
            "POST", "/apis/batch/v1/namespaces/default/jobs/xjob/complete",
            {})
        assert spin(mgr, ctl, lambda: wl.is_finished), \
            "remote completion never propagated"

        # GC: the remote mirror is deleted once the dispatch is done.
        deadline = time.time() + 15
        while time.time() < deadline:
            if client.get_status(wl.key) is None \
                    and not client.list_workload_keys():
                break
            ctl.reconcile()
            time.sleep(0.05)
        assert client.get_status(wl.key) is None
        client.close()

    def test_watch_mirror_is_live(self, worker):
        """get_status is served from the watch mirror (not a per-call GET)
        once the stream connects — the reference's watch-based mirroring."""
        client = HTTPRemote(worker, queue_name="main")
        assert client.connected()
        deadline = time.time() + 10
        while time.time() < deadline and not client._watch_live.is_set():
            time.sleep(0.05)
        assert client._watch_live.is_set()
        client.close()

    def test_worker_lost_then_requeued(self, worker):
        """An unreachable worker trips the lost-timeout path and resets
        the dispatch with a Retry check state
        (multikueuecluster.go workerLostTimeout)."""
        clock = [1000.0]
        mgr = make_manager()
        mgr.clock = lambda: clock[0]
        ctl = MultiKueueController(mgr, check_name="mk",
                                   worker_lost_timeout=60.0)
        dead = HTTPRemote("http://127.0.0.1:1", watch=False, timeout=0.2)
        live = HTTPRemote(worker, queue_name="main")
        ctl.add_cluster("w1", live)

        job = BatchJob(name="lostjob", queue_name="main", parallelism=1,
                       requests={"cpu": 1})
        wl = mgr.submit_job(job)
        assert spin(mgr, ctl, lambda: wl.is_admitted)

        # Swap the live client for a dead one: worker lost.
        ctl.clusters["w1"] = dead
        ctl.reconcile()
        clock[0] += 61.0
        ctl.reconcile()
        assert wl.admission_check_states["mk"].state == "Retry"
        live.delete_workload(wl.key)
        live.close()
        dead.close()
