"""The native decision decoder (kueue_tpu/native/decode.cpp) must produce
object trees identical to the pure-Python decode on randomized problems —
same Assignment/PodSetAssignmentResult/FlavorAssignment fields, same usage
maps, same resume state."""

import pytest

from kueue_tpu.models.flavor_fit import (
    BatchSolver,
    _decode_assignments_py,
    decode_assignments,
    device_static,
    solve_flavor_fit,
)
from kueue_tpu.solver import schema as sch
from kueue_tpu.utils import native_decode

from tests.test_solver_equivalence import random_problem

pytestmark = pytest.mark.skipif(
    not native_decode.decode_available(),
    reason="native decoder unavailable (no toolchain)")


def _norm(a):
    return (
        [(ps.name, dict(ps.requests), ps.count, list(ps.reasons), ps.error,
          {r: (fa.name, fa.mode, fa.tried_flavor_idx, fa.borrow)
           for r, fa in ps.flavors.items()})
         for ps in a.pod_sets],
        a.borrowing,
        a.usage,
        (a.last_state.cluster_queue_generation,
         a.last_state.cohort_generation,
         a.last_state.last_tried_flavor_idx),
    )


@pytest.mark.parametrize("seed", range(8))
def test_native_matches_python_decode(seed):
    cache, pending = random_problem(seed, num_cqs=5, num_flavors=3,
                                    num_wls=32)
    snapshot = cache.snapshot()
    enc = sch.encode_cluster_queues(snapshot)
    usage = sch.encode_usage(snapshot, enc)
    wt = sch.encode_workloads(pending, snapshot, enc)
    out = solve_flavor_fit(enc, usage, wt, static=device_static(enc))

    native = decode_assignments(pending, snapshot, enc, out)
    python = _decode_assignments_py(pending, snapshot, enc, out)
    assert len(native) == len(python) == len(pending)
    for i, (x, y) in enumerate(zip(native, python)):
        assert _norm(x) == _norm(y), f"workload {i} (seed {seed})"


def test_native_decode_objects_survive_gc():
    import gc
    cache, pending = random_problem(3, num_cqs=3, num_flavors=2, num_wls=16)
    snapshot = cache.snapshot()
    assignments = BatchSolver().solve(pending, snapshot)
    gc.collect()
    # Objects built by the extension must be fully initialized, GC-tracked
    # Python objects: attribute access and mutation behave normally.
    for a in assignments:
        for ps in a.pod_sets:
            ps.reasons = list(ps.reasons)
        assert a.representative_mode in (0, 1, 2)
    gc.collect()
