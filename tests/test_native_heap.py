"""Native C++ keyed heap: parity with the Python heap + microbench sanity.

The native heap (utils/native_heap.py over native/heap.cpp) must order and
mutate identically to utils/heap.KeyedHeap under the pending-queue ordering
contract (priority desc, timestamp asc).
"""

import random
import time

import pytest

from kueue_tpu.utils import native_heap
from kueue_tpu.utils.heap import KeyedHeap

pytestmark = pytest.mark.skipif(
    not native_heap.native_available(), reason="no native toolchain")


class Item:
    def __init__(self, key, priority, ts):
        self.key = key
        self.priority = priority
        self.ts = ts

    def __repr__(self):
        return f"Item({self.key}, p={self.priority}, t={self.ts})"


def make_pair():
    py = KeyedHeap(
        key_fn=lambda it: it.key,
        less=lambda a, b: (a.priority > b.priority
                           or (a.priority == b.priority and a.ts <= b.ts)))
    nat = native_heap.NativeKeyedHeap(
        key_fn=lambda it: it.key,
        sort_key_fn=lambda it: (-it.priority, int(it.ts * 1e9)),
        key_len=2)
    return py, nat


class TestParity:
    def test_basic_order(self):
        py, nat = make_pair()
        items = [Item("a", 0, 3.0), Item("b", 5, 9.0), Item("c", 0, 1.0),
                 Item("d", 5, 2.0)]
        for it in items:
            py.push_if_not_present(it)
            nat.push_if_not_present(it)
        order_py = [py.pop().key for _ in range(4)]
        order_nat = [nat.pop().key for _ in range(4)]
        assert order_py == order_nat == ["d", "b", "c", "a"]

    def test_update_reorders(self):
        _, nat = make_pair()
        a, b = Item("a", 0, 1.0), Item("b", 0, 2.0)
        nat.push_if_not_present(a)
        nat.push_if_not_present(b)
        assert nat.peek().key == "a"
        b.priority = 10
        nat.push_or_update(b)
        assert nat.peek().key == "b"

    def test_delete_and_contains(self):
        _, nat = make_pair()
        a = Item("a", 0, 1.0)
        nat.push_if_not_present(a)
        assert "a" in nat and len(nat) == 1
        assert nat.delete("a").key == "a"
        assert "a" not in nat and len(nat) == 0
        assert nat.delete("a") is None
        assert nat.pop() is None

    def test_randomized_pop_order_parity(self):
        rnd = random.Random(7)
        py, nat = make_pair()
        live = {}
        for step in range(3000):
            op = rnd.random()
            if op < 0.55 or not live:
                key = f"k{rnd.randrange(800)}"
                it = Item(key, rnd.randrange(5),
                          round(rnd.uniform(0, 100), 6))
                if key in live:
                    live[key] = it
                    py.push_or_update(it)
                    nat.push_or_update(it)
                else:
                    live[key] = it
                    py.push_if_not_present(it)
                    nat.push_if_not_present(it)
            elif op < 0.75:
                key = rnd.choice(list(live))
                del live[key]
                assert (py.delete(key) is None) == (nat.delete(key) is None)
            else:
                a, b = py.pop(), nat.pop()
                # Ties on (priority, ts) may legitimately order differently;
                # compare sort keys, not identities.
                assert (a.priority, a.ts) == (b.priority, b.ts)
                # Keep both heaps consistent: remove whichever the other
                # popped too.
                if a.key != b.key:
                    py.delete(b.key)
                    nat.delete(a.key)
                    live.pop(b.key, None)
                live.pop(a.key, None)
        while True:
            a, b = py.pop(), nat.pop()
            assert (a is None) == (b is None)
            if a is None:
                break
            assert (a.priority, a.ts) == (b.priority, b.ts)
            if a.key != b.key:
                py.delete(b.key)
                nat.delete(a.key)


class TestSpeed:
    def test_native_faster_at_scale(self):
        n = 20000
        items = [Item(f"k{i}", random.randrange(10), random.random())
                 for i in range(n)]
        py, nat = make_pair()
        t0 = time.perf_counter()
        for it in items:
            py.push_if_not_present(it)
        while py.pop() is not None:
            pass
        t_py = time.perf_counter() - t0
        t0 = time.perf_counter()
        for it in items:
            nat.push_if_not_present(it)
        while nat.pop() is not None:
            pass
        t_nat = time.perf_counter() - t0
        # The native heap should never be slower than Python at 20k items.
        assert t_nat < t_py, (t_nat, t_py)
