"""Fingerprinted nominate cache: replay correctness + invalidation.

The solver caches each head's verdict keyed on a usage-dependency
fingerprint (BatchSolver._fingerprints); a head whose fingerprint is
unchanged skips tensorize+solve+decode and replays. These tests pin the
invalidation edge cases the fingerprint must catch — every event below
must force a re-solve (and the re-solve must land the NEW decision):

  * quota release in the head's cohort (usage generation),
  * ClusterQueue quota edit (structural rotation),
  * cohort membership change (structural rotation),
  * delete_resource_flavor (structural rotation -> CQ inactive).

The 200-tick randomized churn differential in tests/test_arena.py pins
cache-vs-recompute decision-trail identity wholesale; these are the
targeted per-event regressions.
"""

from kueue_tpu.api.types import PodSet, Workload
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.models.flavor_fit import BatchSolver

from tests.util import fq, make_cq, make_flavor, make_lq, rg


def _fw(*cqs):
    fw = Framework(batch_solver=BatchSolver())
    fw.create_namespace("default", labels={})
    fw.create_resource_flavor(make_flavor("on-demand"))
    for name, groups, cohort in cqs:
        fw.create_cluster_queue(make_cq(name, *groups, cohort=cohort,
                                        strategy="StrictFIFO"))
        fw.create_local_queue(make_lq(f"lq-{name}", "default", cq=name))
    return fw


def _pend(fw, name, lq, cpu, **kw):
    wl = Workload(name=name, namespace="default", queue_name=lq,
                  priority=0, creation_time=kw.pop("creation_time", 1.0),
                  pod_sets=[PodSet.make("ps0", count=1, cpu=cpu)])
    fw.submit(wl)
    return wl


def _settle_cached(fw, ticks=6):
    """Tick until the head replays from the cache; returns the solver."""
    solver = fw.scheduler.batch_solver
    for _ in range(ticks):
        fw.tick()
    h0 = solver.nominate_cache_hits
    fw.tick()
    assert solver.nominate_cache_hits > h0, \
        "head never reached the replay steady state"
    return solver


def test_cache_replays_and_usage_release_invalidates():
    fw = _fw(("cq", [rg("cpu", fq("on-demand", cpu=4))], ""))
    blocker = _pend(fw, "blocker", "lq-cq", cpu=4)
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/blocker"]
    waiter = _pend(fw, "waiter", "lq-cq", cpu=4, creation_time=2.0)
    solver = _settle_cached(fw)
    m0 = solver.nominate_cache_misses
    # Quota release bumps the cohort usage generation: the waiter must
    # re-solve (miss) and admit.
    fw.finish(blocker)
    fw.delete_workload(blocker)
    fw.run_until_settled()
    assert solver.nominate_cache_misses > m0
    assert waiter.is_admitted


def test_cluster_queue_quota_edit_invalidates():
    fw = _fw(("cq", [rg("cpu", fq("on-demand", cpu=2))], ""))
    waiter = _pend(fw, "waiter", "lq-cq", cpu=4)
    solver = _settle_cached(fw)
    m0 = solver.nominate_cache_misses
    # Quota edit: structural mutation -> encoding rotation -> the cached
    # NoFit verdict must NOT replay against the larger quota.
    fw.update_cluster_queue(make_cq(
        "cq", rg("cpu", fq("on-demand", cpu=8)), strategy="StrictFIFO"))
    fw.run_until_settled()
    assert solver.nominate_cache_misses > m0
    assert waiter.is_admitted


def test_cohort_membership_change_invalidates():
    fw = _fw(
        ("cq-a", [rg("cpu", fq("on-demand", cpu=2))], ""),
        ("cq-b", [rg("cpu", fq("on-demand", cpu=8))], "co"),
    )
    waiter = _pend(fw, "waiter", "lq-cq-a", cpu=4)
    solver = _settle_cached(fw)
    m0 = solver.nominate_cache_misses
    # Joining the cohort opens borrowing from cq-b's idle quota: the
    # cached solo-CQ NoFit must not replay.
    fw.update_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("on-demand", cpu=2)), cohort="co",
        strategy="StrictFIFO"))
    fw.run_until_settled()
    assert solver.nominate_cache_misses > m0
    assert waiter.is_admitted
    assert waiter.admission.cluster_queue == "cq-a"


def test_delete_resource_flavor_invalidates():
    fw = _fw(("cq", [rg("cpu", fq("on-demand", cpu=2))], ""))
    waiter = _pend(fw, "waiter", "lq-cq", cpu=4)
    _settle_cached(fw)
    # Deleting the flavor deactivates the CQ (missing flavor): the next
    # attempt must surface the inactive verdict, not the cached
    # insufficient-quota one.
    fw.delete_resource_flavor("on-demand")
    for _ in range(3):
        fw.tick()
    cond = waiter.find_condition("QuotaReserved")
    assert cond is not None and not cond.status
    assert "inactive" in cond.message
