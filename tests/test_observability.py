"""Metrics, visibility API and the state dumper."""

import json

from kueue_tpu.controllers.debugger import Dumper
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.controllers.visibility import VisibilityServer
from kueue_tpu.metrics import REGISTRY, Histogram, Registry

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg


def small_framework(quota_cpu=2):
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq("cq", rg("cpu", fq("default", cpu=quota_cpu))))
    fw.create_local_queue(make_lq("main", cq="cq"))
    return fw


def test_admission_metrics_count():
    fw = small_framework(quota_cpu=2)
    before = REGISTRY.admitted_workloads_total.get("cq")
    fw.submit(make_wl("w0", cpu=1))
    fw.submit(make_wl("w1", cpu=1))
    fw.submit(make_wl("w2", cpu=1))  # won't fit
    fw.run_until_settled()
    assert REGISTRY.admitted_workloads_total.get("cq") - before == 2
    fw.update_metrics_gauges()
    assert REGISTRY.pending_workloads.get("cq", "inadmissible") == 1
    assert REGISTRY.reserving_active_workloads.get("cq") == 2
    assert REGISTRY.cluster_queue_resource_usage.get("cq", "default", "cpu") == 2000


def test_export_text_format():
    fw = small_framework()
    fw.submit(make_wl("w", cpu=1))
    fw.run_until_settled()
    text = REGISTRY.export_text()
    assert "# TYPE kueue_admitted_workloads_total counter" in text
    assert 'kueue_admitted_workloads_total{cluster_queue="cq"}' in text
    assert "# TYPE kueue_admission_attempt_duration_seconds histogram" in text


def test_histogram_percentile():
    h = Histogram("x", "test", buckets=(0.01, 0.1, 1.0))
    for v in [0.005] * 90 + [0.5] * 10:
        h.observe(value=v)
    assert h.percentile(0.5) == 0.01
    assert h.percentile(0.99) == 1.0


def test_visibility_positions():
    fw = small_framework(quota_cpu=0)
    fw.create_local_queue(make_lq("other", cq="cq"))
    fw.submit(make_wl("low", priority=0, creation_time=1.0))
    fw.submit(make_wl("high", priority=5, creation_time=2.0))
    fw.submit(make_wl("other-wl", "other", priority=0, creation_time=3.0))
    vis = VisibilityServer(fw.queues)
    pending = vis.pending_workloads_in_cq("cq")
    assert [p.name for p in pending] == ["high", "low", "other-wl"]
    assert [p.position_in_cluster_queue for p in pending] == [0, 1, 2]
    assert pending[2].position_in_local_queue == 0
    by_lq = vis.pending_workloads_in_lq("default", "main")
    assert [p.name for p in by_lq] == ["high", "low"]


def test_visibility_includes_inadmissible():
    fw = small_framework(quota_cpu=1)
    fw.submit(make_wl("fits", cpu=1, creation_time=1.0))
    fw.submit(make_wl("parked", cpu=1, creation_time=2.0))
    fw.run_until_settled()
    vis = VisibilityServer(fw.queues)
    pending = vis.pending_workloads_in_cq("cq")
    assert [p.name for p in pending] == ["parked"]


def test_dumper_roundtrip():
    fw = small_framework(quota_cpu=1)
    fw.submit(make_wl("running", cpu=1, creation_time=1.0))
    fw.submit(make_wl("waiting", cpu=1, creation_time=2.0))
    fw.run_until_settled()
    dump = json.loads(Dumper(fw.cache, fw.queues).dump_json())
    assert dump["cache"]["cq"]["admittedWorkloads"] == ["default/running"]
    assert dump["cache"]["cq"]["usage"]["default"]["cpu"] == 1000
    assert dump["queues"]["cq"]["inadmissible"] == ["default/waiting"]


def test_gauges_pruned_on_cq_delete():
    fw = small_framework()
    fw.submit(make_wl("w", cpu=1))
    fw.run_until_settled()
    fw.update_metrics_gauges()
    assert REGISTRY.cluster_queue_resource_usage.get("cq", "default", "cpu") == 1000
    fw.delete_cluster_queue("cq")
    assert REGISTRY.cluster_queue_resource_usage.get("cq", "default", "cpu") == 0
    assert ("cq", "active") not in REGISTRY.pending_workloads.values


def test_fragmentation_gauge_pruned_on_flavor_delete():
    """A deleted ResourceFlavor's topology_fragmentation and per-(cq,
    flavor) series must stop exporting — stale series previously lived
    until process exit (the flavor delete path never pruned)."""
    from kueue_tpu.api.types import ResourceFlavor, TopologySpec

    fw = Framework()
    tpu = ResourceFlavor.make("tpu", topology=TopologySpec.uniform(
        ("block", "rack"), (1, 2), leaf_capacity=2))
    fw.create_resource_flavor(tpu)
    fw.create_cluster_queue(make_cq("cq", rg("cpu", fq("tpu", cpu=4))))
    fw.create_local_queue(make_lq("main", cq="cq"))
    fw.submit(make_wl("w", cpu=1))
    fw.run_until_settled()
    fw.update_metrics_gauges()
    assert ("tpu", "block") in REGISTRY.topology_fragmentation.values
    assert REGISTRY.cluster_queue_resource_usage.get("cq", "tpu", "cpu") \
        == 1000
    fw.delete_resource_flavor("tpu")
    assert ("tpu", "block") not in REGISTRY.topology_fragmentation.values
    assert ("tpu", "rack") not in REGISTRY.topology_fragmentation.values
    assert ("cq", "tpu", "cpu") \
        not in REGISTRY.cluster_queue_resource_usage.values


def test_flavor_delete_via_store_prunes(tmp_path):
    """The StoreAdapter routes a ResourceFlavor DELETE into the prune path
    (it previously ignored flavor deletions entirely)."""
    from kueue_tpu.controllers.store import KIND_RESOURCE_FLAVOR, Store, \
        StoreAdapter
    from tests.util import make_flavor as mf

    fw = Framework()
    store = Store()
    StoreAdapter(store, fw)
    store.create(KIND_RESOURCE_FLAVOR, mf("default"))
    assert "default" in fw.cache.resource_flavors
    store.delete(KIND_RESOURCE_FLAVOR, "default")
    assert "default" not in fw.cache.resource_flavors


def test_quota_gauges_pruned_on_cq_delete_even_without_knob():
    """Series set while metrics.enableClusterQueueResources was on must
    die with their CQ even if the knob is off at delete time."""
    REGISTRY.cluster_queue_borrowing_limit.set(
        "co", "doomed-cq", "default", "cpu", value=1.0)
    REGISTRY.cluster_queue_resource_reservation.set(
        "co", "doomed-cq", "default", "cpu", value=2.0)
    fw = small_framework()
    fw.delete_cluster_queue("cq")
    # Unrelated CQ series survive a delete of another CQ.
    assert REGISTRY.cluster_queue_borrowing_limit.get(
        "co", "doomed-cq", "default", "cpu") == 1.0
    fw.create_cluster_queue(make_cq(
        "doomed-cq", rg("cpu", fq("default", cpu=1)), cohort="co"))
    fw.delete_cluster_queue("doomed-cq")
    assert ("co", "doomed-cq", "default", "cpu") \
        not in REGISTRY.cluster_queue_borrowing_limit.values
    assert ("co", "doomed-cq", "default", "cpu") \
        not in REGISTRY.cluster_queue_resource_reservation.values


def test_event_recorder_counts_drops_and_reports_occupancy():
    from kueue_tpu.events import EventRecorder

    rec = EventRecorder(capacity=3)
    before = REGISTRY.events_dropped_total.get()
    for i in range(5):
        rec.event(f"default/w{i}", "Normal", "QuotaReserved", "m", now=1.0)
    assert rec.occupancy == 3
    assert rec.dropped == 2
    assert REGISTRY.events_dropped_total.get() - before == 2
    # Dumper surfaces the recorder's occupancy/drop accounting.
    fw = small_framework()
    dump = Dumper(fw.cache, fw.queues, events=rec).dump()
    assert dump["events"] == {"occupancy": 3, "capacity": 3, "dropped": 2}


def test_eviction_metrics_all_reasons():
    from kueue_tpu.config import Configuration, WaitForPodsReady
    from tests.test_pods_ready import FakeClock
    clock = FakeClock()
    fw = Framework(config=Configuration(
        wait_for_pods_ready=WaitForPodsReady(enable=True, timeout_seconds=10.0,
                                             block_admission=False)), clock=clock)
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq("cq", rg("cpu", fq("default", cpu=4))))
    fw.create_local_queue(make_lq("main", cq="cq"))
    before = REGISTRY.evicted_workloads_total.get("cq", "PodsReadyTimeout")
    fw.submit(make_wl("w", cpu=1))
    fw.run_until_settled()
    clock.now += 11.0
    fw.reconcile()
    assert REGISTRY.evicted_workloads_total.get("cq", "PodsReadyTimeout") - before == 1


def test_readmission_wait_time_measured():
    from kueue_tpu.api.types import ClusterQueuePreemption
    from tests.test_pods_ready import FakeClock
    clock = FakeClock()
    fw = Framework(clock=clock)
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=2)),
        preemption=ClusterQueuePreemption(within_cluster_queue="LowerPriority")))
    fw.create_local_queue(make_lq("main", cq="cq"))
    low = make_wl("low", cpu=2, priority=-1, creation_time=clock.now)
    fw.submit(low)
    fw.run_until_settled()
    high = make_wl("high", cpu=2, priority=5, creation_time=clock.now)
    fw.submit(high)
    fw.run_until_settled()
    assert low.is_evicted
    # high finishes 100s later; low waits that long from its eviction.
    clock.now += 100.0
    fw.finish(high)
    hist = REGISTRY.admission_wait_time_seconds
    count_before = hist.totals.get(("cq",), 0)
    fw.run_until_settled()
    assert low.is_admitted
    assert hist.totals[("cq",)] == count_before + 1
    # The new observation is ~100s (bucketed between 60 and 300).
    assert hist.percentile(1.0, "cq") >= 60


def test_queue_visibility_snapshots_gated_and_throttled():
    """The CQ-status snapshot workers (clusterqueue_controller.go:685-720):
    feature-gated, top-N capped, updated on the configured cadence."""
    from kueue_tpu import features
    from kueue_tpu.config import Configuration, QueueVisibility

    clock = [100.0]
    cfg = Configuration(queue_visibility=QueueVisibility(
        max_count=2, update_interval_seconds=5.0))
    fw = Framework(config=cfg, clock=lambda: clock[0])
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq("cq", rg("cpu", fq("default", cpu=0))))
    fw.create_local_queue(make_lq("main", cq="cq"))
    # cpu=1 against zero quota: all stay pending forever.
    for i, prio in enumerate((5, 3, 1)):
        fw.submit(make_wl(f"w{i}", priority=prio, creation_time=float(i),
                          cpu=1))

    with features.override(features.QUEUE_VISIBILITY, False):
        fw.tick()
        assert fw.queue_visibility.snapshot("cq") == []  # gated off

    with features.override(features.QUEUE_VISIBILITY, True):
        fw.tick()
        snap = fw.queue_visibility.snapshot("cq")
        live = VisibilityServer(fw.queues).pending_workloads_in_cq(
            "cq", limit=2)
        assert len(snap) == 2  # top-N capped at maxCount
        assert [p.name for p in snap] == [p.name for p in live]
        # A new arrival inside the interval is not published yet.
        fw.submit(make_wl("w9", priority=9, creation_time=50.0, cpu=1))
        clock[0] += 1.0
        fw.tick()
        assert [p.name for p in fw.queue_visibility.snapshot("cq")] \
            == [p.name for p in snap]  # stale view within the interval
        clock[0] += 5.0
        fw.tick()
        names = {p.name for p in fw.queue_visibility.snapshot("cq")}
        assert "w9" in names  # refreshed after the interval


def test_multikueue_gc_interval_and_origin_label():
    """Remote-orphan GC runs on the configured cadence and only touches
    mirrors carrying this manager's origin label."""
    from kueue_tpu.api.types import PodSet, Workload
    from kueue_tpu.config import Configuration, MultiKueueConfig
    from kueue_tpu.controllers.multikueue import (
        ORIGIN_LABEL,
        InProcessRemote,
        MultiKueueController,
    )

    clock = [1000.0]
    cfg = Configuration(multikueue=MultiKueueConfig(
        gc_interval_seconds=30.0, origin="mgr-a"))
    mgr = Framework(config=cfg, clock=lambda: clock[0])
    mgr.create_resource_flavor(make_flavor("default"))
    mgr.create_cluster_queue(make_cq("cq", rg("cpu", fq("default", cpu=8))))
    mgr.create_local_queue(make_lq("main", cq="cq"))

    worker = Framework()
    worker.create_resource_flavor(make_flavor("default"))
    worker.create_cluster_queue(make_cq("cq", rg("cpu", fq("default", cpu=8))))
    worker.create_local_queue(make_lq("main", cq="cq"))

    client = InProcessRemote(worker)
    ctl = MultiKueueController(mgr, check_name="mk")
    ctl.add_cluster("w1", client)
    assert ctl.origin == "mgr-a" and ctl.gc_interval == 30.0
    assert client.origin == "mgr-a"

    # An orphan mirror with our origin label but no local dispatch (e.g.
    # left over from before a manager restart).
    orphan = Workload(name="orphan", queue_name="main",
                      labels={ORIGIN_LABEL: "mgr-a"},
                      pod_sets=[PodSet.make("main", 1, cpu=1)])
    worker.submit(orphan)
    # A foreign mirror owned by another manager: must never be touched.
    foreign = Workload(name="foreign", queue_name="main",
                       labels={ORIGIN_LABEL: "mgr-b"},
                       pod_sets=[PodSet.make("main", 1, cpu=1)])
    worker.submit(foreign)

    ctl.reconcile()  # first pass: GC due immediately
    assert "default/orphan" not in worker.workloads
    assert "default/foreign" in worker.workloads

    # Within the interval, a new orphan survives; after it, collected.
    orphan2 = Workload(name="orphan2", queue_name="main",
                       labels={ORIGIN_LABEL: "mgr-a"},
                       pod_sets=[PodSet.make("main", 1, cpu=1)])
    worker.submit(orphan2)
    clock[0] += 10.0
    ctl.reconcile()
    assert "default/orphan2" in worker.workloads
    clock[0] += 30.0
    ctl.reconcile()
    assert "default/orphan2" not in worker.workloads


def test_tick_phase_histogram_observed():
    """Every tick records snapshot/nominate/admit/requeue phase timings;
    the batched solver additionally records tensorize/device_solve/decode
    (SURVEY §5 TPU-build observability additions)."""
    from kueue_tpu.metrics import REGISTRY
    from kueue_tpu.models.flavor_fit import BatchSolver

    fw = Framework(batch_solver=BatchSolver())
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq("cq", rg("cpu", fq("default", cpu=4))))
    fw.create_local_queue(make_lq("main", cq="cq"))
    fw.submit(make_wl("w", cpu=1))
    fw.tick()
    phases = {labels[0] for labels in REGISTRY.tick_phase_seconds.totals}
    assert {"snapshot", "nominate", "admit", "requeue",
            "tensorize", "device_solve", "decode"} <= phases
    assert "kueue_tick_phase_seconds" in REGISTRY.export_text()


def test_optional_quota_gauges():
    """metrics.enableClusterQueueResources gates the three per-CQ quota
    gauges (reference metrics.go:137-177): borrowing/lending limits from
    the spec, reservation from reserved usage, reference label order
    (cohort, cq, flavor, resource); lending only under the feature gate."""
    from kueue_tpu import features
    from kueue_tpu.config import Configuration, MetricsConfig

    fw = Framework(config=Configuration(
        metrics=MetricsConfig(enable_cluster_queue_resources=True)))
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=(4, 2, 3))), cohort="co"))
    fw.create_local_queue(make_lq("main", cq="cq"))
    fw.submit(make_wl("w0", cpu=1))
    fw.run_until_settled()
    fw.update_metrics_gauges()
    assert REGISTRY.cluster_queue_borrowing_limit.get(
        "co", "cq", "default", "cpu") == 2000
    assert REGISTRY.cluster_queue_resource_reservation.get(
        "co", "cq", "default", "cpu") == 1000
    if features.enabled(features.LENDING_LIMIT):
        assert REGISTRY.cluster_queue_lending_limit.get(
            "co", "cq", "default", "cpu") == 3000
    # Gauges prune when the ClusterQueue goes away.
    fw.delete_cluster_queue("cq")
    fw.update_metrics_gauges()
    assert REGISTRY.cluster_queue_borrowing_limit.get(
        "co", "cq", "default", "cpu") in (None, 0)


def test_quota_gauges_absent_without_knob():
    fw = small_framework()
    fw.submit(make_wl("wq", cpu=1))
    fw.run_until_settled()
    fw.update_metrics_gauges()
    assert not REGISTRY.cluster_queue_resource_reservation.values
