"""Pipelined scheduling (depth > 1): the production async-dispatch path.

Verifies the optimistic-concurrency contract: with up to N ticks'
device solves in flight, stale FIT decisions are re-validated at
completion and never overadmit, and the drained end-state matches the
synchronous (reference-equivalent) mode.
"""

import pytest

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    Workload,
)
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.models.flavor_fit import BatchSolver


def build_fw(depth, num_cqs=4, quota=8, cohort=""):
    fw = Framework(batch_solver=BatchSolver(), pipeline_depth=depth)
    fw.create_resource_flavor(ResourceFlavor.make("default"))
    for c in range(num_cqs):
        fw.create_cluster_queue(ClusterQueue(
            name=f"cq-{c}",
            cohort=cohort,
            resource_groups=(ResourceGroup(
                ("cpu",), (FlavorQuotas.make("default", cpu=quota),)),)))
        fw.create_local_queue(LocalQueue(
            name=f"lq-{c}", namespace="default", cluster_queue=f"cq-{c}"))
    return fw


def submit_backlog(fw, per_cq=6, num_cqs=4, cpu=2):
    for i in range(per_cq):
        for c in range(num_cqs):
            fw.submit(Workload(
                name=f"wl-{c}-{i}", queue_name=f"lq-{c}",
                creation_time=float(i * num_cqs + c),
                pod_sets=[PodSet.make("main", count=1, cpu=cpu)]))


def usage_cpu(fw, cq_name):
    return fw.cache.cluster_queues[cq_name].usage.get(
        "default", {}).get("cpu", 0)


class TestPipelinedEquivalence:
    @pytest.mark.parametrize("depth", [2, 4])
    def test_drained_state_matches_sync(self, depth):
        sync = build_fw(1)
        pipe = build_fw(depth)
        for fw in (sync, pipe):
            submit_backlog(fw)
            fw.run_until_settled(max_ticks=60)
        for c in range(4):
            assert sorted(sync.admitted_workloads(f"cq-{c}")) == \
                sorted(pipe.admitted_workloads(f"cq-{c}"))
            assert usage_cpu(sync, f"cq-{c}") == usage_cpu(pipe, f"cq-{c}")

    def test_no_overadmission_under_staleness(self):
        """Quota 8 cpu, jobs of 2 cpu: exactly 4 admit per CQ no matter
        how many solves were in flight against stale usage."""
        fw = build_fw(4)
        submit_backlog(fw, per_cq=10)
        fw.run_until_settled(max_ticks=80)
        for c in range(4):
            assert usage_cpu(fw, f"cq-{c}") <= 8000  # milliCPU
            assert len(fw.admitted_workloads(f"cq-{c}")) == 4

    def test_cohort_no_overadmission_under_staleness(self):
        """Cohort borrowing with pipelining: combined cohort usage never
        exceeds the cohort's total capacity."""
        fw = build_fw(3, num_cqs=4, quota=4, cohort="pool")
        submit_backlog(fw, per_cq=8, cpu=2)
        fw.run_until_settled(max_ticks=80)
        total = sum(usage_cpu(fw, f"cq-{c}") for c in range(4))
        assert total <= 4 * 4000  # milliCPU
        assert total == 16000  # fully packed: drained to capacity

    def test_drain_completes_inflight_ticks(self):
        fw = build_fw(4)
        submit_backlog(fw, per_cq=1)
        # One tick dispatches everything; queue is then empty and the next
        # tick must drain the in-flight solve rather than strand it.
        fw.tick()
        fw.tick()
        assert not fw._inflight_ticks
        assert sum(len(fw.admitted_workloads(f"cq-{c}"))
                   for c in range(4)) == 4

    def test_structural_change_mid_pipeline(self):
        """A structural mutation (new CQ + flavor) landing between a
        tick's dispatch and its finish rotates the solver's encoding to a
        new flavor/resource index space. In-flight assignments carry
        usage_idx coordinates in the OLD space — the finish must detect
        the rotation (BatchSolver.encoding_matches) and fall back to the
        name-keyed walks instead of scattering into the wrong cells: no
        crash, no overadmission, correct usage accounting."""
        fw = build_fw(4)
        submit_backlog(fw, per_cq=10)
        # Dispatch a first tick (in flight, not finished at depth 4).
        fw.tick()
        assert fw._inflight_ticks
        # Structural mutation: a new flavor sorted BEFORE "default" plus a
        # CQ using it — the rebuilt encoding permutes flavor indices.
        fw.create_resource_flavor(ResourceFlavor.make("aaa-first"))
        fw.create_cluster_queue(ClusterQueue(
            name="cq-new",
            resource_groups=(ResourceGroup(
                ("cpu",), (FlavorQuotas.make("aaa-first", cpu=8),)),)))
        fw.create_local_queue(LocalQueue(
            name="lq-new", namespace="default", cluster_queue="cq-new"))
        fw.run_until_settled(max_ticks=80)
        for c in range(4):
            assert usage_cpu(fw, f"cq-{c}") <= 8000
            assert len(fw.admitted_workloads(f"cq-{c}")) == 4
        # The solver usage tensor stayed in lockstep with the cache: one
        # more tick's worth of solves must still see correct remaining
        # quota (a wrong-cell scatter would shift later decisions).
        fw.submit(Workload(
            name="probe", queue_name="lq-0", creation_time=999.0,
            pod_sets=[PodSet.make("main", count=1, cpu=2)]))
        fw.run_until_settled(max_ticks=20)
        assert usage_cpu(fw, "cq-0") <= 8000
