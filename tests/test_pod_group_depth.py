"""Pod-group heavyweight semantics + event recording tests
(reference: jobs/pod/pod_controller.go excess cleanup, expectations.go,
KEP-976 replacement; scheduler Event emissions)."""

from kueue_tpu import events as events_mod
from kueue_tpu.api.types import (
    ClusterQueue,
    ClusterQueuePreemption,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    Workload,
)
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.jobs.pod_group import ExpectationsStore, GroupedPod, PodGroup


def make_fw(cpu=8, preemption=None):
    fw = Framework()
    fw.create_resource_flavor(ResourceFlavor.make("default"))
    kwargs = {"preemption": preemption} if preemption else {}
    fw.create_cluster_queue(ClusterQueue(
        name="cq",
        resource_groups=(ResourceGroup(
            covered_resources=("cpu",),
            flavors=(FlavorQuotas.make("default", cpu=cpu),)),), **kwargs))
    fw.create_local_queue(LocalQueue(
        name="lq", namespace="default", cluster_queue="cq"))
    return fw


class TestExpectations:
    def test_satisfied_lifecycle(self):
        ex = ExpectationsStore()
        assert ex.satisfied("g")
        ex.expect_deletions("g", ["p1", "p2"])
        assert not ex.satisfied("g")
        ex.observed_deletion("g", "p1")
        assert not ex.satisfied("g")
        ex.observed_deletion("g", "p2")
        assert ex.satisfied("g")
        ex.observed_deletion("g", "never-expected")  # no-op


class TestExcessCleanup:
    def test_trims_newest_ungated_first(self):
        pods = [GroupedPod(f"p{i}", {"cpu": 1}, group="g") for i in range(3)]
        group = PodGroup("g", "lq", pods, total_count=2)
        group.add_pod(GroupedPod("late", {"cpu": 1}, group="g"))
        removed = group.cleanup_excess()
        assert [p.name for p in removed] == ["late", "p2"]
        assert len(group.pods) == 2
        assert group.expectations.satisfied("g")

    def test_no_excess_noop(self):
        pods = [GroupedPod("p0", {"cpu": 1})]
        group = PodGroup("g", "lq", pods, total_count=2)
        assert group.cleanup_excess() == []


class TestReplacement:
    def test_failed_pod_replaced_keeps_reservation(self):
        fw = make_fw()
        pods = [GroupedPod(f"p{i}", {"cpu": 2}, group="g") for i in range(2)]
        group = PodGroup("g", "lq", pods)
        wl = fw.submit_job(group)
        fw.run_until_settled()
        assert wl.has_quota_reservation
        pods[0].finished = True
        pods[0].succeeded = False
        assert group.replace_pod("p0", GroupedPod("p0-r", {"cpu": 2},
                                                  group="g"))
        fw.tick()
        assert wl.has_quota_reservation and not wl.is_finished
        # Replacement of a running pod is refused.
        assert not group.replace_pod("p1", GroupedPod("x", {"cpu": 2}))

    def test_reclaimable_on_partial_success(self):
        fw = make_fw()
        pods = [GroupedPod(f"p{i}", {"cpu": 2}, group="g") for i in range(3)]
        group = PodGroup("g", "lq", pods)
        wl = fw.submit_job(group)
        fw.run_until_settled()
        assert fw.cache.cluster_queues["cq"].usage["default"]["cpu"] == 6000
        pods[0].finished = True
        fw.tick()
        # One finished pod released its quota share (KEP-78).
        assert wl.reclaimable_pods
        assert fw.cache.cluster_queues["cq"].usage["default"]["cpu"] == 4000


class TestEvents:
    def test_admission_preemption_finish_events(self):
        fw = make_fw(
            cpu=4,
            preemption=ClusterQueuePreemption(
                within_cluster_queue="LowerPriority"))
        low = Workload(name="low", queue_name="lq", priority=-1,
                       pod_sets=[PodSet.make("main", 1, cpu=3)])
        fw.submit(low)
        fw.run_until_settled()
        assert fw.events.for_object(
            "default/low", reason=events_mod.REASON_QUOTA_RESERVED)
        high = Workload(name="high", queue_name="lq", priority=5,
                        pod_sets=[PodSet.make("main", 1, cpu=3)])
        fw.submit(high)
        fw.run_until_settled()
        assert fw.events.for_object(
            "default/low", reason=events_mod.REASON_PREEMPTED)
        fw.finish(fw.workloads["default/high"])
        assert fw.events.for_object(
            "default/high", reason=events_mod.REASON_FINISHED)
