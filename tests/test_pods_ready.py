"""waitForPodsReady lifecycle: admission gating, timeout eviction with
exponential backoff, deactivation (KEP-349; workload_controller.go:342-406)."""

from kueue_tpu.config import (
    Configuration,
    RequeuingStrategy,
    WaitForPodsReady,
    requeue_backoff_seconds,
)
from kueue_tpu.controllers.runtime import Framework

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


def ready_framework(block_admission=True, backoff_limit=None, timeout=300.0):
    clock = FakeClock()
    fw = Framework(
        config=Configuration(wait_for_pods_ready=WaitForPodsReady(
            enable=True, timeout_seconds=timeout,
            block_admission=block_admission,
            requeuing_strategy=RequeuingStrategy(
                backoff_limit_count=backoff_limit))),
        clock=clock)
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq("cq", rg("cpu", fq("default", cpu=8))))
    fw.create_local_queue(make_lq("main", cq="cq"))
    return fw, clock


def test_block_admission_until_pods_ready():
    fw, clock = ready_framework()
    w0 = make_wl("w0", cpu=2, creation_time=1.0)
    fw.submit(w0)
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/w0"]
    # Second workload is gated: w0's pods are not ready yet.
    fw.submit(make_wl("w1", cpu=2, creation_time=2.0))
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/w0"]
    # Pods come up: the gate opens.
    fw.mark_pods_ready(w0)
    fw.run_until_settled()
    assert len(fw.admitted_workloads("cq")) == 2


def test_timeout_evicts_with_backoff():
    fw, clock = ready_framework(timeout=300.0)
    w0 = make_wl("w0", cpu=2, creation_time=1.0)
    fw.submit(w0)
    fw.run_until_settled()
    assert w0.is_admitted
    # Time passes beyond the timeout without the pods becoming ready.
    clock.now += 301.0
    fw.reconcile()
    fw.reconcile()
    assert w0.is_evicted
    assert w0.find_condition("Evicted").reason == "PodsReadyTimeout"
    assert w0.requeue_state.count == 1
    assert w0.requeue_state.requeue_at == clock.now + requeue_backoff_seconds(1)
    assert not w0.has_quota_reservation
    # The requeue respects the backoff: nothing admitted before requeue_at.
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == []
    # After the backoff expires, the framework readmits on its own.
    clock.now += 2.0
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/w0"]
    assert not w0.is_evicted


def test_deactivation_after_backoff_limit():
    fw, clock = ready_framework(timeout=10.0, backoff_limit=1)
    w0 = make_wl("w0", cpu=2, creation_time=1.0)
    fw.submit(w0)
    fw.run_until_settled()
    # First timeout: backoff requeue (count=1).
    clock.now += 11.0
    fw.reconcile()
    assert w0.requeue_state.count == 1
    assert w0.active
    # Readmit after backoff.
    clock.now += 5.0
    fw.run_until_settled()
    assert w0.is_admitted
    # Second timeout exceeds backoffLimitCount=1: deactivated.
    clock.now += 11.0
    fw.reconcile()
    assert not w0.active
    assert w0.find_condition("Evicted").reason == "InactiveWorkload"
    # Deactivated workloads never requeue.
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == []
    assert fw.pending_workloads("cq") == 0


def test_backoff_formula():
    assert requeue_backoff_seconds(1) == 1.0
    assert abs(requeue_backoff_seconds(2) - 1.41284738) < 1e-9
    assert abs(requeue_backoff_seconds(3) - 1.41284738**2) < 1e-9


def test_priority_class_resolution():
    from kueue_tpu.api.types import WorkloadPriorityClass
    fw, clock = ready_framework()
    fw.create_workload_priority_class(WorkloadPriorityClass("high", 100))
    wl = make_wl("w", cpu=1)
    wl.priority_class = "high"
    fw.submit(wl)
    assert wl.priority == 100
