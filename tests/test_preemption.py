"""Preemption-victim search tests (scenarios modeled on preemption_test.go)."""

import time

from kueue_tpu.api.types import (
    BorrowWithinCohort,
    ClusterQueuePreemption,
)
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.workload import WorkloadInfo, WorkloadOrdering
from kueue_tpu.scheduler.preemption import get_targets
from kueue_tpu.solver.modes import PREEMPT
from kueue_tpu.solver.referee import assign_flavors

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg
from tests.test_cache import admit

ORD = WorkloadOrdering()


def targets_for(cache, wl, cq_name):
    snap = cache.snapshot()
    cq = snap.cluster_queues[cq_name]
    wi = WorkloadInfo(wl, cluster_queue=cq_name)
    a = assign_flavors(wi, cq, snap.resource_flavors)
    assert a.representative_mode == PREEMPT, a.message()
    return get_targets(wi, a, snap, ORD, time.time()), snap


def test_within_cq_lower_priority_minimal():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=4)),
        preemption=ClusterQueuePreemption(within_cluster_queue="LowerPriority")))
    cache.add_local_queue(make_lq("main", cq="cq"))
    # Three admitted 1-cpu workloads at priorities -1, -2, 0.
    for name, prio in [("low1", -1), ("low2", -2), ("high", 0)]:
        cache.add_or_update_workload(
            admit(make_wl(name, priority=prio, cpu=1), "cq", "default"))
    # Incoming 2-cpu at priority 0: usage 3/4, need to free 1 cpu.
    targets, snap = targets_for(cache, make_wl("in", priority=0, cpu=2), "cq")
    assert [t.obj.name for t in targets] == ["low2"]
    # Snapshot restored.
    assert snap.cluster_queues["cq"].usage["default"]["cpu"] == 3000


def test_within_cq_never_policy_no_candidates():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq("cq", rg("cpu", fq("default", cpu=4))))
    cache.add_local_queue(make_lq("main", cq="cq"))
    cache.add_or_update_workload(
        admit(make_wl("low", priority=-1, cpu=3), "cq", "default"))
    targets, _ = targets_for(cache, make_wl("in", priority=0, cpu=2), "cq")
    assert targets == []


def test_minimal_set_add_back():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=6)),
        preemption=ClusterQueuePreemption(within_cluster_queue="LowerPriority")))
    cache.add_local_queue(make_lq("main", cq="cq"))
    # Admitted: a(-3, 1cpu), b(-2, 3cpu), c(-1, 2cpu): usage 6/6.
    cache.add_or_update_workload(admit(make_wl("a", priority=-3, cpu=1), "cq", "default"))
    cache.add_or_update_workload(admit(make_wl("b", priority=-2, cpu=3), "cq", "default"))
    cache.add_or_update_workload(admit(make_wl("c", priority=-1, cpu=2), "cq", "default"))
    # Incoming 3 cpu: greedy removes a(1) then b(3) -> fits; add-back pass
    # re-adds a (3 still free). Minimal set is just b.
    targets, _ = targets_for(cache, make_wl("in", priority=0, cpu=3), "cq")
    assert [t.obj.name for t in targets] == ["b"]


def test_reclaim_within_cohort_only_borrowers():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("default", cpu=4)), cohort="co",
        preemption=ClusterQueuePreemption(reclaim_within_cohort="Any")))
    cache.add_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("default", cpu=4)), cohort="co"))
    cache.add_local_queue(make_lq("a", cq="cq-a"))
    cache.add_local_queue(make_lq("b", cq="cq-b"))
    # cq-b borrows: uses 6 of cohort's 8 (nominal 4).
    cache.add_or_update_workload(admit(make_wl("b1", "b", cpu=3), "cq-b", "default"))
    cache.add_or_update_workload(admit(make_wl("b2", "b", cpu=3), "cq-b", "default"))
    # Incoming on cq-a needs 4 (its nominal): must reclaim from borrower.
    targets, _ = targets_for(cache, make_wl("in", "a", cpu=4), "cq-a")
    assert len(targets) == 1
    assert targets[0].cluster_queue == "cq-b"


def test_reclaim_lower_priority_policy():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("default", cpu=4)), cohort="co",
        preemption=ClusterQueuePreemption(reclaim_within_cohort="LowerPriority")))
    cache.add_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("default", cpu=4)), cohort="co"))
    cache.add_local_queue(make_lq("a", cq="cq-a"))
    cache.add_local_queue(make_lq("b", cq="cq-b"))
    cache.add_or_update_workload(
        admit(make_wl("b1", "b", priority=5, cpu=6), "cq-b", "default"))
    # Incoming priority 0 cannot reclaim from higher-priority borrower.
    targets, _ = targets_for(cache, make_wl("in", "a", priority=0, cpu=4), "cq-a")
    assert targets == []


def test_borrow_within_cohort_threshold():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    preemption = ClusterQueuePreemption(
        reclaim_within_cohort="Any",
        borrow_within_cohort=BorrowWithinCohort(
            policy="LowerPriority", max_priority_threshold=-5))
    cache.add_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("default", cpu=4)), cohort="co",
        preemption=preemption))
    cache.add_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("default", cpu=4)), cohort="co"))
    cache.add_local_queue(make_lq("a", cq="cq-a"))
    cache.add_local_queue(make_lq("b", cq="cq-b"))
    # cq-b borrows with a mid-priority workload above the threshold.
    cache.add_or_update_workload(
        admit(make_wl("b-mid", "b", priority=-1, cpu=6), "cq-b", "default"))
    # Incoming 6 cpu (needs borrowing). Candidate priority -1 >= threshold+1
    # (-4): allowBorrowing flips off, so after evicting b-mid the 6-cpu
    # request must fit nominal quota 4 -> no targets.
    targets, _ = targets_for(cache, make_wl("in", "a", priority=0, cpu=6), "cq-a")
    assert targets == []

    # An incoming 4-cpu fits nominal after the reclaim.
    targets2, _ = targets_for(cache, make_wl("in2", "a", priority=0, cpu=4), "cq-a")
    assert [t.obj.name for t in targets2] == ["b-mid"]


def test_evicted_candidates_first():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=4)),
        preemption=ClusterQueuePreemption(within_cluster_queue="LowerPriority")))
    cache.add_local_queue(make_lq("main", cq="cq"))
    w1 = admit(make_wl("already-evicted", priority=-1, cpu=2), "cq", "default")
    w1.set_condition("Evicted", True, reason="Preempted")
    cache.add_or_update_workload(w1)
    cache.add_or_update_workload(
        admit(make_wl("other", priority=-2, cpu=2), "cq", "default"))
    # Eviction-in-progress candidates are preferred even over lower priority.
    targets, _ = targets_for(cache, make_wl("in", priority=0, cpu=2), "cq")
    assert [t.obj.name for t in targets] == ["already-evicted"]
