"""Golden preemption-victim scenarios transliterated from the reference's
TestPreemption table (pkg/scheduler/preemption/preemption_test.go:58-1120):
same ClusterQueue fixture (standalone / cohort / cohort-no-limits /
preventStarvation / with_shared_cq / cohort-lend), same admitted state, same
incoming workload and assignment, same expected victim sets — and the
snapshot must come back unmodified.

Engine equivalence: every scenario is parametrized across ALL victim-search
engines — the host referee, the per-problem lax.scan device kernel
(ops/preemption_scan), the Pallas kernel where importable, and the batched
engines (ops/preemption_batch: C++ native and the packed-XLA dispatch) —
asserting identical victim sets, so no engine can drift from the
reference's minimalPreemptions semantics unnoticed."""

import pytest

from kueue_tpu import features
from kueue_tpu.api.resources import resource_value
from kueue_tpu.api.types import (
    Admission,
    BorrowWithinCohort,
    ClusterQueuePreemption,
    PodSet,
    PodSetAssignment,
    Workload,
)
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.workload import WorkloadInfo, WorkloadOrdering
from kueue_tpu.scheduler.preemption import get_targets
from kueue_tpu.solver.modes import FIT, PREEMPT
from kueue_tpu.solver.referee import (
    Assignment,
    FlavorAssignment,
    PodSetAssignmentResult,
)

from tests.util import fq, make_cq, make_flavor, rg

ORD = WorkloadOrdering()
NOW = 1_000_000.0


def cpu(v):
    return resource_value("cpu", v)


def mem(v):
    return resource_value("memory", v)


def build_cache():
    """The TestPreemption ClusterQueue fixture (preemption_test.go:58-230)."""
    cache = Cache()
    for f in ("default", "alpha", "beta"):
        cache.add_or_update_resource_flavor(make_flavor(f))

    lower = ClusterQueuePreemption(within_cluster_queue="LowerPriority")
    lower_reclaim_lower = ClusterQueuePreemption(
        within_cluster_queue="LowerPriority",
        reclaim_within_cohort="LowerPriority")
    never_reclaim_any = ClusterQueuePreemption(
        within_cluster_queue="Never", reclaim_within_cohort="Any")
    bwc_standard = ClusterQueuePreemption(
        within_cluster_queue="Never", reclaim_within_cohort="LowerPriority",
        borrow_within_cohort=BorrowWithinCohort(
            policy="LowerPriority", max_priority_threshold=0))

    cache.add_cluster_queue(make_cq(
        "standalone",
        rg("cpu", fq("default", cpu=6)),
        rg("memory", fq("alpha", memory="3Gi"), fq("beta", memory="3Gi")),
        preemption=lower))
    cache.add_cluster_queue(make_cq(
        "c1", rg(("cpu", "memory"),
                 fq("default", cpu=(6, 12), memory=("3Gi", "6Gi"))),
        cohort="cohort", preemption=lower_reclaim_lower))
    cache.add_cluster_queue(make_cq(
        "c2", rg(("cpu", "memory"),
                 fq("default", cpu=(6, 12), memory=("3Gi", "6Gi"))),
        cohort="cohort", preemption=never_reclaim_any))
    cache.add_cluster_queue(make_cq(
        "d1", rg(("cpu", "memory"), fq("default", cpu=6, memory="3Gi")),
        cohort="cohort-no-limits", preemption=lower_reclaim_lower))
    cache.add_cluster_queue(make_cq(
        "d2", rg(("cpu", "memory"), fq("default", cpu=6, memory="3Gi")),
        cohort="cohort-no-limits", preemption=never_reclaim_any))
    cache.add_cluster_queue(make_cq(
        "l1", rg(("cpu", "memory"),
                 fq("default", cpu=(6, 12), memory=("3Gi", "6Gi"))),
        cohort="legion", preemption=lower_reclaim_lower))
    cache.add_cluster_queue(make_cq(
        "preventStarvation", rg("cpu", fq("default", cpu=6)),
        preemption=ClusterQueuePreemption(
            within_cluster_queue="LowerOrNewerEqualPriority")))
    cache.add_cluster_queue(make_cq(
        "a_standard", rg("cpu", fq("default", cpu=(1, 12))),
        cohort="with_shared_cq", preemption=bwc_standard))
    cache.add_cluster_queue(make_cq(
        "b_standard", rg("cpu", fq("default", cpu=(1, 12))),
        cohort="with_shared_cq", preemption=bwc_standard))
    cache.add_cluster_queue(make_cq(
        "a_best_effort", rg("cpu", fq("default", cpu=(1, 12))),
        cohort="with_shared_cq", preemption=bwc_standard))
    cache.add_cluster_queue(make_cq(
        "shared", rg("cpu", fq("default", cpu=10)), cohort="with_shared_cq"))
    cache.add_cluster_queue(make_cq(
        "lend1", rg("cpu", fq("default", cpu=(6, None, 4))),
        cohort="cohort-lend", preemption=lower_reclaim_lower))
    cache.add_cluster_queue(make_cq(
        "lend2", rg("cpu", fq("default", cpu=(6, None, 2))),
        cohort="cohort-lend", preemption=lower_reclaim_lower))
    return cache


_seq = [0]


def wl(name, priority=0, creation=None, **requests):
    _seq[0] += 1
    reqs = {r: resource_value(r, q) for r, q in requests.items()}
    return Workload(
        name=name, namespace="", queue_name="",
        pod_sets=[PodSet(name="main", count=1, requests=reqs)],
        priority=priority,
        creation_time=creation if creation is not None else NOW - 60 + _seq[0])


def padmit(cache, w, cq_name, flavor, reserved_at=NOW - 30):
    """ReserveQuota: admit into the cache with the given flavor."""
    w.admission = Admission(
        cluster_queue=cq_name,
        pod_set_assignments=[
            PodSetAssignment(
                name=p.name, flavors={r: flavor for r in p.requests},
                resource_usage={r: v * p.count for r, v in p.requests.items()},
                count=p.count)
            for p in w.pod_sets
        ])
    w.set_condition("QuotaReserved", True, now=reserved_at)
    w.set_condition("Admitted", True, now=reserved_at)
    cache.add_or_update_workload(w)
    return w


def assignment_for(wi, flavors_modes):
    """singlePodSetAssignment: {resource: (flavor, mode)} for podset main."""
    a = Assignment(usage={})
    for p in wi.total_requests:
        psa = PodSetAssignmentResult(
            name=p.name, requests=dict(p.requests), count=p.count)
        for res, (fname, mode) in flavors_modes.items():
            if res in p.requests:
                psa.flavors[res] = FlavorAssignment(name=fname, mode=mode)
        a.pod_sets.append(psa)
    return a


# Parametrization is derived from the registry (solver/modes.ENGINES), so a
# newly registered engine is golden-verified automatically; only engines
# declared optional_import may drop out, and only when their import fails
# (tests/test_engine_coverage.py pins this contract).
from kueue_tpu.solver import modes as _modes

ENGINES = [e.name for e in _modes.ENGINES
           if not e.optional_import or _modes.engine_importable(e)]


@pytest.fixture(params=ENGINES)
def engine(request):
    return request.param


def _run_batch_engine(wi, assignment, snap, backend):
    """Victim search through the batched engine entry (one-item batch):
    the path the scheduler takes with preemptionEngine native/jax."""
    from kueue_tpu.ops.preemption_batch import BatchContext
    from kueue_tpu.scheduler.preemption import (
        DEFAULT_FAIR_STRATEGIES, get_targets_batch)
    from kueue_tpu.solver import schema as sch

    enc = sch.encode_cluster_queues(snap)
    usage = sch.encode_usage(snap, enc).usage
    ctx = BatchContext(enc, features.enabled(features.LENDING_LIMIT))
    return get_targets_batch([(wi, assignment)], snap, ORD, NOW,
                             DEFAULT_FAIR_STRATEGIES, ctx, usage,
                             backend=backend)[0]


def run_case(cache, incoming, target_cq, flavors_modes, engine):
    snap = cache.snapshot()
    before = {name: {f: dict(r) for f, r in cq.usage.items()}
              for name, cq in snap.cluster_queues.items()}
    wi = WorkloadInfo(incoming, cluster_queue=target_cq)
    assignment = assignment_for(wi, flavors_modes)
    if engine.startswith("batch-"):
        targets = _run_batch_engine(wi, assignment, snap,
                                    engine.split("-", 1)[1])
    else:
        eng = {"host": None, "scan-jax": "jax",
               "scan-pallas": "pallas"}[engine]
        targets = get_targets(wi, assignment, snap, ORD, NOW, engine=eng)
    after = {name: {f: dict(r) for f, r in cq.usage.items()}
             for name, cq in snap.cluster_queues.items()}
    assert after == before, "snapshot was modified"
    return {t.obj.name for t in targets}


def test_preempt_lowest_priority(engine):
    cache = build_cache()
    padmit(cache, wl("low", priority=-1, cpu=2), "standalone", "default")
    padmit(cache, wl("mid", cpu=2), "standalone", "default")
    padmit(cache, wl("high", priority=1, cpu=2), "standalone", "default")
    got = run_case(cache, wl("in", priority=1, cpu=2), "standalone",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"low"}


def test_preempt_multiple(engine):
    cache = build_cache()
    padmit(cache, wl("low", priority=-1, cpu=2), "standalone", "default")
    padmit(cache, wl("mid", cpu=2), "standalone", "default")
    padmit(cache, wl("high", priority=1, cpu=2), "standalone", "default")
    got = run_case(cache, wl("in", priority=1, cpu=3), "standalone",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"low", "mid"}


def test_no_preemption_for_low_priority(engine):
    cache = build_cache()
    padmit(cache, wl("low", priority=-1, cpu=3), "standalone", "default")
    padmit(cache, wl("mid", cpu=3), "standalone", "default")
    got = run_case(cache, wl("in", priority=-1, cpu=1), "standalone",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == set()


def test_not_enough_low_priority_workloads(engine):
    cache = build_cache()
    padmit(cache, wl("low", priority=-1, cpu=3), "standalone", "default")
    padmit(cache, wl("mid", cpu=3), "standalone", "default")
    got = run_case(cache, wl("in", cpu=4), "standalone",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == set()


def test_some_free_quota_preempt_low_priority(engine):
    cache = build_cache()
    padmit(cache, wl("low", priority=-1, cpu=1), "standalone", "default")
    padmit(cache, wl("mid", cpu=1), "standalone", "default")
    padmit(cache, wl("high", priority=1, cpu=3), "standalone", "default")
    got = run_case(cache, wl("in", priority=1, cpu=2), "standalone",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"low"}


def test_minimal_set_excludes_low_priority(engine):
    cache = build_cache()
    padmit(cache, wl("low", priority=-1, cpu=1), "standalone", "default")
    padmit(cache, wl("mid", cpu=2), "standalone", "default")
    padmit(cache, wl("high", priority=1, cpu=3), "standalone", "default")
    got = run_case(cache, wl("in", priority=1, cpu=2), "standalone",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"mid"}


def test_only_preempt_workloads_using_chosen_flavor(engine):
    cache = build_cache()
    padmit(cache, wl("low", priority=-1, memory="2Gi"), "standalone", "alpha")
    padmit(cache, wl("mid", memory="1Gi"), "standalone", "beta")
    padmit(cache, wl("high", priority=1, memory="1Gi"), "standalone", "beta")
    got = run_case(cache, wl("in", priority=1, cpu=1, memory="2Gi"),
                   "standalone",
                   {"cpu": ("default", FIT), "memory": ("beta", PREEMPT)},
                   engine)
    assert got == {"mid"}


def test_reclaim_quota_from_borrower(engine):
    cache = build_cache()
    padmit(cache, wl("c1-low", priority=-1, cpu=3), "c1", "default")
    padmit(cache, wl("c2-mid", cpu=3), "c2", "default")
    padmit(cache, wl("c2-high", priority=1, cpu=6), "c2", "default")
    got = run_case(cache, wl("in", priority=1, cpu=3), "c1",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"c2-mid"}


def test_no_workloads_borrowing(engine):
    cache = build_cache()
    padmit(cache, wl("c1-high", priority=1, cpu=4), "c1", "default")
    padmit(cache, wl("c2-low-1", priority=-1, cpu=4), "c2", "default")
    got = run_case(cache, wl("in", priority=1, cpu=4), "c1",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == set()


def test_not_enough_workloads_borrowing(engine):
    cache = build_cache()
    padmit(cache, wl("c1-high", priority=1, cpu=4), "c1", "default")
    padmit(cache, wl("c2-low-1", priority=-1, cpu=4), "c2", "default")
    padmit(cache, wl("c2-low-2", priority=-1, cpu=4), "c2", "default")
    got = run_case(cache, wl("in", priority=1, cpu=4), "c1",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == set()


def test_preempt_locally_and_borrow_other_resources_no_cohort_candidates(
        engine):
    cache = build_cache()
    padmit(cache, wl("c1-low", priority=-1, cpu=4), "c1", "default")
    padmit(cache, wl("c2-low-1", priority=-1, cpu=4), "c2", "default")
    padmit(cache, wl("c2-high-2", priority=1, cpu=4), "c2", "default")
    got = run_case(cache, wl("in", priority=1, cpu=4, memory="5Gi"), "c1",
                   {"cpu": ("default", PREEMPT),
                    "memory": ("default", PREEMPT)}, engine)
    assert got == {"c1-low"}


def test_preempt_from_all_cluster_queues_in_cohort(engine):
    cache = build_cache()
    padmit(cache, wl("c1-low", priority=-1, cpu=3), "c1", "default")
    padmit(cache, wl("c1-mid", cpu=2), "c1", "default")
    padmit(cache, wl("c2-low", priority=-1, cpu=3), "c2", "default")
    padmit(cache, wl("c2-mid", cpu=4), "c2", "default")
    got = run_case(cache, wl("in", cpu=4), "c1",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"c1-low", "c2-low"}


def test_cannot_preempt_within_cq_when_policy_never(engine):
    cache = build_cache()
    padmit(cache, wl("c2-low", priority=-1, cpu=3), "c2", "default")
    got = run_case(cache, wl("in", priority=1, cpu=4), "c2",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == set()


def test_preempt_newer_workloads_with_same_priority(engine):
    cache = build_cache()
    padmit(cache, wl("wl1", priority=2, cpu=2), "preventStarvation",
           "default")
    padmit(cache, wl("wl2", priority=1, cpu=2, creation=NOW),
           "preventStarvation", "default", reserved_at=NOW + 1)
    padmit(cache, wl("wl3", priority=1, cpu=2, creation=NOW),
           "preventStarvation", "default", reserved_at=NOW)
    got = run_case(cache, wl("in", priority=1, cpu=2, creation=NOW - 15),
                   "preventStarvation", {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"wl2"}


def test_bwc_preempt_lower_priority_in_other_cq_while_borrowing(engine):
    cache = build_cache()
    padmit(cache, wl("a_best_effort_low", priority=-1, cpu=10),
           "a_best_effort", "default")
    padmit(cache, wl("b_best_effort_low", priority=-1, cpu=1),
           "b_best_effort", "default")
    got = run_case(cache, wl("in", cpu=10), "a_standard",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"a_best_effort_low"}


def test_bwc_threshold_blocks_when_still_borrowing_after_preemption(engine):
    cache = build_cache()
    padmit(cache, wl("b_standard", priority=1, cpu=10), "b_standard",
           "default")
    got = run_case(cache, wl("in", priority=2, cpu=10), "a_standard",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == set()


def test_bwc_above_threshold_ok_when_not_borrowing_after_preemption(engine):
    cache = build_cache()
    padmit(cache, wl("b_standard", priority=1, cpu=13), "b_standard",
           "default")
    got = run_case(cache, wl("in", priority=2, cpu=1), "a_standard",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"b_standard"}


def test_bwc_does_not_apply_within_same_cluster_queue(engine):
    cache = build_cache()
    padmit(cache, wl("a_standard", priority=1, cpu=13), "a_standard",
           "default")
    got = run_case(cache, wl("in", priority=2, cpu=1), "a_standard",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == set()


def test_reclaim_quota_from_lender(engine):
    features.set_enabled(features.LENDING_LIMIT, True)
    cache = build_cache()
    padmit(cache, wl("lend1-low", priority=-1, cpu=3), "lend1", "default")
    padmit(cache, wl("lend2-mid", cpu=3), "lend2", "default")
    padmit(cache, wl("lend2-high", priority=1, cpu=4), "lend2", "default")
    got = run_case(cache, wl("in", priority=1, cpu=3), "lend1",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"lend2-mid"}


def test_preempt_from_all_cluster_queues_in_cohort_lend(engine):
    features.set_enabled(features.LENDING_LIMIT, True)
    cache = build_cache()
    padmit(cache, wl("lend1-low", priority=-1, cpu=3), "lend1", "default")
    padmit(cache, wl("lend1-mid", cpu=2), "lend1", "default")
    padmit(cache, wl("lend2-low", priority=-1, cpu=3), "lend2", "default")
    padmit(cache, wl("lend2-mid", cpu=4), "lend2", "default")
    got = run_case(cache, wl("in", cpu=4), "lend1",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"lend1-low", "lend2-low"}


def test_cannot_preempt_beyond_lending_limited_requestable_quota(engine):
    features.set_enabled(features.LENDING_LIMIT, True)
    cache = build_cache()
    padmit(cache, wl("lend2-low", priority=-1, cpu=10), "lend2", "default")
    got = run_case(cache, wl("in", cpu=9), "lend1",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == set()


# -- round-4 expansion: the remaining TestPreemption cases -------------------


# "preempting locally and borrowing same resource in cohort": when the
# preemptor borrows the pending resource itself, only same-CQ victims are
# taken (the borrowing-fallback round).
def test_preempt_locally_borrowing_same_resource(engine):
    cache = build_cache()
    padmit(cache, wl("c1-med", cpu=4), "c1", "default")
    padmit(cache, wl("c1-low", priority=-1, cpu=4), "c1", "default")
    padmit(cache, wl("c2-low-1", priority=-1, cpu=4), "c2", "default")
    got = run_case(cache, wl("in", priority=1, cpu=4), "c1",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"c1-low"}


# Same in a cohort with no borrowing limits (cohort-no-limits).
def test_preempt_locally_borrowing_same_resource_no_limits(engine):
    cache = build_cache()
    padmit(cache, wl("d1-med", cpu=4), "d1", "default")
    padmit(cache, wl("d1-low", priority=-1, cpu=4), "d1", "default")
    padmit(cache, wl("d2-low-1", priority=-1, cpu=4), "d2", "default")
    got = run_case(cache, wl("in", priority=1, cpu=4), "d1",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"d1-low"}


# "preempting locally and borrowing other resources in cohort, with
# cohort candidates": cross-CQ candidates exist but the first round
# (no borrowing) can succeed with the same-CQ victim alone.
def test_preempt_locally_borrow_other_resources_with_cohort_candidates(engine):
    cache = build_cache()
    padmit(cache, wl("c1-med", cpu=4), "c1", "default")
    padmit(cache, wl("c2-low-1", priority=-1, cpu=5), "c2", "default")
    padmit(cache, wl("c2-low-2", priority=-1, cpu=1), "c2", "default")
    padmit(cache, wl("c2-low-3", priority=-1, cpu=1), "c2", "default")
    got = run_case(cache, wl("in", priority=1, cpu=2, memory="5Gi"), "c1",
                   {"cpu": ("default", PREEMPT),
                    "memory": ("default", PREEMPT)}, engine)
    assert got == {"c1-med"}


# "preempting locally and not borrowing same resource in 1-queue cohort":
# with no other member to borrow from, the within-CQ round applies and the
# newest-first minimality picks the mid-priority victim.
def test_preempt_locally_one_queue_cohort(engine):
    cache = build_cache()
    padmit(cache, wl("l1-med", cpu=4), "l1", "default")
    padmit(cache, wl("l1-low", priority=-1, cpu=2), "l1", "default")
    got = run_case(cache, wl("in", priority=1, cpu=4), "l1",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"l1-med"}


# "do not reclaim borrowed quota from same priority for
# withinCohort=ReclaimFromLowerPriority"
def test_no_reclaim_same_priority_lower_priority_policy(engine):
    cache = build_cache()
    padmit(cache, wl("c1", cpu=2), "c1", "default")
    padmit(cache, wl("c2-1", cpu=4), "c2", "default")
    padmit(cache, wl("c2-2", cpu=4), "c2", "default")
    got = run_case(cache, wl("in", cpu=4), "c1",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == set()


# "reclaim borrowed quota from same priority for withinCohort=ReclaimFromAny"
def test_reclaim_same_priority_any_policy(engine):
    cache = build_cache()
    padmit(cache, wl("c1-1", cpu=4), "c1", "default")
    padmit(cache, wl("c1-2", priority=1, cpu=4), "c1", "default")
    padmit(cache, wl("c2", cpu=2), "c2", "default")
    got = run_case(cache, wl("in", cpu=4), "c2",
                   {"cpu": ("default", PREEMPT)}, engine)
    assert got == {"c1-1"}


# "each podset preempts a different flavor"
def test_each_podset_preempts_different_flavor_targets(engine):
    cache = build_cache()
    padmit(cache, wl("low-alpha", priority=-1, memory="2Gi"),
           "standalone", "alpha")
    padmit(cache, wl("low-beta", priority=-1, memory="2Gi"),
           "standalone", "beta")
    incoming = Workload(
        name="in", namespace="", queue_name="",
        pod_sets=[
            PodSet(name="launcher", count=1,
                   requests={"memory": mem("2Gi")}),
            PodSet(name="workers", count=2,
                   requests={"memory": mem("1Gi")}),
        ],
        creation_time=NOW - 10)
    snap = cache.snapshot()
    wi = WorkloadInfo(incoming, cluster_queue="standalone")
    a = Assignment(usage={})
    for p, fname in zip(wi.total_requests, ("alpha", "beta")):
        psa = PodSetAssignmentResult(
            name=p.name, requests=dict(p.requests), count=p.count)
        psa.flavors["memory"] = FlavorAssignment(name=fname, mode=PREEMPT)
        a.pod_sets.append(psa)
    targets = get_targets(wi, a, snap, ORD, NOW, engine=engine)
    assert {t.obj.name for t in targets} == {"low-alpha", "low-beta"}
