"""Decision-equivalence: device preemption scan vs the host referee.

The host `_minimal_preemptions` (scheduler/preemption.py, itself golden
against reference preemption.go:172-231) is ground truth; the device scan
(ops/preemption_scan.py) must select the identical victim set on every
scenario, including the randomized fuzz sweep.
"""

import random
import time

import pytest

from kueue_tpu import features
from kueue_tpu.api.types import (
    BorrowWithinCohort,
    ClusterQueuePreemption,
)
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.workload import WorkloadInfo, WorkloadOrdering
from kueue_tpu.ops.preemption_scan import minimal_preemptions_device
from kueue_tpu.scheduler import preemption
from kueue_tpu.solver.modes import PREEMPT
from kueue_tpu.solver.referee import assign_flavors

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg
from tests.test_cache import admit

ORD = WorkloadOrdering()


BACKEND = "jax"


def both_ways(cache, wl, cq_name, allow_borrowing=True, threshold=None):
    """Run host and device minimalPreemptions on the same candidates."""
    snap = cache.snapshot()
    cq = snap.cluster_queues[cq_name]
    wi = WorkloadInfo(wl, cluster_queue=cq_name)
    a = assign_flavors(wi, cq, snap.resource_flavors)
    if a.representative_mode != PREEMPT:
        # The scheduler only searches for victims on Preempt assignments
        # (scheduler.go:390-429).
        return set(), set(), a.representative_mode
    res_per_flv = preemption._resources_requiring_preemption(a)
    candidates = preemption._find_candidates(wi, ORD, cq, res_per_flv)
    candidates.sort(
        key=lambda c: preemption._candidate_sort_key(c, cq_name, time.time()))
    wl_req = preemption._total_requests_for_assignment(wi, a)

    host = preemption._minimal_preemptions(
        wi, a, snap, res_per_flv, candidates, allow_borrowing, threshold)
    device = minimal_preemptions_device(
        wl_req, cq, snap, res_per_flv, candidates, allow_borrowing, threshold,
        backend=BACKEND)
    return ({t.obj.name for t in host}, {t.obj.name for t in device},
            a.representative_mode)


class TestScenarios:
    def _single_cq(self):
        cache = Cache()
        cache.add_or_update_resource_flavor(make_flavor("default"))
        cache.add_cluster_queue(make_cq(
            "cq", rg("cpu", fq("default", cpu=6)),
            preemption=ClusterQueuePreemption(
                within_cluster_queue="LowerPriority")))
        cache.add_local_queue(make_lq("main", cq="cq"))
        return cache

    def test_minimal_add_back(self):
        cache = self._single_cq()
        for name, prio, cpu in [("a", -3, 1), ("b", -2, 3), ("c", -1, 2)]:
            cache.add_or_update_workload(
                admit(make_wl(name, priority=prio, cpu=cpu), "cq", "default"))
        host, device, mode = both_ways(
            cache, make_wl("in", priority=0, cpu=3), "cq")
        assert mode == PREEMPT
        assert host == device == {"b"}

    def test_no_fit_returns_empty(self):
        cache = self._single_cq()
        cache.add_or_update_workload(
            admit(make_wl("big", priority=5, cpu=6), "cq", "default"))
        # Only one candidate (priority above) -> no candidates at all; force
        # via direct call with empty list.
        host, device, _ = both_ways(
            cache, make_wl("in", priority=0, cpu=3), "cq")
        assert host == device == set()

    def test_cohort_reclaim(self):
        cache = Cache()
        cache.add_or_update_resource_flavor(make_flavor("default"))
        cache.add_cluster_queue(make_cq(
            "cq-a", rg("cpu", fq("default", cpu=4)), cohort="co",
            preemption=ClusterQueuePreemption(reclaim_within_cohort="Any")))
        cache.add_cluster_queue(make_cq(
            "cq-b", rg("cpu", fq("default", cpu=4)), cohort="co"))
        cache.add_local_queue(make_lq("a", cq="cq-a"))
        cache.add_local_queue(make_lq("b", cq="cq-b"))
        cache.add_or_update_workload(
            admit(make_wl("b1", "b", cpu=3), "cq-b", "default"))
        cache.add_or_update_workload(
            admit(make_wl("b2", "b", cpu=3), "cq-b", "default"))
        host, device, _ = both_ways(
            cache, make_wl("in", "a", cpu=4), "cq-a", allow_borrowing=False)
        assert host == device and host

    def test_borrow_threshold_flips_borrowing(self):
        cache = Cache()
        cache.add_or_update_resource_flavor(make_flavor("default"))
        cache.add_cluster_queue(make_cq(
            "cq-a", rg("cpu", fq("default", cpu=4)), cohort="co",
            preemption=ClusterQueuePreemption(
                reclaim_within_cohort="Any",
                borrow_within_cohort=BorrowWithinCohort(
                    policy="LowerPriority", max_priority_threshold=0))))
        cache.add_cluster_queue(make_cq(
            "cq-b", rg("cpu", fq("default", cpu=8)), cohort="co"))
        cache.add_local_queue(make_lq("a", cq="cq-a"))
        cache.add_local_queue(make_lq("b", cq="cq-b"))
        cache.add_or_update_workload(
            admit(make_wl("b1", "b", priority=-1, cpu=6), "cq-b", "default"))
        cache.add_or_update_workload(
            admit(make_wl("b2", "b", priority=2, cpu=4), "cq-b", "default"))
        host, device, _ = both_ways(
            cache, make_wl("in", "a", priority=1, cpu=6), "cq-a",
            allow_borrowing=True, threshold=1)
        assert host == device


class TestSchedulerWiring:
    def test_scheduler_preempts_via_device_engine(self):
        from kueue_tpu.api.types import (
            ClusterQueue as CQ,
            ClusterQueuePreemption as CQP,
            FlavorQuotas,
            LocalQueue,
            PodSet,
            ResourceFlavor,
            ResourceGroup,
            Workload,
        )
        from kueue_tpu.controllers.runtime import Framework
        from kueue_tpu.scheduler.scheduler import Scheduler

        fw = Framework()
        fw.scheduler.preemption_engine = "jax"
        fw.create_resource_flavor(ResourceFlavor.make("default"))
        fw.create_cluster_queue(CQ(
            name="cq",
            resource_groups=(ResourceGroup(
                ("cpu",), (FlavorQuotas.make("default", cpu=4),)),),
            preemption=CQP(within_cluster_queue="LowerPriority")))
        fw.create_local_queue(LocalQueue(
            name="lq", namespace="default", cluster_queue="cq"))
        low = Workload(name="low", queue_name="lq", priority=-1,
                       pod_sets=[PodSet.make("main", 1, cpu=3)])
        fw.submit(low)
        fw.run_until_settled()
        assert low.is_admitted
        high = Workload(name="high", queue_name="lq", priority=5,
                        pod_sets=[PodSet.make("main", 1, cpu=3)])
        fw.submit(high)
        fw.run_until_settled()
        assert low.is_evicted and high.is_admitted


class TestFuzz:
    @pytest.mark.parametrize("lending", [False, True])
    @pytest.mark.parametrize("backend", ["jax", "pallas"])
    def test_randomized_equivalence(self, lending, backend, monkeypatch):
        monkeypatch.setattr(__import__("tests.test_preemption_scan",
                                       fromlist=["BACKEND"]),
                            "BACKEND", backend)
        if lending:
            features.set_enabled(features.LENDING_LIMIT, True)
        rnd = random.Random(42 + lending)
        mismatches = []
        preempt_cases = 0
        for trial in range(60):
            cache = Cache()
            cache.add_or_update_resource_flavor(make_flavor("default"))
            n_cq = rnd.randint(1, 3)
            cohort = "co" if n_cq > 1 else ""
            for ci in range(n_cq):
                kwargs = {}
                if lending and cohort and rnd.random() < 0.5:
                    kwargs["lending_limit"] = rnd.randint(0, 4)
                cache.add_cluster_queue(make_cq(
                    f"cq{ci}",
                    rg("cpu", fq("default",
                                 cpu=(rnd.randint(4, 10),
                                      rnd.randint(0, 6),
                                      kwargs.get("lending_limit"))
                                 if (cohort and rnd.random() < 0.6)
                                 else rnd.randint(4, 10))),
                    cohort=cohort,
                    preemption=ClusterQueuePreemption(
                        within_cluster_queue=rnd.choice(
                            ["LowerPriority", "Never"]),
                        reclaim_within_cohort=rnd.choice(
                            ["Any", "LowerPriority", "Never"]))))
                cache.add_local_queue(make_lq(f"q{ci}", cq=f"cq{ci}"))
            for wi_idx in range(rnd.randint(1, 8)):
                ci = rnd.randrange(n_cq)
                wl = make_wl(f"w{wi_idx}", f"q{ci}",
                             priority=rnd.randint(-3, 3),
                             cpu=rnd.randint(1, 4))
                try:
                    cache.add_or_update_workload(
                        admit(wl, f"cq{ci}", "default"))
                except Exception:
                    continue
            target = rnd.randrange(n_cq)
            incoming = make_wl("in", f"q{target}",
                               priority=rnd.randint(-1, 4),
                               cpu=rnd.randint(2, 8))
            allow_borrowing = rnd.random() < 0.5
            threshold = rnd.choice([None, 0, 2])
            try:
                host, device, mode = both_ways(
                    cache, incoming, f"cq{target}",
                    allow_borrowing=allow_borrowing, threshold=threshold)
            except AssertionError:
                raise
            if mode == PREEMPT:
                preempt_cases += 1
            if host != device:
                mismatches.append((trial, host, device))
        assert not mismatches, mismatches
        assert preempt_cases > 5  # the sweep actually exercises preemption


class TestBatchEngine:
    """The whole-tick batched victim search (ops/preemption_batch via
    preemption.get_targets_batch) must reproduce the host get_targets
    per entry — including the two-round cross-CQ fallback, thresholds,
    cohort membership and lending splits."""

    @pytest.mark.parametrize("batch_backend", ["native", "jax"])
    @pytest.mark.parametrize("lending", [False, True])
    def test_randomized_batch_equivalence(self, lending, batch_backend):
        from kueue_tpu.models.flavor_fit import BatchSolver
        from kueue_tpu.ops.preemption_batch import _native_lib
        from kueue_tpu.solver import schema as sch

        if batch_backend == "native" and _native_lib() is None:
            pytest.skip("native toolchain unavailable — C++ engine untestable")

        if lending:
            features.set_enabled(features.LENDING_LIMIT, True)
        rnd = random.Random(7 + lending)
        preempt_cases = 0
        for trial in range(25):
            cache = Cache()
            cache.add_or_update_resource_flavor(make_flavor("default"))
            n_cq = rnd.randint(1, 4)
            cohort = "co" if n_cq > 1 else ""
            for ci in range(n_cq):
                lend = rnd.randint(0, 4) if (lending and cohort
                                             and rnd.random() < 0.5) else None
                bwc = None
                if cohort and rnd.random() < 0.4:
                    bwc = BorrowWithinCohort(
                        policy="LowerPriority",
                        max_priority_threshold=rnd.choice([None, 0, 2]))
                cache.add_cluster_queue(make_cq(
                    f"cq{ci}",
                    rg("cpu", fq("default",
                                 cpu=(rnd.randint(4, 10),
                                      rnd.randint(0, 6), lend)
                                 if (cohort and rnd.random() < 0.6)
                                 else rnd.randint(4, 10))),
                    cohort=cohort,
                    preemption=ClusterQueuePreemption(
                        within_cluster_queue=rnd.choice(
                            ["LowerPriority", "Never"]),
                        reclaim_within_cohort=rnd.choice(
                            ["Any", "LowerPriority", "Never"]),
                        borrow_within_cohort=bwc)))
                cache.add_local_queue(make_lq(f"q{ci}", cq=f"cq{ci}"))
            for wi_idx in range(rnd.randint(2, 10)):
                ci = rnd.randrange(n_cq)
                wl = make_wl(f"w{wi_idx}", f"q{ci}",
                             priority=rnd.randint(-3, 3),
                             cpu=rnd.randint(1, 4))
                try:
                    cache.add_or_update_workload(
                        admit(wl, f"cq{ci}", "default"))
                except Exception:
                    continue
            snap = cache.snapshot()

            # A batch of incoming PREEMPT-mode entries.
            items = []
            for k in range(rnd.randint(1, 5)):
                ci = rnd.randrange(n_cq)
                wl = make_wl(f"in{k}", f"q{ci}",
                             priority=rnd.randint(-1, 4),
                             cpu=rnd.randint(2, 8))
                wi = WorkloadInfo(wl, cluster_queue=f"cq{ci}")
                a = assign_flavors(wi, snap.cluster_queues[f"cq{ci}"],
                                   snap.resource_flavors)
                if a.representative_mode == PREEMPT:
                    items.append((wi, a))
            if not items:
                continue
            preempt_cases += len(items)

            solver = BatchSolver()
            solver._enc = sch.encode_cluster_queues(snap)
            solver._usage_enc = sch.UsageEncoder(solver._enc)
            solver._usage_enc.refresh(snap)
            ctx, usage = solver.preemption_context()

            now = time.time()
            batched = preemption.get_targets_batch(
                items, snap, ORD, now, preemption.DEFAULT_FAIR_STRATEGIES,
                ctx, usage, backend=batch_backend)
            for (wi, a), got in zip(items, batched):
                want = preemption.get_targets(
                    wi, a, snap, ORD, now,
                    preemption.DEFAULT_FAIR_STRATEGIES, engine=None)
                assert ({t.obj.name for t in got}
                        == {t.obj.name for t in want}), (
                    f"trial={trial} wl={wi.key}: batched "
                    f"{sorted(t.obj.name for t in got)} != host "
                    f"{sorted(t.obj.name for t in want)}")
        assert preempt_cases > 10


class TestSentinelOverflowRegression:
    """kueueverify TRC02 regression: `workloadFits` used to evaluate
    `own <= nominal + blim`, and with nominal/blim near the BIG/NO_LIMIT
    2^62 sentinel (or user quotas in canonical units — 4Ei of memory is
    2^62 bytes) the sum passed 2^63 and wrapped negative, flipping the
    borrowing-cap verdict against the host referee's exact Python
    arithmetic. The subtraction form is algebraically identical and stays
    in range."""

    def test_blim_cap_exact_at_2pow62_quota(self):
        import jax.numpy as jnp
        import numpy as np

        from kueue_tpu.ops import preemption_scan as ps

        FR = 4
        big = np.int64(1) << 62
        U = jnp.zeros((1, FR), dtype=jnp.int64)
        wl_req = jnp.full(FR, 10, dtype=jnp.int64)
        mask = jnp.ones(FR, dtype=bool)
        nominal0 = jnp.full(FR, big, dtype=jnp.int64)
        blim = jnp.full(FR, big, dtype=jnp.int64)
        ok = ps._fits(
            U, wl_req=wl_req, wl_req_mask=mask, t_def=mask,
            nominal0=nominal0, blim=blim, blim_def=mask,
            guaranteed=jnp.zeros((1, FR), dtype=jnp.int64),
            requestable=jnp.full(FR, big, dtype=jnp.int64),
            has_cohort=jnp.asarray(True), lending=jnp.asarray(False),
            allow_b=jnp.asarray(True))
        # Exact arithmetic: 10 <= 2^62 + 2^62 is trivially true; the
        # wrapped form said False and starved every borrowing preemptor.
        assert bool(ok)

    def test_scan_kernel_matches_exact_arithmetic_at_scale(self):
        import jax.numpy as jnp
        import numpy as np

        from kueue_tpu.ops import preemption_scan as ps

        # One borrowing candidate whose removal makes the preemptor fit;
        # every quota rides at 2^62-magnitude values.
        big = np.int64(1) << 62
        FR = 2
        usage0 = np.array([[big // 2, 0], [0, 0]], dtype=np.int64)
        nominal = np.array([[big // 4, big], [big, big]], dtype=np.int64)
        q_def = np.array([[True, False], [False, False]])
        victim, fits = ps.scan_kernel(
            jnp.asarray(usage0), jnp.asarray(nominal), jnp.asarray(q_def),
            jnp.zeros((2, FR), dtype=jnp.int64),
            jnp.asarray(np.array([big // 4, 0], dtype=np.int64)),
            jnp.asarray(np.array([True, False])),
            jnp.asarray(np.array([big, 0], dtype=np.int64)),
            jnp.asarray(np.array([True, False])),
            jnp.asarray(np.array([big, big], dtype=np.int64)),
            jnp.asarray(np.array([True, False])),
            jnp.asarray(np.zeros(1, dtype=np.int32)),
            jnp.asarray(np.array([[big // 2, 0]], dtype=np.int64)),
            jnp.asarray(np.zeros(1, dtype=np.int32)),
            jnp.asarray(True), jnp.asarray(False), jnp.asarray(True),
            jnp.asarray(False), jnp.asarray(0, dtype=jnp.int32))
        # Exact semantics: after removing the candidate the target's own
        # usage (big//4) is within nominal+blim (big//4 + big) and the
        # cohort pool fits -> the candidate is the victim.
        assert bool(fits)
        assert np.asarray(victim).tolist() == [True]
