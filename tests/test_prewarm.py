"""Compile-proof ticks (VERDICT r5 Weak #2): a head-count bucket rotation
must NOT land an XLA compile inside a measured scheduling tick.

The batched solve compiles once per padded shape; the scheduler warmup
hook (`Scheduler.prewarm`) covers startup buckets, and whenever the live
head count drifts within 1/8 bucket of a rotation boundary the solver
queues the neighbor bucket (`BatchSolver._maybe_prewarm`), which
`prewarm_idle()` compiles synchronously in the idle window BETWEEN ticks
(the serve loop's inter-tick gap / the bench's churn slot — no
background thread, so the compile can't contend with a measured tick
either). `BatchSolver.cold_dispatches` counts solves whose shape was NOT
already compiled — the regression assertion.
"""

from kueue_tpu.api.types import PodSet, Workload
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.models.flavor_fit import BatchSolver

from tests.util import fq, make_cq, make_flavor, make_lq, rg


def _world(num_cqs: int):
    solver = BatchSolver()
    fw = Framework(batch_solver=solver)
    fw.create_resource_flavor(make_flavor("default"))
    for i in range(num_cqs):
        fw.create_cluster_queue(
            make_cq(f"cq{i}", rg("cpu", fq("default", cpu=1000))))
        fw.create_local_queue(make_lq(f"lq{i}", cq=f"cq{i}"))
    return fw, solver


_seq = [0]


def _submit(fw, heads: int) -> None:
    """One fresh pending workload per ClusterQueue 0..heads-1 — the tick
    pops exactly `heads` heads (one per CQ)."""
    for i in range(heads):
        _seq[0] += 1
        fw.submit(Workload(
            name=f"pw{_seq[0]}", queue_name=f"lq{i}",
            pod_sets=[PodSet.make("m", 1, cpu=1)]))


def test_no_device_solve_compile_inside_measured_tick():
    """Smoke-shape arrival flux that rotates the head-count bucket
    (8 -> 16): with the startup warmup hook plus the imminence-triggered
    background prewarm, every measured tick dispatches an
    already-compiled shape (cold_dispatches stays 0)."""
    fw, solver = _world(12)
    # Startup warmup hook: compile the expected steady-state bucket OFF
    # the measured path.
    fw.scheduler.prewarm([5])
    assert solver.cold_dispatches == 0

    _submit(fw, 5)          # bucket 8 (warmed)
    fw.tick()
    assert solver.cold_dispatches == 0

    # Drift to the grow boundary: 7 heads is within one-eighth of the
    # bucket-8 ceiling, so the solver queues bucket 16 for the next idle
    # window.
    _submit(fw, 7)
    fw.tick()
    assert solver.cold_dispatches == 0
    assert fw.prewarm_idle() == 1   # compiles bucket 16, off-tick

    # Rotation: 9 heads pad to bucket 16 — already compiled off-path.
    _submit(fw, 9)
    fw.tick()
    assert solver.cold_dispatches == 0


def test_shrink_rotation_prewarms_previous_bucket():
    """Coming back down: a 16-bucket tick whose head count drifts to the
    shrink boundary prewarms bucket 8 so the shrunk tick is warm too."""
    fw, solver = _world(16)
    fw.scheduler.prewarm([12])       # bucket 16
    assert solver.cold_dispatches == 0

    _submit(fw, 12)
    fw.tick()                        # W=16, warm
    assert solver.cold_dispatches == 0

    _submit(fw, 9)                   # within W/2 + W/8 = 10 -> queue 8
    fw.tick()
    assert solver.cold_dispatches == 0
    assert fw.prewarm_idle() == 1    # compiles bucket 8, off-tick

    _submit(fw, 6)                   # bucket 8, compiled off-path
    fw.tick()
    assert solver.cold_dispatches == 0


def test_cold_dispatch_counter_counts_unwarmed_shapes():
    """Sanity: without any warmup, the first dispatch of a shape is cold
    (the counter the two regressions above assert on really trips)."""
    fw, solver = _world(4)
    _submit(fw, 3)
    fw.tick()
    assert solver.cold_dispatches == 1


def test_podset_axis_is_sticky_within_encoding_generation():
    """The P axis must not rotate DOWN with batch composition: after a
    tick whose batch held a multi-podset workload (P=2), a later
    all-single-podset tick re-encodes at the floored P and hits the warm
    kernel instead of compiling a (W, 1, ...) twin — the compile cliff
    the bench's cold-dispatch guard caught on the cohortlend mix."""
    fw, solver = _world(4)

    # Tick 1: one 2-podset workload in the batch -> P=2 compiles.
    _seq[0] += 1
    fw.submit(Workload(
        name=f"pw{_seq[0]}", queue_name="lq0",
        pod_sets=[PodSet.make("driver", 1, cpu=1),
                  PodSet.make("workers", 2, cpu=1)]))
    _submit(fw, 3)
    fw.tick()
    cold_after_first = solver.cold_dispatches
    assert cold_after_first >= 1

    # Tick 2: all heads single-podset. Without the P floor this encoded
    # P=1 — a brand-new shape — and compiled inside the tick.
    _submit(fw, 4)
    fw.tick()
    assert solver.cold_dispatches == cold_after_first
