from kueue_tpu.core.workload import WorkloadInfo, WorkloadOrdering
from kueue_tpu.queue.manager import Manager, RequeueReason

from tests.util import make_cq, make_lq, make_wl, rg, fq


def build_manager(strategy="BestEffortFIFO", cohort=""):
    m = Manager()
    m.add_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=10)), strategy=strategy,
        cohort=cohort))
    m.add_local_queue(make_lq("main", cq="cq"))
    return m


def test_heads_priority_then_fifo():
    m = build_manager()
    m.add_or_update_workload(make_wl("old-low", priority=0, creation_time=1.0))
    m.add_or_update_workload(make_wl("new-high", priority=5, creation_time=2.0))
    m.add_or_update_workload(make_wl("newer-high", priority=5, creation_time=3.0))
    heads = m.heads(timeout=0)
    assert [h.obj.name for h in heads] == ["new-high"]
    assert m.heads(timeout=0)[0].obj.name == "newer-high"
    assert m.heads(timeout=0)[0].obj.name == "old-low"
    assert m.heads(timeout=0) == []


def test_one_head_per_cq():
    m = Manager()
    for name in ("cq-a", "cq-b"):
        m.add_cluster_queue(make_cq(name, rg("cpu", fq("default", cpu=10))))
    m.add_local_queue(make_lq("a", cq="cq-a"))
    m.add_local_queue(make_lq("b", cq="cq-b"))
    m.add_or_update_workload(make_wl("wa1", "a"))
    m.add_or_update_workload(make_wl("wa2", "a"))
    m.add_or_update_workload(make_wl("wb1", "b"))
    heads = m.heads(timeout=0)
    assert sorted(h.obj.name for h in heads) == ["wa1", "wb1"]


def test_best_effort_parks_inadmissible():
    m = build_manager(strategy="BestEffortFIFO")
    wl = make_wl("w")
    m.add_or_update_workload(wl)
    wi = m.heads(timeout=0)[0]
    # Generic requeue -> parked as inadmissible, not in the heap.
    assert m.requeue_workload(wi, RequeueReason.GENERIC)
    assert m.heads(timeout=0) == []
    assert m.pending("cq") == 1
    # A relevant event (workload finished in the cohort) flushes the parking lot.
    m.queue_inadmissible_workloads(["cq"])
    assert [h.obj.name for h in m.heads(timeout=0)] == ["w"]


def test_best_effort_requeues_after_nomination_failure():
    m = build_manager(strategy="BestEffortFIFO")
    m.add_or_update_workload(make_wl("w"))
    wi = m.heads(timeout=0)[0]
    assert m.requeue_workload(wi, RequeueReason.FAILED_AFTER_NOMINATION)
    assert [h.obj.name for h in m.heads(timeout=0)] == ["w"]


def test_strict_fifo_requeues_immediately():
    m = build_manager(strategy="StrictFIFO")
    m.add_or_update_workload(make_wl("w"))
    wi = m.heads(timeout=0)[0]
    assert m.requeue_workload(wi, RequeueReason.GENERIC)
    assert [h.obj.name for h in m.heads(timeout=0)] == ["w"]


def test_race_guard_requeues_when_flush_during_schedule():
    # If a flush happens between Pop and requeue, the workload must go back
    # to the heap, not the parking lot (cluster_queue_impl.go:49-57).
    m = build_manager(strategy="BestEffortFIFO")
    m.add_or_update_workload(make_wl("w"))
    wi = m.heads(timeout=0)[0]
    m.queue_inadmissible_workloads(["cq"])  # concurrent event mid-cycle
    assert m.requeue_workload(wi, RequeueReason.GENERIC)
    assert [h.obj.name for h in m.heads(timeout=0)] == ["w"]


def test_requeue_with_pending_flavors_goes_to_heap():
    from kueue_tpu.core.workload import AssignmentClusterQueueState
    m = build_manager(strategy="BestEffortFIFO")
    m.add_or_update_workload(make_wl("w"))
    wi = m.heads(timeout=0)[0]
    wi.last_assignment = AssignmentClusterQueueState(
        last_tried_flavor_idx=[{"cpu": 0}])
    # Untried flavors remain: retry immediately.
    assert m.requeue_workload(wi, RequeueReason.GENERIC)
    assert [h.obj.name for h in m.heads(timeout=0)] == ["w"]


def test_cohort_flush():
    m = Manager()
    m.add_cluster_queue(make_cq("cq-a", rg("cpu", fq("default", cpu=1)), cohort="co"))
    m.add_cluster_queue(make_cq("cq-b", rg("cpu", fq("default", cpu=1)), cohort="co"))
    m.add_local_queue(make_lq("a", cq="cq-a"))
    m.add_local_queue(make_lq("b", cq="cq-b"))
    m.add_or_update_workload(make_wl("wa", "a"))
    wi = m.heads(timeout=0)[0]
    m.requeue_workload(wi, RequeueReason.GENERIC)
    assert m.heads(timeout=0) == []
    # Finishing a workload on cq-b's local queue flushes the whole cohort.
    finished = make_wl("wb", "b")
    m.queue_associated_inadmissible_workloads(finished)
    assert [h.obj.name for h in m.heads(timeout=0)] == ["wa"]
