"""Flavor-assigner referee tests: scenarios modeled on the reference's
flavorassigner_test.go semantics."""

from kueue_tpu import features
from kueue_tpu.api.types import (
    BorrowWithinCohort,
    ClusterQueuePreemption,
    FlavorFungibility,
    MatchExpression,
    PodSet,
    Taint,
    Toleration,
    Workload,
)
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.workload import WorkloadInfo
from kueue_tpu.solver.modes import FIT, NO_FIT, PREEMPT
from kueue_tpu.solver.referee import assign_flavors

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg
from tests.test_cache import admit


def solve(cache, wl, cq_name, counts=None):
    snap = cache.snapshot()
    cq = snap.cluster_queues[cq_name]
    wi = WorkloadInfo(wl, cluster_queue=cq_name)
    return assign_flavors(wi, cq, snap.resource_flavors, counts)


def single_cq_cache(quota_cpu=4, **cq_kwargs):
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(
        make_cq("cq", rg("cpu", fq("default", cpu=quota_cpu)), **cq_kwargs))
    cache.add_local_queue(make_lq("main", cq="cq"))
    return cache


def test_single_flavor_fit():
    cache = single_cq_cache()
    a = solve(cache, make_wl("w", cpu=2), "cq")
    assert a.representative_mode == FIT
    assert a.pod_sets[0].flavors["cpu"].name == "default"
    assert not a.borrowing
    assert a.usage == {"default": {"cpu": 2000}}


def test_no_fit_exceeds_nominal():
    cache = single_cq_cache(quota_cpu=1)
    a = solve(cache, make_wl("w", cpu=2), "cq")
    assert a.representative_mode == NO_FIT
    assert "insufficient quota" in a.message()


def test_preempt_mode_when_used():
    cache = single_cq_cache(quota_cpu=4)
    cache.add_or_update_workload(admit(make_wl("w0", cpu=3), "cq", "default"))
    a = solve(cache, make_wl("w", cpu=2), "cq")
    assert a.representative_mode == PREEMPT
    assert a.pod_sets[0].flavors["cpu"].mode == PREEMPT


def test_multiple_resources_same_flavor():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq", rg(("cpu", "memory"), fq("default", cpu=4, memory="4Gi"))))
    a = solve(cache, make_wl("w", cpu=2, memory="1Gi"), "cq")
    assert a.representative_mode == FIT
    flavors = a.pod_sets[0].flavors
    assert flavors["cpu"].name == "default"
    assert flavors["memory"].name == "default"


def test_one_resource_no_fit_fails_podset():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq", rg(("cpu", "memory"), fq("default", cpu=4, memory="1Gi"))))
    a = solve(cache, make_wl("w", cpu=2, memory="2Gi"), "cq")
    assert a.representative_mode == NO_FIT


def test_resource_not_in_cq():
    cache = single_cq_cache()
    a = solve(cache, make_wl("w", cpu=1, **{"gpu": 1}), "cq")
    # gpu resource isn't configured on the CQ.
    assert a.representative_mode == NO_FIT
    assert "unavailable in ClusterQueue" in a.message()


def test_second_flavor_when_first_full():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("on-demand"))
    cache.add_or_update_resource_flavor(make_flavor("spot"))
    cache.add_cluster_queue(make_cq(
        "cq", rg("cpu", fq("on-demand", cpu=2), fq("spot", cpu=10))))
    a = solve(cache, make_wl("w", cpu=4), "cq")
    assert a.representative_mode == FIT
    assert a.pod_sets[0].flavors["cpu"].name == "spot"


def test_taint_skips_flavor():
    cache = Cache()
    cache.add_or_update_resource_flavor(
        ResourceFlavor := make_flavor("tainted"))
    # Recreate with taints.
    from kueue_tpu.api.types import ResourceFlavor as RF
    cache.add_or_update_resource_flavor(RF.make(
        "tainted", node_taints=[Taint(key="gpu", value="true")]))
    cache.add_or_update_resource_flavor(make_flavor("clean"))
    cache.add_cluster_queue(make_cq(
        "cq", rg("cpu", fq("tainted", cpu=10), fq("clean", cpu=10))))
    a = solve(cache, make_wl("w", cpu=2), "cq")
    assert a.pod_sets[0].flavors["cpu"].name == "clean"

    # A workload tolerating the taint takes the first flavor.
    wl = make_wl("w2", pod_sets=[PodSet.make(
        "main", count=1, cpu=2,
        tolerations=[Toleration(key="gpu", operator="Equal", value="true")])])
    a2 = solve(cache, wl, "cq")
    assert a2.pod_sets[0].flavors["cpu"].name == "tainted"


def test_node_affinity_selects_flavor():
    from kueue_tpu.api.types import ResourceFlavor as RF
    cache = Cache()
    cache.add_or_update_resource_flavor(RF.make("east", node_labels={"zone": "east"}))
    cache.add_or_update_resource_flavor(RF.make("west", node_labels={"zone": "west"}))
    cache.add_cluster_queue(make_cq(
        "cq", rg("cpu", fq("east", cpu=10), fq("west", cpu=10))))
    wl = make_wl("w", pod_sets=[PodSet.make(
        "main", count=1, cpu=2, node_selector={"zone": "west"})])
    a = solve(cache, wl, "cq")
    assert a.pod_sets[0].flavors["cpu"].name == "west"

    wl2 = make_wl("w2", pod_sets=[PodSet.make(
        "main", count=1, cpu=2,
        affinity_terms=[[MatchExpression("zone", "In", ("west",))]])])
    a2 = solve(cache, wl2, "cq")
    assert a2.pod_sets[0].flavors["cpu"].name == "west"


def test_borrowing_in_cohort():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("default", cpu=4)), cohort="co"))
    cache.add_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("default", cpu=4)), cohort="co"))
    a = solve(cache, make_wl("w", cpu=6), "cq-a")
    assert a.representative_mode == FIT
    assert a.borrowing
    assert a.pod_sets[0].flavors["cpu"].borrow


def test_borrowing_limit_blocks():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("default", cpu=(4, 1))), cohort="co"))
    cache.add_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("default", cpu=4)), cohort="co"))
    a = solve(cache, make_wl("w", cpu=6), "cq-a")
    assert a.representative_mode == NO_FIT
    assert "borrowing limit" in a.message()


def test_cohort_usage_limits_borrowing():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("default", cpu=4)), cohort="co"))
    cache.add_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("default", cpu=4)), cohort="co"))
    cache.add_local_queue(make_lq("a", cq="cq-a"))
    cache.add_local_queue(make_lq("b", cq="cq-b"))
    cache.add_or_update_workload(admit(make_wl("wa", "a", cpu=1), "cq-a", "default"))
    cache.add_or_update_workload(admit(make_wl("wb", "b", cpu=4), "cq-b", "default"))
    # Cohort has 8 total, 5 used. 6 > nominal and borrowWithinCohort is off:
    # NoFit.
    a = solve(cache, make_wl("w", "a", cpu=6), "cq-a")
    assert a.representative_mode == NO_FIT
    # 4 fits nominal but not unused cohort quota (5+4 > 8): Preempt.
    a2 = solve(cache, make_wl("w2", "a", cpu=4), "cq-a")
    assert a2.representative_mode == PREEMPT


def test_borrow_within_cohort_enables_preempt_with_borrow():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    preemption = ClusterQueuePreemption(
        reclaim_within_cohort="Any",
        borrow_within_cohort=BorrowWithinCohort(policy="LowerPriority"))
    cache.add_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("default", cpu=4)), cohort="co",
        preemption=preemption))
    cache.add_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("default", cpu=4)), cohort="co"))
    cache.add_local_queue(make_lq("b", cq="cq-b"))
    cache.add_or_update_workload(admit(make_wl("wb", "b", cpu=4), "cq-b", "default"))
    # 6 > nominal 4, but within cohort capacity 8: preempt-with-borrow.
    a = solve(cache, make_wl("w", cpu=6), "cq-a")
    assert a.representative_mode == PREEMPT
    assert a.pod_sets[0].flavors["cpu"].borrow


def test_fungibility_stop_at_first_fit_with_borrow():
    # Default whenCanBorrow=Borrow: stop at first flavor that fits, even
    # borrowing.
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("f1"))
    cache.add_or_update_resource_flavor(make_flavor("f2"))
    cache.add_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("f1", cpu=2), fq("f2", cpu=10)), cohort="co"))
    cache.add_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("f1", cpu=10), fq("f2", cpu=0)), cohort="co"))
    a = solve(cache, make_wl("w", cpu=4), "cq-a")
    assert a.representative_mode == FIT
    assert a.pod_sets[0].flavors["cpu"].name == "f1"
    assert a.borrowing


def test_fungibility_try_next_when_borrow():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("f1"))
    cache.add_or_update_resource_flavor(make_flavor("f2"))
    fung = FlavorFungibility(when_can_borrow="TryNextFlavor")
    cache.add_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("f1", cpu=2), fq("f2", cpu=10)), cohort="co",
        fungibility=fung))
    cache.add_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("f1", cpu=10), fq("f2", cpu=0)), cohort="co"))
    a = solve(cache, make_wl("w", cpu=4), "cq-a")
    # f2 fits without borrowing and is preferred under TryNextFlavor.
    assert a.representative_mode == FIT
    assert a.pod_sets[0].flavors["cpu"].name == "f2"
    assert not a.borrowing


def test_last_state_resume_index():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("f1"))
    cache.add_or_update_resource_flavor(make_flavor("f2"))
    cache.add_or_update_resource_flavor(make_flavor("f3"))
    fung = FlavorFungibility(when_can_preempt="Preempt")
    cache.add_cluster_queue(make_cq(
        "cq", rg("cpu", fq("f1", cpu=2), fq("f2", cpu=4), fq("f3", cpu=10)),
        fungibility=fung))
    cache.add_local_queue(make_lq("main", cq="cq"))
    cache.add_or_update_workload(admit(make_wl("w0", cpu=4), "cq", "f2"))
    wl = make_wl("w", cpu=4)
    snap = cache.snapshot()
    wi = WorkloadInfo(wl, cluster_queue="cq")
    a = assign_flavors(wi, snap.cluster_queues["cq"], snap.resource_flavors)
    # f1: NoFit (4>2). f2: preempt possible (4<=4, used) -> whenCanPreempt=
    # Preempt stops there.
    assert a.representative_mode == PREEMPT
    assert a.pod_sets[0].flavors["cpu"].name == "f2"
    assert a.last_state.last_tried_flavor_idx[0]["cpu"] == 1

    # Resume: next attempt starts at f3 and fits.
    wi.last_assignment = a.last_state
    a2 = assign_flavors(wi, snap.cluster_queues["cq"], snap.resource_flavors)
    assert a2.representative_mode == FIT
    assert a2.pod_sets[0].flavors["cpu"].name == "f3"
    # Reached the end of the list: resume resets to -1.
    assert a2.last_state.last_tried_flavor_idx[0]["cpu"] == -1


def test_resume_state_invalidated_by_generation():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("f1"))
    cache.add_or_update_resource_flavor(make_flavor("f2"))
    cache.add_cluster_queue(make_cq(
        "cq", rg("cpu", fq("f1", cpu=4), fq("f2", cpu=10))))
    snap = cache.snapshot()
    wi = WorkloadInfo(make_wl("w", cpu=2), cluster_queue="cq")
    wi.last_assignment = __import__(
        "kueue_tpu.core.workload", fromlist=["AssignmentClusterQueueState"]
    ).AssignmentClusterQueueState(
        last_tried_flavor_idx=[{"cpu": 0}],
        cluster_queue_generation=0)
    # CQ generation (1) exceeds the recorded generation (0): state cleared,
    # search starts at f1 again.
    a = assign_flavors(wi, snap.cluster_queues["cq"], snap.resource_flavors)
    assert a.pod_sets[0].flavors["cpu"].name == "f1"


def test_pods_resource_counted():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq", rg(("cpu", "pods"), fq("default", cpu=100, pods=3))))
    wl = make_wl("w", pod_sets=[PodSet.make("main", count=5, cpu="100m")])
    a = solve(cache, wl, "cq")
    assert a.representative_mode == NO_FIT  # 5 pods > 3


def test_partial_admission_scaling():
    cache = single_cq_cache(quota_cpu=4)
    wl = make_wl("w", pod_sets=[PodSet.make("main", count=8, min_count=2, cpu=1)])
    a = solve(cache, wl, "cq")
    assert a.representative_mode == NO_FIT
    a2 = solve(cache, wl, "cq", counts=[4])
    assert a2.representative_mode == FIT
    assert a2.pod_sets[0].count == 4
    assert a2.usage["default"]["cpu"] == 4000
