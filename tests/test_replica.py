"""Multi-process replica scheduler: decision-identity goldens + the
cross-replica commit protocol (parallel/replica.py,
controllers/replica_runtime.py).

The replica split must be decision-INVISIBLE: for any replica count, the
partitioned deployment (one queue manager/cache/solver slice per shard
group + the coordinator commit protocol for split KEP-79 roots) admits
and preempts exactly what the single-process scheduler does. Pinned:

  * 200-tick randomized churn (the tests/test_shard.py harness shape —
    flat cohorts + a hierarchical tree whose subtree cohorts hash onto
    different replicas, so the commit protocol runs live during churn)
    at replicas {1, 2, 4}, across every registered victim-search
    engine, against the unsharded single-process trail, bitwise;
  * a deterministic cross-REPLICA LendingLimit scenario where two
    same-tick heads on different replicas both pass their local
    optimistic view but only one fits the shared clamp — the
    coordinator MUST revoke exactly one, matching single-process;
  * a spawn-mode (real multiprocessing) identity run — same protocol,
    real pipes — plus the fail-over drill: kill a replica mid-window,
    the lease holder reassigns its shard group, the partition journal
    replays, and the admitted set matches the uninterrupted run.

The churn goldens run the LOOPBACK transport (threads + queues): the
protocol and the worker code are identical to spawn mode; only the
channel differs, and the spawn smoke pins that the pipes carry the same
decisions.
"""

import os
import random
import zlib

import pytest

from kueue_tpu import features
from kueue_tpu.api.types import (
    ClusterQueuePreemption,
    CohortSpec,
    PodSet,
    Workload,
)
from kueue_tpu.config import Configuration, TPUSolverConfig
from kueue_tpu.controllers.replica_runtime import ReplicaRuntime
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.models.flavor_fit import BatchSolver
from kueue_tpu.parallel.replica import GroupMap, group_key, group_of
from kueue_tpu.solver import modes as _modes

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg

TICKS = 200

_ENGINE_KNOB = {
    "host": None,
    "scan-jax": "jax",
    "scan-pallas": "pallas",
    "batch-native": "native",
    "batch-jax": "jax",
}

_KNOBS = []
for _spec in _modes.ENGINES:
    if _spec.optional_import and not _modes.engine_importable(_spec):
        continue
    knob = _ENGINE_KNOB[_spec.name]
    if knob not in _KNOBS:
        _KNOBS.append(knob)


def _split_pair(n_groups: int):
    """Two cohort names whose hashes land on different groups at both 2
    and `n_groups` replicas — the tree they share is replica-split."""
    names = ["east", "west", "north", "south", "alpha", "beta", "gamma",
             "delta", "omega", "sigma"]
    for i, a in enumerate(names):
        ha = zlib.crc32(a.encode())
        for b in names[i + 1:]:
            hb = zlib.crc32(b.encode())
            if ha % n_groups != hb % n_groups and ha % 2 != hb % 2:
                return a, b
    raise AssertionError("no splitting cohort-name pair found")


def _world_objects():
    """The test_shard mixed topology: 4 CQs over 2 flat cohorts with
    cohort-reclaim preemption, plus a hierarchical tree
    `hroot <- {A, B, hpool}` where hpool lends at most 4 cpu and A/B
    hash to different replicas — every borrow across the tree runs the
    commit protocol when replicated."""
    ca, cb = _split_pair(4)
    objs = [
        ("flavor", make_flavor("on-demand", zone="a")),
        ("flavor", make_flavor("spot", zone="b")),
    ]
    for i in range(4):
        objs.append(("cq", make_cq(
            f"cq-{i}",
            rg("cpu", fq("on-demand", cpu=(16, 16)), fq("spot", cpu=(8, 8))),
            cohort=f"cohort-{i % 2}",
            preemption=ClusterQueuePreemption(
                within_cluster_queue="LowerPriority",
                reclaim_within_cohort="Any"))))
        objs.append(("lq", make_lq(f"lq-{i}", "default", cq=f"cq-{i}")))
    objs.append(("cohort", CohortSpec(name="hroot")))
    objs.append(("cohort", CohortSpec(name=ca, parent="hroot")))
    objs.append(("cohort", CohortSpec(name=cb, parent="hroot")))
    objs.append(("cohort", CohortSpec(
        name="hpool", parent="hroot",
        resource_groups=(rg("cpu", fq("on-demand", cpu=(8, None, 4))),))))
    for side, idx in ((ca, 4), (cb, 5)):
        objs.append(("cq", make_cq(
            f"cq-{idx}", rg("cpu", fq("on-demand", cpu=4)), cohort=side)))
        objs.append(("lq", make_lq(f"lq-{idx}", "default",
                                   cq=f"cq-{idx}")))
    return objs


def _apply_world(target) -> None:
    handlers = {
        "flavor": target.create_resource_flavor,
        "cohort": target.create_cohort,
        "cq": target.create_cluster_queue,
        "lq": target.create_local_queue,
    }
    for kind, obj in _world_objects():
        handlers[kind](obj)


class _SingleTarget:
    """Single-process Framework behind the same driving interface the
    replica runtime exposes — so ONE churn loop drives both and every
    input is provably identical."""

    def __init__(self, engine):
        features.set_enabled(features.LENDING_LIMIT, True)
        cfg = Configuration(tpu_solver=TPUSolverConfig(
            preemption_engine="host" if engine is None else engine))
        self.fw = Framework(batch_solver=BatchSolver(), config=cfg,
                            pipeline_depth=1)
        self.fw.create_namespace("default", labels={})
        self._admitted: list = []
        self._preempted: list = []
        orig_admit = self.fw.scheduler.apply_admission
        orig_preempt = self.fw.scheduler.apply_preemption

        def apply_admission(wl):
            ok = orig_admit(wl)
            if ok:
                self._admitted.append((wl.key, wl.admission.cluster_queue))
            return ok

        def apply_preemption(wl, msg):
            self._preempted.append(wl.key)
            return orig_preempt(wl, msg)

        self.fw.scheduler.apply_admission = apply_admission
        self.fw.scheduler.apply_preemption = apply_preemption
        _apply_world(self.fw)

    def submit(self, wl):
        self.fw.submit(wl)

    def finish(self, key, cq=None, delete=True):
        wl = self.fw.workloads.get(key)
        if wl is not None:
            self.fw.finish(wl)
            if delete:
                self.fw.delete_workload(wl)

    def delete_workload(self, key):
        wl = self.fw.workloads.get(key)
        if wl is not None:
            self.fw.delete_workload(wl)

    def tick(self):
        self._admitted, self._preempted = [], []
        self.fw.tick()
        self.fw.prewarm_idle()
        return {"admitted": list(self._admitted),
                "preempted": list(self._preempted)}

    def pending_total(self):
        return sum(self.fw.queues.pending(f"cq-{i}") for i in range(6))

    def revocations(self):
        return self.fw.scheduler.metrics.reconcile_revocations

    def close(self):
        pass


class _ReplicaTarget:
    def __init__(self, engine, replicas, spawn=False, state_dir=None):
        features.set_enabled(features.LENDING_LIMIT, True)
        self.rt = ReplicaRuntime(
            replicas, spawn=spawn, state_dir=state_dir,
            engine="host" if engine is None else engine)
        _apply_world(self.rt)
        self._revocations = 0

    def submit(self, wl):
        self.rt.submit(wl)

    def finish(self, key, cq=None, delete=True):
        self.rt.finish(key, cq=cq, delete=delete)

    def delete_workload(self, key):
        self.rt.delete_workload(key)

    def tick(self):
        stats = self.rt.tick()
        self._revocations += stats["revocations"]
        return stats

    def pending_total(self):
        return sum(self.rt.dump()["pending"].get(f"cq-{i}", 0)
                   for i in range(6))

    def revocations(self):
        return self._revocations

    def close(self):
        self.rt.close()


def drive(target, ticks: int = TICKS):
    """Seeded churn through the shared driving interface; returns the
    decision trail. All bookkeeping runs on the tick stats (keys + CQs),
    never on object state, so the single-process and replica drives
    receive byte-identical inputs."""
    rnd = random.Random(4321)
    seq = [0]
    pending: dict = {}    # key -> True (submitted, not admitted)
    admitted: dict = {}   # key -> cq
    trail = []

    def submit_one():
        seq[0] += 1
        i = seq[0]
        if i % 4 == 0:
            q = f"lq-{4 + (i // 4) % 2}"
            cpu = rnd.randint(2, 8)
        else:
            q = f"lq-{rnd.randrange(4)}"
            cpu = rnd.randint(1, 4)
        wl = Workload(
            name=f"wl-{i}", namespace="default", queue_name=q,
            priority=rnd.randint(-2, 3),
            creation_time=float(1000 + i),
            pod_sets=[PodSet.make("ps0", count=rnd.randint(1, 3), cpu=cpu)])
        pending[wl.key] = True
        target.submit(wl)

    for _ in range(40):
        submit_one()

    for _ in range(ticks):
        stats = target.tick()
        tick_admitted = sorted(k for k, _cq in stats["admitted"])
        tick_preempted = sorted(stats["preempted"])
        trail.append((tuple(tick_admitted), tuple(tick_preempted)))
        for key, cq in stats["admitted"]:
            admitted[key] = cq
            pending.pop(key, None)
        for key in stats["preempted"]:
            # Evicted this tick's reconcile: back to pending.
            if key in admitted:
                admitted.pop(key)
                pending[key] = True
        for _ in range(rnd.randint(0, 3)):
            submit_one()
        if pending and rnd.random() < 0.3:
            key = rnd.choice(sorted(pending))
            del pending[key]
            target.delete_workload(key)
        done = sorted(admitted)
        for key in done[:rnd.randint(0, 4)]:
            cq = admitted.pop(key)
            target.finish(key, cq=cq)
    trail.append(("pending", target.pending_total()))
    return trail


_BASELINES: dict = {}


def _baseline(engine):
    if engine not in _BASELINES:
        target = _SingleTarget(engine)
        _BASELINES[engine] = drive(target)
        target.close()
    return _BASELINES[engine]


@pytest.mark.parametrize("engine", _KNOBS, ids=[str(k) for k in _KNOBS])
@pytest.mark.parametrize("replicas", [1, 2, 4])
def test_replica_churn_decisions_identical(engine, replicas):
    """200 randomized churn ticks: the partitioned deployment (per-group
    vertical slices + the coordinator commit protocol for the split
    tree) must replay the single-process trail byte for byte, at every
    replica count, on every engine."""
    target = _ReplicaTarget(engine, replicas)
    try:
        trail = drive(target)
    finally:
        target.close()
    assert trail == _baseline(engine)


def _lending_world(target, ca, cb):
    for kind, obj in [
        ("flavor", make_flavor("on-demand")),
        ("cohort", CohortSpec(name="hroot")),
        ("cohort", CohortSpec(name=ca, parent="hroot")),
        ("cohort", CohortSpec(name=cb, parent="hroot")),
        ("cohort", CohortSpec(
            name="hpool", parent="hroot",
            resource_groups=(rg("cpu",
                                fq("on-demand", cpu=(8, None, 4))),))),
        ("cq", make_cq("cq-a", rg("cpu", fq("on-demand", cpu=4)),
                       cohort=ca)),
        ("lq", make_lq("lq-a", "default", cq="cq-a")),
        ("cq", make_cq("cq-b", rg("cpu", fq("on-demand", cpu=4)),
                       cohort=cb)),
        ("lq", make_lq("lq-b", "default", cq="cq-b")),
    ]:
        {"flavor": target.create_resource_flavor,
         "cohort": target.create_cohort,
         "cq": target.create_cluster_queue,
         "lq": target.create_local_queue}[kind](obj)


def test_lending_clamp_commit_protocol_revokes():
    """Two same-tick heads on different REPLICAS of a split tree, both
    borrowing from one lending-limited pool that can serve only one:
    each replica's local optimistic pass admits its own, the coordinator
    commits exactly one in global cycle order and revokes the other —
    and the winner matches the single-process decision."""
    features.set_enabled(features.LENDING_LIMIT, True)
    ca, cb = _split_pair(2)

    cfg = Configuration(tpu_solver=TPUSolverConfig(
        preemption_engine="host"))
    fw = Framework(batch_solver=BatchSolver(), config=cfg,
                   pipeline_depth=1)
    fw.create_namespace("default", labels={})
    _lending_world(fw, ca, cb)
    fw.submit(make_wl("wa", "lq-a", cpu=8, creation_time=1.0))
    fw.submit(make_wl("wb", "lq-b", cpu=8, creation_time=2.0))
    fw.run_until_settled(max_ticks=6)
    single = tuple(sorted(
        fw.admitted_workloads("cq-a") + fw.admitted_workloads("cq-b")))
    assert len(single) == 1

    rt = ReplicaRuntime(2, spawn=False, engine="host")
    try:
        _lending_world(rt, ca, cb)
        assert "hroot" in rt.gmap.split_roots
        rt.submit(make_wl("wa", "lq-a", cpu=8, creation_time=1.0))
        rt.submit(make_wl("wb", "lq-b", cpu=8, creation_time=2.0))
        revocations = 0
        for _ in range(6):
            revocations += rt.tick()["revocations"]
        dump = rt.dump()
        winners = tuple(sorted(dump["admitted"].get("cq-a", [])
                               + dump["admitted"].get("cq-b", [])))
        assert winners == single
        assert revocations >= 1
        assert rt.coordinator.revocations >= 1
        assert rt.coordinator.commits >= 1
    finally:
        rt.close()


def test_spawn_identity_smoke():
    """Real multiprocessing (spawn) replicas, 3 processes: a short churn
    drive must match the single-process trail — the pipes carry exactly
    what the loopback queues carry. This is the `make replica-smoke`
    identity gate."""
    target = _ReplicaTarget(None, 3, spawn=True)
    try:
        trail = drive(target, ticks=30)
    finally:
        target.close()
    single = _SingleTarget(None)
    expect = drive(single, ticks=30)
    assert trail == expect


def test_spawn_failover_drill(tmp_path):
    """Kill a replica PROCESS mid-window (SIGKILL, no shutdown path):
    the lease-holding parent reassigns its shard group, the partition
    journal replays on the adopter, and the final admitted set matches
    the uninterrupted single-process run — the PR 2 HA takeover, per
    partition. This is the `make replica-smoke` fail-over drill."""
    state = str(tmp_path / "state")

    def build(target):
        target.create_resource_flavor(make_flavor("default"))
        for i in range(3):
            target.create_cluster_queue(make_cq(
                f"cq-{i}", rg("cpu", fq("default", cpu=4))))
            target.create_local_queue(make_lq(
                f"lq-{i}", "default", cq=f"cq-{i}"))

    def load(target):
        for i in range(3):
            target.submit(make_wl(f"fits-{i}", f"lq-{i}", cpu=3,
                                  creation_time=float(i)))
            target.submit(make_wl(f"waits-{i}", f"lq-{i}", cpu=3,
                                  creation_time=float(10 + i)))

    # Uninterrupted single-process reference.
    fw = Framework(batch_solver=None, config=Configuration(
        tpu_solver=TPUSolverConfig(enable=False)))
    fw.create_namespace("default", labels={})
    build(fw)
    load(fw)
    fw.run_until_settled(max_ticks=8)
    expect = {f"cq-{i}": sorted(fw.cache.cluster_queues[f"cq-{i}"].workloads)
              for i in range(3)}

    rt = ReplicaRuntime(3, spawn=True, engine="host", state_dir=state)
    try:
        build(rt)
        load(rt)
        for _ in range(4):
            rt.tick()
        before = rt.dump()
        assert {k: v for k, v in before["admitted"].items()} == expect
        victim_gid = rt.gmap.cq_group["cq-0"]
        victim = rt.group_owner[victim_gid]
        rt.kill_replica(victim)
        for _ in range(5):
            rt.tick()
        after = rt.dump()
        assert rt.group_owner[victim_gid] != victim
        assert {k: v for k, v in after["admitted"].items()} == expect
        # The recovered admissions still hold the quota: every pending
        # workload must still be waiting (exactly-once, never re-admitted
        # or double-counted across the takeover).
        assert all(n == 1 for n in after["pending"].values()), \
            after["pending"]
    finally:
        rt.close()


def test_merged_trace_is_valid_chrome_with_flow_events():
    """The coordinator merges per-process ring dumps into ONE
    Perfetto-loadable trace: per-pid lanes, process_name metadata, and
    the reconcile round-trips visible as flow events (replica rtt span
    -> coordinator round span)."""
    from kueue_tpu.tracing import TRACER, validate_chrome_trace

    features.set_enabled(features.LENDING_LIMIT, True)
    ca, cb = _split_pair(2)
    TRACER.reset()
    TRACER.configure(enabled=True)
    try:
        rt = ReplicaRuntime(2, spawn=False, engine="host")
        try:
            _lending_world(rt, ca, cb)
            rt.submit(make_wl("wa", "lq-a", cpu=8, creation_time=1.0))
            rt.submit(make_wl("wb", "lq-b", cpu=8, creation_time=2.0))
            for _ in range(3):
                rt.tick()
            doc = rt.export_chrome()
        finally:
            rt.close()
    finally:
        TRACER.configure(enabled=False)
        TRACER.reset()
    assert validate_chrome_trace(doc) == []
    names = {e["name"] for e in doc["traceEvents"]}
    assert "reconcile.round" in names
    assert "admit.reconcile.rtt" in names
    flows = [e for e in doc["traceEvents"] if e.get("ph") in ("s", "f")]
    assert flows, "reconcile round-trips must appear as flow events"
    assert {e["ph"] for e in flows} == {"s", "f"}
    # Every flow event carries an id binding start to finish.
    assert all(e.get("id") is not None for e in flows)


# -- unit tests --------------------------------------------------------------


def test_group_map_split_roots():
    gm = GroupMap(4)
    ca, cb = _split_pair(4)
    gm.note_cohort("hroot", None)
    gm.note_cohort(ca, "hroot")
    gm.note_cohort(cb, "hroot")
    gm.place_cq("cq-a", ca)
    gm.place_cq("cq-b", cb)
    gm.place_cq("cq-flat", "flat-1")
    assert gm.recompute_split() == frozenset({"hroot"})
    # Flat cohorts hash whole: never split.
    gm.place_cq("cq-flat2", "flat-1")
    assert gm.recompute_split() == frozenset({"hroot"})
    # Stable first-seen placement survives cohort updates.
    g = gm.cq_group["cq-a"]
    gm.place_cq("cq-a", cb)
    assert gm.cq_group["cq-a"] == g


def test_group_hash_matches_mesh_hash():
    """The replica partition key IS the PR 7 cohort hash: the same
    crc32, the same __solo__ naming, so a cohort's replica and its
    device-mesh shard derive from one function of its name."""
    from kueue_tpu.parallel.mesh import _crc_shard

    for name in ("cohort-1", "east", "__solo__/cq-7"):
        assert group_of(name, 8) == _crc_shard(name, 8)
    assert group_key("cq-7", None) == "__solo__/cq-7"
    assert group_key("cq-7", "east") == "east"


def test_store_bridge_routes_partitioned_watch_stream():
    """The partitioned watch stream: a parent apiserver-analog Store
    drives the replica deployment through ReplicaStoreBridge exactly
    like direct create_* calls — including MODIFIED (quota edit reaches
    the owning replica) and DELETED (workload removal) routing."""
    from kueue_tpu.api.types import FlavorQuotas, ResourceGroup
    from kueue_tpu.controllers.replica_runtime import ReplicaStoreBridge
    from kueue_tpu.controllers.store import (
        KIND_CLUSTER_QUEUE,
        KIND_LOCAL_QUEUE,
        KIND_RESOURCE_FLAVOR,
        KIND_WORKLOAD,
        Store,
    )

    rt = ReplicaRuntime(2, spawn=False, engine="host")
    store = Store()
    ReplicaStoreBridge(store, rt)
    try:
        store.create(KIND_RESOURCE_FLAVOR, make_flavor("default"))
        for i in range(3):
            store.create(KIND_CLUSTER_QUEUE, make_cq(
                f"cq-{i}", rg("cpu", fq("default", cpu=2)),
                cohort=f"flat-{i}"))
            store.create(KIND_LOCAL_QUEUE,
                         make_lq(f"lq-{i}", "default", cq=f"cq-{i}"))
        for i in range(3):
            store.create(KIND_WORKLOAD, make_wl(
                f"small-{i}", f"lq-{i}", cpu=2, creation_time=float(i)))
            store.create(KIND_WORKLOAD, make_wl(
                f"big-{i}", f"lq-{i}", cpu=4,
                creation_time=float(10 + i)))
        for _ in range(4):
            rt.tick()
        dump = rt.dump()
        # cpu=2 quota: only the small workloads fit, the big ones wait.
        assert {name: keys for name, keys in dump["admitted"].items()} \
            == {f"cq-{i}": [f"default/small-{i}"] for i in range(3)}
        # Quota edit flows as MODIFIED to the owning replica: raise
        # cq-1 to 8 cpu and its big workload admits.
        cq1 = make_cq("cq-1", ResourceGroup(
            covered_resources=("cpu",),
            flavors=(FlavorQuotas.make("default", cpu=8),)),
            cohort="flat-1")
        store.update(KIND_CLUSTER_QUEUE, cq1)
        for _ in range(4):
            rt.tick()
        assert sorted(rt.dump()["admitted"]["cq-1"]) == [
            "default/big-1", "default/small-1"]
        # Worker-published status mirrors back into the parent Store
        # (the GET/watch read surface): the admitted workload shows its
        # conditions + admission there, and the mirror's MODIFIED echo
        # must NOT route back (a takeover replay would doubly rebuild).
        mirrored = store.get(KIND_WORKLOAD, "default/big-1")
        assert mirrored.has_quota_reservation
        assert mirrored.admission.cluster_queue == "cq-1"
        # Workload DELETE routes to the owner and releases the quota.
        store.delete(KIND_WORKLOAD, "default/small-0")
        for _ in range(2):
            rt.tick()
        assert rt.dump()["admitted"]["cq-0"] == []
    finally:
        rt.close()


def test_cli_replica_mode_smoke(tmp_path):
    """`python -m kueue_tpu --replicas 2`: the single-binary CLI runs
    the manifests through real replica processes (the KUEUE_TPU_REPLICAS
    / --replicas opt-in) and reports the same admission summary shape;
    the merged multi-process trace lands at --trace-out."""
    import json
    import subprocess
    import sys

    from kueue_tpu.api import serialization
    from kueue_tpu.controllers.store import KIND_WORKLOAD
    from kueue_tpu.tracing import validate_chrome_trace

    wl_path = tmp_path / "workloads.yaml"
    docs = [serialization.encode(KIND_WORKLOAD, make_wl(
        f"wl-{i}", "user-queue", cpu=3, creation_time=float(i)))
        for i in range(3)]
    wl_path.write_text("\n---\n".join(json.dumps(d) for d in docs))
    trace_path = tmp_path / "trace.json"

    res = subprocess.run(
        [sys.executable, "-m", "kueue_tpu", "--replicas", "2",
         "--objects", "examples/single-clusterqueue-setup.yaml",
         "--objects", str(wl_path), "--ticks", "5",
         "--trace-out", str(trace_path)],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert res.returncode == 0, res.stderr[-2000:]
    summary = json.loads(res.stdout.strip().splitlines()[-1])
    assert summary["replicas"] == 2
    # 9 cpu quota, three 3-cpu workloads: all admitted.
    assert summary["clusterQueues"]["cluster-queue"]["admitted"] == 3
    assert summary["clusterQueues"]["cluster-queue"]["pending"] == 0
    doc = json.loads(trace_path.read_text())
    assert validate_chrome_trace(doc) == []
    assert doc["otherData"]["merged_processes"] >= 1


def test_synthetic_cq_filter_slices_union_to_whole():
    """The per-worker synthetic slice contract: filtered generation
    draws the identical random stream, so the union of slices equals
    the unfiltered world object for object."""
    from kueue_tpu.utils.synthetic import synthetic_objects

    kw = dict(num_cqs=12, num_cohorts=3, num_flavors=4, num_pending=40,
              usage_fill=0.5, seed=9)
    _fl, cqs, lqs, admitted, pending, _cs = synthetic_objects(**kw)
    def sig(w):
        return (w.name, w.priority,
                tuple((ps.count, tuple(sorted(ps.requests.items()))
                       if isinstance(ps.requests, dict) else ())
                      for ps in w.pod_sets))

    got_cqs, got_lqs, got_adm, got_pend = [], [], [], []
    for part in range(3):
        _fl2, c2, l2, a2, p2, _cs2 = synthetic_objects(
            cq_filter=lambda c: c % 3 == part, **kw)
        got_cqs += [c.name for c in c2]
        got_lqs += [lq.name for lq in l2]
        got_adm += [w.name for w in a2]
        got_pend += [sig(w) for w in p2]
    assert sorted(got_cqs) == sorted(c.name for c in cqs)
    assert sorted(got_lqs) == sorted(lq.name for lq in lqs)
    assert sorted(got_adm) == sorted(w.name for w in admitted)
    expect_pend = [sig(w) for w in pending]
    assert sorted(got_pend) == sorted(expect_pend)
