from kueue_tpu.api.resources import format_quantity, parse_quantity, resource_value


def test_parse_plain():
    assert parse_quantity(5) == 5.0
    assert parse_quantity("10") == 10.0
    assert parse_quantity(2.5) == 2.5


def test_parse_milli():
    assert parse_quantity("500m") == 0.5
    assert resource_value("cpu", "500m") == 500
    assert resource_value("cpu", 2) == 2000
    assert resource_value("cpu", "1.5") == 1500


def test_parse_binary():
    assert parse_quantity("1Ki") == 1024
    assert resource_value("memory", "10Gi") == 10 * 1024**3
    assert resource_value("memory", "512Mi") == 512 * 1024**2


def test_parse_decimal_suffixes():
    assert parse_quantity("2k") == 2000
    assert resource_value("memory", "1M") == 10**6


def test_counted_resources():
    assert resource_value("pods", 3) == 3
    assert resource_value("nvidia.com/gpu", "4") == 4


def test_format():
    assert format_quantity("cpu", 2000) == "2"
    assert format_quantity("cpu", 1500) == "1500m"
    assert format_quantity("memory", 10 * 1024**3) == "10Gi"
    assert format_quantity("pods", 7) == "7"
