"""End-to-end scheduler tests over the in-memory runtime (scenarios modeled
on the reference scheduler_test.go / integration suites)."""

from kueue_tpu.api.types import (
    BorrowWithinCohort,
    ClusterQueuePreemption,
    LabelSelector,
)
from kueue_tpu.controllers.runtime import Framework

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg


def single_cq_framework(quota_cpu=4, strategy="BestEffortFIFO", **cq_kwargs):
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=quota_cpu)), strategy=strategy,
        **cq_kwargs))
    fw.create_local_queue(make_lq("main", cq="cq"))
    return fw


def test_admit_until_full_then_park():
    fw = single_cq_framework(quota_cpu=4)
    for i in range(6):
        fw.submit(make_wl(f"w{i}", cpu=1, creation_time=float(i)))
    admitted = fw.run_until_settled()
    assert admitted == 4
    assert fw.admitted_workloads("cq") == [f"default/w{i}" for i in range(4)]
    assert fw.pending_workloads("cq") == 2


def test_fifo_order_respected():
    fw = single_cq_framework(quota_cpu=2)
    fw.submit(make_wl("later", cpu=2, creation_time=10.0))
    fw.submit(make_wl("earlier", cpu=2, creation_time=5.0))
    fw.tick()
    assert fw.admitted_workloads("cq") == ["default/earlier"]


def test_priority_order_respected():
    fw = single_cq_framework(quota_cpu=2)
    fw.submit(make_wl("low", cpu=2, priority=0, creation_time=1.0))
    fw.submit(make_wl("high", cpu=2, priority=10, creation_time=2.0))
    fw.tick()
    assert fw.admitted_workloads("cq") == ["default/high"]


def test_free_quota_admits_parked():
    fw = single_cq_framework(quota_cpu=2)
    w0 = make_wl("w0", cpu=2)
    fw.submit(w0)
    fw.submit(make_wl("w1", cpu=2))
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/w0"]
    fw.finish(w0)
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/w1"]


def test_preemption_end_to_end():
    fw = single_cq_framework(
        quota_cpu=4,
        preemption=ClusterQueuePreemption(within_cluster_queue="LowerPriority"))
    low = make_wl("low", cpu=4, priority=-1)
    fw.submit(low)
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/low"]
    fw.submit(make_wl("high", cpu=4, priority=10))
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/high"]
    assert low.is_evicted


def test_borrowing_and_reclaim():
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("default", cpu=4)), cohort="co",
        preemption=ClusterQueuePreemption(reclaim_within_cohort="Any")))
    fw.create_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("default", cpu=4)), cohort="co"))
    fw.create_local_queue(make_lq("a", cq="cq-a"))
    fw.create_local_queue(make_lq("b", cq="cq-b"))
    # cq-b borrows the whole cohort.
    for i in range(4):
        fw.submit(make_wl(f"b{i}", "b", cpu=2, creation_time=float(i)))
    fw.run_until_settled()
    assert len(fw.admitted_workloads("cq-b")) == 4
    # cq-a reclaims its nominal quota.
    fw.submit(make_wl("a0", "a", cpu=4, creation_time=10.0))
    fw.run_until_settled()
    assert fw.admitted_workloads("cq-a") == ["default/a0"]
    assert len(fw.admitted_workloads("cq-b")) == 2


def test_one_borrowing_admission_per_cohort_per_cycle():
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    for name in ("cq-a", "cq-b"):
        fw.create_cluster_queue(make_cq(
            name, rg("cpu", fq("default", cpu=2)), cohort="co"))
    fw.create_local_queue(make_lq("a", cq="cq-a"))
    fw.create_local_queue(make_lq("b", cq="cq-b"))
    # Both heads want 3 cpu (borrowing); cohort only fits one (4 total).
    fw.submit(make_wl("wa", "a", cpu=3, creation_time=1.0))
    fw.submit(make_wl("wb", "b", cpu=3, creation_time=2.0))
    admitted_first_tick = fw.scheduler.schedule(timeout=0.0)
    assert admitted_first_tick == 1
    fw.reconcile()
    fw.run_until_settled()
    total = fw.admitted_workloads("cq-a") + fw.admitted_workloads("cq-b")
    assert total == ["default/wa"]


def test_namespace_selector_mismatch():
    fw = single_cq_framework(
        quota_cpu=4, namespace_selector=LabelSelector.of(team="alpha"))
    fw.create_namespace("ns-beta", {"team": "beta"})
    fw.create_namespace("ns-alpha", {"team": "alpha"})
    fw.create_local_queue(make_lq("main", namespace="ns-beta", cq="cq"))
    fw.create_local_queue(make_lq("main", namespace="ns-alpha", cq="cq"))
    fw.submit(make_wl("w-beta", namespace="ns-beta", cpu=1))
    fw.submit(make_wl("w-alpha", namespace="ns-alpha", cpu=1))
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["ns-alpha/w-alpha"]
    assert fw.pending_workloads("cq") == 1


def test_strict_fifo_blocks_behind_head():
    fw = single_cq_framework(quota_cpu=4, strategy="StrictFIFO")
    fw.submit(make_wl("big", cpu=10, creation_time=1.0))
    fw.submit(make_wl("small", cpu=1, creation_time=2.0))
    # StrictFIFO requeues the inadmissible head into the heap, so the small
    # workload behind it is stuck waiting.
    for _ in range(3):
        fw.tick()
    assert fw.admitted_workloads("cq") == []


def test_best_effort_skips_blocked_head():
    fw = single_cq_framework(quota_cpu=4, strategy="BestEffortFIFO")
    fw.submit(make_wl("big", cpu=10, creation_time=1.0))
    fw.submit(make_wl("small", cpu=1, creation_time=2.0))
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/small"]


def test_two_phase_admission_checks():
    fw = single_cq_framework(quota_cpu=4, admission_checks=("prov",))
    wl = make_wl("w", cpu=2)
    fw.submit(wl)
    fw.run_until_settled()
    # Quota reserved but not admitted until the check is Ready.
    assert wl.has_quota_reservation
    assert not wl.is_admitted
    fw.set_admission_check_state(wl, "prov", "Ready")
    fw.reconcile()
    assert wl.is_admitted


def test_partial_admission():
    fw = single_cq_framework(quota_cpu=4)
    from kueue_tpu.api.types import PodSet
    wl = make_wl("w", pod_sets=[PodSet.make("main", count=8, min_count=2, cpu=1)])
    fw.submit(wl)
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/w"]
    assert wl.admission.pod_set_assignments[0].count == 4


def test_apply_admission_failure_requeues_cleanly():
    fw = single_cq_framework(quota_cpu=4)
    fails = {"n": 1}

    def flaky_apply(wl):
        if fails["n"] > 0:
            fails["n"] -= 1
            return False
        return True

    fw.scheduler.apply_admission = flaky_apply
    wl = make_wl("w", cpu=2)
    fw.submit(wl)
    fw.tick()
    # First apply failed: no reservation left behind, workload still queued.
    assert not wl.has_quota_reservation
    assert wl.admission is None
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/w"]


def test_scheduler_close_detaches_mirror_sink():
    """A retired scheduler's snapshot mirror must stop receiving dirty
    marks (Cache.unregister_dirty_sink) so a replacement scheduler over a
    long-lived cache doesn't leave the old sink accumulating names."""
    fw = single_cq_framework(quota_cpu=4)
    retired_sink = fw.scheduler._mirror._dirty
    fw.scheduler.close()
    retired_sink.clear()
    wl = make_wl("w", cpu=2)
    fw.submit(wl)
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/w"]
    # The cache mutated (admission accounted) but the detached sink saw
    # nothing.
    assert not retired_sink
