"""The scheduler driving the batched JAX solver must behave identically to
the referee path."""

import pytest

from kueue_tpu.api.types import ClusterQueuePreemption, PodSet
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.models.flavor_fit import BatchSolver

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg
from tests.test_solver_equivalence import random_problem


def batched_framework(quota_cpu=4, **cq_kwargs):
    fw = Framework(batch_solver=BatchSolver())
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=quota_cpu)), **cq_kwargs))
    fw.create_local_queue(make_lq("main", cq="cq"))
    return fw


def test_batched_admission():
    fw = batched_framework(quota_cpu=4)
    for i in range(6):
        fw.submit(make_wl(f"w{i}", cpu=1, creation_time=float(i)))
    assert fw.run_until_settled() == 4
    assert fw.admitted_workloads("cq") == [f"default/w{i}" for i in range(4)]


def test_batched_preemption():
    fw = batched_framework(
        quota_cpu=4,
        preemption=ClusterQueuePreemption(within_cluster_queue="LowerPriority"))
    low = make_wl("low", cpu=4, priority=-1)
    fw.submit(low)
    fw.run_until_settled()
    fw.submit(make_wl("high", cpu=4, priority=10))
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/high"]
    assert low.is_evicted


def test_batched_cohort_borrowing():
    fw = Framework(batch_solver=BatchSolver())
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("default", cpu=4)), cohort="co",
        preemption=ClusterQueuePreemption(reclaim_within_cohort="Any")))
    fw.create_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("default", cpu=4)), cohort="co"))
    fw.create_local_queue(make_lq("a", cq="cq-a"))
    fw.create_local_queue(make_lq("b", cq="cq-b"))
    for i in range(4):
        fw.submit(make_wl(f"b{i}", "b", cpu=2, creation_time=float(i)))
    fw.run_until_settled()
    assert len(fw.admitted_workloads("cq-b")) == 4
    fw.submit(make_wl("a0", "a", cpu=4, creation_time=10.0))
    fw.run_until_settled()
    assert fw.admitted_workloads("cq-a") == ["default/a0"]
    assert len(fw.admitted_workloads("cq-b")) == 2


def test_batched_partial_admission():
    fw = batched_framework(quota_cpu=4)
    wl = make_wl("w", pod_sets=[PodSet.make("main", count=8, min_count=2, cpu=1)])
    fw.submit(wl)
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/w"]
    assert wl.admission.pod_set_assignments[0].count == 4


@pytest.mark.parametrize("seed", [3, 11])
def test_batched_vs_referee_full_drain(seed):
    """Drain an identical random problem through both scheduler paths; the
    sets of admitted workloads must match exactly."""
    def build(batch_solver):
        cache, pending = random_problem(seed, num_wls=16)
        fw = Framework(batch_solver=batch_solver)
        fw.cache = cache
        fw.scheduler.cache = cache
        # Rebuild queue side from the cache's CQ specs.
        for name, lq in cache.local_queues.items():
            fw.queues.local_queues[name] = lq
        from kueue_tpu.queue.manager import PendingClusterQueue
        for cq_name, ccq in cache.cluster_queues.items():
            from tests.util import make_cq as _mk
            import kueue_tpu.api.types as t
            spec = t.ClusterQueue(
                name=cq_name,
                resource_groups=tuple(ccq.resource_groups),
                cohort=ccq.cohort_name,
                preemption=ccq.preemption,
                flavor_fungibility=ccq.flavor_fungibility)
            fw.queues.add_cluster_queue(spec)
        for wi in pending:
            fw.workloads[wi.key] = wi.obj
            fw.queues.add_or_update_workload(wi.obj)
        fw.run_until_settled(max_ticks=60)
        admitted = {
            key for cq in cache.cluster_queues.values() for key in cq.workloads}
        return admitted

    ref_admitted = build(None)
    jax_admitted = build(BatchSolver())
    assert jax_admitted == ref_admitted
