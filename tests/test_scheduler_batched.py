"""The scheduler driving the batched JAX solver must behave identically to
the referee path."""

import pytest

from kueue_tpu.api.types import ClusterQueuePreemption, PodSet
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.models.flavor_fit import BatchSolver

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg
from tests.test_solver_equivalence import random_problem


def batched_framework(quota_cpu=4, **cq_kwargs):
    fw = Framework(batch_solver=BatchSolver())
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq(
        "cq", rg("cpu", fq("default", cpu=quota_cpu)), **cq_kwargs))
    fw.create_local_queue(make_lq("main", cq="cq"))
    return fw


def test_batched_admission():
    fw = batched_framework(quota_cpu=4)
    for i in range(6):
        fw.submit(make_wl(f"w{i}", cpu=1, creation_time=float(i)))
    assert fw.run_until_settled() == 4
    assert fw.admitted_workloads("cq") == [f"default/w{i}" for i in range(4)]


def test_batched_preemption():
    fw = batched_framework(
        quota_cpu=4,
        preemption=ClusterQueuePreemption(within_cluster_queue="LowerPriority"))
    low = make_wl("low", cpu=4, priority=-1)
    fw.submit(low)
    fw.run_until_settled()
    fw.submit(make_wl("high", cpu=4, priority=10))
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/high"]
    assert low.is_evicted


def test_batched_cohort_borrowing():
    fw = Framework(batch_solver=BatchSolver())
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("default", cpu=4)), cohort="co",
        preemption=ClusterQueuePreemption(reclaim_within_cohort="Any")))
    fw.create_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("default", cpu=4)), cohort="co"))
    fw.create_local_queue(make_lq("a", cq="cq-a"))
    fw.create_local_queue(make_lq("b", cq="cq-b"))
    for i in range(4):
        fw.submit(make_wl(f"b{i}", "b", cpu=2, creation_time=float(i)))
    fw.run_until_settled()
    assert len(fw.admitted_workloads("cq-b")) == 4
    fw.submit(make_wl("a0", "a", cpu=4, creation_time=10.0))
    fw.run_until_settled()
    assert fw.admitted_workloads("cq-a") == ["default/a0"]
    assert len(fw.admitted_workloads("cq-b")) == 2


def test_batched_partial_admission():
    fw = batched_framework(quota_cpu=4)
    wl = make_wl("w", pod_sets=[PodSet.make("main", count=8, min_count=2, cpu=1)])
    fw.submit(wl)
    fw.run_until_settled()
    assert fw.admitted_workloads("cq") == ["default/w"]
    assert wl.admission.pod_set_assignments[0].count == 4
    # The cache accounts SPEC-count totals scaled back up (workload.go:
    # 230-234) — the job integration reclaims the difference later; the
    # reduced assignment usage (4000) would under-count held quota.
    assert fw.cache.usage("cq") == {"default": {"cpu": 8000}}


@pytest.mark.parametrize("seed", [3, 11])
def test_batched_vs_referee_full_drain(seed):
    """Drain an identical random problem through both scheduler paths; the
    sets of admitted workloads must match exactly."""
    def build(batch_solver):
        cache, pending = random_problem(seed, num_wls=16)
        fw = Framework(batch_solver=batch_solver)
        fw.cache = cache
        fw.scheduler.cache = cache
        # Rebuild queue side from the cache's CQ specs.
        for name, lq in cache.local_queues.items():
            fw.queues.local_queues[name] = lq
        from kueue_tpu.queue.manager import PendingClusterQueue
        for cq_name, ccq in cache.cluster_queues.items():
            from tests.util import make_cq as _mk
            import kueue_tpu.api.types as t
            spec = t.ClusterQueue(
                name=cq_name,
                resource_groups=tuple(ccq.resource_groups),
                cohort=ccq.cohort_name,
                preemption=ccq.preemption,
                flavor_fungibility=ccq.flavor_fungibility)
            fw.queues.add_cluster_queue(spec)
        for wi in pending:
            fw.workloads[wi.key] = wi.obj
            fw.queues.add_or_update_workload(wi.obj)
        fw.run_until_settled(max_ticks=60)
        admitted = {
            key for cq in cache.cluster_queues.values() for key in cq.workloads}
        return admitted

    ref_admitted = build(None)
    jax_admitted = build(BatchSolver())
    assert jax_admitted == ref_admitted


def test_usage_encoder_lockstep_with_cache():
    """The incremental UsageEncoder's fast path (refresh skipping
    version-matched rows + note_admission deltas) must serve exactly the
    tensors a full re-encode of a fresh snapshot would produce, across
    admissions, evictions, and requeues (solver/schema.py UsageEncoder)."""
    import numpy as np

    from kueue_tpu.solver import schema as sch

    solver = BatchSolver()
    fw = Framework(batch_solver=solver)
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_resource_flavor(make_flavor("spot"))
    fw.create_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("default", cpu=6), fq("spot", cpu=6)),
        cohort="co"))
    fw.create_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("default", cpu=2)), cohort="co"))
    fw.create_local_queue(make_lq("qa", cq="cq-a"))
    fw.create_local_queue(make_lq("qb", cq="cq-b"))

    def check():
        snap = fw.cache.snapshot()
        got = solver._usage_enc.refresh(snap)
        want = sch.encode_usage(snap, solver._enc)
        np.testing.assert_array_equal(got.usage, want.usage)
        np.testing.assert_array_equal(got.cohort_usage, want.cohort_usage)

    for i in range(5):
        fw.submit(make_wl(f"a{i}", "qa", cpu=2, creation_time=float(i)))
    fw.submit(make_wl("b0", "qb", cpu=4, creation_time=9.0))  # borrows
    fw.run_until_settled()
    assert solver._usage_enc is not None
    check()

    # Finishing a workload frees usage and bumps the allocatable
    # generation; the next solve rebuilds the encoding, and the fresh
    # encoder must still match.
    fw.finish(fw.workloads["default/a0"])
    fw.run_until_settled()
    check()

    # More churn through the delta fast path.
    fw.submit(make_wl("a9", "qa", cpu=1, creation_time=20.0))
    fw.run_until_settled()
    check()


def test_batched_partial_no_referee_calls(monkeypatch):
    """VERDICT r3 task 7 done-criterion: the batch path must not run the
    sequential referee for partial-admission probes — the min_count binary
    search rounds go through the batched device solve."""
    import kueue_tpu.scheduler.scheduler as sched_mod

    def boom(*a, **k):
        raise AssertionError("assign_flavors must not run in batch mode")

    monkeypatch.setattr(sched_mod, "assign_flavors", boom)
    fw = batched_framework(quota_cpu=4)
    wl = make_wl("w", pod_sets=[PodSet.make("main", count=8, min_count=2,
                                            cpu=1)])
    fw.submit(wl)
    fw.run_until_settled()
    assert wl.admission.pod_set_assignments[0].count == 4


@pytest.mark.parametrize("seed", range(6))
def test_batched_partial_equivalence(seed):
    """Randomized min_count workloads: batch-mode lockstep search admits
    the same workloads at the same reduced counts as the referee path."""
    import random

    def build(batch):
        from kueue_tpu.api.types import FlavorFungibility

        rnd = random.Random(seed)
        fw = Framework(batch_solver=BatchSolver() if batch else None)
        fw.create_resource_flavor(make_flavor("default"))
        fw.create_resource_flavor(make_flavor("second"))
        for c in range(3):
            # Mixed one- and two-flavor CQs with varying fungibility: the
            # probes' flavor-resume state must match the sequential
            # reducer's (it resumes from the PREVIOUS attempt, not from
            # this tick's full-count solve).
            flavors = [fq("default", cpu=rnd.randint(3, 10))]
            if rnd.random() < 0.6:
                flavors.append(fq("second", cpu=rnd.randint(3, 10)))
            fung = FlavorFungibility(
                when_can_borrow=rnd.choice(["Borrow", "TryNextFlavor"]),
                when_can_preempt=rnd.choice(["Preempt", "TryNextFlavor"]))
            fw.create_cluster_queue(make_cq(
                f"cq{c}", rg("cpu", *flavors),
                cohort="co" if rnd.random() < 0.5 else "",
                fungibility=fung))
            fw.create_local_queue(make_lq(f"q{c}", cq=f"cq{c}"))
        for i in range(10):
            c = rnd.randrange(3)
            count = rnd.randint(2, 9)
            min_count = rnd.randint(1, count) if rnd.random() < 0.7 else None
            fw.submit(make_wl(
                f"w{i}", f"q{c}", priority=rnd.randint(-1, 2),
                creation_time=float(i),
                pod_sets=[PodSet.make("main", count=count,
                                      min_count=min_count, cpu=1)]))
        fw.run_until_settled(max_ticks=60)
        return {
            key: (wl.admission.pod_set_assignments[0].count,
                  dict(wl.admission.pod_set_assignments[0].flavors))
            for key, wl in fw.workloads.items() if wl.is_admitted
        }

    ref = build(batch=False)
    got = build(batch=True)
    assert got == ref, f"seed={seed}: batch {got} != referee {ref}"
