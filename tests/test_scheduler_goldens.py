"""Golden scheduling scenarios transliterated from the reference's
TestSchedule table (pkg/scheduler/scheduler_test.go:60-1360): same fixture
(sales / eng-alpha / eng-beta / eng-gamma / lend cohorts), same workloads,
same expected admissions, preemptions, and queue placement after one cycle.

These pin decision-equivalence of the whole tick — entry ordering, cohort
cycle bookkeeping, borrowing rules, preemption targeting — not just the
flavor assigner. Each scenario runs under both the referee and the batched
device solver."""

import pytest

from kueue_tpu import features
from kueue_tpu.api.resources import resource_value
from kueue_tpu.api.types import (
    Admission,
    ClusterQueuePreemption,
    FlavorQuotas,
    LabelSelector,
    MatchExpression,
    PodSet,
    PodSetAssignment,
    ResourceQuota,
    Workload,
)
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.models.flavor_fit import BatchSolver

from tests.util import fq, make_cq, make_flavor, make_lq, rg


def cpu(v):
    return resource_value("cpu", v)


GPU = "example.com/gpu"


def dep_selector(value):
    return LabelSelector(
        match_expressions=(MatchExpression("dep", "In", (value,)),))


def fqr(flavor, *resources):
    """FlavorQuotas from (resource, nominal, borrowing[, lending]) rows —
    for resource names that are not Python identifiers."""
    return FlavorQuotas(name=flavor, resources=tuple(
        (r[0], ResourceQuota.make(r[0], *r[1:])) for r in resources))


def wl(name, namespace, queue, pod_sets, priority=0, creation=None):
    return Workload(name=name, namespace=namespace, queue_name=queue,
                    pod_sets=list(pod_sets), priority=priority,
                    creation_time=creation if creation is not None else 100.0)


def ps(name, count, requests, min_count=None):
    return PodSet(name=name, count=count, requests=dict(requests),
                  min_count=min_count)


def preadmit(fw, workload, cq_name, flavors_per_podset):
    """A workload already holding quota (wrappers.go ReserveQuota)."""
    workload.admission = Admission(
        cluster_queue=cq_name,
        pod_set_assignments=[
            PodSetAssignment(
                name=p.name, flavors=dict(fmap),
                resource_usage={r: v * p.count for r, v in p.requests.items()},
                count=p.count)
            for p, fmap in zip(workload.pod_sets, flavors_per_podset)
        ])
    workload.set_condition("QuotaReserved", True)
    workload.set_condition("Admitted", True)
    fw.workloads[workload.key] = workload
    fw.cache.add_or_update_workload(workload)
    return workload


def build(batch):
    fw = Framework(batch_solver=BatchSolver() if batch else None)
    for ns, dep in (("sales", "sales"), ("eng-alpha", "eng"),
                    ("eng-beta", "eng"), ("lend", "lend")):
        fw.create_namespace(ns, labels={"dep": dep})
    for f in ("default", "on-demand", "spot", "model-a"):
        fw.create_resource_flavor(make_flavor(f))

    # The reference fixture gives sales an explicit borrowingLimit of 0; a
    # cohort-less CQ cannot borrow anyway, and the webhook (like the
    # reference's, which the Go unit test bypasses) rejects a limit without
    # a cohort — so plain nominal quota here, same semantics.
    fw.create_cluster_queue(make_cq(
        "sales", rg("cpu", fq("default", cpu=50)),
        strategy="StrictFIFO", namespace_selector=dep_selector("sales")))
    fw.create_cluster_queue(make_cq(
        "eng-alpha",
        rg("cpu", fq("on-demand", cpu=(50, 50)), fq("spot", cpu=(100, 0))),
        cohort="eng", strategy="StrictFIFO",
        namespace_selector=dep_selector("eng")))
    fw.create_cluster_queue(make_cq(
        "eng-beta",
        rg("cpu", fq("on-demand", cpu=(50, 10)), fq("spot", cpu=(0, 100))),
        rg((GPU,), fqr("model-a", (GPU, 20, 0))),
        cohort="eng", strategy="StrictFIFO",
        namespace_selector=dep_selector("eng"),
        preemption=ClusterQueuePreemption(
            reclaim_within_cohort="Any",
            within_cluster_queue="LowerPriority")))
    fw.create_cluster_queue(make_cq(
        "flavor-nonexistent-cq",
        rg("cpu", fq("nonexistent-flavor", cpu=50)), strategy="StrictFIFO"))
    fw.create_cluster_queue(make_cq(
        "lend-a", rg("cpu", fq("default", cpu=(3, None, 2))), cohort="lend",
        namespace_selector=dep_selector("lend")))
    fw.create_cluster_queue(make_cq(
        "lend-b", rg("cpu", fq("default", cpu=(2, None, 2))), cohort="lend",
        namespace_selector=dep_selector("lend")))

    fw.create_local_queue(make_lq("main", "sales", cq="sales"))
    fw.create_local_queue(make_lq("blocked", "sales", cq="eng-alpha"))
    fw.create_local_queue(make_lq("main", "eng-alpha", cq="eng-alpha"))
    fw.create_local_queue(make_lq("main", "eng-beta", cq="eng-beta"))
    fw.create_local_queue(make_lq("flavor-nonexistent-queue", "sales",
                                  cq="flavor-nonexistent-cq"))
    fw.create_local_queue(make_lq("lend-a-queue", "lend", cq="lend-a"))
    fw.create_local_queue(make_lq("lend-b-queue", "lend", cq="lend-b"))
    return fw


@pytest.fixture(params=["referee", "batch"])
def golden(request):
    return build(batch=request.param == "batch")


def heap_keys(fw, cq):
    return {wi.key for wi in fw.queues.cluster_queues[cq].heap.items()}


def inadmissible_keys(fw, cq):
    return set(fw.queues.cluster_queues[cq].inadmissible)


def assert_admission(fw, key, cq_name, podsets):
    """podsets: [(name, {resource: flavor}, {resource: usage}, count)]."""
    w = fw.workloads[key]
    assert w.admission is not None, f"{key} not admitted"
    assert w.admission.cluster_queue == cq_name
    got = [(a.name, dict(a.flavors), dict(a.resource_usage), a.count)
           for a in w.admission.pod_set_assignments]
    assert got == list(podsets), f"{key}: {got}"


def not_admitted(fw, key):
    assert fw.workloads[key].admission is None, key


# scheduler_test.go "workload fits in single clusterQueue"
def test_fits_in_single_cluster_queue(golden):
    fw = golden
    fw.submit(wl("foo", "sales", "main", [ps("one", 10, {"cpu": cpu(1)})]))
    fw.tick()
    assert_admission(fw, "sales/foo", "sales",
                     [("one", {"cpu": "default"}, {"cpu": cpu(10)}, 10)])


# "single clusterQueue full": the head stays in the heap (StrictFIFO)
def test_single_cluster_queue_full(golden):
    fw = golden
    assigned = wl("assigned", "sales", "main", [ps("one", 40, {"cpu": cpu(1)})])
    preadmit(fw, assigned, "sales", [{"cpu": "default"}])
    fw.submit(wl("new", "sales", "main", [ps("one", 11, {"cpu": cpu(1)})]))
    fw.tick()
    not_admitted(fw, "sales/new")
    assert heap_keys(fw, "sales") == {"sales/new"}


# "failed to match clusterQueue selector": inadmissible on eng-alpha
def test_namespace_selector_mismatch(golden):
    fw = golden
    fw.submit(wl("new", "sales", "blocked", [ps("one", 1, {"cpu": cpu(1)})]))
    fw.tick()
    not_admitted(fw, "sales/new")
    assert inadmissible_keys(fw, "eng-alpha") == {"sales/new"}


# "admit in different cohorts"
def test_admit_in_different_cohorts(golden):
    fw = golden
    fw.submit(wl("new", "sales", "main", [ps("one", 1, {"cpu": cpu(1)})]))
    fw.submit(wl("new", "eng-alpha", "main",
                 [ps("one", 51, {"cpu": cpu(1)})]))  # borrows
    fw.tick()
    assert_admission(fw, "sales/new", "sales",
                     [("one", {"cpu": "default"}, {"cpu": cpu(1)}, 1)])
    assert_admission(fw, "eng-alpha/new", "eng-alpha",
                     [("one", {"cpu": "on-demand"}, {"cpu": cpu(51)}, 51)])


# "admit in same cohort with no borrowing"
def test_admit_in_same_cohort_no_borrowing(golden):
    fw = golden
    fw.submit(wl("new", "eng-alpha", "main", [ps("one", 40, {"cpu": cpu(1)})],
                 creation=10.0))
    fw.submit(wl("new", "eng-beta", "main", [ps("one", 40, {"cpu": cpu(1)})],
                 creation=11.0))
    fw.tick()
    assert_admission(fw, "eng-alpha/new", "eng-alpha",
                     [("one", {"cpu": "on-demand"}, {"cpu": cpu(40)}, 40)])
    assert_admission(fw, "eng-beta/new", "eng-beta",
                     [("one", {"cpu": "on-demand"}, {"cpu": cpu(40)}, 40)])


# "assign multiple resources and flavors"
def test_assign_multiple_resources_and_flavors(golden):
    fw = golden
    fw.submit(wl("new", "eng-beta", "main", [
        ps("one", 10, {"cpu": cpu(6), GPU: 1}),
        ps("two", 40, {"cpu": cpu(1)}),
    ]))
    fw.tick()
    assert_admission(fw, "eng-beta/new", "eng-beta", [
        ("one", {"cpu": "on-demand", GPU: "model-a"},
         {"cpu": cpu(60), GPU: 10}, 10),
        ("two", {"cpu": "spot"}, {"cpu": cpu(40)}, 40),
    ])


# "cannot borrow if cohort was assigned and would result in overadmission"
def test_cannot_borrow_when_cohort_assigned_overadmission(golden):
    fw = golden
    fw.submit(wl("new", "eng-alpha", "main", [ps("one", 45, {"cpu": cpu(1)})],
                 creation=10.0))
    fw.submit(wl("new", "eng-beta", "main", [ps("one", 56, {"cpu": cpu(1)})],
                 creation=11.0))
    fw.tick()
    assert_admission(fw, "eng-alpha/new", "eng-alpha",
                     [("one", {"cpu": "on-demand"}, {"cpu": cpu(45)}, 45)])
    not_admitted(fw, "eng-beta/new")
    assert heap_keys(fw, "eng-beta") == {"eng-beta/new"}


# "can borrow if cohort was assigned and will not result in overadmission"
def test_can_borrow_when_cohort_assigned_no_overadmission(golden):
    fw = golden
    fw.submit(wl("new", "eng-alpha", "main", [ps("one", 45, {"cpu": cpu(1)})],
                 creation=10.0))
    fw.submit(wl("new", "eng-beta", "main", [ps("one", 55, {"cpu": cpu(1)})],
                 creation=11.0))
    fw.tick()
    assert_admission(fw, "eng-alpha/new", "eng-alpha",
                     [("one", {"cpu": "on-demand"}, {"cpu": cpu(45)}, 45)])
    assert_admission(fw, "eng-beta/new", "eng-beta",
                     [("one", {"cpu": "on-demand"}, {"cpu": cpu(55)}, 55)])


# "can borrow if needs reclaim from cohort in different flavor"
def test_borrow_beats_reclaim_pending_in_other_cq(golden):
    fw = golden
    fw.submit(wl("can-reclaim", "eng-alpha", "main",
                 [ps("main", 1, {"cpu": cpu(100)})], creation=10.0))
    fw.submit(wl("needs-to-borrow", "eng-beta", "main",
                 [ps("main", 1, {"cpu": cpu(1)})], creation=11.0))
    preadmit(fw, wl("user-on-demand", "eng-beta", "",
                    [ps("main", 1, {"cpu": cpu(50)})]),
             "eng-beta", [{"cpu": "on-demand"}])
    preadmit(fw, wl("user-spot", "eng-beta", "",
                    [ps("main", 1, {"cpu": cpu(1)})]),
             "eng-beta", [{"cpu": "spot"}])
    fw.scheduler.schedule(timeout=0.0)
    assert_admission(fw, "eng-beta/needs-to-borrow", "eng-beta",
                     [("main", {"cpu": "on-demand"}, {"cpu": cpu(1)}, 1)])
    not_admitted(fw, "eng-alpha/can-reclaim")
    assert heap_keys(fw, "eng-alpha") == {"eng-alpha/can-reclaim"}


# "workload exceeds lending limit when borrow in cohort"
def test_lending_limit_blocks_borrowing(golden):
    fw = golden
    features.set_enabled(features.LENDING_LIMIT, True)
    preadmit(fw, wl("a", "lend", "",
                    [ps("main", 1, {"cpu": cpu(2)})]),
             "lend-b", [{"cpu": "default"}])
    fw.submit(wl("b", "lend", "lend-b-queue",
                 [ps("main", 1, {"cpu": cpu(3)})]))
    fw.tick()
    not_admitted(fw, "lend/b")
    assert inadmissible_keys(fw, "lend-b") == {"lend/b"}


# "preempt workloads in ClusterQueue and cohort"
def test_preempt_in_cluster_queue_and_cohort(golden):
    fw = golden
    fw.submit(wl("preemptor", "eng-beta", "main",
                 [ps("main", 1, {"cpu": cpu(20)})]))
    preadmit(fw, wl("use-all-spot", "eng-alpha", "",
                    [ps("main", 1, {"cpu": cpu(100)})]),
             "eng-alpha", [{"cpu": "spot"}])
    low1 = preadmit(fw, wl("low-1", "eng-beta", "",
                           [ps("main", 1, {"cpu": cpu(30)})], priority=-1),
                    "eng-beta", [{"cpu": "on-demand"}])
    low2 = preadmit(fw, wl("low-2", "eng-beta", "",
                           [ps("main", 1, {"cpu": cpu(10)})], priority=-2),
                    "eng-beta", [{"cpu": "on-demand"}])
    borrower = preadmit(fw, wl("borrower", "eng-alpha", "",
                               [ps("main", 1, {"cpu": cpu(60)})]),
                        "eng-alpha", [{"cpu": "on-demand"}])
    fw.scheduler.schedule(timeout=0.0)
    not_admitted(fw, "eng-beta/preemptor")
    assert heap_keys(fw, "eng-beta") == {"eng-beta/preemptor"}
    evicted = {w.key for w in (low1, low2, borrower) if w.is_evicted}
    assert evicted == {"eng-beta/low-2", "eng-alpha/borrower"}
    assert not fw.workloads["eng-alpha/use-all-spot"].is_evicted
    assert not low1.is_evicted


# "cannot borrow resource not listed in clusterQueue"
def test_cannot_borrow_resource_not_listed(golden):
    fw = golden
    fw.submit(wl("new", "eng-alpha", "main", [ps("main", 1, {GPU: 1})]))
    fw.tick()
    not_admitted(fw, "eng-alpha/new")
    assert heap_keys(fw, "eng-alpha") == {"eng-alpha/new"}


# "not enough resources to borrow, fallback to next flavor"
def test_borrow_fallback_to_next_flavor(golden):
    fw = golden
    fw.submit(wl("new", "eng-alpha", "main",
                 [ps("one", 60, {"cpu": cpu(1)})]))
    preadmit(fw, wl("existing", "eng-beta", "",
                    [ps("one", 45, {"cpu": cpu(1)})]),
             "eng-beta", [{"cpu": "on-demand"}])
    fw.tick()
    assert_admission(fw, "eng-alpha/new", "eng-alpha",
                     [("one", {"cpu": "spot"}, {"cpu": cpu(60)}, 60)])


# "workload should not fit in clusterQueue with nonexistent flavor"
def test_nonexistent_flavor_cluster_queue(golden):
    fw = golden
    fw.submit(wl("foo", "sales", "flavor-nonexistent-queue",
                 [ps("main", 1, {"cpu": cpu(1)})]))
    fw.tick()
    not_admitted(fw, "sales/foo")
    assert heap_keys(fw, "flavor-nonexistent-cq") == {"sales/foo"}


# "partial admission single variable pod set": 50 pods, min 20 -> 25 fit
def test_partial_admission_single_variable_podset(golden):
    fw = golden
    fw.submit(wl("new", "sales", "main",
                 [ps("one", 50, {"cpu": cpu(2)}, min_count=20)]))
    fw.tick()
    assert_admission(fw, "sales/new", "sales",
                     [("one", {"cpu": "default"}, {"cpu": cpu(50)}, 25)])


def submit_unvalidated(fw, workload):
    """Inject below the webhook layer (the reference unit test talks to the
    queues directly; its webhook also caps minCount at one podSet)."""
    fw.workloads[workload.key] = workload
    fw.queues.add_or_update_workload(workload)


# "partial admission multiple variable pod sets"
def test_partial_admission_multiple_variable_podsets(golden):
    fw = golden
    submit_unvalidated(fw, wl("new", "sales", "main", [
        ps("one", 20, {"cpu": cpu(1)}),
        ps("two", 30, {"cpu": cpu(1)}, min_count=10),
        ps("three", 15, {"cpu": cpu(1)}, min_count=5),
    ]))
    fw.tick()
    assert_admission(fw, "sales/new", "sales", [
        ("one", {"cpu": "default"}, {"cpu": cpu(20)}, 20),
        ("two", {"cpu": "default"}, {"cpu": cpu(20)}, 20),
        ("three", {"cpu": "default"}, {"cpu": cpu(10)}, 10),
    ])


# "partial admission disabled, multiple variable pod sets"
def test_partial_admission_disabled(golden):
    fw = golden
    features.set_enabled(features.PARTIAL_ADMISSION, False)
    submit_unvalidated(fw, wl("new", "sales", "main", [
        ps("one", 20, {"cpu": cpu(1)}),
        ps("two", 30, {"cpu": cpu(1)}, min_count=10),
        ps("three", 15, {"cpu": cpu(1)}, min_count=5),
    ]))
    fw.tick()
    not_admitted(fw, "sales/new")
    assert heap_keys(fw, "sales") == {"sales/new"}


def _same_cycle_borrow_fixture(fw):
    preemption = ClusterQueuePreemption(
        reclaim_within_cohort="Any", within_cluster_queue="LowerPriority")
    for name in ("cq1", "cq2", "cq3"):
        fw.create_cluster_queue(make_cq(
            name, rg(("r1", "r2"), fqr("default", ("r1", 10, 10),
                                       ("r2", 10, 10))),
            cohort="co", preemption=preemption))
    for i in (1, 2, 3):
        fw.create_local_queue(make_lq(f"lq{i}", "sales", cq=f"cq{i}"))


# "two workloads can borrow different resources from the same flavor in the
# same cycle"
def test_same_cycle_borrow_different_resources(golden):
    fw = golden
    _same_cycle_borrow_fixture(fw)
    fw.submit(wl("wl1", "sales", "lq1", [ps("main", 1, {"r1": 16})],
                 priority=-1))
    fw.submit(wl("wl2", "sales", "lq2", [ps("main", 1, {"r2": 16})],
                 priority=-2))
    fw.tick()
    assert_admission(fw, "sales/wl1", "cq1",
                     [("main", {"r1": "default"}, {"r1": 16}, 1)])
    assert_admission(fw, "sales/wl2", "cq2",
                     [("main", {"r2": "default"}, {"r2": 16}, 1)])


# "two workloads can borrow the same resources ... if fits in cohort quota"
def test_same_cycle_borrow_same_resource_fits(golden):
    fw = golden
    _same_cycle_borrow_fixture(fw)
    fw.submit(wl("wl1", "sales", "lq1", [ps("main", 1, {"r1": 16})],
                 priority=-1))
    fw.submit(wl("wl2", "sales", "lq2", [ps("main", 1, {"r1": 14})],
                 priority=-2))
    fw.tick()
    assert_admission(fw, "sales/wl1", "cq1",
                     [("main", {"r1": "default"}, {"r1": 16}, 1)])
    assert_admission(fw, "sales/wl2", "cq2",
                     [("main", {"r1": "default"}, {"r1": 14}, 1)])


# "only one workload can borrow ... if cohort quota cannot fit"
def test_same_cycle_borrow_same_resource_does_not_fit(golden):
    fw = golden
    _same_cycle_borrow_fixture(fw)
    fw.submit(wl("wl1", "sales", "lq1", [ps("main", 1, {"r1": 16})],
                 priority=-1))
    fw.submit(wl("wl2", "sales", "lq2", [ps("main", 1, {"r1": 16})],
                 priority=-2))
    fw.tick()
    assert_admission(fw, "sales/wl1", "cq1",
                     [("main", {"r1": "default"}, {"r1": 16}, 1)])
    not_admitted(fw, "sales/wl2")
    assert heap_keys(fw, "cq2") == {"sales/wl2"}


# "no overadmission while borrowing": eng-gamma already borrows on-demand;
# beta (earliest) and alpha (1 cpu) admit, gamma's new workload must wait.
def test_no_overadmission_while_borrowing(golden):
    fw = golden
    fw.create_cluster_queue(make_cq(
        "eng-gamma",
        rg("cpu", fq("on-demand", cpu=(50, 10)), fq("spot", cpu=(0, 100))),
        cohort="eng", namespace_selector=dep_selector("eng"),
        preemption=ClusterQueuePreemption(
            reclaim_within_cohort="Any",
            within_cluster_queue="LowerPriority")))
    fw.create_namespace("eng-gamma", labels={"dep": "eng"})
    fw.create_local_queue(make_lq("main", "eng-gamma", cq="eng-gamma"))

    preadmit(fw, wl("existing", "eng-gamma", "", [
        ps("borrow-on-demand", 51, {"cpu": cpu(1)}),
        ps("use-all-spot", 100, {"cpu": cpu(1)}),
    ]), "eng-gamma", [{"cpu": "on-demand"}, {"cpu": "spot"}])

    fw.submit(wl("new", "eng-beta", "main", [ps("one", 50, {"cpu": cpu(1)})],
                 creation=98.0))
    fw.submit(wl("new-alpha", "eng-alpha", "main",
                 [ps("one", 1, {"cpu": cpu(1)})], creation=99.0))
    fw.submit(wl("new-gamma", "eng-gamma", "main",
                 [ps("one", 50, {"cpu": cpu(1)})], creation=100.0))
    fw.scheduler.schedule(timeout=0.0)
    assert_admission(fw, "eng-beta/new", "eng-beta",
                     [("one", {"cpu": "on-demand"}, {"cpu": cpu(50)}, 50)])
    assert_admission(fw, "eng-alpha/new-alpha", "eng-alpha",
                     [("one", {"cpu": "on-demand"}, {"cpu": cpu(1)}, 1)])
    not_admitted(fw, "eng-gamma/new-gamma")
    assert heap_keys(fw, "eng-gamma") == {"eng-gamma/new-gamma"}


# "preemption while borrowing, workload waiting for preemption should not
# block a borrowing workload in another CQ"
def test_preemption_wait_does_not_block_borrower(golden):
    fw = golden
    from kueue_tpu.api.types import BorrowWithinCohort
    preemption = ClusterQueuePreemption(
        reclaim_within_cohort="LowerPriority",
        borrow_within_cohort=BorrowWithinCohort(policy="LowerPriority"))
    fw.create_cluster_queue(make_cq(
        "cq-shared", rg("cpu", fq("default", cpu=(4, 0))),
        cohort="preemption-while-borrowing"))
    fw.create_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("default", cpu=(0, 3))),
        cohort="preemption-while-borrowing", preemption=preemption))
    fw.create_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("default", cpu=0)),
        cohort="preemption-while-borrowing", preemption=preemption))
    fw.create_local_queue(make_lq("lq-a", "eng-alpha", cq="cq-a"))
    fw.create_local_queue(make_lq("lq-b", "eng-beta", cq="cq-b"))

    preadmit(fw, wl("admitted-a", "eng-alpha", "lq-a",
                    [ps("main", 1, {"cpu": cpu(2)})]),
             "cq-a", [{"cpu": "default"}])
    fw.submit(wl("a", "eng-alpha", "lq-a", [ps("main", 1, {"cpu": cpu(3)})],
                 creation=101.0))
    fw.submit(wl("b", "eng-beta", "lq-b", [ps("main", 1, {"cpu": cpu(1)})],
                 creation=102.0))
    fw.scheduler.schedule(timeout=0.0)
    assert_admission(fw, "eng-beta/b", "cq-b",
                     [("main", {"cpu": "default"}, {"cpu": cpu(1)}, 1)])
    not_admitted(fw, "eng-alpha/a")
    assert inadmissible_keys(fw, "cq-a") == {"eng-alpha/a"}


# "workload fits in single clusterQueue, with check state ready": Admitted
# syncs at admit time because every recorded check state is Ready.
def test_fits_with_check_state_ready(golden):
    from kueue_tpu.api.types import AdmissionCheckState
    fw = golden
    w = wl("foo", "sales", "main", [ps("one", 10, {"cpu": cpu(1)})])
    w.admission_check_states["check"] = AdmissionCheckState(
        name="check", state="Ready")
    fw.submit(w)
    fw.tick()
    assert_admission(fw, "sales/foo", "sales",
                     [("one", {"cpu": "default"}, {"cpu": cpu(10)}, 10)])
    assert w.is_admitted


# "workload fits in single clusterQueue, with check state pending": quota
# reserved, but a Pending check state blocks Admitted at admit time.
def test_fits_with_check_state_pending(golden):
    from kueue_tpu.api.types import AdmissionCheckState
    fw = golden
    w = wl("foo", "sales", "main", [ps("one", 10, {"cpu": cpu(1)})])
    w.admission_check_states["check"] = AdmissionCheckState(
        name="check", state="Pending")
    fw.submit(w)
    fw.scheduler.schedule(timeout=0.0)
    assert_admission(fw, "sales/foo", "sales",
                     [("one", {"cpu": "default"}, {"cpu": cpu(10)}, 10)])
    assert w.has_quota_reservation and not w.is_admitted


# "error during admission": the apply fails, the assumption rolls back and
# the head goes back to its heap.
def test_error_during_admission(golden):
    fw = golden
    fw.scheduler.apply_admission = lambda _wl: False
    fw.submit(wl("foo", "sales", "main", [ps("one", 10, {"cpu": cpu(1)})]))
    fw.scheduler.schedule(timeout=0.0)
    not_admitted(fw, "sales/foo")
    assert heap_keys(fw, "sales") == {"sales/foo"}
    assert fw.cache.usage("sales")["default"]["cpu"] == 0


# "can borrow if needs reclaim from cohort in different flavor": alpha's
# reclaim pends on on-demand, but beta's borrow rides the same cycle
# because the pending preemption holds a different... (scheduler_test.go:631)
def test_can_borrow_when_reclaim_needs_different_flavor(golden):
    fw = golden
    preadmit(fw, wl("user-on-demand", "eng-beta", "main",
                    [ps("main", 1, {"cpu": cpu(50)})]),
             "eng-beta", [{"cpu": "on-demand"}])
    preadmit(fw, wl("user-spot", "eng-beta", "main",
                    [ps("main", 1, {"cpu": cpu(1)})]),
             "eng-beta", [{"cpu": "spot"}])
    fw.submit(wl("can-reclaim", "eng-alpha", "main",
                 [ps("main", 1, {"cpu": cpu(100)})], creation=101.0))
    fw.submit(wl("needs-to-borrow", "eng-beta", "main",
                 [ps("main", 1, {"cpu": cpu(1)})], creation=102.0))
    fw.scheduler.schedule(timeout=0.0)
    assert_admission(fw, "eng-beta/needs-to-borrow", "eng-beta",
                     [("main", {"cpu": "on-demand"}, {"cpu": cpu(1)}, 1)])
    not_admitted(fw, "eng-alpha/can-reclaim")
    assert heap_keys(fw, "eng-alpha") == {"eng-alpha/can-reclaim"}


# "multiple CQs need preemption": a preemption pending in one cohort must
# not block the other cohort's preemptor from issuing its own.
def test_multiple_cqs_need_preemption(golden):
    fw = golden
    fw.create_cluster_queue(make_cq(
        "other-alpha", rg("cpu", fq("on-demand", cpu=(50, 50))),
        cohort="other"))
    fw.create_cluster_queue(make_cq(
        "other-beta", rg("cpu", fq("on-demand", cpu=(50, 10))),
        cohort="other",
        preemption=ClusterQueuePreemption(
            reclaim_within_cohort="Any",
            within_cluster_queue="LowerPriority")))
    fw.create_local_queue(make_lq("other", "eng-alpha", cq="other-alpha"))
    fw.create_local_queue(make_lq("other", "eng-beta", cq="other-beta"))
    use_all = wl("use-all", "eng-alpha", "other",
                 [ps("main", 1, {"cpu": cpu(100)})])
    preadmit(fw, use_all, "other-alpha", [{"cpu": "on-demand"}])
    fw.submit(wl("preemptor", "eng-beta", "other",
                 [ps("main", 1, {"cpu": cpu(1)})], priority=-1,
                 creation=101.0))
    fw.submit(wl("pending", "eng-alpha", "other",
                 [ps("main", 1, {"cpu": cpu(1)})], priority=1,
                 creation=102.0))
    fw.scheduler.schedule(timeout=0.0)
    # The preemptor issued its reclaim and waits; the borrowing victim is
    # evicted; the other CQ's head is inadmissible this cycle.
    assert use_all.is_evicted
    not_admitted(fw, "eng-beta/preemptor")
    assert heap_keys(fw, "other-beta") == {"eng-beta/preemptor"}
    assert inadmissible_keys(fw, "other-alpha") == {"eng-alpha/pending"}


# "workload should not fit in nonexistent clusterQueue"
def test_nonexistent_cluster_queue(golden):
    fw = golden
    fw.submit(wl("foo", "sales", "cq-nonexistent-queue",
                 [ps("main", 1, {"cpu": cpu(1)})]))
    fw.tick()
    not_admitted(fw, "sales/foo")
    # Never enqueued anywhere: the LocalQueue doesn't exist.
    assert all("sales/foo" not in heap_keys(fw, name)
               for name in fw.queues.cluster_queues)


# "partial admission single variable pod set, preempt first": the reducer
# stops at the first count whose preemption can succeed — no reduction
# below what eviction frees.
def test_partial_admission_preempt_first(golden):
    fw = golden
    old = wl("old", "eng-beta", "main", [ps("one", 10, {GPU: 1})],
             priority=-4)
    preadmit(fw, old, "eng-beta", [{GPU: "model-a"}])
    fw.submit(wl("new", "eng-beta", "main",
                 [ps("one", 20, {GPU: 1}, min_count=10)], priority=4,
                 creation=101.0))
    fw.scheduler.schedule(timeout=0.0)
    assert old.is_evicted
    not_admitted(fw, "eng-beta/new")
    assert heap_keys(fw, "eng-beta") == {"eng-beta/new"}
