"""Secondary decision tables transliterated from the reference.

The three big tables (TestSchedule / TestAssignFlavors / TestPreemption)
live in test_{scheduler,flavorassigner,preemption}_goldens.py; this file
carries the remaining reference suites that pin the tick's supporting
decisions:

- TestEntryOrdering (scheduler_test.go:1483) — the admission sort under
  PrioritySortingWithinCohort x pods-ready requeuing-timestamp configs.
- TestResourcesToReserve (scheduler_test.go:2196) — how much of a
  preempting assignment's usage reserves cohort quota in the cycle.
- TestLastAssignmentOutdated (flavorassigner_test.go:2302) — when
  flavor-fungibility resume state is dropped on allocatable-generation
  movement.
- TestRequeueAndUpdate (scheduler_test.go:2056) — requeue destination
  (heap vs inadmissible parking) and the Pending status surface per
  entry status.
"""

from kueue_tpu import features
from kueue_tpu.api.types import Condition, ResourceQuota, Workload
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.workload import (
    AssignmentClusterQueueState,
    WorkloadInfo,
    WorkloadOrdering,
)
from kueue_tpu.queue.manager import Manager, RequeueReason
from kueue_tpu.scheduler import scheduler as scheduler_mod
from kueue_tpu.scheduler.scheduler import (
    ASSUMED,
    NOMINATED,
    NOT_NOMINATED,
    SKIPPED,
    Entry,
    Scheduler,
    _resources_to_reserve,
)
from kueue_tpu.solver.modes import FIT, PREEMPT
from kueue_tpu.solver.referee import (
    Assignment,
    FlavorAssignment,
    PodSetAssignmentResult,
)

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg

NOW = 1_000_000.0


# -- TestEntryOrdering (scheduler_test.go:1483-1637) -------------------------


def _entry(name, creation, *, priority=0, borrowing=False, evicted_at=None):
    wl = Workload(name=name, namespace="ns", queue_name="q",
                  priority=priority, creation_time=creation, pod_sets=[])
    if evicted_at is not None:
        wl.conditions.append(Condition(
            "Evicted", True, "PodsReadyTimeout", "",
            last_transition_time=evicted_at))
    a = Assignment(borrowing=borrowing)
    return Entry(info=WorkloadInfo(wl, cluster_queue="cq"), assignment=a)


def _ordering_input():
    return [
        _entry("old_borrowing", NOW, borrowing=True),
        _entry("old", NOW + 1),
        _entry("new", NOW + 3),
        _entry("high_pri_borrowing", NOW + 3, priority=1, borrowing=True),
        _entry("new_high_pri", NOW + 4, priority=1),
        _entry("new_borrowing", NOW + 3, borrowing=True),
        _entry("evicted_borrowing", NOW + 1, borrowing=True,
               evicted_at=NOW + 2),
        _entry("recently_evicted", NOW, evicted_at=NOW + 2),
    ]


ORDERING_CASES = [
    # (priority_sorting, requeuing_timestamp, want order)
    (True, "Eviction",
     ["new_high_pri", "old", "recently_evicted", "new",
      "high_pri_borrowing", "old_borrowing", "evicted_borrowing",
      "new_borrowing"]),
    (True, "Creation",
     ["new_high_pri", "recently_evicted", "old", "new",
      "high_pri_borrowing", "old_borrowing", "evicted_borrowing",
      "new_borrowing"]),
    (False, "Eviction",
     ["old", "recently_evicted", "new", "new_high_pri",
      "old_borrowing", "evicted_borrowing", "high_pri_borrowing",
      "new_borrowing"]),
    (False, "Creation",
     ["recently_evicted", "old", "new", "new_high_pri",
      "old_borrowing", "evicted_borrowing", "high_pri_borrowing",
      "new_borrowing"]),
]


def test_entry_ordering_table():
    for priority_sorting, ts, want in ORDERING_CASES:
        features.set_enabled(features.PRIORITY_SORTING_WITHIN_COHORT,
                             priority_sorting)
        sched = Scheduler(
            Manager(), Cache(),
            ordering=WorkloadOrdering(pods_ready_requeuing_timestamp=ts))
        entries = _ordering_input()
        sched._sort_entries(entries)
        got = [e.info.obj.name for e in entries]
        assert got == want, (priority_sorting, ts)
        # The vectorized lexsort path and the tuple-key sort must agree.
        small = _ordering_input()
        small.sort(key=sched._entry_sort_key)
        assert [e.info.obj.name for e in small] == want, \
            (priority_sorting, ts, "tuple-key path")


# -- TestResourcesToReserve (scheduler_test.go:2196-2331) --------------------


def _reserve_cq(cq_usage):
    cache = Cache()
    for f in ("on-demand", "spot", "model-a", "model-b"):
        cache.add_or_update_resource_flavor(make_flavor(f))
    cache.add_cluster_queue(make_cq(
        "cq",
        rg(("memory",),
           fq("on-demand", memory=100),
           fq("spot", memory=(0, 100))),
        rg(("gpu",),
           fq("model-a", gpu=(10, 0)),
           fq("model-b", gpu=(10, 5))),
        cohort="eng"))
    snap = cache.snapshot()
    cq = snap.cluster_queues["cq"]
    for fname, res in cq_usage.items():
        for rname, val in res.items():
            cq.usage.setdefault(fname, {})[rname] = val
    return cq


def _reserve_entry(mode, borrowing, usage):
    pod_sets = []
    for ps_name, rname in (("memory", "memory"), ("gpu", "gpu")):
        psa = PodSetAssignmentResult(
            name=ps_name,
            flavors={rname: FlavorAssignment(name="", mode=mode)})
        if mode != FIT:
            psa.reasons = ["preempt"]
        pod_sets.append(psa)
    a = Assignment(pod_sets=pod_sets, borrowing=borrowing, usage=usage)
    wl = Workload(name="w", namespace="ns", queue_name="q", pod_sets=[])
    return Entry(info=WorkloadInfo(wl, cluster_queue="cq"), assignment=a)


RESERVE_CASES = [
    # (mode, borrowing, assignment usage, cq usage, want reserved)
    (PREEMPT, False,
     {"on-demand": {"memory": 50}, "model-a": {"gpu": 6}},
     {"on-demand": {"memory": 60}, "spot": {"memory": 50},
      "model-a": {"gpu": 6}, "model-b": {"gpu": 2}},
     {"on-demand": {"memory": 40}, "model-a": {"gpu": 4}}),
    (PREEMPT, False,
     {"on-demand": {"memory": 30}, "model-a": {"gpu": 2}},
     {"on-demand": {"memory": 60}, "spot": {"memory": 50},
      "model-a": {"gpu": 2}, "model-b": {"gpu": 2}},
     {"on-demand": {"memory": 30}, "model-a": {"gpu": 2}}),
    (FIT, False,
     {"on-demand": {"memory": 50}, "model-a": {"gpu": 2}},
     {"on-demand": {"memory": 60}, "spot": {"memory": 50},
      "model-a": {"gpu": 2}, "model-b": {"gpu": 2}},
     {"on-demand": {"memory": 50}, "model-a": {"gpu": 2}}),
    (PREEMPT, False,
     {"spot": {"memory": 50}, "model-b": {"gpu": 2}},
     {"on-demand": {"memory": 60}, "spot": {"memory": 60},
      "model-a": {"gpu": 2}, "model-b": {"gpu": 10}},
     {"spot": {"memory": 0}, "model-b": {"gpu": 0}}),
    (PREEMPT, True,
     {"spot": {"memory": 50}, "model-b": {"gpu": 2}},
     {"on-demand": {"memory": 60}, "spot": {"memory": 60},
      "model-a": {"gpu": 2}, "model-b": {"gpu": 10}},
     {"spot": {"memory": 40}, "model-b": {"gpu": 2}}),
    (PREEMPT, True,
     {"on-demand": {"memory": 50}, "model-b": {"gpu": 2}},
     {"on-demand": {"memory": 60}, "spot": {"memory": 60},
      "model-a": {"gpu": 2}, "model-b": {"gpu": 10}},
     {"on-demand": {"memory": 50}, "model-b": {"gpu": 2}}),
]


def test_resources_to_reserve_table():
    for i, (mode, borrowing, a_usage, cq_usage, want) in \
            enumerate(RESERVE_CASES):
        cq = _reserve_cq(cq_usage)
        e = _reserve_entry(mode, borrowing, a_usage)
        got = _resources_to_reserve(e, cq)
        assert got == want, (i, got, want)


# -- TestLastAssignmentOutdated (flavorassigner_test.go:2302-2371) -----------


def test_last_assignment_outdated_table():
    """The resume-state staleness predicate, exercised through the
    referee's resume path: a stale generation means the search restarts
    from the first flavor (the state is dropped)."""
    from kueue_tpu.solver.referee import assign_flavors

    def build(cohort=""):
        cache = Cache()
        cache.add_or_update_resource_flavor(make_flavor("f0"))
        cache.add_or_update_resource_flavor(make_flavor("f1"))
        cache.add_cluster_queue(make_cq(
            "cq", rg(("cpu",), fq("f0", cpu=4), fq("f1", cpu=4)),
            cohort=cohort))
        return cache.snapshot()

    cases = [
        # (cq gen bump, cohort gen bump, has cohort, want outdated)
        (1, 0, False, True),    # CQ generation increased
        (0, 1, True, True),     # cohort generation increased
        (0, 0, True, False),    # nothing moved
    ]
    for cq_bump, cohort_bump, has_cohort, want_outdated in cases:
        snap = build(cohort="pool" if has_cohort else "")
        cq = snap.cluster_queues["cq"]
        cq.allocatable_generation += cq_bump
        if has_cohort:
            cq.cohort.allocatable_generation += cohort_bump
        wl = make_wl("w", "lq", cpu=2, creation_time=1.0)
        wi = WorkloadInfo(wl, cluster_queue="cq")
        # Resume state says: next time skip to flavor index 1.
        wi.last_assignment = AssignmentClusterQueueState(
            last_tried_flavor_idx=[{"cpu": 0}],
            cluster_queue_generation=cq.allocatable_generation - cq_bump,
            cohort_generation=(cq.cohort.allocatable_generation - cohort_bump
                               if has_cohort else 0))
        a = assign_flavors(wi, cq, snap.resource_flavors)
        got_flavor = a.pod_sets[0].flavors["cpu"].name
        if want_outdated:
            # State dropped: the search restarts at f0.
            assert got_flavor == "f0", (cq_bump, cohort_bump, got_flavor)
        else:
            # State honored: the search resumes at f1.
            assert got_flavor == "f1", (cq_bump, cohort_bump, got_flavor)


# -- TestRequeueAndUpdate (scheduler_test.go:2056-2194) ----------------------


def _requeue_fixture():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq(
        "cq", rg(("cpu",), fq("default", cpu=8))))
    qm = Manager()
    qm.add_cluster_queue(make_cq("cq", rg(("cpu",), fq("default", cpu=8))))
    qm.add_local_queue(make_lq("q1", cq="cq", namespace="ns1"))
    cache.add_local_queue(make_lq("q1", cq="cq", namespace="ns1"))
    wl = Workload(name="w1", namespace="ns1", queue_name="q1",
                  creation_time=1.0,
                  pod_sets=[make_wl("t", "q1", cpu=1).pod_sets[0]])
    qm.add_or_update_workload(wl)
    heads = qm.heads(timeout=0)
    assert len(heads) == 1
    sched = Scheduler(qm, cache)
    return sched, qm, heads[0], wl


REQUEUE_CASES = [
    # (status, inadmissible_msg, want location, want pending condition)
    (NOT_NOMINATED, "didn't fit", "inadmissible", True),
    (ASSUMED, "", "none", False),
    (NOMINATED, "failed to admit workload", "heap", False),
    (SKIPPED, "cohort used in this cycle", "heap", True),
]


def test_requeue_and_update_table():
    for status, msg, want_loc, want_condition in REQUEUE_CASES:
        sched, qm, wi, wl = _requeue_fixture()
        e = Entry(info=wi, status=status, inadmissible_msg=msg)
        if status == ASSUMED:
            # The sweep's caller filters assumed entries out; the
            # reference's requeueAndUpdate no-ops on them likewise.
            continue
        sched._requeue_sweep([e])
        cq = qm.cluster_queues["cq"]
        in_heap = cq.heap.get_by_key(wl.key) is not None
        parked = wl.key in cq.inadmissible
        if want_loc == "heap":
            assert in_heap and not parked, (status, want_loc)
        elif want_loc == "inadmissible":
            assert parked and not in_heap, (status, want_loc)
        cond = wl.find_condition("QuotaReserved")
        if want_condition:
            assert cond is not None and not cond.status
            assert cond.reason == "Pending"
            assert cond.message == msg, (status, cond.message)
        else:
            assert cond is None, status


# -- TestLastSchedulingContext (scheduler_test.go:1639-2054) -----------------
# Two schedule() cycles with flavor-fungibility resume context carried
# between them: preempt-vs-next-flavor, deletes invalidating the context,
# borrow-before/after-next-flavor, borrow/preempt on the first flavor when
# the next is full.

import pytest

from kueue_tpu.api.types import (
    Admission,
    ClusterQueuePreemption,
    FlavorFungibility,
    PodSet,
    PodSetAssignment,
)
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.models.flavor_fit import BatchSolver


def _ctx_fw(batch, cohort_trio):
    fw = Framework(batch_solver=BatchSolver() if batch else None)
    for f in ("on-demand", "spot"):
        fw.create_resource_flavor(make_flavor(f))
    if not cohort_trio:
        # eng-alpha standalone: BestEffortFIFO, preempt lower-priority
        # within the CQ, WhenCanPreempt=Preempt. (The reference gives it
        # a borrowingLimit without a cohort, which the webhook rejects
        # like the reference's would — cohortless quota is equivalent.)
        fw.create_cluster_queue(make_cq(
            "eng-alpha",
            rg(("cpu",), fq("on-demand", cpu=50), fq("spot", cpu=100)),
            preemption=ClusterQueuePreemption(
                within_cluster_queue="LowerPriority"),
            fungibility=FlavorFungibility(when_can_preempt="Preempt")))
        fw.create_local_queue(make_lq("main", cq="eng-alpha"))
    else:
        for name, preempt_pol, borrow_pol in (
                ("eng-cohort-alpha", "Preempt", "Borrow"),
                ("eng-cohort-beta", "Preempt", "Borrow"),
                ("eng-cohort-theta", "TryNextFlavor", "TryNextFlavor")):
            fw.create_cluster_queue(make_cq(
                name,
                rg(("cpu",), fq("on-demand", cpu=(50, 50)),
                   fq("spot", cpu=(100, 0))),
                cohort="cohort", strategy="StrictFIFO",
                preemption=ClusterQueuePreemption(
                    within_cluster_queue="Never",
                    reclaim_within_cohort="LowerPriority"),
                fungibility=FlavorFungibility(
                    when_can_preempt=preempt_pol,
                    when_can_borrow=borrow_pol)))
        fw.create_local_queue(make_lq("main-alpha", cq="eng-cohort-alpha"))
        fw.create_local_queue(make_lq("main-beta", cq="eng-cohort-beta"))
        fw.create_local_queue(make_lq("main-theta", cq="eng-cohort-theta"))
    return fw


def _preadmit(fw, name, cq_name, flavor, cpu_v, priority=0):
    w = Workload(name=name, namespace="default", queue_name="",
                 priority=priority, creation_time=1.0,
                 pod_sets=[PodSet.make("main", 1, cpu=cpu_v)])
    w.admission = Admission(cluster_queue=cq_name, pod_set_assignments=[
        PodSetAssignment(name="main", flavors={"cpu": flavor},
                         resource_usage={"cpu": cpu_v * 1000}, count=1)])
    w.set_condition("QuotaReserved", True, now=1.0)
    w.set_condition("Admitted", True, now=1.0)
    fw.workloads[w.key] = w
    fw.cache.add_or_update_workload(w)
    return w


def _admission_flavor(fw, key):
    w = fw.workloads.get(key)
    if w is None or w.admission is None:
        return None
    return (w.admission.cluster_queue,
            w.admission.pod_set_assignments[0].flavors["cpu"])


@pytest.fixture(params=["referee", "batch"])
def ctx_batch(request):
    return request.param == "batch"


def test_ctx_use_next_flavor_if_cant_preempt(ctx_batch):
    fw = _ctx_fw(ctx_batch, cohort_trio=False)
    _preadmit(fw, "low-1", "eng-alpha", "on-demand", 50)
    fw.submit(make_wl("new", "main", cpu=20, creation_time=10.0))
    fw.tick()
    assert _admission_flavor(fw, "default/new") is None
    fw.tick()
    assert _admission_flavor(fw, "default/new") == ("eng-alpha", "spot")
    assert _admission_flavor(fw, "default/low-1") == \
        ("eng-alpha", "on-demand")


def test_ctx_some_workloads_were_deleted(ctx_batch):
    fw = _ctx_fw(ctx_batch, cohort_trio=False)
    low1 = _preadmit(fw, "low-1", "eng-alpha", "on-demand", 50)
    fw.submit(make_wl("preemptor", "main", cpu=20, creation_time=10.0))
    fw.tick()
    assert _admission_flavor(fw, "default/preemptor") is None
    fw.delete_workload(low1)
    fw.tick()
    assert _admission_flavor(fw, "default/preemptor") == \
        ("eng-alpha", "on-demand")


def test_ctx_borrow_before_next_flavor(ctx_batch):
    fw = _ctx_fw(ctx_batch, cohort_trio=True)
    _preadmit(fw, "placeholder", "eng-cohort-alpha", "on-demand", 50)
    fw.submit(make_wl("borrower", "main-alpha", cpu=20, creation_time=10.0))
    fw.submit(make_wl("workload1", "main-beta", cpu=20, creation_time=11.0))
    fw.tick()
    assert _admission_flavor(fw, "default/borrower") == \
        ("eng-cohort-alpha", "on-demand")
    assert _admission_flavor(fw, "default/workload1") == \
        ("eng-cohort-beta", "on-demand")
    fw.tick()
    assert _admission_flavor(fw, "default/placeholder") == \
        ("eng-cohort-alpha", "on-demand")


def test_ctx_borrow_after_all_flavors(ctx_batch):
    fw = _ctx_fw(ctx_batch, cohort_trio=True)
    _preadmit(fw, "placeholder", "eng-cohort-alpha", "on-demand", 50)
    _preadmit(fw, "placeholder1", "eng-cohort-theta", "on-demand", 50)
    fw.submit(make_wl("workload", "main-theta", cpu=20, creation_time=10.0))
    fw.tick()
    assert _admission_flavor(fw, "default/workload") == \
        ("eng-cohort-theta", "spot")
    fw.tick()
    assert _admission_flavor(fw, "default/workload") == \
        ("eng-cohort-theta", "spot")


def test_ctx_next_flavor_full_but_can_borrow_on_first(ctx_batch):
    fw = _ctx_fw(ctx_batch, cohort_trio=True)
    _preadmit(fw, "placeholder", "eng-cohort-alpha", "on-demand", 40)
    _preadmit(fw, "placeholder1", "eng-cohort-theta", "on-demand", 40)
    _preadmit(fw, "placeholder2", "eng-cohort-theta", "spot", 100)
    fw.submit(make_wl("workload", "main-theta", cpu=20, creation_time=10.0))
    fw.tick()
    assert _admission_flavor(fw, "default/workload") == \
        ("eng-cohort-theta", "on-demand")
    fw.tick()
    assert _admission_flavor(fw, "default/workload") == \
        ("eng-cohort-theta", "on-demand")


def test_ctx_next_flavor_full_but_can_preempt_on_first(ctx_batch):
    fw = _ctx_fw(ctx_batch, cohort_trio=True)
    alpha = _preadmit(fw, "placeholder-alpha", "eng-cohort-alpha",
                      "on-demand", 150, priority=-1)
    _preadmit(fw, "placeholder-theta-spot", "eng-cohort-theta", "spot", 100)
    fw.submit(make_wl("new", "main-theta", cpu=20, creation_time=10.0))
    fw.tick()
    assert fw.workloads["default/placeholder-alpha"].is_evicted, \
        "reclaim preemption must target the lower-priority borrower"
    assert _admission_flavor(fw, "default/new") is None
    fw.delete_workload(alpha)
    fw.tick()
    assert _admission_flavor(fw, "default/new") == \
        ("eng-cohort-theta", "on-demand")
    assert _admission_flavor(fw, "default/placeholder-theta-spot") == \
        ("eng-cohort-theta", "spot")


# -- TestCandidatesOrdering (preemption_test.go:1121-1168) -------------------


def test_candidates_ordering_table():
    """Victim ordering: evicted first, other-ClusterQueue first, lowest
    priority, newest quota reservation, UID tiebreak."""
    from kueue_tpu.scheduler.preemption import _candidate_sort_key

    now = NOW

    def cand(name, cq="self", priority=0, evicted=False,
             reserved_at=None, uid=None):
        w = Workload(name=name, namespace="", queue_name="",
                     priority=priority, creation_time=1.0, pod_sets=[])
        if uid is not None:
            w.uid = uid
        if evicted:
            w.set_condition("Evicted", True, now=now)
        else:
            w.set_condition("QuotaReserved", True,
                            now=reserved_at if reserved_at is not None
                            else now)
        return WorkloadInfo(w, cluster_queue=cq)

    candidates = [
        cand("high", priority=10),
        cand("low", priority=-10),
        cand("other", cq="other", priority=10),
        cand("evicted", evicted=True),
        cand("old-a", reserved_at=now, uid="old-a"),
        cand("old-b", reserved_at=now, uid="old-b"),
        cand("current", reserved_at=now + 1),
    ]
    candidates.sort(key=lambda c: _candidate_sort_key(c, "self", now))
    got = [c.obj.name for c in candidates]
    assert got == ["evicted", "other", "low", "current",
                   "old-a", "old-b", "high"], got
