"""Manifest encode/decode round-trips for every kind served by the API
server — the wire-format contract of the process boundary."""

from kueue_tpu.api import serialization
from kueue_tpu.api.types import (
    AdmissionCheck,
    Admission,
    BorrowWithinCohort,
    ClusterQueue,
    ClusterQueuePreemption,
    CohortSpec,
    FairSharing,
    FlavorFungibility,
    FlavorQuotas,
    LabelSelector,
    LocalQueue,
    MatchExpression,
    PodSet,
    PodSetAssignment,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Taint,
    Toleration,
    Workload,
    WorkloadPriorityClass,
)


def roundtrip(kind, obj):
    doc = serialization.encode(kind, obj)
    kind2, back = serialization.decode(doc)
    assert kind2 == kind
    return doc, back


class TestRoundTrips:
    def test_resource_flavor(self):
        rf = ResourceFlavor.make(
            "gpu", node_labels={"type": "a100"},
            node_taints=(Taint(key="gpu", value="yes", effect="NoSchedule"),),
            tolerations=(Toleration(key="gpu", operator="Exists"),))
        _, back = roundtrip("ResourceFlavor", rf)
        assert back == rf

    def test_cluster_queue(self):
        cq = ClusterQueue(
            name="cq",
            cohort="pool",
            resource_groups=(ResourceGroup(
                covered_resources=("cpu", "memory"),
                flavors=(FlavorQuotas(
                    name="default",
                    resources=(("cpu", ResourceQuota(nominal=8000,
                                                     borrowing_limit=2000,
                                                     lending_limit=1000)),
                               ("memory", ResourceQuota(nominal=1 << 30)))),
                         )),),
            queueing_strategy="StrictFIFO",
            namespace_selector=LabelSelector(
                match_labels=(("team", "ml"),),
                match_expressions=(MatchExpression(
                    key="env", operator="In", values=("prod",)),)),
            preemption=ClusterQueuePreemption(
                within_cluster_queue="LowerPriority",
                reclaim_within_cohort="Any",
                borrow_within_cohort=BorrowWithinCohort(
                    policy="LowerPriority", max_priority_threshold=100)),
            flavor_fungibility=FlavorFungibility(
                when_can_borrow="TryNextFlavor", when_can_preempt="Preempt"),
            admission_checks=("prov",),
            fair_sharing=FairSharing(weight=2.0))
        _, back = roundtrip("ClusterQueue", cq)
        assert back == cq

    def test_local_queue(self):
        lq = LocalQueue(name="main", namespace="team-a", cluster_queue="cq")
        _, back = roundtrip("LocalQueue", lq)
        assert back == lq

    def test_admission_check(self):
        ac = AdmissionCheck(name="prov",
                            controller_name="kueue.x-k8s.io/provisioning",
                            parameters=("kueue.x-k8s.io",
                                        "ProvisioningRequestConfig", "cfg"))
        _, back = roundtrip("AdmissionCheck", ac)
        assert back == ac

    def test_priority_class(self):
        pc = WorkloadPriorityClass(name="high", value=1000)
        _, back = roundtrip("WorkloadPriorityClass", pc)
        assert back == pc

    def test_cohort(self):
        cohort = CohortSpec(
            name="pool", parent="root",
            resource_groups=(ResourceGroup(
                covered_resources=("cpu",),
                flavors=(FlavorQuotas(
                    name="default",
                    resources=(("cpu", ResourceQuota(nominal=4000)),)),)),))
        _, back = roundtrip("Cohort", cohort)
        assert back == cohort

    def test_workload_spec_and_status(self):
        wl = Workload(
            name="wl", namespace="ns", queue_name="main",
            labels={"a": "b"}, annotations={"k": "v"},
            pod_sets=[PodSet(
                name="driver", count=1, requests={"cpu": 500, "memory": 1024},
                node_selector=(("zone", "z1"),),
                tolerations=(Toleration(key="gpu", operator="Exists"),),
                affinity_terms=((MatchExpression(
                    key="type", operator="In", values=("a100",)),),)),
                PodSet(name="worker", count=4, min_count=2,
                       requests={"cpu": 1000})],
            priority=7, priority_class="high")
        wl.set_condition("QuotaReserved", True, reason="QuotaReserved", now=5.0)
        wl.admission = Admission(
            cluster_queue="cq",
            pod_set_assignments=[PodSetAssignment(
                name="driver", flavors={"cpu": "default"},
                resource_usage={"cpu": 500}, count=1)])
        wl.reclaimable_pods = {"worker": 1}

        doc = serialization.encode("Workload", wl)
        _, back = serialization.decode(doc)
        serialization.decode_workload_status(doc, back)

        assert back.name == wl.name and back.namespace == wl.namespace
        assert back.labels == wl.labels and back.annotations == wl.annotations
        assert back.priority == 7 and back.priority_class == "high"
        assert back.uid == wl.uid
        assert back.creation_time == wl.creation_time
        assert len(back.pod_sets) == 2
        for got, want in zip(back.pod_sets, wl.pod_sets):
            assert got.name == want.name and got.count == want.count
            assert got.min_count == want.min_count
            assert got.requests == want.requests
            assert got.node_selector == want.node_selector
            assert got.tolerations == want.tolerations
            assert got.affinity_terms == want.affinity_terms
        assert back.has_quota_reservation
        assert back.admission.cluster_queue == "cq"
        assert back.admission.pod_set_assignments[0].resource_usage == \
            {"cpu": 500}
        assert back.reclaimable_pods == {"worker": 1}
