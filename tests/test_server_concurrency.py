"""Server read concurrency + cross-process leader election (VERDICT r3
task 8): reads must not stall behind the scheduler tick, and a standby
--serve replica sharing the state dir must defer until the leader dies."""

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from kueue_tpu.api.types import PodSet, ResourceFlavor, Workload
from kueue_tpu.controllers.leaderelection import FileLeaseStore
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.controllers.store import (
    KIND_CLUSTER_QUEUE,
    KIND_LOCAL_QUEUE,
    KIND_RESOURCE_FLAVOR,
    KIND_WORKLOAD,
    Store,
    StoreAdapter,
)
from kueue_tpu.controllers.visibility import VisibilityServer
from kueue_tpu.server import APIServer

from tests.util import fq, make_cq, make_flavor, make_lq, rg


class TestReadsDontStallBehindTicks:
    def test_get_and_list_latency_bounded_while_lock_held(self):
        """Hold the runtime lock (simulating a long tick) while issuing
        reads: GET/list serve from the copy-on-write view and stay fast."""
        fw = Framework()
        store = Store()
        adapter = StoreAdapter(store, fw)
        lock = threading.RLock()
        server = APIServer(store, fw, visibility=VisibilityServer(fw.queues),
                           host="127.0.0.1", port=0, runtime_lock=lock,
                           sync_status=adapter.sync_status)
        server.start()
        try:
            store.create(KIND_RESOURCE_FLAVOR, make_flavor("default"))
            store.create(KIND_CLUSTER_QUEUE,
                         make_cq("cq", rg("cpu", fq("default", cpu=8))))
            store.create(KIND_LOCAL_QUEUE, make_lq("main", cq="cq"))
            for i in range(50):
                store.create(KIND_WORKLOAD, Workload(
                    name=f"w{i}", queue_name="main",
                    pod_sets=[PodSet.make("m", 1, cpu=1)]))
            adapter.tick()

            base = (f"{server.url}/apis/kueue.x-k8s.io/v1beta1/"
                    "namespaces/default/workloads")
            release = threading.Event()

            def hog():
                with lock:          # a 1.5s "tick"
                    release.wait(1.5)

            t = threading.Thread(target=hog)
            t.start()
            time.sleep(0.05)
            lat = []
            deadline = time.time() + 1.0
            while time.time() < deadline:
                t0 = time.perf_counter()
                with urllib.request.urlopen(f"{base}/w0", timeout=5) as r:
                    json.load(r)
                with urllib.request.urlopen(base, timeout=5) as r:
                    doc = json.load(r)
                lat.append(time.perf_counter() - t0)
                assert len(doc["items"]) == 50
            release.set()
            t.join()
            p99 = float(np.percentile(np.array(lat) * 1000, 99))
            # Reads completed DURING the lock hold, far under its 1.5s
            # (generous bound: shared CI hosts jitter, but a read that
            # waited for the lock would take the full 1.5s).
            assert len(lat) > 10
            assert p99 < 500, f"read p99 {p99:.0f}ms stalled behind the tick"
        finally:
            server.stop()

    def test_read_sees_published_status(self):
        """The COW view serves the status as of the last sync, and a new
        sync publishes fresh status."""
        fw = Framework()
        store = Store()
        adapter = StoreAdapter(store, fw)
        server = APIServer(store, fw, visibility=None, host="127.0.0.1",
                           port=0, runtime_lock=threading.RLock(),
                           sync_status=adapter.sync_status)
        server.start()
        try:
            store.create(KIND_RESOURCE_FLAVOR, make_flavor("default"))
            store.create(KIND_CLUSTER_QUEUE,
                         make_cq("cq", rg("cpu", fq("default", cpu=8))))
            store.create(KIND_LOCAL_QUEUE, make_lq("main", cq="cq"))
            store.create(KIND_WORKLOAD, Workload(
                name="w", queue_name="main",
                pod_sets=[PodSet.make("m", 1, cpu=1)]))
            base = (f"{server.url}/apis/kueue.x-k8s.io/v1beta1/"
                    "namespaces/default/workloads/w")
            with urllib.request.urlopen(base, timeout=5) as r:
                before = json.load(r)
            assert not any(c["type"] == "Admitted"
                           for c in before.get("status", {}).get(
                               "conditions", []))
            adapter.tick()   # admits + syncs status
            with urllib.request.urlopen(base, timeout=5) as r:
                after = json.load(r)
            conds = {c["type"]: c["status"]
                     for c in after["status"]["conditions"]}
            assert conds.get("Admitted") == "True"
        finally:
            server.stop()


class TestFileLeaseStore:
    def test_cas_semantics(self, tmp_path):
        store = FileLeaseStore(str(tmp_path / "leases.json"))
        assert store.try_acquire_or_renew("lease", "a", 1.0, now=10.0)
        # Held: another identity cannot take it...
        assert not store.try_acquire_or_renew("lease", "b", 1.0, now=10.5)
        # ...the holder renews...
        assert store.try_acquire_or_renew("lease", "a", 1.0, now=10.8)
        # ...and after expiry the other identity takes over.
        assert store.try_acquire_or_renew("lease", "b", 1.0, now=12.0)
        assert store.holder("lease") == "b"
        store.release("lease", "b")
        assert store.holder("lease") == ""


LEADER_CFG = """\
apiVersion: config.kueue.x-k8s.io/v1beta1
kind: Configuration
leaderElection:
  leaderElect: true
  leaseDuration: 2s
  renewDeadline: 1s
  retryPeriod: 200ms
"""

SETUP_YAML = """\
apiVersion: kueue.x-k8s.io/v1beta1
kind: ResourceFlavor
metadata:
  name: default
---
apiVersion: kueue.x-k8s.io/v1beta1
kind: ClusterQueue
metadata:
  name: cq
spec:
  namespaceSelector: {}
  resourceGroups:
  - coveredResources: ["cpu"]
    flavors:
    - name: default
      resources:
      - name: cpu
        nominalQuota: 4
---
apiVersion: kueue.x-k8s.io/v1beta1
kind: LocalQueue
metadata:
  name: main
  namespace: default
spec:
  clusterQueue: cq
---
apiVersion: kueue.x-k8s.io/v1beta1
kind: Workload
metadata:
  name: wl1
  namespace: default
spec:
  queueName: main
  podSets:
  - name: m
    count: 1
    template:
      spec:
        containers:
        - name: c
          resources:
            requests:
              cpu: "1"
"""


def _spawn_replica(state_dir, setup, cfg, lease_file):
    proc = subprocess.Popen(
        [sys.executable, "-m", "kueue_tpu", "--serve", "--port", "0",
         "--tick-interval", "0.05", "--state-dir", state_dir,
         "--lease-file", lease_file,
         "--config", cfg, "--objects", setup],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stderr=subprocess.PIPE, stdout=subprocess.DEVNULL, text=True)
    url = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stderr.readline()
        m = re.search(r"serving HTTP API on (http://\S+)", line or "")
        if m:
            url = m.group(1)
            break
        if proc.poll() is not None:
            raise RuntimeError("replica died during startup")
    assert url
    # Keep draining stderr: a full pipe would block the replica.
    threading.Thread(target=lambda: proc.stderr.read(), daemon=True).start()
    return proc, url


def _admitted(url, name) -> bool:
    base = f"{url}/apis/kueue.x-k8s.io/v1beta1/namespaces/default/workloads"
    try:
        with urllib.request.urlopen(f"{base}/{name}", timeout=5) as r:
            doc = json.load(r)
    except Exception:
        return False
    return any(c["type"] == "Admitted" and c.get("status") == "True"
               for c in (doc.get("status") or {}).get("conditions") or ())


class TestTwoProcessElection:
    def test_standby_defers_then_takes_over(self, tmp_path):
        state = str(tmp_path / "state")
        os.makedirs(state)
        setup = tmp_path / "setup.yaml"
        setup.write_text(SETUP_YAML)
        cfg = tmp_path / "config.yaml"
        cfg.write_text(LEADER_CFG)

        # Replicas share ONE state dir (journal + lease) — the etcd
        # analog. The journal attach is deferred until a replica leads
        # (__main__.tick_once), so the standby replays the leader's
        # journal at takeover instead of keeping a private copy.
        lease = os.path.join(state, "leases.json")
        proc_a, url_a = _spawn_replica(state, str(setup), str(cfg), lease)
        try:
            deadline = time.time() + 20
            while time.time() < deadline and not _admitted(url_a, "wl1"):
                time.sleep(0.1)
            assert _admitted(url_a, "wl1"), "leader A never admitted"

            proc_b, url_b = _spawn_replica(state, str(setup), str(cfg), lease)
            try:
                # B holds wl1 pending: it defers while A leads.
                time.sleep(1.5)
                assert not _admitted(url_b, "wl1"), \
                    "standby admitted while the leader was alive"
                # Kill the leader; B takes over after the lease expires.
                proc_a.send_signal(signal.SIGKILL)
                proc_a.wait(timeout=10)
                deadline = time.time() + 20
                while time.time() < deadline and not _admitted(url_b, "wl1"):
                    time.sleep(0.1)
                assert _admitted(url_b, "wl1"), \
                    "standby never took over after the leader died"
            finally:
                proc_b.send_signal(signal.SIGKILL)
                proc_b.wait(timeout=10)
        finally:
            if proc_a.poll() is None:
                proc_a.send_signal(signal.SIGKILL)
                proc_a.wait(timeout=10)
