"""Cohort-sharded solve differential goldens + two-phase reconcile.

The cohort mesh (kueue_tpu/parallel/mesh.CohortMesh) must be decision-
INVISIBLE: for any shard count, the sharded solve + two-phase admit cycle
(optimistic per-shard pass, then the cross-shard lending-clamp reconcile)
produces byte-identical admission decisions to the single-device,
single-phase path. Pinned three ways:

  * 200-tick randomized churn (the tests/test_arena.py harness shape)
    over a MIXED topology — flat cohorts plus a hierarchical tree whose
    subtree cohorts hash to different shards (so the reconcile pass runs
    live during churn) — at shards in {1, 2, 8}, across every registered
    victim-search engine, against the unsharded trail;
  * a deterministic cross-cohort LendingLimit scenario where two
    same-tick heads on different shards both fit their shard-local
    optimistic view but only one fits the shared clamp — the reconcile
    MUST revoke exactly one and match the unsharded decision;
  * jaxpr structure: the per-shard program depends only on the padded
    per-shard bucket, never on the shard count (the TRC03
    one-compile-per-bucket contract, per shard).

Runs on the virtual 8-device CPU mesh from conftest.py.
"""

import random
import zlib

import numpy as np
import pytest

from kueue_tpu import features
from kueue_tpu.api.types import (
    ClusterQueuePreemption,
    CohortSpec,
    PodSet,
    Workload,
)
from kueue_tpu.config import Configuration, TPUSolverConfig
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.models.flavor_fit import BatchSolver
from kueue_tpu.parallel.mesh import (
    CohortMesh,
    assign_shards,
    plan_shards,
)
from kueue_tpu.solver import modes as _modes
from kueue_tpu.solver import schema as sch

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg

TICKS = 200

_ENGINE_KNOB = {
    "host": None,
    "scan-jax": "jax",
    "scan-pallas": "pallas",
    "batch-native": "native",
    "batch-jax": "jax",
}

_KNOBS = []
for _spec in _modes.ENGINES:
    if _spec.optional_import and not _modes.engine_importable(_spec):
        continue
    knob = _ENGINE_KNOB[_spec.name]
    if knob not in _KNOBS:
        _KNOBS.append(knob)


def _split_pair(n_shards: int = 8):
    """Two cohort names whose hashes land on different shards at both 2
    and `n_shards` shards — guarantees the tree they share splits."""
    names = ["east", "west", "north", "south", "alpha", "beta", "gamma",
             "delta", "omega", "sigma"]
    for i, a in enumerate(names):
        ha = zlib.crc32(a.encode())
        for b in names[i + 1:]:
            hb = zlib.crc32(b.encode())
            if ha % n_shards != hb % n_shards and ha % 2 != hb % 2:
                return a, b
    raise AssertionError("no splitting cohort-name pair found")


def build(shards, engine):
    """Mixed topology: 4 CQs over 2 flat cohorts (the test_arena shape)
    PLUS a hierarchical tree `root <- {A, B, pool}` where pool lends at
    most 4 cpu (lendingLimit) and A/B hash to different shards — every
    borrow across the tree exercises the reconcile pass when sharded."""
    features.set_enabled(features.LENDING_LIMIT, True)
    cfg = Configuration(tpu_solver=TPUSolverConfig(
        preemption_engine="host" if engine is None else engine))
    fw = Framework(batch_solver=BatchSolver(shards=shards), config=cfg)
    fw.create_namespace("default", labels={})
    fw.create_resource_flavor(make_flavor("on-demand", zone="a"))
    fw.create_resource_flavor(make_flavor("spot", zone="b"))
    for i in range(4):
        fw.create_cluster_queue(make_cq(
            f"cq-{i}",
            rg("cpu", fq("on-demand", cpu=(16, 16)), fq("spot", cpu=(8, 8))),
            cohort=f"cohort-{i % 2}",
            preemption=ClusterQueuePreemption(
                within_cluster_queue="LowerPriority",
                reclaim_within_cohort="Any")))
        fw.create_local_queue(make_lq(f"lq-{i}", "default", cq=f"cq-{i}"))
    ca, cb = _split_pair()
    fw.create_cohort(CohortSpec(name="hroot"))
    fw.create_cohort(CohortSpec(name=ca, parent="hroot"))
    fw.create_cohort(CohortSpec(name=cb, parent="hroot"))
    fw.create_cohort(CohortSpec(
        name="hpool", parent="hroot",
        resource_groups=(rg("cpu", fq("on-demand", cpu=(8, None, 4))),)))
    for side, idx in ((ca, 4), (cb, 5)):
        fw.create_cluster_queue(make_cq(
            f"cq-{idx}", rg("cpu", fq("on-demand", cpu=4)), cohort=side))
        fw.create_local_queue(make_lq(f"lq-{idx}", "default",
                                      cq=f"cq-{idx}"))
    return fw


def drive(shards, engine, ticks: int = TICKS):
    """Seeded churn over the mixed topology; returns the decision trail
    plus the reconcile revocation count."""
    fw = build(shards, engine)
    rnd = random.Random(4321)
    seq = [0]
    pending: dict = {}
    admitted: dict = {}
    trail = []

    orig_admit = fw.scheduler.apply_admission
    orig_preempt = fw.scheduler.apply_preemption
    tick_admitted: list = []
    tick_preempted: list = []

    def apply_admission(wl):
        ok = orig_admit(wl)
        if ok:
            tick_admitted.append(wl.key)
            admitted[wl.key] = wl
            pending.pop(wl.key, None)
        return ok

    def apply_preemption(wl, msg):
        tick_preempted.append(wl.key)
        return orig_preempt(wl, msg)

    fw.scheduler.apply_admission = apply_admission
    fw.scheduler.apply_preemption = apply_preemption

    def submit_one():
        seq[0] += 1
        i = seq[0]
        # Mostly flat-cohort traffic; every 4th lands in the split tree
        # (cpu up to 8 > nominal 4 forces borrowing through the clamp).
        if i % 4 == 0:
            q = f"lq-{4 + (i // 4) % 2}"
            cpu = rnd.randint(2, 8)
        else:
            q = f"lq-{rnd.randrange(4)}"
            cpu = rnd.randint(1, 4)
        wl = Workload(
            name=f"wl-{i}", namespace="default", queue_name=q,
            priority=rnd.randint(-2, 3),
            creation_time=float(1000 + i),
            pod_sets=[PodSet.make("ps0", count=rnd.randint(1, 3), cpu=cpu)])
        pending[wl.key] = wl
        fw.submit(wl)

    for _ in range(40):
        submit_one()

    for _ in range(ticks):
        tick_admitted.clear()
        tick_preempted.clear()
        fw.tick()
        trail.append((tuple(sorted(tick_admitted)),
                      tuple(sorted(tick_preempted))))
        for _ in range(rnd.randint(0, 3)):
            submit_one()
        if pending and rnd.random() < 0.3:
            key = rnd.choice(sorted(pending))
            wl = pending.pop(key)
            if not wl.is_admitted:
                fw.delete_workload(wl)
        done = [k for k, w in sorted(admitted.items())
                if w.is_admitted and not w.is_finished]
        for key in done[:rnd.randint(0, 4)]:
            wl = admitted.pop(key)
            fw.finish(wl)
            fw.delete_workload(wl)
        for key in list(admitted):
            if not admitted[key].is_admitted:
                wl = admitted.pop(key)
                if not wl.is_finished:
                    pending[key] = wl
        fw.prewarm_idle()

    trail.append(("pending", sum(fw.queues.pending(f"cq-{i}")
                                 for i in range(6))))
    return trail, fw.scheduler.metrics.reconcile_revocations


_BASELINES: dict = {}


def _baseline(engine):
    if engine not in _BASELINES:
        _BASELINES[engine] = drive(None, engine)[0]
    return _BASELINES[engine]


@pytest.mark.parametrize("engine", _KNOBS, ids=[str(k) for k in _KNOBS])
@pytest.mark.parametrize("shards", [1, 2, 8])
def test_sharded_churn_decisions_identical(engine, shards):
    """200 randomized churn ticks: the cohort-sharded path (per-shard
    solve blocks + two-phase reconcile) must replay the unsharded trail
    byte for byte, at every shard count, on every engine."""
    trail, _ = drive(shards, engine)
    assert trail == _baseline(engine)


def test_sharded_victim_scan_flat_cohorts():
    """The packed-XLA victim search shards over the same cohort mesh
    (per-shard search blocks). Flat-cohort preemption churn at shards=2
    must be decision-identical to unsharded AND must actually route
    through the sharded scan program (hier scenarios fall back to the
    host searches, so the churn matrix above never compiles it)."""
    from kueue_tpu.ops import preemption_batch as pb

    def flat_drive(shards):
        cfg = Configuration(tpu_solver=TPUSolverConfig(
            preemption_engine="jax"))
        fw = Framework(batch_solver=BatchSolver(shards=shards), config=cfg)
        fw.create_namespace("default", labels={})
        fw.create_resource_flavor(make_flavor("on-demand"))
        for i in range(4):
            fw.create_cluster_queue(make_cq(
                f"cq-{i}", rg("cpu", fq("on-demand", cpu=(8, 8))),
                cohort=f"cohort-{i % 2}",
                preemption=ClusterQueuePreemption(
                    within_cluster_queue="LowerPriority",
                    reclaim_within_cohort="Any")))
            fw.create_local_queue(make_lq(f"lq-{i}", "default",
                                          cq=f"cq-{i}"))
        rnd = random.Random(99)
        trail = []
        tick_events: list = []
        orig_admit = fw.scheduler.apply_admission
        orig_preempt = fw.scheduler.apply_preemption

        def apply_admission(wl):
            ok = orig_admit(wl)
            if ok:
                tick_events.append(("A", wl.key))
            return ok

        def apply_preemption(wl, msg):
            tick_events.append(("P", wl.key))
            return orig_preempt(wl, msg)

        fw.scheduler.apply_admission = apply_admission
        fw.scheduler.apply_preemption = apply_preemption
        # Saturate with low priority, then churn high-priority arrivals
        # so every tick runs real victim searches.
        for i in range(24):
            fw.submit(make_wl(f"low-{i}", f"lq-{i % 4}", cpu=2,
                              priority=-1, creation_time=float(i)))
        for t in range(60):
            tick_events.clear()
            fw.tick()
            trail.append(tuple(sorted(tick_events)))
            if t % 3 == 0:
                # Two arrivals on DIFFERENT cohorts per wave: the tick's
                # admit cycle then batches two victim searches, which is
                # what routes through the per-shard scan blocks.
                for q in (0, 1):
                    fw.submit(make_wl(
                        f"hi-{t}-{q}", f"lq-{q + 2 * rnd.randrange(2)}",
                        cpu=2, priority=2,
                        creation_time=float(1000 + 2 * t + q)))
            fw.prewarm_idle()
        return trail

    pb._SHARDED_SCAN_CACHE.clear()
    sharded = flat_drive(2)
    assert pb._SHARDED_SCAN_CACHE, \
        "the sharded victim scan never ran (searches fell back to the " \
        "single-device kernel)"
    unsharded = flat_drive(None)
    assert sharded == unsharded


def test_split_tree_detected():
    fw = build(8, None)
    fw.submit(make_wl("probe", "lq-4", cpu=1, creation_time=5.0))
    fw.tick()
    solver = fw.scheduler.batch_solver
    a = solver._cohort_mesh.assignment(solver._enc)
    assert "hroot" in a.split_roots
    # Flat cohorts can never split: each hashes to exactly one shard.
    assert all(r == "hroot" for r in a.split_roots)


def test_lending_clamp_reconcile_revokes():
    """Two same-tick heads on different shards of a split tree, both
    borrowing from one lending-limited pool that can serve only one:
    shard-locally both fit (optimistic), globally one must lose — the
    reconcile pass revokes it, and the final decision matches the
    unsharded cycle exactly."""
    results = {}
    for shards in (None, 8):
        fw = build(shards, None)
        # Each alone borrows 4 of the pool's lendingLimit 4; together
        # they need 8 — exactly one can win.
        fw.submit(make_wl("wa", "lq-4", cpu=8, creation_time=1.0))
        fw.submit(make_wl("wb", "lq-5", cpu=8, creation_time=2.0))
        fw.run_until_settled(max_ticks=6)
        winners = tuple(sorted(
            fw.admitted_workloads("cq-4") + fw.admitted_workloads("cq-5")))
        results[shards] = (winners, fw.scheduler.metrics)
    w_unsharded, _ = results[None]
    w_sharded, metrics = results[8]
    assert len(w_unsharded) == 1
    assert w_sharded == w_unsharded
    assert metrics.reconcile_revocations >= 1


def test_assignment_deterministic_and_flat_cohorts_never_split():
    fw = build(8, None)
    fw.submit(make_wl("p", "lq-0", cpu=1, creation_time=1.0))
    fw.tick()
    enc = fw.scheduler.batch_solver._enc
    a1 = assign_shards(enc, 8)
    a2 = assign_shards(enc, 8)
    assert np.array_equal(a1.shard_of_cq, a2.shard_of_cq)
    assert a1.split_roots == a2.split_roots
    # Every CQ of a flat cohort shares its cohort's shard.
    for ci, k in enumerate(enc.cohort_id):
        assert a1.shard_of_cq[ci] == a1.shard_of_cohort[k]


def test_plan_shards_roundtrip():
    rnd = np.random.RandomState(7)
    shard_of_cq = rnd.randint(0, 8, size=40).astype(np.int32)
    wl_cq = rnd.randint(0, 40, size=100).astype(np.int32)

    class A:
        n_shards = 8
    a = A()
    a.shard_of_cq = shard_of_cq
    dest, counts, Ws = plan_shards(a, wl_cq, 100)
    assert counts.sum() == 100
    assert Ws >= counts.max() and (Ws & (Ws - 1)) == 0
    # Slots are unique and land inside their shard's block.
    assert len(set(dest.tolist())) == 100
    shards = shard_of_cq[wl_cq]
    assert np.array_equal(dest // Ws, shards)
    # Batch order is preserved within each shard (decision order).
    for s in range(8):
        rows = dest[shards == s] % Ws
        assert np.array_equal(rows, np.arange(len(rows)))


def test_arena_shard_views_follow_sink_events():
    """The per-shard pending/admitted counts ride the same queue/cache
    sink events that feed the arenas."""
    fw = build(8, None)
    solver = fw.scheduler.batch_solver
    for i in range(12):
        fw.submit(Workload(
            name=f"w-{i}", namespace="default",
            queue_name=f"lq-{i % 4}", priority=0, creation_time=float(i),
            pod_sets=[PodSet.make("ps0", count=1, cpu=1)]))
    fw.run_until_settled()
    a = solver._cohort_mesh.assignment(solver._enc)
    arena = solver._arena
    assert arena is not None and arena.shard_counts is not None
    # Recompute per-shard pending rows from scratch and compare.
    expect = np.zeros(8, dtype=np.int64)
    for row in arena._rows.values():
        expect[a.shard_of_cq[arena.wl_cq[row]]] += 1
    assert np.array_equal(arena.shard_counts, expect)
    admit = solver._admit_arena
    assert admit is not None and admit.shard_counts is not None
    expect_adm = np.zeros(8, dtype=np.int64)
    for row in admit._rows.values():
        expect_adm[a.shard_of_cq[admit.row_ci[row]]] += 1
    assert np.array_equal(admit.shard_counts, expect_adm)
    assert int(admit.shard_counts.sum()) > 0
    su = admit.shard_usage()
    assert su is not None and su.shape[0] == 8
    # Per-shard usage sums telescope to the total committed usage.
    assert su.sum() == admit.usage_cfr.sum()


def test_per_shard_jaxpr_is_shard_count_independent():
    """TRC03 across shard counts: at a fixed per-shard bucket, the
    program each device compiles is structurally identical whether the
    mesh has 2 or 4 shards — the one-compile-per-bucket contract holds
    per shard, independent of fleet size."""
    import jax

    from kueue_tpu.analysis import jaxpr_tools as jt
    from kueue_tpu.parallel import mesh as pmesh

    fw = build(None, None)
    fw.submit(make_wl("p", "lq-0", cpu=1, creation_time=1.0))
    fw.tick()
    enc = fw.scheduler.batch_solver._enc
    Ws, P = 8, 1

    def inner_jaxpr(n_shards):
        cm = CohortMesh(n_shards)
        program = pmesh._build_cohort_program(
            cm, enc.num_slots, enc.num_cohorts, True, enc.hier is not None)
        R = len(enc.resource_names)
        G = enc.num_groups
        S = enc.num_slots
        WsS = n_shards * Ws
        args = pmesh._static_args(enc) + (
            np.zeros(enc.nominal.shape, np.int64),
            np.zeros(WsS, np.int32), np.zeros((WsS, P, R), np.int64),
            np.zeros((WsS, P, R), bool), np.zeros((WsS, P), bool),
            np.zeros((WsS, P), bool), np.zeros((WsS, P, G, S), bool),
            np.zeros((WsS, P, G), np.int32))
        closed = jax.make_jaxpr(program)(*args)

        def find(jaxpr):
            for eqn in jaxpr.eqns:
                if "shard_map" in eqn.primitive.name:
                    return eqn.params["jaxpr"]
                for v in eqn.params.values():
                    inner = getattr(v, "jaxpr", v if hasattr(v, "eqns")
                                    else None)
                    if inner is not None:
                        hit = find(inner)
                        if hit is not None:
                            return hit
            return None

        hit = find(closed.jaxpr)
        assert hit is not None, \
            "no shard_map equation in the lowered program"
        return hit

    j2 = inner_jaxpr(2)
    j4 = inner_jaxpr(4)
    sig2 = jt.structural_signature(j2)
    sig4 = jt.structural_signature(j4)
    assert jt.first_divergence(sig2, sig4) is None, \
        "per-shard program depends on the shard count"
