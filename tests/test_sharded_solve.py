"""Multi-chip sharded solve (kueue_tpu.parallel.mesh) equivalence.

The sharded program must reproduce the single-device kernel bit-for-bit —
including hierarchical cohorts (KEP-79), which round-3's sharded path
silently dropped (VERDICT r3 Weak #2: an 8-CQ tree under a lending-limited
mid-cohort returned FIT sharded where the hier-aware single-device kernel
returned NO_FIT — silent overadmission). The repro here is that exact
scenario, kept as a regression gate.

Runs on the virtual 8-device CPU mesh from conftest.py.
"""

import numpy as np
import pytest

from kueue_tpu.api.types import CohortSpec
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.core.workload import WorkloadInfo
from kueue_tpu.models.flavor_fit import solve_flavor_fit
from kueue_tpu.parallel.mesh import make_mesh, sharded_flavor_fit
from kueue_tpu.solver import schema as sch
from kueue_tpu.solver.modes import FIT, NO_FIT

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg

OUT_KEYS = ("wl_mode", "res_flavor", "res_mode", "res_borrow", "ps_ok",
            "ps_mode", "group_chosen", "group_tried")


def _solve_both(fw, pending, n_devices=8):
    snapshot = fw.cache.snapshot()
    enc = sch.encode_cluster_queues(snapshot)
    usage = sch.encode_usage(snapshot, enc)
    infos = [WorkloadInfo(wl, cluster_queue=fw.cache.cluster_queue_for(wl))
             for wl in pending]
    wt = sch.encode_workloads(infos, snapshot, enc)
    mesh = make_mesh(n_devices)
    sharded = sharded_flavor_fit(enc, usage, wt, mesh)
    single = solve_flavor_fit(enc, usage, wt)
    return enc, wt, sharded, single


def _assert_equal(sharded, single, ctx=""):
    for key in OUT_KEYS:
        assert np.array_equal(sharded[key], single[key]), \
            f"{ctx}: sharded solve diverged from single-device on {key}"


def test_sharded_hierarchical_lending_limited_mid_cohort():
    """The round-3 divergence repro: 8 ClusterQueues under a mid-cohort
    whose lendingLimit is 0, so capacity in the 'west' subtree must NOT be
    borrowable from the 'east' subtree. A cpu=6 workload on an east CQ with
    nominal 4 must be NO_FIT (the flat-cohort math says FIT because it sees
    the whole root pool as one lendable bucket)."""
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    # root
    # ├─ west (lendingLimit 0 — its subtree capacity stays inside)
    # │   └─ cq-w0..w3, nominal 4 each
    # └─ east
    #     └─ cq-e0..e3, nominal 4 each
    fw.create_cohort(CohortSpec(name="root"))
    fw.create_cohort(CohortSpec(
        name="west", parent="root",
        resource_groups=(rg("cpu", fq("default", cpu=(0, None, 0))),)))
    fw.create_cohort(CohortSpec(name="east", parent="root"))
    for i in range(4):
        fw.create_cluster_queue(make_cq(
            f"cq-w{i}", rg("cpu", fq("default", cpu=4)), cohort="west"))
        fw.create_local_queue(make_lq(f"lq-w{i}", cq=f"cq-w{i}"))
        fw.create_cluster_queue(make_cq(
            f"cq-e{i}", rg("cpu", fq("default", cpu=4)), cohort="east"))
        fw.create_local_queue(make_lq(f"lq-e{i}", cq=f"cq-e{i}"))

    # cpu=6 > nominal 4: needs to borrow 2. The east subtree has 12 spare,
    # west's 16 are locked behind lendingLimit 0 at the west node... but
    # east's spare IS reachable. Fill east's other CQs so only west
    # capacity remains: then the tree says NO_FIT while flat math says FIT.
    filled = []
    for i in range(1, 4):
        wl = make_wl(f"bg-{i}", f"lq-e{i}", cpu=4, creation_time=float(i))
        fw.submit(wl)
        filled.append(wl)
    assert fw.run_until_settled() == 3

    probe = make_wl("probe", "lq-e0", cpu=6, creation_time=10.0)
    enc, wt, sharded, single = _solve_both(fw, [probe])
    assert enc.hier is not None

    # Single-device hier-aware kernel: NO_FIT (east is out of lendable
    # capacity; west lends nothing).
    assert single["wl_mode"][0] == NO_FIT
    # Regression: the sharded solve must agree — round 3 returned FIT here.
    assert sharded["wl_mode"][0] == NO_FIT
    _assert_equal(sharded, single, "hier-lending")


def test_sharded_hierarchical_borrow_allowed_matches():
    """Same tree without the lending clamp: borrowing across subtrees IS
    allowed and both paths must say FIT (guards against the fix
    over-rotating into under-admission)."""
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cohort(CohortSpec(name="root"))
    fw.create_cohort(CohortSpec(name="west", parent="root"))
    fw.create_cohort(CohortSpec(name="east", parent="root"))
    for i in range(4):
        fw.create_cluster_queue(make_cq(
            f"cq-w{i}", rg("cpu", fq("default", cpu=4)), cohort="west"))
        fw.create_local_queue(make_lq(f"lq-w{i}", cq=f"cq-w{i}"))
        fw.create_cluster_queue(make_cq(
            f"cq-e{i}", rg("cpu", fq("default", cpu=4)), cohort="east"))
        fw.create_local_queue(make_lq(f"lq-e{i}", cq=f"cq-e{i}"))
    for i in range(1, 4):
        fw.submit(make_wl(f"bg-{i}", f"lq-e{i}", cpu=4, creation_time=float(i)))
    assert fw.run_until_settled() == 3

    probe = make_wl("probe", "lq-e0", cpu=6, creation_time=10.0)
    enc, wt, sharded, single = _solve_both(fw, [probe])
    assert single["wl_mode"][0] == FIT
    assert sharded["wl_mode"][0] == FIT
    _assert_equal(sharded, single, "hier-borrow")


@pytest.mark.parametrize("seed", range(4))
def test_sharded_random_equivalence_flat(seed):
    """Randomized flat-cohort problems: sharded == single-device on every
    output tensor."""
    from kueue_tpu.utils.synthetic import synthetic_problem

    cache, pending = synthetic_problem(
        num_cqs=24, num_cohorts=5, num_flavors=4, num_pending=64,
        seed=seed)
    snapshot = cache.snapshot()
    enc = sch.encode_cluster_queues(snapshot)
    usage = sch.encode_usage(snapshot, enc)
    wt = sch.encode_workloads(pending, snapshot, enc)
    mesh = make_mesh(8)
    sharded = sharded_flavor_fit(enc, usage, wt, mesh)
    single = solve_flavor_fit(enc, usage, wt)
    _assert_equal(sharded, single, f"flat seed={seed}")


def test_product_sharded_batch_solver_matches_single_device():
    """The PRODUCT path to the sharded solve: a Framework configured with
    tpuSolver.shardDevices drives BatchSolver(mesh=...) through real ticks
    (pipelined dispatch, decode, admission cycle, partial admission off the
    same plumbing) and must land exactly the admissions the single-device
    solver lands."""
    from kueue_tpu.config import Configuration, TPUSolverConfig
    from kueue_tpu.models.flavor_fit import BatchSolver

    def build(shard):
        # Depth 1 on both sides: the runtime clamps sharded solvers to the
        # synchronous mode (the sharded program completes at dispatch), so
        # the single-device comparator must run the same schedule order.
        cfg = Configuration(tpu_solver=TPUSolverConfig(
            enable=True, pipeline_depth=1, shard_devices=shard))
        fw = Framework(config=cfg)
        if shard > 1:
            assert fw.scheduler.batch_solver._mesh is not None, \
                "config must select the sharded solver"
        fw.create_resource_flavor(make_flavor("default"))
        fw.create_resource_flavor(make_flavor("spot"))
        for c in range(6):
            fw.create_cluster_queue(make_cq(
                f"cq-{c}", rg("cpu", fq("default", cpu=4), fq("spot", cpu=2)),
                cohort=f"pool-{c % 2}"))
            fw.create_local_queue(make_lq(f"lq-{c}", cq=f"cq-{c}"))
        for i in range(8):
            for c in range(6):
                fw.submit(make_wl(f"wl-{c}-{i}", f"lq-{c}", cpu=2,
                                  creation_time=float(i * 6 + c)))
        fw.run_until_settled(max_ticks=60)
        return fw

    sharded_fw = build(4)
    single_fw = build(0)
    for c in range(6):
        assert sorted(sharded_fw.admitted_workloads(f"cq-{c}")) == \
            sorted(single_fw.admitted_workloads(f"cq-{c}")), f"cq-{c}"
        s_usage = sharded_fw.cache.cluster_queues[f"cq-{c}"].usage
        d_usage = single_fw.cache.cluster_queues[f"cq-{c}"].usage
        assert s_usage == d_usage, f"cq-{c} usage"


def test_shard_devices_config_parsing(tmp_path):
    """tpuSolver.shardDevices round-trips through the reference-format
    Configuration file and rejects nonsense."""
    from kueue_tpu.config import ConfigurationError, load

    p = tmp_path / "cfg.yaml"
    p.write_text(
        "apiVersion: config.kueue.x-k8s.io/v1beta1\n"
        "kind: Configuration\n"
        "tpuSolver:\n"
        "  enable: true\n"
        "  shardDevices: 8\n")
    cfg = load(str(p))
    assert cfg.tpu_solver.shard_devices == 8

    p.write_text(
        "apiVersion: config.kueue.x-k8s.io/v1beta1\n"
        "kind: Configuration\n"
        "tpuSolver:\n"
        "  shardDevices: -2\n")
    with pytest.raises(ConfigurationError):
        load(str(p))
