"""Multi-chip sharded solve (kueue_tpu.parallel.mesh) equivalence.

The sharded program must reproduce the single-device kernel bit-for-bit —
including hierarchical cohorts (KEP-79), which round-3's sharded path
silently dropped (VERDICT r3 Weak #2: an 8-CQ tree under a lending-limited
mid-cohort returned FIT sharded where the hier-aware single-device kernel
returned NO_FIT — silent overadmission). The repro here is that exact
scenario, kept as a regression gate.

Runs on the virtual 8-device CPU mesh from conftest.py.
"""

import numpy as np
import pytest

from kueue_tpu.api.types import CohortSpec
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.core.workload import WorkloadInfo
from kueue_tpu.models.flavor_fit import solve_flavor_fit
from kueue_tpu.parallel.mesh import make_mesh, sharded_flavor_fit
from kueue_tpu.solver import schema as sch
from kueue_tpu.solver.modes import FIT, NO_FIT

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg

OUT_KEYS = ("wl_mode", "res_flavor", "res_mode", "res_borrow", "ps_ok",
            "ps_mode", "group_chosen", "group_tried")


def _solve_both(fw, pending, n_devices=8):
    snapshot = fw.cache.snapshot()
    enc = sch.encode_cluster_queues(snapshot)
    usage = sch.encode_usage(snapshot, enc)
    infos = [WorkloadInfo(wl, cluster_queue=fw.cache.cluster_queue_for(wl))
             for wl in pending]
    wt = sch.encode_workloads(infos, snapshot, enc)
    mesh = make_mesh(n_devices)
    sharded = sharded_flavor_fit(enc, usage, wt, mesh)
    single = solve_flavor_fit(enc, usage, wt)
    return enc, wt, sharded, single


def _assert_equal(sharded, single, ctx=""):
    for key in OUT_KEYS:
        assert np.array_equal(sharded[key], single[key]), \
            f"{ctx}: sharded solve diverged from single-device on {key}"


def test_sharded_hierarchical_lending_limited_mid_cohort():
    """The round-3 divergence repro: 8 ClusterQueues under a mid-cohort
    whose lendingLimit is 0, so capacity in the 'west' subtree must NOT be
    borrowable from the 'east' subtree. A cpu=6 workload on an east CQ with
    nominal 4 must be NO_FIT (the flat-cohort math says FIT because it sees
    the whole root pool as one lendable bucket)."""
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    # root
    # ├─ west (lendingLimit 0 — its subtree capacity stays inside)
    # │   └─ cq-w0..w3, nominal 4 each
    # └─ east
    #     └─ cq-e0..e3, nominal 4 each
    fw.create_cohort(CohortSpec(name="root"))
    fw.create_cohort(CohortSpec(
        name="west", parent="root",
        resource_groups=(rg("cpu", fq("default", cpu=(0, None, 0))),)))
    fw.create_cohort(CohortSpec(name="east", parent="root"))
    for i in range(4):
        fw.create_cluster_queue(make_cq(
            f"cq-w{i}", rg("cpu", fq("default", cpu=4)), cohort="west"))
        fw.create_local_queue(make_lq(f"lq-w{i}", cq=f"cq-w{i}"))
        fw.create_cluster_queue(make_cq(
            f"cq-e{i}", rg("cpu", fq("default", cpu=4)), cohort="east"))
        fw.create_local_queue(make_lq(f"lq-e{i}", cq=f"cq-e{i}"))

    # cpu=6 > nominal 4: needs to borrow 2. The east subtree has 12 spare,
    # west's 16 are locked behind lendingLimit 0 at the west node... but
    # east's spare IS reachable. Fill east's other CQs so only west
    # capacity remains: then the tree says NO_FIT while flat math says FIT.
    filled = []
    for i in range(1, 4):
        wl = make_wl(f"bg-{i}", f"lq-e{i}", cpu=4, creation_time=float(i))
        fw.submit(wl)
        filled.append(wl)
    assert fw.run_until_settled() == 3

    probe = make_wl("probe", "lq-e0", cpu=6, creation_time=10.0)
    enc, wt, sharded, single = _solve_both(fw, [probe])
    assert enc.hier is not None

    # Single-device hier-aware kernel: NO_FIT (east is out of lendable
    # capacity; west lends nothing).
    assert single["wl_mode"][0] == NO_FIT
    # Regression: the sharded solve must agree — round 3 returned FIT here.
    assert sharded["wl_mode"][0] == NO_FIT
    _assert_equal(sharded, single, "hier-lending")


def test_sharded_hierarchical_borrow_allowed_matches():
    """Same tree without the lending clamp: borrowing across subtrees IS
    allowed and both paths must say FIT (guards against the fix
    over-rotating into under-admission)."""
    fw = Framework()
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cohort(CohortSpec(name="root"))
    fw.create_cohort(CohortSpec(name="west", parent="root"))
    fw.create_cohort(CohortSpec(name="east", parent="root"))
    for i in range(4):
        fw.create_cluster_queue(make_cq(
            f"cq-w{i}", rg("cpu", fq("default", cpu=4)), cohort="west"))
        fw.create_local_queue(make_lq(f"lq-w{i}", cq=f"cq-w{i}"))
        fw.create_cluster_queue(make_cq(
            f"cq-e{i}", rg("cpu", fq("default", cpu=4)), cohort="east"))
        fw.create_local_queue(make_lq(f"lq-e{i}", cq=f"cq-e{i}"))
    for i in range(1, 4):
        fw.submit(make_wl(f"bg-{i}", f"lq-e{i}", cpu=4, creation_time=float(i)))
    assert fw.run_until_settled() == 3

    probe = make_wl("probe", "lq-e0", cpu=6, creation_time=10.0)
    enc, wt, sharded, single = _solve_both(fw, [probe])
    assert single["wl_mode"][0] == FIT
    assert sharded["wl_mode"][0] == FIT
    _assert_equal(sharded, single, "hier-borrow")


@pytest.mark.parametrize("seed", range(4))
def test_sharded_random_equivalence_flat(seed):
    """Randomized flat-cohort problems: sharded == single-device on every
    output tensor."""
    from kueue_tpu.utils.synthetic import synthetic_problem

    cache, pending = synthetic_problem(
        num_cqs=24, num_cohorts=5, num_flavors=4, num_pending=64,
        seed=seed)
    snapshot = cache.snapshot()
    enc = sch.encode_cluster_queues(snapshot)
    usage = sch.encode_usage(snapshot, enc)
    wt = sch.encode_workloads(pending, snapshot, enc)
    mesh = make_mesh(8)
    sharded = sharded_flavor_fit(enc, usage, wt, mesh)
    single = solve_flavor_fit(enc, usage, wt)
    _assert_equal(sharded, single, f"flat seed={seed}")
