"""Golden snapshot scenarios transliterated from the reference's
TestSnapshot / TestSnapshotAddRemoveWorkload tables
(pkg/cache/snapshot_test.go:45-626,628-900): same ClusterQueues, flavors
and admitted workloads, same expected cohort RequestableResources / Usage
accumulation (plain and lending-limited) and per-CQ usage — plus the
add/remove-workload simulation primitive used by preemption."""

from kueue_tpu import features
from kueue_tpu.api.types import (
    Admission,
    FlavorQuotas,
    PodSet,
    PodSetAssignment,
    ResourceQuota,
    Workload,
)
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.workload import WorkloadInfo

from tests.util import fq, make_cq, make_flavor, rg

GPU = "example.com/gpu"
Gi = 1024 * 1024 * 1024


def wl(name, requests, cq=None, flavors=None, count=1):
    """A workload; admitted with per-resource flavors when cq is given."""
    w = Workload(name=name, namespace="", queue_name="",
                 pod_sets=[PodSet(name="main", count=count,
                                  requests=dict(requests))],
                 creation_time=1.0)
    if cq is not None:
        w.admission = Admission(
            cluster_queue=cq,
            pod_set_assignments=[PodSetAssignment(
                name="main", flavors=dict(flavors),
                resource_usage={r: v * count for r, v in requests.items()},
                count=count)])
        w.set_condition("QuotaReserved", True)
        w.set_condition("Admitted", True)
    return w


# snapshot_test.go "independent clusterQueues"
def test_independent_cluster_queues():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq("a", rg("cpu", fq("default", cpu=100))))
    cache.add_cluster_queue(make_cq("b", rg("cpu", fq("default", cpu=100))))
    cache.add_or_update_workload(
        wl("alpha", {"cpu": 2000}, cq="a", flavors={"cpu": "default"}))
    cache.add_or_update_workload(
        wl("beta", {"cpu": 1000}, cq="b", flavors={"cpu": "default"}))
    snap = cache.snapshot()
    assert snap.cluster_queues["a"].cohort is None
    assert snap.cluster_queues["a"].usage == {"default": {"cpu": 2000}}
    assert snap.cluster_queues["b"].usage == {"default": {"cpu": 1000}}
    assert sorted(snap.cluster_queues["a"].workloads) == ["/alpha"]


# "inactive clusterQueues" — a CQ with a missing flavor is excluded
def test_inactive_cluster_queues():
    cache = Cache()
    cache.add_cluster_queue(make_cq(
        "flavor-nonexistent-cq", rg("cpu", fq("nonexistent", cpu=100))))
    snap = cache.snapshot()
    assert snap.cluster_queues == {}
    assert snap.inactive_cluster_queues == {"flavor-nonexistent-cq"}


# "cohort": accumulation of requestable resources + usage over members
def test_cohort_accumulation():
    cache = Cache()
    for name, labels in (("demand", {"instance": "demand"}),
                         ("spot", {"instance": "spot"}), ("default", {})):
        cache.add_or_update_resource_flavor(make_flavor(name, **labels))
    cache.add_cluster_queue(make_cq(
        "a", rg("cpu", fq("demand", cpu=100), fq("spot", cpu=200)),
        cohort="borrowing"))
    cache.add_cluster_queue(make_cq(
        "b", rg("cpu", fq("spot", cpu=100)),
        rg((GPU,), FlavorQuotas(name="default", resources=(
            (GPU, ResourceQuota(nominal=50)),))),
        cohort="borrowing"))
    cache.add_cluster_queue(make_cq(
        "c", rg("cpu", fq("default", cpu=100))))

    cache.add_or_update_workload(wl(
        "alpha", {"cpu": 2000}, count=5, cq="a",
        flavors={"cpu": "demand"}))
    cache.add_or_update_workload(wl(
        "beta", {"cpu": 1000, GPU: 2}, count=5, cq="b",
        flavors={"cpu": "spot", GPU: "default"}))
    cache.add_or_update_workload(wl(
        "gamma", {"cpu": 1000, GPU: 1}, count=5, cq="b",
        flavors={"cpu": "spot", GPU: "default"}))
    cache.add_or_update_workload(wl("sigma", {"cpu": 1000}, count=5))

    snap = cache.snapshot()
    cohort = snap.cluster_queues["a"].cohort
    assert cohort is snap.cluster_queues["b"].cohort
    assert cohort.requestable_resources == {
        "demand": {"cpu": 100_000},
        "spot": {"cpu": 300_000},
        "default": {GPU: 50},
    }
    assert cohort.usage == {
        "demand": {"cpu": 10_000},
        "spot": {"cpu": 10_000},
        "default": {GPU: 15},
    }
    assert snap.cluster_queues["c"].cohort is None
    # sigma holds no quota: not in any CQ.
    for cq in snap.cluster_queues.values():
        assert "/sigma" not in cq.workloads


# "lendingLimit with 2 clusterQueues and 2 flavors": requestable counts
# only the lendable part; cohort usage only the above-guaranteed part
def test_lending_limit_cohort_accumulation():
    features.set_enabled(features.LENDING_LIMIT, True)
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("arm", arch="arm"))
    cache.add_or_update_resource_flavor(make_flavor("x86", arch="x86"))
    for name in ("a", "b"):
        cache.add_cluster_queue(make_cq(
            name, rg("cpu", fq("arm", cpu=(10, None, 5)),
                     fq("x86", cpu=(20, None, 10))),
            cohort="lending"))
    cache.add_or_update_workload(wl(
        "alpha", {"cpu": 2000}, count=5, cq="a", flavors={"cpu": "arm"}))
    cache.add_or_update_workload(wl(
        "beta", {"cpu": 1000}, count=5, cq="a", flavors={"cpu": "arm"}))
    cache.add_or_update_workload(wl(
        "gamma", {"cpu": 2000}, count=5, cq="a", flavors={"cpu": "x86"}))

    snap = cache.snapshot()
    a = snap.cluster_queues["a"]
    # Requestable = sum of lendingLimits (5+5, 10+10).
    assert a.cohort.requestable_resources == {
        "arm": {"cpu": 10_000}, "x86": {"cpu": 20_000}}
    # Cohort usage = max(0, used - guaranteed): arm 15-5=10, x86 10-10=0.
    assert a.cohort.usage == {"arm": {"cpu": 10_000}, "x86": {"cpu": 0}}
    assert a.usage == {"arm": {"cpu": 15_000}, "x86": {"cpu": 10_000}}
    # Guaranteed quota = nominal - lendingLimit (clusterqueue.go:211-229).
    assert a._guaranteed("arm", "cpu") == 5_000
    assert a._guaranteed("x86", "cpu") == 10_000


def _add_remove_fixture():
    cache = Cache()
    for f in ("default", "alpha", "beta"):
        cache.add_or_update_resource_flavor(make_flavor(f))
    cache.add_cluster_queue(make_cq(
        "c1", rg("cpu", fq("default", cpu=6)),
        rg("memory", fq("alpha", memory="6Gi"), fq("beta", memory="6Gi")),
        cohort="cohort"))
    cache.add_cluster_queue(make_cq(
        "c2", rg("cpu", fq("default", cpu=6)), cohort="cohort"))
    wls = {
        "/c1-cpu": wl("c1-cpu", {"cpu": 1000}, cq="c1",
                      flavors={"cpu": "default"}),
        "/c1-memory-alpha": wl("c1-memory-alpha", {"memory": Gi}, cq="c1",
                               flavors={"memory": "alpha"}),
        "/c1-memory-beta": wl("c1-memory-beta", {"memory": Gi}, cq="c1",
                              flavors={"memory": "beta"}),
        "/c2-cpu-1": wl("c2-cpu-1", {"cpu": 1000}, cq="c2",
                        flavors={"cpu": "default"}),
        "/c2-cpu-2": wl("c2-cpu-2", {"cpu": 1000}, cq="c2",
                        flavors={"cpu": "default"}),
    }
    for w in wls.values():
        cache.add_or_update_workload(w)
    return cache, wls


def _usage_state(snap):
    return ({name: {f: dict(r) for f, r in cq.usage.items()}
             for name, cq in snap.cluster_queues.items()},
            {f: dict(r) for f, r in
             snap.cluster_queues["c1"].cohort.usage.items()})


# TestSnapshotAddRemoveWorkload "no-op remove add"
def test_snapshot_remove_add_roundtrip():
    cache, wls = _add_remove_fixture()
    snap = cache.snapshot()
    initial = _usage_state(snap)
    for key in ("/c1-cpu", "/c2-cpu-1"):
        snap.remove_workload(WorkloadInfo(
            wls[key], cluster_queue=wls[key].admission.cluster_queue))
    for key in ("/c1-cpu", "/c2-cpu-1"):
        snap.add_workload(WorkloadInfo(
            wls[key], cluster_queue=wls[key].admission.cluster_queue))
    assert _usage_state(snap) == initial


# "remove c1-memory-alpha": cohort drops only the alpha usage
def test_snapshot_remove_one_flavor_usage():
    cache, wls = _add_remove_fixture()
    snap = cache.snapshot()
    w = wls["/c1-memory-alpha"]
    snap.remove_workload(WorkloadInfo(w, cluster_queue="c1"))
    assert snap.cluster_queues["c1"].usage["alpha"]["memory"] == 0
    assert snap.cluster_queues["c1"].usage["beta"]["memory"] == Gi
    assert snap.cluster_queues["c1"].cohort.usage["alpha"]["memory"] == 0
    assert snap.cluster_queues["c1"].cohort.usage["beta"]["memory"] == Gi


# "remove all"
def test_snapshot_remove_all():
    cache, wls = _add_remove_fixture()
    snap = cache.snapshot()
    for key, w in wls.items():
        snap.remove_workload(
            WorkloadInfo(w, cluster_queue=w.admission.cluster_queue))
    assert snap.cluster_queues["c1"].usage == {
        "default": {"cpu": 0}, "alpha": {"memory": 0}, "beta": {"memory": 0}}
    assert snap.cluster_queues["c2"].usage == {"default": {"cpu": 0}}
    cohort_usage = snap.cluster_queues["c1"].cohort.usage
    assert all(v == 0 for res in cohort_usage.values()
               for v in res.values())


# Regression (advisor round 2, high): a usage-only change on a stopped CQ
# must not re-insert it into the incremental mirror — the reference's
# snapshot skips inactive CQs entirely (snapshot.go), so cohort requestable
# capacity must not bounce back after a workload delete on a drained CQ.
def test_mirror_skips_inactive_cq_on_usage_change():
    import dataclasses

    from kueue_tpu.api.types import StopPolicy
    from kueue_tpu.core.snapshot import SnapshotMirror

    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    spec_a = make_cq("a", rg("cpu", fq("default", cpu=10000)), cohort="co")
    cache.add_cluster_queue(spec_a)
    cache.add_cluster_queue(
        make_cq("b", rg("cpu", fq("default", cpu=10000)), cohort="co"))
    w = wl("alpha", {"cpu": 2000}, cq="a", flavors={"cpu": "default"})
    cache.add_or_update_workload(w)

    mirror = SnapshotMirror(cache)
    snap = mirror.refresh()
    assert snap.cluster_queues["b"].cohort.requestable_resources == {
        "default": {"cpu": 20000000}}

    # Stop CQ a: structure bump → full rebuild excludes it.
    cache.update_cluster_queue(
        dataclasses.replace(spec_a, stop_policy=StopPolicy.HOLD))
    snap = mirror.refresh()
    assert "a" not in snap.cluster_queues
    assert snap.cluster_queues["b"].cohort.requestable_resources == {
        "default": {"cpu": 10000000}}

    # Delete the workload on the stopped CQ: usage_version bump only.
    cache.delete_workload(w)
    snap = mirror.refresh()
    assert "a" not in snap.cluster_queues
    assert snap.cluster_queues["b"].cohort.requestable_resources == {
        "default": {"cpu": 10000000}}
    assert snap.cluster_queues["b"].cohort.usage["default"]["cpu"] == 0
