"""Decision-equivalence: the batched JAX solver must reproduce the referee's
decisions exactly (modes, flavor choices, borrow flags, usage, resume state)
on randomized problems."""

import random

import pytest

from kueue_tpu.api.types import (
    BorrowWithinCohort,
    ClusterQueuePreemption,
    FlavorFungibility,
    PodSet,
    ResourceFlavor,
    Taint,
    Toleration,
    Workload,
)
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.workload import WorkloadInfo
from kueue_tpu.models.flavor_fit import BatchSolver
from kueue_tpu.solver.referee import assign_flavors

from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg
from tests.test_cache import admit


def random_problem(seed: int, num_cqs=4, num_flavors=3, num_wls=24):
    rnd = random.Random(seed)
    cache = Cache()
    flavors = []
    for i in range(num_flavors):
        taints = []
        if rnd.random() < 0.3:
            taints = [Taint(key="special", value="true")]
        labels = {"tier": f"t{i}"} if rnd.random() < 0.5 else None
        flavors.append(ResourceFlavor.make(f"f{i}", node_labels=labels,
                                           node_taints=taints))
        cache.add_or_update_resource_flavor(flavors[-1])

    cohorts = ["co-a", "co-b", ""]
    resources = ["cpu", "memory"]
    for c in range(num_cqs):
        n_flavors = rnd.randint(1, num_flavors)
        order = rnd.sample(range(num_flavors), n_flavors)
        fqs = []
        for fi in order:
            quotas = {}
            for r in resources:
                nominal = rnd.randint(0, 12)
                borrow = rnd.choice([None, rnd.randint(0, 6)])
                quotas[r] = (nominal, borrow)
            fqs.append(fq(f"f{fi}", **quotas))
        preemption = ClusterQueuePreemption(
            within_cluster_queue=rnd.choice(["Never", "LowerPriority"]),
            reclaim_within_cohort=rnd.choice(["Never", "Any"]),
            borrow_within_cohort=rnd.choice([
                None, BorrowWithinCohort(policy="LowerPriority")]))
        fungibility = FlavorFungibility(
            when_can_borrow=rnd.choice(["Borrow", "TryNextFlavor"]),
            when_can_preempt=rnd.choice(["Preempt", "TryNextFlavor"]))
        cq = make_cq(f"cq{c}", rg(tuple(resources), *fqs),
                     cohort=rnd.choice(cohorts),
                     preemption=preemption, fungibility=fungibility)
        cache.add_cluster_queue(cq)
        cache.add_local_queue(make_lq(f"lq{c}", cq=f"cq{c}"))

    # Random admitted workloads to create usage.
    for i in range(num_wls // 2):
        c = rnd.randrange(num_cqs)
        wl = make_wl(f"adm{i}", f"lq{c}",
                     cpu=rnd.randint(1, 4), memory=rnd.randint(1, 4))
        flavor = f"f{rnd.randrange(num_flavors)}"
        cache.add_or_update_workload(admit(wl, f"cq{c}", flavor))

    # Pending workloads to solve.
    pending = []
    for i in range(num_wls):
        c = rnd.randrange(num_cqs)
        pod_sets = []
        for p in range(rnd.randint(1, 2)):
            kwargs = {}
            if rnd.random() < 0.25:
                kwargs["tolerations"] = [
                    Toleration(key="special", operator="Equal", value="true")]
            if rnd.random() < 0.25:
                kwargs["node_selector"] = {"tier": f"t{rnd.randrange(num_flavors)}"}
            pod_sets.append(PodSet.make(
                f"ps{p}", count=rnd.randint(1, 3),
                cpu=rnd.randint(0, 5), memory=rnd.randint(0, 5), **kwargs))
        wl = make_wl(f"pend{i}", f"lq{c}", priority=rnd.randint(-2, 2),
                     pod_sets=pod_sets)
        pending.append(WorkloadInfo(wl, cluster_queue=f"cq{c}"))
    return cache, pending


def assert_assignment_equal(ref, got, ctx):
    assert got.representative_mode == ref.representative_mode, \
        f"{ctx}: mode {got.representative_mode} != {ref.representative_mode}"
    if ref.representative_mode == 0:
        # NoFit: flavor details beyond the failing podset are unspecified,
        # but the resume state still matters (it drives requeue decisions).
        assert (got.last_state.last_tried_flavor_idx
                == ref.last_state.last_tried_flavor_idx), f"{ctx}: last state"
        return
    assert got.borrowing == ref.borrowing, f"{ctx}: borrowing"
    assert got.usage == ref.usage, f"{ctx}: usage {got.usage} != {ref.usage}"
    assert len(got.pod_sets) == len(ref.pod_sets), f"{ctx}: podsets"
    for p, (rps, gps) in enumerate(zip(ref.pod_sets, got.pod_sets)):
        ref_flavors = {r: (fa.name, fa.mode, fa.borrow, fa.tried_flavor_idx)
                       for r, fa in rps.flavors.items()}
        got_flavors = {r: (fa.name, fa.mode, fa.borrow, fa.tried_flavor_idx)
                       for r, fa in gps.flavors.items()}
        assert got_flavors == ref_flavors, \
            f"{ctx} podset {p}: {got_flavors} != {ref_flavors}"
    assert (got.last_state.last_tried_flavor_idx
            == ref.last_state.last_tried_flavor_idx), f"{ctx}: last state"


@pytest.mark.parametrize("seed", range(20))
def test_random_equivalence(seed):
    cache, pending = random_problem(seed)
    snap_ref = cache.snapshot()
    snap_jax = cache.snapshot()

    ref_results = []
    for wi in pending:
        cq = snap_ref.cluster_queues[wi.cluster_queue]
        ref_results.append(
            assign_flavors(wi.clone(), cq, snap_ref.resource_flavors))

    solver = BatchSolver()
    jax_results = solver.solve([wi.clone() for wi in pending], snap_jax)

    for i, (ref, got) in enumerate(zip(ref_results, jax_results)):
        assert_assignment_equal(ref, got, f"seed={seed} wl={pending[i].key}")


def test_equivalence_with_resume_state(seed=7):
    # Second attempts must resume from the recorded flavor index in both
    # implementations.
    cache, pending = random_problem(seed)
    snap = cache.snapshot()
    solver = BatchSolver()

    ref_infos = [wi.clone() for wi in pending]
    jax_infos = [wi.clone() for wi in pending]

    # First pass records resume state on the infos.
    for wi in ref_infos:
        a = assign_flavors(wi, snap.cluster_queues[wi.cluster_queue],
                           snap.resource_flavors)
        wi.last_assignment = a.last_state
    first = solver.solve(jax_infos, snap)
    for wi, a in zip(jax_infos, first):
        wi.last_assignment = a.last_state

    # Second pass must agree.
    ref2 = []
    for wi in ref_infos:
        ref2.append(assign_flavors(
            wi, snap.cluster_queues[wi.cluster_queue], snap.resource_flavors))
    got2 = solver.solve(jax_infos, snap)
    for i, (ref, got) in enumerate(zip(ref2, got2)):
        assert_assignment_equal(ref, got, f"resume wl={ref_infos[i].key}")


def _solve_both(cache, wl, cq_name):
    snap = cache.snapshot()
    wi = WorkloadInfo(wl, cluster_queue=cq_name)
    ref = assign_flavors(wi.clone(), snap.cluster_queues[cq_name],
                         snap.resource_flavors)
    got = BatchSolver().solve([wi.clone()], snap)[0]
    return ref, got


def test_resource_in_vocab_but_not_in_cq():
    # 'gpu' exists in the global vocabulary (cq-b covers it) but cq-a does
    # not cover it: both solvers must reject the workload.
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cache.add_cluster_queue(make_cq("cq-a", rg("cpu", fq("default", cpu=8))))
    cache.add_cluster_queue(make_cq(
        "cq-b", rg("gpu", fq("default", **{"gpu": 4}))))
    wl = make_wl("w", pod_sets=[
        PodSet.make(
            "main", count=1, cpu=1, **{"gpu": 1})])
    ref, got = _solve_both(cache, wl, "cq-a")
    assert ref.representative_mode == 0
    assert got.representative_mode == 0
    assert_assignment_equal(ref, got, "uncovered-resource")


def test_same_flavor_in_two_groups_group_scoped_affinity():
    # fA appears in two groups; the tier selector is only constraining in
    # the group whose flavors carry the 'tier' label key.
    from kueue_tpu.api.types import ResourceFlavor as RF
    cache = Cache()
    cache.add_or_update_resource_flavor(RF.make("fA"))
    cache.add_or_update_resource_flavor(RF.make("fB", node_labels={"tier": "t1"}))
    cache.add_cluster_queue(make_cq(
        "cq",
        rg("cpu", fq("fA", cpu=8)),
        rg("gpu", fq("fB", **{"gpu": 4}),
           fq("fA", **{"gpu": 4}))))
    wl = make_wl("w", pod_sets=[PodSet.make(
        "main", count=1, cpu=1, node_selector={"tier": "t1"},
        **{"gpu": 1})])
    ref, got = _solve_both(cache, wl, "cq")
    assert ref.representative_mode == 2
    assert_assignment_equal(ref, got, "two-group-flavor")


def test_fungibility_gate_off():
    from kueue_tpu import features
    features.set_enabled(features.FLAVOR_FUNGIBILITY, False)
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("f0"))
    cache.add_or_update_resource_flavor(make_flavor("f1"))
    fung = FlavorFungibility(when_can_preempt="Preempt")
    cache.add_cluster_queue(make_cq(
        "cq", rg("cpu", fq("f0", cpu=4), fq("f1", cpu=8)), fungibility=fung))
    cache.add_local_queue(make_lq("main", cq="cq"))
    cache.add_or_update_workload(admit(make_wl("w0", cpu=4), "cq", "f0"))
    # Gate off ignores whenCanPreempt=Preempt: keep scanning to the Fit on f1.
    ref, got = _solve_both(cache, make_wl("w", cpu=2), "cq")
    assert ref.representative_mode == 2
    assert ref.pod_sets[0].flavors["cpu"].name == "f1"
    assert_assignment_equal(ref, got, "gate-off")


@pytest.mark.parametrize("seed", range(8))
def test_revalidate_fits_matches_referee(seed):
    """The vectorized staleness re-validation (BatchSolver.revalidate_fits)
    must agree with the per-entry referee walk
    (scheduler._assignment_still_fits) on every FIT assignment, including
    after usage moved under the solve (the pipelined-staleness scenario)."""
    from kueue_tpu.scheduler.scheduler import _assignment_still_fits

    cache, pending = random_problem(seed)
    snap = cache.snapshot()
    solver = BatchSolver()
    assignments = solver.solve([wi.clone() for wi in pending], snap)

    fit_items = [(wi, a) for wi, a in zip(pending, assignments)
                 if a.representative_mode == 2]
    if not fit_items:
        return

    # Staleness: land some of the FIT assignments as admissions, mirroring
    # into the solver's usage tensor, then re-validate ALL of them against
    # the moved usage.
    from kueue_tpu.api.types import Admission, PodSetAssignment

    rnd = random.Random(seed + 100)
    for wi, a in fit_items:
        if rnd.random() < 0.5:
            wi.obj.admission = Admission(
                cluster_queue=wi.cluster_queue,
                pod_set_assignments=[
                    PodSetAssignment(
                        name=ps.name,
                        flavors={r: fa.name for r, fa in ps.flavors.items()},
                        resource_usage=dict(ps.requests), count=ps.count)
                    for ps in a.pod_sets])
            admitted_wi = WorkloadInfo(wi.obj, cluster_queue=wi.cluster_queue)
            cq = snap.cluster_queues[wi.cluster_queue]
            cq.add_workload_usage(admitted_wi, cohort_too=True)
            solver.note_admission(wi.cluster_queue, a.usage)

    mask = solver.revalidate_fits(
        [(wi.cluster_queue, a) for wi, a in fit_items])
    assert mask is not None
    for (wi, a), got in zip(fit_items, mask.tolist()):
        cq = snap.cluster_queues[wi.cluster_queue]
        want = _assignment_still_fits(a, cq)
        assert got == want, (
            f"seed={seed} wl={wi.key}: vectorized {got} != referee {want}")


@pytest.mark.parametrize("seed", range(6))
def test_fast_path_encode_matches_slow_path(seed):
    """The selector-free fast path in encode_workloads (any podset count)
    must produce bit-identical tensors to the generic _encode_row path.
    Forcing `counts` to the spec counts routes every workload down the
    slow path without changing the encoded problem (scaled_to(count) with
    the spec count is the identity)."""
    import numpy as np

    from kueue_tpu.solver import schema as sch

    rnd = random.Random(seed)
    cache, _ = random_problem(seed, num_wls=0)
    pending = []
    for i in range(24):
        c = rnd.randrange(4)
        pod_sets = [
            PodSet.make(f"ps{p}", count=rnd.randint(1, 3),
                        cpu=rnd.randint(0, 5), memory=rnd.randint(0, 5))
            for p in range(rnd.randint(1, 3))]
        wl = make_wl(f"mp{i}", f"lq{c}", pod_sets=pod_sets)
        pending.append(WorkloadInfo(wl, cluster_queue=f"cq{c}"))
    snap = cache.snapshot()
    enc = sch.encode_cluster_queues(snap)
    fast = sch.encode_workloads(pending, snap, enc)
    slow = sch.encode_workloads(
        pending, snap, enc,
        counts=[[ps.count for ps in wi.obj.pod_sets] for wi in pending])
    for field in ("wl_cq", "req", "has_req", "podset_valid", "podset_unsat",
                  "elig", "resume_slot", "wl_valid"):
        np.testing.assert_array_equal(
            getattr(fast, field), getattr(slow, field),
            err_msg=f"seed={seed} field={field}")


def test_encode_zero_podset_workload():
    """A workload with pod_sets=[] rides the fast path without rows; the
    empty fancy-index must not crash (float64 empty-array index)."""
    from kueue_tpu.solver import schema as sch

    cache, _ = random_problem(0, num_wls=0)
    wl = make_wl("empty", "lq0", pod_sets=[])
    pending = [WorkloadInfo(wl, cluster_queue="cq0")]
    snap = cache.snapshot()
    enc = sch.encode_cluster_queues(snap)
    wt = sch.encode_workloads(pending, snap, enc)
    assert not wt.podset_valid[0].any()
